/* Native worker data plane: tx-stream framing + batch accumulation.
 *
 * The reference's throughput hot path is worker/src/batch_maker.rs:71-156 —
 * per-transaction work (frame split, byte counting, sample-id scan, batch
 * serialization) at up to hundreds of thousands of tx/s.  In this framework
 * every per-transaction step happens here, in C, on raw buffers; Python sees
 * only sealed ~500 kB batches (tens per second).
 *
 * Wire format (narwhal_tpu/utils/serde.py, network/framing.py):
 *   tx frame on the socket:  [u32le len][len bytes]
 *   WorkerMessage::Batch:    [u8 tag=0][u32le count][count * ([u32le len][tx])]
 * The in-batch entry encoding equals the socket frame encoding, so the
 * batcher accumulates inbound frame bytes verbatim and sealing is a 5-byte
 * header prepend plus one memcpy — no per-tx re-serialization ever.
 *
 * Sample transactions (benchmark methodology, reference
 * node/src/benchmark_client.rs:258-271): byte0 == 0, u64le id at bytes 1..9.
 * Their ids are collected during accumulation so the Python side can emit
 * the "Batch X contains sample tx N" log lines the parser joins on.
 */

#include <stdint.h>
#include <stdlib.h>
#include <string.h>

#define DP_MAX_FRAME (32u * 1024u * 1024u) /* network/framing.py MAX_FRAME */

/* ---------------------------------------------------------------- batcher */

typedef struct DpBatcher {
    uint8_t *buf;      /* batch body: concatenated [u32 len][tx] entries */
    uint32_t len;      /* bytes used in buf */
    uint32_t cap;
    uint32_t tx_count;
    uint32_t tx_bytes; /* payload bytes (sum of tx lens, excl. prefixes) */
    uint64_t *samples;
    uint32_t n_samples;
    uint32_t samples_cap;
    uint32_t batch_size; /* seal threshold on tx_bytes */
} DpBatcher;

DpBatcher *dp_batcher_new(uint32_t batch_size) {
    DpBatcher *b = (DpBatcher *)calloc(1, sizeof(DpBatcher));
    if (!b) return NULL;
    b->batch_size = batch_size;
    b->cap = batch_size + batch_size / 4 + 4096;
    b->buf = (uint8_t *)malloc(b->cap);
    b->samples_cap = 256;
    b->samples = (uint64_t *)malloc(b->samples_cap * sizeof(uint64_t));
    if (!b->buf || !b->samples) {
        free(b->buf);
        free(b->samples);
        free(b);
        return NULL;
    }
    return b;
}

void dp_batcher_free(DpBatcher *b) {
    if (!b) return;
    free(b->buf);
    free(b->samples);
    free(b);
}

static int dp_batcher_reserve(DpBatcher *b, uint32_t extra) {
    if ((uint64_t)b->len + extra <= b->cap) return 0;
    uint64_t want = (uint64_t)b->len + extra;
    uint64_t cap = b->cap;
    while (cap < want) cap *= 2;
    if (cap > UINT32_MAX) return -1;
    uint8_t *nb = (uint8_t *)realloc(b->buf, cap);
    if (!nb) return -1;
    b->buf = nb;
    b->cap = (uint32_t)cap;
    return 0;
}

/* Append one complete tx (payload only; the entry prefix is added here). */
static int dp_batcher_push(DpBatcher *b, const uint8_t *tx, uint32_t len) {
    if (dp_batcher_reserve(b, len + 4) != 0) return -1;
    uint8_t *p = b->buf + b->len;
    p[0] = (uint8_t)(len);
    p[1] = (uint8_t)(len >> 8);
    p[2] = (uint8_t)(len >> 16);
    p[3] = (uint8_t)(len >> 24);
    memcpy(p + 4, tx, len);
    b->len += len + 4;
    b->tx_count += 1;
    b->tx_bytes += len;
    if (len >= 9 && tx[0] == 0) {
        if (b->n_samples == b->samples_cap) {
            uint32_t nc = b->samples_cap * 2;
            uint64_t *ns =
                (uint64_t *)realloc(b->samples, nc * sizeof(uint64_t));
            if (!ns) return -1;
            b->samples = ns;
            b->samples_cap = nc;
        }
        uint64_t id = 0;
        for (int i = 7; i >= 0; i--) id = (id << 8) | tx[1 + i];
        b->samples[b->n_samples++] = id;
    }
    return 0;
}

uint32_t dp_batcher_tx_bytes(const DpBatcher *b) { return b->tx_bytes; }
uint32_t dp_batcher_tx_count(const DpBatcher *b) { return b->tx_count; }
int dp_batcher_ready(const DpBatcher *b) {
    return b->tx_bytes >= b->batch_size;
}

/* Size of the message dp_batcher_seal would emit right now. */
uint32_t dp_batcher_sealed_size(const DpBatcher *b) { return 5 + b->len; }

/* Seal the accumulated batch into `out` as a complete WorkerMessage::Batch
 * (tag + count + entries).  Copies up to `samples_cap` sample ids into
 * `samples` and the true count into *n_samples; *n_txs and *tx_bytes get
 * the batch's tx count / payload byte count.  Resets the batcher.
 * Returns the message length, 0 if the batch is empty, -1 if `out_cap` or
 * `samples_cap` is too small (nothing consumed). */
int64_t dp_batcher_seal(DpBatcher *b, uint8_t *out, uint32_t out_cap,
                        uint64_t *samples, uint32_t samples_cap,
                        uint32_t *n_samples, uint32_t *n_txs,
                        uint32_t *tx_bytes) {
    if (b->tx_count == 0) return 0;
    uint32_t total = 5 + b->len;
    if (out_cap < total || samples_cap < b->n_samples) return -1;
    out[0] = 0; /* WORKER_BATCH tag */
    uint32_t c = b->tx_count;
    out[1] = (uint8_t)(c);
    out[2] = (uint8_t)(c >> 8);
    out[3] = (uint8_t)(c >> 16);
    out[4] = (uint8_t)(c >> 24);
    memcpy(out + 5, b->buf, b->len);
    memcpy(samples, b->samples, b->n_samples * sizeof(uint64_t));
    *n_samples = b->n_samples;
    *n_txs = b->tx_count;
    *tx_bytes = b->tx_bytes;
    b->len = 0;
    b->tx_count = 0;
    b->tx_bytes = 0;
    b->n_samples = 0;
    return (int64_t)total;
}

/* Validate a serialized WorkerMessage::Batch (tag + count + entries) with
 * no allocation: every entry length prefix must be in-bounds and the body
 * must be fully consumed.  Returns the tx count, or -1 if malformed.  Used
 * on the inter-worker receive path before a batch is ACKed and stored. */
int64_t dp_validate_batch(const uint8_t *buf, uint32_t len) {
    if (len < 5 || buf[0] != 0) return -1;
    uint32_t count = (uint32_t)buf[1] | ((uint32_t)buf[2] << 8) |
                     ((uint32_t)buf[3] << 16) | ((uint32_t)buf[4] << 24);
    uint32_t pos = 5;
    for (uint32_t i = 0; i < count; i++) {
        if (len - pos < 4) return -1;
        uint32_t flen = (uint32_t)buf[pos] | ((uint32_t)buf[pos + 1] << 8) |
                        ((uint32_t)buf[pos + 2] << 16) |
                        ((uint32_t)buf[pos + 3] << 24);
        if (flen > DP_MAX_FRAME || len - pos - 4 < flen) return -1;
        pos += 4 + flen;
    }
    return pos == len ? (int64_t)count : -1;
}

/* ----------------------------------------------------------------- framer */

/* Per-connection splitter for the length-prefixed tx stream.  Complete
 * frames go straight into the shared batcher; a trailing partial frame is
 * retained for the next feed. */
typedef struct DpFramer {
    uint8_t *pend;
    uint32_t pend_len;
    uint32_t pend_cap;
} DpFramer;

DpFramer *dp_framer_new(void) {
    DpFramer *f = (DpFramer *)calloc(1, sizeof(DpFramer));
    if (!f) return NULL;
    f->pend_cap = 4096;
    f->pend = (uint8_t *)malloc(f->pend_cap);
    if (!f->pend) {
        free(f);
        return NULL;
    }
    return f;
}

void dp_framer_free(DpFramer *f) {
    if (!f) return;
    free(f->pend);
    free(f);
}

static int dp_framer_keep(DpFramer *f, const uint8_t *data, uint32_t len) {
    if (len > f->pend_cap) {
        uint32_t cap = f->pend_cap;
        while (cap < len) cap *= 2;
        uint8_t *np = (uint8_t *)realloc(f->pend, cap);
        if (!np) return -1;
        f->pend = np;
        f->pend_cap = cap;
    }
    memmove(f->pend, data, len);
    f->pend_len = len;
    return 0;
}

/* Feed a socket chunk.  Transactions are appended to the batcher ONE AT A
 * TIME with the seal threshold checked after each (matching the reference's
 * per-tx seal check, worker/src/batch_maker.rs:77-87): when the batcher
 * reaches its threshold mid-chunk, the remaining bytes are retained and the
 * call returns 1 so the caller can seal and resume with an empty feed.
 *
 * Returns: 1 = batcher ready (seal, then call again with len 0 to drain the
 * remainder), 0 = chunk fully consumed, -1 = malformed stream (oversized
 * frame) or allocation failure — caller should drop the connection. */
int dp_framer_feed(DpFramer *f, DpBatcher *b, const uint8_t *data,
                   uint32_t len) {
    const uint8_t *p;
    uint32_t n;
    uint8_t *joined = NULL;

    if (f->pend_len > 0) {
        /* Prepend the retained bytes.  Rare (once per chunk at most), so a
         * single join allocation is fine. */
        joined = (uint8_t *)malloc((size_t)f->pend_len + len);
        if (!joined) return -1;
        memcpy(joined, f->pend, f->pend_len);
        memcpy(joined + f->pend_len, data, len);
        p = joined;
        n = f->pend_len + len;
        f->pend_len = 0;
    } else {
        p = data;
        n = len;
    }

    int ready = 0;
    uint32_t pos = 0;
    while (n - pos >= 4) {
        if (dp_batcher_ready(b)) {
            ready = 1;
            break;
        }
        uint32_t flen = (uint32_t)p[pos] | ((uint32_t)p[pos + 1] << 8) |
                        ((uint32_t)p[pos + 2] << 16) |
                        ((uint32_t)p[pos + 3] << 24);
        if (flen > DP_MAX_FRAME) {
            free(joined);
            return -1;
        }
        if (n - pos - 4 < flen) break; /* partial frame */
        if (dp_batcher_push(b, p + pos + 4, flen) != 0) {
            free(joined);
            return -1;
        }
        pos += 4 + flen;
    }
    if (!ready && dp_batcher_ready(b)) ready = 1;
    if (pos < n) {
        if (dp_framer_keep(f, p + pos, n - pos) != 0) {
            free(joined);
            return -1;
        }
    }
    free(joined);
    return ready;
}
