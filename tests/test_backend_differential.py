"""Differential adversarial suite: every TpuBackend verdict vs the CPU
backend's, on hostile inputs (ISSUE 14 satellite).

The safety property is one-sided by design: the batched path must NEVER
accept a signature the serial path rejects (a forgery slipping in only
when the committee runs the fast backend would be a consensus-split
machine).  The kernel is deliberately STRICTER than RFC 8032
cofactorless verifiers on small-order points (dalek `verify_strict`
semantics — see ops/ed25519.py's docstring), so on that one documented
class the verdicts legitimately diverge with the kernel on the
rejecting side; everywhere else — non-canonical scalars (S ≥ L),
non-canonical y encodings (y ≥ p), off-curve points, x=0/sign=1,
wrong keys, bit-flip corruptions, RFC 8032 vectors — the verdicts must
be EQUAL.

Ground truth is whatever `cpu_verify` rides on this host (OpenSSL via
`cryptography`, or the pure-Python RFC 8032 fallback) — i.e. exactly
the serial path a NARWHAL_CRYPTO_BACKEND=cpu committee trusts, which
is the comparison that matters for the A/B.

Marked ``slow``: the first kernel call costs an XLA compile (minutes on
a sandboxed CPU host without the persistent cache).  CI runs this file
explicitly in the check workflow, where the tier-1 test_ed25519 run has
already populated the in-job compile cache.
"""

import random

import pytest

jax = pytest.importorskip("jax")

import numpy as np  # noqa: E402

from narwhal_tpu.crypto import KeyPair  # noqa: E402
from narwhal_tpu.crypto import _ed25519_py as PY  # noqa: E402
from narwhal_tpu.crypto.keys import cpu_verify  # noqa: E402
from narwhal_tpu.ops import ed25519 as E  # noqa: E402
from narwhal_tpu.ops import field25519 as F  # noqa: E402

pytestmark = pytest.mark.slow

rng = random.Random(19)


def sign(kp: KeyPair, msg: bytes) -> bytes:
    """Raw-bytes signing via the pure-Python signer (works with or
    without OpenSSL and over arbitrary-length messages)."""
    a, prefix = PY._secret_expand(bytes(kp.secret))
    return PY.sign_expanded(a, prefix, bytes(kp.name), msg)


def tpu_mask(cases):
    msgs, keys, sigs = zip(*cases)
    return [bool(v) for v in E.verify_batch_arrays(msgs, keys, sigs)]


def cpu_mask(cases):
    return [bool(cpu_verify(m, k, s)) for m, k, s in cases]


def assert_never_looser(cases, context=""):
    """The one-sided safety gate: tpu accepts ⇒ cpu accepts."""
    t, c = tpu_mask(cases), cpu_mask(cases)
    for i, (tv, cv) in enumerate(zip(t, c)):
        if tv:
            assert cv, (
                f"{context}: batched path accepted case {i} that the "
                f"serial path rejects — {cases[i]!r}"
            )
    return t, c


# RFC 8032 §7.1 TEST 1-3: (secret key, public key, message) hex; the
# signatures are derived from the secret keys by the pure-Python RFC
# signer, with the PUBLISHED public keys pinned as the independent
# anchor (a signer drift would break the pk assert, not silently
# re-derive a self-consistent wrong vector).  TEST 1's signature is
# additionally pinned verbatim.
RFC8032_VECTORS = [
    (
        "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60",
        "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a",
        "",
    ),
    (
        "4ccd089b28ff96da9db6c346ec114e0f5b8a319f35aba624da8cf6ed4fb8a6fb",
        "3d4017c3e843895a92b70aa74d1b7ebc9c982ccf2ec4968cc0cd55f12af4660c",
        "72",
    ),
    (
        "c5aa8df43f9f837bedb7442f31dcb7b166d38535076f094b85ce3a2e0b4458f7",
        "fc51cd8e6218a1a38da47ed00230f0580816ed13ba3303ac5deb911548908025",
        "af82",
    ),
]

RFC8032_TEST1_SIG = (
    "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e06522490155"
    "5fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b"
)


def test_rfc8032_vectors_verdict_identical():
    cases = []
    for sk, pk, m in RFC8032_VECTORS:
        sk, pk, m = bytes.fromhex(sk), bytes.fromhex(pk), bytes.fromhex(m)
        assert PY.secret_to_public(sk) == pk, "RFC pk anchor drifted"
        cases.append((m, pk, PY.sign(sk, m)))
    assert cases[0][2] == bytes.fromhex(RFC8032_TEST1_SIG)
    # Corrupted copies: each vector with one flipped message bit.
    for m, pk, sig in list(cases):
        mm = bytearray(m or b"\x00")
        mm[0] ^= 1
        cases.append((bytes(mm), pk, sig))
    t, c = assert_never_looser(cases, "rfc8032")
    assert t == c, (t, c)
    assert t[:3] == [True, True, True]
    assert t[3:] == [False, False, False]


def test_non_canonical_scalar_verdict_identical():
    """S' = S + L (signature malleability): both backends reject."""
    kp = KeyPair.generate(rng.randbytes(32))
    m = rng.randbytes(32)
    sig = sign(kp, m)
    s_int = int.from_bytes(sig[32:], "little")
    forged = sig[:32] + (s_int + E.L_ORDER).to_bytes(32, "little")
    cases = [(m, bytes(kp.name), sig), (m, bytes(kp.name), forged)]
    t, c = assert_never_looser(cases, "scalar-malleability")
    assert t == c == [True, False]


def test_non_canonical_y_and_off_curve_verdict_identical():
    kp = KeyPair.generate(rng.randbytes(32))
    m = rng.randbytes(32)
    sig = sign(kp, m)
    # y >= p in the key and in R, and an off-curve y (x² non-square).
    bad_y = (F.P + 3).to_bytes(32, "little")
    y = 2
    while True:
        u = (y * y - 1) % F.P
        v = (PY.D * y * y + 1) % F.P
        xx = (u * pow(v, F.P - 2, F.P)) % F.P
        if pow(xx, (F.P - 1) // 2, F.P) == F.P - 1:
            break
        y += 1
    off_curve = y.to_bytes(32, "little")
    cases = [
        (m, bad_y, sig),
        (m, bytes(kp.name), bad_y + sig[32:]),  # non-canonical R
        (m, off_curve, sig),
    ]
    t, c = assert_never_looser(cases, "non-canonical")
    assert t == c == [False, False, False]


def test_wrong_key_verdict_identical():
    kp1 = KeyPair.generate(rng.randbytes(32))
    kp2 = KeyPair.generate(rng.randbytes(32))
    m = rng.randbytes(32)
    cases = [(m, bytes(kp2.name), sign(kp1, m))]
    t, c = assert_never_looser(cases, "wrong-key")
    assert t == c == [False]


def _small_order_forgery():
    """A cofactorless forgery under A = identity: k·A is the identity
    for every k, so R = [S]B satisfies [S]B = R + [k]A for ANY message
    — the classic small-order-key attack `verify_strict` exists for."""
    s = 987654321
    rx, ry = E._ref_scalarmult(s)
    r_bytes = (ry | ((rx & 1) << 255)).to_bytes(32, "little")
    ident = (1).to_bytes(32, "little")
    return (rng.randbytes(32), ident, r_bytes + s.to_bytes(32, "little"))


def test_small_order_key_batched_strictly_more_rejecting():
    """The ONE documented divergence class: the serial cofactorless
    verifiers (OpenSSL / pure-Python RFC 8032) ACCEPT the identity-key
    forgery, the kernel (verify_strict semantics) rejects it.  The
    divergence is on the rejecting side — the safety property holds —
    and this test pins both facts so a backend change that silently
    flips either direction fails loudly."""
    case = _small_order_forgery()
    m, k, s = case
    t, c = tpu_mask([case]), cpu_mask([case])
    assert t == [False], "kernel must reject a small-order key"
    # The RFC 8032 cofactorless reference (the pure-Python verifier)
    # ACCEPTS this forgery — pinned so the exemption class stays
    # documented by an executable fact.  The host's cpu_verify may ride
    # OpenSSL, whose verdict we don't pin — the never-looser property
    # (tpu False here) holds under either.
    assert PY.verify(k, m, s) is True, (
        "the cofactorless reference became strict on small-order keys "
        "— fold this class back into the verdict-equality gate"
    )
    assert c in ([True], [False])  # either way, kernel is not looser


def test_truncated_signature_never_accepted():
    """Truncated/oversized raw signatures: the typed protocol seam
    (`Signature`) makes these unrepresentable in a live burst, and at
    the raw-array seam the kernel fails LOUD (ValueError) while the
    serial path returns False — neither path can accept."""
    kp = KeyPair.generate(rng.randbytes(32))
    m = rng.randbytes(32)
    sig = sign(kp, m)
    for bad in (sig[:63], sig[:32], sig + b"\x00"):
        assert cpu_verify(m, kp.name, bad) is False
        with pytest.raises(ValueError):
            E.verify_batch_arrays([m], [bytes(kp.name)], [bad])
    for bad_key in (bytes(kp.name)[:31], bytes(kp.name) + b"\x00"):
        assert cpu_verify(m, bad_key, sig) is False
        with pytest.raises(ValueError):
            E.verify_batch_arrays([m], [bad_key], [sig])


def test_bitflip_fuzz_verdicts_never_looser_and_equal_off_torsion():
    """Seeded bit-flip fuzz across message/key/signature bytes: the
    batched verdict must equal the serial one except where the flip
    lands a small-order encoding (kernel-stricter, still never-looser).
    One batch, padded shape 32 (reuses the warm compile)."""
    kp = KeyPair.generate(rng.randbytes(32))
    cases, flips = [], []
    for i in range(24):
        m = bytearray(rng.randbytes(32))
        k = bytearray(kp.name)
        s = bytearray(sign(kp, bytes(m)))
        target = rng.choice(("sig", "key", "msg", "none"))
        if target == "sig":
            s[rng.randrange(64)] ^= 1 << rng.randrange(8)
        elif target == "key":
            k[rng.randrange(32)] ^= 1 << rng.randrange(8)
        elif target == "msg":
            m[rng.randrange(32)] ^= 1 << rng.randrange(8)
        flips.append(target)
        cases.append((bytes(m), bytes(k), bytes(s)))
    t, c = assert_never_looser(cases, "bitflip-fuzz")
    for i, (tv, cv) in enumerate(zip(t, c)):
        if flips[i] == "none":
            assert tv and cv, f"untouched case {i} must verify on both"
        if tv != cv:
            # Divergence is only legal kernel-stricter, and only when
            # the corrupted encoding decodes to a small-order point.
            assert not tv and cv
            _, key, sig = cases[i]
            a = PY._point_decompress(key)
            r = PY._point_decompress(sig[:32])
            small = False
            for p in (a, r):
                if p is None:
                    continue
                q = p
                for _ in range(3):
                    q = PY._point_add(q, q)
                if PY._point_equal(q, PY._NEUTRAL):
                    small = True
            assert small, (
                f"case {i}: verdicts diverge on a non-small-order input"
            )


def test_batch_positions_and_padding_boundaries():
    """Mask positions line up across a mixed batch spanning the pad
    boundary, and agree with the serial path elementwise."""
    kp = KeyPair.generate(rng.randbytes(32))
    cases = []
    for i in range(19):  # pads to 32
        m = rng.randbytes(32)
        s = sign(kp, m)
        if i % 3 == 0:
            s = s[:32] + bytes(32)  # S = 0: [0]B = identity != R
        cases.append((m, bytes(kp.name), s))
    t, c = assert_never_looser(cases, "positions")
    assert t == c
    assert t == [i % 3 != 0 for i in range(19)]


def test_mesh_sharded_verify_matches_single_device(monkeypatch):
    """NARWHAL_VERIFY_MESH=1 (stretch): the shard_map-sharded kernel
    over the conftest's 8-device virtual CPU mesh must produce the
    exact mask the single-device kernel does, across a mixed
    valid/invalid batch that exercises the raised pad floor
    (16 x devices)."""
    kp = KeyPair.generate(rng.randbytes(32))
    cases = []
    for i in range(21):
        m = rng.randbytes(32)
        s = sign(kp, m)
        if i % 4 == 0:
            s = s[:32] + (E.L_ORDER + 5).to_bytes(32, "little")
        cases.append((m, bytes(kp.name), s))
    plain = tpu_mask(cases)
    monkeypatch.setenv("NARWHAL_VERIFY_MESH", "1")
    assert E.mesh_devices() == len(jax.devices()) > 1
    sharded = tpu_mask(cases)
    assert sharded == plain == [i % 4 != 0 for i in range(21)]


def test_mesh_flag_off_is_single_device(monkeypatch):
    monkeypatch.delenv("NARWHAL_VERIFY_MESH", raising=False)
    assert E.mesh_devices() == 1


def test_backend_seam_masks_match_cpu_backend():
    """The crypto.backend seam itself: TpuBackend.verify_batch_mask ==
    CpuBackend.verify_batch_mask over a mixed valid/hostile batch of
    typed (Digest, PublicKey, Signature) inputs — the exact call shape
    Core's burst uses."""
    from narwhal_tpu.crypto.backend import CpuBackend
    from narwhal_tpu.crypto.digest import Digest
    from narwhal_tpu.crypto.keys import PublicKey, Signature
    from narwhal_tpu.ops.ed25519 import TpuBackend

    kp = KeyPair.generate(rng.randbytes(32))
    d = Digest(rng.randbytes(32))
    good = kp.sign(d)
    msgs = [bytes(d)] * 4
    keys = [PublicKey(kp.name)] * 4
    sigs = [
        good,
        Signature(bytes(64)),
        Signature(good[:32] + (0).to_bytes(32, "little")),
        good,
    ]
    t = TpuBackend().verify_batch_mask(msgs, keys, sigs)
    c = CpuBackend().verify_batch_mask(msgs, keys, sigs)
    assert list(t) == list(c) == [True, False, False, True]
