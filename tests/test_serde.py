from narwhal_tpu.utils.serde import Reader, Writer


def test_roundtrip():
    w = Writer()
    w.u8(7).u32(1_000_000).u64(2**50).bytes(b"hello").raw(b"\x01\x02")
    buf = w.finish()
    r = Reader(buf)
    assert r.u8() == 7
    assert r.u32() == 1_000_000
    assert r.u64() == 2**50
    assert r.bytes() == b"hello"
    assert r.raw(2) == b"\x01\x02"
    r.expect_done()


def test_underrun():
    r = Reader(b"\x01")
    try:
        r.u32()
        assert False
    except ValueError:
        pass


def test_trailing_detected():
    r = Reader(b"\x01\x02")
    r.u8()
    try:
        r.expect_done()
        assert False
    except ValueError:
        pass


def test_deterministic():
    a = Writer().u64(5).bytes(b"x").finish()
    b = Writer().u64(5).bytes(b"x").finish()
    assert a == b
