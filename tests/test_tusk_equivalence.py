"""Indexed Tusk vs the frozen r06 dict-walk oracle (consensus/golden.py).

The PR 4 commit-path rebuild (digest→certificate index, incremental
leader-support counters, one GC sweep per commit burst) must be
certificate-for-certificate — byte-identical commit order — equivalent to
the golden walk on every recorded stream: the reference scenarios,
multi-leader commit bursts, gc-window wrap, checkpoint restore, and
randomized DAGs (in-order and out-of-order delivery).  The white-box
tests additionally pin the two new state structures to their invariants:
index membership == DAG membership, and the incremental support counter
== the golden from-scratch rescan at every query point.
"""

import asyncio
import random

from narwhal_tpu import metrics
from narwhal_tpu.consensus import Consensus, Tusk
from narwhal_tpu.consensus.golden import GoldenTusk
from narwhal_tpu.primary.messages import Certificate, Header, genesis
from tests.common import committee, keys
from tests.test_consensus import (
    feed,
    genesis_digests,
    make_certificates,
    mock_certificate,
    sorted_names,
)


def both_walks(certs, gc_depth=50):
    """Feed the identical delivery order through the golden dict walk and
    the indexed walk; assert byte-identical commit sequences."""
    c = committee()
    golden = feed(GoldenTusk(c, gc_depth=gc_depth, fixed_coin=True), certs)
    indexed = feed(Tusk(c, gc_depth=gc_depth, fixed_coin=True), certs)
    assert [bytes(x.digest()) for x in indexed] == [
        bytes(x.digest()) for x in golden
    ]
    return golden


def _random_dag_certs(rng, rounds):
    names = sorted_names()
    certs = []
    parents = sorted(genesis_digests(committee()))
    for r in range(1, rounds + 1):
        live = rng.sample(names, rng.randint(3, 4))
        next_parents = []
        for name in sorted(live):
            chosen = rng.sample(
                parents, min(len(parents), rng.randint(3, len(parents)))
            )
            digest, cert = mock_certificate(name, r, chosen)
            certs.append(cert)
            next_parents.append(digest)
        parents = sorted(next_parents)
    return certs


def test_reference_scenarios_equivalence():
    """The four reference consensus_tests.rs scenarios, golden vs indexed."""
    c = committee()
    names = sorted_names()

    # commit_one
    certs, next_parents = make_certificates(1, 4, genesis_digests(c), names)
    _, trigger = mock_certificate(names[0], 5, next_parents)
    committed = both_walks(certs + [trigger])
    assert [x.round for x in committed] == [1, 1, 1, 1, 2]

    # dead_node
    certs, _ = make_certificates(1, 9, genesis_digests(c), names[:3])
    assert len(both_walks(certs)) == 16

    # missing_leader
    certs = []
    out, parents = make_certificates(1, 2, genesis_digests(c), names[1:])
    certs.extend(out)
    out, parents = make_certificates(3, 6, parents, names)
    certs.extend(out)
    _, trigger = mock_certificate(names[0], 7, parents)
    both_walks(certs + [trigger])


def test_multi_leader_burst_equivalence():
    """Odd rounds delivered before even rounds: nothing commits until one
    trigger certificate, which then commits the ENTIRE chain of linked
    leaders in one process_certificate call — the worst case for the
    per-certificate golden GC sweep the indexed walk batches."""
    c = committee()
    names = sorted_names()
    certs, parents = make_certificates(1, 16, genesis_digests(c), names)
    order = sorted(certs, key=lambda x: (x.round % 2 == 0, x.round))
    _, trigger = mock_certificate(names[0], 17, parents)

    golden = GoldenTusk(c, gc_depth=50, fixed_coin=True)
    indexed = Tusk(c, gc_depth=50, fixed_coin=True)
    assert feed(golden, order) == []
    assert feed(indexed, order) == []
    got = indexed.process_certificate(trigger)
    want = golden.process_certificate(trigger)
    assert [bytes(x.digest()) for x in got] == [
        bytes(x.digest()) for x in want
    ]
    # The burst spans several leader rounds (multi-leader commit).
    assert len({x.round for x in got if x.round % 2 == 0}) >= 3


def test_gc_window_wrap_equivalence():
    """Continuous commits across several multiples of a small gc window:
    the batched sweep must leave the DAG (and therefore every later
    commit) exactly where the golden per-certificate sweep leaves it."""
    c = committee()
    names = sorted_names()
    certs, _ = make_certificates(1, 30, genesis_digests(c), names)
    golden = GoldenTusk(c, gc_depth=6, fixed_coin=True)
    indexed = Tusk(c, gc_depth=6, fixed_coin=True)
    got_g = feed(golden, certs)
    got_i = feed(indexed, certs)
    assert [bytes(x.digest()) for x in got_i] == [
        bytes(x.digest()) for x in got_g
    ]
    assert got_g, "fixture must commit"
    # End-state parity, not just sequence parity: same frontier, same
    # surviving DAG window.
    assert indexed.state.last_committed == golden.state.last_committed
    assert indexed.state.last_committed_round == golden.state.last_committed_round
    assert {
        r: set(v) for r, v in indexed.state.dag.items()
    } == {r: set(v) for r, v in golden.state.dag.items()}


def test_checkpoint_restore_equivalence():
    """Both walks restored from the same frontier blob must ignore a full
    catch-up replay of pre-crash history and then commit new rounds
    byte-identically."""
    c = committee()
    names = sorted_names()
    certs, next_parents = make_certificates(1, 4, genesis_digests(c), names)
    _, trigger = mock_certificate(names[0], 5, next_parents)

    first = GoldenTusk(c, gc_depth=50, fixed_coin=True)
    assert feed(first, certs + [trigger])
    blob = first.state.snapshot_bytes()

    golden = GoldenTusk(c, gc_depth=50, fixed_coin=True)
    golden.state.restore(blob)
    indexed = Tusk(c, gc_depth=50, fixed_coin=True)
    indexed.state.restore(blob)
    assert feed(golden, certs + [trigger]) == []
    assert feed(indexed, certs + [trigger]) == []

    more, tail_parents = make_certificates(5, 8, next_parents, names)
    more = more[1:]  # round-5 leader already exists as `trigger`
    _, trigger2 = mock_certificate(names[0], 9, tail_parents)
    got = feed(indexed, more + [trigger2])
    want = feed(golden, more + [trigger2])
    assert [bytes(x.digest()) for x in got] == [
        bytes(x.digest()) for x in want
    ]
    assert got, "the restored instances must keep committing"


def test_fuzz_equivalence_in_and_out_of_order():
    rng = random.Random(0x1D5)
    for trial in range(6):
        certs = _random_dag_certs(rng, rounds=rng.randint(6, 20))
        order = list(certs)
        order.sort(key=lambda x: (x.round, rng.random()))
        both_walks(order)
    for trial in range(4):
        certs = _random_dag_certs(rng, rounds=rng.randint(6, 16))
        order = list(certs)
        # Children ahead of their parents in delivery order.
        order.sort(key=lambda x: x.round + rng.uniform(-2.2, 0.0))
        both_walks(order)


def test_fuzz_small_gc_depth_equivalence():
    rng = random.Random(0x6C)
    for _ in range(3):
        both_walks(_random_dag_certs(rng, rounds=14), gc_depth=4)


# -- white-box: the two new indexed structures --------------------------------


def _dag_index(state):
    return {
        d: cert
        for authorities in state.dag.values()
        for (d, cert) in authorities.values()
    }


def test_digest_index_is_exactly_dag_membership():
    """After arbitrary feeds (commits, GC, replays), digest_index holds
    exactly the certificates currently in the DAG — the invariant
    order_dag/linked rely on for O(1) parent resolution."""
    rng = random.Random(0xF00)
    for gc_depth in (50, 6):
        for _ in range(3):
            certs = _random_dag_certs(rng, rounds=rng.randint(8, 20))
            tusk = Tusk(committee(), gc_depth=gc_depth, fixed_coin=True)
            feed(tusk, certs)
            want = _dag_index(tusk.state)
            assert dict(tusk.state.digest_index) == want
            # Replay everything (catch-up flood): still exact.
            feed(tusk, certs)
            assert dict(tusk.state.digest_index) == _dag_index(tusk.state)


def _rescan_support(tusk, leader_round):
    got = tusk.leader(leader_round, tusk.state.dag)
    if got is None:
        return 0
    leader_digest = got[0]
    return sum(
        tusk.committee.stake(cert.origin)
        for _, cert in tusk.state.dag.get(leader_round + 1, {}).values()
        if leader_digest in cert.header.parents
    )


def test_incremental_support_matches_rescan():
    """At every point the commit rule can query it (even rounds above the
    committed frontier), the incremental counter equals the golden
    from-scratch rescan of the child round — including streams where the
    leader arrives AFTER its supporters (the seeding path)."""
    rng = random.Random(0x5AB)
    for trial in range(5):
        certs = _random_dag_certs(rng, rounds=rng.randint(6, 16))
        order = list(certs)
        if trial % 2:
            order.sort(key=lambda x: x.round + rng.uniform(-2.2, 0.0))
        tusk = Tusk(committee(), gc_depth=50, fixed_coin=True)
        for cert in order:
            tusk.process_certificate(cert)
            top = max(tusk.state.dag)
            for lr in range(
                tusk.state.last_committed_round + 2, top + 1, 2
            ):
                assert tusk._support.get(lr, 0) == _rescan_support(
                    tusk, lr
                ), (trial, lr)


def test_support_exact_after_equivocation_overwrite():
    """An equivocating certificate replacing a (round, origin) slot —
    either a supporter changing its parents or the leader itself changing
    digest — must leave the counter equal to the rescan (the recompute
    path)."""
    c = committee()
    names = sorted_names()
    certs, parents = make_certificates(1, 4, genesis_digests(c), names)
    tusk = Tusk(c, gc_depth=50, fixed_coin=True)
    feed(tusk, certs)

    def equivocate(author, round_, parents):
        # Mock certs leave header.id at zero (digest ignores parents);
        # an equivocating twin needs a genuinely different digest, so
        # compute the real header id.
        header = Header(
            author=author, round=round_, payload={}, parents=set(parents)
        )
        header.id = header.compute_digest()
        return Certificate(header=header)

    # Supporter overwrite: names[1]'s round-3 certificate re-issued with a
    # thinner parent set that drops the round-2 leader.
    leader_digest = tusk.leader(2, tusk.state.dag)[0]
    thin = {
        d for d, _ in tusk.state.dag[2].values() if d != leader_digest
    }
    twin = equivocate(names[1], 3, thin)
    assert twin.digest() != tusk.state.dag[3][names[1]][0]
    tusk.insert_certificate(twin)
    assert tusk._support.get(2, 0) == _rescan_support(tusk, 2)

    # Leader overwrite: the round-2 leader re-issued with different
    # parents → different digest; all round-3 support must be re-counted
    # against the NEW digest.
    old_leader = tusk.state.dag[2][names[0]][1]
    relead = equivocate(
        names[0], 2, set(list(old_leader.header.parents)[:3])
    )
    assert relead.digest() != old_leader.digest()
    tusk.insert_certificate(relead)
    assert tusk._support.get(2, 0) == _rescan_support(tusk, 2)


def test_runner_burst_drains_backlog():
    """A backlog queued before the runner wakes is processed in ONE drain
    (the drain histogram observes one large batch, not one-per-wakeup),
    and the delivered order matches the pure state machine."""
    reg = metrics.registry()
    reg.reset()

    async def go():
        c = committee()
        names = sorted_names()
        certs, next_parents = make_certificates(
            1, 8, genesis_digests(c), names
        )
        _, trigger = mock_certificate(names[0], 9, next_parents)
        certs.append(trigger)

        rx, tx_primary, tx_output = (
            asyncio.Queue(),
            asyncio.Queue(),
            asyncio.Queue(),
        )
        consensus = Consensus(
            c, 50, rx, tx_primary, tx_output, fixed_coin=True
        )
        for cert in certs:  # whole backlog queued BEFORE the runner starts
            rx.put_nowait(cert)
        task = asyncio.ensure_future(consensus.run())
        want = feed(Tusk(c, gc_depth=50, fixed_coin=True), certs)
        assert want
        out = [
            await asyncio.wait_for(tx_output.get(), 5)
            for _ in range(len(want))
        ]
        assert [bytes(x.digest()) for x in out] == [
            bytes(x.digest()) for x in want
        ]
        task.cancel()

        drain = reg.histograms["consensus.drain_batch_size"]
        assert drain.count >= 1
        assert drain.sum == len(certs), "every certificate drained exactly once"
        # The backlog collapsed into few wakeups, not one per certificate.
        assert drain.count < len(certs)

    asyncio.run(asyncio.wait_for(go(), 15))
