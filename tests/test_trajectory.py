"""benchmark/trajectory.py tests: loaders per artifact shape, graceful
skip of missing/malformed/rc!=0/zero-valued files, the attr. namespace
split for fixed-rate artifacts, regression detection with pinned
tolerances, waivers, and the gate's exit codes — including that the
REPO'S OWN committed artifacts pass the gate while the known r05
regression is flagged (waived)."""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmark import trajectory  # noqa: E402


def write(path, obj):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        if isinstance(obj, str):
            f.write(obj)
        else:
            json.dump(obj, f)


def driver_bench(value, rc=0, metric="end_to_end_tps_local_4n", **extra):
    return {
        "n": 1,
        "cmd": "python bench.py",
        "rc": rc,
        "parsed": {"metric": metric, "value": value, "unit": "tx/s", **extra},
    }


def gate_config(tmp_path, tolerances=None, waivers=None):
    p = str(tmp_path / "gate.json")
    write(p, {
        "tolerances": tolerances
        if tolerances is not None
        else {"end_to_end_tps": 0.15},
        "waivers": waivers or [],
    })
    return p


def test_collect_revisions_and_graceful_skips(tmp_path, capsys):
    root = str(tmp_path)
    write(f"{root}/BENCH_r01.json", driver_bench(10_000))
    write(f"{root}/BENCH_r02.json", driver_bench(12_000))
    # rc != 0: warn and skip, never crash the gate.
    write(f"{root}/BENCH_r03.json", driver_bench(9_000, rc=1))
    # Failed measurement published zeros with a clean rc (the real
    # r03/r04 shape): unusable, skipped.
    write(f"{root}/BENCH_r04.json", driver_bench(0.0))
    # Malformed JSON: skip.
    write(f"{root}/BENCH_r05.json", "{not json")
    # Unrecognized artifact shape: skip with reason.
    write(f"{root}/artifacts/foo_r02.json", {"rows": [1, 2, 3]})
    # before/pre arms are skipped by design.
    write(
        f"{root}/artifacts/thing_r02_before.json",
        {"end_to_end_tps": 1.0},
    )
    revisions, skipped = trajectory.collect(root)
    assert sorted(revisions) == ["r01", "r02"]
    assert revisions["r01"]["metrics"]["end_to_end_tps"] == 10_000
    reasons = {s["file"]: s["reason"] for s in skipped}
    assert "rc=1" in reasons["BENCH_r03.json"]
    assert "no usable measurement" in reasons["BENCH_r04.json"]
    assert "malformed" in reasons["BENCH_r05.json"]
    assert "unrecognized" in reasons[os.path.join("artifacts", "foo_r02.json")]
    assert "skipped by design" in reasons[
        os.path.join("artifacts", "thing_r02_before.json")
    ]


def test_artifacts_feed_attr_namespace_not_the_gate(tmp_path):
    """Fixed-rate artifacts/ captures are cross-revision comparable with
    each other but not with the saturation-probe driver numbers — they
    land under attr.* which the gate config never names."""
    root = str(tmp_path)
    write(f"{root}/BENCH_r01.json", driver_bench(10_000))
    write(
        f"{root}/artifacts/breakdown_r01.json",
        {
            "consensus_tps": 2_000,
            "stages_ms": {"seal_to_commit": 2_100.0},
        },
    )
    revisions, _ = trajectory.collect(root)
    m = revisions["r01"]["metrics"]
    assert m["end_to_end_tps"] == 10_000
    assert m["attr.consensus_tps"] == 2_000
    assert m["attr.stage.seal_to_commit"] == 2_100.0
    assert "consensus_tps" not in m


def test_runs_artifact_takes_median(tmp_path):
    root = str(tmp_path)
    write(
        f"{root}/artifacts/ab_r07.json",
        {
            "runs": [
                {"end_to_end_tps": 100.0},
                {"end_to_end_tps": 300.0},
                {"end_to_end_tps": 200.0},
            ]
        },
    )
    revisions, _ = trajectory.collect(root)
    assert revisions["r07"]["metrics"]["attr.end_to_end_tps"] == 200.0


def test_regression_against_best_prior_revision():
    series = {
        "end_to_end_tps": [
            ("r01", 10_000.0),
            ("r02", 12_000.0),
            ("r03", 11_000.0),  # -8.3% vs r02: inside 15%
            ("r04", 9_000.0),  # -25% vs r02: regression
        ],
        "end_to_end_latency_ms": [
            ("r01", 800.0),
            ("r02", 2_000.0),  # +150% vs r01: regression (lower-better)
        ],
    }
    config = {
        "tolerances": {
            "end_to_end_tps": 0.15,
            "end_to_end_latency_ms": 0.5,
        },
        "waivers": [],
    }
    regs = trajectory.find_regressions(series, config)
    assert [(r["metric"], r["revision"]) for r in regs] == [
        ("end_to_end_latency_ms", "r02"),
        ("end_to_end_tps", "r04"),
    ]
    tps = next(r for r in regs if r["metric"] == "end_to_end_tps")
    assert tps["baseline_revision"] == "r02"
    assert tps["change_pct"] == -25.0
    assert not tps["waived"]


def test_waiver_keeps_regression_in_report_but_gate_green(tmp_path, capsys):
    root = str(tmp_path)
    write(f"{root}/BENCH_r01.json", driver_bench(10_000))
    write(f"{root}/BENCH_r02.json", driver_bench(5_000))
    cfg = gate_config(
        tmp_path,
        waivers=[
            {
                "metric": "end_to_end_tps",
                "revision": "r02",
                "reason": "known, owned elsewhere",
            }
        ],
    )
    report = str(tmp_path / "report.json")
    rc = trajectory.main(
        ["--root", root, "--gate-config", cfg, "--report", report]
    )
    assert rc == 0
    rep = json.load(open(report))
    assert len(rep["regressions"]) == 1
    assert rep["regressions"][0]["waived"] is True
    assert rep["gate"]["unwaived_regressions"] == 0


def test_gate_fails_nonzero_on_injected_synthetic_regression(tmp_path):
    root = str(tmp_path)
    write(f"{root}/BENCH_r01.json", driver_bench(10_000))
    write(f"{root}/BENCH_r02.json", driver_bench(4_000))  # -60%
    cfg = gate_config(tmp_path)
    rc = trajectory.main(["--root", root, "--gate-config", cfg, "--quiet"])
    assert rc == 2
    # --no-gate reports but never fails.
    assert (
        trajectory.main(
            ["--root", root, "--gate-config", cfg, "--no-gate", "--quiet"]
        )
        == 0
    )


def test_missing_gate_config_disables_gating_loudly(tmp_path, capsys):
    root = str(tmp_path)
    write(f"{root}/BENCH_r01.json", driver_bench(10_000))
    write(f"{root}/BENCH_r02.json", driver_bench(1_000))
    rc = trajectory.main(
        [
            "--root", root,
            "--gate-config", str(tmp_path / "nope.json"),
            "--quiet",
        ]
    )
    assert rc == 0
    assert "gating disabled" in capsys.readouterr().err


def test_empty_root_reports_nothing_and_passes(tmp_path):
    rc = trajectory.main(
        [
            "--root", str(tmp_path),
            "--gate-config", str(tmp_path / "nope.json"),
            "--quiet",
        ]
    )
    assert rc == 0


def test_repo_committed_artifacts_pass_with_r05_waived():
    """The acceptance pin: over THIS repo's committed BENCH_r*.json the
    gate is green, all five driver artifacts are covered (r03/r04 as
    explicit skips — they published zeros for failed runs), and the r05
    e2e regression is flagged but waived by name."""
    revisions, skipped = trajectory.collect(trajectory.REPO, quiet=True)
    assert {"r01", "r02", "r05"} <= set(revisions)
    skipped_files = {s["file"] for s in skipped}
    assert {"BENCH_r03.json", "BENCH_r04.json"} <= skipped_files
    series = trajectory.build_series(revisions)
    config = trajectory.load_gate_config(trajectory.DEFAULT_GATE_CONFIG)
    regs = trajectory.find_regressions(series, config)
    r05 = [r for r in regs if r["revision"] == "r05"]
    assert r05, "the r05 e2e regression must be detected"
    assert all(r["waived"] for r in regs), (
        "committed history must carry no unwaived regression: "
        + repr([r for r in regs if not r["waived"]])
    )
    tps = next(r for r in r05 if r["metric"] == "end_to_end_tps")
    assert tps["baseline_revision"] == "r02"


def test_knee_matrix_artifact_flattens_to_attr_namespace(tmp_path):
    """A benchmark/knee_matrix artifact loads as knee.n<N>.* metrics —
    attribution-namespaced via its artifacts/ placement, never gated —
    and a matrix with no located knees is skipped with a reason."""
    root = str(tmp_path)
    write(
        f"{root}/artifacts/knee_matrix_r21.json",
        {
            "generated_by": "benchmark/knee_matrix",
            "configs": [
                {
                    "n": 4,
                    "mode": "socketed",
                    "points": [],
                    "knee": {
                        "rate": 20_000,
                        "tps": 11_000.0,
                        "latency_ms": 1_900.0,
                        "first_saturating": {
                            "channel": "worker.to_quorum",
                        },
                    },
                },
                {"n": 10, "mode": "sim", "points": [], "knee": {}},
            ],
        },
    )
    revisions, _ = trajectory.collect(root)
    m = revisions["r21"]["metrics"]
    assert m["attr.knee.n4.rate"] == 20_000
    assert m["attr.knee.n4.tps"] == 11_000.0
    assert m["attr.knee.n4.latency_ms"] == 1_900.0
    assert not any(k.startswith("attr.knee.n10.") for k in m)

    write(
        f"{root}/artifacts/knee_matrix_r22.json",
        {"generated_by": "benchmark/knee_matrix", "configs": []},
    )
    _, skipped = trajectory.collect(root)
    reasons = {s["file"]: s["reason"] for s in skipped}
    assert "without located knees" in reasons[
        os.path.join("artifacts", "knee_matrix_r22.json")
    ]
