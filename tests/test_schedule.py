"""ExploringEventLoop + race-explore acceptance suite (ISSUE 10, dynamic
half).

- the loop permutes same-tick task wakeups deterministically from its
  seed (same seed → same order, different seeds → different orders);
- non-task callbacks keep their FIFO order (asyncio's internal plumbing
  relies on it — the sock_connect/_sock_write_done contract);
- the clean pipeline scenario commits byte-identically to the golden
  walk under every seed, and a seed is reproducible end-to-end;
- the planted RacyConsensus race DIVERGES under a known seed before the
  fix shape (the mutation) and the clean Consensus passes under the SAME
  seed — the seed-pinned regression pattern the triage satellite asks
  for, with the found-race as its subject.
"""

import asyncio
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from narwhal_tpu.analysis.schedule import (  # noqa: E402
    ExploringEventLoop,
    run_with_seed,
)


def _order_probe(n_tasks: int = 6, rounds: int = 5):
    """N tasks that each append their id per round, with a yield between
    appends: the final order is a pure function of the schedule."""

    async def main():
        out = []
        gate = asyncio.Event()

        async def worker(i):
            await gate.wait()
            for _ in range(rounds):
                out.append(i)
                await asyncio.sleep(0)

        tasks = [
            asyncio.get_running_loop().create_task(worker(i))
            for i in range(n_tasks)
        ]
        gate.set()  # all workers become runnable in the same tick
        await asyncio.gather(*tasks)
        return tuple(out)

    return main


def test_same_seed_same_schedule():
    a, stats_a = run_with_seed(_order_probe(), seed=7, timeout=30)
    b, stats_b = run_with_seed(_order_probe(), seed=7, timeout=30)
    assert a == b
    assert stats_a["permutations"] > 0, "probe explored nothing"


def test_different_seeds_explore_different_schedules():
    orders = {
        run_with_seed(_order_probe(), seed=s, timeout=30)[0]
        for s in range(8)
    }
    assert len(orders) > 1, "eight seeds produced one schedule"


def test_plain_callbacks_keep_fifo_order():
    """call_soon callbacks (non-task) must NEVER be reordered, whatever
    the seed — asyncio's internals depend on their FIFO contract."""
    for seed in range(5):
        async def main():
            out = []
            loop = asyncio.get_running_loop()
            done = asyncio.Event()
            for i in range(10):
                loop.call_soon(out.append, i)
            loop.call_soon(done.set)
            await done.wait()
            return out

        out, _ = run_with_seed(main, seed=seed, timeout=30)
        assert out == list(range(10)), (seed, out)


def test_stats_and_loop_attributes():
    loop = ExploringEventLoop(seed=3)
    try:
        assert loop.seed == 3 and loop.permutations == 0
    finally:
        loop.close()


# -- pipeline scenario: the seed-pinned regression pair -----------------------

PINNED_SEED = 1000  # the seed race_explore's mutation arm diverges at


def test_clean_pipeline_is_byte_identical_under_pinned_seed(tmp_path):
    from benchmark.race_explore import run_pipeline_seed

    report = run_pipeline_seed(PINNED_SEED, str(tmp_path))
    assert report["ok"], report
    assert report["identical_to_golden"] and report["audit_replay_ok"]
    assert report["schedule"]["permutations"] >= 10, (
        "the reference scenario has gone vacuous"
    )


def test_planted_race_diverges_under_pinned_seed_and_is_reproducible(
    tmp_path,
):
    """The regression pair: the mutated (pre-fix) shape diverges under
    this exact seed; the clean (fixed) shape passes under it (previous
    test).  Divergence itself is deterministic: the same seed re-run
    produces the same diverging byte sequence — the repro contract.

    No slow-host skip: the pipeline arm runs on the VIRTUAL clock, so
    the quiesce polls and the deadlock guard are pure functions of the
    seed — a guard trip would be a deterministic finding, never a
    host-speed artifact, and byte-reproducibility holds unconditionally."""
    from benchmark.race_explore import run_pipeline_seed

    first = run_pipeline_seed(PINNED_SEED, str(tmp_path), mutated=True)
    assert not first["ok"], (
        "the planted RacyConsensus race no longer diverges at the "
        "pinned seed — the dynamic half went blind"
    )
    again = run_pipeline_seed(PINNED_SEED, str(tmp_path), mutated=True)
    assert not first["guard_tripped"] and not again["guard_tripped"], (
        "virtual-time guard tripped: the pipeline scenario deadlocked "
        "deterministically under this seed"
    )
    assert again["sequence_sha"] == first["sequence_sha"]
    assert again["commits"] == first["commits"]


def test_divergence_is_detected_by_the_audit_replay_too(tmp_path):
    """The oracle replay is an independent judge: the racy run's audit
    segment must fail replay (duplicate/lost commits), not just the
    byte-compare against the golden walk."""
    from benchmark.race_explore import run_pipeline_seed

    report = run_pipeline_seed(PINNED_SEED, str(tmp_path), mutated=True)
    assert not (report["identical_to_golden"] and report["audit_replay_ok"])
