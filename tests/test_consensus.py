"""Tusk golden tests (analog of reference consensus_tests.rs): synthetic
certificate DAGs with no signatures and no network, leader coin pinned to
authority 0, exact commit sequences asserted."""

import asyncio

from narwhal_tpu.crypto import Digest
from narwhal_tpu.primary.messages import Certificate, Header, genesis
from narwhal_tpu.consensus import Consensus, Tusk
from tests.common import committee, keys


def mock_certificate(origin, round_, parents):
    cert = Certificate(
        header=Header(
            author=origin, round=round_, payload={}, parents=set(parents)
        )
    )
    return cert.digest(), cert


def make_certificates(start, stop, initial_parents, names):
    """One certificate per authority for rounds [start, stop]; returns the
    certificates and the digests to use as next parents."""
    certificates = []
    parents = set(initial_parents)
    next_parents = set()
    for round_ in range(start, stop + 1):
        next_parents = set()
        for name in names:
            digest, cert = mock_certificate(name, round_, parents)
            certificates.append(cert)
            next_parents.add(digest)
        parents = set(next_parents)
    return certificates, next_parents


def sorted_names():
    return sorted(kp.name for kp in keys())


def genesis_digests(c):
    return {x.digest() for x in genesis(c)}


def feed(tusk, certificates):
    committed = []
    for cert in certificates:
        committed.extend(tusk.process_certificate(cert))
    return committed


def test_commit_one():
    """4 ideal rounds: the leader of round 2 commits with its round-1
    parents (reference consensus_tests.rs commit_one)."""
    c = committee()
    names = sorted_names()
    certs, next_parents = make_certificates(1, 4, genesis_digests(c), names)
    _, trigger = mock_certificate(names[0], 5, next_parents)
    certs.append(trigger)

    tusk = Tusk(c, gc_depth=50, fixed_coin=True)
    committed = feed(tusk, certs)
    assert [x.round for x in committed] == [1, 1, 1, 1, 2]


def test_dead_node():
    """One dead (non-leader) node across 9 rounds: leaders of rounds 2, 4, 6
    commit; sequence interleaves whole rounds of 3."""
    c = committee()
    names = sorted_names()[:3]  # drop the last authority
    certs, _ = make_certificates(1, 9, genesis_digests(c), names)

    tusk = Tusk(c, gc_depth=50, fixed_coin=True)
    committed = feed(tusk, certs)
    rounds = [x.round for x in committed]
    expected = [(i - 1) // 3 + 1 for i in range(1, 16)] + [6]
    assert rounds[:16] == expected


def test_not_enough_support():
    """The leader of round 2 lacks f+1 support at first; it commits later,
    before the leader of round 4 (reference not_enough_support)."""
    c = committee()
    names = sorted_names()
    certs = []

    # Round 1: fully connected among the first 3 nodes.
    out, parents = make_certificates(1, 1, genesis_digests(c), names[:3])
    certs.extend(out)

    # Round 2: the only round with 4 certificates; remember the leader's.
    leader_2_digest, cert = mock_certificate(names[0], 2, parents)
    certs.append(cert)
    out, parents = make_certificates(2, 2, parents, names[1:])
    certs.extend(out)

    # Round 3: only node 0 links to the round-2 leader.
    next_parents = set()
    d, cert = mock_certificate(names[1], 3, parents)
    certs.append(cert)
    next_parents.add(d)
    d, cert = mock_certificate(names[2], 3, parents)
    certs.append(cert)
    next_parents.add(d)
    d, cert = mock_certificate(names[0], 3, parents | {leader_2_digest})
    certs.append(cert)
    next_parents.add(d)
    parents = next_parents

    # Rounds 4-6: fully connected among the first 3 nodes.
    out, parents = make_certificates(4, 6, parents, names[:3])
    certs.extend(out)

    # Round 7 triggers the commits.
    _, trigger = mock_certificate(names[0], 7, parents)
    certs.append(trigger)

    tusk = Tusk(c, gc_depth=50, fixed_coin=True)
    committed = feed(tusk, certs)
    rounds = [x.round for x in committed]
    assert rounds[:11] == [1, 1, 1, 2, 2, 2, 2, 3, 3, 3, 4]


def test_missing_leader():
    """Node 0 (the leader) is absent in rounds 1-2 and reappears from round
    3: nothing commits until the leader of round 4 (reference
    missing_leader)."""
    c = committee()
    names = sorted_names()
    certs = []
    out, parents = make_certificates(1, 2, genesis_digests(c), names[1:])
    certs.extend(out)
    out, parents = make_certificates(3, 6, parents, names)
    certs.extend(out)
    _, trigger = mock_certificate(names[0], 7, parents)
    certs.append(trigger)

    tusk = Tusk(c, gc_depth=50, fixed_coin=True)
    committed = feed(tusk, certs)
    rounds = [x.round for x in committed]
    assert rounds[:11] == [1, 1, 1, 2, 2, 2, 3, 3, 3, 3, 4]


def test_idempotent_no_double_commit():
    """Feeding the same certificates again commits nothing new."""
    c = committee()
    names = sorted_names()
    certs, next_parents = make_certificates(1, 4, genesis_digests(c), names)
    _, trigger = mock_certificate(names[0], 5, next_parents)

    tusk = Tusk(c, gc_depth=50, fixed_coin=True)
    committed = feed(tusk, certs + [trigger])
    assert len(committed) == 5
    committed_again = feed(tusk, certs + [trigger])
    assert committed_again == []


def test_async_consensus_runner():
    """The async wrapper forwards commits to both outputs in order."""

    async def go():
        c = committee()
        names = sorted_names()
        certs, next_parents = make_certificates(1, 4, genesis_digests(c), names)
        _, trigger = mock_certificate(names[0], 5, next_parents)
        certs.append(trigger)

        rx, tx_primary, tx_output = (
            asyncio.Queue(),
            asyncio.Queue(),
            asyncio.Queue(),
        )
        consensus = Consensus(c, 50, rx, tx_primary, tx_output, fixed_coin=True)
        task = asyncio.ensure_future(consensus.run())
        for cert in certs:
            await rx.put(cert)
        out = [await asyncio.wait_for(tx_output.get(), 5) for _ in range(5)]
        fb = [await asyncio.wait_for(tx_primary.get(), 5) for _ in range(5)]
        assert [x.round for x in out] == [1, 1, 1, 1, 2]
        assert [x.digest() for x in fb] == [x.digest() for x in out]
        task.cancel()

    asyncio.run(asyncio.wait_for(go(), 15))


def test_restore_torn_blob_raises_without_mutation():
    """A truncated/corrupt checkpoint must raise BEFORE any state mutates:
    the caller's fallback is the fresh frontier, which must be intact
    (ADVICE.md r05 — the old code assigned last_committed_round before
    validating the length)."""
    import pytest

    c = committee()
    names = sorted_names()
    certs, next_parents = make_certificates(1, 4, genesis_digests(c), names)
    _, trigger = mock_certificate(names[0], 5, next_parents)
    tusk = Tusk(c, gc_depth=50, fixed_coin=True)
    assert feed(tusk, certs + [trigger])
    blob = tusk.state.snapshot_bytes()

    fresh = Tusk(c, gc_depth=50, fixed_coin=True)
    before_round = fresh.state.last_committed_round
    before_map = dict(fresh.state.last_committed)
    for bad in (blob[: len(blob) // 2], b"", b"JUNK!!" + blob[6:], blob[:17]):
        with pytest.raises(ValueError):
            fresh.state.restore(bad)
        assert fresh.state.last_committed_round == before_round
        assert fresh.state.last_committed == before_map


def test_corrupt_checkpoint_boots_fresh_and_commits(tmp_path):
    """A torn checkpoint file on disk must not crash-loop the node: the
    Consensus boot logs loudly, ignores it, and commits from a fresh
    frontier (the reference's behavior — it has no checkpoint at all)."""

    async def go():
        ckpt = str(tmp_path / "consensus.ckpt")
        with open(ckpt, "wb") as f:
            f.write(b"NCKPT1\x00\x01")  # torn mid-write

        c = committee()
        names = sorted_names()
        certs, next_parents = make_certificates(1, 4, genesis_digests(c), names)
        _, trigger = mock_certificate(names[0], 5, next_parents)
        certs.append(trigger)

        rx, tx_primary, tx_output = (
            asyncio.Queue(),
            asyncio.Queue(),
            asyncio.Queue(),
        )
        consensus = Consensus(
            c, 50, rx, tx_primary, tx_output,
            fixed_coin=True, checkpoint_path=ckpt,
        )
        assert consensus.tusk.state.last_committed_round == 0  # fresh
        task = asyncio.ensure_future(consensus.run())
        for cert in certs:
            await rx.put(cert)
        out = [await asyncio.wait_for(tx_output.get(), 5) for _ in range(5)]
        assert [x.round for x in out] == [1, 1, 1, 1, 2]
        # The commit rewrote the checkpoint: a restart now restores
        # cleanly.  The rewrite runs in the executor (off the event
        # loop, PR 4), so poll for the write to land BEFORE cancelling
        # the runner — cancelling first could cancel a not-yet-started
        # executor job and the file would never appear.
        state = Tusk(c, gc_depth=50, fixed_coin=True).state
        for _ in range(100):
            with open(ckpt, "rb") as f:
                blob = f.read()
            try:
                state.restore(blob)
                break
            except ValueError:
                await asyncio.sleep(0.05)
        task.cancel()
        assert state.last_committed_round == 2

    asyncio.run(asyncio.wait_for(go(), 15))


def test_checkpoint_restore_resumes_without_redelivery():
    """Committed-frontier checkpointing (beyond reference parity —
    consensus/src/lib.rs:18-19 marks persisted consensus state as
    intended-but-unimplemented).  A restored Tusk fed the FULL certificate
    history again (the worst-case catch-up replay: e.g. a lagging peer
    rebroadcasting old rounds through the Core) must not re-deliver
    anything already committed, and must resume committing new rounds."""
    c = committee()
    names = sorted_names()
    certs, next_parents = make_certificates(1, 4, genesis_digests(c), names)
    _, trigger = mock_certificate(names[0], 5, next_parents)

    first = Tusk(c, gc_depth=50, fixed_coin=True)
    committed = feed(first, certs + [trigger])
    assert committed, "fixture must commit something"
    blob = first.state.snapshot_bytes()

    # "Restart": fresh Tusk, restore the frontier, replay ALL certificates
    # (pre-crash history + the trigger) as a catch-up flood would.
    second = Tusk(c, gc_depth=50, fixed_coin=True)
    second.state.restore(blob)
    assert second.state.last_committed_round == first.state.last_committed_round
    replayed = feed(second, certs + [trigger])
    assert replayed == [], (
        "restored frontier must keep replayed history out of the sequence: "
        f"{[(x.origin, x.round) for x in replayed]}"
    )

    # New rounds after the replay commit exactly what the uninterrupted
    # instance commits for them.
    more, tail_parents = make_certificates(5, 8, next_parents, names)
    more = more[1:]  # round-5 leader already exists as `trigger`
    _, trigger2 = mock_certificate(names[0], 9, tail_parents)
    got = feed(second, more + [trigger2])
    want = feed(first, more + [trigger2])
    assert [x.digest() for x in got] == [x.digest() for x in want]
    assert got, "the resumed instance must keep committing"
