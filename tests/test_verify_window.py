"""Verify-batch accumulation window (ISSUE r19, ROADMAP item 1): with
NARWHAL_VERIFY_BATCH_WINDOW_MS > 0 the Core routes drained peer bursts
through a pipelined verify stage that coalesces cross-message-type
signature claims from MULTIPLE drains into ONE backend dispatch — the
serial→batched conversion the crypto ledger must show as a batch-size
distribution shift.  These tests pin the coalescing (one batch_burst
call covering several puts), the replay semantics (every message still
processed, per-kind claim arithmetic intact), the batch-max bound, and
backend-selection ergonomics (strict boot failure vs explicit cpu
fallback, env/CLI precedence)."""

import asyncio
import sys

import pytest

from narwhal_tpu import metrics
from narwhal_tpu.crypto import backend as cb
from tests.common import (
    committee,
    keys,
    make_certificate,
    make_header,
)
from tests.test_core import make_core


def run(coro, timeout=30):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def cnt(name: str) -> float:
    c = metrics.registry().counters.get(name)
    return c.value if c is not None else 0


def hist_count(name: str) -> int:
    h = metrics.registry().histograms.get(name)
    return h.count if h is not None else 0


def make_window_core(c, me, window_ms=200.0, batch_max=256):
    core, store, qs = make_core(c, me)
    # Reconfigure the window post-construction (make_core builds with
    # the env default, off): the queue exists iff the window is on.
    core.verify_window_s = window_ms / 1000.0
    core.verify_batch_max = batch_max
    core._verify_q = asyncio.Queue(maxsize=max(256, 2 * batch_max))
    return core, store, qs


async def drive(core, qs, items, done, deadline_s=15.0):
    """Run core.run() while feeding ``items`` into rx_primaries in two
    spaced puts (two separate drains that the window must coalesce),
    then poll until ``done()`` (a counter predicate) or the deadline."""
    task = asyncio.get_running_loop().create_task(core.run())
    try:
        half = max(1, len(items) // 2)
        for it in items[:half]:
            qs["primaries"].put_nowait(it)
        # Let run() drain the first chunk into the verify queue, then
        # land the second chunk inside the accumulation window.
        for _ in range(4):
            await asyncio.sleep(0)
        for it in items[half:]:
            qs["primaries"].put_nowait(it)
        loop = asyncio.get_running_loop()
        stop = loop.time() + deadline_s
        while not done() and loop.time() < stop:
            await asyncio.sleep(0.01)
        assert done(), "burst never replayed within the deadline"
    finally:
        task.cancel()
        await asyncio.gather(task, return_exceptions=True)
        core.network.close()


def test_window_coalesces_two_drains_into_one_dispatch():
    """Certificates landing in two separate drains within the window
    must verify in ONE batch_burst call whose op count is the sum of
    both drains' claims (quorum+1 each)."""

    async def go():
        c = committee()
        me = keys()[0]
        core, store, qs = make_window_core(c, me, window_ms=300.0)
        quorum = c.quorum_threshold()
        certs = [
            make_certificate(make_header(kp, c=c))
            for kp in keys()[1:4]
        ]
        calls0 = hist_count("crypto.verify.batch_size.batch_burst")
        ops0 = cnt("crypto.verify.ops.batch_burst")
        certs0 = cnt("primary.certificates_processed")
        await drive(
            core, qs, [("certificate", x) for x in certs],
            done=lambda: cnt("primary.certificates_processed") - certs0
            >= len(certs),
        )
        assert cnt("primary.certificates_processed") - certs0 == len(certs)
        assert (
            cnt("crypto.verify.ops.batch_burst") - ops0
            == len(certs) * (quorum + 1)
        )
        # The coalescing claim: ONE dispatch covered both drains.
        assert (
            hist_count("crypto.verify.batch_size.batch_burst") - calls0 == 1
        )

    run(go())


def test_window_off_keeps_inline_per_burst_dispatch():
    """window=0 (the default): the verify queue does not exist and each
    _handle_primaries_burst call dispatches inline — the pre-r19 path
    the serial A/B arm measures."""

    async def go():
        c = committee()
        me = keys()[0]
        core, store, qs = make_core(c, me)
        assert core._verify_q is None
        calls0 = hist_count("crypto.verify.batch_size.batch_burst")
        for kp in keys()[1:3]:
            cert = make_certificate(make_header(kp, c=c))
            await core._handle_primaries_burst([("certificate", cert)])
        assert (
            hist_count("crypto.verify.batch_size.batch_burst") - calls0 == 2
        )
        core.network.close()

    run(go())


def test_window_respects_batch_max():
    """More messages than verify_batch_max inside one window must split
    into at least two dispatches, none covering more than the cap."""

    async def go():
        c = committee()
        me = keys()[0]
        core, store, qs = make_window_core(c, me, window_ms=300.0,
                                           batch_max=2)
        certs = [
            make_certificate(make_header(kp, round_=r, c=c))
            for r in (1,)
            for kp in keys()[1:4]
        ]
        calls0 = hist_count("crypto.verify.batch_size.batch_burst")
        certs0 = cnt("primary.certificates_processed")
        await drive(
            core, qs, [("certificate", x) for x in certs],
            done=lambda: cnt("primary.certificates_processed") - certs0
            >= len(certs),
        )
        assert cnt("primary.certificates_processed") - certs0 == len(certs)
        assert (
            hist_count("crypto.verify.batch_size.batch_burst") - calls0 >= 2
        )

    run(go())


def test_window_replay_still_counts_per_kind_claims():
    """The burst-claims protocol arithmetic (one header claim per
    header, quorum+1 per certificate) survives the window path — the
    bench's protocol_check reads these."""

    async def go():
        c = committee()
        me = keys()[0]
        core, store, qs = make_window_core(c, me, window_ms=300.0)
        quorum = c.quorum_threshold()
        header = make_header(keys()[1], c=c)
        cert = make_certificate(make_header(keys()[2], c=c))
        h0 = cnt("crypto.burst_claims.header")
        c0 = cnt("crypto.burst_claims.certificate")
        hdr0 = cnt("primary.headers_processed")
        await drive(
            core, qs, [("header", header), ("certificate", cert)],
            done=lambda: (
                cnt("crypto.burst_claims.certificate") - c0 >= quorum + 1
                and cnt("primary.headers_processed") - hdr0 >= 2
            ),
        )
        assert cnt("crypto.burst_claims.header") - h0 == 1
        assert cnt("crypto.burst_claims.certificate") - c0 == quorum + 1

    run(go())


def test_env_window_constructs_verify_queue(monkeypatch):
    """NARWHAL_VERIFY_BATCH_WINDOW_MS > 0 in the environment arms the
    pipeline at Core construction (what `node run` children see when
    the bench passes --verify-window-ms)."""
    monkeypatch.setenv("NARWHAL_VERIFY_BATCH_WINDOW_MS", "15")
    monkeypatch.setenv("NARWHAL_VERIFY_BATCH_MAX", "64")

    async def go():
        c = committee()
        core, store, qs = make_core(c, keys()[0])
        assert core._verify_q is not None
        assert core.verify_window_s == pytest.approx(0.015)
        assert core.verify_batch_max == 64
        core.network.close()

    run(go())


def test_crashed_verify_loop_surfaces_instead_of_wedging():
    """A verify stage that dies must re-raise out of run() — even when
    run() is blocked forwarding into a FULL verify queue (the sole
    consumer is gone, so without the race the primary would silently
    stop processing peer messages forever)."""

    async def go():
        c = committee()
        me = keys()[0]
        core, store, qs = make_window_core(c, me, window_ms=50.0)
        core._verify_q = asyncio.Queue(maxsize=1)  # force the full path

        async def boom(items):
            raise RuntimeError("verify stage boom")

        core._handle_primaries_burst = boom
        task = asyncio.get_running_loop().create_task(core.run())
        try:
            for kp in keys()[1:4]:
                qs["primaries"].put_nowait(
                    ("certificate",
                     make_certificate(make_header(kp, c=c)))
                )
            with pytest.raises(RuntimeError, match="boom"):
                await asyncio.wait_for(task, 10)
        finally:
            if not task.done():
                task.cancel()
                await asyncio.gather(task, return_exceptions=True)
            core.network.close()

    run(go())


def test_crashed_verify_loop_wakes_idle_run():
    """The verify task rides in run()'s wait set: its death surfaces
    promptly even with NO further traffic arriving."""

    async def go():
        c = committee()
        me = keys()[0]
        core, store, qs = make_window_core(c, me, window_ms=10.0)

        async def boom(items):
            raise RuntimeError("idle boom")

        core._handle_primaries_burst = boom
        task = asyncio.get_running_loop().create_task(core.run())
        try:
            qs["primaries"].put_nowait(
                ("certificate",
                 make_certificate(make_header(keys()[1], c=c)))
            )
            # One message, then silence: the crash must still re-raise.
            with pytest.raises(RuntimeError, match="idle boom"):
                await asyncio.wait_for(task, 10)
        finally:
            if not task.done():
                task.cancel()
                await asyncio.gather(task, return_exceptions=True)
            core.network.close()

    run(go())


# -- backend selection ergonomics (ISSUE 14 satellite) ------------------------


def test_set_backend_strict_raises_at_boot_on_import_failure(monkeypatch):
    """A jax/tpu request whose import fails must raise AT SELECTION
    (node boot), with the import error in the message — not deep in the
    first verify burst."""
    monkeypatch.setitem(sys.modules, "narwhal_tpu.ops.ed25519", None)
    with pytest.raises(RuntimeError, match="failed to import"):
        cb.set_backend("jax", strict=True)
    # The live backend is untouched by the failed selection.
    assert cb.get_backend().name == "cpu"


def test_set_backend_fallback_only_when_explicitly_allowed(monkeypatch):
    """NARWHAL_CRYPTO_BACKEND_STRICT=0 downgrades the boot failure to a
    logged cpu fallback; the default (strict) raises."""
    monkeypatch.setitem(sys.modules, "narwhal_tpu.ops.ed25519", None)
    monkeypatch.setenv("NARWHAL_CRYPTO_BACKEND_STRICT", "0")
    cb.set_backend("tpu")
    assert cb.get_backend().name == "cpu"
    monkeypatch.setenv("NARWHAL_CRYPTO_BACKEND_STRICT", "1")
    with pytest.raises(RuntimeError):
        cb.set_backend("tpu")


def test_set_backend_from_env_precedence(monkeypatch):
    """CLI choice wins over NARWHAL_CRYPTO_BACKEND; the env knob wins
    over the cpu default; unknown names still fail loud."""
    monkeypatch.setenv("NARWHAL_CRYPTO_BACKEND", "cpu")
    assert cb.set_backend_from_env(None) == "cpu"
    assert cb.get_backend().name == "cpu"
    monkeypatch.setitem(sys.modules, "narwhal_tpu.ops.ed25519", None)
    monkeypatch.setenv("NARWHAL_CRYPTO_BACKEND", "jax")
    with pytest.raises(RuntimeError):
        cb.set_backend_from_env(None)
    assert cb.set_backend_from_env("cpu") == "cpu"
    monkeypatch.delenv("NARWHAL_CRYPTO_BACKEND")
    with pytest.raises(ValueError):
        cb.set_backend("never-a-backend")


def test_averify_records_device_seconds_split():
    """The async batched seam records BOTH wall (across the await) and
    backend compute seconds per site — wall >= compute, and the compute
    histogram gains exactly one observation per call."""

    async def go():
        me = keys()[0]
        from narwhal_tpu.crypto import digest32

        d = digest32(b"w" * 32)
        sig = me.sign(d)
        reg = metrics.registry()

        def h(name):
            return reg.histograms.get(name)

        calls0 = h("crypto.verify.seconds.other")
        calls0 = calls0.count if calls0 else 0
        dev0 = h("crypto.verify.device_seconds.other")
        dev0 = dev0.count if dev0 else 0
        ok = await cb.averify_batch_mask(
            [bytes(d)] * 3, [me.name] * 3, [sig] * 3
        )
        assert ok == [True, True, True]
        wall = h("crypto.verify.seconds.other")
        dev = h("crypto.verify.device_seconds.other")
        assert wall.count == calls0 + 1
        assert dev.count == dev0 + 1
        assert dev.sum <= wall.sum + 1e-9

    run(go())
