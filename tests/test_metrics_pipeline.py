"""Tier-1 in-process pipeline metrics test: a 4-node committee (primary +
worker + consensus each) on loopback TCP, client transactions pushed into
node 0, and the per-process metrics registry must tell a CONSISTENT story
end to end:

- conservation: every batch sealed is either committed or accounted for by
  a drop counter (batches sealed == committed + quorum-dropped);
- the stage trace carries all six pipeline stamps per committed digest, in
  monotonic (causal) order: seal ≤ quorum ≤ digest-at-primary ≤ header ≤
  cert ≤ commit;
- layer counters (headers proposed, votes, certificates, commits, store
  puts, network frames) are live and mutually consistent.

This is the standalone target of `make metrics-smoke`; when
NARWHAL_METRICS_DUMP is set (CI), the final registry snapshot is written
there as an inspectable workflow artifact.
"""

import asyncio
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from narwhal_tpu import metrics
from narwhal_tpu.config import Parameters
from narwhal_tpu.crypto import digest32
from narwhal_tpu.messages import encode_batch
from narwhal_tpu.network.framing import parse_address, write_frame
from narwhal_tpu.node import spawn_primary_node, spawn_worker_node
from tests.common import committee, keys


def test_pipeline_metrics_consistency():
    reg = metrics.registry()
    reg.reset()

    async def go():
        c = committee(base_port=15400)
        params = Parameters(
            header_size=32,  # propose as soon as one digest arrives
            max_header_delay=100,
            batch_size=400,
            max_batch_delay=100,
        )
        commits = {i: [] for i in range(4)}
        nodes = []
        for i, kp in enumerate(keys()):
            nodes.append(
                await spawn_primary_node(
                    kp,
                    c,
                    params,
                    on_commit=lambda cert, i=i: commits[i].append(cert),
                )
            )
            nodes.append(await spawn_worker_node(kp, 0, c, params))

        # Push 8 txs into node 0's worker; batch_size=400 seals every 4 of
        # the 100 B txs into one batch (same shape as test_e2e).
        host, port = parse_address(c.worker(keys()[0].name, 0).transactions)
        _, w = await asyncio.open_connection(host, port)
        txs = [
            bytes([1]) + (0xA500 + i).to_bytes(8, "little") + bytes(91)
            for i in range(8)
        ]
        for tx in txs:
            await write_frame(w, tx)

        expected = {
            digest32(encode_batch(txs[:4])),
            digest32(encode_batch(txs[4:])),
        }
        expected_hex = {bytes(d).hex() for d in expected}

        def payload_committed(certs):
            return expected <= {
                d for cert in certs for d in cert.header.payload
            }

        for _ in range(600):
            if all(payload_committed(v) for v in commits.values()):
                break
            await asyncio.sleep(0.1)
        else:
            raise AssertionError(
                f"payload never committed: {[len(v) for v in commits.values()]}"
            )

        w.close()
        for node in nodes:
            await node.shutdown()
        return expected_hex

    expected_hex = asyncio.run(asyncio.wait_for(go(), 60))

    snap = reg.snapshot()
    counters = snap["counters"]
    trace = snap["trace"]

    # --- conservation: sealed == committed + dropped ------------------------
    # All 4 nodes share this process's registry; only node 0's worker
    # sealed batches.  Every sealed digest must reach commit (or be
    # accounted for by the quorum-drop counter — zero in a healthy run).
    sealed_digests = {d for d, e in trace.items() if "seal" in e}
    committed_digests = {d for d, e in trace.items() if "commit" in e}
    dropped = counters.get("worker.quorum_dropped", 0)
    assert counters["worker.batches_sealed"] == len(sealed_digests)
    assert len(sealed_digests) == len(sealed_digests & committed_digests) + dropped, (
        f"sealed {len(sealed_digests)} != committed "
        f"{len(sealed_digests & committed_digests)} + dropped {dropped}"
    )
    assert expected_hex <= sealed_digests
    assert expected_hex <= committed_digests
    # 8 txs of 100 B each, split into two sealed batches.
    assert counters["worker.txs_sealed"] == 8
    assert counters["worker.batch_bytes_sealed"] == 800

    # --- stage stamps present and monotonic ---------------------------------
    order = list(metrics.STAGES)
    for d in expected_hex:
        entry = trace[d]
        stamps = [entry[s] for s in order if s in entry]
        assert len(stamps) == len(order), (
            f"digest {d} missing stages: {sorted(set(order) - set(entry))}"
        )
        assert stamps == sorted(stamps), (
            f"stage timestamps not monotonic for {d}: "
            f"{[(s, entry[s]) for s in order]}"
        )

    # --- layer counters live and consistent ---------------------------------
    assert counters["worker.quorum_reached"] >= len(expected_hex)
    assert counters["primary.headers_proposed"] > 0
    assert counters["primary.votes_received"] > 0
    assert counters["primary.certificates_formed"] > 0
    assert counters["consensus.committed_certificates"] > 0
    # Each of the 4 consensus instances committed both payload batches.
    assert counters["consensus.committed_batch_digests"] >= 2 * 4
    assert counters["store.puts"] > 0
    assert counters["net.reliable.frames_sent"] > 0
    assert counters["net.recv.frames"] > 0
    hist = snap["histograms"]["worker.quorum_latency_seconds"]
    assert hist["count"] == counters["worker.quorum_reached"]

    # --- CI artifact dump ----------------------------------------------------
    dump_dir = os.environ.get("NARWHAL_METRICS_DUMP")
    if dump_dir:
        os.makedirs(dump_dir, exist_ok=True)
        with open(os.path.join(dump_dir, "metrics-smoke.json"), "w") as f:
            json.dump(snap, f, indent=1)
