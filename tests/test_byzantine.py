"""Live in-process Byzantine committee tests (tier-1 arm of the fault
suite): a 4-node committee with one adversarial primary must (a) keep
committing client payload — the paper's under-faults claim — and (b)
light up the matching detection rule on the honest nodes' registry.

All four nodes share one process/registry (the test_health_failover
pattern), so the honest Cores' detection counters are directly
observable and a manually evaluated HealthMonitor pins down the rule
firing deterministically.  The full multi-process arm (per-node
registries, WAN shims, crash/restart) runs via benchmark/fault_bench.py;
artifacts under artifacts/faults_r11/."""

import asyncio
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from narwhal_tpu import metrics  # noqa: E402
from narwhal_tpu.config import Parameters  # noqa: E402
from narwhal_tpu.crypto import digest32  # noqa: E402
from narwhal_tpu.faults.byzantine import ByzantinePlan  # noqa: E402
from narwhal_tpu.messages import encode_batch  # noqa: E402
from narwhal_tpu.metrics import HealthMonitor, default_rules  # noqa: E402
from narwhal_tpu.network.framing import parse_address, write_frame  # noqa: E402
from narwhal_tpu.node import spawn_primary_node, spawn_worker_node  # noqa: E402
from tests.common import committee, keys  # noqa: E402


def _tx(i: int) -> bytes:
    return bytes([1]) + (0xFA0000 + i).to_bytes(8, "little") + bytes(91)


def _run_byzantine_committee(base_port, behaviors, counter_name, rule_name):
    """Boot 3 honest + 1 Byzantine node, drive payload through a fault
    window, and return once (commits survived, detection counter rose,
    rule fired).  Asserts along the way."""
    reg = metrics.registry()
    reg.reset()

    async def go():
        c = committee(base_port=base_port)
        params = Parameters(
            header_size=32,
            max_header_delay=100,
            batch_size=400,
            max_batch_delay=100,
        )
        kps = keys()
        commits = {i: [] for i in range(4)}
        plan = ByzantinePlan(behaviors, seed=5)
        nodes = []
        for i, kp in enumerate(kps):
            nodes.append(
                await spawn_primary_node(
                    kp,
                    c,
                    params,
                    on_commit=lambda cert, i=i: commits[i].append(cert),
                    fault_plan=plan if i == 3 else None,
                )
            )
            nodes.append(await spawn_worker_node(kp, 0, c, params))

        monitor = HealthMonitor(reg, rules=default_rules({}), interval_s=0.5)

        async def send_txs(ids, node=0):
            host, port = parse_address(c.worker(kps[node].name, 0).transactions)
            _, w = await asyncio.open_connection(host, port)
            txs = [_tx(i) for i in ids]
            for tx in txs:
                await write_frame(w, tx)
            w.close()
            return {digest32(encode_batch(txs))}

        async def wait_commit(expected, nodes_idx, timeout_s=60):
            for _ in range(int(timeout_s / 0.1)):
                if all(
                    expected
                    <= {
                        d
                        for cert in commits[i]
                        for d in cert.header.payload
                    }
                    for i in nodes_idx
                ):
                    return
                await asyncio.sleep(0.1)
            raise AssertionError(
                f"payload never committed on {nodes_idx}: "
                f"{[len(commits[i]) for i in nodes_idx]}"
            )

        # Liveness UNDER the fault: the adversary is active from boot,
        # and honest nodes still commit client payload.
        batch1 = await send_txs(range(4))
        await wait_commit(batch1, range(3))

        # Detection: the honest Cores' counter crosses zero...
        counter = reg.counters.get(counter_name)
        for _ in range(400):
            if counter is not None and counter.value > 0:
                break
            await asyncio.sleep(0.05)
            counter = reg.counters.get(counter_name)
        else:
            raise AssertionError(f"{counter_name} never incremented")

        # ... and the rule names the anomaly on the next evaluation.
        firing = {f["rule"] for f in monitor.evaluate()}
        assert rule_name in firing, f"expected {rule_name}, got {firing}"

        # Still alive after detection: fresh payload keeps committing.
        batch2 = await send_txs(range(100, 104), node=1)
        await wait_commit(batch2, range(3))

        for node in nodes:
            await node.shutdown()

    asyncio.run(asyncio.wait_for(go(), 120))


def test_equivocating_primary_detected_and_committee_survives():
    """Split-brain headers: the twin-voting honest node proves the
    equivocation when the real header's certificate reaches it."""
    _run_byzantine_committee(
        base_port=15900,
        behaviors=["equivocate"],
        counter_name="primary.equivocations_detected",
        rule_name="equivocation",
    )


def test_wrong_key_primary_detected_and_committee_survives():
    """Rogue-key signatures: every honest node rejects the headers at the
    signature gate and the invalid_signature rule latches."""
    _run_byzantine_committee(
        base_port=15930,
        behaviors=["wrong_key"],
        counter_name="primary.invalid_signatures",
        rule_name="invalid_signature",
    )
