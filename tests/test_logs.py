"""Log-parser unit tests against synthetic fixtures.

Reproduces the reference's measurement arithmetic exactly
(reference benchmark/benchmark/logs.py:155-198): consensus duration runs
first *proposal* (Created line) → last commit, consensus latency is
commit − proposal per committed digest, end-to-end duration starts at the
client's `Start sending transactions` line, and the config echo-back from
every primary must agree.
"""

import sys
import os

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmark.logs import parse_logs  # noqa: E402

TX = 512


def _t(ms: int) -> str:
    """Millisecond offset → log timestamp (fixed date)."""
    s, msec = divmod(ms, 1000)
    mins, sec = divmod(s, 60)
    return f"2026-01-01T00:{mins:02d}:{sec:02d}.{msec:03d}Z"


CONFIG_ECHO = "\n".join(
    [
        _t(0) + " INFO narwhal.node Header size set to 1000 B",
        _t(0) + " INFO narwhal.node Max header delay set to 100 ms",
        _t(0) + " INFO narwhal.node Min header delay set to 0 ms",
        _t(0) + " INFO narwhal.node Header linger set to 0 ms",
        _t(0) + " INFO narwhal.node Garbage collection depth set to 50 rounds",
        _t(0) + " INFO narwhal.node Sync retry delay set to 5000 ms",
        _t(0) + " INFO narwhal.node Sync retry nodes set to 3 nodes",
        _t(0) + " INFO narwhal.node Batch size set to 500000 B",
        _t(0) + " INFO narwhal.node Max batch delay set to 100 ms",
    ]
)


def make_logs():
    client = "\n".join(
        [
            _t(1000) + " INFO narwhal.client Start sending transactions",
            _t(1000) + " INFO narwhal.client Transactions size: 512 B",
            _t(1000) + " INFO narwhal.client Transactions rate: 1000 tx/s",
            _t(1100) + " INFO narwhal.client Sending sample transaction 7",
        ]
    )
    worker = "\n".join(
        [
            _t(1200) + " INFO narwhal.worker Batch AAA= contains sample tx 7",
            _t(1200) + " INFO narwhal.worker Batch AAA= contains 102400 B",
            _t(1600) + " INFO narwhal.worker Batch BBB= contains 51200 B",
        ]
    )
    primary = "\n".join(
        [
            CONFIG_ECHO,
            _t(1300) + " INFO narwhal.primary Created B1(H1=) -> AAA=",
            _t(1700) + " INFO narwhal.primary Created B2(H2=) -> BBB=",
            _t(1900) + " INFO narwhal.consensus Committed B1(H1=) -> AAA=",
            _t(2300) + " INFO narwhal.consensus Committed B2(H2=) -> BBB=",
        ]
    )
    return [client], [worker], [primary]


def test_reference_arithmetic():
    clients, workers, primaries = make_logs()
    r = parse_logs(clients, workers, primaries, TX)
    assert not r.errors, r.errors

    # Consensus: duration = first Created (1.3 s) → last commit (2.3 s).
    committed_bytes = 102400 + 51200
    assert r.committed_bytes == committed_bytes
    assert abs(r.duration_s - 1.0) < 1e-6
    assert abs(r.consensus_bps - committed_bytes / 1.0) < 0.1
    assert abs(r.consensus_tps - committed_bytes / TX / 1.0) < 0.1
    # Latency: mean((1.9−1.3), (2.3−1.7)) = 600 ms — proposal-based, NOT
    # batch-creation-based (the batch was created at 1.2 s).
    assert abs(r.consensus_latency_ms - 600.0) < 0.1

    # End-to-end: duration = client start (1.0 s) → last commit (2.3 s);
    # latency = sample send (1.1 s) → commit of AAA (1.9 s) = 800 ms.
    assert abs(r.end_to_end_bps - committed_bytes / 1.3) < 0.1
    assert abs(r.end_to_end_latency_ms - 800.0) < 0.1
    assert r.samples == 1

    # Config echo-back parsed into the result.
    assert r.config["batch_size"] == 500000
    assert r.config["gc_depth"] == 50


def test_committed_without_created_is_flagged():
    clients, workers, primaries = make_logs()
    primaries[0] = primaries[0].replace(
        _t(1700) + " INFO narwhal.primary Created B2(H2=) -> BBB=\n", ""
    )
    r = parse_logs(clients, workers, primaries, TX)
    assert any("no Created line" in e for e in r.errors)


def test_config_echo_missing_is_flagged():
    clients, workers, primaries = make_logs()
    primaries[0] = primaries[0].replace(
        " INFO narwhal.node Batch size set to 500000 B\n", "\n"
    )
    r = parse_logs(clients, workers, primaries, TX)
    assert any("config echo missing" in e for e in r.errors)


def test_config_echo_mismatch_is_flagged():
    clients, workers, primaries = make_logs()
    second = primaries[0].replace(
        "Batch size set to 500000 B", "Batch size set to 9 B"
    )
    r = parse_logs(clients, workers, primaries + [second], TX)
    assert any("config echo differs" in e for e in r.errors)


def test_errors_name_offending_file_and_line():
    """Parse errors must locate the bad source (satellite: a mis-scrape
    used to cost a full re-run to even find the file)."""
    clients, workers, primaries = make_logs()
    primaries[0] += "\n" + _t(3000) + " ERROR narwhal.primary boom happened"
    r = parse_logs(
        clients,
        workers,
        primaries,
        TX,
        client_names=["client-0.log"],
        worker_names=["worker-0.log"],
        primary_names=["primary-0.log"],
    )
    assert any(
        e.startswith("primary-0.log:") and "boom happened" in e
        for e in r.errors
    )


def test_config_echo_errors_name_the_file():
    clients, workers, primaries = make_logs()
    primaries[0] = primaries[0].replace(
        " INFO narwhal.node Batch size set to 500000 B\n", "\n"
    )
    r = parse_logs(
        clients, workers, primaries, TX, primary_names=["primary-7.log"]
    )
    assert any(
        "config echo missing" in e
        and "primary-7.log" in e
        and "batch_size" in e
        for e in r.errors
    )

    # Mismatch names the disagreeing file too.
    clients, workers, primaries = make_logs()
    second = primaries[0].replace(
        "Batch size set to 500000 B", "Batch size set to 9 B"
    )
    r = parse_logs(
        clients,
        workers,
        primaries + [second],
        TX,
        primary_names=["primary-0.log", "primary-1.log"],
    )
    assert any(
        "config echo differs" in e and "primary-1.log" in e for e in r.errors
    )


def test_committed_without_created_names_digest_and_source():
    clients, workers, primaries = make_logs()
    primaries[0] = primaries[0].replace(
        _t(1700) + " INFO narwhal.primary Created B2(H2=) -> BBB=\n", ""
    )
    r = parse_logs(
        clients, workers, primaries, TX, primary_names=["primary-3.log"]
    )
    assert any(
        "no Created line" in e and "BBB=" in e and "primary-3.log" in e
        for e in r.errors
    )


def test_unnamed_logs_get_index_labels():
    """Backwards-compatible call (no names): sources label by index."""
    clients, workers, primaries = make_logs()
    primaries[0] += "\n" + _t(3000) + " CRITICAL narwhal.primary dead"
    r = parse_logs(clients, workers, primaries, TX)
    assert any(e.startswith("primary[0]:") for e in r.errors)


def test_earliest_timestamp_wins_across_primaries():
    clients, workers, primaries = make_logs()
    # A second primary saw the commit of AAA= later; earliest must win.
    late = "\n".join(
        [
            CONFIG_ECHO,
            _t(2500) + " INFO narwhal.consensus Committed B1(H1=) -> AAA=",
        ]
    )
    r = parse_logs(clients, workers, primaries + [late], TX)
    assert not r.errors, r.errors
    assert abs(r.consensus_latency_ms - 600.0) < 1e-3  # unchanged
