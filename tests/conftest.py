"""Test harness config: force JAX onto a virtual 8-device CPU mesh so
sharding/pjit tests run without TPU hardware (the driver separately
dry-runs the multi-chip path; see __graft_entry__.py).

The host environment may pin JAX to a real accelerator two ways: the
JAX_PLATFORMS env var, and an interpreter-startup plugin (sitecustomize)
that registers a backend and overrides ``jax_platforms`` via jax.config.
Both are overridden here — env vars first (read when the CPU client is
created), then the config knob, which wins over anything a startup hook
set."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = [
    f for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f
]
_flags.append("--xla_force_host_platform_device_count=8")
os.environ["XLA_FLAGS"] = " ".join(_flags)

try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except ImportError:  # tests that don't need jax still run
    pass

# --- destroyed-pending-task escalation (ISSUE 9 satellite) -------------------
#
# "Task was destroyed but it is pending!" is NOT a warning: asyncio emits
# it through Task.__del__ -> loop.call_exception_handler -> the `asyncio`
# logger, so pytest's filterwarnings cannot escalate it (the
# never-awaited-coroutine RuntimeWarning half lives in pyproject.toml).
# Trap the logger instead and fail the test in whose teardown the message
# surfaces.  No forced gc.collect() here: a full collection per test
# costs whole minutes across the suite with jax loaded, and CPython's
# refcounting destroys a dropped pending task immediately in the
# non-cyclic (i.e. common) case — a cyclic straggler surfaces in a later
# test's teardown, which still names the leaked task.

import logging  # noqa: E402

import pytest  # noqa: E402

_DESTROYED_PENDING = "Task was destroyed but it is pending"


class _AsyncioErrorTrap(logging.Handler):
    def __init__(self) -> None:
        super().__init__(logging.ERROR)
        self.messages: list = []

    def emit(self, record: logging.LogRecord) -> None:
        msg = record.getMessage()
        if _DESTROYED_PENDING in msg:
            self.messages.append(msg)


_asyncio_trap = _AsyncioErrorTrap()
logging.getLogger("asyncio").addHandler(_asyncio_trap)


@pytest.fixture(autouse=True)
def _fail_on_destroyed_pending_tasks():
    yield
    if _asyncio_trap.messages:
        msgs = list(_asyncio_trap.messages)
        _asyncio_trap.messages.clear()
        pytest.fail(
            "asyncio destroyed pending task(s) — a fire-and-forget task "
            "was GC'd mid-flight (use narwhal_tpu.utils.tasks.spawn):\n"
            + "\n".join(msgs)
        )
