"""Test harness config: force JAX onto a virtual 8-device CPU mesh so
sharding/pjit tests run without TPU hardware (the driver separately
dry-runs the multi-chip path; see __graft_entry__.py).

The host environment may pin JAX to a real accelerator two ways: the
JAX_PLATFORMS env var, and an interpreter-startup plugin (sitecustomize)
that registers a backend and overrides ``jax_platforms`` via jax.config.
Both are overridden here — env vars first (read when the CPU client is
created), then the config knob, which wins over anything a startup hook
set."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = [
    f for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f
]
_flags.append("--xla_force_host_platform_device_count=8")
os.environ["XLA_FLAGS"] = " ".join(_flags)

try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except ImportError:  # tests that don't need jax still run
    pass
