"""Multi-chip sharding correctness: the committee-sharded commit step on the
8-device virtual CPU mesh (tests/conftest.py) must produce bit-identical
results to the unsharded single-device run.

This exercises the SAME program the driver runs (``__graft_entry__``'s
commit-step builder) — the driver validates that the path compiles+runs;
this test validates that the sharded numerics match.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

from __graft_entry__ import (  # noqa: E402
    commit_fixture,
    make_commit_step,
    shard_commit_args,
)


def test_sharded_commit_step_matches_unsharded():
    n_devices = 8
    assert len(jax.devices()) >= n_devices, (
        "conftest must provision the 8-device CPU mesh"
    )
    window, n = 16, 4 * n_devices
    fixture = commit_fixture(1, window, n)
    commit_step = make_commit_step(window)

    # Unsharded ground truth on one device.
    (parent, exists, leader_onehot, is_leader_slot, stake,
     anchor_slot, anchor_onehot) = fixture
    ref = commit_step(
        jnp.asarray(parent), jnp.asarray(exists), jnp.asarray(leader_onehot),
        jnp.asarray(is_leader_slot), jnp.asarray(stake),
        jnp.int32(anchor_slot), jnp.asarray(anchor_onehot),
    )

    # Committee-axis sharded run over the mesh.
    mesh = Mesh(np.array(jax.devices()[:n_devices]), ("committee",))
    args = shard_commit_args(mesh, fixture)
    with mesh:
        got = jax.jit(commit_step)(*args)
        jax.block_until_ready(got)

    for r, g in zip(ref, got):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(g))


def test_sharded_verify_batch_matches_unsharded():
    """The ed25519 batch verifier data-parallel over the mesh: the batch
    axis sharded across 8 devices must produce the same accept/reject mask
    as the single-device run — the multi-chip scaling story for the
    per-round crypto (one chip per primary today; batch-sharded chips per
    primary is the same program with a different mesh)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from narwhal_tpu.crypto import KeyPair
    from narwhal_tpu.crypto.digest import Digest
    from narwhal_tpu.ops import ed25519 as E

    n_devices = 8
    assert len(jax.devices()) >= n_devices, (
        "conftest must provision the 8-device CPU mesh"
    )
    batch = 16  # one pad shape: divisible by the mesh, tiny for CPU compile
    kp = KeyPair.generate(b"\x07" * 32)
    msgs, keys, sigs = [], [], []
    for i in range(batch):
        m = bytes(Digest(bytes([i]) * 32))
        msgs.append(m)
        keys.append(kp.name)
        sigs.append(kp.sign(Digest(m)))
    sigs[3] = type(sigs[3])(bytes(64))  # one forgery: mask must reject it

    args = E.prepare_batch(msgs, keys, sigs, batch)
    ref = np.asarray(E._verify_kernel(*(jnp.asarray(a) for a in args)))
    assert ref.tolist() == [i != 3 for i in range(batch)]

    mesh = Mesh(np.array(jax.devices()[:n_devices]), ("batch",))
    # Every per-signature array is sharded on its batch axis (axis 0 for
    # all of prepare_batch's outputs).
    sharded = [
        jax.device_put(jnp.asarray(a), NamedSharding(mesh, P("batch")))
        for a in args
    ]
    with mesh:
        got = np.asarray(E._verify_kernel(*sharded))
    np.testing.assert_array_equal(ref, got)


def test_dryrun_multichip_subprocess_green():
    """The actual driver hook must run green end-to-end (it self-provisions
    a CPU mesh in a subprocess, so it works regardless of this process's
    JAX backend)."""
    from __graft_entry__ import dryrun_multichip

    dryrun_multichip(4)
