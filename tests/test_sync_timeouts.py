"""Timeout-path coverage for the dependency-sync machinery (ISSUE 6
satellite): the HeaderWaiter's parent-request escalation after the sync
deadline, cancellation of the retry once the obligation is satisfied, the
worker-fetch command for missing batches, and the CertificateWaiter's
park/release/GC discipline.  These are the paths a crash/restart scenario
leans on (a restarted node is one big missing-dependency storm), so they
need direct, deterministic tests — not just incidental e2e coverage."""

import asyncio
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from narwhal_tpu.crypto import Digest  # noqa: E402
from narwhal_tpu.network import Receiver  # noqa: E402
from narwhal_tpu.primary import header_waiter as hw_mod  # noqa: E402
from narwhal_tpu.primary.core import AtomicRound  # noqa: E402
from narwhal_tpu.primary.certificate_waiter import CertificateWaiter  # noqa: E402
from narwhal_tpu.primary.header_waiter import HeaderWaiter  # noqa: E402
from narwhal_tpu.primary.messages import decode_primary_message  # noqa: E402
from narwhal_tpu.primary.synchronizer import payload_key  # noqa: E402
from narwhal_tpu.store import Store  # noqa: E402
from tests.common import (  # noqa: E402
    RecordingAckHandler,
    committee,
    keys,
    make_certificate,
    make_header,
)


def _requests_for(handler, digest):
    """certificates_request frames at this receiver naming `digest`."""
    hits = 0
    for frame in handler.received:
        try:
            decoded = decode_primary_message(frame)
        except ValueError:
            continue
        if decoded[0] == "certificates_request" and digest in decoded[1]:
            hits += 1
    return hits


def test_missing_parent_rerequested_after_deadline_then_cancelled(
    monkeypatch,
):
    """A missing parent is requested from the author immediately; once the
    sync deadline passes the timer escalates to `sync_retry_nodes` random
    peers; writing the parent releases the parked header AND cancels the
    retry loop (no further requests)."""
    monkeypatch.setattr(hw_mod, "TIMER_RESOLUTION", 0.05)

    async def go():
        c = committee(base_port=15800)
        kps = keys()
        name = kps[0].name
        handlers = {}
        receivers = []
        for kp in kps[1:]:
            h = RecordingAckHandler()
            addr = c.primary(kp.name).primary_to_primary
            receivers.append(await Receiver.spawn(addr, h))
            handlers[kp.name] = h

        store = Store()
        rx = asyncio.Queue()
        tx_core = asyncio.Queue()
        waiter = HeaderWaiter(
            name,
            c,
            store,
            AtomicRound(),
            gc_depth=50,
            sync_retry_delay_ms=150,
            sync_retry_nodes=3,
            rx_synchronizer=rx,
            tx_core=tx_core,
        )
        task = asyncio.get_running_loop().create_task(waiter.run())

        missing = Digest(bytes([7]) * 32)
        header = make_header(kps[1], round_=2, parents={missing}, c=c)
        await rx.put(("sync_parents", [missing], header))

        # Initial optimistic request goes to the header author.
        author_h = handlers[kps[1].name]
        await asyncio.wait_for(author_h.arrived.wait(), 5)
        assert _requests_for(author_h, missing) >= 1

        def total():
            return sum(_requests_for(h, missing) for h in handlers.values())

        # Past the deadline the timer escalates via lucky_broadcast: the
        # committee-wide request count must GROW beyond the initial ask.
        initial = total()
        deadline = asyncio.get_running_loop().time() + 5
        while total() <= initial:
            assert asyncio.get_running_loop().time() < deadline, (
                "sync deadline passed but no re-request escalated"
            )
            await asyncio.sleep(0.05)

        # Satisfy the obligation: the parked header loops back to the
        # Core and the request bookkeeping empties.
        store.write(bytes(missing), b"anything")
        out = await asyncio.wait_for(tx_core.get(), 5)
        assert out.id == header.id
        assert waiter.pending == {}
        assert waiter.parent_requests == {}

        # ... and the retry is actually CANCELLED: several more timer
        # periods produce zero new requests.
        settled = total()
        await asyncio.sleep(0.5)
        assert total() == settled, "retry kept firing after satisfaction"

        task.cancel()
        await asyncio.gather(task, return_exceptions=True)
        waiter.sender.close()
        for r in receivers:
            await r.shutdown()

    asyncio.run(asyncio.wait_for(go(), 30))


def test_missing_batch_commands_worker_fetch_then_releases(monkeypatch):
    """A missing payload batch sends a Synchronize command to OUR worker
    serving that id, parks the header on the (digest ‖ worker_id) store
    key, and releases it the moment the worker stores the batch marker."""
    monkeypatch.setattr(hw_mod, "TIMER_RESOLUTION", 0.05)

    async def go():
        c = committee(base_port=15830)
        kps = keys()
        name = kps[0].name
        worker_h = RecordingAckHandler()
        worker_addr = c.worker(name, 0).primary_to_worker
        receiver = await Receiver.spawn(worker_addr, worker_h)

        store = Store()
        rx = asyncio.Queue()
        tx_core = asyncio.Queue()
        waiter = HeaderWaiter(
            name,
            c,
            store,
            AtomicRound(),
            gc_depth=50,
            sync_retry_delay_ms=150,
            sync_retry_nodes=3,
            rx_synchronizer=rx,
            tx_core=tx_core,
        )
        task = asyncio.get_running_loop().create_task(waiter.run())

        digest = Digest(bytes([9]) * 32)
        header = make_header(kps[1], round_=2, c=c)
        await rx.put(("sync_batches", {digest: 0}, header))

        # The fetch command reaches our worker and names the digest.
        await asyncio.wait_for(worker_h.arrived.wait(), 5)
        assert any(bytes(digest) in f for f in worker_h.received)
        assert header.id in waiter.pending

        # The worker "fetches" the batch: writing the payload marker
        # releases the parked header.
        store.write(payload_key(digest, 0), b"")
        out = await asyncio.wait_for(tx_core.get(), 5)
        assert out.id == header.id
        assert waiter.pending == {}

        task.cancel()
        await asyncio.gather(task, return_exceptions=True)
        waiter.sender.close()
        await receiver.shutdown()

    asyncio.run(asyncio.wait_for(go(), 30))


def test_certificate_waiter_parks_until_all_parents_then_releases():
    async def go():
        c = committee()
        kps = keys()
        store = Store()
        rx = asyncio.Queue()
        tx_core = asyncio.Queue()
        waiter = CertificateWaiter(
            store, AtomicRound(), gc_depth=10, rx_synchronizer=rx,
            tx_core=tx_core,
        )
        task = asyncio.get_running_loop().create_task(waiter.run())

        p1, p2 = Digest(bytes([1]) * 32), Digest(bytes([2]) * 32)
        cert = make_certificate(
            make_header(kps[1], round_=3, parents={p1, p2}, c=c)
        )
        await rx.put(cert)
        await asyncio.sleep(0.1)
        assert cert.digest() in waiter.pending
        assert tx_core.empty()

        # One parent is not enough; the SECOND write releases the loop-back.
        store.write(bytes(p1), b"x")
        await asyncio.sleep(0.1)
        assert tx_core.empty()
        store.write(bytes(p2), b"y")
        out = await asyncio.wait_for(tx_core.get(), 5)
        assert out.digest() == cert.digest()
        assert waiter.pending == {}

        task.cancel()
        await asyncio.gather(task, return_exceptions=True)

    asyncio.run(asyncio.wait_for(go(), 30))


def test_certificate_waiter_gc_cancels_stale_obligations():
    """A parked certificate whose round falls behind the GC horizon is
    dropped and its notify_read task cancelled — the obligation must not
    outlive the round it serves (a restarted committee floods this path)."""

    async def go():
        c = committee()
        kps = keys()
        store = Store()
        rx = asyncio.Queue()
        tx_core = asyncio.Queue()
        consensus_round = AtomicRound()
        waiter = CertificateWaiter(
            store, consensus_round, gc_depth=10, rx_synchronizer=rx,
            tx_core=tx_core,
        )
        task = asyncio.get_running_loop().create_task(waiter.run())

        old_parent = Digest(bytes([3]) * 32)
        stale = make_certificate(
            make_header(kps[1], round_=3, parents={old_parent}, c=c)
        )
        await rx.put(stale)
        await asyncio.sleep(0.1)
        assert stale.digest() in waiter.pending
        parked_task = waiter.pending[stale.digest()][1]

        # Consensus moves on: round 20 puts the gc horizon at 10 > 3.
        consensus_round.value = 20
        fresh_parent = Digest(bytes([4]) * 32)
        fresh = make_certificate(
            make_header(kps[2], round_=19, parents={fresh_parent}, c=c)
        )
        await rx.put(fresh)  # any message triggers the GC sweep
        await asyncio.sleep(0.1)
        assert stale.digest() not in waiter.pending
        assert fresh.digest() in waiter.pending
        assert parked_task.cancelled() or parked_task.done()
        # The store obligation is gone too (cancelled waiters un-park).
        assert bytes(old_parent) not in store._obligations

        task.cancel()
        await asyncio.gather(task, return_exceptions=True)

    asyncio.run(asyncio.wait_for(go(), 30))


def test_landed_parent_drops_out_of_retry_while_sibling_still_missing(
    monkeypatch,
):
    """A header parked on TWO missing parents: once one of them lands in
    the store, the timer must stop re-requesting it (helpful peers would
    re-send it every period — the duplicate flood that outran signature
    verification in the partition-heal fault scenario) while the still-
    missing sibling keeps escalating."""
    monkeypatch.setattr(hw_mod, "TIMER_RESOLUTION", 0.05)

    async def go():
        c = committee(base_port=15860)
        kps = keys()
        name = kps[0].name
        handlers = {}
        receivers = []
        for kp in kps[1:]:
            h = RecordingAckHandler()
            addr = c.primary(kp.name).primary_to_primary
            receivers.append(await Receiver.spawn(addr, h))
            handlers[kp.name] = h

        store = Store()
        rx = asyncio.Queue()
        tx_core = asyncio.Queue()
        waiter = HeaderWaiter(
            name,
            c,
            store,
            AtomicRound(),
            gc_depth=50,
            sync_retry_delay_ms=100,
            sync_retry_nodes=3,
            rx_synchronizer=rx,
            tx_core=tx_core,
        )
        task = asyncio.get_running_loop().create_task(waiter.run())

        landed = Digest(bytes([5]) * 32)
        missing = Digest(bytes([6]) * 32)
        header = make_header(kps[1], round_=2, parents={landed, missing}, c=c)
        await rx.put(("sync_parents", [landed, missing], header))
        await asyncio.wait_for(handlers[kps[1].name].arrived.wait(), 5)

        def total(digest):
            return sum(_requests_for(h, digest) for h in handlers.values())

        # One parent lands; the header stays parked on the other.
        store.write(bytes(landed), b"cert-bytes")

        # The sibling keeps escalating...
        base_missing = total(missing)
        deadline = asyncio.get_running_loop().time() + 5
        while total(missing) <= base_missing:
            assert asyncio.get_running_loop().time() < deadline
            await asyncio.sleep(0.05)
        assert header.id in waiter.pending  # still parked

        # ... but the landed one falls out of the retry set on the next
        # sweep that observes the store write.  Waited for, not asserted
        # immediately: the receiver-side counts the escalation loop
        # above watches lag the sweep by socket delivery, so under heavy
        # load (the -X dev sanitizer tier) the sibling's count can grow
        # from a PRE-landing sweep's frames while the post-landing sweep
        # hasn't run yet.
        while landed in waiter.parent_requests:
            assert asyncio.get_running_loop().time() < deadline
            await asyncio.sleep(0.05)
        # Drain in-flight frames from pre-landing sweeps (they can
        # arrive seconds late on a loaded host): take the settled count
        # only once it has held still for a few periods...
        settled = total(landed)
        stable_since = asyncio.get_running_loop().time()
        hard_stop = asyncio.get_running_loop().time() + 10
        while asyncio.get_running_loop().time() - stable_since < 0.2:
            assert asyncio.get_running_loop().time() < hard_stop
            await asyncio.sleep(0.05)
            if total(landed) != settled:
                settled = total(landed)
                stable_since = asyncio.get_running_loop().time()
        # ... and only then require it stops growing for good.
        await asyncio.sleep(0.4)
        assert total(landed) == settled, "landed parent kept being re-requested"

        task.cancel()
        await asyncio.gather(task, return_exceptions=True)
        waiter.sender.close()
        for r in receivers:
            await r.shutdown()

    asyncio.run(asyncio.wait_for(go(), 30))
