"""utils/tasks.spawn() + the loop-stall watchdog (ISSUE 9 runtime half)."""

import asyncio
import logging
import os
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from narwhal_tpu import metrics  # noqa: E402
from narwhal_tpu.analysis.watchdog import (  # noqa: E402
    LoopWatchdog,
    install_from_env,
)
from narwhal_tpu.utils import tasks  # noqa: E402
from narwhal_tpu.utils.tasks import spawn  # noqa: E402


# -- spawn() ------------------------------------------------------------------

def test_spawn_retains_strong_ref_until_done():
    async def main():
        release = asyncio.Event()

        async def work():
            await release.wait()

        task = spawn(work(), name="retained")
        await asyncio.sleep(0)
        assert task in tasks._TASKS
        assert tasks.alive_count() >= 1
        release.set()
        await task
        # The done-callback runs after the await completes.
        await asyncio.sleep(0)
        assert task not in tasks._TASKS

    asyncio.run(main())


def test_spawn_logs_unhandled_exception(caplog):
    async def main():
        async def dies():
            raise RuntimeError("pipeline stage exploded")

        task = spawn(dies(), name="doomed-stage")
        await asyncio.gather(task, return_exceptions=True)
        await asyncio.sleep(0)

    with caplog.at_level(logging.ERROR, logger="narwhal.tasks"):
        asyncio.run(main())
    died = [r for r in caplog.records if "died of an unhandled" in r.message]
    assert len(died) == 1
    assert "doomed-stage" in died[0].getMessage()
    assert died[0].exc_info is not None


def test_spawn_cancellation_is_silent(caplog):
    async def main():
        async def forever():
            await asyncio.Event().wait()

        task = spawn(forever(), name="cancelled")
        await asyncio.sleep(0)
        task.cancel()
        await asyncio.gather(task, return_exceptions=True)
        await asyncio.sleep(0)
        assert task not in tasks._TASKS

    with caplog.at_level(logging.ERROR, logger="narwhal.tasks"):
        asyncio.run(main())
    assert not [r for r in caplog.records if "died" in r.message]


def test_asyncio_trap_catches_destroyed_pending_message():
    # The conftest escalation path for "Task was destroyed but it is
    # pending!" (emitted via the asyncio LOGGER, not as a warning —
    # filterwarnings cannot catch it).  Exercise the handler directly:
    # routing a real record through the live logger would rightly fail
    # THIS test's teardown.
    from tests.conftest import _AsyncioErrorTrap

    trap = _AsyncioErrorTrap()
    record = logging.LogRecord(
        "asyncio", logging.ERROR, __file__, 0,
        "Task was destroyed but it is pending!", None, None,
    )
    trap.emit(record)
    assert trap.messages == ["Task was destroyed but it is pending!"]
    trap.emit(logging.LogRecord(
        "asyncio", logging.ERROR, __file__, 0, "unrelated", None, None
    ))
    assert len(trap.messages) == 1


def test_background_tasks_gauge_registered():
    if metrics.registry().enabled:
        assert "runtime.background_tasks" in metrics.registry().gauge_fns


# -- loop-stall watchdog ------------------------------------------------------

def _stall_instruments():
    reg = metrics.registry()
    return (
        reg.histograms.get("runtime.loop_stall_seconds"),
        reg.counters.get("runtime.loop_stalls"),
    )


@pytest.mark.skipif(
    not metrics.registry().enabled, reason="metrics stubbed"
)
def test_watchdog_measures_a_real_stall_and_names_the_stack():
    async def main():
        dog = LoopWatchdog(threshold_s=0.05, interval_s=0.01).start()
        hist, ctr = _stall_instruments()
        count0, stalls0 = hist.count, ctr.value
        try:
            # Hold the loop well past the threshold (tests/ are outside
            # the linter's scope, and this blocking IS the fixture).
            await asyncio.sleep(0.03)  # let the beat task stamp once
            time.sleep(0.3)
            # Two beats after the stall: one measures the overshoot, the
            # next gives the watcher thread a tick to settle.
            await asyncio.sleep(0.05)
        finally:
            await dog.shutdown()
        assert hist.count > count0, "stall was not observed"
        assert hist.sum > 0.2  # the 0.3 s hold dominates the observation
        assert ctr.value > stalls0
        last = dog._last_stall
        assert last.get("stall_s", 0) > 0.2
        # The watcher thread captured the loop thread's stack mid-stall,
        # naming this very test as the culprit.
        assert "time.sleep" in last.get("stack", "") or "test_watchdog" in (
            last.get("stack", "")
        )

    asyncio.run(main())


def test_watchdog_quiet_loop_observes_nothing():
    async def main():
        dog = LoopWatchdog(threshold_s=0.2, interval_s=0.02).start()
        hist, _ = _stall_instruments()
        count0 = hist.count if hist else 0
        await asyncio.sleep(0.15)
        await dog.shutdown()
        assert (hist.count if hist else 0) == count0

    asyncio.run(main())


def test_install_from_env(monkeypatch):
    async def unset():
        monkeypatch.delenv("NARWHAL_LOOP_WATCHDOG_MS", raising=False)
        assert install_from_env() is None

    async def armed():
        monkeypatch.setenv("NARWHAL_LOOP_WATCHDOG_MS", "50")
        dog = install_from_env()
        assert dog is not None and dog.threshold_s == pytest.approx(0.05)
        assert asyncio.get_running_loop().slow_callback_duration == (
            pytest.approx(0.05)
        )
        await dog.shutdown()

    asyncio.run(unset())
    asyncio.run(armed())
