"""Sampling-profiler tests (narwhal_tpu/profiling.py): samples accumulate
against a busy thread with the busy frame dominating self-time, folded
output is flamegraph-shaped, the main-thread leaf timeline run-length
encodes, and a disabled profiler leaves zero series behind."""

import os
import re
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from narwhal_tpu import metrics, profiling  # noqa: E402
from narwhal_tpu.metrics import Registry  # noqa: E402
from narwhal_tpu.profiling import SamplingProfiler  # noqa: E402


def _burn_cycles_for_profiler(stop: threading.Event) -> None:
    """Deliberately-named busy loop the sampler must attribute.  The
    stop check runs once per big inner batch so the samples land in THIS
    frame, not in Event.is_set."""
    x = 1
    while not stop.is_set():
        for _ in range(50_000):
            x = (x * 31 + 7) % 1000003


def test_samples_accumulate_on_a_busy_thread():
    reg = Registry()
    prof = SamplingProfiler(hz=250, reg=reg)
    stop = threading.Event()
    t = threading.Thread(
        target=_burn_cycles_for_profiler, args=(stop,), name="busy-worker"
    )
    t.start()
    park = threading.Event()  # main-thread poll leaf = Event.wait (idle)
    try:
        prof.start()
        deadline = time.time() + 5.0
        while (
            reg.counters["profile.samples"].value < 30
            and time.time() < deadline
        ):
            park.wait(0.02)
    finally:
        prof.shutdown()
        stop.set()
        t.join()

    assert reg.counters["profile.samples"].value >= 30
    assert reg.gauges["profile.hz"].value == 250

    # The busy function dominates self-time among non-idle frames.
    top = prof.top_table()
    assert top, "top table empty despite samples"
    busy_rows = [
        r for r in top if "_burn_cycles_for_profiler" in r["frame"]
    ]
    assert busy_rows, f"busy frame missing from top table: {top[:5]}"
    assert busy_rows[0]["self"] > 0
    assert busy_rows[0]["total"] >= busy_rows[0]["self"]
    assert busy_rows[0] == max(top, key=lambda r: r["self"]), (
        "busy loop is not the dominant self-time frame: " f"{top[:5]}"
    )

    # Folded output: `thread;frame;…;leaf count` lines, busy stack present.
    folded = prof.folded()
    assert folded
    for line in folded.splitlines():
        assert re.fullmatch(r"[^ ]+( [^ ]+)* \d+", line), line
    assert any(
        "busy-worker;" in line and "_burn_cycles_for_profiler" in line
        for line in folded.splitlines()
    ), folded[:500]

    # The registry snapshot carries every profile.* surface.
    snap = reg.snapshot()
    assert snap["counters"]["profile.samples"] >= 30
    assert snap["detail"]["profile.top"]
    assert isinstance(snap["detail"]["profile.folded"], str)


def test_main_thread_timeline_run_length_encodes():
    reg = Registry()
    prof = SamplingProfiler(hz=100, reg=reg)
    # Drive sampling synchronously (no daemon thread): the main thread —
    # this test — is mid-call, so every tick appends/extends a run.
    for _ in range(10):
        prof.sample_once()
    runs = reg.snapshot()["detail"]["profile.timeline"]
    assert runs, "no main-thread leaf runs recorded"
    for start, end, samples, label in runs:
        assert end >= start and samples >= 1 and isinstance(label, str)
    # 10 identical-leaf ticks collapse into far fewer runs.
    assert sum(r[2] for r in runs) == 10
    assert len(runs) < 10


def test_idle_leaves_counted_but_excluded_from_self_time():
    reg = Registry()
    prof = SamplingProfiler(hz=100, reg=reg)
    waiter_parked = threading.Event()
    release = threading.Event()

    def waiter():
        waiter_parked.set()
        release.wait(10)

    t = threading.Thread(target=waiter, name="parked")
    t.start()
    try:
        assert waiter_parked.wait(5)
        time.sleep(0.05)  # let the waiter actually enter Event.wait
        for _ in range(5):
            prof.sample_once()
    finally:
        release.set()
        t.join()
    assert reg.counters["profile.idle_samples"].value > 0
    # The wait frame appears in the folded stacks (wall-clock truth) …
    assert "waiter" in prof.folded()
    # … but never as a self-time row (CPU attribution).
    assert not any("threading.py:wait" == r["frame"] for r in prof.top_table())


def test_disabled_profiler_leaves_zero_series(monkeypatch):
    monkeypatch.setenv("NARWHAL_PROFILE_HZ", "0")
    assert profiling.install_from_env() is None
    # A fresh registry never touched by a profiler carries no profile.*
    # series at all — "zero series when disabled".
    reg = Registry()
    snap = reg.snapshot()
    assert not any(k.startswith("profile.") for k in snap["counters"])
    assert not any(k.startswith("profile.") for k in snap["gauges"])
    assert not any(k.startswith("profile.") for k in snap["detail"])


def test_install_from_env_declines_on_stubbed_registry(monkeypatch):
    monkeypatch.setenv("NARWHAL_PROFILE_HZ", "100")
    monkeypatch.setattr(metrics.registry(), "enabled", False)
    try:
        assert profiling.install_from_env() is None
    finally:
        monkeypatch.undo()
