"""Unit tests for the metrics registry (narwhal_tpu/metrics.py): instrument
semantics, the bounded stage-trace table, snapshot atomicity under a
concurrent writer, concurrent counter updates from asyncio tasks, the
Prometheus rendering and HTTP endpoint, and the NARWHAL_METRICS=0 stub."""

import asyncio
import json
import math
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from narwhal_tpu import metrics  # noqa: E402
from narwhal_tpu.metrics import (  # noqa: E402
    COUNT_BUCKETS,
    MetricsServer,
    Registry,
    SnapshotWriter,
    TraceTable,
)


def test_counter_gauge_semantics():
    reg = Registry()
    c = reg.counter("t.counter")
    c.inc()
    c.inc(41)
    assert c.value == 42
    assert reg.counter("t.counter") is c  # memoized by name

    g = reg.gauge("t.gauge")
    g.set(7)
    g.inc(3)
    g.dec()
    assert g.value == 9

    reg.gauge_fn("t.cb", lambda: 123)
    snap = reg.snapshot()
    assert snap["counters"]["t.counter"] == 42
    assert snap["gauges"]["t.gauge"] == 9
    assert snap["gauges"]["t.cb"] == 123


def test_histogram_buckets_and_mean():
    reg = Registry()
    h = reg.histogram("t.lat")  # default latency buckets
    for v in (0.0005, 0.003, 0.003, 0.08, 99.0):
        h.observe(v)
    assert h.count == 5
    assert abs(h.sum - 99.0865) < 1e-9
    cum = dict(h.cumulative())
    assert cum[0.001] == 1          # 0.0005
    assert cum[0.005] == 3          # + both 0.003
    assert cum[0.1] == 4            # + 0.08
    assert cum[float("inf")] == 5   # 99.0 lands in +Inf
    assert abs(h.mean - 99.0865 / 5) < 1e-9

    hc = reg.histogram("t.size", COUNT_BUCKETS)
    hc.observe(1)
    hc.observe(1024)
    hc.observe(5000)
    assert dict(hc.cumulative())[1] == 1
    assert dict(hc.cumulative())[float("inf")] == 3


def test_trace_table_first_mark_wins_and_bounded():
    t = TraceTable(cap=3)
    t.mark("d1", "seal", ts=10.0, bytes=100)
    t.mark("d1", "seal", ts=5.0)  # later mark must NOT overwrite
    t.mark("d1", "quorum", ts=11.0)
    assert t.entries["d1"]["seal"] == 10.0
    assert t.entries["d1"]["quorum"] == 11.0
    assert t.entries["d1"]["bytes"] == 100
    # FIFO eviction at capacity.
    t.mark("d2", "seal", ts=1.0)
    t.mark("d3", "seal", ts=1.0)
    t.mark("d4", "seal", ts=1.0)
    assert "d1" not in t.entries and len(t.entries) == 3
    with pytest.raises(ValueError):
        t.mark("d5", "not_a_stage")


def test_concurrent_updates_from_tasks():
    """1000 increments from 10 interleaved tasks must not lose a count
    (the single-event-loop execution model the registry assumes)."""
    reg = Registry()
    c = reg.counter("t.n")
    h = reg.histogram("t.h")

    async def worker():
        for _ in range(100):
            c.inc()
            h.observe(0.01)
            await asyncio.sleep(0)

    async def go():
        await asyncio.gather(*(worker() for _ in range(10)))

    asyncio.run(go())
    assert c.value == 1000
    assert h.count == 1000


def test_snapshot_writer_atomic(tmp_path):
    """Readers polling mid-run must always see valid JSON: the writer
    rewrites via temp + os.replace, and a final snapshot lands on cancel."""
    reg = Registry()
    c = reg.counter("t.n")
    path = str(tmp_path / "metrics-test.json")

    async def go():
        writer = SnapshotWriter(reg, path, interval_s=0.005)
        task = asyncio.get_running_loop().create_task(writer.run())
        deadline = asyncio.get_running_loop().time() + 0.3
        reads = 0
        while asyncio.get_running_loop().time() < deadline:
            c.inc()
            if os.path.exists(path):
                with open(path) as f:
                    snap = json.load(f)  # must never be torn
                assert snap["counters"]["t.n"] <= c.value
                reads += 1
            await asyncio.sleep(0.002)
        task.cancel()
        await asyncio.gather(task, return_exceptions=True)
        return reads

    reads = asyncio.run(go())
    assert reads > 10
    # Final flush on cancellation captured the last value.
    with open(path) as f:
        assert json.load(f)["counters"]["t.n"] > 0


def test_prometheus_rendering():
    reg = Registry()
    reg.counter("worker.batches_sealed").inc(3)
    reg.gauge("primary.round").set(17)
    h = reg.histogram("worker.quorum_latency_seconds")
    h.observe(0.004)
    text = reg.render_prometheus()
    assert "# TYPE narwhal_worker_batches_sealed_total counter" in text
    assert "narwhal_worker_batches_sealed_total 3" in text
    assert "narwhal_primary_round 17" in text
    assert 'narwhal_worker_quorum_latency_seconds_bucket{le="+Inf"} 1' in text
    assert "narwhal_worker_quorum_latency_seconds_count 1" in text


def test_metrics_http_endpoint():
    """GET /metrics serves Prometheus text, /metrics.json the snapshot,
    anything else 404 — over a raw socket, no http client dependency."""
    reg = Registry()
    reg.counter("t.hits").inc(5)

    async def fetch(port, target):
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(
            f"GET {target} HTTP/1.1\r\nHost: x\r\n\r\n".encode()
        )
        await writer.drain()
        data = await reader.read()
        writer.close()
        return data

    async def go():
        server = await MetricsServer.spawn(reg, 0, host="127.0.0.1")
        try:
            prom = await fetch(server.port, "/metrics")
            assert b"200 OK" in prom
            assert b"narwhal_t_hits_total 5" in prom
            js = await fetch(server.port, "/metrics.json")
            body = js.split(b"\r\n\r\n", 1)[1]
            assert json.loads(body)["counters"]["t.hits"] == 5
            missing = await fetch(server.port, "/nope")
            assert b"404" in missing
        finally:
            await server.shutdown()

    asyncio.run(go())


def test_disabled_registry_is_inert():
    """NARWHAL_METRICS=0 semantics: every instrument is a shared no-op and
    snapshots stay empty — the stub the overhead measurement compares
    against."""
    reg = Registry(enabled=False)
    c = reg.counter("t.n")
    c.inc(100)
    reg.gauge("t.g").set(5)
    reg.histogram("t.h").observe(1.0)
    reg.trace.mark("d", "seal")
    reg.gauge_fn("t.cb", lambda: 1)
    snap = reg.snapshot()
    assert snap["enabled"] is False
    assert snap["counters"] == {}
    assert snap["gauges"] == {}
    assert snap["histograms"] == {}
    assert snap["trace"] == {}


def test_gauge_callback_failure_is_inband():
    """A dead callback (e.g. a torn-down queue) must not kill the
    snapshot — it is reported under `errors` instead."""
    reg = Registry()

    def boom():
        raise RuntimeError("gone")

    reg.gauge_fn("t.dead", boom)
    reg.counter("t.ok").inc()
    snap = reg.snapshot()
    assert snap["counters"]["t.ok"] == 1
    assert snap["gauges"]["t.dead"] is None
    assert any("t.dead" in e for e in snap.get("errors", []))
    # Prometheus rendering simply skips the dead gauge.
    assert "t_dead" not in reg.render_prometheus()


def test_registry_reset_zeroes_in_place():
    """reset() must keep instrument IDENTITY (module-level code holds
    references fetched at import) while zeroing values."""
    reg = Registry()
    c = reg.counter("t.n")
    c.inc(5)
    h = reg.histogram("t.h")
    h.observe(1.0)
    reg.trace.mark("d", "seal")
    reg.reset()
    assert reg.counter("t.n") is c and c.value == 0
    assert h.count == 0 and h.sum == 0.0
    assert reg.snapshot()["trace"] == {}
    c.inc()  # the held reference still counts into the registry
    assert reg.snapshot()["counters"]["t.n"] == 1


def test_stage_names_match_metrics_check():
    """The bench-side join (benchmark/metrics_check.py) and the registry
    must agree on stage names, or the breakdown silently comes out empty."""
    from benchmark.metrics_check import STAGE_ORDER

    assert tuple(STAGE_ORDER) == metrics.STAGES


def test_trace_eviction_counter_exported_and_warned(capsys):
    """Evictions past NARWHAL_TRACE_CAP must be counted, exported in the
    snapshot (gauges["metrics.trace_evictions"]), and surfaced by the
    bench cross-check as a loud UNDER-JOINED warning + stages_ms
    annotation — never a silently biased breakdown (ROADMAP item)."""
    from benchmark.logs import ParseResult
    from benchmark.metrics_check import cross_validate

    reg = Registry(trace_cap=2)
    reg.trace.mark("d1", "seal", ts=1.0)
    reg.trace.mark("d2", "seal", ts=2.0)
    assert reg.trace.evictions == 0
    reg.trace.mark("d3", "seal", ts=3.0)  # evicts d1
    reg.trace.mark("d4", "seal", ts=4.0)  # evicts d2
    assert reg.trace.evictions == 2
    snap = reg.snapshot()
    assert snap["gauges"]["metrics.trace_evictions"] == 2
    assert "d1" not in snap["trace"] and "d4" in snap["trace"]

    r = ParseResult(committed_bytes=0)
    summary = cross_validate(r, [snap], tx_size=512)
    assert summary["trace_evictions"] == 2
    assert r.stages_ms["trace_evictions"] == 2.0
    assert "UNDER-JOINED" in capsys.readouterr().err

    # reset() zeroes the eviction count with everything else.
    reg.reset()
    assert reg.trace.evictions == 0


def test_json_log_formatter_machine_joinable():
    """--log-json records: one JSON object per line with ts (unix
    epoch), level, logger, msg, node — joinable against the metrics
    time-series without timestamp re-parsing."""
    import logging
    import time

    from narwhal_tpu.node.main import JsonLogFormatter

    fmt = JsonLogFormatter("primary-AbCd1234")
    record = logging.LogRecord(
        "narwhal.metrics", logging.WARNING, __file__, 1,
        "HEALTH anomaly %s rule=%s", ("FIRING", "peer_unreachable"), None,
    )
    line = fmt.format(record)
    entry = json.loads(line)
    assert "\n" not in line
    assert entry["level"] == "WARNING"
    assert entry["logger"] == "narwhal.metrics"
    assert entry["msg"] == "HEALTH anomaly FIRING rule=peer_unreachable"
    assert entry["node"] == "primary-AbCd1234"
    assert abs(entry["ts"] - time.time()) < 60

    try:
        raise ValueError("boom")
    except ValueError:
        import sys as _sys

        rec2 = logging.LogRecord(
            "narwhal.node", logging.ERROR, __file__, 1, "died", (),
            _sys.exc_info(),
        )
    entry2 = json.loads(fmt.format(rec2))
    assert "ValueError: boom" in entry2["exc"]


def test_cross_validate_agreement_and_failure():
    """The bench cross-check passes on agreeing channels, hard-fails
    (error entry) past the 5% tolerance, and emits the stage breakdown."""
    from benchmark.logs import ParseResult
    from benchmark.metrics_check import cross_validate

    def snap(trace):
        return {"enabled": True, "trace": trace}

    # Worker snapshot: seal/quorum stamps + bytes; primary snapshot: the
    # rest of the chain.  Two batches of 512 B * 100 tx each.
    worker = snap({
        "d1": {"seal": 1.0, "quorum": 1.1, "bytes": 51200},
        "d2": {"seal": 2.0, "quorum": 2.1, "bytes": 51200},
    })
    primary = snap({
        "d1": {"digest_at_primary": 1.2, "header": 1.3, "cert": 1.5,
               "cert_inserted": 1.6, "commit_trigger": 1.8,
               "walk_done": 1.85, "commit": 1.9},
        "d2": {"digest_at_primary": 2.2, "header": 2.3, "cert": 2.5,
               "cert_inserted": 2.6, "commit_trigger": 2.8,
               "walk_done": 2.85, "commit": 2.9},
    })

    r = ParseResult(committed_bytes=102400)
    summary = cross_validate(r, [worker, primary], tx_size=512)
    assert not r.errors
    assert r.metrics_committed_tx == 200.0
    assert r.metrics_disagreement == 0.0
    assert summary["traced_full_chain"] == 2
    # Mean per-leg latencies (both batches identical): e.g. seal→quorum
    # 100 ms, cert→commit 400 ms, full chain 900 ms.  cert→commit is
    # reported BOTH as the aggregate leg (the number every prior artifact
    # tracks) and as its new sub-stages.
    assert math.isclose(r.stages_ms["seal_to_quorum"], 100.0, abs_tol=0.2)
    assert math.isclose(r.stages_ms["cert_to_commit"], 400.0, abs_tol=0.2)
    assert math.isclose(
        r.stages_ms["cert_to_cert_inserted"], 100.0, abs_tol=0.2
    )
    assert math.isclose(
        r.stages_ms["cert_inserted_to_commit_trigger"], 200.0, abs_tol=0.2
    )
    assert math.isclose(
        r.stages_ms["commit_trigger_to_walk_done"], 50.0, abs_tol=0.2
    )
    assert math.isclose(
        r.stages_ms["walk_done_to_commit"], 50.0, abs_tol=0.2
    )
    assert math.isclose(r.stages_ms["seal_to_commit"], 900.0, abs_tol=0.2)

    # >5% disagreement between channels is fatal.
    r2 = ParseResult(committed_bytes=200000)
    cross_validate(r2, [worker, primary], tx_size=512)
    assert any("cross-check FAILED" in e for e in r2.errors)


# --- round-cadence trace + attribution (ISSUE r10) ---------------------------


def test_round_trace_table_semantics():
    """The per-round cadence trace: validates ROUND_STAGES names (digest
    stages are rejected), appears in snapshots under round_trace, and
    resets with the registry."""
    reg = Registry()
    reg.round_trace.mark("3", "header_proposed", ts=1.0)
    reg.round_trace.mark("3", "round_advance", ts=2.0)
    with pytest.raises(ValueError):
        reg.round_trace.mark("3", "seal")  # digest stage, wrong table
    with pytest.raises(ValueError):
        reg.trace.mark("d1", "header_proposed")  # round stage, wrong table
    snap = reg.snapshot()
    assert snap["round_trace"]["3"] == {
        "header_proposed": 1.0, "round_advance": 2.0,
    }
    assert reg.snapshot(include_trace=False)["round_trace"] == {}
    reg.reset()
    assert reg.round_trace.entries == {}


def test_round_attribution_telescopes_to_round_period(capsys):
    """round_attribution: legs (including the derived advance→proposed
    wait) telescope to exactly the per-round period, aggregate across
    nodes without cross-node joins, and cross-check against the
    round_advance_seconds histogram."""
    from benchmark.metrics_check import round_attribution

    def entry(base, scale=1.0):
        # One round's stages: proposed at +0, broadcast +10ms, first vote
        # +20ms, quorum +40ms, cert bcast +45ms, parent quorum +70ms,
        # advance +75ms.
        offs = {
            "header_proposed": 0.0, "header_broadcast": 0.010,
            "first_vote": 0.020, "vote_quorum": 0.040,
            "cert_broadcast": 0.045, "parent_quorum": 0.070,
            "round_advance": 0.075,
        }
        return {k: base + scale * v for k, v in offs.items()}

    # Node A: rounds 1-3, 100 ms apart (so the advance->proposed wait is
    # 25 ms); node B: same shape shifted — legs must NOT join across
    # nodes (a cross-node join would corrupt every leg).
    snap_a = {
        "enabled": True,
        "round_trace": {"1": entry(0.0), "2": entry(0.1), "3": entry(0.2)},
        "histograms": {
            "primary.round_advance_seconds": {"count": 2, "sum": 0.2}
        },
    }
    snap_b = {
        "enabled": True,
        "round_trace": {"1": entry(50.0), "2": entry(50.1)},
        "histograms": {
            "primary.round_advance_seconds": {"count": 1, "sum": 0.1}
        },
    }
    out = round_attribution([snap_a, snap_b])
    # Rounds 2,3 on A + round 2 on B (round 1 has no previous advance).
    assert out["rounds_joined"] == 3
    legs = out["round_stages_ms"]
    assert math.isclose(legs["advance_to_header_proposed"], 25.0, abs_tol=0.01)
    assert math.isclose(legs["header_proposed_to_header_broadcast"], 10.0, abs_tol=0.01)
    assert math.isclose(legs["first_vote_to_vote_quorum"], 20.0, abs_tol=0.01)
    assert math.isclose(legs["parent_quorum_to_round_advance"], 5.0, abs_tol=0.01)
    # Telescoping: legs sum to the measured 100 ms round period, which
    # agrees with the histogram (no warning).
    assert math.isclose(out["round_period_ms"], 100.0, abs_tol=0.01)
    assert math.isclose(out["stage_sum_ms"], 100.0, abs_tol=0.01)
    assert math.isclose(out["round_advance_hist_ms"], 100.0, abs_tol=0.01)
    assert out["stage_sum_vs_hist"] < 0.10
    assert "WARNING" not in capsys.readouterr().err

    # A >10% gap between the stage sum and the histogram warns loudly.
    snap_bad = dict(snap_a)
    snap_bad["histograms"] = {
        "primary.round_advance_seconds": {"count": 2, "sum": 0.4}
    }
    out_bad = round_attribution([snap_bad])
    assert out_bad["stage_sum_vs_hist"] > 0.10
    assert "round-cadence sub-stages" in capsys.readouterr().err


def test_round_attribution_partial_rounds_skipped():
    """Boot/tail rounds missing stages (or the previous round's advance
    anchor) are dropped, never fabricated."""
    from benchmark.metrics_check import round_attribution

    snap = {
        "enabled": True,
        "round_trace": {
            "1": {"header_proposed": 0.0, "round_advance": 0.075},
            # round 2 is complete but round 1 is partial -> still usable
            # (only the PREVIOUS round_advance is needed as anchor).
            "2": {
                "header_proposed": 0.1, "header_broadcast": 0.11,
                "first_vote": 0.12, "vote_quorum": 0.14,
                "cert_broadcast": 0.145, "parent_quorum": 0.17,
                "round_advance": 0.175,
            },
            # round 4: no round 3 anchor -> dropped.
            "4": {
                "header_proposed": 0.3, "header_broadcast": 0.31,
                "first_vote": 0.32, "vote_quorum": 0.34,
                "cert_broadcast": 0.345, "parent_quorum": 0.37,
                "round_advance": 0.375,
            },
            "not-a-round": {"header_proposed": 9.9},
        },
    }
    out = round_attribution([snap])
    assert out["rounds_joined"] == 1
    assert math.isclose(out["round_period_ms"], 100.0, abs_tol=0.01)


def test_cross_validate_carries_round_attribution():
    """cross_validate embeds the round attribution next to stages_ms and
    fills ParseResult.round_stages_ms for the bench JSON."""
    from benchmark.logs import ParseResult
    from benchmark.metrics_check import cross_validate

    snap = {
        "enabled": True,
        "trace": {},
        "round_trace": {
            "1": {
                "header_proposed": 0.0, "header_broadcast": 0.01,
                "first_vote": 0.02, "vote_quorum": 0.04,
                "cert_broadcast": 0.045, "parent_quorum": 0.07,
                "round_advance": 0.075,
            },
            "2": {
                "header_proposed": 0.1, "header_broadcast": 0.11,
                "first_vote": 0.12, "vote_quorum": 0.14,
                "cert_broadcast": 0.145, "parent_quorum": 0.17,
                "round_advance": 0.175,
            },
        },
    }
    r = ParseResult(committed_bytes=0)
    summary = cross_validate(r, [snap], tx_size=512)
    assert summary["round_attribution"]["rounds_joined"] == 1
    assert math.isclose(
        r.round_stages_ms["advance_to_header_proposed"], 25.0, abs_tol=0.01
    )
    assert math.isclose(
        summary["round_attribution"]["round_period_ms"], 100.0, abs_tol=0.01
    )


def test_node_scope_attributes_detection_counters():
    """Registry.node_scope (PR 15): DETECTION_COUNTERS fetched inside a
    scope return a facade feeding both the shared counter and a
    per-node `detect.<counter>.<label>` shadow; ordinary counters and
    out-of-scope fetches are untouched, and production (no scope) hands
    out the plain counter object."""
    from narwhal_tpu.metrics import DETECTION_COUNTERS, Registry

    reg = Registry()
    name = "primary.equivocations_detected"
    assert name in DETECTION_COUNTERS

    plain = reg.counter(name)
    plain.inc()
    with reg.node_scope("primary-0"):
        a = reg.counter(name)
        other = reg.counter("primary.headers_processed")
        a.inc(2)
        other.inc()
    with reg.node_scope("primary-1"):
        b = reg.counter(name)
        b.inc(3)
    # Shared counter aggregates everything; facade .value reads through.
    assert reg.counters[name].value == 6
    assert a.value == 6 and a.name == name
    # Shadows split by node; the non-detection counter grew no shadow.
    assert reg.counters[f"detect.{name}.primary-0"].value == 2
    assert reg.counters[f"detect.{name}.primary-1"].value == 3
    assert not any(
        n.startswith("detect.primary.headers_processed") for n in reg.counters
    )
    # Outside any scope the plain counter object is returned (and incs
    # recorded through an earlier facade landed on the same object).
    again = reg.counter(name)
    assert again is reg.counters[name]
    # A scope held across an inc after exit still writes the shadow (the
    # facade captured its node at construction — by design: components
    # fetch at init inside the scope and inc forever after).
    a.inc()
    assert reg.counters[f"detect.{name}.primary-0"].value == 3
    assert reg.counters[name].value == 7
