"""LowDepthTusk vs its frozen oracle (consensus/golden_lowdepth.py).

The lower-depth commit rule CHANGES the commit sequence by design, so it
gets its own golden oracle and the full PR 4 replay/fuzz discipline:
reference scenarios, multi-leader bursts, gc-window wrap, checkpoint
restore, and randomized DAGs (in-order and out-of-order delivery) must
be byte-identical between the live indexed rule and the naive dict-walk
oracle — while classic-rule runs stay byte-identical to GoldenTusk
(pinned here too, so the flag can never leak across arms).  The flag
plumbing is covered alongside: constructor/env resolution, the classic
default, the kernel refusal, cross-rule checkpoint refusal, and the
audit rule marker judged per segment.
"""

import asyncio
import os
import random

import pytest

from narwhal_tpu.consensus import (
    CheckpointRuleMismatch,
    Consensus,
    LowDepthTusk,
    Tusk,
    resolve_commit_rule,
)
from narwhal_tpu.consensus.golden import GoldenTusk
from narwhal_tpu.consensus.golden_lowdepth import GoldenLowDepthTusk
from narwhal_tpu.consensus.replay import read_audit, replay_segments, TAG_RULE
from tests.common import committee
from tests.test_consensus import (
    feed,
    genesis_digests,
    make_certificates,
    mock_certificate,
    sorted_names,
)
from tests.test_tusk_equivalence import _random_dag_certs


def both_walks(certs, gc_depth=50):
    """Feed the identical delivery order through the frozen lowdepth
    oracle and the live indexed rule; assert byte-identical sequences."""
    c = committee()
    golden = feed(
        GoldenLowDepthTusk(c, gc_depth=gc_depth, fixed_coin=True), certs
    )
    live = feed(LowDepthTusk(c, gc_depth=gc_depth, fixed_coin=True), certs)
    assert [bytes(x.digest()) for x in live] == [
        bytes(x.digest()) for x in golden
    ]
    return golden


def test_reference_scenarios_equivalence():
    """The reference consensus_tests.rs stream shapes, lowdepth live vs
    lowdepth oracle — plus the depth claim itself: at equal stream depth
    the lowdepth rule commits leaders the classic rule still holds."""
    c = committee()
    names = sorted_names()

    # commit_one's stream: rounds 1..4 + the round-5 trigger.  A single
    # round-5 certificate satisfies the classic trigger (f+1 support for
    # leader 2 already sits at round 3) but NOT the lowdepth direct gate
    # for leader 4 (2f+1 support needs a quorum of round-5 children), so
    # both rules commit exactly the leader-2 cone — the lowdepth rule
    # just commits it EARLIER: at the third round-3 certificate, four
    # deliveries before classic's round-5 trigger.
    certs, next_parents = make_certificates(1, 4, genesis_digests(c), names)
    _, trigger = mock_certificate(names[0], 5, next_parents)
    committed = both_walks(certs + [trigger])
    classic = feed(Tusk(c, gc_depth=50, fixed_coin=True), certs + [trigger])
    assert [bytes(x.digest()) for x in committed] == [
        bytes(x.digest()) for x in classic
    ]
    early = LowDepthTusk(c, gc_depth=50, fixed_coin=True)
    first_commit_at = next(
        i
        for i, cert in enumerate(certs)
        if early.process_certificate(cert)
    )
    assert first_commit_at < len(certs) - 1, (
        "lowdepth must commit before the stream (let alone the round-5 "
        "trigger) ends"
    )

    # dead_node: one authority silent for the whole run.
    certs, _ = make_certificates(1, 9, genesis_digests(c), names[:3])
    assert both_walks(certs)

    # missing_leader: the leader authority idle for rounds 1-2.
    certs = []
    out, parents = make_certificates(1, 2, genesis_digests(c), names[1:])
    certs.extend(out)
    out, parents = make_certificates(3, 6, parents, names)
    certs.extend(out)
    _, trigger = mock_certificate(names[0], 7, parents)
    both_walks(certs + [trigger])


def test_multi_leader_burst_equivalence():
    """Odd rounds delivered before even rounds: direct support exists
    before any leader does, so each leader's own (late) arrival is the
    trigger — the seeding path — and each commit burst must match the
    oracle's."""
    c = committee()
    names = sorted_names()
    certs, parents = make_certificates(1, 16, genesis_digests(c), names)
    order = sorted(certs, key=lambda x: (x.round % 2 == 0, x.round))
    _, trigger = mock_certificate(names[0], 17, parents)
    got = both_walks(order + [trigger])
    # Several leader rounds committed (multi-leader coverage).
    assert len({x.round for x in got if x.round % 2 == 0}) >= 3


def test_gc_window_wrap_equivalence():
    """Continuous commits across several multiples of a small gc window:
    end-state parity, not just sequence parity."""
    c = committee()
    names = sorted_names()
    certs, _ = make_certificates(1, 30, genesis_digests(c), names)
    golden = GoldenLowDepthTusk(c, gc_depth=6, fixed_coin=True)
    live = LowDepthTusk(c, gc_depth=6, fixed_coin=True)
    got_g = feed(golden, certs)
    got_l = feed(live, certs)
    assert [bytes(x.digest()) for x in got_l] == [
        bytes(x.digest()) for x in got_g
    ]
    assert got_g, "fixture must commit"
    assert live.state.last_committed == golden.state.last_committed
    assert live.state.last_committed_round == golden.state.last_committed_round
    assert {
        r: set(v) for r, v in live.state.dag.items()
    } == {r: set(v) for r, v in golden.state.dag.items()}


def test_checkpoint_restore_equivalence():
    """Both lowdepth walks restored from the same frontier blob ignore a
    full catch-up replay and then commit new rounds byte-identically."""
    c = committee()
    names = sorted_names()
    certs, next_parents = make_certificates(1, 4, genesis_digests(c), names)
    _, trigger = mock_certificate(names[0], 5, next_parents)

    first = GoldenLowDepthTusk(c, gc_depth=50, fixed_coin=True)
    assert feed(first, certs + [trigger])
    blob = first.state.snapshot_bytes()
    assert blob[:6] == b"NCKLD1"

    golden = GoldenLowDepthTusk(c, gc_depth=50, fixed_coin=True)
    golden.state.restore(blob)
    live = LowDepthTusk(c, gc_depth=50, fixed_coin=True)
    live.state.restore(blob)
    assert feed(golden, certs + [trigger]) == []
    assert feed(live, certs + [trigger]) == []

    more, tail_parents = make_certificates(5, 8, next_parents, names)
    more = more[1:]  # round-5 leader already exists as `trigger`
    _, trigger2 = mock_certificate(names[0], 9, tail_parents)
    got = feed(live, more + [trigger2])
    want = feed(golden, more + [trigger2])
    assert [bytes(x.digest()) for x in got] == [
        bytes(x.digest()) for x in want
    ]
    assert got, "the restored instances must keep committing"


def test_fuzz_equivalence_in_and_out_of_order():
    rng = random.Random(0x10D)
    for trial in range(6):
        certs = _random_dag_certs(rng, rounds=rng.randint(6, 20))
        order = list(certs)
        order.sort(key=lambda x: (x.round, rng.random()))
        both_walks(order)
    for trial in range(4):
        certs = _random_dag_certs(rng, rounds=rng.randint(6, 16))
        order = list(certs)
        # Children ahead of their parents in delivery order.
        order.sort(key=lambda x: x.round + rng.uniform(-2.2, 0.0))
        both_walks(order)


def test_fuzz_small_gc_depth_equivalence():
    rng = random.Random(0x1DC)
    for _ in range(3):
        both_walks(_random_dag_certs(rng, rounds=14), gc_depth=4)


def test_lowdepth_commits_ahead_of_classic():
    """The latency mechanism, pinned structurally: on one round-ordered
    full stream the lowdepth frontier is NEVER behind classic, runs 2
    rounds ahead whenever its direct path has fired (depth 1 vs depth 3
    on the leader), every leader is committed at a strictly earlier
    delivery index, and the full sequences agree where both committed
    (the lowdepth sequence extends the classic one, never reorders
    it)."""
    c = committee()
    names = sorted_names()
    certs, _ = make_certificates(1, 20, genesis_digests(c), names)
    classic = Tusk(c, gc_depth=50, fixed_coin=True)
    lowdepth = LowDepthTusk(c, gc_depth=50, fixed_coin=True)
    gaps = set()
    seq_classic, seq_lowdepth = [], []
    first_commit = {}  # leader round → (lowdepth index, classic index)
    for i, cert in enumerate(certs):
        seq_classic.extend(classic.process_certificate(cert))
        seq_lowdepth.extend(lowdepth.process_certificate(cert))
        for tusk, slot in ((lowdepth, 0), (classic, 1)):
            r = tusk.state.last_committed_round
            if r and r not in first_commit:
                first_commit.setdefault(r, [None, None])
            for rr in first_commit:
                if rr <= r and first_commit[rr][slot] is None:
                    first_commit[rr][slot] = i
        if classic.state.last_committed_round > 0:
            gaps.add(
                lowdepth.state.last_committed_round
                - classic.state.last_committed_round
            )
    assert gaps == {0, 2}, gaps
    assert min(gaps) >= 0, "lowdepth frontier must never trail classic"
    reached_by_both = [
        v for v in first_commit.values() if None not in v
    ]
    assert reached_by_both
    assert all(low < cl for low, cl in reached_by_both), first_commit
    # Sequence agreement: lowdepth extends, never reorders.
    a = [bytes(x.digest()) for x in seq_classic]
    b = [bytes(x.digest()) for x in seq_lowdepth]
    assert len(b) > len(a)
    assert b[: len(a)] == a


# -- flag plumbing -------------------------------------------------------------


def run_consensus(tmp_path, certs, want, name, **kwargs):
    """Drive a Consensus instance over `certs`; assert the output equals
    `want`; return the audit segment path."""
    audit = os.path.join(str(tmp_path), f"{name}.audit.bin")

    async def go():
        rx, tx_primary, tx_output = (
            asyncio.Queue(), asyncio.Queue(), asyncio.Queue(),
        )
        cons = Consensus(
            committee(), 50, rx, tx_primary, tx_output,
            fixed_coin=True, audit_path=audit, **kwargs,
        )
        for cert in certs:
            rx.put_nowait(cert)
        task = asyncio.ensure_future(cons.run())
        out = [
            await asyncio.wait_for(tx_output.get(), 5) for _ in range(len(want))
        ]
        task.cancel()
        await asyncio.gather(task, return_exceptions=True)
        cons._audit.close()
        assert [bytes(x.digest()) for x in out] == [
            bytes(x.digest()) for x in want
        ]
        return cons

    cons = asyncio.run(asyncio.wait_for(go(), 15))
    return audit, cons


def _stream():
    c = committee()
    names = sorted_names()
    certs, next_parents = make_certificates(1, 8, genesis_digests(c), names)
    _, trigger = mock_certificate(names[0], 9, next_parents)
    return certs + [trigger]


def test_classic_default_and_env_selection(tmp_path, monkeypatch):
    """Unset flag → classic, byte-identical to GoldenTusk; the env knob
    selects lowdepth; the constructor arg beats the env (CLI precedence
    — node/main.py passes --commit-rule through as the arg)."""
    certs = _stream()
    c = committee()

    monkeypatch.delenv("NARWHAL_COMMIT_RULE", raising=False)
    want = feed(GoldenTusk(c, 50, fixed_coin=True), certs)
    _, cons = run_consensus(tmp_path, certs, want, "default")
    assert isinstance(cons.tusk, Tusk) and not isinstance(
        cons.tusk, LowDepthTusk
    )
    assert cons.commit_rule == "classic"

    monkeypatch.setenv("NARWHAL_COMMIT_RULE", "lowdepth")
    assert resolve_commit_rule() == "lowdepth"
    want = feed(GoldenLowDepthTusk(c, 50, fixed_coin=True), certs)
    _, cons = run_consensus(tmp_path, certs, want, "env")
    assert isinstance(cons.tusk, LowDepthTusk)

    # Explicit arg (the CLI path) wins over the env.
    want = feed(GoldenTusk(c, 50, fixed_coin=True), certs)
    _, cons = run_consensus(
        tmp_path, certs, want, "arg-wins", commit_rule="classic"
    )
    assert cons.commit_rule == "classic"

    monkeypatch.setenv("NARWHAL_COMMIT_RULE", "sideways")
    with pytest.raises(ValueError, match="sideways"):
        resolve_commit_rule()
    assert resolve_commit_rule("lowdepth") == "lowdepth"


def test_kernel_refuses_lowdepth(tmp_path):
    with pytest.raises(ValueError, match="classic walk only"):
        Consensus(
            committee(), 50,
            asyncio.Queue(), asyncio.Queue(), asyncio.Queue(),
            use_kernel=True, commit_rule="lowdepth",
        )


def test_checkpoint_refuses_cross_rule_restore(tmp_path):
    """A checkpoint written under one rule must refuse — loudly, at boot,
    NOT via the torn-file fresh-frontier fallback — to restore under the
    other (both directions)."""
    c = committee()
    for writer, reader_rule in (
        (Tusk(c, 50, fixed_coin=True), "lowdepth"),
        (LowDepthTusk(c, 50, fixed_coin=True), "classic"),
    ):
        feed(writer, _stream())
        assert writer.state.last_committed_round > 0
        path = os.path.join(
            str(tmp_path), f"ckpt-{writer.commit_rule}.consensus.ckpt"
        )
        with open(path, "wb") as f:
            f.write(writer.state.snapshot_bytes())
        with pytest.raises(CheckpointRuleMismatch):
            Consensus(
                c, 50,
                asyncio.Queue(), asyncio.Queue(), asyncio.Queue(),
                fixed_coin=True,
                checkpoint_path=path,
                commit_rule=reader_rule,
            )
        # Same rule restores fine.
        cons = Consensus(
            c, 50,
            asyncio.Queue(), asyncio.Queue(), asyncio.Queue(),
            fixed_coin=True,
            checkpoint_path=path,
            commit_rule=writer.commit_rule,
        )
        assert (
            cons.tusk.state.last_committed_round
            == writer.state.last_committed_round
        )


def test_audit_rule_marker_judged_per_segment(tmp_path):
    """Each audit segment records its commit rule and the replay judge
    picks the matching oracle per segment: a lowdepth recording passes
    under the lowdepth oracle, is NOT judged by GoldenTusk, and a
    classic segment alongside it still judges classic — while a
    lowdepth recording whose marker claims classic fails its replay."""
    c = committee()
    certs = _stream()

    want_ld = feed(GoldenLowDepthTusk(c, 50, fixed_coin=True), certs)
    audit_ld, _ = run_consensus(
        tmp_path, certs, want_ld, "seg-ld", commit_rule="lowdepth"
    )
    records = read_audit(audit_ld)
    assert records[1] == (TAG_RULE, b"lowdepth")

    want_cl = feed(GoldenTusk(c, 50, fixed_coin=True), certs)
    audit_cl, _ = run_consensus(
        tmp_path, certs, want_cl, "seg-cl", commit_rule="classic"
    )
    assert read_audit(audit_cl)[1] == (TAG_RULE, b"classic")

    # Each judged under its own oracle, in one replay call.
    verdict = replay_segments(c, 50, [audit_ld], fixed_coin=True)
    assert verdict["ok"], verdict["violations"]
    assert verdict["rules"] == ["lowdepth"]
    verdict = replay_segments(c, 50, [audit_cl], fixed_coin=True)
    assert verdict["ok"], verdict["violations"]
    assert verdict["rules"] == ["classic"]

    # A lying marker (lowdepth recording re-tagged classic) must FAIL.
    # The stream matters: on a trigger-terminated stream both rules
    # commit the identical sequence (lowdepth only commits EARLIER), so
    # use the trigger-less stream where the lowdepth recording commits
    # two leader rounds the classic oracle never reaches — the recorded
    # sequence is then longer than the lying oracle's and diverges.
    body = _stream()[:-1]
    want_tail = feed(GoldenLowDepthTusk(c, 50, fixed_coin=True), body)
    audit_tail, _ = run_consensus(
        tmp_path, body, want_tail, "seg-tail", commit_rule="lowdepth"
    )
    classic_replay = feed(GoldenTusk(c, 50, fixed_coin=True), body)
    assert len(want_tail) > len(classic_replay)
    lying = os.path.join(str(tmp_path), "seg-lying.audit.bin")
    with open(audit_tail, "rb") as f:
        blob = f.read()
    with open(lying, "wb") as f:
        f.write(blob.replace(b"M\x08\x00\x00\x00lowdepth", b"M\x07\x00\x00\x00classic", 1))
    verdict = replay_segments(c, 50, [lying], fixed_coin=True)
    assert not verdict["ok"]
    assert verdict["rules"] == ["classic"]


def test_markerless_segment_replays_classic(tmp_path):
    """Pre-marker segments (and harness-written fixtures) still judge:
    no TAG_RULE record means the classic oracle, which is what recorded
    them."""
    c = committee()
    certs = _stream()
    want = feed(GoldenTusk(c, 50, fixed_coin=True), certs)
    audit, _ = run_consensus(
        tmp_path, certs, want, "seg-old", commit_rule="classic"
    )
    with open(audit, "rb") as f:
        blob = f.read()
    stripped = os.path.join(str(tmp_path), "seg-stripped.audit.bin")
    with open(stripped, "wb") as f:
        f.write(blob.replace(b"M\x07\x00\x00\x00classic", b"", 1))
    verdict = replay_segments(c, 50, [stripped], fixed_coin=True)
    assert verdict["ok"], verdict["violations"]
    assert verdict["rules"] == ["classic"]
