"""Wire-goodput ledger tests: per-type accounting at the network seam,
retransmitted bytes in their own counter (never inflating per-type
protocol bytes), sender/receiver reconciliation — including under forced
ReliableSender retries and netem segment loss — and the bench-side
``wire``/``crypto`` summary join."""

import asyncio
import contextlib

from narwhal_tpu import metrics
from narwhal_tpu.faults import netem
from narwhal_tpu.network import wirev2
from narwhal_tpu.messages import (
    PRIMARY_WORKER_FRAME_TYPES,
    WORKER_FRAME_TYPES,
    frame_classifier,
)
from narwhal_tpu.network import Receiver, ReliableSender, SimpleSender
from narwhal_tpu.network.framing import read_frame, write_frame
from narwhal_tpu.primary.messages import PRIMARY_FRAME_TYPES
from benchmark.metrics_check import wire_crypto_summary
from tests.common import RecordingAckHandler


def run(coro, timeout=30):
    return asyncio.run(asyncio.wait_for(coro, timeout))


@contextlib.contextmanager
def legacy_wire():
    """Pin the legacy (pre-v2) wire arm: the byte-exact accounting
    assertions below are the LEGACY path's contract — counted bytes ==
    len(data) per frame — which wire v2 deliberately changes (counted
    bytes are the compressed wire payload; tests/test_wire_v2.py covers
    that arm's invariants)."""
    wirev2.set_enabled(False)
    try:
        yield
    finally:
        wirev2.set_enabled(None)


def cnt(name: str) -> float:
    c = metrics.registry().counters.get(name)
    return c.value if c is not None else 0


class _Delta:
    """Counter deltas across a block (the global registry is shared
    across tests, so assertions use differences, not absolutes)."""

    def __init__(self, *names):
        self.names = names

    def __enter__(self):
        self.before = {n: cnt(n) for n in self.names}
        return self

    def __exit__(self, *exc):
        self.after = {n: cnt(n) for n in self.names}
        return False

    def __getitem__(self, name):
        return self.after[name] - self.before[name]


def test_frame_classifier_maps_plane_tags():
    classify = frame_classifier(PRIMARY_FRAME_TYPES)
    assert classify(bytes([0]) + b"x") == "header"
    assert classify(bytes([1])) == "vote"
    assert classify(bytes([2])) == "certificate"
    assert classify(bytes([3])) == "cert_request"
    assert classify(bytes([250])) == "unknown"
    assert classify(b"") == "unknown"
    # Independent tag spaces: the same first byte means different things
    # per plane — which is why each Receiver gets its own classifier.
    assert frame_classifier(WORKER_FRAME_TYPES)(bytes([0])) == "batch"
    assert (
        frame_classifier(PRIMARY_WORKER_FRAME_TYPES)(bytes([0]))
        == "synchronize"
    )


def test_wire_ledger_flat_counters_and_peer_detail():
    reg = metrics.Registry()
    reg.wire.account("out", "header", "10.0.0.1:100", 500)
    reg.wire.account("out", "header", "10.0.0.1:100", 500, retransmit=True)
    reg.wire.account("in", "batch", "10.0.0.2", 1000)
    assert reg.counters["wire.out.frames.header"].value == 1
    assert reg.counters["wire.out.bytes.header"].value == 500
    assert reg.counters["wire.out.retransmit_frames.header"].value == 1
    assert reg.counters["wire.out.retransmit_bytes.header"].value == 500
    assert reg.counters["wire.in.bytes.batch"].value == 1000
    # Peer detail: [frames, bytes, re_frames, re_bytes], via detail_fn.
    snap = reg.snapshot(include_trace=False)
    peers = snap["detail"]["wire.peers"]
    assert peers["out"]["header"]["10.0.0.1:100"] == [1, 500, 1, 500]
    assert peers["in"]["batch"]["10.0.0.2"] == [1, 1000, 0, 0]
    # reset() zeroes the counters and clears per-peer state in place.
    reg.reset()
    assert reg.counters["wire.out.bytes.header"].value == 0
    assert reg.wire.peers == {"out": {}, "in": {}}


def test_sender_receiver_totals_reconcile_per_type():
    """Typed frames through a live ReliableSender → Receiver: sender-side
    first-transmission totals equal receiver-side totals exactly per
    type (loopback, no loss)."""

    async def go():
        addr = "127.0.0.1:12310"
        handler = RecordingAckHandler()
        assert not wirev2.enabled()
        recv = await Receiver.spawn(
            addr, handler, classify=frame_classifier(PRIMARY_FRAME_TYPES)
        )
        sender = ReliableSender()
        frames = [
            (bytes([0]) + b"h" * 99, "header"),
            (bytes([0]) + b"h" * 99, "header"),
            (bytes([1]) + b"v" * 49, "vote"),
            (bytes([2]) + b"c" * 199, "certificate"),
        ]
        with _Delta(
            "wire.out.bytes.header", "wire.in.bytes.header",
            "wire.out.frames.header", "wire.in.frames.header",
            "wire.out.bytes.vote", "wire.in.bytes.vote",
            "wire.out.bytes.certificate", "wire.in.bytes.certificate",
            "wire.out.retransmit_bytes.header",
        ) as d:
            futs = [sender.send(addr, data, t) for data, t in frames]
            await asyncio.gather(*futs)
        assert d["wire.out.bytes.header"] == 200
        assert d["wire.out.frames.header"] == 2
        assert d["wire.out.bytes.vote"] == 50
        assert d["wire.out.bytes.certificate"] == 200
        assert d["wire.out.retransmit_bytes.header"] == 0
        # Receiver classified the same bytes into the same types.
        assert d["wire.in.bytes.header"] == 200
        assert d["wire.in.frames.header"] == 2
        assert d["wire.in.bytes.vote"] == 50
        assert d["wire.in.bytes.certificate"] == 200
        sender.close()
        await recv.shutdown()

    with legacy_wire():
        run(go())


def test_simple_sender_typed_accounting():
    async def go():
        addr = "127.0.0.1:12320"
        handler = RecordingAckHandler()
        recv = await Receiver.spawn(
            addr, handler,
            classify=frame_classifier(PRIMARY_WORKER_FRAME_TYPES),
        )
        sender = SimpleSender()
        with _Delta(
            "wire.out.bytes.cleanup", "wire.in.bytes.cleanup"
        ) as d:
            sender.send(addr, bytes([1]) + b"r" * 8, msg_type="cleanup")
            await asyncio.wait_for(handler.arrived.wait(), 10)
            # One extra poll tick: the receiver-side account happens just
            # before dispatch, but give the sender's write accounting a
            # breath too.
            await asyncio.sleep(0.05)
        assert d["wire.out.bytes.cleanup"] == 9
        assert d["wire.in.bytes.cleanup"] == 9
        sender.close()
        await recv.shutdown()

    run(go())


def test_retransmitted_bytes_land_in_retransmit_counter():
    """Force a ReliableSender retry: a peer that reads the frame and dies
    without ACKing.  The re-write after reconnect must land in the
    retransmit counters — the per-type first-transmission bytes count
    the frame exactly ONCE, so goodput's per-type protocol cost is
    never inflated by the retry."""

    async def go():
        port = 12330
        addr = f"127.0.0.1:{port}"
        data = bytes([0]) + b"h" * 199  # "header"

        first_conn = asyncio.Event()

        async def flaky(reader, writer):
            # Read the frame (so the sender believes the write
            # succeeded), then drop the connection without ACKing.
            try:
                await read_frame(reader)
            except Exception:
                pass
            first_conn.set()
            writer.close()

        flaky_srv = await asyncio.start_server(flaky, "127.0.0.1", port)
        sender = ReliableSender()
        with _Delta(
            "wire.out.bytes.header",
            "wire.out.frames.header",
            "wire.out.retransmit_bytes.header",
            "wire.out.retransmit_frames.header",
            "wire.in.bytes.header",
            "net.reliable.retransmissions",
        ) as d:
            fut = sender.send(addr, data, "header")
            await asyncio.wait_for(first_conn.wait(), 10)
            flaky_srv.close()
            await flaky_srv.wait_closed()
            # Real receiver takes over the port; the sender's reconnect
            # loop redelivers the un-ACKed frame.
            handler = RecordingAckHandler()
            recv = await Receiver.spawn(
                addr, handler,
                classify=frame_classifier(PRIMARY_FRAME_TYPES),
            )
            await asyncio.wait_for(fut, 20)  # resolves on the real ACK
        # First transmission counted once; every re-write is retransmit.
        assert d["wire.out.bytes.header"] == 200
        assert d["wire.out.frames.header"] == 1
        assert d["wire.out.retransmit_frames.header"] >= 1
        assert d["wire.out.retransmit_bytes.header"] == (
            200 * d["wire.out.retransmit_frames.header"]
        )
        assert d["net.reliable.retransmissions"] >= 1
        # The instrumented receiver saw it exactly once.
        assert d["wire.in.bytes.header"] == 200
        sender.close()
        await recv.shutdown()

    with legacy_wire():
        run(go())


def test_netem_loss_reconciles_within_retransmit_accounting():
    """Under netem segment loss the per-type FIRST-transmission bytes
    still count each message exactly once (goodput's denominator drift
    is zero), every extra write is retransmit-counted, and the receiver
    total is bounded by sent-plus-retransmitted."""

    async def go():
        addr = "127.0.0.1:12340"
        n_msgs, size = 8, 150
        handler = RecordingAckHandler()
        recv = await Receiver.spawn(
            addr, handler, classify=frame_classifier(PRIMARY_FRAME_TYPES)
        )
        netem.install(
            netem.NetEmulator(
                {addr: netem.Shape(loss=0.5)}, None, [], seed=11
            )
        )
        sender = ReliableSender()
        try:
            with _Delta(
                "wire.out.bytes.certificate",
                "wire.out.frames.certificate",
                "wire.out.retransmit_bytes.certificate",
                "wire.in.bytes.certificate",
            ) as d:
                futs = [
                    sender.send(addr, bytes([2]) + b"c" * (size - 1),
                                "certificate")
                    for _ in range(n_msgs)
                ]
                # Every future resolves = every message ACKed at least
                # once despite the 50% loss (reconnect + retransmit).
                await asyncio.gather(*futs)
        finally:
            netem.reset()
            sender.close()
            await recv.shutdown()
        assert d["wire.out.frames.certificate"] == n_msgs
        assert d["wire.out.bytes.certificate"] == n_msgs * size
        # The seeded 50% loss over 8 frames forces at least one retry.
        assert d["wire.out.retransmit_bytes.certificate"] > 0
        # Receiver: every message at least once (all ACKed), never more
        # than everything written.
        assert d["wire.in.bytes.certificate"] >= n_msgs * size
        assert d["wire.in.bytes.certificate"] <= (
            d["wire.out.bytes.certificate"]
            + d["wire.out.retransmit_bytes.certificate"]
        )

    with legacy_wire():
        run(go())


def test_wire_crypto_summary_derived_metrics():
    """The bench-side join: per-type totals, sender coverage, recv/sent
    reconciliation, goodput ratio, cert signature fraction, empty-cert
    overhead, and the protocol-arithmetic cross-check."""
    snap = {
        "enabled": True,
        "counters": {
            # 10 batches of 1000 B broadcast, one retransmitted.
            "wire.out.frames.batch": 10,
            "wire.out.bytes.batch": 10_000,
            "wire.out.retransmit_frames.batch": 1,
            "wire.out.retransmit_bytes.batch": 1_000,
            "wire.in.frames.batch": 11,
            "wire.in.bytes.batch": 11_000,
            # Control plane: 4 headers, 12 votes, 4 certs of 600 B.
            "wire.out.frames.header": 4,
            "wire.out.bytes.header": 1_200,
            "wire.out.frames.vote": 12,
            "wire.out.bytes.vote": 2_400,
            "wire.out.frames.certificate": 4,
            "wire.out.bytes.certificate": 2_400,
            "net.reliable.bytes_sent": 16_900,
            "net.simple.bytes_sent": 100,
            "primary.own_headers_empty": 2,
            "primary.own_headers_payload": 2,
            "primary.votes_received": 16,
            "primary.late_votes": 1,
            "primary.certificates_processed": 16,
            "primary.certificates_formed": 4,
            "primary.verify_cache_hits": 3,
            "primary.verify_cache_misses": 12,
            "crypto.burst_claims.vote": 13,
            "crypto.burst_claims.certificate": 48,
            "crypto.verify.ops.batch_burst": 61,
            "crypto.sign.ops.header": 4,
        },
        "histograms": {
            "crypto.verify.seconds.batch_burst": {"sum": 0.5, "count": 10},
            "crypto.verify.batch_size.batch_burst": {
                "sum": 61, "count": 10,
            },
            "crypto.sign.seconds.header": {"sum": 0.01, "count": 4},
        },
    }
    out = wire_crypto_summary(
        [snap], committed_payload_bytes=5_000, quorum_weight=3
    )
    wire, crypto = out["wire"], out["crypto"]
    totals = wire["totals"]
    assert totals["out_bytes"] == 16_000
    assert totals["out_retransmit_bytes"] == 1_000
    assert totals["out_bytes_total"] == 17_000
    # Every sender byte carries a type label.
    assert totals["sender_coverage"] == 1.0
    assert wire["goodput_ratio"] == round(5_000 / 17_000, 4)
    # recv == sent+retransmit for batches: ratio 1.0.
    assert wire["recv_vs_sent"]["batch"] == 1.0
    # 3 votes × 96 B + 64 B header sig = 352 of a 600 B mean cert frame.
    assert wire["cert_sig_bytes_per_cert"] == 352
    assert wire["cert_sig_bytes_fraction"] == round(352 / 600, 4)
    # Half the headers were empty → half the control-plane bytes (6000)
    # are empty-round overhead, per committed byte.
    assert wire["empty_cert_overhead_per_committed_byte"] == round(
        0.5 * 6_000 / 5_000, 6
    )
    # Crypto side.
    assert crypto["verify"]["batch_burst"]["ops"] == 61
    assert crypto["verify"]["batch_burst"]["mean_batch"] == 6.1
    assert crypto["sign"]["header"] == {"ops": 4, "wall_s": 0.01}
    assert crypto["verify_cache"] == {"hits": 3, "misses": 12}
    # Protocol arithmetic: expected vote claims = received − own headers
    # + late = 16 − 4 + 1 = 13 (measured 13); certs = 48 claims over 12
    # wire certs = 4 per cert = quorum+1.
    assert crypto["protocol_check"]["votes"]["ratio"] == 1.0
    assert crypto["protocol_check"]["certificates"]["claims_per_cert"] == 4.0
    assert crypto["protocol_check"]["certificates"]["ratio"] == 1.0


def test_summary_tolerates_empty_and_disabled_snapshots():
    out = wire_crypto_summary(
        [{"enabled": False, "counters": {"wire.out.bytes.batch": 5}}, {}],
        committed_payload_bytes=0,
        quorum_weight=None,
    )
    assert out["wire"]["totals"]["out_bytes_total"] == 0
    assert "goodput_ratio" not in out["wire"]
    assert out["crypto"]["verify"] == {}
