"""Crypto-cost ledger tests: per-call-site sign/verify attribution
(header / vote / certificate / batch_burst), batch-size histograms, the
Core burst's per-kind claim counters against protocol arithmetic, and
the VERIFIED_CACHE hit/miss export (re-delivered certificates must be
crypto-free IN THE LEDGER, not just in principle)."""

import asyncio

from narwhal_tpu import metrics
from narwhal_tpu.crypto import SignatureService, backend as cb
from tests.common import (
    committee,
    keys,
    make_certificate,
    make_header,
    make_vote,
)
from tests.test_core import make_core


def run(coro, timeout=30):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def cnt(name: str) -> float:
    c = metrics.registry().counters.get(name)
    return c.value if c is not None else 0


def hist(name: str):
    return metrics.registry().histograms.get(name)


def test_sign_sites_via_signature_service():
    """Header.new / Vote.new label their signing ops "header" / "vote"
    through the SignatureService; direct KeyPair.sign stays "other"."""

    async def go():
        from narwhal_tpu.primary.messages import Header, Vote

        me, author = keys()[0], keys()[1]
        svc = SignatureService(me)
        h_before = cnt("crypto.sign.ops.header")
        v_before = cnt("crypto.sign.ops.vote")
        header = await Header.new(me.name, 1, {}, set(), svc)
        await Vote.new(header, me.name, svc)
        assert cnt("crypto.sign.ops.header") - h_before == 1
        assert cnt("crypto.sign.ops.vote") - v_before == 1
        o_before = cnt("crypto.sign.ops.other")
        author.sign(header.id)
        assert cnt("crypto.sign.ops.other") - o_before == 1
        # Wall time recorded per site.
        h = hist("crypto.sign.seconds.header")
        assert h is not None and h.count >= 1 and h.sum > 0
        svc.close()

    run(go())


def test_verify_sites_inline_serial_path():
    """The inline sanitization path attributes ops per message kind —
    and a certificate's verify splits into its embedded header's
    signature ("header") plus the 2f+1 vote batch ("certificate")."""
    c = committee()
    author = keys()[1]
    header = make_header(author, c=c)
    cert = make_certificate(header)
    vote = make_vote(header, keys()[2])

    before = {
        s: cnt(f"crypto.verify.ops.{s}")
        for s in ("header", "vote", "certificate")
    }
    cert_calls = (
        hist("crypto.verify.batch_size.certificate").count
        if hist("crypto.verify.batch_size.certificate")
        else 0
    )
    header.verify(c)
    vote.verify(c)
    cert.verify(c)
    # header.verify once directly + once inside cert.verify.
    assert cnt("crypto.verify.ops.header") - before["header"] == 2
    assert cnt("crypto.verify.ops.vote") - before["vote"] == 1
    # 2f+1 = 3 vote signatures batched over the certificate digest.
    assert cnt("crypto.verify.ops.certificate") - before["certificate"] == 3
    h = hist("crypto.verify.batch_size.certificate")
    assert h.count == cert_calls + 1
    # The one new observation was a 3-signature batch (bucket mean).
    assert h.sum >= 3


def test_core_burst_claims_match_protocol_arithmetic():
    """One certificate through the Core's burst path: quorum+1 claims
    (2f+1 votes + the embedded header's signature) counted under
    crypto.burst_claims.certificate and verified at site batch_burst."""

    async def go():
        c = committee()
        me, author = keys()[0], keys()[1]
        core, store, qs = make_core(c, me)
        cert = make_certificate(make_header(author, c=c))
        quorum = c.quorum_threshold()

        before_claims = cnt("crypto.burst_claims.certificate")
        before_ops = cnt("crypto.verify.ops.batch_burst")
        await core._handle_primaries_burst([("certificate", cert)])
        assert (
            cnt("crypto.burst_claims.certificate") - before_claims
            == quorum + 1
        )
        assert (
            cnt("crypto.verify.ops.batch_burst") - before_ops == quorum + 1
        )
        core.network.close()

    asyncio.run(asyncio.wait_for(go(), 30))


def test_verified_cache_hits_export_and_zero_new_verify_ops():
    """The PR 6 verified-digest cache, now observable: a re-delivered
    certificate produces a cache HIT and ZERO new verify ops in the
    crypto ledger (first delivery is a counted MISS that pays quorum+1
    ops)."""

    async def go():
        c = committee()
        me, author = keys()[0], keys()[1]
        core, store, qs = make_core(c, me)
        cert = make_certificate(make_header(author, c=c))

        hits0 = cnt("primary.verify_cache_hits")
        miss0 = cnt("primary.verify_cache_misses")
        ops0 = cnt("crypto.verify.ops.batch_burst")
        await core._handle_primaries_burst([("certificate", cert)])
        assert cnt("primary.verify_cache_misses") - miss0 == 1
        assert cnt("primary.verify_cache_hits") - hits0 == 0
        ops_after_first = cnt("crypto.verify.ops.batch_burst")
        assert ops_after_first - ops0 == c.quorum_threshold() + 1

        # Re-delivery: a hit, and the verify-op counter does not move.
        await core._handle_primaries_burst([("certificate", cert)])
        assert cnt("primary.verify_cache_hits") - hits0 == 1
        assert cnt("primary.verify_cache_misses") - miss0 == 1
        assert cnt("crypto.verify.ops.batch_burst") == ops_after_first
        core.network.close()

    run_coro(go())


def run_coro(coro):
    return asyncio.run(asyncio.wait_for(coro, 30))


def test_averify_site_default_does_not_pollute_burst_site():
    async def go():
        me = keys()[0]
        d = metrics_digest(b"m" * 32)
        sig = me.sign(d)
        before = cnt("crypto.verify.ops.batch_burst")
        other = cnt("crypto.verify.ops.other")
        ok = await cb.averify_batch_mask([bytes(d)], [me.name], [sig])
        assert ok == [True]
        assert cnt("crypto.verify.ops.batch_burst") == before
        assert cnt("crypto.verify.ops.other") - other == 1

    run_coro(go())


def metrics_digest(data: bytes):
    from narwhal_tpu.crypto import digest32

    return digest32(data)
