"""Unit tests for the live-health layer (narwhal_tpu/metrics.py
HealthMonitor): hysteresis (no flapping), rate-rule windows, the built-in
default rules, /healthz 200↔503 transitions, per-peer instruments from the
reliable sender, and the bench scraper against a canned MetricsServer."""

import asyncio
import json
import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from narwhal_tpu import metrics  # noqa: E402
from narwhal_tpu.metrics import (  # noqa: E402
    HealthMonitor,
    HealthRule,
    MetricsServer,
    Registry,
    default_rules,
)


def _ceiling_rule(limit=10, **kw):
    def check(ctx):
        v = ctx.gauge("t.val")
        if v is not None and v > limit:
            return {"": {"value": v, "threshold": limit}}
        return {}

    return HealthRule("ceiling", check, **kw)


# -- hysteresis ---------------------------------------------------------------

def test_hysteresis_fires_after_for_intervals_and_no_flapping():
    reg = Registry()
    g = reg.gauge("t.val")
    mon = HealthMonitor(
        reg,
        rules=[_ceiling_rule(for_intervals=2, clear_intervals=2)],
        interval_s=1.0,
    )
    t = 1000.0
    assert mon.evaluate(t) == []
    # One breaching sample must NOT fire (for_intervals=2).
    g.set(11)
    assert mon.evaluate(t + 1) == []
    # Second consecutive breach fires.
    firing = mon.evaluate(t + 2)
    assert [f["rule"] for f in firing] == ["ceiling"]
    assert firing[0]["since"] == t + 2
    assert firing[0]["detail"]["value"] == 11
    # One clean sample must NOT clear (clear_intervals=2) ...
    g.set(0)
    assert mon.evaluate(t + 3), "cleared after a single clean interval"
    # ... and a re-breach resets the clean streak without re-firing.
    g.set(11)
    assert mon.evaluate(t + 4)
    assert sum(1 for e in mon.events if e["event"] == "FIRING") == 1
    # Two consecutive clean samples clear.
    g.set(0)
    mon.evaluate(t + 5)
    assert mon.evaluate(t + 6) == []
    kinds = [e["event"] for e in mon.events]
    assert kinds == ["FIRING", "cleared"]  # exactly one cycle — no flap
    assert mon.ok()


def test_single_interval_spike_never_fires():
    reg = Registry()
    g = reg.gauge("t.val")
    mon = HealthMonitor(
        reg, rules=[_ceiling_rule(for_intervals=2)], interval_s=1.0
    )
    t = 0.0
    for i in range(6):
        g.set(11 if i % 2 == 0 else 0)  # alternating spike
        assert mon.evaluate(t + i) == []
    assert list(mon.events) == []


# -- rate windows -------------------------------------------------------------

def test_rate_rule_window_rises_and_slides_back_down():
    reg = Registry()
    c = reg.counter("t.events")

    def check(ctx):
        r = ctx.rate("t.events", 5.0)
        if r is not None and r > 10:
            return {"": {"rate": r}}
        return {}

    mon = HealthMonitor(
        reg,
        rules=[HealthRule("rate", check, series=("t.events",))],
        interval_s=1.0,
    )
    # History must SPAN the 5 s window before a rate exists at all — an
    # early burst must not be judged against a full-window threshold.
    assert mon.evaluate(0.0) == []  # single sample: no rate yet
    c.inc(100)
    for t in (1.0, 2.0, 3.0, 4.0):
        assert mon.evaluate(t) == [], f"fired before window spanned at {t}"
    # At t=5 the window is spanned: 100 events over 5 s = 20/s > 10.
    firing = mon.evaluate(5.0)
    assert [f["rule"] for f in firing] == ["rate"]
    # No further events: the burst slides out of the 5 s window and the
    # rule clears (after clear_intervals clean evaluations).
    cleared = None
    for i in range(6, 16):
        if mon.evaluate(float(i)) == []:
            cleared = i
            break
    assert cleared is not None and cleared <= 14


def test_last_change_age_drives_commit_stall_rule():
    reg = Registry()
    reg.gauge("primary.round").set(5)
    commits = reg.counter("consensus.committed_certificates")
    commits.inc(3)
    mon = HealthMonitor(
        reg,
        rules=default_rules({"NARWHAL_HEALTH_COMMIT_STALL_S": "10"}),
        interval_s=1.0,
    )
    t = 50.0
    assert mon.evaluate(t) == []
    # 11 s with zero commit progress past round 2 → stall fires.
    firing = mon.evaluate(t + 11)
    assert [f["rule"] for f in firing] == ["commit_stall"]
    # A commit resets the change age and the rule clears.
    commits.inc()
    mon.evaluate(t + 12)
    assert mon.evaluate(t + 13) == []


def test_commit_stall_guarded_before_round_2():
    reg = Registry()
    reg.gauge("primary.round").set(1)  # freshly booted committee
    reg.counter("consensus.committed_certificates")
    mon = HealthMonitor(reg, rules=default_rules(), interval_s=1.0)
    mon.evaluate(0.0)
    assert mon.evaluate(1000.0) == []  # idle forever, still healthy


def test_peer_unreachable_names_the_peer():
    reg = Registry()
    reg.gauge("net.reliable.peer.consecutive_failures.10.0.0.9:7001").set(3)
    mon = HealthMonitor(
        reg,
        rules=default_rules({"NARWHAL_HEALTH_PEER_FAILURES": "3"}),
        interval_s=1.0,
    )
    firing = mon.evaluate()
    assert [f["rule"] for f in firing] == ["peer_unreachable"]
    assert firing[0]["subject"] == "10.0.0.9:7001"
    # Recovery: failures reset to 0 on a successful connect.
    reg.gauge("net.reliable.peer.consecutive_failures.10.0.0.9:7001").set(0)
    mon.evaluate()
    assert mon.evaluate() == []


# -- /healthz -----------------------------------------------------------------

async def _fetch(port, target):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(f"GET {target} HTTP/1.1\r\nHost: x\r\n\r\n".encode())
    await writer.drain()
    data = await reader.read()
    writer.close()
    return data


def test_healthz_transitions_200_503_200():
    reg = Registry()
    g = reg.gauge("t.val")
    mon = HealthMonitor(
        reg,
        rules=[_ceiling_rule(for_intervals=1, clear_intervals=1)],
        interval_s=1.0,
    )
    reg.health = mon

    async def go():
        server = await MetricsServer.spawn(reg, 0, host="127.0.0.1")
        try:
            mon.evaluate(0.0)
            ok = await _fetch(server.port, "/healthz")
            assert b"200 OK" in ok
            assert json.loads(ok.split(b"\r\n\r\n", 1)[1])["status"] == "ok"

            g.set(99)
            mon.evaluate(1.0)
            bad = await _fetch(server.port, "/healthz")
            assert b"503" in bad
            body = json.loads(bad.split(b"\r\n\r\n", 1)[1])
            assert body["status"] == "failing"
            assert [f["rule"] for f in body["firing"]] == ["ceiling"]

            g.set(0)
            mon.evaluate(2.0)
            again = await _fetch(server.port, "/healthz")
            assert b"200 OK" in again
            # The health section also rides in the registry snapshot.
            assert reg.snapshot()["health"]["status"] == "ok"
        finally:
            await server.shutdown()

    asyncio.run(asyncio.wait_for(go(), 15))


def test_healthz_unmonitored_is_200():
    reg = Registry()

    async def go():
        server = await MetricsServer.spawn(reg, 0, host="127.0.0.1")
        try:
            resp = await _fetch(server.port, "/healthz")
            assert b"200 OK" in resp
            body = json.loads(resp.split(b"\r\n\r\n", 1)[1])
            assert body["status"] == "unmonitored"
        finally:
            await server.shutdown()

    asyncio.run(asyncio.wait_for(go(), 15))


# -- per-peer reliable-sender instruments -------------------------------------

def test_reliable_sender_per_peer_rtt_and_failure_gauges():
    """A real send/ACK exchange must land per-peer observations under
    names carrying the peer address; a peer that DIES must accumulate the
    consecutive-failure gauge the peer_unreachable rule reads.  The dead
    peer is connected once first: since the boot-stagger fix (a fuzzed
    control arm fired peer_unreachable during a slow boot), failures only
    reach the gauge for peers that have been seen alive."""
    from narwhal_tpu.network import Receiver, ReliableSender
    from tests.test_network import EchoAckHandler

    reg = metrics.registry()
    reg.reset()

    async def go():
        recv = await Receiver.spawn("127.0.0.1:0", EchoAckHandler())
        addr = f"127.0.0.1:{recv.port}"
        sender = ReliableSender()
        ack = await asyncio.wait_for(sender.send(addr, b"ping"), 5)
        assert ack == b"Ack"

        # A once-alive peer dies: connect failures accrue with backoff.
        dying = await Receiver.spawn("127.0.0.1:0", EchoAckHandler())
        dead = f"127.0.0.1:{dying.port}"
        await asyncio.wait_for(sender.send(dead, b"ping"), 5)
        await dying.shutdown()
        sender.send(dead, b"void")
        for _ in range(200):
            g = reg.gauges.get(
                f"net.reliable.peer.consecutive_failures.{dead}"
            )
            if g is not None and g.value >= 2:
                break
            await asyncio.sleep(0.05)
        sender.close()
        await recv.shutdown()
        return addr, dead

    addr, dead = asyncio.run(asyncio.wait_for(go(), 15))
    snap = metrics.registry().snapshot()
    rtt = snap["histograms"][f"net.reliable.peer.rtt_seconds.{addr}"]
    assert rtt["count"] == 1 and rtt["sum"] > 0
    assert (
        snap["gauges"][f"net.reliable.peer.consecutive_failures.{dead}"] >= 2
    )
    assert snap["gauges"][f"net.reliable.peer.backing_off.{dead}"] == 1
    # The live peer's failure gauge ended at zero (successful connect).
    assert (
        snap["gauges"][f"net.reliable.peer.consecutive_failures.{addr}"] == 0
    )
    # Prometheus rendering mangles the address into a legal metric name.
    prom = metrics.registry().render_prometheus()
    assert f"net_reliable_peer_rtt_seconds_{addr}".replace(
        ".", "_"
    ).replace(":", "_") in prom


# -- scraper ------------------------------------------------------------------

class _ServerThread:
    """Host a MetricsServer on its own asyncio loop in a daemon thread so
    the synchronous Scraper can poll it like a real node."""

    def __init__(self, reg):
        self.reg = reg
        self.port = None
        self._started = threading.Event()
        self._stop = None
        self._loop = None
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        assert self._started.wait(10), "metrics server thread never started"

    def _run(self):
        asyncio.run(self._main())

    async def _main(self):
        self._loop = asyncio.get_running_loop()
        self._stop = asyncio.Event()
        server = await MetricsServer.spawn(self.reg, 0, host="127.0.0.1")
        self.port = server.port
        self._started.set()
        await self._stop.wait()
        await server.shutdown()

    def stop(self):
        self._loop.call_soon_threadsafe(self._stop.set)
        self._thread.join(timeout=10)


def test_scraper_against_canned_server():
    from benchmark.metrics_check import build_timeline
    from benchmark.scraper import Scraper

    reg = Registry()
    commits = reg.counter("consensus.committed_certificates")
    reg.gauge("primary.round").set(4)
    reg.trace.mark("d1", "seal")  # trace must NOT ride along (?trace=0)
    mon = HealthMonitor(reg, rules=default_rules(), interval_s=1.0)
    reg.health = mon
    mon.evaluate()

    srv = _ServerThread(reg)
    try:
        scraper = Scraper(
            [
                ("node-0", "127.0.0.1", srv.port),
                ("node-gone", "127.0.0.1", 1),  # unreachable: skipped
            ],
            interval_s=0.05,
        )
        assert scraper.sample_once() == 1
        commits.inc(10)
        assert scraper.sample_once() == 1
        assert scraper.commits_observed() == 10

        healthz = scraper.healthz_all()
        assert healthz["node-0"][0] == 200
        assert healthz["node-gone"][0] is None

        timeline = build_timeline(
            scraper.samples, interval_s=0.05, healthz=healthz
        )
        series = timeline["nodes"]["node-0"]
        assert len(series) == 2
        assert series[0]["commits"] == 0 and series[1]["commits"] == 10
        assert series[1]["commit_rate_per_s"] > 0
        assert series[1]["round"] == 4
        assert series[1]["health_firing"] == 0
        assert timeline["healthz"]["node-0"]["status"] == 200
        assert timeline["healthz"]["node-gone"]["status"] is None
        # ?trace=0 kept the heavyweight table out of every sample.
        assert all("trace" not in s for s in scraper.samples)
    finally:
        srv.stop()


def test_scraper_start_stop_collects_over_time():
    from benchmark.scraper import Scraper

    reg = Registry()
    c = reg.counter("consensus.committed_certificates")
    srv = _ServerThread(reg)
    try:
        scraper = Scraper(
            [("n0", "127.0.0.1", srv.port)], interval_s=0.05
        ).start()
        import time as _time

        deadline = _time.time() + 5
        while len(scraper.samples) < 3 and _time.time() < deadline:
            c.inc()
            _time.sleep(0.02)
        scraper.stop()
        assert len(scraper.samples) >= 3
        assert all(s["node"] == "n0" for s in scraper.samples)
    finally:
        srv.stop()


# -- quorum-waiter wedge rule (worker-side; PR 4) -----------------------------

def test_quorum_wedge_rule_fires_and_clears_deterministically():
    """evaluate()-injection drive of the quorum_wedge rule: a wait-age
    gauge past NARWHAL_HEALTH_QUORUM_WEDGE_S fires after for_intervals=2
    breaches, names the acked stake vs threshold in the detail, and
    clears once the waiter releases."""
    reg = Registry()
    age = {"v": 0.0}
    reg.gauge_fn("worker.quorum_wait_age_seconds", lambda: age["v"])
    reg.gauge("worker.quorum_acked_stake").set(2)  # wedged at 2f
    reg.gauge("worker.quorum_threshold").set(3)
    mon = HealthMonitor(
        reg,
        rules=default_rules({"NARWHAL_HEALTH_QUORUM_WEDGE_S": "5"}),
        interval_s=1.0,
    )
    t = 2000.0
    assert mon.evaluate(t) == []
    age["v"] = 6.0
    assert mon.evaluate(t + 1) == []  # first breach: hysteresis holds
    age["v"] = 7.0
    firing = mon.evaluate(t + 2)
    assert [f["rule"] for f in firing] == ["quorum_wedge"]
    detail = firing[0]["detail"]
    assert detail["acked_stake"] == 2
    assert detail["quorum_threshold"] == 3
    assert detail["seconds_waiting"] == 7.0
    # Waiter releases (age back to 0): clears after clear_intervals=2.
    age["v"] = 0.0
    reg.gauges["worker.quorum_acked_stake"].set(0)
    mon.evaluate(t + 3)
    assert mon.evaluate(t + 4) == []
    assert [e["event"] for e in mon.events] == ["FIRING", "cleared"]


def test_quorum_waiter_exports_wedge_gauges():
    """A live QuorumWaiter stuck one ACK short of quorum exports a
    growing wait-age gauge and the acked stake so far; releasing the
    last ACK zeroes both."""
    import time as _time

    from narwhal_tpu.worker.quorum_waiter import QuorumWaiter
    from tests.common import committee, keys

    reg = metrics.registry()
    reg.reset()

    async def go():
        c = committee()
        kp = keys()[0]
        loop = asyncio.get_running_loop()
        in_q, out_q = asyncio.Queue(), asyncio.Queue()
        waiter = QuorumWaiter(kp.name, c, in_q, out_q)
        task = loop.create_task(waiter.run())
        # 3 peer ACK futures (stake 1 each); quorum threshold is 3, our
        # own stake counts 1 — resolve one, leave the waiter at 2 < 3.
        futs = [loop.create_future() for _ in range(3)]
        digest = b"\x01" * 32
        await in_q.put((digest, b"batch", [(1, f) for f in futs]))
        futs[0].set_result(None)
        deadline = _time.time() + 5
        while (
            reg.gauges["worker.quorum_acked_stake"].value < 2
            and _time.time() < deadline
        ):
            await asyncio.sleep(0.01)
        assert reg.gauges["worker.quorum_acked_stake"].value == 2
        assert reg.gauges["worker.quorum_threshold"].value == 3
        await asyncio.sleep(0.05)
        assert reg.gauge_fns["worker.quorum_wait_age_seconds"]() > 0.0
        # Third ACK releases the batch: gauges reset, batch forwarded.
        futs[1].set_result(None)
        got = await asyncio.wait_for(out_q.get(), 5)
        assert got[0] == digest
        assert reg.gauges["worker.quorum_acked_stake"].value == 0
        assert reg.gauge_fns["worker.quorum_wait_age_seconds"]() == 0.0
        task.cancel()

    asyncio.run(asyncio.wait_for(go(), 15))


# -- anomaly events as a first-class timeline track (PR 4) --------------------

def test_build_timeline_renders_anomaly_event_track():
    """HealthMonitor FIRING/cleared transitions ride the scraped samples'
    cumulative events ring; build_timeline must dedupe them into one
    committee-wide, time-sorted `events` track naming rule + subject +
    fire/clear timestamps, merged with the quiesce /healthz bodies."""
    from benchmark.metrics_check import build_timeline

    reg = Registry()
    g = reg.gauge("t.val")
    mon = HealthMonitor(
        reg, rules=[_ceiling_rule(for_intervals=1)], interval_s=1.0
    )
    reg.health = mon

    def sample(t):
        return {
            "t": t,
            "node": "primary-0",
            "counters": {},
            "gauges": {},
            "histograms": {},
            "health": mon.health_snapshot(),
        }

    t = 3000.0
    mon.evaluate(t)
    samples = [sample(t)]
    g.set(99)
    mon.evaluate(t + 1)  # FIRING at t+1
    samples.append(sample(t + 1))
    g.set(0)
    mon.evaluate(t + 2)
    mon.evaluate(t + 3)  # cleared at t+3
    samples.append(sample(t + 3))
    # The ring is cumulative: the same FIRING event appears in samples 2
    # and 3 — the track must carry it once.
    healthz = {"primary-0": (200, mon.health_snapshot())}

    timeline = build_timeline(samples, interval_s=1.0, healthz=healthz)
    events = timeline["events"]
    assert [(e["event"], e["t"]) for e in events] == [
        ("FIRING", t + 1),
        ("cleared", t + 3),
    ]
    assert all(e["rule"] == "ceiling" for e in events)
    assert all(e["node"] == "primary-0" for e in events)
    assert events[0]["detail"]["value"] == 99
    # Per-sample firing counts still ride along next to the track.
    series = timeline["nodes"]["primary-0"]
    assert [p["health_firing"] for p in series] == [0, 1, 0]


def test_build_timeline_events_from_quiesce_healthz_only():
    """A transition after the last scrape tick still lands in the track
    via the /healthz body (the quiesce probe)."""
    from benchmark.metrics_check import build_timeline

    reg = Registry()
    reg.gauge("t.val").set(50)
    mon = HealthMonitor(
        reg, rules=[_ceiling_rule(for_intervals=1)], interval_s=1.0
    )
    reg.health = mon
    mon.evaluate(4000.0)
    timeline = build_timeline(
        [], interval_s=1.0, healthz={"w-0": (503, mon.health_snapshot())}
    )
    assert [(e["node"], e["event"]) for e in timeline["events"]] == [
        ("w-0", "FIRING")
    ]
    assert timeline["healthz"]["w-0"]["firing"] == ["ceiling"]


# -- stale_replay default vs post-heal catch-up (ISSUE 7 satellite) -----------


def test_stale_replay_default_rides_out_heal_burst_but_fires_on_flood():
    """The DEFAULT stale-rate threshold must sit ABOVE a healed node's
    catch-up burst: the wan_partition_heal scenario's healed node
    replays its backlog at a measured 2.4-2.9 stale messages/s, and the
    old 2/s default FIRED transiently on exactly that (ROADMAP item 4's
    named follow-up).  The replay-flood attack the rule exists for
    (byz_replay_stale: 10/s per peer) must still fire — with NO env
    overrides, since this test pins the shipped default."""
    reg = Registry()
    stale = reg.counter("primary.stale_messages")
    mon = HealthMonitor(reg, rules=default_rules({}), interval_s=1.0)
    t = 5000.0
    # Post-heal catch-up: 2.9 stale/s sustained for 15 s — the worst
    # burst observed on the healed node — must never fire.
    acc = 0.0
    for i in range(15):
        acc += 2.9
        while stale.value < int(acc):
            stale.inc()
        firing = mon.evaluate(t + i)
        assert "stale_replay" not in {f["rule"] for f in firing}, (
            f"heal-burst rate fired at tick {i}: {firing}"
        )
    # An actual replay flood (10/s, the byz_replay_stale magnitude per
    # peer) fires within a few intervals.
    fired = False
    for i in range(15, 25):
        stale.inc(10)
        firing = mon.evaluate(t + i)
        fired = fired or "stale_replay" in {f["rule"] for f in firing}
    assert fired


# -- queue backpressure rules (ISSUE 17) --------------------------------------

def test_queue_saturated_names_channel_with_hysteresis():
    """A wide channel sitting at >= 90% of capacity for three intervals
    fires queue_saturated with the channel as subject; a single deep
    sample (one interval) must not — transient bursts are what bounded
    queues are FOR."""
    reg = Registry()
    depth = reg.gauge("queue.primary.others_digests.depth")
    reg.gauge("queue.primary.others_digests.capacity").set(1000)
    reg.gauge("queue.primary.others_digests.high_water").set(960)
    mon = HealthMonitor(reg, rules=default_rules({}), interval_s=1.0)
    t = 100.0
    depth.set(500)
    assert mon.evaluate(t) == []
    # Deep for one interval, then drained: no firing (for_intervals=3).
    depth.set(950)
    assert mon.evaluate(t + 1) == []
    depth.set(10)
    assert mon.evaluate(t + 2) == []
    # Deep for three consecutive intervals: fires, naming the channel.
    depth.set(950)
    assert mon.evaluate(t + 3) == []
    assert mon.evaluate(t + 4) == []
    firing = mon.evaluate(t + 5)
    assert [f["rule"] for f in firing] == ["queue_saturated"]
    assert firing[0]["subject"] == "primary.others_digests"
    assert firing[0]["detail"]["fill_ratio"] == 0.95
    assert firing[0]["detail"]["high_water"] == 960.0
    # Draining clears it (clear_intervals default).
    depth.set(10)
    mon.evaluate(t + 6)
    assert mon.evaluate(t + 7) == []


def test_queue_saturated_skips_narrow_pipeline_windows():
    """Channels below the min-capacity floor — worker.to_quorum's
    QUORUM_WINDOW=8, the sim's tiny handoffs — run full BY DESIGN under
    steady load and must never alert; lowering the floor via env brings
    them back in scope."""
    reg = Registry()
    depth = reg.gauge("queue.worker.to_quorum.depth")
    reg.gauge("queue.worker.to_quorum.capacity").set(8)
    depth.set(8)  # pegged, by design
    mon = HealthMonitor(reg, rules=default_rules({}), interval_s=1.0)
    for i in range(6):
        assert mon.evaluate(200.0 + i) == [], "narrow window alerted"
    # Floor lowered: the same gauges now fire after the hysteresis run.
    mon2 = HealthMonitor(
        reg,
        rules=default_rules({"NARWHAL_HEALTH_QUEUE_SAT_MIN_CAP": "8"}),
        interval_s=1.0,
    )
    fired = []
    for i in range(6):
        fired = mon2.evaluate(300.0 + i) or fired
    assert [f["rule"] for f in fired] == ["queue_saturated"]
    assert fired[0]["subject"] == "worker.to_quorum"


def test_ingress_drops_fires_on_sustained_rate_not_burst():
    """ingress_drops judges the overflow RATE over its window: a
    sustained client-ingress overflow (offered load past the admission
    plane) fires; zero overflow never does; and draining the overflow
    stream clears the rule."""
    reg = Registry()
    c = reg.counter("worker.ingress_overflow")
    mon = HealthMonitor(
        reg,
        rules=default_rules({"NARWHAL_HEALTH_INGRESS_DROP_WINDOW_S": "5"}),
        interval_s=1.0,
    )
    t = 400.0
    assert mon.evaluate(t) == []
    # Sustained 10 overflows/s: the rule fires once the window is
    # spanned plus its for_intervals=2 hysteresis.
    fired_at = None
    for i in range(1, 12):
        c.inc(10)
        firing = mon.evaluate(t + i)
        if "ingress_drops" in {f["rule"] for f in firing}:
            fired_at = i
            detail = [f for f in firing if f["rule"] == "ingress_drops"][0]
            assert detail["detail"]["overflows_per_s"] > 1.0
            break
    assert fired_at is not None, "sustained overflow never fired"
    assert fired_at >= 5, "fired before the rate window was spanned"
    # Overflow stops: the burst slides out of the window and it clears.
    cleared = None
    for i in range(fired_at + 1, fired_at + 15):
        if mon.evaluate(t + i) == []:
            cleared = i
            break
    assert cleared is not None, "never cleared after overflow stopped"
