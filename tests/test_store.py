"""Analog of reference store/src/tests/store_tests.rs: create/read/write/
unknown-key and the notify_read blocked-until-write contract, plus crash
recovery via log replay."""

import asyncio
import os

from narwhal_tpu.store import Store


def test_create_read_write():
    s = Store()
    s.write(b"key", b"value")
    assert s.read(b"key") == b"value"
    assert s.read(b"missing") is None


def test_notify_read_existing():
    async def go():
        s = Store()
        s.write(b"k", b"v")
        assert await s.notify_read(b"k") == b"v"

    asyncio.run(go())


def test_notify_read_blocks_until_write():
    async def go():
        s = Store()
        task = asyncio.ensure_future(s.notify_read(b"k"))
        await asyncio.sleep(0.02)
        assert not task.done()
        s.write(b"k", b"v")
        assert await asyncio.wait_for(task, 1) == b"v"

    asyncio.run(go())


def test_notify_read_multiple_waiters():
    async def go():
        s = Store()
        tasks = [asyncio.ensure_future(s.notify_read(b"k")) for _ in range(5)]
        await asyncio.sleep(0)
        s.write(b"k", b"v")
        assert await asyncio.gather(*tasks) == [b"v"] * 5

    asyncio.run(go())


def test_persistence_replay(tmp_path):
    path = os.path.join(tmp_path, "db", "store.log")
    s = Store(path)
    s.write(b"a", b"1")
    s.write(b"b", b"22")
    s.write(b"a", b"333")  # overwrite: last write wins on replay
    s.close()
    s2 = Store(path)
    assert s2.read(b"a") == b"333"
    assert s2.read(b"b") == b"22"
    s2.close()


def test_torn_tail_discarded(tmp_path):
    path = os.path.join(tmp_path, "store.log")
    s = Store(path)
    s.write(b"a", b"1")
    s.close()
    with open(path, "ab") as f:
        f.write(b"\xff\xff")  # simulate a crash mid-record
    s2 = Store(path)
    assert s2.read(b"a") == b"1"
    s2.close()


def test_failed_append_keeps_memory_and_log_consistent(tmp_path, monkeypatch):
    """A failed log append must leave memory WITHOUT the record too (fail
    together), roll the file back to the record boundary, and keep the
    store usable — regression for the round-3 advisor finding."""
    path = os.path.join(tmp_path, "store.log")
    s = Store(path)
    s.write(b"a", b"1")

    import pytest

    def boom(fd, bufs):
        raise OSError("injected disk error")

    monkeypatch.setattr(os, "writev", boom)
    with pytest.raises(OSError):
        s.write(b"b", b"2")
    monkeypatch.undo()

    assert s.read(b"b") is None  # memory did not diverge from the log
    s.write(b"c", b"3")  # boundary intact: later appends still replayable
    s.close()
    s2 = Store(path)
    assert s2.read(b"a") == b"1"
    assert s2.read(b"b") is None
    assert s2.read(b"c") == b"3"
    s2.close()


def test_write_deferred_visible_immediately_logged_on_flush(tmp_path):
    """write_deferred (the Core's coalesced persist-before-vote path):
    memory and notify_read waiters see the record IMMEDIATELY, but the
    log record only hits the file at flush_deferred — one writev for the
    whole batch."""
    path = os.path.join(tmp_path, "store.log")
    s = Store(path)
    s.write_deferred(b"h1", b"v1")
    s.write_deferred(b"h2", b"v2")
    # In-process invariants identical to write():
    assert s.read(b"h1") == b"v1" and s.read(b"h2") == b"v2"
    # ...but nothing on disk yet.
    assert os.path.getsize(path) == 0

    writev_calls = []
    real_writev = os.writev

    def counting(fd, bufs):
        writev_calls.append(len(bufs))
        return real_writev(fd, bufs)

    os.writev = counting
    try:
        s.flush_deferred()
    finally:
        os.writev = real_writev
    assert writev_calls == [6]  # 2 records x (len header, key, value)
    assert os.path.getsize(path) > 0
    s.flush_deferred()  # idempotent no-op when drained

    s.close()
    s2 = Store(path)
    assert s2.read(b"h1") == b"v1" and s2.read(b"h2") == b"v2"
    s2.close()


def test_write_deferred_wakes_parked_notify_read(tmp_path):
    async def go():
        s = Store(os.path.join(tmp_path, "store.log"))
        task = asyncio.ensure_future(s.notify_read(b"k"))
        await asyncio.sleep(0.02)
        assert not task.done()
        s.write_deferred(b"k", b"v")  # wakes BEFORE the log flush
        assert await asyncio.wait_for(task, 1) == b"v"
        s.close()

    asyncio.run(go())


def test_close_flushes_deferred_records(tmp_path):
    """A node tearing down mid-burst must not lose buffered records."""
    path = os.path.join(tmp_path, "store.log")
    s = Store(path)
    s.write(b"a", b"1")
    s.write_deferred(b"b", b"2")
    s.close()
    s2 = Store(path)
    assert s2.read(b"a") == b"1" and s2.read(b"b") == b"2"
    s2.close()


def test_interleaved_write_and_deferred_replay(tmp_path):
    """Immediate write() between deferred records: replay must see every
    record regardless of the log's physical order."""
    path = os.path.join(tmp_path, "store.log")
    s = Store(path)
    s.write_deferred(b"h1", b"v1")
    s.write(b"c1", b"x")  # cert path: immediate
    s.write_deferred(b"h2", b"v2")
    s.flush_deferred()
    s.close()
    s2 = Store(path)
    assert [s2.read(k) for k in (b"h1", b"c1", b"h2")] == [b"v1", b"x", b"v2"]
    s2.close()


def test_multi_chunk_flush_retries_short_writes_per_chunk(tmp_path):
    """A deferred flush spanning multiple IOV_MAX chunks whose writev
    returns short must retry the SHORT CHUNK before appending the next
    one — a tail-retry against the flattened whole would leave a silent
    mid-log tear that replay discovers only by truncating everything
    after it."""
    path = os.path.join(tmp_path, "store.log")
    s = Store(path)
    n = 400  # 1200 buffers: spans two IOV_MAX(1024) chunks
    for i in range(n):
        s.write_deferred(b"k%d" % i, b"v%d" % i)

    real_writev = os.writev

    def short_writev(fd, bufs):
        # Accept only the first buffer: every chunk comes up short.
        return real_writev(fd, bufs[:1])

    os.writev = short_writev
    try:
        s.flush_deferred()
    finally:
        os.writev = real_writev
    s.close()
    s2 = Store(path)
    for i in range(n):
        assert s2.read(b"k%d" % i) == b"v%d" % i, i
    s2.close()


def test_flush_failure_keeps_records_pending_for_retry(tmp_path, monkeypatch):
    """A transient append failure during flush_deferred must NOT drop the
    buffered records: the file is rolled back to the record boundary and
    the records stay pending, so a later flush (or close) lands them —
    memory never silently diverges from the log."""
    import pytest

    path = os.path.join(tmp_path, "store.log")
    s = Store(path)
    s.write_deferred(b"h1", b"v1")

    def boom(fd, bufs):
        raise OSError("injected disk error")

    monkeypatch.setattr(os, "writev", boom)
    with pytest.raises(OSError):
        s.flush_deferred()
    monkeypatch.undo()

    assert s.read(b"h1") == b"v1"  # memory unchanged
    s.flush_deferred()  # transient condition cleared: retry lands it
    s.close()
    s2 = Store(path)
    assert s2.read(b"h1") == b"v1"
    s2.close()


def test_immediate_write_drains_deferred_first(tmp_path):
    """An immediate write() while records are buffered must flush them
    ahead of itself: the log order must never invert the callers' persist
    order (a certificate logged before the header it certifies)."""
    path = os.path.join(tmp_path, "store.log")
    s = Store(path)
    s.write_deferred(b"header", b"H")
    s.write(b"cert", b"C")  # must land AFTER the buffered header record
    # Crash before any explicit flush: simulate by replaying the file as
    # it stands (write() drained the buffer, so both records are there,
    # header first).
    with open(path, "rb") as f:
        data = f.read()
    assert data.index(b"header") < data.index(b"cert")
    s.close()
    replayed = Store(path)
    assert replayed.read(b"header") == b"H"
    assert replayed.read(b"cert") == b"C"
    replayed.close()
