"""Analog of reference store/src/tests/store_tests.rs: create/read/write/
unknown-key and the notify_read blocked-until-write contract, plus crash
recovery via log replay."""

import asyncio
import os

from narwhal_tpu.store import Store


def test_create_read_write():
    s = Store()
    s.write(b"key", b"value")
    assert s.read(b"key") == b"value"
    assert s.read(b"missing") is None


def test_notify_read_existing():
    async def go():
        s = Store()
        s.write(b"k", b"v")
        assert await s.notify_read(b"k") == b"v"

    asyncio.run(go())


def test_notify_read_blocks_until_write():
    async def go():
        s = Store()
        task = asyncio.ensure_future(s.notify_read(b"k"))
        await asyncio.sleep(0.02)
        assert not task.done()
        s.write(b"k", b"v")
        assert await asyncio.wait_for(task, 1) == b"v"

    asyncio.run(go())


def test_notify_read_multiple_waiters():
    async def go():
        s = Store()
        tasks = [asyncio.ensure_future(s.notify_read(b"k")) for _ in range(5)]
        await asyncio.sleep(0)
        s.write(b"k", b"v")
        assert await asyncio.gather(*tasks) == [b"v"] * 5

    asyncio.run(go())


def test_persistence_replay(tmp_path):
    path = os.path.join(tmp_path, "db", "store.log")
    s = Store(path)
    s.write(b"a", b"1")
    s.write(b"b", b"22")
    s.write(b"a", b"333")  # overwrite: last write wins on replay
    s.close()
    s2 = Store(path)
    assert s2.read(b"a") == b"333"
    assert s2.read(b"b") == b"22"
    s2.close()


def test_torn_tail_discarded(tmp_path):
    path = os.path.join(tmp_path, "store.log")
    s = Store(path)
    s.write(b"a", b"1")
    s.close()
    with open(path, "ab") as f:
        f.write(b"\xff\xff")  # simulate a crash mid-record
    s2 = Store(path)
    assert s2.read(b"a") == b"1"
    s2.close()


def test_failed_append_keeps_memory_and_log_consistent(tmp_path, monkeypatch):
    """A failed log append must leave memory WITHOUT the record too (fail
    together), roll the file back to the record boundary, and keep the
    store usable — regression for the round-3 advisor finding."""
    path = os.path.join(tmp_path, "store.log")
    s = Store(path)
    s.write(b"a", b"1")

    import pytest

    def boom(fd, bufs):
        raise OSError("injected disk error")

    monkeypatch.setattr(os, "writev", boom)
    with pytest.raises(OSError):
        s.write(b"b", b"2")
    monkeypatch.undo()

    assert s.read(b"b") is None  # memory did not diverge from the log
    s.write(b"c", b"3")  # boundary intact: later appends still replayable
    s.close()
    s2 = Store(path)
    assert s2.read(b"a") == b"1"
    assert s2.read(b"b") is None
    assert s2.read(b"c") == b"3"
    s2.close()
