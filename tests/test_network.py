"""Analog of reference network/src/tests: receiver dispatch, simple send,
broadcast, reliable send with ACK futures and retry across a peer restart.
Multi-node behavior is tested in one process over loopback TCP, as in the
reference (SURVEY.md §4)."""

import asyncio

import pytest

from narwhal_tpu.network import Receiver, ReliableSender, SimpleSender


class EchoAckHandler:
    """ACKs every frame with b"Ack" and records messages."""

    def __init__(self):
        self.received = []

    async def dispatch(self, writer, message):
        self.received.append(message)
        await writer.send(b"Ack")


class SilentHandler:
    def __init__(self):
        self.received = []

    async def dispatch(self, writer, message):
        self.received.append(message)


@pytest.fixture
def run():
    def _run(coro):
        return asyncio.run(asyncio.wait_for(coro, 15))

    return _run


def test_receive_and_reply(run):
    async def go():
        handler = EchoAckHandler()
        recv = await Receiver.spawn("127.0.0.1:0", handler)
        addr = f"127.0.0.1:{recv.port}"
        reader, writer = await asyncio.open_connection("127.0.0.1", recv.port)
        from narwhal_tpu.network.framing import write_frame, read_frame

        await write_frame(writer, b"hello")
        assert await read_frame(reader) == b"Ack"
        assert handler.received == [b"hello"]
        writer.close()
        await recv.shutdown()
        return addr

    run(go())


def test_simple_send(run):
    async def go():
        handler = EchoAckHandler()
        recv = await Receiver.spawn("127.0.0.1:0", handler)
        sender = SimpleSender()
        sender.send(f"127.0.0.1:{recv.port}", b"msg")
        for _ in range(100):
            if handler.received:
                break
            await asyncio.sleep(0.01)
        assert handler.received == [b"msg"]
        sender.close()
        await recv.shutdown()

    run(go())


def test_simple_broadcast(run):
    async def go():
        handlers = [SilentHandler() for _ in range(3)]
        recvs = [await Receiver.spawn("127.0.0.1:0", h) for h in handlers]
        sender = SimpleSender()
        sender.broadcast([f"127.0.0.1:{r.port}" for r in recvs], b"all")
        for _ in range(100):
            if all(h.received for h in handlers):
                break
            await asyncio.sleep(0.01)
        assert [h.received for h in handlers] == [[b"all"]] * 3
        sender.close()
        for r in recvs:
            await r.shutdown()

    run(go())


def test_reliable_send_resolves_on_ack(run):
    async def go():
        handler = EchoAckHandler()
        recv = await Receiver.spawn("127.0.0.1:0", handler)
        sender = ReliableSender()
        fut = sender.send(f"127.0.0.1:{recv.port}", b"important")
        assert await fut == b"Ack"
        assert handler.received == [b"important"]
        sender.close()
        await recv.shutdown()

    run(go())


def test_reliable_broadcast_quorum(run):
    async def go():
        handlers = [EchoAckHandler() for _ in range(4)]
        recvs = [await Receiver.spawn("127.0.0.1:0", h) for h in handlers]
        sender = ReliableSender()
        futs = sender.broadcast([f"127.0.0.1:{r.port}" for r in recvs], b"b")
        done, _ = await asyncio.wait(futs, return_when=asyncio.ALL_COMPLETED)
        assert all(f.result() == b"Ack" for f in done)
        sender.close()
        for r in recvs:
            await r.shutdown()

    run(go())


def test_reliable_send_retries_across_restart(run):
    """Send to a dead peer; boot the peer afterwards; delivery happens."""

    async def go():
        # Reserve a port by binding then shutting down.
        probe = await Receiver.spawn("127.0.0.1:0", SilentHandler())
        port = probe.port
        await probe.shutdown()

        sender = ReliableSender()
        fut = sender.send(f"127.0.0.1:{port}", b"late")
        await asyncio.sleep(0.3)  # a few failed connect attempts
        assert not fut.done()
        handler = EchoAckHandler()
        recv = await Receiver.spawn(f"127.0.0.1:{port}", handler)
        assert await asyncio.wait_for(fut, 10) == b"Ack"
        assert handler.received == [b"late"]
        sender.close()
        await recv.shutdown()

    run(go())


def test_reliable_cancel_abandons_delivery(run):
    async def go():
        probe = await Receiver.spawn("127.0.0.1:0", SilentHandler())
        port = probe.port
        await probe.shutdown()
        sender = ReliableSender()
        fut = sender.send(f"127.0.0.1:{port}", b"gone")
        fut.cancel()
        await asyncio.sleep(0.3)
        handler = EchoAckHandler()
        recv = await Receiver.spawn(f"127.0.0.1:{port}", handler)
        await asyncio.sleep(0.5)
        assert handler.received == []  # cancelled message never delivered
        sender.close()
        await recv.shutdown()

    run(go())


def test_oversized_message_fails_fast(run):
    async def go():
        sender = ReliableSender()
        fut = sender.send("127.0.0.1:1", b"x" * (33 * 1024 * 1024))
        try:
            await fut
            assert False
        except ValueError:
            pass
        sender.close()

    run(go())


def test_close_cancels_outstanding(run):
    async def go():
        sender = ReliableSender()
        fut = sender.send("127.0.0.1:1", b"never")  # unreachable peer
        await asyncio.sleep(0.05)
        sender.close()
        await asyncio.sleep(0)
        assert fut.cancelled()

    run(go())


def test_boot_stagger_failures_hidden_until_first_connect(run):
    """Regression (fuzzed-scenario catch: a CLEAN control arm fired
    peer_unreachable at boot): connect failures against a peer that has
    never accepted a connection stay OFF the health gauge — a committee
    boots staggered, and a not-yet-bound socket is not a dead validator.
    Once the peer has been seen alive, failures count immediately."""
    from narwhal_tpu import metrics

    async def go():
        probe = await Receiver.spawn("127.0.0.1:0", SilentHandler())
        port = probe.port
        await probe.shutdown()
        addr = f"127.0.0.1:{port}"
        gauge = lambda: metrics.registry().gauges[  # noqa: E731
            f"net.reliable.peer.consecutive_failures.{addr}"
        ].value

        sender = ReliableSender()
        fut = sender.send(addr, b"late")
        deadline = asyncio.get_running_loop().time() + 5
        conn = sender._connections[addr]
        while conn.failures < 3:  # enough to cross the rule threshold
            assert asyncio.get_running_loop().time() < deadline
            await asyncio.sleep(0.05)
        assert gauge() == 0, "boot-time failures leaked to the health plane"

        # Peer comes up: delivery completes, the peer is known-alive.
        handler = EchoAckHandler()
        recv = await Receiver.spawn(addr, handler)
        assert await asyncio.wait_for(fut, 10) == b"Ack"
        assert gauge() == 0 and conn.ever_connected

        # NOW the peer dies: the very next connect failures are real
        # and must reach the gauge (peer_unreachable's input).
        await recv.shutdown()
        sender.send(addr, b"into the void")
        deadline = asyncio.get_running_loop().time() + 5
        while gauge() < 1:
            assert asyncio.get_running_loop().time() < deadline, (
                "post-liveness failures never reached the gauge"
            )
            await asyncio.sleep(0.05)
        sender.close()

    run(go())


def test_never_connected_peer_reported_after_boot_grace(run, monkeypatch):
    """The boot-stagger suppression is a GRACE WINDOW, not a permanent
    blind spot: a validator that is already dead when this process
    starts (we restarted while it stayed down) must still reach the
    consecutive-failures gauge once the grace passes."""
    from narwhal_tpu import metrics
    from narwhal_tpu.network import reliable_sender as rs

    monkeypatch.setattr(rs, "_NEVER_CONNECTED_GRACE_S", 0.5)

    async def go():
        probe = await Receiver.spawn("127.0.0.1:0", SilentHandler())
        port = probe.port
        await probe.shutdown()
        addr = f"127.0.0.1:{port}"

        sender = ReliableSender()
        sender.send(addr, b"into the void")
        gauge = lambda: metrics.registry().gauges[  # noqa: E731
            f"net.reliable.peer.consecutive_failures.{addr}"
        ].value
        deadline = asyncio.get_running_loop().time() + 8
        while gauge() < 1:  # fires without EVER connecting
            assert asyncio.get_running_loop().time() < deadline, (
                "never-connected dead peer never reached the gauge"
            )
            await asyncio.sleep(0.05)
        sender.close()

    run(go())
