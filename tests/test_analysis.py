"""narwhal-lint acceptance suite (ISSUE 9).

Three layers, mirroring how the linter will actually be trusted:

1. **Fixture snippets** — minimal must-flag / must-pass sources injected
   as in-memory overlay modules, one pair per rule, plus pragma
   semantics (reason suppresses, missing reason is itself a finding,
   unknown pragma names are findings).
2. **Live tree is clean** — ``run_lint(REPO)`` returns zero findings;
   this is the same gate ``make lint`` / CI enforce.
3. **Seeded mutations** — re-introduce one violation per rule class
   into a REAL file (via overlay, no disk writes) and assert the rule
   catches it.  A rule that cannot fire on the tree it guards is dead
   weight; this layer is what proves each one is alive.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from narwhal_tpu.analysis import run_lint  # noqa: E402
from narwhal_tpu.utils import env as env_mod  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

FIXTURE = "narwhal_tpu/_lint_fixture.py"


def rules_of(findings):
    return {f.rule for f in findings}


def fixture_findings(source, rule=None, path=FIXTURE):
    """Lint the live tree plus one overlay module; return the findings
    attributed to the overlay (optionally filtered by rule)."""
    findings = [
        f for f in run_lint(REPO, overlay={path: source}) if f.path == path
    ]
    if rule is not None:
        findings = [f for f in findings if f.rule == rule]
    return findings


# -- live tree ----------------------------------------------------------------

def test_live_tree_is_clean():
    findings = run_lint(REPO)
    assert findings == [], "\n".join(f.render() for f in findings)


# -- rule 1: no-blocking-in-async ---------------------------------------------

BLOCKING_FLAGGED = '''
import os
import subprocess
import time


async def bad_sleep():
    time.sleep(1)


async def bad_fsync(fd):
    os.fsync(fd)


async def bad_open(path):
    with open(path) as f:
        return f.read()


async def bad_subprocess():
    subprocess.run(["true"])


async def bad_crypto(key, digest):
    return key.sign(digest)
'''

BLOCKING_CLEAN = '''
import asyncio
import time


def sync_helper_is_fine():
    time.sleep(1)


async def async_ok():
    await asyncio.sleep(1)

    def executor_target():  # nested sync def: a new, unchecked scope
        time.sleep(1)

    await asyncio.get_running_loop().run_in_executor(None, executor_target)
'''


def test_blocking_rule_flags_each_shape():
    found = fixture_findings(BLOCKING_FLAGGED, "no-blocking-in-async")
    assert len(found) == 5, found
    messages = " | ".join(f.message for f in found)
    for needle in ("time.sleep", "os.fsync", "open", "subprocess.run", ".sign"):
        assert needle in messages, (needle, messages)


def test_blocking_rule_passes_sync_and_executor_shapes():
    assert fixture_findings(BLOCKING_CLEAN, "no-blocking-in-async") == []


# -- rule 2: task-retention ---------------------------------------------------

TASKS_FLAGGED = '''
import asyncio


async def fire_and_forget(coro):
    asyncio.get_running_loop().create_task(coro)
    asyncio.ensure_future(coro)
'''

TASKS_CLEAN = '''
import asyncio

from .utils.tasks import spawn


async def retained(coro):
    spawn(coro)
    task = asyncio.get_running_loop().create_task(coro)
    await task
'''


def test_task_retention_flags_bare_statements():
    found = fixture_findings(TASKS_FLAGGED, "task-retention")
    assert len(found) == 2, found


def test_task_retention_passes_spawn_and_retained():
    assert fixture_findings(TASKS_CLEAN, "task-retention") == []


# -- rule 3: wire-type-coverage -----------------------------------------------

WIRE_FLAGGED = '''
def run(sender, addr, data):
    sender.send(addr, data)
    sender.broadcast([addr], data, msg_type="not_a_real_type")
'''

WIRE_CLEAN = '''
def run(sender, addr, data):
    sender.send(addr, data, msg_type="header")
    writer.send(data)  # receiver reply channel: not a wire sender
'''


def test_wire_type_rule_flags_missing_and_unknown():
    found = fixture_findings(WIRE_FLAGGED, "wire-type-coverage")
    assert len(found) == 2, found
    assert any("without msg_type" in f.message for f in found)
    assert any("not_a_real_type" in f.message for f in found)


def test_wire_type_rule_passes_labeled_sends():
    assert fixture_findings(WIRE_CLEAN, "wire-type-coverage") == []


# -- rule 4: metric-name-drift ------------------------------------------------

def test_metric_drift_flags_consumed_but_never_emitted():
    path = "benchmark/metrics_check.py"
    src = open(os.path.join(REPO, path)).read()
    src += '\n_PROBE = "primary.metric_that_nothing_emits"\n'
    findings = [
        f
        for f in run_lint(REPO, overlay={path: src})
        if f.rule == "metric-name-drift"
    ]
    assert len(findings) == 1, findings
    assert "primary.metric_that_nothing_emits" in findings[0].message


def test_metric_drift_flags_unresolvable_emit_name():
    found = fixture_findings(
        'from . import metrics\n\n\ndef emit(name):\n'
        "    metrics.counter(name).inc()\n",
        "metric-name-drift",
    )
    assert len(found) == 1 and "non-literal" in found[0].message


def test_metric_drift_accepts_fstring_prefix_families():
    assert fixture_findings(
        'from . import metrics\n\n\ndef emit(site):\n'
        '    metrics.counter(f"crypto.verify.ops.{site}").inc()\n',
        "metric-name-drift",
    ) == []


def test_metric_drift_checks_readme_tables():
    readme = open(os.path.join(REPO, "README.md")).read()
    readme += "\nThe `worker.metric_invented_by_docs` gauge shows X.\n"
    findings = [
        f
        for f in run_lint(REPO, overlay={"README.md": readme})
        if f.rule == "metric-name-drift"
    ]
    assert len(findings) == 1, findings
    assert "worker.metric_invented_by_docs" in findings[0].message


# -- rule 5: env-var-registry -------------------------------------------------

def test_env_rule_flags_undeclared_and_direct_reads():
    found = fixture_findings(
        'import os\n\nX = os.environ.get("NARWHAL_NOT_DECLARED")\n',
        "env-var-registry",
    )
    assert len(found) == 2, found  # undeclared literal + direct read
    assert any("not declared" in f.message for f in found)
    assert any("direct os.environ.get" in f.message for f in found)


def test_env_rule_flags_dead_declaration():
    # Name assembled at runtime: the unread check text-searches tests/
    # too, so a verbatim literal HERE would count as the knob's reader.
    dead = "NARWHAL_" + "DECLARED_BUT_DEAD"
    path = "narwhal_tpu/utils/env.py"
    src = open(os.path.join(REPO, path)).read()
    src = src.replace(
        "_VARS = [",
        f'_VARS = [\n    EnvVar("{dead}", "str", None, "x"),',
        1,
    )
    findings = [
        f
        for f in run_lint(REPO, overlay={path: src})
        if f.rule == "env-var-registry"
    ]
    assert any(
        dead in f.message and "nothing reads it" in f.message
        for f in findings
    ), findings


def test_env_accessors_reject_undeclared_names():
    import pytest

    with pytest.raises(KeyError):
        env_mod.env_str("NARWHAL_NOT_DECLARED_ANYWHERE")


def test_env_table_matches_readme():
    readme = open(os.path.join(REPO, "README.md")).read()
    assert env_mod.TABLE_BEGIN in readme and env_mod.TABLE_END in readme
    section = (
        readme.split(env_mod.TABLE_BEGIN, 1)[1]
        .split(env_mod.TABLE_END, 1)[0]
        .strip()
    )
    assert section == env_mod.render_table().strip()


# -- pragmas ------------------------------------------------------------------

def test_pragma_with_reason_suppresses():
    src = (
        "import time\n\n\nasync def staged():\n"
        "    # lint: allow-blocking(fixture: measured harmless)\n"
        "    time.sleep(0)\n"
    )
    assert fixture_findings(src, "no-blocking-in-async") == []


def test_pragma_without_reason_is_a_finding_and_does_not_suppress():
    src = (
        "import time\n\n\nasync def staged():\n"
        "    time.sleep(0)  # lint: allow-blocking()\n"
    )
    found = fixture_findings(src)
    assert {"no-blocking-in-async", "pragma"} <= rules_of(found), found


def test_unknown_pragma_name_is_a_finding():
    found = fixture_findings(
        "X = 1  # lint: allow-everything(sure)\n", "pragma"
    )
    assert len(found) == 1 and "unknown pragma" in found[0].message


# -- seeded mutations: one re-introduced violation per rule class -------------

def _mutate(path, old, new):
    src = open(os.path.join(REPO, path)).read()
    assert old in src, f"mutation anchor drifted in {path}: {old!r}"
    return {path: src.replace(old, new, 1)}


def test_mutation_blocking_sleep_on_snapshot_loop():
    overlay = _mutate(
        "narwhal_tpu/metrics.py",
        "                await asyncio.sleep(self.interval_s)",
        "                time.sleep(self.interval_s)",
    )
    found = [
        f for f in run_lint(REPO, overlay=overlay)
        if f.rule == "no-blocking-in-async"
    ]
    assert len(found) == 1 and found[0].path == "narwhal_tpu/metrics.py"


def test_mutation_fire_and_forget_consensus_task():
    overlay = _mutate(
        "narwhal_tpu/node/node.py",
        '    node.tasks.append(spawn(consensus.run(), name="consensus"))',
        "    asyncio.get_running_loop().create_task(consensus.run())",
    )
    found = [
        f for f in run_lint(REPO, overlay=overlay)
        if f.rule == "task-retention"
    ]
    assert len(found) == 1 and found[0].path == "narwhal_tpu/node/node.py"


def test_mutation_unlabeled_wire_send():
    overlay = _mutate(
        "narwhal_tpu/worker/primary_connector.py",
        ', msg_type="batch_digest"',
        "",
    )
    found = [
        f for f in run_lint(REPO, overlay=overlay)
        if f.rule == "wire-type-coverage"
    ]
    # The call site loses its label AND the declared 'batch_digest'
    # frame type loses its only sender.
    assert any("without msg_type" in f.message for f in found), found
    assert any("batch_digest" in f.message for f in found), found


def test_mutation_health_rule_reads_renamed_metric():
    overlay = _mutate(
        "narwhal_tpu/metrics.py",
        'ctx.gauge("consensus.commit_lag_rounds")',
        'ctx.gauge("consensus.commit_lag_roundz")',
    )
    found = [
        f for f in run_lint(REPO, overlay=overlay)
        if f.rule == "metric-name-drift"
    ]
    assert len(found) == 1 and "commit_lag_roundz" in found[0].message


def test_mutation_env_read_of_typoed_name():
    overlay = _mutate(
        "narwhal_tpu/network/reliable_sender.py",
        'env_raw("NARWHAL_NET_BACKOFF_MAX_S")',
        'env_raw("NARWHAL_NET_BACKOFF_TYPO")',
    )
    found = [
        f for f in run_lint(REPO, overlay=overlay)
        if f.rule == "env-var-registry"
    ]
    assert any("NARWHAL_NET_BACKOFF_TYPO" in f.message for f in found), found


# -- CLI ----------------------------------------------------------------------

def test_cli_clean_tree_and_env_table(capsys):
    from narwhal_tpu.analysis.__main__ import main

    assert main([]) == 0
    assert "narwhal-lint: clean" in capsys.readouterr().out
    assert main(["--env-table"]) == 0
    out = capsys.readouterr().out
    assert env_mod.TABLE_BEGIN in out and "NARWHAL_LOOP_WATCHDOG_MS" in out


def test_cli_report_artifact(tmp_path, capsys):
    import json

    from narwhal_tpu.analysis.__main__ import main

    report = tmp_path / "lint.json"
    assert main(["--report", str(report)]) == 0
    capsys.readouterr()
    data = json.loads(report.read_text())
    assert data["count"] == 0 and data["findings"] == []
