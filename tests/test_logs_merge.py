"""benchmark/logs_merge.py: k-way merge of per-node --log-json streams
into one time-sorted committee-wide JSONL (ISSUE r10 satellite — the
ROADMAP's remaining observability follow-up)."""

import io
import json

from benchmark.logs_merge import merge_streams


def lines(*records):
    return [json.dumps(r) for r in records]


def merged(named_texts):
    out = io.StringIO()
    n = merge_streams(named_texts, out)
    recs = [json.loads(ln) for ln in out.getvalue().splitlines()]
    assert n == len(recs)
    return recs


def test_merge_is_time_sorted_and_node_tagged():
    a = lines(
        {"ts": 1.0, "level": "INFO", "msg": "a1", "node": "primary-0"},
        {"ts": 3.0, "level": "INFO", "msg": "a2", "node": "primary-0"},
    )
    b = lines(
        {"ts": 2.0, "level": "INFO", "msg": "b1", "node": "worker-0-0"},
        {"ts": 4.0, "level": "WARNING", "msg": "b2", "node": "worker-0-0"},
    )
    recs = merged([("primary-0.log", a), ("worker-0-0.log", b)])
    assert [r["msg"] for r in recs] == ["a1", "b1", "a2", "b2"]
    assert [r["node"] for r in recs] == [
        "primary-0", "worker-0-0", "primary-0", "worker-0-0",
    ]
    assert [r["ts"] for r in recs] == sorted(r["ts"] for r in recs)


def test_missing_node_tag_falls_back_to_filename_stem():
    a = lines({"ts": 1.0, "level": "INFO", "msg": "untagged"})
    recs = merged([("/tmp/bench/primary-3.log", a)])
    assert recs[0]["node"] == "primary-3"


def test_non_json_lines_are_wrapped_not_dropped():
    a = [
        json.dumps({"ts": 10.0, "level": "INFO", "msg": "ok", "node": "n0"}),
        "Traceback (most recent call last):",
        '  raise RuntimeError("boom")',
        json.dumps({"ts": 12.0, "level": "ERROR", "msg": "after", "node": "n0"}),
    ]
    b = lines({"ts": 11.0, "level": "INFO", "msg": "other", "node": "n1"})
    recs = merged([("n0.log", a), ("n1.log", b)])
    # Every input line survives the merge.
    assert len(recs) == 5
    raw = [r for r in recs if r["level"] == "RAW"]
    assert len(raw) == 2 and raw[0]["msg"].startswith("Traceback")
    # Raw lines inherit the last seen timestamp, so they sort adjacent to
    # their context (after "ok" at 10.0, before "other" at 11.0).
    order = [r["msg"] for r in recs]
    assert order.index("ok") < order.index(raw[0]["msg"]) < order.index("other")


def test_same_timestamp_keeps_within_file_order():
    a = lines(
        {"ts": 5.0, "msg": "first", "node": "n0"},
        {"ts": 5.0, "msg": "second", "node": "n0"},
        {"ts": 5.0, "msg": "third", "node": "n0"},
    )
    recs = merged([("n0.log", a)])
    assert [r["msg"] for r in recs] == ["first", "second", "third"]


def test_empty_and_blank_streams():
    recs = merged([("n0.log", []), ("n1.log", ["", "  "])])
    # Blank lines are skipped; whitespace-only lines wrap as RAW.
    assert [r["level"] for r in recs] == ["RAW"]
