"""Header/Vote/Certificate hashing, signing, verification, wire roundtrips."""

import pytest

from narwhal_tpu.crypto import Signature
from narwhal_tpu.primary.errors import (
    CertificateRequiresQuorum,
    InvalidHeaderId,
    InvalidSignature,
    UnknownAuthority,
)
from narwhal_tpu.primary.messages import (
    Certificate,
    decode_primary_message,
    encode_certificates_request,
    encode_primary_message,
    genesis,
)
from tests.common import (
    committee,
    keys,
    make_certificate,
    make_header,
    make_vote,
)


def test_header_digest_deterministic():
    kp = keys()[0]
    a = make_header(kp)
    b = make_header(kp)
    assert a.id == b.id
    c2 = make_header(kp, round_=2, parents=a.parents)
    assert c2.id != a.id


def test_header_verify():
    c = committee()
    h = make_header(keys()[0])
    h.verify(c)  # no raise


def test_header_verify_rejects_tampered_id():
    c = committee()
    h = make_header(keys()[0])
    h.round = 99  # id no longer matches content
    with pytest.raises(InvalidHeaderId):
        h.verify(c)


def test_header_verify_rejects_bad_signature():
    c = committee()
    h = make_header(keys()[0])
    h.signature = Signature.default()
    with pytest.raises(InvalidSignature):
        h.verify(c)


def test_vote_verify():
    c = committee()
    h = make_header(keys()[0])
    v = make_vote(h, keys()[1])
    v.verify(c)
    v.signature = Signature.default()
    with pytest.raises(InvalidSignature):
        v.verify(c)


def test_certificate_verify_quorum():
    c = committee()
    cert = make_certificate(make_header(keys()[0]))
    cert.verify(c)  # 3 votes = quorum


def test_certificate_rejects_insufficient_quorum():
    c = committee()
    cert = make_certificate(make_header(keys()[0]))
    cert.votes = cert.votes[:1]
    with pytest.raises(CertificateRequiresQuorum):
        cert.verify(c)


def test_certificate_rejects_forged_vote():
    c = committee()
    cert = make_certificate(make_header(keys()[0]))
    name, _ = cert.votes[0]
    cert.votes[0] = (name, Signature.default())
    with pytest.raises(InvalidSignature):
        cert.verify(c)


def test_certificate_rejects_unknown_voter():
    from narwhal_tpu.crypto import KeyPair

    c = committee()
    cert = make_certificate(make_header(keys()[0]))
    outsider = KeyPair.generate(bytes([99]) * 32)
    cert.votes[0] = (outsider.name, cert.votes[0][1])
    with pytest.raises(UnknownAuthority):
        cert.verify(c)


def test_genesis_always_valid():
    c = committee()
    for cert in genesis(c):
        cert.verify(c)
    assert len({x.digest() for x in genesis(c)}) == 4  # distinct per authority


def test_wire_roundtrips():
    h = make_header(keys()[0], payload={})
    for obj in (h, make_vote(h, keys()[1]), make_certificate(h)):
        decoded = decode_primary_message(encode_primary_message(obj))
        if decoded[0] == "header":
            assert decoded[1].id == h.id and decoded[1].signature == h.signature
        elif decoded[0] == "vote":
            assert decoded[1].digest() == obj.digest()
        else:
            assert decoded[1] == obj

    digests = [make_certificate(h).digest()]
    kind, ds, req = decode_primary_message(
        encode_certificates_request(digests, keys()[2].name)
    )
    assert kind == "certificates_request" and ds == digests and req == keys()[2].name


def test_certificate_store_roundtrip():
    cert = make_certificate(make_header(keys()[0]))
    assert Certificate.deserialize(cert.serialize()) == cert


def test_forged_genesis_lookalike_rejected():
    """A certificate with zero header id and no votes must NOT pass as
    genesis when its round is non-zero (safety: would skip all signature
    checks). Reference messages.rs:249-256."""
    from narwhal_tpu.crypto import Digest
    from narwhal_tpu.primary.messages import Header
    from narwhal_tpu.primary.errors import DagError

    c = committee()
    honest = keys()[1].name
    forged = Certificate(
        header=Header(
            author=honest,
            round=7,
            payload={},
            parents={x.digest() for x in genesis(c)},
            id=Digest.zero(),
            signature=Signature.default(),
        ),
        votes=[],
    )
    with pytest.raises(DagError):
        forged.verify(c)
