"""Clock-offset estimation, skew-corrected joins, and quorum-straggler
attribution (PR 17).

Three layers under test:

- the estimator itself (narwhal_tpu/network/clocksync.py): stamped-ACK
  wire format, RTT gating, and the zero-mean reconciliation algebra;
- the harness-side correction (benchmark/metrics_check.py): per-node
  corrections from snapshot gauges, the corrected cross-node stage join
  recovering ground-truth legs from skewed stamps, critical-path
  telescoping, and the straggler ranking;
- the sim skew-injection arm (narwhal_tpu/sim/committee.py
  ``clock_skew_ms``): injected per-node wall skew must show up in the
  UNCORRECTED pairwise offsets as exactly the skew delta, the
  reconciled vector must recover the injected ground truth, and the
  whole clock section must be bit-reproducible per (seed, spec).
"""

import asyncio

import pytest

from narwhal_tpu import metrics
from narwhal_tpu.network import clocksync
from narwhal_tpu.network.clocksync import (
    OffsetEstimator,
    parse_ack,
    reconcile_zero_mean,
    record_ack_sample,
    stamp_ack,
)

from benchmark.metrics_check import (
    STAGE_ORDER,
    clock_summary,
    corrected_stage_join,
    critical_path_summary,
    quorum_straggler_summary,
    snapshot_correction_ms,
)


# -- wire format ---------------------------------------------------------------


def test_stamped_ack_roundtrips_and_legacy_parses_to_none():
    ack = stamp_ack()
    assert ack.startswith(b"Ack") and len(ack) == 11
    t = parse_ack(ack)
    assert isinstance(t, float) and t > 0
    # Legacy bare ACK (pre-PR-17 peer, and every test stub): no stamp,
    # no sample — the sender must treat it as a plain acknowledgment.
    assert parse_ack(b"Ack") is None
    assert parse_ack(b"") is None
    assert parse_ack(b"Nak" + bytes(8)) is None


# -- estimator -----------------------------------------------------------------


def test_estimator_rejects_congested_round_trips():
    est = OffsetEstimator()
    assert est.add(10.0, rtt_s=0.010)  # first sample always folds
    assert est.samples == 1 and est.offset_s == 10.0
    # A round trip far beyond the best-seen RTT carries an asymmetry
    # bound wider than the signal: rejected, estimate unchanged.
    assert not est.add(99.0, rtt_s=1.0)
    assert est.samples == 1 and est.offset_s == 10.0
    # A comparable-RTT sample folds (EWMA toward the new value).
    assert est.add(12.0, rtt_s=0.012)
    assert est.samples == 2 and 10.0 < est.offset_s < 12.0


def test_record_ack_sample_drives_live_gauges():
    reg = metrics.registry()
    clocksync.reset_estimators()
    try:
        # offset = t_peer - midpoint(send, recv) = 100.05 - 100.005
        record_ack_sample("10.0.0.7:4000", 100.0, 100.01, 100.05)
        g = reg.gauges["clock.offset_ms.10.0.0.7:4000"]
        assert g.value == pytest.approx(45.0, abs=0.01)
        u = reg.gauges["clock.offset_uncertainty_ms.10.0.0.7:4000"]
        assert u.value == pytest.approx(5.0, abs=0.01)
        # Labelled (sim) sources stay OUT of the shared gauges and land
        # in the per-source estimator table instead.
        record_ack_sample("10.0.0.8:4000", 100.0, 100.01, 100.05,
                          src="primary-1")
        assert "clock.offset_ms.10.0.0.8:4000" not in reg.gauges
        assert "10.0.0.8:4000" in clocksync.offsets_by_source()["primary-1"]
    finally:
        clocksync.reset_estimators()
        for name in [n for n in reg.gauges if n.startswith("clock.")]:
            del reg.gauges[name]


def test_reconcile_zero_mean_recovers_centered_skew():
    # True skews: a=+250, b=-250, c=0, d=0 (zero-mean already).  Each
    # node's gauge for a peer reads skew_peer - skew_self.
    skew = {"a": 250.0, "b": -250.0, "c": 0.0, "d": 0.0}
    peer_offsets = {
        n: {p: skew[p] - skew[n] for p in skew if p != n} for n in skew
    }
    out = reconcile_zero_mean(peer_offsets)
    for n, s in skew.items():
        assert out[n] == pytest.approx(s, abs=1e-9)
    # Non-zero-mean skew vector: recovered up to the common shift (the
    # estimator can only see relative offsets).
    skew2 = {"a": 300.0, "b": 100.0}
    po2 = {
        n: {p: skew2[p] - skew2[n] for p in skew2 if p != n} for n in skew2
    }
    out2 = reconcile_zero_mean(po2)
    mean = sum(skew2.values()) / len(skew2)
    for n, s in skew2.items():
        assert out2[n] == pytest.approx(s - mean, abs=1e-9)
    assert reconcile_zero_mean({"n": {}}) == {"n": 0.0}


# -- metrics_check correction layer --------------------------------------------


def _skewed_snapshots():
    """Two-node run with ±250 ms wall skew.  Ground truth: every leg of
    the digest's chain is 50 ms; odd stages stamped on node B.  Each
    node's stamps carry its own skew; the gauges carry what the
    estimator would have measured (peer skew minus own skew)."""
    base = 1000.0
    truth = {s: base + 0.05 * i for i, s in enumerate(STAGE_ORDER)}
    skew_a, skew_b = 0.25, -0.25
    trace_a = {
        s: t + skew_a for i, (s, t) in enumerate(truth.items()) if i % 2 == 0
    }
    trace_a["bytes"] = 512
    trace_b = {
        s: t + skew_b for i, (s, t) in enumerate(truth.items()) if i % 2 == 1
    }
    snap_a = {
        "node": "primary-0",
        "gauges": {"clock.offset_ms.B": -500.0},
        "trace": {"d1": trace_a},
    }
    snap_b = {
        "node": "primary-1",
        "gauges": {"clock.offset_ms.A": 500.0},
        "trace": {"d1": trace_b},
    }
    return snap_a, snap_b, truth


def test_corrected_join_recovers_zero_skew_ground_truth():
    snap_a, snap_b, truth = _skewed_snapshots()
    # The corrections themselves: ±250 ms, recovered from one gauge each.
    assert snapshot_correction_ms(snap_a) == pytest.approx(250.0)
    assert snapshot_correction_ms(snap_b) == pytest.approx(-250.0)
    joined, seal_bytes = corrected_stage_join([snap_a, snap_b])
    assert seal_bytes == {"d1": 512}
    for s, t in truth.items():
        assert joined["d1"][s] == pytest.approx(t, abs=1e-6), s
    # The UNCORRECTED join is off by the skew: cross-node legs swing by
    # ±500 ms and even go acausal (the PR 6 localtime-parse bug shape).
    snap_a2 = {k: v for k, v in snap_a.items() if k != "gauges"}
    snap_b2 = {k: v for k, v in snap_b.items() if k != "gauges"}
    raw, _ = corrected_stage_join([snap_a2, snap_b2])
    first_leg = raw["d1"][STAGE_ORDER[1]] - raw["d1"][STAGE_ORDER[0]]
    assert first_leg == pytest.approx(0.05 - 0.5, abs=1e-6)  # acausal
    assert clock_summary([snap_a, snap_b])["primary-0"][
        "correction_ms"
    ] == pytest.approx(250.0)


def test_critical_path_legs_telescope_to_e2e():
    snap_a, snap_b, _ = _skewed_snapshots()
    joined, _ = corrected_stage_join([snap_a, snap_b])
    # A second, faster chain: the summary must rank the slow one first.
    joined["d2"] = {
        s: 2000.0 + 0.001 * i for i, s in enumerate(STAGE_ORDER)
    }
    # A partial chain (never committed): counted out of full_chains.
    joined["d3"] = {STAGE_ORDER[0]: 3000.0}
    out = critical_path_summary(joined, top_k=2)
    assert out["full_chains"] == 2
    assert out["path"]["digest"] == "d1"
    assert [c["digest"] for c in out["slowest"]] == ["d1", "d2"]
    for chain in out["slowest"]:
        assert chain["legs_sum_ms"] == pytest.approx(
            chain["e2e_ms"], abs=0.01
        )
    assert out["path"]["e2e_ms"] == pytest.approx(
        50.0 * (len(STAGE_ORDER) - 1), abs=0.01
    )
    assert critical_path_summary({}) == {"full_chains": 0}


def test_quorum_straggler_summary_ranks_most_charged_first():
    snaps = [
        {
            "counters": {
                "primary.quorum_straggler.127.0.0.1:1": 3,
                "primary.quorum_straggler.127.0.0.1:2": 7,
                "consensus.support_straggler.127.0.0.1:1": 2,
            },
            "histograms": {
                "primary.vote_quorum_gap_ms": {"sum": 30.0, "count": 10},
                "consensus.support_arrival_ms": {"sum": 84.0, "count": 2},
            },
        },
        {
            "counters": {"primary.quorum_straggler.127.0.0.1:1": 5},
            "histograms": {},
        },
    ]
    out = quorum_straggler_summary(snaps)
    assert [e["address"] for e in out["vote_quorum"]] == [
        "127.0.0.1:1", "127.0.0.1:2",
    ]
    assert out["vote_quorum"][0]["count"] == 8
    assert out["support_quorum"] == [
        {"address": "127.0.0.1:1", "count": 2}
    ]
    assert out["gaps"]["vote_quorum_gap_ms"]["mean"] == pytest.approx(3.0)
    assert out["gaps"]["support_arrival_ms"]["count"] == 2


# -- straggler attribution at the protocol layer -------------------------------


def test_vote_quorum_charges_exactly_the_closing_voter():
    """Of the 2f+1 votes that assemble our certificate, only the author
    of the quorum-CROSSING vote is charged; a duplicate re-delivery of
    an already-counted vote (AuthorityReuse) charges nobody."""
    from tests.common import committee, keys, make_header, make_votes
    from tests.test_core import make_core

    async def go():
        c = committee(base_port=13900)
        me = keys()[0]
        core, store, qs = make_core(c, me)
        reg = metrics.registry()
        gap_before = reg.histograms["primary.vote_quorum_gap_ms"].count
        header = make_header(me, c=c)
        core.current_header = header
        votes = make_votes(header)  # the three other authorities, in order
        base = {
            n: core._m_quorum_straggler[n].value
            for n in core._m_quorum_straggler
        }
        for vote in votes:
            await core._handle("primaries", ("vote", vote), sig_ok=True)
        charged = {
            n: core._m_quorum_straggler[n].value - base[n]
            for n in core._m_quorum_straggler
        }
        # Exactly ONE authority charged: the third (2f+1-th) voter.
        assert charged == {
            n: (1 if n == votes[-1].author else 0) for n in charged
        }
        assert (
            reg.histograms["primary.vote_quorum_gap_ms"].count
            == gap_before + 1
        )
        # Duplicate re-delivery of an already-counted vote: the
        # aggregator raises AuthorityReuse into the DagError handler —
        # nobody is (re-)charged, no second gap observation.
        await core._handle("primaries", ("vote", votes[0]), sig_ok=True)
        after = {
            n: core._m_quorum_straggler[n].value - base[n]
            for n in core._m_quorum_straggler
        }
        assert after == charged
        assert (
            reg.histograms["primary.vote_quorum_gap_ms"].count
            == gap_before + 1
        )
        core.network.close()

    asyncio.run(asyncio.wait_for(go(), 20))


def test_parent_quorum_charges_once_despite_redelivery():
    """The certificate whose arrival completes the round's parent quorum
    is charged exactly once; re-delivered copies (origin-deduped by the
    aggregator) neither advance the quorum nor charge anyone."""
    from tests.common import committee, keys, make_certificate, make_header
    from tests.test_core import make_core

    async def go():
        c = committee(base_port=14000)
        me = keys()[0]
        core, store, qs = make_core(c, me)
        reg = metrics.registry()
        gap_before = reg.histograms["primary.parent_quorum_gap_ms"].count
        certs = [
            make_certificate(make_header(kp, c=c)) for kp in keys()[:3]
        ]
        base = {
            n: core._m_quorum_straggler[n].value
            for n in core._m_quorum_straggler
        }
        await core.process_certificate(certs[0])
        # Re-deliver the first certificate before quorum: deduped.
        await core.process_certificate(certs[0])
        await core.process_certificate(certs[1])
        await core.process_certificate(certs[2])  # closes the quorum
        # Late re-delivery after quorum: silent again.
        await core.process_certificate(certs[1])
        charged = {
            n: core._m_quorum_straggler[n].value - base[n]
            for n in core._m_quorum_straggler
        }
        assert charged == {
            n: (1 if n == certs[2].origin else 0) for n in charged
        }
        assert (
            reg.histograms["primary.parent_quorum_gap_ms"].count
            == gap_before + 1
        )
        core.network.close()

    asyncio.run(asyncio.wait_for(go(), 20))


def test_support_quorum_charges_the_crossing_supporter_once():
    """The round-(r+1) certificate whose direct-support bump crosses
    2f+1 closes the leader's support quorum: exactly one charge, and
    neither idempotent re-inserts nor an equivocation overwrite (the
    cold recompute path) fire the observer again."""
    from narwhal_tpu.consensus import Consensus
    from narwhal_tpu.primary.messages import Certificate, Header
    from tests.common import committee
    from tests.test_consensus import (
        genesis_digests,
        make_certificates,
        sorted_names,
    )

    async def go():
        reg = metrics.registry()
        c = committee(base_port=14100)
        names = sorted_names()
        cons = Consensus(
            c, 50, asyncio.Queue(), asyncio.Queue(), asyncio.Queue(),
            fixed_coin=True,
        )
        addr = {
            n: a.primary.primary_to_primary
            for n, a in c.authorities.items()
        }
        base = {
            n: reg.counters[f"consensus.support_straggler.{addr[n]}"].value
            for n in names
        }
        sa_before = reg.histograms["consensus.support_arrival_ms"].count
        certs, _ = make_certificates(1, 3, genesis_digests(c), names)
        for cert in certs:
            cons.tusk.process_certificate(cert)
        charged = {
            n: reg.counters[f"consensus.support_straggler.{addr[n]}"].value
            - base[n]
            for n in names
        }
        # Round-3 certificates all support the round-2 leader; the THIRD
        # one (2f+1 stake) crossed the line.
        closer = [x for x in certs if x.round == 3][2].origin
        assert charged == {n: (1 if n == closer else 0) for n in names}
        assert (
            reg.histograms["consensus.support_arrival_ms"].count
            == sa_before + 1
        )
        # Idempotent re-insert of the whole round: observer stays quiet.
        for cert in certs:
            cons.tusk.insert_certificate(cert)
        # Equivocation overwrite of a round-3 slot: different parent set,
        # same (round, origin) — the cold recompute path is silent by
        # design (arrival order is gone).
        r2 = {x.digest() for x in certs if x.round == 2}
        twin = Certificate(
            header=Header(
                author=names[3], round=3, payload={},
                parents=set(sorted(r2)[:3]),
            )
        )
        cons.tusk.insert_certificate(twin)
        after = {
            n: reg.counters[f"consensus.support_straggler.{addr[n]}"].value
            - base[n]
            for n in names
        }
        assert after == charged
        assert (
            reg.histograms["consensus.support_arrival_ms"].count
            == sa_before + 1
        )

    asyncio.run(asyncio.wait_for(go(), 20))


# -- sim skew-injection arm ----------------------------------------------------


def _skew_spec():
    from narwhal_tpu.faults.spec import parse_scenario

    return parse_scenario({
        "name": "sim_t_skew", "nodes": 4, "workers": 1, "rate": 400,
        "tx_size": 256, "duration": 12, "seed": 5,
    })


def test_sim_skew_injection_recovered_and_bit_reproducible(tmp_path):
    """±250 ms injected wall skew: the uncorrected pairwise offsets are
    off by exactly the skew delta, the reconciled vector recovers the
    injected ground truth, the protocol itself is skew-invariant (all
    verdicts still pass), and the whole clock section is inside the
    deterministic blob — byte-identical across two runs of the same
    (seed, spec)."""
    from narwhal_tpu.sim.committee import deterministic_blob, run_sim_scenario

    skew = {0: 250.0, 1: -250.0}
    a = run_sim_scenario(
        _skew_spec(), 31, str(tmp_path / "a"), clock_skew_ms=skew
    )
    assert all(v["ok"] for v in a["verdicts"].values()), a["verdicts"]
    clock = a["clock"]
    # UNCORRECTED: node 0 sees node 1 behind by the full 500 ms delta.
    peer = clock["peer_offsets_ms"]
    assert peer["primary-0"]["primary-1"] == pytest.approx(-500.0, abs=5.0)
    assert peer["primary-1"]["primary-0"] == pytest.approx(500.0, abs=5.0)
    assert peer["primary-2"]["primary-3"] == pytest.approx(0.0, abs=5.0)
    # CORRECTED: reconciliation recovers the injected skew vector (it is
    # zero-mean over the committee, so no common-shift ambiguity).
    truth = {f"primary-{i}": skew.get(i, 0.0) for i in range(4)}
    for node, want in truth.items():
        assert clock["reconciled_ms"][node] == pytest.approx(
            want, abs=5.0
        ), node
    # Residual after correction: every pairwise offset is explained by
    # the reconciled vector.
    for src, peers in peer.items():
        for dst, off in peers.items():
            residual = off - (
                clock["reconciled_ms"][dst] - clock["reconciled_ms"][src]
            )
            assert residual == pytest.approx(0.0, abs=5.0), (src, dst)
    # Straggler attribution populated and labeled by authority.
    assert a["stragglers"]["quorum"], a["stragglers"]
    assert all(k.startswith("primary-") for k in a["stragglers"]["quorum"])
    # Bit-reproducible per (seed, spec): clock + stragglers ride inside
    # the deterministic blob.
    b = run_sim_scenario(
        _skew_spec(), 31, str(tmp_path / "b"), clock_skew_ms=skew
    )
    assert deterministic_blob(a) == deterministic_blob(b)
    assert a["clock"] == b["clock"] and a["stragglers"] == b["stragglers"]
