"""Flight-recorder tests (narwhal_tpu/metrics.py FlightRecorder): the
bounded ring, tick deltas, the three dump triggers (/healthz 503
transition, unhandled task death — SIGTERM is exercised end-to-end by the
bench harness), the /debug/flight endpoint, and the scraper pull."""

import asyncio
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from narwhal_tpu import metrics  # noqa: E402
from narwhal_tpu.metrics import (  # noqa: E402
    FlightRecorder,
    HealthMonitor,
    HealthRule,
    MetricsServer,
    Registry,
)
from narwhal_tpu.utils.tasks import spawn  # noqa: E402


def _ceiling_rule(limit=10, **kw):
    def check(ctx):
        v = ctx.gauge("t.val")
        if v is not None and v > limit:
            return {"": {"value": v, "threshold": limit}}
        return {}

    return HealthRule("ceiling", check, **kw)


# -- the ring ------------------------------------------------------------------

def test_ring_is_bounded_and_ordered():
    reg = Registry()
    fl = FlightRecorder(reg, cap=16)
    for i in range(100):
        fl.record("round_advance", round=i)
    events = list(fl.events)
    assert len(events) == 16
    # FIFO eviction: only the newest 16 survive, in order.
    assert [e["round"] for e in events] == list(range(84, 100))
    assert reg.counters["flight.events"].value == 100
    snap = fl.snapshot()
    assert snap["cap"] == 16 and len(snap["events"]) == 16


def test_ring_rides_in_registry_snapshot():
    reg = Registry()
    reg.flight.record("commit", certs=3, batches=7, round=4)
    detail = reg.snapshot()["detail"]["flight.ring"]
    assert detail["events"][-1]["kind"] == "commit"
    assert detail["events"][-1]["certs"] == 3


def test_tick_records_deltas_and_gauges():
    reg = Registry()
    reg.counter("consensus.committed_certificates").inc(5)
    reg.counter("wire.out.bytes.header").inc(1000)
    reg.gauge("primary.round").set(9)
    fl = reg.flight
    fl.tick()
    reg.counter("consensus.committed_certificates").inc(2)
    reg.counter("wire.out.bytes.header").inc(500)
    fl.tick()
    first, second = [e for e in fl.events if e["kind"] == "tick"]
    # First tick measures from zero; the second measures the delta.
    assert first["d"]["commits"] == 5 and first["d"]["wire_out_b"] == 1000
    assert second["d"]["commits"] == 2 and second["d"]["wire_out_b"] == 500
    assert second["round"] == 9


def test_disabled_recorder_is_inert(tmp_path, monkeypatch):
    monkeypatch.setenv("NARWHAL_FLIGHT", "0")
    reg = Registry()
    reg.flight.dir = str(tmp_path)
    reg.flight.record("commit", certs=1)
    reg.flight.tick()
    assert reg.flight.dump("healthz-503") is None
    assert list(reg.flight.events) == []
    assert "flight.events" not in reg.counters
    assert "flight.ring" not in reg.snapshot()["detail"]
    assert list(tmp_path.iterdir()) == []


# -- dump triggers -------------------------------------------------------------

def test_flight_dump_fires_on_induced_503_transition(tmp_path):
    """The ISSUE acceptance pair with test_health's 200↔503 test: the
    moment the monitor's verdict crosses ok→failing (what /healthz
    serves as 503), the ring must land on disk — with the events that
    led up to the anomaly inside it."""
    reg = Registry()
    reg.flight.dir = str(tmp_path)
    g = reg.gauge("t.val")
    mon = HealthMonitor(
        reg, rules=[_ceiling_rule(for_intervals=2, clear_intervals=2)],
        interval_s=1.0,
    )
    reg.health = mon
    reg.flight.record("round_advance", round=3)
    mon.evaluate(0.0)
    assert list(tmp_path.glob("flight-*.json")) == []
    g.set(99)
    mon.evaluate(1.0)  # first breach: hysteresis holds, no dump yet
    assert list(tmp_path.glob("flight-*.json")) == []
    mon.evaluate(2.0)  # second breach: FIRING -> 503 transition -> dump
    dumps = list(tmp_path.glob("flight-*-healthz-503.json"))
    assert len(dumps) == 1
    body = json.loads(dumps[0].read_text())
    assert body["reason"] == "healthz-503"
    kinds = [e["kind"] for e in body["events"]]
    assert "round_advance" in kinds  # pre-anomaly history was captured
    health = [e for e in body["events"] if e["kind"] == "health"]
    assert health and health[-1]["rule"] == "ceiling"
    assert health[-1]["event"] == "FIRING"
    # Staying failing must not re-dump (the trigger is the TRANSITION) …
    g.set(100)
    mon.evaluate(3.0)
    assert len(list(tmp_path.glob("flight-*.json"))) == 1
    # … and a clear + re-fire is a new transition, hence a new dump.
    g.set(0)
    mon.evaluate(4.0)
    mon.evaluate(5.0)
    g.set(99)
    mon.evaluate(6.0)
    mon.evaluate(7.0)
    assert len(list(tmp_path.glob("flight-*.json"))) == 2
    assert reg.counters["flight.dumps"].value == 2


def test_flight_dump_fires_on_unhandled_task_death(tmp_path):
    reg = metrics.registry()
    reg.reset()
    # registry() is the module singleton spawn() records into; point its
    # recorder at a scratch dir for the dump assertion.
    reg.flight.dir = str(tmp_path)

    async def go():
        async def doomed():
            raise RuntimeError("boom")

        task = spawn(doomed(), name="doomed-stage")
        await asyncio.gather(task, return_exceptions=True)
        await asyncio.sleep(0)  # let the done-callback run

    asyncio.run(asyncio.wait_for(go(), 10))
    reg.flight.dir = None
    deaths = [e for e in reg.flight.events if e["kind"] == "task_death"]
    assert deaths and deaths[-1]["task"] == "doomed-stage"
    assert "boom" in deaths[-1]["exc"]
    dumps = list(tmp_path.glob("flight-*-task-death.json"))
    assert len(dumps) == 1
    body = json.loads(dumps[0].read_text())
    assert any(e["kind"] == "task_death" for e in body["events"])


def test_dump_without_dir_is_ring_only():
    reg = Registry()
    assert reg.flight.dir is None
    assert reg.flight.dump("healthz-503") is None
    # The dump marker still lands in the ring (and the counter).
    assert [e["kind"] for e in reg.flight.events] == ["dump"]
    assert reg.counters["flight.dumps"].value == 1


# -- /debug/flight endpoint ----------------------------------------------------

async def _fetch(port, target):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(f"GET {target} HTTP/1.1\r\nHost: x\r\n\r\n".encode())
    await writer.drain()
    data = await reader.read()
    writer.close()
    return data


def test_debug_flight_endpoint_serves_the_ring():
    reg = Registry()
    reg.flight.node_id = "primary-test"
    reg.flight.record("commit", certs=2, batches=5, round=7)
    reg.flight.record("loop_stall", stall_s=0.25)

    async def go():
        server = await MetricsServer.spawn(reg, 0, host="127.0.0.1")
        try:
            resp = await _fetch(server.port, "/debug/flight")
            assert b"200 OK" in resp
            body = json.loads(resp.split(b"\r\n\r\n", 1)[1])
            assert body["node"] == "primary-test"
            assert [e["kind"] for e in body["events"]] == [
                "commit", "loop_stall",
            ]
            assert body["events"][0]["certs"] == 2
        finally:
            await server.shutdown()

    asyncio.run(asyncio.wait_for(go(), 15))


def test_scraper_flight_all_pulls_rings():
    """The quiesce-time pull both harnesses embed as the bench JSON
    `flight` section — against a live endpoint and a dead target."""
    from benchmark.scraper import Scraper

    reg = Registry()
    reg.flight.record("commit", certs=1, batches=2, round=3)
    result = {}

    async def go():
        server = await MetricsServer.spawn(reg, 0, host="127.0.0.1")
        try:
            scraper = Scraper(
                [("node-0", "127.0.0.1", server.port),
                 ("node-gone", "127.0.0.1", 1)],
                interval_s=0.05,
            )
            result.update(
                await asyncio.get_running_loop().run_in_executor(
                    None, scraper.flight_all
                )
            )
        finally:
            await server.shutdown()

    asyncio.run(asyncio.wait_for(go(), 15))
    assert result["node-gone"] is None
    assert [e["kind"] for e in result["node-0"]["events"]] == ["commit"]
