"""Fault-injection subsystem unit tests: scenario spec validation and
seeding, the netem shaping/partition shims at the network seam, the
jittered env-tunable reconnect backoff (ISSUE 6 satellite), the new
Byzantine-detection health rules, and the audit-replay safety checker's
ability to actually CATCH violations (a checker that can't fail is not a
verdict)."""

import asyncio
import json
import os
import random
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from narwhal_tpu import metrics  # noqa: E402
from narwhal_tpu.consensus.replay import (  # noqa: E402
    AuditWriter,
    cross_node_prefix,
    read_audit,
    replay_segments,
)
from narwhal_tpu.faults import netem  # noqa: E402
from narwhal_tpu.faults.spec import (  # noqa: E402
    SpecError,
    parse_scenario,
)
from narwhal_tpu.metrics import HealthMonitor, Registry, default_rules  # noqa: E402
from narwhal_tpu.network.framing import read_frame, write_frame  # noqa: E402
from narwhal_tpu.network.reliable_sender import (  # noqa: E402
    backoff_cap,
    next_backoff,
)
from tests.common import committee, keys  # noqa: E402
from tests.test_consensus import (  # noqa: E402
    feed,
    genesis_digests,
    make_certificates,
    mock_certificate,
    sorted_names,
)


# -- scenario spec ------------------------------------------------------------

def _minimal(**overrides):
    base = {"name": "t", "byzantine": [{"node": 0, "behaviors": ["equivocate"]}]}
    base.update(overrides)
    return base


def test_spec_parses_and_env_seed_overrides():
    s = parse_scenario(_minimal(seed=5), env={})
    assert s.seed == 5 and s.byzantine_nodes() == [0]
    assert s.honest_nodes() == [1, 2, 3]
    s2 = parse_scenario(_minimal(seed=5), env={"NARWHAL_FAULT_SEED": "99"})
    assert s2.seed == 99
    # A malformed override must fail LOUD, not silently fall back to the
    # spec's own seed: the operator asked to replay a specific draw.
    with pytest.raises(SpecError):
        parse_scenario(_minimal(seed=5), env={"NARWHAL_FAULT_SEED": "0x2A"})


def test_spec_rejects_unknown_fields_and_behaviors():
    with pytest.raises(SpecError):
        parse_scenario(_minimal(bogus=1), env={})
    with pytest.raises(SpecError):
        parse_scenario(
            {"name": "t", "byzantine": [{"node": 0, "behaviors": ["fly"]}]},
            env={},
        )


def test_spec_enforces_bft_fault_bound():
    # 2 byzantine of 4 exceeds f=1.
    with pytest.raises(SpecError):
        parse_scenario(
            {
                "name": "t",
                "byzantine": [
                    {"node": 0, "behaviors": ["equivocate"]},
                    {"node": 1, "behaviors": ["wrong_key"]},
                ],
            },
            env={},
        )
    # byzantine + crashed together exceed f=1 too.
    with pytest.raises(SpecError):
        parse_scenario(
            {
                "name": "t",
                "byzantine": [{"node": 0, "behaviors": ["equivocate"]}],
                "crash": [{"node": 1, "at_s": 5}],
            },
            env={},
        )
    # An oversized partition group is rejected.
    with pytest.raises(SpecError):
        parse_scenario(
            {
                "name": "t",
                "wan": {"partitions": [{"group": [0, 1], "from_s": 1}]},
            },
            env={},
        )
    # Fault planes compose against the SAME f: a within-bound byzantine
    # node plus a within-bound partitioned node is 2 faulty of 4.
    with pytest.raises(SpecError):
        parse_scenario(
            {
                "name": "t",
                "byzantine": [{"node": 0, "behaviors": ["equivocate"]}],
                "wan": {"partitions": [{"group": [1], "from_s": 1}]},
            },
            env={},
        )


def test_spec_rejects_fault_offsets_outside_duration():
    """A timed fault landing at/after `duration` would silently stretch
    the run and push the liveness settle point outside the measured
    window — the one authoring error the spec used to let through."""
    with pytest.raises(SpecError):
        parse_scenario(
            {"name": "t", "duration": 20, "crash": [{"node": 0, "at_s": 20}]},
            env={},
        )
    with pytest.raises(SpecError):
        parse_scenario(
            {
                "name": "t",
                "duration": 30,
                "crash": [{"node": 0, "at_s": 5, "restart_at_s": 30}],
            },
            env={},
        )
    with pytest.raises(SpecError):
        parse_scenario(
            {
                "name": "t",
                "duration": 20,
                "wan": {"partitions": [{"group": [0], "from_s": 25}]},
            },
            env={},
        )
    with pytest.raises(SpecError):
        parse_scenario(
            {
                "name": "t",
                "duration": 20,
                "wan": {
                    "partitions": [
                        {"group": [0], "from_s": 5, "until_s": 21}
                    ]
                },
            },
            env={},
        )
    # A heal exactly at window close is fine (the runner settles after).
    # The two planes are checked separately: composing them on DIFFERENT
    # nodes would exceed f=1 and is rejected (see the bound test above).
    s = parse_scenario(
        {
            "name": "t",
            "duration": 20,
            "crash": [{"node": 0, "at_s": 5, "restart_at_s": 12}],
        },
        env={},
    )
    assert s.crash[0].restart_at_s == 12.0
    parse_scenario(
        {
            "name": "t",
            "duration": 20,
            "wan": {
                "partitions": [{"group": [1], "from_s": 5, "until_s": 20}]
            },
        },
        env={},
    )


def test_control_arm_strips_faults_keeps_knobs():
    s = parse_scenario(
        _minimal(
            env={"NARWHAL_HEALTH_PEER_RETRANS_RATE": "3"},
            parameters={"gc_depth": 8},
        ),
        env={},
    )
    c = s.control_arm()
    assert c.is_clean() and not s.is_clean()
    assert c.env == s.env and c.parameters == s.parameters
    assert c.name == "t.control"


# -- jittered, env-tunable backoff (satellite) --------------------------------

def test_backoff_jitter_and_cap():
    rng = random.Random(42)
    delay = 0.2
    sleeps = []
    for _ in range(12):
        sleep, delay = next_backoff(delay, cap=5.0, rng=rng)
        sleeps.append(sleep)
    # Delay doubles toward the cap and stays there.
    assert delay == 5.0
    # Every sleep is 50-100% of its (capped) nominal delay — never more
    # than the cap, never degenerate.
    assert all(0 < s <= 5.0 for s in sleeps)
    # Jitter actually varies (a constant schedule thundering-herds).
    tail = sleeps[-6:]
    assert max(tail) - min(tail) > 0.1


def test_backoff_desynchronizes_lockstep_peers():
    # Two peers that failed at the same instant must drift apart: after a
    # few steps their cumulative wakeup times differ materially.
    t_a = t_b = 0.0
    d_a = d_b = 0.2
    rng_a, rng_b = random.Random(1), random.Random(2)
    for _ in range(8):
        s, d_a = next_backoff(d_a, cap=60.0, rng=rng_a)
        t_a += s
        s, d_b = next_backoff(d_b, cap=60.0, rng=rng_b)
        t_b += s
    assert abs(t_a - t_b) > 1.0


def test_backoff_cap_env_override(monkeypatch):
    monkeypatch.setenv("NARWHAL_NET_BACKOFF_MAX_S", "2.5")
    assert backoff_cap() == 2.5
    sleep, nxt = next_backoff(60.0, rng=random.Random(0))
    assert sleep <= 2.5 and nxt == 2.5
    monkeypatch.setenv("NARWHAL_NET_BACKOFF_MAX_S", "garbage")
    assert backoff_cap() == 60.0
    monkeypatch.delenv("NARWHAL_NET_BACKOFF_MAX_S")
    assert backoff_cap() == 60.0


# -- netem ---------------------------------------------------------------------

def _emulator(rules=None, default=None, partitions=(), start_ts=0.0):
    return netem.NetEmulator(
        rules or {}, default, list(partitions), seed=7, node="t",
        start_ts=start_ts,
    )


def test_partition_window_timing():
    win = netem.PartitionWindow(
        peers=frozenset({"10.0.0.2:7001"}), from_s=5.0, until_s=12.0
    )
    emu = _emulator(partitions=[win], start_ts=100.0)
    assert not emu.blocked("10.0.0.2:7001", now=104.9)
    assert emu.blocked("10.0.0.2:7001", now=105.0)
    assert emu.blocked("10.0.0.2:7001", now=111.9)
    assert not emu.blocked("10.0.0.2:7001", now=112.0)  # healed
    assert not emu.blocked("10.0.0.3:7001", now=108.0)  # other peer
    forever = netem.PartitionWindow(
        peers=frozenset({"10.0.0.2:7001"}), from_s=5.0, until_s=None
    )
    emu2 = _emulator(partitions=[forever], start_ts=100.0)
    assert emu2.blocked("10.0.0.2:7001", now=1e9)


def test_no_emulator_hooks_are_passthrough():
    netem.install(None)
    try:
        assert not netem.blocked("1.2.3.4:1")
        assert netem.wrap("1.2.3.4:1", None, None) == (None, None)
    finally:
        netem.reset()


def test_netem_config_load_selects_node(tmp_path):
    cfg = {
        "seed": 3,
        "start_ts": 50.0,
        "nodes": {
            "primary-0": {
                "rules": [
                    {"dst": "9.9.9.9:1", "latency_ms": 40, "loss": 0.5},
                    {"dst": "*", "latency_ms": 10},
                ],
                "partitions": [
                    {"peers": ["9.9.9.9:2"], "from_s": 1, "until_s": 2}
                ],
            }
        },
    }
    path = tmp_path / "netem.json"
    path.write_text(json.dumps(cfg))
    emu = netem.NetEmulator.load(str(path), "primary-0")
    assert emu.shape_for("9.9.9.9:1").latency_ms == 40
    assert emu.shape_for("anything:else").latency_ms == 10  # wildcard
    assert emu.blocked("9.9.9.9:2", now=51.5)
    # A node the scenario doesn't shape loads as None (all hooks no-op).
    assert netem.NetEmulator.load(str(path), "worker-3-0") is None


def test_shaped_writer_delays_frames_in_order():
    async def go():
        received = []
        got_two = asyncio.Event()

        async def on_conn(reader, writer):
            loop = asyncio.get_running_loop()
            try:
                while True:
                    frame = await read_frame(reader)
                    received.append((loop.time(), frame))
                    if len(received) >= 2:
                        got_two.set()
            except (asyncio.IncompleteReadError, ConnectionError):
                pass

        server = await asyncio.start_server(on_conn, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        emu = _emulator(
            rules={f"127.0.0.1:{port}": netem.Shape(latency_ms=80)}
        )
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        reader, shaped = emu.wrap(f"127.0.0.1:{port}", reader, writer)
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        await write_frame(shaped, b"one")
        await write_frame(shaped, b"two")
        await asyncio.wait_for(got_two.wait(), 5)
        assert [f for _, f in received] == [b"one", b"two"]  # order kept
        # Both frames arrived no earlier than the shaped latency.
        assert all(t - t0 >= 0.07 for t, _ in received)
        shaped.close()
        server.close()
        await server.wait_closed()

    asyncio.run(asyncio.wait_for(go(), 15))


def test_shaped_writer_loss_surfaces_as_connection_reset():
    async def go():
        async def on_conn(reader, writer):
            try:
                while True:
                    await read_frame(reader)
            except (asyncio.IncompleteReadError, ConnectionError):
                pass

        server = await asyncio.start_server(on_conn, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        emu = _emulator(
            rules={f"127.0.0.1:{port}": netem.Shape(loss=1.0)}
        )
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        _, shaped = emu.wrap(f"127.0.0.1:{port}", reader, writer)
        with pytest.raises(ConnectionResetError):
            await write_frame(shaped, b"doomed")
        shaped.close()
        server.close()
        await server.wait_closed()

    asyncio.run(asyncio.wait_for(go(), 15))


def test_partition_cuts_established_connection():
    async def go():
        async def on_conn(reader, writer):
            try:
                while True:
                    await read_frame(reader)
            except (asyncio.IncompleteReadError, ConnectionError):
                pass

        server = await asyncio.start_server(on_conn, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        addr = f"127.0.0.1:{port}"
        loop = asyncio.get_running_loop()
        # Window opens 0.2 s from now: the connection is established and
        # working BEFORE the partition begins.
        import time as _time

        emu = _emulator(
            partitions=[
                netem.PartitionWindow(
                    peers=frozenset({addr}), from_s=0.2, until_s=None
                )
            ],
            start_ts=_time.time(),
        )
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        _, shaped = emu.wrap(addr, reader, writer)
        await write_frame(shaped, b"before")  # flows while healthy
        await asyncio.sleep(0.25)
        with pytest.raises(ConnectionResetError):
            await write_frame(shaped, b"after")
        assert emu.blocked(addr)
        shaped.close()
        server.close()
        await server.wait_closed()
        _ = loop

    asyncio.run(asyncio.wait_for(go(), 15))


# -- detection rules -----------------------------------------------------------

def test_equivocation_and_invalid_signature_rules_latch():
    reg = Registry()
    mon = HealthMonitor(reg, rules=default_rules({}), interval_s=1.0)
    t = 1000.0
    assert mon.evaluate(t) == []
    reg.counter("primary.equivocations_detected").inc()
    firing = {f["rule"] for f in mon.evaluate(t + 1)}
    assert "equivocation" in firing
    reg.counter("primary.invalid_signatures").inc(3)
    firing = {f["rule"] for f in mon.evaluate(t + 2)}
    assert {"equivocation", "invalid_signature"} <= firing
    # Latched: counters are monotone, the proof doesn't expire.
    assert "equivocation" in {f["rule"] for f in mon.evaluate(t + 30)}


def test_peer_vote_silence_requires_round_progress():
    reg = Registry()
    reg.counter("primary.peer_votes.10.0.0.2:7001")
    active = reg.counter("primary.peer_votes.10.0.0.3:7001")
    rnd = reg.gauge("primary.round")
    mon = HealthMonitor(
        reg,
        rules=default_rules({"NARWHAL_HEALTH_VOTE_SILENCE_WINDOW_S": "8"}),
        interval_s=1.0,
    )
    t = 2000.0
    # Idle committee: rounds not advancing — silent even though the peer
    # counter is flat.
    rnd.set(5)
    for i in range(12):
        assert mon.evaluate(t + i) == []
    # Rounds advance, the active peer keeps voting, the silent one
    # doesn't: only the silent one is named.
    for i in range(12, 26):
        rnd.set(5 + i)
        active.inc(2)
        firing = mon.evaluate(t + i)
    subjects = {
        f["subject"] for f in firing if f["rule"] == "peer_vote_silence"
    }
    assert subjects == {"10.0.0.2:7001"}


def test_stale_replay_rule_fires_on_rate_not_trickle():
    reg = Registry()
    stale = reg.counter("primary.stale_messages")
    mon = HealthMonitor(
        reg,
        rules=default_rules(
            {"NARWHAL_HEALTH_STALE_RATE": "2",
             "NARWHAL_HEALTH_STALE_WINDOW_S": "5"}
        ),
        interval_s=1.0,
    )
    t = 3000.0
    # A slow trickle (1 per 2 s) stays under the 2/s threshold.
    for i in range(10):
        if i % 2 == 0:
            stale.inc()
        assert mon.evaluate(t + i) == []
    # A flood (10/s) fires.
    firing = []
    for i in range(10, 18):
        stale.inc(10)
        firing = mon.evaluate(t + i)
    assert "stale_replay" in {f["rule"] for f in firing}


def test_new_rules_silent_on_clean_registry():
    reg = Registry()
    reg.gauge("primary.round").set(50)
    votes = reg.counter("primary.peer_votes.10.0.0.2:7001")
    mon = HealthMonitor(reg, rules=default_rules({}), interval_s=1.0)
    t = 4000.0
    for i in range(20):
        reg.gauge("primary.round").inc(1)
        votes.inc(3)  # healthy peer votes every round
        assert mon.evaluate(t + i) == [], "rule fired on a clean node"


# -- audit replay: the checker must catch real violations ----------------------

def _write_segment(path, inserts, commits_interleaved, restore=b""):
    """commits_interleaved: {index-in-inserts: [digests to record after
    that insert]} — mirrors the runner's I/C interleaving."""
    w = AuditWriter(str(path))
    w.restore_marker(restore)
    for i, cert in enumerate(inserts):
        w.insert(cert)
        for d in commits_interleaved.get(i, []):
            w._record(b"C", bytes(d))
    w.close()


def test_replay_segment_roundtrip_clean_stream(tmp_path):
    c = committee()
    names = sorted_names()
    certs, parents = make_certificates(1, 6, genesis_digests(c), names)
    _, trigger = mock_certificate(names[0], 7, parents)
    stream = certs + [trigger]
    # Record exactly what a live fixed-coin node would: golden's commits.
    from narwhal_tpu.consensus.golden import GoldenTusk

    golden = GoldenTusk(c, 50, fixed_coin=True)
    commits = {}
    for i, cert in enumerate(stream):
        seq = golden.process_certificate(cert)
        if seq:
            commits[i] = [x.digest() for x in seq]
    path = tmp_path / "seg0.bin"
    _write_segment(path, stream, commits)
    verdict = replay_segments(c, 50, [str(path)], fixed_coin=True)
    assert verdict["ok"], verdict["violations"]
    assert verdict["recorded_commits"] == verdict["golden_commits"] > 0


def test_replay_detects_reordered_and_forged_commits(tmp_path):
    c = committee()
    names = sorted_names()
    certs, parents = make_certificates(1, 6, genesis_digests(c), names)
    _, trigger = mock_certificate(names[0], 7, parents)
    stream = certs + [trigger]
    from narwhal_tpu.consensus.golden import GoldenTusk

    golden = GoldenTusk(c, 50, fixed_coin=True)
    commits = {}
    for i, cert in enumerate(stream):
        seq = golden.process_certificate(cert)
        if seq:
            commits[i] = [x.digest() for x in seq]
    # Reorder two commits within a burst: byte-identity must fail.
    (k, seq) = next((k, v) for k, v in commits.items() if len(v) >= 2)
    commits[k] = [seq[1], seq[0]] + seq[2:]
    path = tmp_path / "seg_bad.bin"
    _write_segment(path, stream, commits)
    verdict = replay_segments(c, 50, [str(path)], fixed_coin=True)
    assert not verdict["ok"]
    assert any("diverges" in v for v in verdict["violations"])


def test_replay_detects_double_commit_within_segment(tmp_path):
    c = committee()
    names = sorted_names()
    certs, parents = make_certificates(1, 6, genesis_digests(c), names)
    _, trigger = mock_certificate(names[0], 7, parents)
    stream = certs + [trigger]
    from narwhal_tpu.consensus.golden import GoldenTusk

    golden = GoldenTusk(c, 50, fixed_coin=True)
    commits = {}
    for i, cert in enumerate(stream):
        seq = golden.process_certificate(cert)
        if seq:
            commits[i] = [x.digest() for x in seq]
    k, seq = next((k, v) for k, v in commits.items() if v)
    commits[k] = seq + [seq[0]]  # same digest committed twice
    path = tmp_path / "seg_dup.bin"
    _write_segment(path, stream, commits)
    verdict = replay_segments(c, 50, [str(path)], fixed_coin=True)
    assert not verdict["ok"]
    assert any("twice" in v for v in verdict["violations"])


def test_audit_writer_rolls_instead_of_appending_to_old_segment(tmp_path):
    """One segment per incarnation is the format's invariant (restore
    marker first).  A fixed NARWHAL_CONSENSUS_AUDIT path reused across a
    restart must NOT append a second 'R' mid-file (that would read as a
    false safety violation) — the writer rolls to `<path>.N` and keeps
    the old segment intact."""
    path = tmp_path / "audit.bin"
    w1 = AuditWriter(str(path))
    w1.restore_marker(b"")
    w1.close()
    assert w1.path == str(path)

    w2 = AuditWriter(str(path))
    w2.restore_marker(b"blob")
    w2.close()
    assert w2.path == str(path) + ".1"

    w3 = AuditWriter(str(path))
    w3.close()
    assert w3.path == str(path) + ".2"

    first = read_audit(str(path))
    second = read_audit(w2.path)
    assert [t for t, _ in first] == [b"R"]
    assert second == [(b"R", b"blob")]


def test_equivocate_requires_unit_stake_committee():
    """The equivocation split sizes parent sets and peer shares by COUNT
    against the stake-denominated quorum threshold — on a weighted
    committee the scenario silently voids (twin below parent quorum, or
    real header never certified), so the wrapper must refuse loudly."""
    from narwhal_tpu.faults.byzantine import _require_unit_stake

    c = committee()
    _require_unit_stake(c)  # unit stakes: fine
    weighted = committee()
    next(iter(weighted.authorities.values())).stake = 2
    with pytest.raises(SpecError, match="unit-stake"):
        _require_unit_stake(weighted)


def test_read_audit_tolerates_torn_tail(tmp_path):
    c = committee()
    names = sorted_names()
    certs, _ = make_certificates(1, 2, genesis_digests(c), names)
    path = tmp_path / "seg_torn.bin"
    _write_segment(path, certs, {})
    whole = read_audit(str(path))
    data = path.read_bytes()
    path.write_bytes(data[:-7])  # SIGKILL mid-record
    torn = read_audit(str(path))
    assert torn == whole[:-1]  # clean prefix, no exception


def test_cross_node_prefix_accepts_lag_rejects_fork():
    a = ["d1", "d2", "d3", "d4"]
    ok = cross_node_prefix({"n0": a, "n1": a[:2], "n2": a[:3]})
    assert ok["ok"] and ok["reference_node"] == "n0"
    bad = cross_node_prefix({"n0": a, "n1": ["d1", "dX"]})
    assert not bad["ok"]
    assert "diverges" in bad["violations"][0]


# -- byzantine plan ------------------------------------------------------------

def test_byzantine_plan_roundtrip_and_split():
    from narwhal_tpu.faults.byzantine import ByzantinePlan

    kps = keys()
    plan = ByzantinePlan.from_json(
        {
            "behaviors": ["withhold_votes", "equivocate"],
            "seed": 9,
            "withhold_targets": [kps[1].name.encode_base64()],
        }
    )
    assert plan.withhold_targets == {kps[1].name}
    # Deterministic under the same seed, keep+rest partitions the set,
    # and two independently-loaded plans (one per role process) agree —
    # the coordination the favored split exists for.
    addr_by_name = {f"auth{i}": f"10.0.0.{i}:7000" for i in range(5)}
    a1, b1 = plan.favored_split(addr_by_name, 3)
    plan2 = ByzantinePlan.from_json({"behaviors": ["equivocate"], "seed": 9})
    a2, b2 = plan2.favored_split(addr_by_name, 3)
    assert len(a1) == 3 and sorted(a1 + b1) == sorted(addr_by_name.values())
    assert (a1, b1) == (a2, b2)
    # A different address PLANE of the same authorities splits to the
    # same names (prefix-aligned), and a different seed re-deals.
    other_plane = {n: f"10.0.1.{i}:8000" for i, n in enumerate(sorted(addr_by_name))}
    c1, _ = plan.favored_split(other_plane, 3)
    assert {a.split(":")[0].rsplit(".", 1)[1] for a in a1} == {
        c.split(":")[0].rsplit(".", 1)[1] for c in c1
    }
    plan3 = ByzantinePlan.from_json({"behaviors": ["equivocate"], "seed": 10})
    deals = {tuple(plan3.favored_split(addr_by_name, 3)[0]), tuple(a1)}
    assert len(deals) == 2

    with pytest.raises(Exception):
        ByzantinePlan.from_json({"behaviors": ["teleport"]})


def test_log_commit_fallback_counts_post_settle_lines(tmp_path):
    """The liveness verdict's scrape-independent fallback: commit log
    lines at/after the settle timestamp count, earlier ones and
    non-commit lines don't, and unreadable/garbled lines are skipped.
    The settle reference is NAIVE LOCAL time: node/main.py formats
    %(asctime)s with logging's default localtime converter (the 'Z' is
    cosmetic), so the parser must read the stamps back in local time —
    a UTC parse would shift every stamp by the host's UTC offset and
    silently invert the verdict on any non-UTC host."""
    from benchmark.fault_bench import _log_commits_after

    log = tmp_path / "primary-0.log"
    log.write_text(
        "2026-01-01T00:00:01.000Z INFO narwhal.consensus "
        "Committed B1(aaaa) -> d1d1\n"
        "2026-01-01T00:00:05.000Z INFO narwhal.consensus "
        "Committed B2(bbbb) -> d2d2\n"
        "garbage line without a timestamp Committed B9(zzzz) -> d9d9\n"
        "2026-01-01T00:00:09.000Z WARNING narwhal.metrics HEALTH "
        "anomaly FIRING rule=commit_stall\n"
        "2026-01-01T00:00:07.000Z INFO narwhal.consensus "
        "Committed B7(eeee)\n"  # EMPTY header: no payload digest, no count
        "2026-01-01T00:00:10.000Z INFO narwhal.consensus "
        "Committed B3(cccc) -> d3d3\n"
    )
    import datetime

    settle = datetime.datetime(2026, 1, 1, 0, 0, 5).timestamp()
    assert _log_commits_after([str(log)], settle) == 2  # B2 + B3
    assert _log_commits_after([str(log)], settle + 100) == 0
    assert _log_commits_after([str(tmp_path / "missing.log")], settle) == 0


def test_log_commit_fallback_incremental_state(tmp_path):
    """With a shared ``state`` dict the fallback scans each log's bytes
    once: appended lines are picked up by the next call, the running
    count persists, and a torn (newline-less) tail is deferred to the
    next poll instead of being miscounted."""
    import datetime

    from benchmark.fault_bench import _log_commits_after

    line = (
        "2026-01-01T00:00:0{s}.000Z INFO narwhal.consensus "
        "Committed B{s}(aaaa) -> dddd\n"
    )
    settle = datetime.datetime(2026, 1, 1, 0, 0, 0).timestamp()
    log = tmp_path / "primary-0.log"
    log.write_text(line.format(s=1))
    state: dict = {}
    assert _log_commits_after([str(log)], settle, state) == 1
    # Append one complete line and one torn tail.
    with open(log, "a") as f:
        f.write(line.format(s=2))
        f.write("2026-01-01T00:00:03.000Z INFO narwhal.consensus Comm")
    assert _log_commits_after([str(log)], settle, state) == 2
    # Complete the torn line: only the tail is re-scanned, count -> 3.
    with open(log, "a") as f:
        f.write("itted B3(cccc) -> d3d3\n")
    # The torn fragment completes into a line whose prefix parses.
    assert _log_commits_after([str(log)], settle, state) == 3
    offset, count = state[str(log)]
    assert count == 3 and offset == log.stat().st_size
