"""Interleave-rule acceptance suite (ISSUE 10, static half).

Same three layers as tests/test_analysis.py: fixture snippets prove each
rule shape fires (and each sanctioned shape passes), the live tree is
clean, and seeded mutations against REAL files prove the rules are alive
on the tree they guard — including stripping the live pragmas, which
must resurface the windows they document.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from narwhal_tpu.analysis import run_lint  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = "narwhal_tpu/_interleave_fixture.py"


def fixture_findings(source, rule=None, path=FIXTURE):
    findings = [
        f for f in run_lint(REPO, overlay={path: source}) if f.path == path
    ]
    if rule is not None:
        findings = [f for f in findings if f.rule == rule]
    return findings


# -- interleave-window: must flag ---------------------------------------------

WINDOW_FLAGGED = '''
import asyncio

from ..utils.tasks import spawn


class Fixture:
    def __init__(self, queue: asyncio.Queue):
        self.queue = queue
        self.backlog = []

    async def run(self):
        while True:
            await self.queue.get()
            spawn(self._drain())

    async def _drain(self):
        staged = self.backlog
        for item in list(staged):
            await self.queue.put(item)
        self.backlog = []
'''


def test_window_rule_flags_spawned_in_loop_race():
    found = fixture_findings(WINDOW_FLAGGED, "interleave-window")
    assert len(found) == 1, found
    msg = found[0].message
    assert "self.backlog" in msg
    assert "multi-instance" in msg  # spawned from inside a loop
    assert "torn-invariant window" in msg


def test_window_finding_reports_the_yield_chain():
    # The suspension is reported as the actual await, not just a line.
    found = fixture_findings(WINDOW_FLAGGED, "interleave-window")
    assert "await self.queue.put" in found[0].message


WINDOW_CROSS_ROOT = '''
import asyncio


class FixtureScribe:
    def __init__(self, state: "FixtureShared"):
        self.state = state

    async def run(self):
        while True:
            await asyncio.sleep(1)
            self.state.slots["k"] = 1


class FixtureShared:
    def __init__(self, queue: asyncio.Queue):
        self.queue = queue
        self.slots = {}

    async def run(self):
        while True:
            probe = self.slots.get("k")
            await self.queue.get()
            self.slots["k"] = probe
'''


def test_window_rule_sees_cross_class_sharing_through_typed_attrs():
    found = fixture_findings(WINDOW_CROSS_ROOT, "interleave-window")
    assert len(found) == 1, found
    assert "self.slots" in found[0].message
    # Names the OTHER task root that writes through the typed attribute.
    assert "FixtureScribe.run" in found[0].message


# -- interleave-window: must pass ---------------------------------------------

WINDOW_CLEAN = '''
import asyncio

from ..utils.tasks import spawn


class SingleRoot:
    """Read→yield→write, but only ONE task ever touches the attr."""

    def __init__(self, queue: asyncio.Queue):
        self.queue = queue
        self.backlog = []

    async def run(self):
        while True:
            staged = self.backlog
            await self.queue.get()
            self.backlog = staged


class TakeBeforeYield:
    """The sanctioned shape: consume shared state atomically BEFORE the
    suspension; another task may refill it meanwhile."""

    def __init__(self, queue: asyncio.Queue):
        self.queue = queue
        self.backlog = []

    def push(self, item):
        self.backlog.append(item)

    async def run(self):
        while True:
            staged, self.backlog = self.backlog, []
            for item in staged:
                await self.queue.put(item)


class AtomicTick:
    """Sleep-then-atomic-tick: every read/write happens after the yield,
    within one uninterrupted slice (the timer pattern all waiters use)."""

    def __init__(self, peer: TakeBeforeYield):
        self.peer = peer
        self.pending = {}

    def note(self, k, v):
        self.pending[k] = v

    async def run(self):
        while True:
            await asyncio.sleep(1.0)
            for k in [k for k in self.pending if k < 0]:
                del self.pending[k]
            self.peer.push(len(self.pending))
'''


def test_window_rule_passes_single_root_take_and_tick_shapes():
    assert fixture_findings(WINDOW_CLEAN, "interleave-window") == []
    assert fixture_findings(WINDOW_CLEAN, "interleave-iteration") == []


NONYIELDING_AWAIT = '''
import asyncio

from ..utils.tasks import spawn


class Handlers:
    """Awaiting an async helper that never suspends is NOT a yield point
    (asyncio runs it to completion synchronously) — the HeaderWaiter's
    atomic-tick handlers depend on exactly this."""

    def __init__(self, queue: asyncio.Queue):
        self.queue = queue
        self.pending = {}

    async def run(self):
        spawn(self._other())
        while True:
            probe = len(self.pending)
            await self._handle(probe)
            self.pending[probe] = True

    async def _handle(self, probe):
        self.pending.setdefault(probe, False)

    async def _other(self):
        while True:
            await asyncio.sleep(1.0)
            self.pending.clear()
'''


def test_awaiting_a_nonyielding_helper_is_not_a_window():
    assert fixture_findings(NONYIELDING_AWAIT, "interleave-window") == []


# -- interleave-iteration ------------------------------------------------------

ITER_FLAGGED = '''
import asyncio

from ..utils.tasks import spawn


class Fixture:
    def __init__(self, queue: asyncio.Queue):
        self.queue = queue
        self.waiting = {}

    async def run(self):
        while True:
            await self.queue.get()
            spawn(self._flush())

    async def _flush(self):
        for digest, item in self.waiting.items():
            await self.queue.put(item)
        self.waiting.clear()
'''

ITER_CLEAN = ITER_FLAGGED.replace(
    "self.waiting.items()", "list(self.waiting.items())"
)


def test_iteration_rule_flags_aliased_iteration_spanning_yield():
    found = fixture_findings(ITER_FLAGGED, "interleave-iteration")
    assert len(found) == 1, found
    assert "self.waiting" in found[0].message
    assert "mid-iteration" in found[0].message


def test_iteration_rule_passes_list_snapshots():
    assert fixture_findings(ITER_CLEAN, "interleave-iteration") == []


# -- pragma semantics ----------------------------------------------------------

def test_pragma_with_reason_suppresses_window():
    src = WINDOW_FLAGGED.replace(
        "        staged = self.backlog",
        "        # lint: allow-interleave(fixture: each drain task owns "
        "its snapshot)\n        staged = self.backlog",
    )
    assert fixture_findings(src, "interleave-window") == []


def test_pragma_without_reason_does_not_suppress():
    src = WINDOW_FLAGGED.replace(
        "        staged = self.backlog",
        "        staged = self.backlog  # lint: allow-interleave()",
    )
    found = fixture_findings(src)
    rules = {f.rule for f in found}
    assert "interleave-window" in rules and "pragma" in rules


# -- live tree -----------------------------------------------------------------

def test_live_tree_is_clean_under_interleave_rules():
    findings = [
        f for f in run_lint(REPO) if f.rule.startswith("interleave")
    ]
    assert findings == [], "\n".join(f.render() for f in findings)


def _strip_pragma(path):
    with open(os.path.join(REPO, path), encoding="utf-8") as f:
        src = f.read()
    out = "\n".join(
        line for line in src.splitlines()
        if "lint: allow-interleave(" not in line
    )
    assert out != src, f"no interleave pragma found in {path}"
    return {path: out}


def test_stripping_proposer_pragma_resurfaces_the_window():
    overlay = _strip_pragma("narwhal_tpu/primary/proposer.py")
    found = [
        f for f in run_lint(REPO, overlay=overlay)
        if f.rule == "interleave-window"
        and f.path == "narwhal_tpu/primary/proposer.py"
    ]
    assert found, "the documented Proposer window is no longer detected"
    assert any("deliver_parents" in f.message for f in found)


def test_stripping_store_pragma_resurfaces_the_window():
    overlay = _strip_pragma("narwhal_tpu/store.py")
    found = [
        f for f in run_lint(REPO, overlay=overlay)
        if f.rule == "interleave-window" and f.path == "narwhal_tpu/store.py"
    ]
    assert found and any("_obligations" in f.message for f in found)


def test_mutation_racy_consensus_is_flagged():
    # The SAME overlay the race-explore mutation arm lints: one source of
    # truth between the static catch here and the dynamic catch in
    # benchmark/race_explore.py.
    from benchmark.race_explore import static_mutation_findings

    findings = static_mutation_findings()
    assert findings, "planted RacyConsensus race not flagged"
    assert any("_committing" in f for f in findings)
