"""Multi-host launcher smoke test: a 4-authority committee split across two
LocalRunner "hosts" (separate workdirs, full TCP mesh between them) must
boot, commit, and parse cleanly through the same path an SSH deployment
uses (benchmark/remote_bench.py; reference remote.py:139-311)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmark.remote_bench import run_remote_bench  # noqa: E402


def test_two_host_committee_commits(tmp_path):
    result = run_remote_bench(
        [f"local:{tmp_path}/h0", f"local:{tmp_path}/h1"],
        nodes=4,
        workers=1,
        rate=2_000,
        tx_size=512,
        duration=8,
        base_port=7910,
        quiet=True,
    )
    assert result.errors == []
    assert result.committed_batches > 0
    assert result.consensus_tps > 0
    assert result.samples > 0  # client→batch→commit join worked end-to-end
