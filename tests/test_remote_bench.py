"""Multi-host launcher smoke test: a 4-authority committee split across two
LocalRunner "hosts" (separate workdirs, full TCP mesh between them) must
boot, commit, and parse cleanly through the same path an SSH deployment
uses (benchmark/remote_bench.py; reference remote.py:139-311)."""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmark.remote_bench import run_remote_bench  # noqa: E402


def _dump_scrape_diagnostics(result):
    """On a failed window, print what the live scrape actually saw —
    which nodes answered, how far their rounds/commits got — so a flake
    is diagnosable from the test log instead of needing a re-run."""
    timeline = getattr(result, "timeline", {}) or {}
    print("scraped-metrics diagnostic dump:", file=sys.stderr)
    for node, series in sorted(timeline.get("nodes", {}).items()):
        last = series[-1] if series else {}
        print(
            f"  {node}: {len(series)} samples, last="
            + json.dumps(
                {
                    k: last.get(k)
                    for k in ("round", "commits", "txs_sealed",
                              "health_firing")
                }
            ),
            file=sys.stderr,
        )
    for node, verdict in sorted((timeline.get("healthz") or {}).items()):
        print(f"  healthz {node}: {verdict}", file=sys.stderr)


def _run_committee(tmp_path, **kwargs):
    """One retry on a failed window: these are measurement runs (boot →
    commit for N seconds → parse), and on a shared single core a
    background CPU spike during the window can starve the whole
    committee past its deadlines — a host artifact, not a protocol bug
    (the protocol-level e2e tests in test_e2e.py poll with generous
    deadlines instead and don't need this).  Two layers of defense:
    the window itself widens on wall-clock progress checks over the
    scraped metrics (progress_wait — no commits seen yet means the
    window isn't a measurement at all), and a zero-commit attempt is
    retried once with the scraped time-series dumped as diagnostics.
    A genuine regression fails both attempts."""
    hosts = [f"{tmp_path}/h0", f"{tmp_path}/h1"]
    kwargs.setdefault("progress_wait", 30)
    for attempt in (1, 2):
        result = run_remote_bench(
            [f"local:{h}" for h in hosts], quiet=True, **kwargs
        )
        ok = (
            result.errors == []
            and result.committed_batches > 0
            and result.samples > 0
        )
        if ok or attempt == 2:
            return result
        print(
            f"window {attempt} failed (errors={result.errors!r}, "
            f"committed={result.committed_batches}); retrying",
            file=sys.stderr,
        )
        _dump_scrape_diagnostics(result)


def test_two_host_committee_commits(tmp_path):
    result = _run_committee(
        tmp_path,
        nodes=4,
        workers=1,
        rate=2_000,
        tx_size=512,
        duration=8,
        base_port=7910,
    )
    assert result.errors == []
    assert result.committed_batches > 0
    assert result.consensus_tps > 0
    assert result.samples > 0  # client→batch→commit join worked end-to-end
    # The remote harness now scrapes every node's --metrics-port during
    # the run: the committee timeline must have real samples and no node
    # may end the window with a firing health rule.
    assert result.timeline["nodes"], "remote scrape collected no samples"
    for node, verdict in result.timeline["healthz"].items():
        assert verdict["status"] in (200, None), (node, verdict)


def test_non_collocated_placement_commits(tmp_path):
    """collocate=False: each authority's primary and worker land on
    different "hosts" (reference remote.py:108-130); the primary↔worker
    hop crosses host boundaries and the committee still commits client
    payloads end-to-end."""
    result = _run_committee(
        tmp_path,
        nodes=4,
        workers=1,
        rate=2_000,
        tx_size=512,
        # A slightly longer window than the collocated test above: commits
        # must additionally cross the host boundary on the primary↔worker
        # hop, and on a shared-core CI host an 8 s window has flaked.
        duration=12,
        base_port=7960,
        collocate=False,
        keep_logs=True,
    )
    assert result.errors == []
    assert result.committed_batches > 0
    assert result.samples > 0
    # Structural check of the property in this test's name: with stride
    # 1+workers = 2 over 2 hosts, every primary lands on h0 and every
    # worker on h1 — a placement regression that silently re-collocated
    # them would commit just fine, so assert where the logs ended up.
    for i in range(4):
        assert os.path.exists(f"{tmp_path}/h0/logs/primary-{i}.log")
        assert not os.path.exists(f"{tmp_path}/h1/logs/primary-{i}.log")
        assert os.path.exists(f"{tmp_path}/h1/logs/worker-{i}-0.log")
        assert not os.path.exists(f"{tmp_path}/h0/logs/worker-{i}-0.log")
