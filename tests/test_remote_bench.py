"""Multi-host launcher smoke test: a 4-authority committee split across two
LocalRunner "hosts" (separate workdirs, full TCP mesh between them) must
boot, commit, and parse cleanly through the same path an SSH deployment
uses (benchmark/remote_bench.py; reference remote.py:139-311)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmark.remote_bench import run_remote_bench  # noqa: E402


def test_two_host_committee_commits(tmp_path):
    result = run_remote_bench(
        [f"local:{tmp_path}/h0", f"local:{tmp_path}/h1"],
        nodes=4,
        workers=1,
        rate=2_000,
        tx_size=512,
        duration=8,
        base_port=7910,
        quiet=True,
    )
    assert result.errors == []
    assert result.committed_batches > 0
    assert result.consensus_tps > 0
    assert result.samples > 0  # client→batch→commit join worked end-to-end


def test_non_collocated_placement_commits(tmp_path):
    """collocate=False: each authority's primary and worker land on
    different "hosts" (reference remote.py:108-130); the primary↔worker
    hop crosses host boundaries and the committee still commits client
    payloads end-to-end."""
    hosts = [f"local:{tmp_path}/h{j}" for j in range(2)]
    result = run_remote_bench(
        hosts,
        nodes=4,
        workers=1,
        rate=2_000,
        tx_size=512,
        # A slightly longer window than the collocated test above: commits
        # must additionally cross the host boundary on the primary↔worker
        # hop, and on a shared-core CI host an 8 s window has flaked.
        duration=12,
        base_port=7960,
        quiet=True,
        collocate=False,
        keep_logs=True,
    )
    assert result.errors == []
    assert result.committed_batches > 0
    assert result.samples > 0
    # Structural check of the property in this test's name: with stride
    # 1+workers = 2 over 2 hosts, every primary lands on h0 and every
    # worker on h1 — a placement regression that silently re-collocated
    # them would commit just fine, so assert where the logs ended up.
    for i in range(4):
        assert os.path.exists(f"{tmp_path}/h0/logs/primary-{i}.log")
        assert not os.path.exists(f"{tmp_path}/h1/logs/primary-{i}.log")
        assert os.path.exists(f"{tmp_path}/h1/logs/worker-{i}-0.log")
        assert not os.path.exists(f"{tmp_path}/h0/logs/worker-{i}-0.log")
