"""narwhal-sim acceptance suite (ISSUE 12).

- the virtual clock jumps at quiesce (compression), caps single jumps,
  and bounds deadlocked runs deterministically;
- a clean simulated committee passes all three verdicts, and the same
  (seed, spec) twice produces a BYTE-IDENTICAL deterministic artifact
  (commit sequences + verdicts + events + schedule);
- mutation arms (the PR 8/10 honesty pattern): a planted Byzantine
  behavior is caught by the detection verdict, and the planted
  RacyConsensus shape is caught by a safety verdict under a pinned
  schedule seed — the harness detects what it claims to detect;
- fuzz grows committee-size and duration draws while every draw stays
  schema-valid under the BFT union bound.
"""

import asyncio
import logging
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from narwhal_tpu.faults.fuzz import SIZES, generate  # noqa: E402
from narwhal_tpu.faults.spec import parse_scenario  # noqa: E402
from narwhal_tpu.sim import run_sim_scenario, run_virtual  # noqa: E402
from narwhal_tpu.sim.committee import deterministic_blob  # noqa: E402

logging.disable(logging.WARNING)

# Schedule seed under which the RacyConsensus mutation arm is known to
# diverge for _RACY_SPEC below (sim_bench's mutation arm scans seeds;
# the tier-1 test pins one so it costs a single run).
RACY_PINNED_SEED = 30_000


def _clean_spec(name="sim_t_clean", nodes=4, duration=15, seed=5):
    return parse_scenario({
        "name": name, "nodes": nodes, "workers": 1, "rate": 400,
        "tx_size": 256, "duration": duration, "seed": seed,
    })


# -- virtual clock ------------------------------------------------------------


def test_virtual_clock_compresses_idle_time():
    async def main():
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        await asyncio.sleep(120)
        return loop.time() - t0

    elapsed, stats = run_virtual(main, seed=3)
    assert elapsed == 120
    assert stats["virtual_s"] >= 120
    # 120 idle virtual seconds must cost (far) under a wall second.
    assert stats["wall_s"] < 1.0
    assert stats["jumps"] >= 1


def test_virtual_clock_caps_single_jumps():
    async def main():
        await asyncio.sleep(500)

    _, stats = run_virtual(main, seed=1, max_virtual_s=1_000)
    # Default cap is 60 s/jump: a 500 s gap takes several capped steps.
    assert stats["capped_jumps"] >= 8


def test_virtual_deadlock_guard_is_deterministic():
    async def dead():
        await asyncio.Event().wait()

    import pytest

    for _ in range(2):
        with pytest.raises(asyncio.TimeoutError):
            run_virtual(dead, seed=2, max_virtual_s=5)


def test_virtual_loop_keeps_schedule_exploration():
    async def probe():
        out = []
        gate = asyncio.Event()

        async def worker(i):
            await gate.wait()
            for _ in range(4):
                out.append(i)
                await asyncio.sleep(0)

        tasks = [
            asyncio.get_running_loop().create_task(worker(i))
            for i in range(5)
        ]
        gate.set()
        await asyncio.gather(*tasks)
        return tuple(out)

    orders = {run_virtual(probe, seed=s)[0] for s in range(6)}
    assert len(orders) > 1, "virtual loop lost the exploration axis"
    a = run_virtual(probe, seed=4)[0]
    b = run_virtual(probe, seed=4)[0]
    assert a == b


# -- committee simulation -----------------------------------------------------


def test_clean_committee_passes_all_three_verdicts(tmp_path):
    art = run_sim_scenario(_clean_spec(), 21, str(tmp_path / "clean"))
    v = art["verdicts"]
    assert v["safety"]["ok"], v["safety"]
    assert v["liveness"]["ok"], v["liveness"]
    assert v["detection"]["ok"], v["detection"]
    assert art["ok"]
    # Non-vacuity: the run committed real payload and explored schedules.
    assert all(
        n["payload_commits_post_settle"] > 0
        for n in v["liveness"]["nodes"].values()
    )
    assert art["schedule"]["permutations"] > 100
    assert art["schedule"]["virtual_s"] >= 15


def test_same_seed_spec_is_bit_reproducible(tmp_path):
    """The repro contract: same (seed, spec) → byte-identical commit
    sequences AND verdict artifacts across two runs."""
    a = run_sim_scenario(_clean_spec(), 22, str(tmp_path / "a"))
    b = run_sim_scenario(_clean_spec(), 22, str(tmp_path / "b"))
    assert deterministic_blob(a) == deterministic_blob(b)
    assert a["commit_sequences"] == b["commit_sequences"]


def test_planted_byzantine_is_detected_without_being_expected(tmp_path):
    """Honesty arm: an equivocating primary with NO expect.rules still
    lights up the equivocation rule — detection is measurement, not
    self-fulfilling configuration."""
    spec = parse_scenario({
        "name": "sim_t_eq", "nodes": 4, "workers": 1, "rate": 400,
        "tx_size": 256, "duration": 20, "seed": 3,
        "byzantine": [{"node": 1, "behaviors": ["equivocate"]}],
    })
    art = run_sim_scenario(spec, 23, str(tmp_path / "eq"))
    assert "equivocation" in art["verdicts"]["detection"]["fired"]
    # And safety holds: equivocation must never doubly commit.
    assert art["verdicts"]["safety"]["ok"], art["verdicts"]["safety"]
    # Per-node attribution (PR 15): the verdict names WHICH validators
    # observed the evidence — honest peers, never the adversary itself
    # (it holds only its own statements, no conflicting pair).
    observers = art["verdicts"]["detection"]["observers"].get(
        "equivocation", []
    )
    assert observers, art["verdicts"]["detection"]
    assert "primary-1" not in observers
    assert all(o.startswith("primary-") for o in observers)


_RACY_SPEC = {
    "name": "sim_mut_racy", "nodes": 4, "workers": 1, "rate": 600,
    "tx_size": 256, "duration": 15, "seed": 7_000 ^ 0xACE,
}


def test_planted_racy_consensus_fails_a_safety_verdict(tmp_path):
    """The other honesty arm: node 0 running the PR 10 found-race shape
    must produce a golden-replay/prefix violation under the pinned
    schedule seed — a sim harness that cannot catch a planted race is
    dead weight."""
    from benchmark.race_explore import RacyConsensus

    art = run_sim_scenario(
        parse_scenario(_RACY_SPEC, env={}), RACY_PINNED_SEED,
        str(tmp_path / "racy"),
        consensus_cls_by_node={0: RacyConsensus},
    )
    assert not art["verdicts"]["safety"]["ok"], (
        "planted RacyConsensus was not caught at the pinned seed — "
        "the sim harness's safety verdict went blind"
    )


@pytest.mark.parametrize("rule", ["classic", "lowdepth"])
def test_planted_corruption_fails_safety_under_both_rules(tmp_path, rule):
    """The deterministic honesty arm (ISSUE 15): node 0 running
    ``CorruptingConsensus`` (one dropped + one re-committed certificate)
    must fail the safety verdict on the FIRST schedule under EITHER
    commit rule — the proof that each arm of a flag-flip sweep judges
    its sequences against its own oracle, which the schedule-dependent
    racy plant cannot give for lowdepth (its await-window race needs
    classic's deep commit backlogs to manifest at sim exploration
    intensity)."""
    from benchmark.sim_bench import CorruptingConsensus

    spec = {
        "name": "sim_mut_corrupt", "nodes": 4, "workers": 1, "rate": 600,
        "tx_size": 256, "duration": 15, "seed": 7_000 ^ 0xC0DE,
    }
    art = run_sim_scenario(
        parse_scenario(spec, env={}), 29_000,
        str(tmp_path / "corrupt"),
        consensus_cls_by_node={0: CorruptingConsensus},
        commit_rule=rule,
    )
    safety = art["verdicts"]["safety"]
    assert not safety["ok"], (
        f"planted sequence corruption was not caught under {rule} — "
        "the arm's oracle is not judging its own sequences"
    )
    violations = [
        v
        for nv in safety["nodes"].values()
        for v in nv.get("violations", [])
    ]
    assert any("committed twice" in v or "diverges" in v
               for v in violations), violations


def test_crash_restart_authority_recovers(tmp_path):
    """Crash/restart plane: the restarted authority (retained in-memory
    store, fresh audit segment) rejoins and keeps committing; the
    peer_unreachable rule names the outage."""
    spec = parse_scenario({
        "name": "sim_t_crash", "nodes": 4, "workers": 1, "rate": 400,
        "tx_size": 256, "duration": 30, "seed": 9,
        "crash": [{"node": 2, "at_s": 8, "restart_at_s": 14}],
        "env": {"NARWHAL_NET_BACKOFF_MAX_S": "2"},
        "expect": {"rules": ["peer_unreachable"]},
    })
    art = run_sim_scenario(spec, 25, str(tmp_path / "crash"))
    v = art["verdicts"]
    assert v["safety"]["ok"], v["safety"]
    assert v["liveness"]["ok"], v["liveness"]
    assert "peer_unreachable" in v["detection"]["fired"]
    # Two audit segments for the crashed node: one per incarnation.
    assert v["safety"]["nodes"]["primary-2"]["segments"] == 2


def test_committee_at_scale_compresses(tmp_path):
    """An N=10 committee's 20 virtual seconds execute well under wall
    real time — the committee-at-scale axis the socketed harness cannot
    reach.  The bound is loose (shared CI cores); the real compression
    gate lives in sim_bench's acceptance arm."""
    spec = _clean_spec(name="sim_t_n10", nodes=10, duration=20, seed=4)
    art = run_sim_scenario(spec, 26, str(tmp_path / "n10"))
    assert art["ok"], art["verdicts"]
    assert art["schedule"]["virtual_s"] >= 20
    assert art["wall"]["compression"] and art["wall"]["compression"] > 1.0


# -- fuzz growth --------------------------------------------------------------


def test_fuzz_draws_cover_sizes_and_durations():
    sizes = set()
    durations = set()
    for seed in range(120):
        obj = generate(seed)
        sizes.add(obj["nodes"])
        durations.add(obj["duration"])
        s = parse_scenario(obj, env={})  # schema + BFT bound revalidate
        f_tol = (s.nodes - 1) // 3
        faulted = set(s.byzantine_nodes()) | {c.node for c in s.crash}
        assert len(faulted) <= f_tol
    assert sizes == set(SIZES), f"size pool not covered: {sizes}"
    assert len(durations) > 2, "duration draw is constant"


def test_fuzz_size_pool_is_prunable():
    for seed in (0, 1, 2):
        obj = generate(seed, sizes=(4,))
        assert obj["nodes"] == 4
        parse_scenario(obj, env={})


def test_per_size_spec_fixtures_are_valid():
    import json

    for n in SIZES:
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "benchmark", "scenarios", f"fuzz_n{n}.spec.json",
        )
        with open(path) as f:
            obj = json.load(f)
        assert obj["nodes"] == n
        s = parse_scenario(obj, env={})
        assert s.byzantine and s.expect_rules
