"""MultiLeaderTusk vs its frozen oracle (consensus/golden_multileader.py).

The multileader rule CHANGES the commit sequence by design (K leader
slots per even round, slot-ordered anchor scan), so it gets its own
golden oracle and the full PR 4 replay/fuzz discipline: reference
scenarios, the quorum-starved burst shape, gc-window wrap, checkpoint
restore, and randomized DAGs (in-order and out-of-order delivery) must
be byte-identical between the live indexed rule and the naive dict-walk
oracle — under the pinned test coin AND under the real round-salted
schedule, which live rule and oracle each derive independently.

Alongside the equivalence suite this file pins the ISSUE 19 satellites:
slot-schedule determinism across processes (a subprocess with a
different PYTHONHASHSEED derives the identical schedule), slot-0
fairness (no authority out of slot 0 for more than committee_size
consecutive even rounds), the six-direction cross-rule checkpoint
refusal (classic/lowdepth/multileader, both ways each), flag plumbing,
the kernel refusal, and the per-segment audit rule marker with its
lying-marker counterpart.
"""

import asyncio
import os
import random
import subprocess
import sys

import pytest

from narwhal_tpu.consensus import (
    CheckpointRuleMismatch,
    Consensus,
    LowDepthTusk,
    MultiLeaderTusk,
    Tusk,
    leader_slots,
    resolve_commit_rule,
)
from narwhal_tpu.consensus.golden_multileader import GoldenMultiLeaderTusk
from narwhal_tpu.consensus.replay import read_audit, replay_segments, TAG_RULE
from narwhal_tpu.consensus.tusk import MULTILEADER_SLOTS
from tests.common import committee
from tests.test_consensus import (
    feed,
    genesis_digests,
    make_certificates,
    mock_certificate,
    sorted_names,
)
from tests.test_tusk_equivalence import _random_dag_certs


def both_walks(certs, gc_depth=50, fixed_coin=True):
    """Feed the identical delivery order through the frozen multileader
    oracle and the live indexed rule; assert byte-identical sequences."""
    c = committee()
    golden = feed(
        GoldenMultiLeaderTusk(c, gc_depth=gc_depth, fixed_coin=fixed_coin),
        certs,
    )
    live = feed(
        MultiLeaderTusk(c, gc_depth=gc_depth, fixed_coin=fixed_coin), certs
    )
    assert [bytes(x.digest()) for x in live] == [
        bytes(x.digest()) for x in golden
    ]
    return golden


def _ml_burst(rounds=12):
    """The multileader worst-case burst: rounds delivered ascending but
    every odd (support) round quorum-STARVED at 2f stake, so each even
    round's slots stay undecided (never dead — the non-supporting stake
    is withheld, not opposed) and nothing commits; the single withheld
    round-(rounds-1) support certificate is the trigger that flattens
    the whole chain in one process_certificate call."""
    c = committee()
    names = sorted_names()
    quorum = c.quorum_threshold()
    parents = genesis_digests(c)
    order, trigger = [], None
    for r in range(1, rounds + 1):
        nxt = set()
        stake = 0
        for name in names:
            digest, cert = mock_certificate(name, r, parents)
            nxt.add(digest)
            if r % 2 == 0:
                order.append(cert)
            elif stake + c.stake(name) < quorum:
                order.append(cert)
                stake += c.stake(name)
            elif trigger is None and r == rounds - 1:
                trigger = cert
        parents = nxt
    assert trigger is not None
    return order, trigger


def test_reference_scenarios_equivalence():
    """The reference consensus_tests.rs stream shapes, multileader live
    vs multileader oracle — plus the depth claim: the direct anchor
    fires at the round-3 support quorum, before classic's round-5
    trigger ever arrives."""
    c = committee()
    names = sorted_names()

    # commit_one's stream: rounds 1..4 + the round-5 trigger.
    certs, next_parents = make_certificates(1, 4, genesis_digests(c), names)
    _, trigger = mock_certificate(names[0], 5, next_parents)
    committed = both_walks(certs + [trigger])
    assert committed, "commit_one stream must commit under multileader"
    early = MultiLeaderTusk(c, gc_depth=50, fixed_coin=True)
    first_commit_at = next(
        i for i, cert in enumerate(certs) if early.process_certificate(cert)
    )
    assert first_commit_at < len(certs) - 1, (
        "multileader must anchor before the stream (let alone the "
        "round-5 trigger) ends"
    )
    assert early.last_anchor == (2, 0)

    # dead_node: one authority silent for the whole run.
    certs, _ = make_certificates(1, 9, genesis_digests(c), names[:3])
    assert both_walks(certs)

    # missing_leader: the slot-0 authority idle for rounds 1-2.
    certs = []
    out, parents = make_certificates(1, 2, genesis_digests(c), names[1:])
    certs.extend(out)
    out, parents = make_certificates(3, 6, parents, names)
    certs.extend(out)
    _, trigger = mock_certificate(names[0], 7, parents)
    both_walks(certs + [trigger])


def test_backup_slot_rescues_dead_slot_zero():
    """The multileader mechanism itself: an even round whose slot-0
    leader never produced is provably DEAD (full child stake, zero
    support), so the scan anchors on slot 1 — a round classic (and
    lowdepth) can only reach indirectly, if at all."""
    c = committee()
    names = sorted_names()
    certs = []
    out, parents = make_certificates(1, 3, genesis_digests(c), names)
    certs.extend(out)
    # Round 4 without the fixed-coin slot-0 authority (names[0]).
    out, parents = make_certificates(4, 4, parents, names[1:])
    certs.extend(out)
    out, parents = make_certificates(5, 8, parents, names)
    certs.extend(out)
    got = both_walks(certs)
    live = MultiLeaderTusk(c, gc_depth=50, fixed_coin=True)
    anchors = []
    for cert in certs:
        if live.process_certificate(cert):
            anchors.append(live.last_anchor)
    assert (4, 1) in anchors, anchors
    assert any(
        x.round == 4 and x.header.author == names[1] for x in got
    ), "the slot-1 leader of the dead-slot-0 round must be committed"


def test_quorum_starved_burst_equivalence():
    """Nothing commits while every support round sits at 2f stake; the
    single withheld support certificate then commits the entire chain —
    and the burst must match the oracle's byte-for-byte."""
    c = committee()
    order, trigger = _ml_burst(rounds=12)
    live = MultiLeaderTusk(c, gc_depth=50, fixed_coin=True)
    for cert in order:
        assert live.process_certificate(cert) == [], (
            "quorum-starved stream must not commit before the trigger"
        )
    burst = live.process_certificate(trigger)
    assert len({x.round for x in burst if x.round % 2 == 0}) >= 4
    both_walks(order + [trigger])


def test_gc_window_wrap_equivalence():
    """Continuous commits across several multiples of a small gc window:
    end-state parity, not just sequence parity."""
    c = committee()
    names = sorted_names()
    certs, _ = make_certificates(1, 30, genesis_digests(c), names)
    golden = GoldenMultiLeaderTusk(c, gc_depth=6, fixed_coin=True)
    live = MultiLeaderTusk(c, gc_depth=6, fixed_coin=True)
    got_g = feed(golden, certs)
    got_l = feed(live, certs)
    assert [bytes(x.digest()) for x in got_l] == [
        bytes(x.digest()) for x in got_g
    ]
    assert got_g, "fixture must commit"
    assert live.state.last_committed == golden.state.last_committed
    assert live.state.last_committed_round == golden.state.last_committed_round
    assert {
        r: set(v) for r, v in live.state.dag.items()
    } == {r: set(v) for r, v in golden.state.dag.items()}


def test_checkpoint_restore_equivalence():
    """Both multileader walks restored from the same frontier blob ignore
    a full catch-up replay and then commit new rounds byte-identically."""
    c = committee()
    names = sorted_names()
    certs, next_parents = make_certificates(1, 4, genesis_digests(c), names)
    _, trigger = mock_certificate(names[0], 5, next_parents)

    first = GoldenMultiLeaderTusk(c, gc_depth=50, fixed_coin=True)
    assert feed(first, certs + [trigger])
    blob = first.state.snapshot_bytes()
    assert blob[:6] == b"NCKML1"

    golden = GoldenMultiLeaderTusk(c, gc_depth=50, fixed_coin=True)
    golden.state.restore(blob)
    live = MultiLeaderTusk(c, gc_depth=50, fixed_coin=True)
    live.state.restore(blob)
    assert feed(golden, certs + [trigger]) == []
    assert feed(live, certs + [trigger]) == []

    more, tail_parents = make_certificates(5, 8, next_parents, names)
    more = more[1:]  # round-5 leader already exists as `trigger`
    _, trigger2 = mock_certificate(names[0], 9, tail_parents)
    got = feed(live, more + [trigger2])
    want = feed(golden, more + [trigger2])
    assert [bytes(x.digest()) for x in got] == [
        bytes(x.digest()) for x in want
    ]
    assert got, "the restored instances must keep committing"


def test_fuzz_equivalence_in_and_out_of_order():
    rng = random.Random(0x311)
    for trial in range(6):
        certs = _random_dag_certs(rng, rounds=rng.randint(6, 20))
        order = list(certs)
        order.sort(key=lambda x: (x.round, rng.random()))
        both_walks(order)
    for trial in range(4):
        certs = _random_dag_certs(rng, rounds=rng.randint(6, 16))
        order = list(certs)
        # Children ahead of their parents in delivery order.
        order.sort(key=lambda x: x.round + rng.uniform(-2.2, 0.0))
        both_walks(order)


def test_fuzz_small_gc_depth_equivalence():
    rng = random.Random(0x31C)
    for _ in range(3):
        both_walks(_random_dag_certs(rng, rounds=14), gc_depth=4)


def test_real_salt_schedule_equivalence():
    """With the round-salted schedule live (fixed_coin=False) the oracle
    and the indexed rule derive the slot permutation INDEPENDENTLY (the
    oracle carries its own frozen copy of the schedule function) — they
    must still agree byte-for-byte on dense and fuzzed streams."""
    c = committee()
    names = sorted_names()
    certs, _ = make_certificates(1, 20, genesis_digests(c), names)
    assert both_walks(certs, fixed_coin=False)
    rng = random.Random(0x5A1)
    for _ in range(4):
        order = _random_dag_certs(rng, rounds=rng.randint(8, 16))
        order.sort(key=lambda x: (x.round, rng.random()))
        both_walks(order, fixed_coin=False)


def test_prefix_consistency_across_delivery_orders():
    """Two nodes seeing the same DAG in different (causally valid)
    orders must never commit conflicting sequences: one's commit
    sequence is a prefix of the other's.  This is the safety property
    the undecided-slot scan stop exists for."""
    rng = random.Random(0xC04E)
    c = committee()
    for _ in range(5):
        certs = _random_dag_certs(rng, rounds=rng.randint(8, 18))
        a_order = sorted(certs, key=lambda x: (x.round, rng.random()))
        b_order = sorted(certs, key=lambda x: (x.round, rng.random()))
        a = feed(MultiLeaderTusk(c, gc_depth=50), a_order)
        b = feed(MultiLeaderTusk(c, gc_depth=50), b_order)
        a_d = [bytes(x.digest()) for x in a]
        b_d = [bytes(x.digest()) for x in b]
        n = min(len(a_d), len(b_d))
        assert a_d[:n] == b_d[:n], "commit sequences forked"


def test_multileader_commits_ahead_of_classic():
    """The latency mechanism, pinned structurally: on one round-ordered
    full stream the multileader frontier is NEVER behind classic (the
    slot-0 anchor fires at depth 1, on the support quorum), and the
    classic sequence is a strict prefix of the multileader one — the
    rule commits more, earlier, without reordering what classic
    commits."""
    c = committee()
    names = sorted_names()
    certs, _ = make_certificates(1, 20, genesis_digests(c), names)
    classic = Tusk(c, gc_depth=50, fixed_coin=True)
    ml = MultiLeaderTusk(c, gc_depth=50, fixed_coin=True)
    seq_classic, seq_ml = [], []
    for cert in certs:
        seq_classic.extend(classic.process_certificate(cert))
        seq_ml.extend(ml.process_certificate(cert))
        assert (
            ml.state.last_committed_round
            >= classic.state.last_committed_round
        ), "multileader frontier must never trail classic"
    a = [bytes(x.digest()) for x in seq_classic]
    b = [bytes(x.digest()) for x in seq_ml]
    assert len(b) > len(a)
    assert b[: len(a)] == a


# -- slot schedule (ISSUE 19 satellite: determinism + fairness) ----------------


def test_slot_schedule_shape():
    """K slots, no duplicates, fixed_coin pins the first K sorted
    authorities — on every even round."""
    names = sorted_names()
    for r in range(0, 40, 2):
        slots = leader_slots(names, r)
        assert len(slots) == min(len(names), MULTILEADER_SLOTS)
        assert len(set(slots)) == len(slots)
        assert set(slots) <= set(names)
        assert leader_slots(names, r, fixed_coin=True) == names[
            :MULTILEADER_SLOTS
        ]


def test_slot_schedule_deterministic_across_processes():
    """Same committee + round ⇒ same slot permutation in a DIFFERENT
    process with a different PYTHONHASHSEED — the schedule must depend
    on nothing but (sorted keys, round), or two nodes (or one node
    across a restart) would anchor on different slots and fork."""
    names = sorted_names()
    local = "|".join(
        ",".join(str(x) for x in leader_slots(names, r))
        for r in range(0, 81, 2)
    )
    script = (
        "import sys; sys.path.insert(0, %r)\n"
        "from tests.common import keys\n"
        "from narwhal_tpu.consensus import leader_slots\n"
        "names = sorted(kp.name for kp in keys())\n"
        "print('|'.join(','.join(str(x) for x in leader_slots(names, r))\n"
        "      for r in range(0, 81, 2)))\n"
    ) % os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for hashseed in ("0", "31337"):
        env = dict(os.environ)
        env.update({"PYTHONHASHSEED": hashseed, "JAX_PLATFORMS": "cpu"})
        out = subprocess.run(
            [sys.executable, "-c", script],
            capture_output=True, text=True, env=env, timeout=120,
        )
        assert out.returncode == 0, out.stderr
        assert out.stdout.strip().splitlines()[-1] == local


def test_slot_zero_fairness():
    """No authority is absent from slot 0 for more than committee_size
    consecutive even rounds: slot 0 rotates, so over any n consecutive
    even rounds every authority holds it exactly once — the salt only
    shuffles the BACKUP slots."""
    names = sorted_names()
    n = len(names)
    last_seen = {name: None for name in names}
    worst = 0
    for i, r in enumerate(range(0, 2 * 25 * n, 2)):
        head = leader_slots(names, r)[0]
        if last_seen[head] is not None:
            worst = max(worst, i - last_seen[head])
        last_seen[head] = i
    assert set(last_seen.values()) != {None}
    assert all(v is not None for v in last_seen.values()), (
        "every authority must hold slot 0"
    )
    assert worst <= n, f"slot-0 starvation: {worst} even rounds between turns"


# -- flag plumbing -------------------------------------------------------------


def run_consensus(tmp_path, certs, want, name, **kwargs):
    """Drive a Consensus instance over `certs`; assert the output equals
    `want`; return the audit segment path."""
    audit = os.path.join(str(tmp_path), f"{name}.audit.bin")

    async def go():
        rx, tx_primary, tx_output = (
            asyncio.Queue(), asyncio.Queue(), asyncio.Queue(),
        )
        cons = Consensus(
            committee(), 50, rx, tx_primary, tx_output,
            fixed_coin=True, audit_path=audit, **kwargs,
        )
        for cert in certs:
            rx.put_nowait(cert)
        task = asyncio.ensure_future(cons.run())
        out = [
            await asyncio.wait_for(tx_output.get(), 5) for _ in range(len(want))
        ]
        task.cancel()
        await asyncio.gather(task, return_exceptions=True)
        cons._audit.close()
        assert [bytes(x.digest()) for x in out] == [
            bytes(x.digest()) for x in want
        ]
        return cons

    cons = asyncio.run(asyncio.wait_for(go(), 15))
    return audit, cons


def _stream():
    c = committee()
    names = sorted_names()
    certs, next_parents = make_certificates(1, 8, genesis_digests(c), names)
    _, trigger = mock_certificate(names[0], 9, next_parents)
    return certs + [trigger]


def test_env_and_arg_select_multileader(tmp_path, monkeypatch):
    """The env knob selects multileader; the constructor arg (the CLI
    path) beats a contradicting env."""
    certs = _stream()
    c = committee()

    monkeypatch.setenv("NARWHAL_COMMIT_RULE", "multileader")
    assert resolve_commit_rule() == "multileader"
    want = feed(GoldenMultiLeaderTusk(c, 50, fixed_coin=True), certs)
    _, cons = run_consensus(tmp_path, certs, want, "env")
    assert isinstance(cons.tusk, MultiLeaderTusk)
    assert cons.commit_rule == "multileader"

    monkeypatch.setenv("NARWHAL_COMMIT_RULE", "classic")
    want = feed(GoldenMultiLeaderTusk(c, 50, fixed_coin=True), certs)
    _, cons = run_consensus(
        tmp_path, certs, want, "arg-wins", commit_rule="multileader"
    )
    assert isinstance(cons.tusk, MultiLeaderTusk)
    assert resolve_commit_rule("multileader") == "multileader"


def test_kernel_refuses_multileader(tmp_path):
    with pytest.raises(ValueError, match="classic walk only"):
        Consensus(
            committee(), 50,
            asyncio.Queue(), asyncio.Queue(), asyncio.Queue(),
            use_kernel=True, commit_rule="multileader",
        )


def test_checkpoint_refuses_cross_rule_restore_all_six(tmp_path):
    """A checkpoint written under any rule must refuse — loudly, naming
    BOTH rules, NOT via the torn-file fresh-frontier fallback — to
    restore under either other rule: classic↔lowdepth↔multileader, all
    six directions.  Same-rule restore stays fine."""
    c = committee()
    makers = {
        "classic": lambda: Tusk(c, 50, fixed_coin=True),
        "lowdepth": lambda: LowDepthTusk(c, 50, fixed_coin=True),
        "multileader": lambda: MultiLeaderTusk(c, 50, fixed_coin=True),
    }
    blobs = {}
    for rule, make in makers.items():
        writer = make()
        feed(writer, _stream())
        assert writer.state.last_committed_round > 0
        path = os.path.join(str(tmp_path), f"ckpt-{rule}.consensus.ckpt")
        with open(path, "wb") as f:
            f.write(writer.state.snapshot_bytes())
        blobs[rule] = (path, writer.state.last_committed_round)
    directions = 0
    for writer_rule, (path, frontier) in blobs.items():
        for reader_rule in makers:
            if reader_rule == writer_rule:
                cons = Consensus(
                    c, 50,
                    asyncio.Queue(), asyncio.Queue(), asyncio.Queue(),
                    fixed_coin=True,
                    checkpoint_path=path,
                    commit_rule=reader_rule,
                )
                assert cons.tusk.state.last_committed_round == frontier
                continue
            with pytest.raises(CheckpointRuleMismatch) as exc:
                Consensus(
                    c, 50,
                    asyncio.Queue(), asyncio.Queue(), asyncio.Queue(),
                    fixed_coin=True,
                    checkpoint_path=path,
                    commit_rule=reader_rule,
                )
            # The refusal must name both rules — the operator flipped
            # the flag on a live store and needs to know which way.
            assert repr(writer_rule) in str(exc.value)
            assert repr(reader_rule) in str(exc.value)
            directions += 1
    assert directions == 6


def test_audit_rule_marker_judged_per_segment(tmp_path):
    """A multileader audit segment records its rule and the replay judge
    picks the multileader oracle for it — while the same recording
    re-tagged classic fails its replay (the multileader recording
    commits a leader round the classic oracle never reaches on the
    trigger-less stream)."""
    c = committee()
    certs = _stream()

    want_ml = feed(GoldenMultiLeaderTusk(c, 50, fixed_coin=True), certs)
    audit_ml, _ = run_consensus(
        tmp_path, certs, want_ml, "seg-ml", commit_rule="multileader"
    )
    records = read_audit(audit_ml)
    assert records[1] == (TAG_RULE, b"multileader")
    verdict = replay_segments(c, 50, [audit_ml], fixed_coin=True)
    assert verdict["ok"], verdict["violations"]
    assert verdict["rules"] == ["multileader"]

    body = _stream()[:-1]
    want_tail = feed(GoldenMultiLeaderTusk(c, 50, fixed_coin=True), body)
    audit_tail, _ = run_consensus(
        tmp_path, body, want_tail, "seg-tail", commit_rule="multileader"
    )
    from narwhal_tpu.consensus.golden import GoldenTusk

    classic_replay = feed(GoldenTusk(c, 50, fixed_coin=True), body)
    assert len(want_tail) > len(classic_replay)
    lying = os.path.join(str(tmp_path), "seg-lying.audit.bin")
    with open(audit_tail, "rb") as f:
        blob = f.read()
    with open(lying, "wb") as f:
        f.write(
            blob.replace(
                b"M\x0b\x00\x00\x00multileader",
                b"M\x07\x00\x00\x00classic",
                1,
            )
        )
    verdict = replay_segments(c, 50, [lying], fixed_coin=True)
    assert not verdict["ok"]
    assert verdict["rules"] == ["classic"]
