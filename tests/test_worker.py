"""Worker pipeline tests, mirroring the reference worker crate's coverage:
seal by size and by timeout, quorum over real ACKs, processor hash+store+
forward, sync request emission, helper replies, and the full worker e2e
(txs in → digest at fake primary).  Fake peers are real TCP listeners in the
same process (SURVEY.md §4)."""

import asyncio

import pytest

from narwhal_tpu.config import Parameters
from narwhal_tpu.crypto import digest32
from narwhal_tpu.messages import (
    decode_worker_message,
    decode_worker_primary_message,
    encode_batch,
)
from narwhal_tpu.network import Receiver
from narwhal_tpu.store import Store
from narwhal_tpu.worker import Worker
from narwhal_tpu.worker.batch_maker import BatchMaker
from narwhal_tpu.worker.helper import Helper
from narwhal_tpu.worker.processor import Processor
from narwhal_tpu.worker.quorum_waiter import QuorumWaiter
from narwhal_tpu.worker.synchronizer import Synchronizer

from tests.common import (
    RecordingAckHandler,
    batch,
    batch_digest,
    committee,
    keys,
    serialized_batch,
    transaction,
)


@pytest.fixture
def run():
    def _run(coro):
        return asyncio.run(asyncio.wait_for(coro, 20))

    return _run


async def spawn_peer_listeners(c, myself, worker_id=0, ack=True):
    """Bind RecordingAckHandlers on every other authority's same-id
    worker_to_worker address."""
    handlers = []
    receivers = []
    for _, addrs in c.others_workers(myself, worker_id):
        h = RecordingAckHandler(ack=ack)
        receivers.append(await Receiver.spawn(addrs.worker_to_worker, h))
        handlers.append(h)
    return handlers, receivers


async def connect_and_send(maker, txs):
    """Open a client connection to the maker's tx socket and write frames."""
    from narwhal_tpu.network.framing import write_frame

    await maker.started.wait()
    host, port = maker.address.rsplit(":", 1)
    _, w = await asyncio.open_connection(host, int(port))
    for tx in txs:
        await write_frame(w, tx)
    return w


def test_batch_maker_seals_by_size(run):
    async def go():
        c = committee(base_port=11000)
        me = keys()[0].name
        handlers, receivers = await spawn_peer_listeners(c, me)
        out_q = asyncio.Queue()
        maker = BatchMaker(me, 0, c, batch_size=200, max_batch_delay_ms=10_000,
                           address=c.worker(me, 0).transactions, out_queue=out_q)
        task = asyncio.ensure_future(maker.run())
        w = await connect_and_send(maker, [transaction(), transaction()])
        digest, serialized, quorum_handlers = await asyncio.wait_for(
            out_q.get(), 5
        )
        w.close()
        kind, decoded = decode_worker_message(serialized)
        assert kind == "batch" and decoded == [transaction(), transaction()]
        assert digest == digest32(serialized)
        assert len(quorum_handlers) == 3  # one ACK future per other authority
        task.cancel()
        maker.sender.close()
        for r in receivers:
            await r.shutdown()

    run(go())


def test_batch_maker_seals_by_timeout(run):
    async def go():
        c = committee(base_port=11020)
        me = keys()[0].name
        handlers, receivers = await spawn_peer_listeners(c, me)
        out_q = asyncio.Queue()
        maker = BatchMaker(me, 0, c, batch_size=1_000_000, max_batch_delay_ms=50,
                           address=c.worker(me, 0).transactions, out_queue=out_q)
        task = asyncio.ensure_future(maker.run())
        w = await connect_and_send(maker, [transaction()])
        _, serialized, _ = await asyncio.wait_for(out_q.get(), 5)
        w.close()
        kind, decoded = decode_worker_message(serialized)
        assert kind == "batch" and decoded == [transaction()]
        task.cancel()
        maker.sender.close()
        for r in receivers:
            await r.shutdown()

    run(go())


def test_quorum_waiter_releases_at_2f1(run):
    async def go():
        c = committee(base_port=11040)
        me = keys()[0].name
        handlers, receivers = await spawn_peer_listeners(c, me)
        to_quorum, released = asyncio.Queue(), asyncio.Queue()
        maker = BatchMaker(me, 0, c, batch_size=200, max_batch_delay_ms=10_000,
                           address=c.worker(me, 0).transactions, out_queue=to_quorum)
        waiter = QuorumWaiter(me, c, to_quorum, released)
        t1 = asyncio.ensure_future(maker.run())
        t2 = asyncio.ensure_future(waiter.run())
        w = await connect_and_send(maker, [transaction(), transaction()])
        _, serialized = await asyncio.wait_for(released.get(), 10)
        w.close()
        assert decode_worker_message(serialized)[0] == "batch"
        # All three peers eventually saw the broadcast.
        for h in handlers:
            await asyncio.wait_for(h.arrived.wait(), 5)
        for t in (t1, t2):
            t.cancel()
        maker.sender.close()
        for r in receivers:
            await r.shutdown()

    run(go())


def test_processor_hashes_stores_forwards(run):
    async def go():
        store = Store()
        in_q, out_q = asyncio.Queue(), asyncio.Queue()
        proc = Processor(3, store, in_q, out_q, own_digests=True)
        task = asyncio.ensure_future(proc.run())
        await in_q.put(serialized_batch())
        msg = await asyncio.wait_for(out_q.get(), 5)
        decoded = decode_worker_primary_message(msg)
        assert decoded.digest == batch_digest()
        assert decoded.worker_id == 3 and decoded.ours
        assert store.read(bytes(batch_digest())) == serialized_batch()
        task.cancel()

    run(go())


def test_synchronizer_sends_batch_request(run):
    async def go():
        c = committee(base_port=11060)
        me, target = keys()[0].name, keys()[1].name
        h = RecordingAckHandler()
        recv = await Receiver.spawn(c.worker(target, 0).worker_to_worker, h)
        in_q = asyncio.Queue()
        sync = Synchronizer(me, 0, c, Store(), 5_000, 3, in_q)
        task = asyncio.ensure_future(sync.run())
        missing = batch_digest()
        await in_q.put(("synchronize", [missing], target))
        await asyncio.wait_for(h.arrived.wait(), 5)
        kind, digests, requestor = decode_worker_message(h.received[0])
        assert kind == "batch_request" and digests == [missing] and requestor == me
        task.cancel()
        sync.sender.close()
        await recv.shutdown()

    run(go())


def test_synchronizer_skips_stored_batches(run):
    async def go():
        c = committee(base_port=11080)
        me, target = keys()[0].name, keys()[1].name
        store = Store()
        store.write(bytes(batch_digest()), serialized_batch())
        h = RecordingAckHandler()
        recv = await Receiver.spawn(c.worker(target, 0).worker_to_worker, h)
        in_q = asyncio.Queue()
        sync = Synchronizer(me, 0, c, store, 5_000, 3, in_q)
        task = asyncio.ensure_future(sync.run())
        await in_q.put(("synchronize", [batch_digest()], target))
        await asyncio.sleep(0.3)
        assert h.received == []  # already stored: no request goes out
        task.cancel()
        sync.sender.close()
        await recv.shutdown()

    run(go())


def test_helper_replies_with_batches(run):
    async def go():
        c = committee(base_port=11100)
        me, requestor = keys()[0].name, keys()[1].name
        store = Store()
        store.write(bytes(batch_digest()), serialized_batch())
        h = RecordingAckHandler()
        recv = await Receiver.spawn(c.worker(requestor, 0).worker_to_worker, h)
        in_q = asyncio.Queue()
        helper = Helper(0, c, store, in_q)
        task = asyncio.ensure_future(helper.run())
        await in_q.put(([batch_digest()], requestor))
        await asyncio.wait_for(h.arrived.wait(), 5)
        assert h.received == [serialized_batch()]
        task.cancel()
        helper.sender.close()
        await recv.shutdown()

    run(go())


def test_worker_end_to_end(run):
    """Client txs in → sealed batch broadcast + quorum → digest at our fake
    primary (reference worker_tests.rs:94-130)."""

    async def go():
        c = committee(base_port=11120)
        me = keys()[0].name
        handlers, receivers = await spawn_peer_listeners(c, me)
        primary_handler = RecordingAckHandler(ack=False)
        primary_recv = await Receiver.spawn(
            c.primary(me).worker_to_primary, primary_handler
        )
        params = Parameters(batch_size=200, max_batch_delay=10_000)
        worker = await Worker.spawn(me, 0, c, params, Store())

        # Drive transactions into the worker's client socket.
        from narwhal_tpu.network.framing import write_frame

        host, port = c.worker(me, 0).transactions.rsplit(":", 1)
        _, w = await asyncio.open_connection(host, int(port))
        txs = [transaction(), transaction()]
        for tx in txs:
            await write_frame(w, tx)

        await asyncio.wait_for(primary_handler.arrived.wait(), 10)
        decoded = decode_worker_primary_message(primary_handler.received[0])
        assert decoded.ours and decoded.worker_id == 0
        expected = digest32(encode_batch(txs))
        assert decoded.digest == expected
        w.close()
        await worker.shutdown()
        await primary_recv.shutdown()
        for r in receivers:
            await r.shutdown()

    run(go())
