"""Worker-plane Byzantine behaviors (ISSUE 8 tentpole): the quorum-ACK vs
availability split of ByzantineBatchMaker, the withholding/poisoning
Helper, the sync-flood amplifier against the Helper's bounds, the new
worker-plane health rules, the fuzzer's seed-determinism — and a live
in-process committee surviving a withholding worker while naming it (the
test_byzantine pattern: the paper's availability claim under attack)."""

import asyncio
import gc
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from narwhal_tpu import metrics, native  # noqa: E402
from narwhal_tpu.config import Parameters  # noqa: E402
from narwhal_tpu.crypto import Digest, digest32  # noqa: E402
from narwhal_tpu.faults.byzantine import ByzantinePlan  # noqa: E402
from narwhal_tpu.faults.byzantine_worker import (  # noqa: E402
    ByzantineBatchMaker,
    ByzantineHelper,
    SyncFlooder,
)
from narwhal_tpu.faults.fuzz import generate  # noqa: E402
from narwhal_tpu.faults.spec import SpecError, parse_scenario  # noqa: E402
from narwhal_tpu.messages import (  # noqa: E402
    decode_worker_message,
    encode_batch,
)
from narwhal_tpu.metrics import HealthMonitor, default_rules  # noqa: E402
from narwhal_tpu.network.framing import parse_address, write_frame  # noqa: E402
from narwhal_tpu.node import spawn_primary_node, spawn_worker_node  # noqa: E402
from narwhal_tpu.store import Store  # noqa: E402
from narwhal_tpu.worker.helper import Helper  # noqa: E402
from tests.common import committee, keys  # noqa: E402
from tests.test_worker_hardening import FakeSender, _counter  # noqa: E402


# Committee ports live BELOW this host's ephemeral range (ip_local_port_range
# starts at 16000 here): a listener in that range races the OS's outgoing
# source ports and flakes EADDRINUSE in full-suite runs.
def _maker(plan, base_port=12000):
    c = committee(base_port=base_port)
    me = keys()[0].name
    maker = ByzantineBatchMaker(
        plan, me, 0, c, 200, 10_000,
        c.worker(me, 0).transactions, asyncio.Queue(),
    )
    maker.sender.close()
    maker.sender = FakeSender()
    return maker, c, me


# -- the quorum-ACK vs availability split -------------------------------------


def test_withhold_split_certifies_but_starves_a_peer():
    """The batch goes to exactly quorum−own_stake peers — enough ACKs to
    certify — while the rest receive nothing and must sync."""

    async def go():
        plan = ByzantinePlan(["withhold_batches"], seed=9)
        maker, c, me = _maker(plan)
        before = _counter("faults.byzantine.batches_withheld")
        handlers = maker._broadcast_batch(Digest(bytes(32)), b"batch")
        # 4-node unit-stake committee: quorum 3, own stake 1 → 2 peers.
        assert len(handlers) == 2
        sent_to = {addr for addr, _ in maker.sender.sent}
        all_peers = {addr for _, addr in maker._peers}
        assert len(all_peers - sent_to) == 1  # one starved peer
        assert sum(stake for stake, _ in handlers) + c.stake(me) \
            >= c.quorum_threshold()
        assert _counter("faults.byzantine.batches_withheld") == before + 1

        # Seed-determinism: a fresh plan with the same seed splits the
        # same way.
        maker2, _, _ = _maker(ByzantinePlan(["withhold_batches"], seed=9))
        maker2._broadcast_batch(Digest(bytes(32)), b"batch")
        assert {a for a, _ in maker2.sender.sent} == sent_to

    asyncio.run(asyncio.wait_for(go(), 10))


def test_honest_behaviors_broadcast_to_everyone():
    """A plan without the under-sharing behaviors (e.g. sync_flood only)
    leaves the broadcast untouched."""

    async def go():
        maker, c, _ = _maker(ByzantinePlan(["sync_flood"], seed=9))
        handlers = maker._broadcast_batch(Digest(bytes(32)), b"batch")
        assert len(handlers) == 3  # every other authority

    asyncio.run(asyncio.wait_for(go(), 10))


def test_withhold_requires_unit_stake():
    async def go():
        c = committee(base_port=12030)
        next(iter(c.authorities.values())).stake = 5
        me = keys()[0].name
        with pytest.raises(SpecError):
            ByzantineBatchMaker(
                ByzantinePlan(["withhold_batches"]), me, 0, c, 200, 10_000,
                c.worker(me, 0).transactions, asyncio.Queue(),
            )

    asyncio.run(asyncio.wait_for(go(), 10))


# -- the byzantine helper -----------------------------------------------------


def test_withholding_helper_never_serves():
    async def go():
        c = committee(base_port=12060)
        store = Store()
        data = encode_batch([bytes(40)])
        store.write(bytes(digest32(data)), data)
        helper = ByzantineHelper(
            ByzantinePlan(["withhold_batches"]), 0, c, store, asyncio.Queue()
        )
        helper.sender = FakeSender()
        before = _counter("faults.byzantine.sync_requests_ignored")
        await helper._respond("addr", [digest32(data)])
        assert helper.sender.sent == []
        assert _counter("faults.byzantine.sync_requests_ignored") == before + 1

    asyncio.run(asyncio.wait_for(go(), 10))


def test_garbage_helper_serves_oversized_and_corrupt_junk():
    """Replies alternate between a structurally-valid OVERSIZED junk
    batch (caught by the receiver's size gate) and a corrupt frame
    (caught by the structural walk) — never the real bytes."""

    async def go():
        c = committee(base_port=12090)
        store = Store()
        data = encode_batch([bytes(40)])
        store.write(bytes(digest32(data)), data)
        helper = ByzantineHelper(
            ByzantinePlan(["garbage_batches"], seed=3, garbage_bytes=2_000),
            0, c, store, asyncio.Queue(),
        )
        helper.sender = FakeSender()
        await helper._respond("addr", [digest32(data), digest32(data)])
        assert len(helper.sender.sent) == 2
        oversized = helper.sender.sent[0][1]
        corrupt = helper.sender.sent[1][1]
        assert oversized != data and corrupt != data
        assert native.validate_batch(oversized) == 1  # valid structure...
        assert len(oversized) == 2_000 + 9            # ...hostile size
        assert native.validate_batch(corrupt) < 0
        assert _counter("faults.byzantine.garbage_served") >= 2

    asyncio.run(asyncio.wait_for(go(), 10))


def test_garbage_reply_is_rejected_by_the_size_gate():
    """End-to-end defense pairing: the garbage helper's oversized reply
    trips the receiving worker's max-batch-bytes gate — counted into
    worker.garbage_batches, not hashed or persisted."""

    async def go():
        from narwhal_tpu.worker.worker import WorkerReceiverHandler
        from tests.test_worker_hardening import FakeWriter

        helper = ByzantineHelper(
            ByzantinePlan(["garbage_batches"], garbage_bytes=800_000),
            0, committee(base_port=12120), Store(), asyncio.Queue(),
        )
        helper.sender = FakeSender()
        await helper._respond("addr", [Digest(bytes(32))])
        junk = helper.sender.sent[0][1]

        handler = WorkerReceiverHandler(
            asyncio.Queue(), asyncio.Queue(),
            max_batch_bytes=2 * 500 + 65_536,
        )
        writer = FakeWriter()
        before = _counter("worker.garbage_batches")
        await handler.dispatch(writer, junk)
        assert _counter("worker.garbage_batches") == before + 1
        assert writer.acks == []

    asyncio.run(asyncio.wait_for(go(), 10))


# -- sync flood vs helper bounds ----------------------------------------------


def test_flood_requests_exceed_cap_and_get_truncated():
    async def go():
        c = committee(base_port=12150)
        store = Store()
        data = encode_batch([bytes(40)])
        store.write(bytes(digest32(data)), data)
        flooder = SyncFlooder(
            ByzantinePlan(["sync_flood"], seed=5), keys()[0].name, 0, c, store
        )
        digests = flooder._flood_digests()
        assert len(digests) >= 1_024  # far past the Helper cap
        assert digest32(data) in digests  # real stored digests lead

        # The honest Helper truncates the flood to the cap and counts it.
        victim = Helper(0, c, store, asyncio.Queue())
        victim.sender = FakeSender()
        before = _counter("worker.helper_rejected_requests")
        bounded = victim._bound(digests, keys()[0].name)
        assert len(bounded) <= victim.max_digests
        assert _counter("worker.helper_rejected_requests") == before + 1

    asyncio.run(asyncio.wait_for(go(), 10))


# -- spec / plan composition --------------------------------------------------


def test_plan_splits_behaviors_by_plane():
    plan = ByzantinePlan(["equivocate", "withhold_batches"])
    assert plan.primary_behaviors() == {"equivocate"}
    assert plan.worker_behaviors() == {"withhold_batches"}


def test_plan_and_spec_reject_withhold_garbage_conflict():
    with pytest.raises(SpecError):
        ByzantinePlan(["withhold_batches", "garbage_batches"])
    with pytest.raises(SpecError):
        parse_scenario(
            {
                "name": "t",
                "byzantine": [
                    {
                        "node": 0,
                        "behaviors": [
                            "withhold_batches", "garbage_batches",
                        ],
                    },
                ],
            },
            env={},
        )


def test_spec_rejects_duplicate_byzantine_entries_for_one_node():
    """The runner writes ONE plan file per authority, so a second entry
    for the same node would silently replace the first's behaviors —
    refused at parse instead."""
    with pytest.raises(SpecError):
        parse_scenario(
            {
                "name": "t",
                "byzantine": [
                    {"node": 1, "behaviors": ["equivocate"]},
                    {"node": 1, "behaviors": ["sync_flood"]},
                ],
            },
            env={},
        )


def test_spec_accepts_worker_plane_composition():
    s = parse_scenario(
        {
            "name": "t",
            "nodes": 7,
            "byzantine": [
                {"node": 5, "behaviors": ["withhold_batches"],
                 "flood_interval_ms": 100, "garbage_bytes": 1_000_000}
            ],
            "crash": [{"node": 2, "at_s": 10, "restart_at_s": 16}],
        },
        env={},
    )
    assert s.byzantine[0].flood_interval_ms == 100
    assert s.byzantine[0].garbage_bytes == 1_000_000
    # distinct byz + crashed nodes within f=2 for n=7
    assert s.honest_nodes() == [0, 1, 2, 3, 4, 6]


def test_spec_rejects_worker_plane_composition_past_f():
    with pytest.raises(SpecError):
        parse_scenario(
            {
                "name": "t",
                "byzantine": [
                    {"node": 3, "behaviors": ["withhold_batches"]}
                ],
                "crash": [{"node": 1, "at_s": 10, "restart_at_s": 16}],
            },
            env={},
        )


# -- new health rules ---------------------------------------------------------


def test_worker_plane_rules_fire_and_stay_silent_when_clean():
    reg = metrics.Registry(enabled=True)
    monitor = HealthMonitor(
        reg, rules=default_rules({"NARWHAL_HEALTH_SYNC_AGE_S": "2"}),
        interval_s=1.0,
    )
    # Clean registry: nothing fires.
    assert monitor.evaluate(now=1.0) == []

    reg.counter("worker.helper_rejected_requests").inc()
    reg.counter("worker.garbage_batches").inc()
    reg.gauge_fn("worker.unserved_sync_age_seconds", lambda: 5.0)
    monitor.evaluate(now=2.0)
    firing = {f["rule"] for f in monitor.evaluate(now=3.0)}
    assert {"helper_abuse", "garbage_batches", "batch_withholding"} <= firing

    # The age gauge clearing (batch finally served) clears the rule; the
    # two latching rules stay raised — the events are proof.
    reg.gauge_fns["worker.unserved_sync_age_seconds"] = lambda: 0.0
    monitor.evaluate(now=4.0)
    firing = {f["rule"] for f in monitor.evaluate(now=5.0)}
    assert "batch_withholding" not in firing
    assert {"helper_abuse", "garbage_batches"} <= firing


# -- fuzzed scenario generation -----------------------------------------------


def test_fuzz_is_deterministic_and_valid():
    for seed in range(40):
        obj = generate(seed)
        assert obj == generate(seed), f"seed {seed} not deterministic"
        s = parse_scenario(obj, env={})  # schema + BFT bounds revalidate
        assert s.name == f"fuzz_{seed}"
        assert s.byzantine, "every fuzz draw carries a byzantine plane"
        assert s.expect_rules, "detection verdict must never be vacuous"
        # All faults land on one node: union ≤ f by construction.
        faulted = set(s.byzantine_nodes()) | {c.node for c in s.crash}
        assert len(faulted) == 1


def test_fuzz_varies_across_seeds():
    draws = [generate(seed) for seed in range(40)]
    behaviors = {tuple(d["byzantine"][0]["behaviors"]) for d in draws}
    assert len(behaviors) >= 5, "fuzzer barely varies behaviors"
    assert any("crash" in d for d in draws)
    assert any("wan" in d for d in draws)
    assert any("crash" not in d for d in draws)


def test_fuzz_spec_roundtrips_through_json():
    import json

    for seed in (7, 23):
        obj = generate(seed)
        assert json.loads(json.dumps(obj)) == obj


# -- live committee: availability under attack --------------------------------


def _tx(i: int) -> bytes:
    return bytes([1]) + (0xFB0000 + i).to_bytes(8, "little") + bytes(91)


def test_withholding_worker_detected_and_committee_survives():
    """One authority's worker certifies batches it then refuses to serve
    (the availability attack the paper's certificate claim rules out).
    The committee must keep committing the other authorities' payload,
    recover the withheld bytes via retry escalation to the honest ACKers,
    and the starved worker must NAME the anomaly via batch_withholding."""
    reg = metrics.registry()
    reg.reset()
    gc.collect()  # drop earlier tests' synchronizers from the age gauge

    async def go():
        c = committee(base_port=12200)
        params = Parameters(
            header_size=32,
            max_header_delay=100,
            batch_size=400,
            max_batch_delay=100,
            sync_retry_delay=4_000,
        )
        kps = keys()
        commits = {i: [] for i in range(4)}
        plan = ByzantinePlan(["withhold_batches"], seed=5)
        nodes = []
        for i, kp in enumerate(kps):
            nodes.append(
                await spawn_primary_node(
                    kp, c, params,
                    on_commit=lambda cert, i=i: commits[i].append(cert),
                )
            )
            nodes.append(
                await spawn_worker_node(
                    kp, 0, c, params,
                    fault_plan=plan if i == 3 else None,
                )
            )

        monitor = HealthMonitor(
            reg,
            rules=default_rules({"NARWHAL_HEALTH_SYNC_AGE_S": "1"}),
            interval_s=0.5,
        )
        age_gauge = reg.gauge_fns["worker.unserved_sync_age_seconds"]

        async def send_txs(ids, node=0):
            host, port = parse_address(c.worker(kps[node].name, 0).transactions)
            _, w = await asyncio.open_connection(host, port)
            txs = [_tx(i) for i in ids]
            for tx in txs:
                await write_frame(w, tx)
            w.close()
            return {digest32(encode_batch(txs))}

        async def wait_commit(expected, nodes_idx, timeout_s=60):
            for _ in range(int(timeout_s / 0.1)):
                if all(
                    expected
                    <= {
                        d
                        for cert in commits[i]
                        for d in cert.header.payload
                    }
                    for i in nodes_idx
                ):
                    return
                await asyncio.sleep(0.1)
            raise AssertionError(
                f"payload never committed on {nodes_idx}: "
                f"{[len(commits[i]) for i in nodes_idx]}"
            )

        # Honest payload commits with the adversary active from boot.
        batch1 = await send_txs(range(4))
        await wait_commit(batch1, range(3))

        # Drive payload through the WITHHOLDING worker: it certifies
        # (quorum-split ACKs) but one honest peer is starved and must
        # sync against a refusing Helper.
        byz_batch = await send_txs(range(50, 54), node=3)
        deadline = asyncio.get_running_loop().time() + 30
        while age_gauge() <= 1.0:
            assert asyncio.get_running_loop().time() < deadline, (
                "no starved sync request ever aged past the threshold"
            )
            await asyncio.sleep(0.05)
        assert _counter("faults.byzantine.batches_withheld") > 0
        monitor.evaluate()
        firing = {f["rule"] for f in monitor.evaluate()}
        assert "batch_withholding" in firing, firing

        # Availability holds regardless: escalation reaches the honest
        # ACK-quorum holders, so even the WITHHELD payload commits...
        await wait_commit(byz_batch, range(3))
        # ... and fresh honest payload kept flowing throughout.
        batch3 = await send_txs(range(100, 104), node=1)
        await wait_commit(batch3, range(3))

        for node in nodes:
            await node.shutdown()

    # 8 in-process nodes on pure-Python crypto: generous ceiling so a
    # loaded shared-core host doesn't flake the suite.
    asyncio.run(asyncio.wait_for(go(), 180))
