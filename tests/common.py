"""Deterministic test fixtures, modeled on the reference's shared fixtures
(reference primary/src/tests/common.rs: seeded RNG keys, canonical 4-authority
committee with stake 1 and sequential localhost ports, committee_with_base_port
so concurrent tests don't collide)."""

from __future__ import annotations

from typing import Dict, List

from narwhal_tpu.config import (
    Authority,
    Committee,
    PrimaryAddresses,
    WorkerAddresses,
)
from narwhal_tpu.crypto import KeyPair, PublicKey


def keys(n: int = 4) -> List[KeyPair]:
    """Deterministic keypairs from fixed seeds (analog of StdRng::from_seed)."""
    return [KeyPair.generate(bytes([i]) * 32) for i in range(n)]


def committee(base_port: int = 0, n: int = 4, workers: int = 1) -> Committee:
    """Canonical committee: stake 1 each, sequential 127.0.0.1 ports.

    With base_port=0 every address gets port 0 — fine for tests that never
    dial (consensus, aggregators); pass a distinct real base per test file
    that opens sockets, like the reference does.
    """
    authorities: Dict[PublicKey, Authority] = {}
    port = base_port
    for kp in keys(n):
        def addr() -> str:
            nonlocal port
            a = f"127.0.0.1:{port}"
            if base_port != 0:
                port += 1
            return a

        primary = PrimaryAddresses(
            primary_to_primary=addr(), worker_to_primary=addr()
        )
        ws: Dict[int, WorkerAddresses] = {}
        for wid in range(workers):
            ws[wid] = WorkerAddresses(
                transactions=addr(), worker_to_worker=addr(), primary_to_worker=addr()
            )
        authorities[kp.name] = Authority(stake=1, primary=primary, workers=ws)
    return Committee(authorities)


# --- worker-plane fixtures (analog of reference worker/src/tests/common.rs) ---

from narwhal_tpu.crypto import digest32  # noqa: E402
from narwhal_tpu.messages import encode_batch  # noqa: E402


def transaction(sample_id: int = 5) -> bytes:
    """A 'sample' transaction: byte0=0 + u64 id + padding."""
    return bytes([0]) + sample_id.to_bytes(8, "little") + bytes(91)


def filler_transaction() -> bytes:
    return bytes([1]) + (7).to_bytes(8, "little") + bytes(91)


def batch():
    return [transaction(), filler_transaction()]


def serialized_batch() -> bytes:
    return encode_batch(batch())


def batch_digest():
    return digest32(serialized_batch())


class RecordingAckHandler:
    """Fake peer: ACKs every frame and records it (analog of the reference's
    `listener(address)` fixture, primary/src/tests/common.rs:169-183)."""

    def __init__(self, ack: bool = True):
        self.ack = ack
        self.received = []
        import asyncio

        self.arrived = asyncio.Event()

    async def dispatch(self, writer, message: bytes) -> None:
        self.received.append(message)
        self.arrived.set()
        if self.ack:
            await writer.send(b"Ack")


# --- primary-plane fixtures (analog of reference primary/src/tests/common.rs) ---

from narwhal_tpu.primary.messages import Certificate, Header, Vote, genesis  # noqa: E402


def make_header(author_kp, round_=1, payload=None, parents=None, c=None):
    """A signed header; parents default to the genesis certificates."""
    c = c or committee()
    parents = parents if parents is not None else {x.digest() for x in genesis(c)}
    h = Header(
        author=author_kp.name,
        round=round_,
        payload=payload or {},
        parents=set(parents),
    )
    h.id = h.compute_digest()
    h.signature = author_kp.sign(h.id)
    return h


def make_headers(round_=1, parents=None, c=None):
    return [make_header(kp, round_, None, parents, c) for kp in keys()]


def make_vote(header, voter_kp):
    v = Vote(
        id=header.id,
        round=header.round,
        origin=header.author,
        author=voter_kp.name,
    )
    v.signature = voter_kp.sign(v.digest())
    return v


def make_votes(header, exclude_author=True):
    kps = [kp for kp in keys() if not exclude_author or kp.name != header.author]
    return [make_vote(header, kp) for kp in kps]


def make_certificate(header):
    """Certificate with votes from every authority except the author
    (3 votes = quorum in the 4-node fixture)."""
    return Certificate(header=header, votes=[
        (v.author, v.signature) for v in make_votes(header)
    ])
