"""Certificate signature schemes (ISSUE 20): ed25519 half-aggregation
behind the crypto backend seam.

Three layers of protection are pinned here:

1. The DIFFERENTIAL one-sided gate (the ISSUE 14 shape): the ``halfagg``
   verifier must NEVER accept signature material the ``individual``
   serial path rejects — rogue-key substitution, wrong subsets,
   duplicate signers, truncated/bit-flipped aggregates, below-quorum
   signer sets.  A forgery slipping in only under the fast scheme would
   be a consensus-split machine.
2. The SCHEME SEAM: scheme-versioned Certificate wire frames (both wire
   formats), loud ``SchemeMismatch`` refusal in every direction — frame
   decode, checkpoint restore (tusk + all three golden oracles),
   persisted-store replay — each counted into
   ``primary.invalid_signatures`` where a live node sees it.
3. The LEDGER invariants: exactly TWO signature claims per halfagg
   certificate, ONE ``certificate_agg`` verify op per certificate, and
   the PR 12 verified-digest cache absorbing re-deliveries with ZERO
   new verify ops (a tampered re-delivery must MISS the cache).
"""

import asyncio
import contextlib
import random

import pytest

from narwhal_tpu import metrics
from narwhal_tpu.crypto import KeyPair, PublicKey, Signature
from narwhal_tpu.crypto import aggregate as agg_mod
from narwhal_tpu.crypto.aggregate import (
    AggregateSignature,
    SchemeMismatch,
    aggregate_votes,
    cert_sig_wire_bytes,
    resolve_scheme,
    verify_halfagg,
)
from narwhal_tpu.crypto.keys import cpu_verify, set_sim_mac, sim_mac_enabled
from narwhal_tpu.messages import set_wire_committee
from narwhal_tpu.network import wirev2
from narwhal_tpu.primary.errors import InvalidSignature
from narwhal_tpu.primary.messages import Certificate, genesis
from tests.common import committee, keys, make_header, make_votes

rng = random.Random(20)


def run(coro, timeout=30):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def cnt(name: str) -> float:
    c = metrics.registry().counters.get(name)
    return c.value if c is not None else 0


@contextlib.contextmanager
def scheme(name):
    """Scope a cert-sig scheme override, restoring any outer override."""
    prev = agg_mod.scheme_override()
    agg_mod.set_scheme(name)
    try:
        yield
    finally:
        agg_mod.set_scheme(prev)


@contextlib.contextmanager
def wire_committee(c):
    """Install the wire-v2 key-index roster, restoring the previous one
    (set_wire_committee has no uninstall — node boot owns it)."""
    from narwhal_tpu import messages as wire_messages

    prev_keys = wire_messages._WIRE_KEYS
    prev_index = wire_messages._WIRE_INDEX
    set_wire_committee(c)
    try:
        yield
    finally:
        wire_messages._WIRE_KEYS = prev_keys
        wire_messages._WIRE_INDEX = prev_index


@contextlib.contextmanager
def v2_wire():
    wirev2.set_enabled(True)
    try:
        yield
    finally:
        wirev2.set_enabled(None)


@contextlib.contextmanager
def v1_wire():
    wirev2.set_enabled(False)
    try:
        yield
    finally:
        wirev2.set_enabled(None)


def quorum_votes(n=5, seed=3, msg=None):
    """n distinct keypairs voting over one 32-byte digest."""
    import hashlib

    msg = msg or hashlib.sha256(b"scheme-test-%d" % seed).digest()
    kps = [
        KeyPair.generate(hashlib.sha256(b"q%d:%d" % (seed, i)).digest())
        for i in range(n)
    ]
    from narwhal_tpu.crypto.digest import Digest

    votes = [(kp.name, kp.sign(Digest(msg))) for kp in kps]
    return msg, kps, votes


def make_agg_certificate(header, exclude_author=True):
    """The halfagg analog of tests.common.make_certificate: fold the
    3-vote quorum into one aggregate at assembly time."""
    cert = Certificate(header=header)
    votes = [
        (v.author, v.signature)
        for v in make_votes(header, exclude_author=exclude_author)
    ]
    signers, agg = aggregate_votes(bytes(cert.digest()), votes)
    cert.agg_signers = signers
    cert.agg = agg
    return cert


# --- the aggregation core ----------------------------------------------------


def test_aggregate_roundtrip_and_order_independence():
    """A valid quorum aggregates to one verifying blob, and the blob is
    a pure function of the vote SET (arrival order folded away by the
    canonical signer sort) — two nodes assembling from differently
    ordered bursts produce byte-identical certificates."""
    msg, kps, votes = quorum_votes(7)
    signers, agg = aggregate_votes(msg, votes)
    assert isinstance(agg, AggregateSignature)
    assert agg.n_signers == 7 and len(agg) == 32 * 8
    assert signers == sorted(signers, key=bytes)
    assert verify_halfagg(msg, [bytes(s) for s in signers], agg)
    shuffled = list(votes)
    rng.shuffle(shuffled)
    signers2, agg2 = aggregate_votes(msg, shuffled)
    assert signers2 == signers and bytes(agg2) == bytes(agg)


def test_duplicate_signer_rejected_at_both_seams():
    msg, kps, votes = quorum_votes(4)
    with pytest.raises(ValueError, match="duplicate"):
        aggregate_votes(msg, votes + [votes[0]])
    signers, agg = aggregate_votes(msg, votes)
    publics = [bytes(s) for s in signers]
    dup = publics[:-1] + [publics[0]]
    assert verify_halfagg(msg, dup, agg) is False


def test_structure_hostility_is_invalid_never_a_crash():
    """Truncated / padded / widened blobs and non-canonical scalars are
    False (or unrepresentable at the type seam), never an exception."""
    msg, kps, votes = quorum_votes(5)
    signers, agg = aggregate_votes(msg, votes)
    publics = [bytes(s) for s in signers]
    assert verify_halfagg(msg, publics, bytes(agg)[:-32]) is False
    assert verify_halfagg(msg, publics, bytes(agg) + bytes(32)) is False
    assert verify_halfagg(msg, publics[:-1], agg) is False  # wrong width
    assert verify_halfagg(msg, [], b"") is False
    # s̄ >= L is non-canonical: forced rejection, not wraparound.
    big = bytes(agg)[:-32] + (agg_mod._L + 1).to_bytes(32, "little")
    assert verify_halfagg(msg, publics, big) is False
    # The typed seam refuses impossible widths outright.
    for bad in (b"", bytes(32), bytes(65)):
        with pytest.raises(ValueError):
            AggregateSignature(bad)


def test_differential_one_sided_gate():
    """The frozen differential battery: for every mutation, assemble the
    aggregate FROM the mutated votes and compare verdicts — the halfagg
    path must never accept a vote set the individual serial path
    rejects.  (The reverse — individual accepts, halfagg rejects — is
    safe and expected for aggregate-only corruptions.)"""
    msg, kps, votes = quorum_votes(6, seed=9)
    cases = [("clean", list(votes))]
    # Bit-flipped scalar half of one vote.
    flipped = list(votes)
    s = bytearray(bytes(flipped[2][1]))
    s[40] ^= 1
    flipped[2] = (flipped[2][0], Signature(bytes(s)))
    cases.append(("bitflip-s", flipped))
    # Bit-flipped nonce commitment of one vote.
    flipped_r = list(votes)
    s = bytearray(bytes(flipped_r[1][1]))
    s[3] ^= 0x80
    flipped_r[1] = (flipped_r[1][0], Signature(bytes(s)))
    cases.append(("bitflip-r", flipped_r))
    # A signature transplanted from another key (rogue substitution).
    swapped = list(votes)
    swapped[0] = (swapped[0][0], votes[1][1])
    cases.append(("transplanted-sig", swapped))
    # A vote over the WRONG message smuggled into the set.
    other_msg, _, other_votes = quorum_votes(6, seed=10)
    mixed = list(votes)
    mixed[3] = (mixed[3][0], other_votes[3][1])
    cases.append(("wrong-message-vote", mixed))
    for name, vset in cases:
        individual = all(cpu_verify(msg, k, s) for k, s in vset)
        try:
            signers, agg = aggregate_votes(msg, vset)
            halfagg = verify_halfagg(
                msg, [bytes(x) for x in signers], agg
            )
        except ValueError:
            halfagg = False
        if halfagg:
            assert individual, (
                f"{name}: halfagg accepted a vote set the serial "
                "path rejects"
            )
        if name == "clean":
            assert halfagg and individual
        else:
            assert not halfagg, f"{name}: corrupted set must not verify"


def test_rogue_key_cannot_ride_an_aggregate():
    """A victim key that never signed cannot be named in the signer list
    of any aggregate an attacker can produce: the coefficients bind the
    full (message, keys, commitments) transcript, so substituting or
    appending a key invalidates the equation."""
    msg, kps, votes = quorum_votes(5, seed=4)
    victim = KeyPair.generate(bytes([99]) * 32)
    signers, agg = aggregate_votes(msg, votes)
    publics = [bytes(s) for s in signers]
    # Substitute the victim for a genuine signer.
    for i in range(len(publics)):
        subst = list(publics)
        subst[i] = bytes(victim.name)
        assert verify_halfagg(msg, subst, agg) is False
    # Claiming a DIFFERENT genuine subset fails too.
    rotated = publics[1:] + publics[:1]
    assert verify_halfagg(msg, rotated, agg) is False


def test_sim_mac_aggregate_is_wire_exact_and_still_rejects_forgery():
    """Sim-MAC mode (the deterministic committee sim): the aggregate
    analog keeps the exact 32·(n+1) wire width, verifies genuine MACs,
    and still rejects a forged vote MAC."""
    assert not sim_mac_enabled()
    set_sim_mac(True)
    try:
        msg, kps, votes = quorum_votes(5, seed=6)
        signers, agg = aggregate_votes(msg, votes)
        publics = [bytes(s) for s in signers]
        assert len(agg) == 32 * 6
        assert verify_halfagg(msg, publics, agg)
        forged = list(votes)
        forged[0] = (forged[0][0], Signature(bytes(64)))
        s2, agg2 = aggregate_votes(msg, forged)
        assert verify_halfagg(msg, [bytes(x) for x in s2], agg2) is False
        flip = bytearray(agg)
        flip[-1] ^= 1  # the closing binder
        assert verify_halfagg(msg, publics, bytes(flip)) is False
    finally:
        set_sim_mac(False)


def test_cert_sig_wire_bytes_formula():
    """The exact numbers the bench summary and the README table quote."""
    assert cert_sig_wire_bytes("individual", 14, 2) == 14 * 65 + 64  # 974
    assert cert_sig_wire_bytes("halfagg", 14, 2) == 14 + 480 + 64  # 558
    assert cert_sig_wire_bytes("individual", 14, 1) == 14 * 96 + 64
    assert cert_sig_wire_bytes("halfagg", 14, 1) == 14 * 32 + 480 + 64
    assert cert_sig_wire_bytes("individual", 3, 2) == 259
    assert cert_sig_wire_bytes("halfagg", 3, 2) == 195
    assert cert_sig_wire_bytes("individual", 34, 2) == 2274
    assert cert_sig_wire_bytes("halfagg", 34, 2) == 1218
    with pytest.raises(ValueError):
        cert_sig_wire_bytes("bls", 14)


def test_resolve_scheme_and_gauge(monkeypatch):
    assert resolve_scheme() == "individual"
    assert resolve_scheme("halfagg") == "halfagg"
    monkeypatch.setenv("NARWHAL_CERT_SIG_SCHEME", "halfagg")
    assert resolve_scheme() == "halfagg"
    with pytest.raises(ValueError, match="unknown cert-sig scheme"):
        resolve_scheme("bls")
    with pytest.raises(ValueError):
        agg_mod.set_scheme("garbage")
    gauge = metrics.registry().gauge_fns["crypto.cert_sig_scheme"]
    with scheme("halfagg"):
        assert gauge() == 1.0
    with scheme("individual"):
        assert gauge() == 0.0


# --- Certificate integration -------------------------------------------------


def test_halfagg_certificate_verifies_and_prices_one_op():
    """End-to-end through Certificate.verify: a halfagg certificate
    verifies with exactly ONE ``certificate_agg`` verify op (the
    2f+1 → 1 collapse), exactly TWO signature claims, and a tampered
    aggregate raises InvalidSignature."""
    c = committee()
    with scheme("halfagg"):
        cert = make_agg_certificate(make_header(keys()[1], c=c))
        assert cert.scheme == "halfagg"
        assert len(cert.signature_claims()) == 2
        before = cnt("crypto.verify.ops.certificate_agg")
        cert.verify(c)
        assert cnt("crypto.verify.ops.certificate_agg") == before + 1
        # Tampered aggregate: rejected, still one op (the equation ran).
        bad = Certificate(
            header=cert.header,
            agg_signers=list(cert.agg_signers),
            agg=AggregateSignature(
                bytes(cert.agg)[:-32] + bytes(32)
            ),
        )
        with pytest.raises(InvalidSignature):
            bad.verify(c)
        # Signer/blob width mismatch fails structure BEFORE stake math.
        torn = Certificate(
            header=cert.header,
            agg_signers=list(cert.agg_signers)[:-1],
            agg=cert.agg,
        )
        with pytest.raises(InvalidSignature, match="aggregate width"):
            torn.verify_structure(c)
        # Below-quorum signer sets refuse at structure too.
        sub_signers = list(cert.agg_signers)[:1]
        _, sub_agg = aggregate_votes(
            bytes(cert.digest()),
            [(cert.agg_signers[0], Signature(bytes(64)))],
        )
        from narwhal_tpu.primary.errors import CertificateRequiresQuorum

        below = Certificate(
            header=cert.header, agg_signers=sub_signers, agg=sub_agg
        )
        with pytest.raises(CertificateRequiresQuorum):
            below.verify_structure(c)


def test_votes_aggregator_assembles_halfagg_certificate():
    """The VotesAggregator's quorum trip emits an aggregate certificate
    under halfagg — no (name, sig) pairs on the wire object at all."""
    from narwhal_tpu.primary.aggregators import VotesAggregator

    c = committee()
    header = make_header(keys()[0], c=c)
    votes = make_votes(header)
    with scheme("halfagg"):
        aggr = VotesAggregator()
        cert = None
        for v in votes:
            cert = aggr.append(v, c, header) or cert
        assert cert is not None
        assert cert.votes == [] and cert.agg is not None
        assert len(cert.agg_signers) == 3
        cert.verify(c)
    with scheme("individual"):
        aggr = VotesAggregator()
        cert = None
        for v in votes:
            cert = aggr.append(v, c, header) or cert
        assert cert is not None and cert.agg is None
        assert len(cert.votes) == 3


def test_wire_roundtrip_both_schemes_both_formats():
    """Scheme-versioned Certificate serialization round-trips under each
    scheme × each wire format, and genesis (voteless, scheme-neutral)
    round-trips under BOTH schemes."""
    c = committee()
    with wire_committee(c):
        for wire_ctx in (v1_wire, v2_wire):
            with wire_ctx():
                with scheme("individual"):
                    from tests.common import make_certificate

                    cert = make_certificate(make_header(keys()[1], c=c))
                    rt = Certificate.deserialize(cert.serialize())
                    assert rt == cert and rt.scheme == "individual"
                with scheme("halfagg"):
                    acert = make_agg_certificate(
                        make_header(keys()[2], c=c)
                    )
                    rt = Certificate.deserialize(acert.serialize())
                    assert rt == acert and rt.scheme == "halfagg"
                    assert rt.agg_signers == acert.agg_signers
                    assert bytes(rt.agg) == bytes(acert.agg)
                for sch in ("individual", "halfagg"):
                    with scheme(sch):
                        g = genesis(c)[0]
                        blob = Certificate(header=g.header).serialize()
                        rt = Certificate.deserialize(blob)
                        assert rt.votes == [] and rt.agg is None


def test_cross_scheme_frames_refuse_loudly():
    """A halfagg frame at an individual node (and vice versa) raises
    SchemeMismatch naming the schemes; an unknown scheme byte (the
    pre-scheme-store shape) is a loud ValueError."""
    c = committee()
    with wire_committee(c):
        from tests.common import make_certificate

        with scheme("halfagg"):
            agg_blob = make_agg_certificate(
                make_header(keys()[1], c=c)
            ).serialize()
        with scheme("individual"):
            ind_blob = make_certificate(
                make_header(keys()[2], c=c)
            ).serialize()
        with scheme("individual"):
            with pytest.raises(SchemeMismatch, match="halfagg"):
                Certificate.deserialize(agg_blob)
        with scheme("halfagg"):
            with pytest.raises(SchemeMismatch, match="halfagg"):
                Certificate.deserialize(ind_blob)
        # Unknown scheme byte: find the scheme byte (first byte after
        # the embedded header) by re-encoding the header alone.
        from narwhal_tpu.utils.serde import Writer

        with scheme("individual"):
            cert = make_certificate(make_header(keys()[3], c=c))
            w = Writer()
            cert.header.encode(w)
            off = len(w.finish())
            blob = cert.serialize()
            assert blob[off] == 0
            mangled = blob[:off] + bytes([7]) + blob[off + 1:]
            with pytest.raises(ValueError, match="scheme byte 7"):
                Certificate.deserialize(mangled)


def test_receiver_counts_cross_scheme_certificate():
    """The PrimaryReceiverHandler seam: a halfagg certificate frame
    arriving at an individual node is dropped, counted into
    ``primary.invalid_signatures`` (where the invalid_signature health
    rule watches), and never ACKed or enqueued."""

    async def go():
        from narwhal_tpu.primary.messages import encode_primary_message
        from narwhal_tpu.primary.primary import PrimaryReceiverHandler

        c = committee()
        with wire_committee(c):
            with scheme("halfagg"):
                frame = encode_primary_message(
                    make_agg_certificate(make_header(keys()[1], c=c))
                )
            sent = []

            class W:
                async def send(self, b):
                    sent.append(b)

            tx_p, tx_h = asyncio.Queue(), asyncio.Queue()
            handler = PrimaryReceiverHandler(tx_p, tx_h)
            with scheme("individual"):
                before = cnt("primary.invalid_signatures")
                await handler.dispatch(W(), frame)
                assert cnt("primary.invalid_signatures") == before + 1
            assert sent == [] and tx_p.empty() and tx_h.empty()

    run(go())


def test_verify_cache_absorbs_halfagg_redelivery_with_zero_new_ops():
    """The PR 12 invariant re-asserted under halfagg: a re-delivered
    aggregate certificate rides the verified-digest cache — ZERO new
    ``certificate_agg`` verify ops — while a re-sent copy whose
    aggregate was tampered MISSES the cache (the dedup key covers the
    signer list and blob) and is re-verified and rejected."""

    async def go():
        import sys

        sys.path.insert(0, "tests")
        from test_core import make_core

        c = committee()
        me = keys()[0]
        with scheme("halfagg"):
            core, store, qs = make_core(c, me)
            cert = make_agg_certificate(make_header(keys()[2], c=c))
            seen = []

            async def recording(source, item, sig_ok):
                seen.append(sig_ok)

            core._handle = recording
            try:
                before = cnt("crypto.verify.ops.certificate_agg")
                hits0 = core._m_verify_cache_hits.value

                await core._handle_primaries_burst(
                    [("certificate", cert)]
                )
                assert cnt("crypto.verify.ops.certificate_agg") == before + 1
                await core._handle_primaries_burst(
                    [("certificate", cert)]
                )
                # Re-delivery: cache hit, zero new aggregate verifies.
                assert cnt("crypto.verify.ops.certificate_agg") == before + 1
                assert core._m_verify_cache_hits.value == hits0 + 1
                assert seen == [True, True]

                tampered = Certificate(
                    header=cert.header,
                    agg_signers=list(cert.agg_signers),
                    agg=AggregateSignature(
                        bytes(cert.agg)[:-32] + bytes(32)
                    ),
                )
                assert tampered.digest() == cert.digest()
                await core._handle_primaries_burst(
                    [("certificate", tampered)]
                )
                assert (
                    cnt("crypto.verify.ops.certificate_agg") == before + 2
                )
                assert seen[-1] is False
                # The genuine copy still rides the cache afterwards.
                await core._handle_primaries_burst(
                    [("certificate", cert)]
                )
                assert (
                    cnt("crypto.verify.ops.certificate_agg") == before + 2
                )
                assert seen[-1] is True
            finally:
                core.network.close()

    run(go())


# --- checkpoint + store seams ------------------------------------------------


def _state_classes():
    from narwhal_tpu.consensus.golden import GoldenTusk
    from narwhal_tpu.consensus.golden_lowdepth import GoldenLowDepthTusk
    from narwhal_tpu.consensus.golden_multileader import (
        GoldenMultiLeaderTusk,
    )
    from narwhal_tpu.consensus.tusk import Tusk

    c = committee()
    return [
        ("tusk", lambda: Tusk(c, gc_depth=50, fixed_coin=True).state),
        (
            "golden",
            lambda: GoldenTusk(c, gc_depth=50, fixed_coin=True).state,
        ),
        (
            "golden_lowdepth",
            lambda: GoldenLowDepthTusk(
                c, gc_depth=50, fixed_coin=True
            ).state,
        ),
        (
            "golden_multileader",
            lambda: GoldenMultiLeaderTusk(
                c, gc_depth=50, fixed_coin=True
            ).state,
        ),
    ]


def test_checkpoint_scheme_trailer_all_rules():
    """Checkpoint blobs carry a scheme trailer: same-scheme restores
    round-trip, cross-scheme restores raise SchemeMismatch naming BOTH
    schemes in BOTH directions, legacy (trailer-less) blobs read as
    individual, and a torn trailer is a loud ValueError — for the tusk
    State and all three golden oracles."""
    for label, mk in _state_classes():
        with scheme("individual"):
            blob_ind = mk().snapshot_bytes()
        with scheme("halfagg"):
            blob_agg = mk().snapshot_bytes()
        # Same-scheme round-trips.
        with scheme("individual"):
            mk().restore(blob_ind)
        with scheme("halfagg"):
            mk().restore(blob_agg)
        # Cross-scheme refusals, both directions, both names present.
        with scheme("individual"):
            with pytest.raises(SchemeMismatch) as e:
                mk().restore(blob_agg)
            assert "halfagg" in str(e.value), label
            assert "individual" in str(e.value), label
        with scheme("halfagg"):
            with pytest.raises(SchemeMismatch) as e:
                mk().restore(blob_ind)
            assert "halfagg" in str(e.value), label
            assert "individual" in str(e.value), label
        # Legacy (pre-scheme) blob: implicit individual.
        legacy = blob_ind[:-5]
        with scheme("individual"):
            mk().restore(legacy)
        with scheme("halfagg"):
            with pytest.raises(SchemeMismatch):
                mk().restore(legacy)
        # Torn trailer: neither body-only nor body+5.
        with scheme("individual"):
            with pytest.raises(ValueError):
                mk().restore(blob_ind[:-2])


def test_store_replay_roundtrips_each_scheme_and_counts_cross():
    """_replay_persisted_certificates under each scheme feeds the
    persisted certificates back to consensus; a store written under the
    OTHER scheme replays nothing and counts every refused certificate
    into ``primary.invalid_signatures``."""

    async def go():
        from narwhal_tpu.consensus.tusk import Tusk
        from narwhal_tpu.node.node import _replay_persisted_certificates
        from narwhal_tpu.store import Store
        from tests.common import make_certificate

        c = committee()
        with wire_committee(c):
            for sch, mk in (
                ("individual", lambda h: make_certificate(h)),
                ("halfagg", lambda h: make_agg_certificate(h)),
            ):
                with scheme(sch):
                    store = Store()
                    cert = mk(make_header(keys()[1], c=c))
                    store.write(bytes(cert.digest()), cert.serialize())
                    state = Tusk(c, gc_depth=50, fixed_coin=True).state
                    q = asyncio.Queue()
                    await _replay_persisted_certificates(store, state, q)
                    assert q.qsize() == 1
                    replayed = q.get_nowait()
                    assert replayed.digest() == cert.digest()
                    assert replayed.scheme == sch

            # Cross-scheme store: written under halfagg, booted under
            # individual — refused, counted, loudly not silently.
            with scheme("halfagg"):
                store = Store()
                cert = make_agg_certificate(make_header(keys()[2], c=c))
                store.write(bytes(cert.digest()), cert.serialize())
            with scheme("individual"):
                state = Tusk(c, gc_depth=50, fixed_coin=True).state
                q = asyncio.Queue()
                before = cnt("primary.invalid_signatures")
                await _replay_persisted_certificates(store, state, q)
                assert q.qsize() == 0
                assert cnt("primary.invalid_signatures") == before + 1

    run(go())
