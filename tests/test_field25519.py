"""Differential tests: GF(2^255-19) limb arithmetic vs Python big ints."""

import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from narwhal_tpu.ops import field25519 as F  # noqa: E402

P = F.P
rng = random.Random(0)

EDGE = [0, 1, 2, 19, (1 << 255) - 20, P - 1, P - 2, (1 << 252), F.MASK]


def rand_elems(n):
    vals = EDGE + [rng.randrange(P) for _ in range(n - len(EDGE))]
    return vals[:n]


def batch(vals):
    return jnp.asarray(np.stack([F.to_limbs(v) for v in vals]))


def test_roundtrip():
    vals = rand_elems(32)
    got = [F.from_limbs(x) for x in np.asarray(batch(vals))]
    assert got == vals


def test_add_sub_neg():
    a_vals, b_vals = rand_elems(64), list(reversed(rand_elems(64)))
    a, b = batch(a_vals), batch(b_vals)
    s = np.asarray(F.canon(F.add(a, b)))
    d = np.asarray(F.canon(F.sub(a, b)))
    n = np.asarray(F.canon(F.neg(a)))
    for i, (x, y) in enumerate(zip(a_vals, b_vals)):
        assert F.from_limbs(s[i]) == (x + y) % P
        assert F.from_limbs(d[i]) == (x - y) % P
        assert F.from_limbs(n[i]) == (-x) % P


def test_mul_square():
    a_vals, b_vals = rand_elems(64), list(reversed(rand_elems(64)))
    a, b = batch(a_vals), batch(b_vals)
    m = np.asarray(F.canon(F.mul(a, b)))
    sq = np.asarray(F.canon(F.square(a)))
    for i, (x, y) in enumerate(zip(a_vals, b_vals)):
        assert F.from_limbs(m[i]) == (x * y) % P, f"mul row {i}"
        assert F.from_limbs(sq[i]) == (x * x) % P, f"sq row {i}"


def test_mul_chain_stays_reduced():
    """Repeated muls never overflow int32 lanes (weak reduction bound)."""
    a_vals = rand_elems(16)
    a = batch(a_vals)
    acc = a
    expect = list(a_vals)
    for _ in range(50):
        acc = F.mul(acc, a)
        assert int(jnp.max(acc)) < (1 << (F.BITS + 1)), "limb escaped weak bound"
        expect = [(e * x) % P for e, x in zip(expect, a_vals)]
    got = np.asarray(F.canon(acc))
    for i, e in enumerate(expect):
        assert F.from_limbs(got[i]) == e


def test_invert():
    vals = [v for v in rand_elems(32) if v != 0]
    a = batch(vals)
    inv = np.asarray(F.canon(F.invert(a)))
    for i, v in enumerate(vals):
        assert F.from_limbs(inv[i]) == pow(v, P - 2, P)


def test_pow_p58():
    vals = rand_elems(16)
    a = batch(vals)
    r = np.asarray(F.canon(F.pow_p58(a)))
    e = (P - 5) // 8
    for i, v in enumerate(vals):
        assert F.from_limbs(r[i]) == pow(v, e, P)


def test_canon_and_eq():
    # p and 0 are the same element; 2^255-19+x ≡ x.
    a = batch([P, 0, P + 5, 5])
    c = np.asarray(F.canon(a))
    assert F.from_limbs(c[0]) == 0 and F.from_limbs(c[2]) == 5
    assert bool(F.eq(a[0], a[1])) and bool(F.eq(a[2], a[3]))
    assert not bool(F.eq(a[1], a[3]))
    assert bool(F.is_zero(a[0])) and not bool(F.is_zero(a[3]))


def test_mul_small():
    vals = rand_elems(16)
    a = batch(vals)
    r = np.asarray(F.canon(F.mul_small(a, 121666)))
    for i, v in enumerate(vals):
        assert F.from_limbs(r[i]) == (v * 121666) % P
