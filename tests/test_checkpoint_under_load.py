"""Checkpoint restore under CONCURRENT inserts (ISSUE 6 satellite).

The existing torn-file tests are quiescent: they restore into an idle
instance.  Here a live Consensus runner is killed MID-STREAM while a
feeder keeps inserting certificates, then restarted over the same
checkpoint file and hit with the full catch-up flood (pre-crash history
replayed INTO consensus, like a lagging peer's sync storm).

Since ISSUE 10 each incarnation runs under a seeded
``ExploringEventLoop`` (narwhal_tpu/analysis/schedule.py): the
feeder/runner/drain interleaving — including where exactly the "crash"
lands relative to the stream — is pinned by the seed instead of
whatever the host scheduler felt like, and the waits are scheduling-tick
polls rather than wall-clock sleeps (the only residual real-time input
is the checkpoint fsync executor thread, whose completion timing cannot
be simulated; the wall deadlines below are deadlock guards, not pacing).
Asserted:

- the restart restores a non-zero frontier from the checkpoint;
- the frozen golden oracle, replayed over the two audit segments (with
  the restore marker applied at the segment boundary), reproduces each
  incarnation's recorded commit sequence byte-identically and passes the
  uniqueness/causal-history invariants (consensus/replay.py);
- the concatenated, re-delivery-deduplicated commit sequence across the
  crash equals the sequence an UNCRASHED golden walk produces over the
  same stream — a crash/restart must be invisible in the committed
  order.
"""

import asyncio
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from narwhal_tpu.analysis.schedule import run_with_seed  # noqa: E402
from narwhal_tpu.consensus import Consensus  # noqa: E402
from narwhal_tpu.consensus.golden import GoldenTusk  # noqa: E402
from narwhal_tpu.consensus.replay import replay_segments  # noqa: E402
from tests.common import committee  # noqa: E402
from tests.test_consensus import (  # noqa: E402
    feed,
    genesis_digests,
    make_certificates,
    mock_certificate,
    sorted_names,
)

GC_DEPTH = 50
# Interleaving pins: one seed per event-loop incarnation.  Change them
# and the tests still must pass — any seed is a legal schedule — but a
# FIXED seed makes a failure replayable byte-for-byte.
SEED_FIRST_RUN = 11
SEED_SECOND_RUN = 22
SEED_TORN_BOOT = 33


def _stream(rounds=24):
    c = committee()
    names = sorted_names()
    certs, parents = make_certificates(1, rounds, genesis_digests(c), names)
    _, trigger = mock_certificate(names[0], rounds + 1, parents)
    return c, certs + [trigger]


def test_restart_mid_burst_with_concurrent_inserts_agrees_with_oracle(
    tmp_path,
):
    c, stream = _stream()
    ckpt = str(tmp_path / "consensus.ckpt")
    seg0 = str(tmp_path / "audit.seg0.bin")
    seg1 = str(tmp_path / "audit.seg1.bin")

    # The uncrashed reference: one golden walk over the whole stream.
    full = [
        bytes(x.digest())
        for x in feed(GoldenTusk(c, GC_DEPTH, fixed_coin=True), list(stream))
    ]
    assert len(full) > 20, "fixture must commit substantially"

    # The first incarnation only ever sees a prefix of the stream (the
    # trigger certificate is withheld until the restart), so no matter
    # how the scheduler interleaves the feeder and the runner the crash
    # provably lands mid-sequence: first_commits <= len(prefix) < full.
    cut = (2 * len(stream)) // 3
    prefix = [
        bytes(x.digest())
        for x in feed(
            GoldenTusk(c, GC_DEPTH, fixed_coin=True), list(stream[:cut])
        )
    ]
    target = len(full) // 3
    assert target <= len(prefix) < len(full), "fixture prefix must straddle"

    async def first_run():
        rx, tx_p, tx_o = asyncio.Queue(), asyncio.Queue(), asyncio.Queue()
        cons = Consensus(
            c, GC_DEPTH, rx_primary=rx, tx_primary=tx_p, tx_output=tx_o,
            fixed_coin=True, checkpoint_path=ckpt, audit_path=seg0,
        )
        task = asyncio.get_running_loop().create_task(cons.run())
        committed = []

        async def drain():
            while True:
                committed.append(bytes((await tx_o.get()).digest()))
                await tx_p.get()  # keep the feedback queue drained too

        drain_task = asyncio.get_running_loop().create_task(drain())

        async def feeder():
            for cert in stream[:cut]:
                await rx.put(cert)
                await asyncio.sleep(0)  # interleave with the runner

        feeder_task = asyncio.get_running_loop().create_task(feeder())
        # Kill the consensus instance MID-BURST: after some commits have
        # landed but (deliberately) well before the stream is done.
        deadline = asyncio.get_running_loop().time() + 90
        while len(committed) < target:
            assert asyncio.get_running_loop().time() < deadline
            await asyncio.sleep(0)
        task.cancel()
        feeder_task.cancel()
        drain_task.cancel()
        await asyncio.gather(
            task, feeder_task, drain_task, return_exceptions=True
        )
        # Drain what consensus already HANDED OFF before the kill: the
        # checkpoint's documented at-least-once boundary is the
        # tx_output put (it is rewritten only after a burst's puts), so
        # the observer must consume everything put before declaring the
        # incarnation dead — under a shuffled schedule the drain task
        # can lag the runner by a whole burst, and dropping those
        # handed-off commits would fake a hole the product never made
        # (the audit segment records them; only this test's view lost
        # them).
        while True:
            try:
                committed.append(bytes(tx_o.get_nowait().digest()))
            except asyncio.QueueEmpty:
                break
        # What a real SIGKILL preserves is everything flushed to the OS;
        # emulate the page-cache boundary by flushing the audit buffer.
        cons._audit.close()
        return committed

    first_commits, _ = run_with_seed(first_run, SEED_FIRST_RUN, timeout=180)
    assert 0 < len(first_commits) < len(full), "must stop mid-burst"
    assert os.path.exists(ckpt), "checkpoint must exist after commits"

    async def second_run():
        rx, tx_p, tx_o = asyncio.Queue(), asyncio.Queue(), asyncio.Queue()
        cons = Consensus(
            c, GC_DEPTH, rx_primary=rx, tx_primary=tx_p, tx_output=tx_o,
            fixed_coin=True, checkpoint_path=ckpt, audit_path=seg1,
        )
        # The checkpoint anchored the frontier: a restart is not round 0.
        assert cons.tusk.state.last_committed_round > 0
        task = asyncio.get_running_loop().create_task(cons.run())
        committed = []

        async def drain():
            while True:
                committed.append(bytes((await tx_o.get()).digest()))
                await tx_p.get()

        drain_task = asyncio.get_running_loop().create_task(drain())
        # Catch-up flood: the ENTIRE stream again, pre-crash history
        # included — exactly what a lagging-peer sync storm delivers.
        for cert in stream:
            await rx.put(cert)
            await asyncio.sleep(0)
        # Settle: wait until the union of both incarnations' commits
        # covers the uncrashed walk (the known completion target — a
        # no-growth heuristic here was load-sensitive: one checkpoint
        # fsync stalling past the stability window under full-suite disk
        # contention cancelled the runner mid-stream).  Tick-based poll
        # (sleep(0)), so the wait itself adds no wall-clock schedule
        # noise; on deadline fall through: the final equality assert
        # reports the actual hole.
        first_set = set(first_commits)
        deadline = asyncio.get_running_loop().time() + 90
        while len(first_set | set(committed)) < len(full):
            if asyncio.get_running_loop().time() >= deadline:
                break
            await asyncio.sleep(0)
        task.cancel()
        drain_task.cancel()
        await asyncio.gather(task, drain_task, return_exceptions=True)
        cons._audit.close()
        return committed

    second_commits, _ = run_with_seed(
        second_run, SEED_SECOND_RUN, timeout=180
    )
    assert second_commits, "restarted instance must keep committing"

    # Golden-oracle replay over both segments: byte-identical per
    # incarnation, uniqueness + causal history clean.
    verdict = replay_segments(
        c, GC_DEPTH, [seg0, seg1], fixed_coin=True
    )
    assert verdict["ok"], verdict["violations"]
    assert verdict["recorded_commits"] >= len(first_commits)

    # The crash is invisible in the committed order: concatenated (and
    # boundary-deduplicated — the checkpoint is at-least-once) sequence
    # equals the uncrashed golden walk.
    seen = set()
    combined = []
    for d in first_commits + second_commits:
        if d not in seen:
            seen.add(d)
            combined.append(d)
    assert combined == full


def test_restart_from_torn_checkpoint_falls_back_fresh_and_stays_safe(
    tmp_path,
):
    """Tear the checkpoint file, restart, and replay the flood: the node
    must boot from a fresh frontier (torn file ignored loudly), re-commit
    from genesis, and the golden replay of its audit segment must still
    agree — re-commits are the allowed at-least-once boundary, disorder
    is not."""
    c, stream = _stream(rounds=12)
    ckpt = str(tmp_path / "consensus.ckpt")
    seg = str(tmp_path / "audit.seg0.bin")
    with open(ckpt, "wb") as f:
        f.write(b"NCKPT1\x03")  # torn: magic + truncated body
    # The fresh boot re-commits the full prefix, in the oracle's order.
    full = [
        bytes(x.digest())
        for x in feed(GoldenTusk(c, GC_DEPTH, fixed_coin=True), list(stream))
    ]
    full_count = len(full)

    async def go():
        rx, tx_p, tx_o = asyncio.Queue(), asyncio.Queue(), asyncio.Queue()
        cons = Consensus(
            c, GC_DEPTH, rx_primary=rx, tx_primary=tx_p, tx_output=tx_o,
            fixed_coin=True, checkpoint_path=ckpt, audit_path=seg,
        )
        assert cons.tusk.state.last_committed_round == 0  # fresh fallback
        task = asyncio.get_running_loop().create_task(cons.run())
        committed = []

        async def drain():
            while True:
                committed.append(bytes((await tx_o.get()).digest()))
                await tx_p.get()

        drain_task = asyncio.get_running_loop().create_task(drain())
        for cert in stream:
            await rx.put(cert)
        # Wait for the known target count (not a no-growth heuristic —
        # see the sibling test), on a tick-based poll; on deadline the
        # final equality assert reports the actual shortfall.
        deadline = asyncio.get_running_loop().time() + 90
        while len(committed) < full_count:
            if asyncio.get_running_loop().time() >= deadline:
                break
            await asyncio.sleep(0)
        task.cancel()
        drain_task.cancel()
        await asyncio.gather(task, drain_task, return_exceptions=True)
        cons._audit.close()
        return committed

    committed, _ = run_with_seed(go, SEED_TORN_BOOT, timeout=180)
    assert committed
    verdict = replay_segments(c, GC_DEPTH, [seg], fixed_coin=True)
    assert verdict["ok"], verdict["violations"]
    assert committed == full


def test_consensus_survives_checkpoint_write_failure(tmp_path):
    """The race the narwhal-race harness caught (ISSUE 10): under the
    seeded loop, the crash/restart pair intermittently lost the SAME 40
    commits — the restarted incarnation's consensus task was DEAD.  Root
    cause pair: (a) ``_write_checkpoint`` used a fixed ``<path>.tmp``,
    so the pre-crash incarnation's still-in-flight executor write raced
    the restarted one's and the loser's ``os.replace`` raised
    FileNotFoundError; (b) Consensus.run let that exception kill the
    whole commit pipeline, permanently, while certificates kept
    queueing.  (b) is pinned here deterministically: a checkpoint path
    whose parent directory does not exist makes EVERY rewrite fail, and
    consensus must still commit the full stream — the checkpoint is an
    optimization, never a liveness dependency.  (a) is fixed by unique
    per-write tmp names (mkstemp), and the seeded-loop harness now joins
    the default executor at teardown so no incarnation's threads leak
    into the next."""
    c, stream = _stream(rounds=12)
    missing_dir = str(tmp_path / "gone" / "consensus.ckpt")
    seg = str(tmp_path / "audit.seg0.bin")
    full = [
        bytes(x.digest())
        for x in feed(GoldenTusk(c, GC_DEPTH, fixed_coin=True), list(stream))
    ]

    async def go():
        rx, tx_p, tx_o = asyncio.Queue(), asyncio.Queue(), asyncio.Queue()
        cons = Consensus(
            c, GC_DEPTH, rx_primary=rx, tx_primary=tx_p, tx_output=tx_o,
            fixed_coin=True, checkpoint_path=missing_dir, audit_path=seg,
        )
        task = asyncio.get_running_loop().create_task(cons.run())
        committed = []

        async def drain():
            while True:
                committed.append(bytes((await tx_o.get()).digest()))
                await tx_p.get()

        drain_task = asyncio.get_running_loop().create_task(drain())
        for cert in stream:
            await rx.put(cert)
            await asyncio.sleep(0)
        deadline = asyncio.get_running_loop().time() + 90
        while len(committed) < len(full):
            assert not task.done(), (
                "consensus task died on a checkpoint write failure: "
                f"{task.exception()!r}"
            )
            if asyncio.get_running_loop().time() >= deadline:
                break
            await asyncio.sleep(0)
        task.cancel()
        drain_task.cancel()
        await asyncio.gather(task, drain_task, return_exceptions=True)
        cons._audit.close()
        return committed

    committed, _ = run_with_seed(go, SEED_FIRST_RUN, timeout=180)
    assert committed == full, (
        f"checkpoint failures cost commits: {len(committed)}/{len(full)}"
    )
