"""Native data plane (native/dataplane.c via ctypes) vs the Python twin:
identical sealed messages, sample extraction, and chunk-boundary handling.
The sealed message must be byte-identical to messages.encode_batch of the
same transactions."""

import random

import pytest

from narwhal_tpu import native
from narwhal_tpu.messages import encode_batch
from narwhal_tpu.network.framing import frame


def _txs(rng, n, size=64):
    out = []
    for i in range(n):
        if rng.random() < 0.2:
            tx = b"\x00" + rng.getrandbits(64).to_bytes(8, "little")
            tx += bytes(size - len(tx))
        else:
            tx = b"\x01" + rng.randbytes(size - 1)
        out.append(tx)
    return out


def _stream(txs):
    return b"".join(frame(tx) for tx in txs)


def _impls():
    impls = [("python", native._PyBatcher, native._PyFramer)]
    if native.native_available():
        lib = native._load()
        impls.append((
            "native",
            lambda size: native._NativeBatcher(lib, size),
            lambda: native._NativeFramer(lib),
        ))
    return impls


@pytest.mark.parametrize("name,mk_batcher,mk_framer", _impls())
def test_seal_matches_encode_batch(name, mk_batcher, mk_framer):
    rng = random.Random(0)
    txs = _txs(rng, 100)
    batcher = mk_batcher(1 << 20)
    framer = mk_framer()
    framer.feed(batcher, _stream(txs))
    assert batcher.tx_count == 100
    assert batcher.tx_bytes == sum(len(t) for t in txs)
    sealed = batcher.seal()
    assert sealed.message == encode_batch(txs)
    assert sealed.tx_count == 100
    want_samples = [
        int.from_bytes(t[1:9], "little") for t in txs if t[0] == 0
    ]
    assert sealed.samples == want_samples
    # Batcher resets after seal.
    assert batcher.tx_count == 0 and batcher.seal() is None


@pytest.mark.parametrize("name,mk_batcher,mk_framer", _impls())
def test_chunk_boundaries(name, mk_batcher, mk_framer):
    """Feeding the same stream in adversarially small/uneven chunks must
    produce the same batch (partial frames span feeds)."""
    rng = random.Random(1)
    txs = _txs(rng, 50, size=37)
    stream = _stream(txs)
    batcher = mk_batcher(1 << 20)
    framer = mk_framer()
    pos = 0
    while pos < len(stream):
        n = rng.randint(1, 11)
        framer.feed(batcher, stream[pos : pos + n])
        pos += n
    sealed = batcher.seal()
    assert sealed.message == encode_batch(txs)


@pytest.mark.parametrize("name,mk_batcher,mk_framer", _impls())
def test_multiple_connections_share_batcher(name, mk_batcher, mk_framer):
    """Per-connection framers feeding one shared batcher interleave whole
    transactions (never partial bytes)."""
    rng = random.Random(2)
    txs_a, txs_b = _txs(rng, 20), _txs(rng, 20)
    batcher = mk_batcher(1 << 20)
    fa, fb = mk_framer(), mk_framer()
    sa, sb = _stream(txs_a), _stream(txs_b)
    # Interleave partial feeds from two connections.
    fa.feed(batcher, sa[:100])
    fb.feed(batcher, sb[:33])
    fa.feed(batcher, sa[100:])
    fb.feed(batcher, sb[33:])
    sealed = batcher.seal()
    assert sealed.tx_count == 40
    # Every tx present exactly once (order depends on interleave).
    from narwhal_tpu.messages import decode_worker_message

    kind, batch = decode_worker_message(sealed.message)
    assert kind == "batch"
    assert sorted(batch) == sorted(txs_a + txs_b)


@pytest.mark.parametrize("name,mk_batcher,mk_framer", _impls())
def test_ready_threshold(name, mk_batcher, mk_framer):
    batcher = mk_batcher(100)
    framer = mk_framer()
    framer.feed(batcher, frame(bytes(60)))
    assert not batcher.ready()
    framer.feed(batcher, frame(bytes(60)))
    assert batcher.ready()


@pytest.mark.parametrize("name,mk_batcher,mk_framer", _impls())
def test_threshold_splits_mid_chunk(name, mk_batcher, mk_framer):
    """One big chunk must seal at tx granularity (reference
    batch_maker.rs:77-87 checks the threshold per tx): 8×100 B txs with a
    400 B threshold yield two 4-tx batches, not one 8-tx batch."""
    txs = [bytes([1]) + i.to_bytes(8, "little") + bytes(91) for i in range(8)]
    batcher = mk_batcher(400)
    framer = mk_framer()
    sealed = []
    more = framer.feed(batcher, _stream(txs))
    while more:
        sealed.append(batcher.seal())
        more = framer.feed(batcher, b"")
    if batcher.tx_count:
        sealed.append(batcher.seal())
    assert [s.tx_count for s in sealed] == [4, 4]
    assert sealed[0].message == encode_batch(txs[:4])
    assert sealed[1].message == encode_batch(txs[4:])


@pytest.mark.parametrize("name,mk_batcher,mk_framer", _impls())
def test_oversized_frame_rejected(name, mk_batcher, mk_framer):
    batcher = mk_batcher(100)
    framer = mk_framer()
    import struct

    bad = struct.pack("<I", 33 * 1024 * 1024)
    with pytest.raises(ValueError):
        framer.feed(batcher, bad + b"xxxx")


def test_validate_batch():
    rng = random.Random(3)
    txs = _txs(rng, 10)
    msg = encode_batch(txs)
    assert native.validate_batch(msg) == 10
    # Tag mismatch, truncation, count lies, oversized entry: all rejected.
    assert native.validate_batch(b"\x01" + msg[1:]) == -1
    assert native.validate_batch(msg[:-1]) == -1
    assert native.validate_batch(msg + b"x") == -1
    bad = bytearray(msg)
    bad[1] = 11  # count claims one more tx than present
    assert native.validate_batch(bytes(bad)) == -1
    import struct as _s

    huge = b"\x00" + _s.pack("<I", 1) + _s.pack("<I", 33 * 1024 * 1024)
    assert native.validate_batch(huge) == -1
    # The Python twin agrees.
    lib, native._lib = native._lib, None
    try:
        builder = native._load  # force fallback by masking the lib
        native._load = lambda: None
        assert native.validate_batch(msg) == 10
        assert native.validate_batch(msg[:-1]) == -1
    finally:
        native._load = builder
        native._lib = lib


def test_store_truncates_torn_tail(tmp_path):
    """A torn record is physically truncated on replay, so post-recovery
    appends stay replayable (not shadowed by tail garbage)."""
    from narwhal_tpu.store import Store

    path = str(tmp_path / "store.log")
    s = Store(path)
    s.write(b"k1", b"v1")
    s.close()
    with open(path, "ab") as f:
        f.write(b"\xff\xff\xff")  # torn tail from a crash mid-write
    s2 = Store(path)
    assert s2.read(b"k1") == b"v1"
    s2.write(b"k2", b"v2")
    s2.close()
    s3 = Store(path)
    assert s3.read(b"k1") == b"v1" and s3.read(b"k2") == b"v2"
    s3.close()


def test_native_is_available():
    """This environment has a C toolchain; the real library must build —
    the Python twin is a fallback for exotic deploys, not for CI."""
    assert native.native_available()
