"""Tier-1 clean-run health test: a healthy 4-node local_bench run must end
with ZERO firing health rules (no false positives — an alert layer that
cries wolf on a clean committee is worse than none) and a populated live
timeline: every node process scraped at least 3 times during the window,
and a per-peer RTT matrix naming each primary's three peers.

This is the false-positive half of the acceptance pair with
tests/test_health_failover.py (the true-positive half), and the first
test to drive benchmark/local_bench.py end to end under pytest."""

import json
import os
import shutil
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmark.local_bench import run_bench  # noqa: E402


def _run_clean_bench(tmp_path):
    """Same shared-core retry convention as tests/test_remote_bench.py:
    a fixed-duration measurement window on a loaded host can starve the
    whole committee — a host artifact, retried once with the scraped
    time-series dumped for diagnosis.  A genuine regression fails both
    attempts."""
    for attempt in (1, 2):
        result = run_bench(
            nodes=4,
            workers=1,
            rate=2_000,
            tx_size=512,
            duration=8,
            base_port=7600,
            workdir=str(tmp_path / f"bench-{attempt}"),
            quiet=True,
            scrape_interval=1.0,
            # ISSUE 11: every clean run also exports the whole committee
            # as ONE Perfetto-loadable Chrome trace — round-tripped and
            # asserted below (8 process rows, cross-process digest flows).
            trace_out=str(tmp_path / f"bench-{attempt}" / "trace.json"),
            # The ISSUE 9 loop-watchdog smoke arm: every node arms the
            # event-loop stall watchdog so a clean run MEASURES (not
            # infers) that no callback held its loop — the series lands
            # in the bench JSON `runtime` section, asserted below.
            loop_watchdog_ms=100,
            # Widen the window on wall-clock payload-commit progress: on
            # a starved core the clients can ramp so late that a fixed
            # 8 s window closes before the first client batch commits.
            progress_wait=30,
        )
        ok = (
            result.errors == []
            and result.committed_batches > 0
            # Every node answered the quiesce /healthz round: a node the
            # probe couldn't reach (status None, a starved-host artifact
            # the harness gate deliberately ignores) fails THIS test's
            # strict assertions below, so burn the retry on it.
            and all(
                v["status"] == 200
                for v in (result.timeline.get("healthz") or {}).values()
            )
        )
        if ok or attempt == 2:
            return result, str(tmp_path / f"bench-{attempt}")
        print(
            f"window {attempt} failed (errors={result.errors!r}); "
            "scraped timeline dump:",
            file=sys.stderr,
        )
        for node, series in sorted(
            (result.timeline.get("nodes") or {}).items()
        ):
            last = series[-1] if series else {}
            print(
                f"  {node}: {len(series)} samples, last={json.dumps(last)}",
                file=sys.stderr,
            )


def test_clean_local_bench_has_timeline_and_no_firing_rules(tmp_path):
    result, workdir = _run_clean_bench(tmp_path)

    # CI artifacts: the committee timeline, the exported Perfetto trace,
    # and the quiesce flight rings from the bench run, uploaded by the
    # workflow (same NARWHAL_METRICS_DUMP convention as the metrics-smoke
    # snapshot; `make trace-smoke` drives this test for exactly these).
    dump_dir = os.environ.get("NARWHAL_METRICS_DUMP")
    if dump_dir:
        os.makedirs(dump_dir, exist_ok=True)
        with open(os.path.join(dump_dir, "timeline.json"), "w") as f:
            json.dump(result.timeline, f, indent=1)
        trace_src = os.path.join(workdir, "trace.json")
        if os.path.exists(trace_src):
            shutil.copyfile(
                trace_src, os.path.join(dump_dir, "trace-smoke.json")
            )
        with open(os.path.join(dump_dir, "flight-rings.json"), "w") as f:
            json.dump(result.flight, f, indent=1)
        # PR 17: the skew-corrected causal sections as their own
        # artifact — slowest committed chain, who-closed-the-quorum
        # table, and the per-node clock corrections behind the join.
        with open(os.path.join(dump_dir, "critical-path.json"), "w") as f:
            json.dump(
                {
                    "critical_path": result.critical_path,
                    "stragglers": result.stragglers,
                    "clock": result.clock,
                },
                f,
                indent=1,
            )

    # The run itself is clean: parses, commits, cross-validates, and —
    # new gate — no node's /healthz reported a firing rule at quiesce
    # (check_quiesce_health would have appended an error).
    assert result.errors == []
    assert result.committed_batches > 0

    timeline = result.timeline
    nodes = timeline["nodes"]
    # All 8 processes (4 primaries + 4 workers) were scraped, ≥3 samples
    # each over the 8 s window at 1 Hz.
    expected = {f"primary-{i}" for i in range(4)} | {
        f"worker-{i}-0" for i in range(4)
    }
    assert set(nodes) == expected, f"scraped: {sorted(nodes)}"
    for name, series in nodes.items():
        assert len(series) >= 3, f"{name}: only {len(series)} samples"
        # No sample ever saw a firing rule on a clean run.
        assert all(p["health_firing"] == 0 for p in series), (
            name,
            [p for p in series if p["health_firing"]],
        )
    # Primaries show commit progress over time (the live channel the
    # post-mortem snapshots cannot provide).
    for i in range(4):
        series = nodes[f"primary-{i}"]
        assert series[-1]["commits"] > 0
        assert series[-1]["round"] > 2

    # Per-peer RTT matrix: each primary exchanged ACKed frames with its
    # three peers, each with a positive mean RTT.
    rtt = timeline["rtt_ms"]
    for i in range(4):
        peers = rtt.get(f"primary-{i}", {})
        assert len(peers) >= 3, f"primary-{i} RTT peers: {sorted(peers)}"
        for peer, stats in peers.items():
            assert stats["count"] > 0 and stats["mean_ms"] > 0

    # Every node answered the quiesce /healthz round with 200.
    healthz = timeline["healthz"]
    assert set(healthz) == expected
    for name, verdict in healthz.items():
        assert verdict["status"] == 200, (name, verdict)
        assert verdict["firing"] == [], (name, verdict)

    # -- wire-goodput ledger (ISSUE 7 acceptance) ----------------------------
    wire = result.wire
    totals = wire["totals"]
    # (a) Per-type wire bytes (incl. retransmits) sum to the raw sender
    # byte counters within 2%: every sent byte carries a type label.
    assert totals["sender_coverage"] is not None
    assert abs(totals["sender_coverage"] - 1.0) <= 0.02, totals
    # The protocol's frame types all flowed on a busy committee.
    for t in ("batch", "batch_digest", "header", "vote", "certificate"):
        assert wire["out"].get(t, {}).get("bytes", 0) > 0, (t, wire["out"])
    # Sender vs receiver totals reconcile per type.  Loopback TCP loses
    # nothing mid-run, but teardown kills nodes with frames in flight
    # and the final snapshot is written at SIGTERM — allow the tail.
    for t, ratio in wire["recv_vs_sent"].items():
        assert 0.85 <= ratio <= 1.01, (t, ratio, wire)
    # -- wire-format v2 gates (ISSUE 13) -------------------------------------
    # Goodput: committed payload ÷ total wire bytes.  Pre-v2 this was
    # structurally < 1 (broadcast amplification); with wire v2's
    # residual deflate + digest references the wire side shrinks below
    # the committed payload, so the CI-gated floor is 0.40 (the r12
    # baseline was 0.24; a clean v2 run measures 2.5-4.5 on this
    # workload) and there is deliberately no upper bound.
    assert wire["goodput_ratio"] >= 0.40, wire
    assert wire["format_version"] == 2, wire
    # Compression actually engaged (raw vs wire bytes, first
    # transmissions), and the signature-material fraction — computed
    # against RAW frame bytes with the v2 per-vote arithmetic — stays a
    # meaningful fraction.
    assert wire["compression_ratio"] > 1.5, wire
    assert 0 < wire["cert_sig_bytes_fraction"] < 1, wire
    # Coalescing is live, not bypassed: flushes are counted, and some
    # flushes carried more than one frame (multi-frame evidence).  The
    # strict mean-frames-per-flush > 1.5 gate lives on the tier-1
    # in-process burst run (tests/test_wire_v2.py::
    # test_coalesced_flush_batches_buffered_frames): on THIS bench's
    # operating point the per-connection inter-frame gaps measure
    # 20-100 ms (round-cadence paced, not bursty), so a >1.5 bench mean
    # would require delaying protocol frames by tens of milliseconds —
    # the wrong trade.  What is gated here: the histogram exists, every
    # flush is counted, and batching happened.
    assert wire["flushes"] > 0, wire
    assert wire["frames_per_flush_mean"] > 1.0, wire
    assert wire["acks_per_flush_mean"] >= 1.0, wire

    # -- loop-stall watchdog smoke arm (ISSUE 9 acceptance) ------------------
    # Every node ran with NARWHAL_LOOP_WATCHDOG_MS=100, so every
    # post-mortem snapshot must carry the runtime.loop_stall_seconds
    # series (count may be 0 — "watchdog ran, saw no stall" is the
    # measurement; a missing series means the watchdog never armed).
    runtime = result.runtime
    assert len(runtime) == 8, sorted(runtime)
    for node, r in runtime.items():
        assert "count" in r["loop_stall_seconds"], (node, r)
        assert r["loop_stall_seconds"]["count"] >= 0
        assert r["stalls"] >= 0

    # -- crypto-cost ledger (ISSUE 7 acceptance) -----------------------------
    crypto = result.crypto
    # The committee verifies through the burst seam; signing splits into
    # header/vote sites.
    assert crypto["verify"]["batch_burst"]["ops"] > 0
    assert crypto["sign"]["header"]["ops"] > 0
    assert crypto["sign"]["vote"]["ops"] > 0
    # (b) Protocol-arithmetic cross-check within 5%: one verified claim
    # per peer vote, quorum+1 claims per wire certificate.
    check = crypto["protocol_check"]
    assert abs(check["votes"]["ratio"] - 1.0) <= 0.05, check
    assert abs(check["certificates"]["ratio"] - 1.0) <= 0.05, check

    # -- queue & backpressure accounting (ISSUE 17 tentpole) -----------------
    # All 8 processes (4 primaries + 4 workers) must publish their
    # per-channel InstrumentedQueue tables into the bench JSON's queues
    # section, and the committee-wide aggregate must carry the load-
    # bearing channels with sane capacities.  A clean run at this rate
    # must not have dropped anything into a full queue on the wide
    # 1000-capacity channels.
    queues = result.queues
    assert len(queues["nodes"]) == 8, sorted(queues["nodes"])
    for pid, channels in queues["nodes"].items():
        assert channels, pid
    agg = queues["channels"]
    for ch in (
        "node.tx_output",
        "primary.others_digests",
        "worker.to_primary",
        "worker.to_quorum",
    ):
        assert ch in agg, sorted(agg)
        assert agg[ch]["enqueued"] > 0, (ch, agg[ch])
    assert agg["worker.to_quorum"]["capacity"] == 8  # QUORUM_WINDOW
    assert agg["node.tx_output"]["capacity"] >= 16
    for ch, a in agg.items():
        if a["capacity"] >= 16:
            assert a["full"] == 0, (ch, a)

    # -- flight recorder at quiesce (ISSUE 11 satellite) ---------------------
    # Every node's /debug/flight ring rides in the bench JSON, so even a
    # clean run carries its last-seconds event history.  Primaries must
    # show protocol landmarks plus the per-tick delta samples.
    expected = {f"primary-{i}" for i in range(4)} | {
        f"worker-{i}-0" for i in range(4)
    }
    flight = result.flight
    assert set(flight) == expected, sorted(flight)
    for name in expected:
        ring = flight[name]
        assert ring is not None and ring["events"], name
    for i in range(4):
        kinds = {e["kind"] for e in flight[f"primary-{i}"]["events"]}
        assert "round_advance" in kinds, (i, sorted(kinds))
        assert "commit" in kinds, (i, sorted(kinds))
        assert "tick" in kinds, (i, sorted(kinds))

    # -- skew-corrected critical path + straggler attribution (PR 17) --------
    # A clean committed run must yield at least one digest carrying the
    # FULL stage chain, and the slowest chain's per-leg sums must
    # telescope to its end-to-end span within 10% — a bigger gap means a
    # stage was dropped from STAGE_ORDER or stamped on an uncorrected
    # clock (the join is only trustworthy when this holds).
    cp = result.critical_path
    assert cp.get("full_chains", 0) > 0, cp
    path = cp["path"]
    assert path["e2e_ms"] > 0, path
    assert len(path["legs_ms"]) >= 5, path
    assert abs(path["legs_sum_ms"] - path["e2e_ms"]) <= 0.10 * path[
        "e2e_ms"
    ] + 0.001, path
    # Quorum stragglers: every assembled certificate charged exactly one
    # closing voter, so the ranked table is non-empty and its addresses
    # are committee primaries.
    stragglers = result.stragglers
    ranked = stragglers.get("vote_quorum") or []
    assert ranked, stragglers
    assert all(e["count"] > 0 for e in ranked), ranked
    assert ranked == sorted(
        ranked, key=lambda e: (-e["count"], e["address"])
    ), ranked
    gaps = stragglers.get("gaps") or {}
    assert gaps.get("vote_quorum_gap_ms", {}).get("count", 0) > 0, gaps

    # -- unified Perfetto trace export (ISSUE 11 tentpole) -------------------
    # One --trace-out command round-trips the run into schema-valid
    # Chrome trace JSON: all 8 process rows and ≥1 cross-process digest
    # flow (seal on a worker row → commit on a primary row).
    with open(os.path.join(workdir, "trace.json")) as f:
        trace = json.load(f)
    assert trace["traceEvents"], "trace is empty"
    for ev in trace["traceEvents"]:
        assert "ph" in ev and "pid" in ev
        if ev["ph"] == "X":
            assert ev["dur"] >= 1 and ev["ts"] >= 0
    names = trace["metadata"]["node_pids"]
    assert set(names) == expected, sorted(names)
    flows = {}
    for ev in trace["traceEvents"]:
        if ev["ph"] in "stf":
            flows.setdefault(ev["id"], []).append(ev)
    cross = [
        chain for chain in flows.values()
        if len({ev["pid"] for ev in chain}) >= 2
        and chain[0]["ph"] == "s"
        and chain[-1]["ph"] == "f"
    ]
    assert cross, f"no cross-process digest flow among {len(flows)} flows"
    worker_pids = {names[n] for n in names if n.startswith("worker")}
    assert any(c[0]["pid"] in worker_pids for c in cross), (
        "no flow starts at a worker's seal slice"
    )

    # The committee-row critical-path track (PR 17): the exported trace
    # carries the same slowest chains as ranked leg slices on a
    # dedicated "committee" process row.
    assert trace["metadata"]["critical_path"].get("full_chains", 0) > 0
    cp_slices = [
        ev for ev in trace["traceEvents"]
        if ev["ph"] == "X" and ev.get("cat") == "critical-path"
    ]
    assert cp_slices, "no critical-path slices in the trace"
    assert {ev["args"]["rank"] for ev in cp_slices} >= {1}, cp_slices

    # -- sampling profiler, always on (ISSUE 11 tentpole) --------------------
    # Default NARWHAL_PROFILE_HZ (~67) armed the profiler in every node:
    # the trace carries sampled-CPU slices and every snapshot-backed row
    # must have accumulated samples (asserted via the cpu track the
    # exporter builds from `profile.timeline`).
    cpu_slices = [
        ev for ev in trace["traceEvents"]
        if ev["ph"] == "X" and ev.get("cat") == "cpu"
    ]
    assert cpu_slices, "no sampled-CPU slices in the trace"
    # Primaries burn their loop in protocol work; each primary row shows
    # sampled CPU (a worker on a starved host may idle, so only gate the
    # primaries).
    cpu_pids = {ev["pid"] for ev in cpu_slices}
    for i in range(4):
        assert names[f"primary-{i}"] in cpu_pids, f"primary-{i} has no cpu"
