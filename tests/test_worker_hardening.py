"""Worker-plane graceful-degradation hardening (ISSUE 8 satellites): the
Synchronizer's jittered capped exponential retry backoff, the Helper's
per-request digest bounds, the Processor's re-delivery dedup, and the
receiver's batch-size gate.  These are the defenses the worker-plane
fault scenarios (byzantine_worker.py) attack — each test here is the
deterministic unit twin of a fault_bench scenario."""

import asyncio
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from narwhal_tpu import metrics  # noqa: E402
from narwhal_tpu.crypto import Digest, digest32  # noqa: E402
from narwhal_tpu.messages import (  # noqa: E402
    decode_worker_message,
    encode_batch,
)
from narwhal_tpu.store import Store  # noqa: E402
from narwhal_tpu.worker.helper import Helper, max_request_digests  # noqa: E402
from narwhal_tpu.worker.processor import Processor  # noqa: E402
from narwhal_tpu.worker.synchronizer import Synchronizer  # noqa: E402
from narwhal_tpu.worker.worker import (  # noqa: E402
    WorkerReceiverHandler,
    max_batch_bytes,
)
from tests.common import (  # noqa: E402
    batch_digest,
    committee,
    keys,
    serialized_batch,
)


class FakeSender:
    """Recording stand-in for Simple/ReliableSender: every send lands in
    ``sent`` synchronously — no sockets, no scheduling jitter."""

    def __init__(self):
        self.sent = []  # (address, data)

    def send(self, address, data, msg_type="other"):
        self.sent.append((address, data))

    def lucky_broadcast(self, addresses, data, nodes, msg_type="other"):
        self.sent.append(("lucky", data))

    def close(self):
        pass


class FakeWriter:
    def __init__(self):
        self.acks = []

    async def send(self, data):
        self.acks.append(data)


def _counter(name):
    c = metrics.registry().counters.get(name)
    return c.value if c is not None else 0


def _digest(i: int) -> Digest:
    return Digest(bytes([i % 256]) * 32)


# -- synchronizer retry backoff ----------------------------------------------


def _make_sync(store=None, retry_ms=1_000, seed=7):
    c = committee()
    sync = Synchronizer(
        keys()[0].name, 0, c, store or Store(), retry_ms, 3,
        asyncio.Queue(), rng=random.Random(seed),
    )
    sync.sender = FakeSender()
    return sync


def test_one_request_per_backoff_window_and_windows_grow():
    """A pending digest is re-requested exactly once per backoff window,
    and the windows double (with 50-100% jitter) toward the cap — not the
    old fixed-cadence flood."""

    async def go():
        sync = _make_sync(retry_ms=1_000)
        d = _digest(1)
        await sync._synchronize([d], keys()[1].name)
        assert len(sync.sender.sent) == 1  # the initial optimistic ask
        p = sync.pending[d]
        assert p.due == p.first_ts + 1.0  # first window un-jittered

        # Sweeps INSIDE a window never re-send.
        assert sync._retry_sweep(now=p.first_ts + 0.5) == 0
        assert sync._retry_sweep(now=p.first_ts + 0.99) == 0

        # Crossing the window re-sends exactly once and re-arms.
        assert sync._retry_sweep(now=p.first_ts + 1.0) == 1
        assert len(sync.sender.sent) == 2
        assert sync._retry_sweep(now=p.first_ts + 1.01) == 0

        # Drive 6 more windows: each sleep is jitter(delay) with delay
        # doubling toward the 60 s default cap — so the observed windows
        # must grow beyond any fixed cadence and stay under the cap.
        windows = [p.due - p.first_ts - 1.0]  # first retry window
        now = p.due
        for _ in range(6):
            assert sync._retry_sweep(now=now) == 1
            windows.append(p.due - now)
            now = p.due
        # delay sequence 1,2,4,8,16,32,60; jitter in [0.5,1.0]x.
        for i, w in enumerate(windows):
            expected = min(2.0 ** i, 60.0)
            assert 0.5 * expected - 1e-9 <= w <= expected + 1e-9, (i, w)
        assert windows[-1] > 10 * windows[0], "backoff never escalated"

        for t in sync._waiters.values():
            t.cancel()

    asyncio.run(asyncio.wait_for(go(), 10))


def test_resolved_digest_not_rerequested_mid_tick():
    """A digest whose batch landed in the store — even before the
    notify_read waiter task has had a chance to clear `pending` — must
    drop out of the retry sweep immediately."""

    async def go():
        store = Store()
        sync = _make_sync(store=store, retry_ms=100)
        d, still_missing = _digest(2), _digest(3)
        await sync._synchronize([d, still_missing], keys()[1].name)
        store.write(bytes(d), b"batch-bytes")  # waiter hasn't run yet
        assert d in sync.pending  # the race window under test
        n = sync._retry_sweep(now=sync.pending[d].due + 1)
        assert n == 1  # only the still-missing sibling escalated
        _, data = sync.sender.sent[-1]
        kind, digests, _ = decode_worker_message(data)
        assert kind == "batch_request"
        assert digests == [still_missing]
        for t in sync._waiters.values():
            t.cancel()

    asyncio.run(asyncio.wait_for(go(), 10))


def test_requests_chunk_under_helper_cap():
    """Both the initial ask and the retry escalation split their digest
    lists into frames of at most the Helper's per-request cap, so an
    honest sync storm never reads as the sync_flood attack."""

    async def go():
        cap = max_request_digests()
        sync = _make_sync(retry_ms=100)
        digests = [
            Digest(i.to_bytes(2, "big") * 16) for i in range(cap + 40)
        ]
        await sync._synchronize(digests, keys()[1].name)
        assert len(sync.sender.sent) == 2  # ceil((cap+40)/cap)
        for _, data in sync.sender.sent:
            kind, got, _ = decode_worker_message(data)
            assert kind == "batch_request" and len(got) <= cap

        sync.sender.sent.clear()
        now = max(p.due for p in sync.pending.values()) + 1
        assert sync._retry_sweep(now=now) == cap + 40
        assert len(sync.sender.sent) == 2
        for _, data in sync.sender.sent:
            _, got, _ = decode_worker_message(data)
            assert len(got) <= cap
        for t in sync._waiters.values():
            t.cancel()

    asyncio.run(asyncio.wait_for(go(), 10))


def test_unserved_sync_age_gauge_tracks_oldest():
    # Collect synchronizers leaked by earlier tests first: the gauge
    # reads the oldest pending entry across EVERY live instance.
    import gc

    gc.collect()

    async def go():
        gauge = metrics.registry().gauge_fns["worker.unserved_sync_age_seconds"]
        sync = _make_sync()
        base = gauge()
        await sync._synchronize([_digest(4)], keys()[1].name)
        await asyncio.sleep(0.15)
        assert gauge() >= 0.15 - 1e-3
        # Resolution clears the pending entry (waiter runs) → age drops.
        sync.store.write(bytes(_digest(4)), b"x")
        await asyncio.sleep(0.05)
        assert sync.pending == {}
        assert gauge() == base == 0.0

    asyncio.run(asyncio.wait_for(go(), 10))


# -- helper request bounds ----------------------------------------------------


def test_helper_truncates_and_counts_over_limit_request():
    """An over-limit BatchRequest is served only up to the cap, the
    remainder is dropped (not amplified), and the abuse is counted."""

    async def go():
        c = committee()
        store = Store()
        frames = {}
        for i in range(200):
            data = encode_batch([bytes([i % 256]) * 40])
            frames[digest32(data)] = data
            store.write(bytes(digest32(data)), data)
        helper = Helper(0, c, store, asyncio.Queue())
        helper.sender = FakeSender()
        assert helper.max_digests == 128

        before = _counter("worker.helper_rejected_requests")
        digests = list(frames)  # 200 > cap
        await helper._respond(
            "addr", helper._bound(digests, keys()[1].name)
        )
        assert len(helper.sender.sent) == 128  # truncated, not amplified
        assert _counter("worker.helper_rejected_requests") == before + 1

        # Duplicate digests within one request dedup to ONE serve — for
        # free, NOT counted as abuse (the counter feeds a latching rule;
        # an under-cap request with duplicates must not brand the peer).
        helper.sender.sent.clear()
        one = digests[0]
        await helper._respond(
            "addr", helper._bound([one] * 50, keys()[1].name)
        )
        assert len(helper.sender.sent) == 1
        assert _counter("worker.helper_rejected_requests") == before + 1

        # An in-bounds request is served in full with no rejection.
        helper.sender.sent.clear()
        await helper._respond(
            "addr", helper._bound(digests[:100], keys()[1].name)
        )
        assert len(helper.sender.sent) == 100
        assert _counter("worker.helper_rejected_requests") == before + 1

    asyncio.run(asyncio.wait_for(go(), 10))


def test_helper_cap_env_override(monkeypatch):
    monkeypatch.setenv("NARWHAL_HELPER_MAX_DIGESTS", "7")
    assert max_request_digests() == 7
    monkeypatch.setenv("NARWHAL_HELPER_MAX_DIGESTS", "bogus")
    assert max_request_digests() == 128
    monkeypatch.delenv("NARWHAL_HELPER_MAX_DIGESTS")
    assert max_request_digests() == 128


# -- processor dedup ----------------------------------------------------------


def test_duplicate_deliveries_store_and_report_once():
    """N duplicate deliveries of one batch (sync-storm re-sends) yield
    ONE store write and ONE digest message toward the primary."""

    async def go():
        store = Store()
        writes = []
        orig = store.write
        store.write = lambda k, v: (writes.append(k), orig(k, v))
        in_q, out_q = asyncio.Queue(), asyncio.Queue()
        proc = Processor(0, store, in_q, out_q, own_digests=False)
        task = asyncio.get_running_loop().create_task(proc.run())
        before = _counter("worker.duplicate_batches")
        for _ in range(5):
            await in_q.put(serialized_batch())
        msg = await asyncio.wait_for(out_q.get(), 5)
        await asyncio.sleep(0.1)  # let the duplicates drain
        assert out_q.empty(), "duplicate digest message reached the primary"
        assert writes == [bytes(batch_digest())]
        assert _counter("worker.duplicate_batches") == before + 4
        assert msg is not None
        task.cancel()

    asyncio.run(asyncio.wait_for(go(), 10))


def test_own_batches_exempt_from_dedup():
    """A byte-identical own re-seal still reports its digest: the dedup
    gate applies only to network re-deliveries."""

    async def go():
        store = Store()
        in_q, out_q = asyncio.Queue(), asyncio.Queue()
        proc = Processor(0, store, in_q, out_q, own_digests=True)
        task = asyncio.get_running_loop().create_task(proc.run())
        for _ in range(2):
            await in_q.put((batch_digest(), serialized_batch()))
        await asyncio.wait_for(out_q.get(), 5)
        await asyncio.wait_for(out_q.get(), 5)  # second one NOT suppressed
        task.cancel()

    asyncio.run(asyncio.wait_for(go(), 10))


# -- batch size validation ----------------------------------------------------


def test_oversized_batch_rejected_uncounted_unacked():
    async def go():
        others_q, helper_q = asyncio.Queue(), asyncio.Queue()
        handler = WorkerReceiverHandler(others_q, helper_q, max_batch_bytes=512)
        writer = FakeWriter()
        before = _counter("worker.garbage_batches")

        # A structurally VALID but oversized junk batch: the size gate
        # must reject it before any hashing/persisting.
        junk = b"\x00" + (1).to_bytes(4, "little") \
            + (2_000).to_bytes(4, "little") + bytes(2_000)
        await handler.dispatch(writer, junk)
        assert _counter("worker.garbage_batches") == before + 1
        assert writer.acks == [] and others_q.empty()

        # A truncated frame fails the structural walk (malformed path).
        m_before = _counter("worker.malformed_frames")
        truncated = b"\x00" + (3).to_bytes(4, "little") + b"\x77"
        await handler.dispatch(writer, truncated)
        assert _counter("worker.malformed_frames") == m_before + 1
        assert writer.acks == [] and others_q.empty()

        # An in-bounds valid batch still flows: ACK (stamped with the
        # sender's wall clock for the clocksync estimator) + queued.
        from narwhal_tpu.network.clocksync import parse_ack

        await handler.dispatch(writer, serialized_batch())
        assert len(writer.acks) == 1
        assert parse_ack(writer.acks[0]) is not None
        assert await asyncio.wait_for(others_q.get(), 1) == serialized_batch()

    asyncio.run(asyncio.wait_for(go(), 10))


def test_max_batch_bytes_default_and_override(monkeypatch):
    assert max_batch_bytes(500_000) == 2 * 500_000 + 65_536
    monkeypatch.setenv("NARWHAL_MAX_BATCH_BYTES", "123456")
    assert max_batch_bytes(500_000) == 123_456
    monkeypatch.setenv("NARWHAL_MAX_BATCH_BYTES", "junk")
    assert max_batch_bytes(1_000) == 2 * 1_000 + 65_536


def test_absurd_request_frame_dropped_before_decode(monkeypatch):
    """A BatchRequest frame too large to ever survive the Helper's
    dedup+cap is dropped on a length compare — the decode itself is
    O(frame), and the sync_flood attacker must not convert capped reply
    amplification into request-decode CPU burn."""

    async def go():
        from narwhal_tpu import messages
        from narwhal_tpu.worker.worker import max_request_bytes

        others_q, helper_q = asyncio.Queue(), asyncio.Queue()
        handler = WorkerReceiverHandler(others_q, helper_q, max_batch_bytes=None)
        writer = FakeWriter()
        decodes = []
        orig = messages.decode_worker_message
        monkeypatch.setattr(
            "narwhal_tpu.worker.worker.decode_worker_message",
            lambda m: (decodes.append(1), orig(m))[1],
        )

        before = _counter("worker.helper_rejected_requests")
        huge = bytes([1]) + bytes(max_request_bytes() + 100)
        await handler.dispatch(writer, huge)
        assert decodes == [], "oversized request frame reached the decoder"
        assert _counter("worker.helper_rejected_requests") == before + 1
        assert writer.acks == [] and helper_q.empty()

        # The fault suite's own 1024-digest flood sits UNDER the byte
        # gate (8x the digest cap): it must still reach the Helper's
        # truncation path, not be silently pre-dropped.
        flood = encode_batch_request_1024()
        assert len(flood) <= max_request_bytes()
        await handler.dispatch(writer, flood)
        assert decodes == [1]
        assert not helper_q.empty()

    def encode_batch_request_1024():
        from narwhal_tpu.crypto import Digest
        from narwhal_tpu.messages import encode_batch_request

        return encode_batch_request(
            [Digest(i.to_bytes(2, "big") * 16) for i in range(1024)],
            keys()[0].name,
        )

    asyncio.run(asyncio.wait_for(go(), 10))
