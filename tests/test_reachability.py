"""KernelTusk (JAX leader-chain scan) vs. golden Python Tusk: identical
commit sequences on the reference consensus scenarios plus randomized DAGs.

The golden scenarios mirror reference consensus_tests.rs (commit_one,
dead_node, not_enough_support, missing_leader); the fuzz builds rounds with
random live subsets (≥ 2f+1) and random quorum parent choices and asserts
the two implementations commit certificate-for-certificate."""

import random

from narwhal_tpu.consensus import Tusk
from narwhal_tpu.ops.reachability import KernelTusk
from narwhal_tpu.primary.messages import genesis

from tests.common import committee, keys
from tests.test_consensus import (
    make_certificates,
    mock_certificate,
    sorted_names,
    genesis_digests,
    feed,
)


def both(certs, gc_depth=50):
    c = committee()
    golden = feed(Tusk(c, gc_depth=gc_depth, fixed_coin=True), certs)
    kernel = feed(KernelTusk(c, gc_depth=gc_depth, fixed_coin=True), certs)
    assert [x.digest() for x in kernel] == [x.digest() for x in golden]
    return golden


def test_commit_one_equivalence():
    c = committee()
    names = sorted_names()
    certs, next_parents = make_certificates(1, 4, genesis_digests(c), names)
    _, trigger = mock_certificate(names[0], 5, next_parents)
    certs.append(trigger)
    committed = both(certs)
    assert [x.round for x in committed] == [1, 1, 1, 1, 2]


def test_dead_node_equivalence():
    c = committee()
    names = sorted_names()[:3]
    certs, _ = make_certificates(1, 9, genesis_digests(c), names)
    committed = both(certs)
    assert len(committed) == 16


def test_not_enough_support_equivalence():
    c = committee()
    names = sorted_names()
    certs = []
    out, parents = make_certificates(1, 1, genesis_digests(c), names[:3])
    certs.extend(out)
    leader_2_digest, cert = mock_certificate(names[0], 2, parents)
    certs.append(cert)
    out, parents = make_certificates(2, 2, parents, names[1:])
    certs.extend(out)
    next_parents = set()
    d, cert = mock_certificate(names[1], 3, parents)
    certs.append(cert)
    next_parents.add(d)
    d, cert = mock_certificate(names[2], 3, parents)
    certs.append(cert)
    next_parents.add(d)
    d, cert = mock_certificate(names[0], 3, parents | {leader_2_digest})
    certs.append(cert)
    next_parents.add(d)
    parents = next_parents
    out, parents = make_certificates(4, 6, parents, names[:3])
    certs.extend(out)
    _, trigger = mock_certificate(names[0], 7, parents)
    certs.append(trigger)
    both(certs)


def test_missing_leader_equivalence():
    c = committee()
    names = sorted_names()
    certs = []
    # Leader (authority 0) absent from rounds 1-4.
    out, parents = make_certificates(1, 4, genesis_digests(c), names[1:])
    certs.extend(out)
    out, parents = make_certificates(5, 7, parents, names)
    certs.extend(out)
    _, trigger = mock_certificate(names[0], 8, parents)
    certs.append(trigger)
    both(certs)


def _random_dag_certs(rng, rounds):
    """Random live subsets of ≥ 3 authorities per round, each picking a
    random ≥ 3-subset of the previous round as parents."""
    names = sorted_names()
    certs = []
    parents = sorted(genesis_digests(committee()))
    for r in range(1, rounds + 1):
        live = rng.sample(names, rng.randint(3, 4))
        next_parents = []
        for name in sorted(live):
            chosen = rng.sample(parents, min(len(parents), rng.randint(3, len(parents))))
            digest, cert = mock_certificate(name, r, chosen)
            certs.append(cert)
            next_parents.append(digest)
        parents = sorted(next_parents)
    return certs


def test_fuzz_equivalence():
    rng = random.Random(0xDA6)
    for trial in range(8):
        certs = _random_dag_certs(rng, rounds=rng.randint(6, 20))
        order = list(certs)
        # Shuffle delivery within causal constraints: keep round order.
        order.sort(key=lambda x: (x.round, rng.random()))
        both(order)


def test_fuzz_equivalence_out_of_order_delivery():
    """Children delivered BEFORE their parents: exercises KernelTusk's
    waiting-child edge repair (a child inserted while its parent digest is
    unknown must get its dense-window edge when the parent arrives).  Both
    implementations see the identical delivery order, so their commit
    sequences must still match certificate-for-certificate."""
    rng = random.Random(0xBEEF)
    for trial in range(6):
        certs = _random_dag_certs(rng, rounds=rng.randint(6, 16))
        order = list(certs)
        # Jitter rounds by up to ~2 so a good fraction of children precede
        # their round-(r-1) parents in delivery order.
        order.sort(key=lambda x: x.round + rng.uniform(-2.2, 0.0))
        assert any(
            a.round > b.round
            for a, b in zip(order, order[1:])
        ), "fixture produced no out-of-order pair"
        both(order)


def test_causal_mask_matches_host_bfs():
    """causal_mask_scan == transitive closure of parent links (host BFS)."""
    import numpy as np
    import jax.numpy as jnp
    from narwhal_tpu.ops.reachability import causal_mask_scan

    rng = np.random.default_rng(42)
    W, N = 16, 8
    for _ in range(5):
        exists = rng.random((W, N)) < 0.8
        exists[0] = True
        parent = np.zeros((W, N, N), dtype=bool)
        for w in range(1, W):
            for i in range(N):
                if exists[w, i]:
                    prev = np.flatnonzero(exists[w - 1])
                    if len(prev):
                        take = rng.choice(prev, size=min(3, len(prev)), replace=False)
                        parent[w, i, take] = True
        starts = np.argwhere(exists)
        w0, i0 = starts[rng.integers(len(starts))]
        onehot = np.zeros(N, dtype=bool)
        onehot[i0] = True

        got = np.asarray(
            causal_mask_scan(
                jnp.asarray(parent), jnp.asarray(exists),
                jnp.int32(w0), jnp.asarray(onehot), W,
            )
        )

        want = np.zeros((W, N), dtype=bool)
        want[w0, i0] = True
        for w in range(int(w0), 0, -1):
            for i in np.flatnonzero(want[w]):
                want[w - 1] |= parent[w, i] & exists[w - 1]
        assert (got == want).all()


def test_fuzz_small_gc_depth():
    rng = random.Random(7)
    for _ in range(3):
        certs = _random_dag_certs(rng, rounds=14)
        both(certs, gc_depth=4)


def test_window_capped_one_static_shape():
    """The kernel window is a single static shape derived from gc_depth
    (VERDICT r2: unbounded power-of-two growth meant a commit stall could
    trigger fresh XLA compiles on the consensus critical path)."""
    c = committee()
    for gc_depth, want in ((6, 8), (14, 16), (50, 64), (126, 128)):
        k = KernelTusk(c, gc_depth=gc_depth, fixed_coin=True)
        assert k.max_window == want, (gc_depth, k.max_window)


def test_stall_beyond_window_falls_back_to_python():
    """A DAG span exceeding the static window must use the golden Python
    walk (same output, zero new compiled shapes) instead of growing the
    kernel window."""
    import narwhal_tpu.ops.reachability as R

    c = committee()
    names = sorted_names()
    # Stall: the fixed-coin leader (names[0]) is dead for rounds 1-17, so
    # nothing commits while the DAG grows 17 rounds past genesis.  It then
    # revives; the round-18 leader gets support and the first commit spans
    # 19 rounds > window 8 (gc_depth 6).
    certs1, parents = make_certificates(1, 17, genesis_digests(c), names[1:])
    certs2, parents = make_certificates(18, 19, parents, names)
    _, trigger = mock_certificate(names[0], 20, parents)
    # After the catch-up commit the span is small again: further rounds
    # must go through the kernel path at the one static shape.
    certs3, parents = make_certificates(20, 23, parents, names)
    _, trigger2 = mock_certificate(names[1], 24, parents)
    all_certs = certs1 + certs2 + [trigger] + certs3 + [trigger2]

    kernel_tusk = KernelTusk(c, gc_depth=6, fixed_coin=True)
    calls = []
    real = R.leader_commit_scan_counts

    def counting(*args, **kw):
        calls.append(args[-1] if not kw else kw.get("window"))
        return real(*args, **kw)

    R.leader_commit_scan_counts = counting
    try:
        kernel = feed(kernel_tusk, all_certs)
    finally:
        R.leader_commit_scan_counts = real

    golden_same_depth = feed(Tusk(c, gc_depth=6, fixed_coin=True), all_certs)
    assert [x.digest() for x in kernel] == [
        x.digest() for x in golden_same_depth
    ]
    assert kernel, "nothing committed — fixture broken"
    assert kernel_tusk.python_fallbacks >= 1
    # The kernel path did run after the stall, always at the static shape.
    assert calls, "kernel never used after catch-up"
    assert all(w == kernel_tusk.max_window for w in calls), calls


def test_gc_window_wrap_equivalence():
    """Continuous commits across 3× the static window: the device window
    shifts (donated gather) on every commit and the total shift distance
    wraps past W several times — the kernel must stay certificate-for-
    certificate equal to the golden walk, without ever falling back."""
    c = committee()
    names = sorted_names()
    gc_depth = 6  # W = 8
    certs, _ = make_certificates(1, 30, genesis_digests(c), names)

    golden = feed(Tusk(c, gc_depth=gc_depth, fixed_coin=True), certs)
    kernel_tusk = KernelTusk(c, gc_depth=gc_depth, fixed_coin=True)
    kernel = feed(kernel_tusk, certs)
    assert [x.digest() for x in kernel] == [x.digest() for x in golden]
    assert golden, "fixture must commit"
    # Commits kept the span inside the window the whole way: the wrap was
    # absorbed by shifts, not by Python fallbacks.
    assert kernel_tusk.python_fallbacks == 0
    assert kernel_tusk._win_base == kernel_tusk.state.last_committed_round
    assert kernel_tusk._win_base > 3 * kernel_tusk.max_window - 10


def test_multi_round_commit_burst_equivalence():
    """Odd rounds delivered before even rounds: no arrival can trigger a
    commit until one final trigger certificate, which then commits the
    ENTIRE chain of linked leaders in one order_leaders call — a single
    committed-bitmap fetch covering many leader rounds.  The inverted
    delivery also floods the kernel's waiting-child repair (every even-
    round parent arrives after its odd-round children)."""
    c = committee()
    names = sorted_names()
    certs, parents = make_certificates(1, 16, genesis_digests(c), names)
    # Odd rounds first (ascending), then even rounds: odd arrivals find no
    # even-round leader in the DAG yet, even arrivals never trigger the
    # commit check (r = round-1 must be even).
    order = sorted(certs, key=lambda x: (x.round % 2 == 0, x.round))
    _, trigger = mock_certificate(names[0], 17, parents)

    golden = Tusk(c, gc_depth=50, fixed_coin=True)
    kernel_tusk = KernelTusk(c, gc_depth=50, fixed_coin=True)
    assert feed(golden, order) == []
    assert feed(kernel_tusk, order) == []
    got = kernel_tusk.process_certificate(trigger)
    want = golden.process_certificate(trigger)
    assert [x.digest() for x in got] == [x.digest() for x in want]
    # The burst commits several leader rounds in one batch.
    assert len({x.round for x in got if x.round % 2 == 0}) >= 3
    assert kernel_tusk.python_fallbacks == 0


def test_device_window_matches_dict_dag_rebuild():
    """White-box: after a flush, the device-resident dense window must be
    exactly the dense rendering of the dict DAG over [win_base,
    win_base+W) — every certificate present, every resolved parent edge,
    nothing else."""
    import numpy as np

    rng = random.Random(0xACE)
    for trial in range(3):
        certs = _random_dag_certs(rng, rounds=rng.randint(8, 18))
        k = KernelTusk(committee(), gc_depth=50, fixed_coin=True)
        feed(k, certs)
        k._flush_pending()

        W, n = k.max_window, k._n
        base = k._win_base
        want_exists = np.zeros((W, n), dtype=bool)
        want_parent = np.zeros((W, n, n), dtype=bool)
        digest_idx = {}
        for r in range(base, base + W):
            for name, (digest, cert) in k.state.dag.get(r, {}).items():
                digest_idx[bytes(digest)] = (r, k._index[name])
        for r in range(base, base + W):
            for name, (digest, cert) in k.state.dag.get(r, {}).items():
                w, i = r - base, k._index[name]
                want_exists[w, i] = True
                if w >= 1:
                    for pd in cert.header.parents:
                        pos = digest_idx.get(bytes(pd))
                        if pos is not None and pos[0] == r - 1:
                            want_parent[w, i, pos[1]] = True
        assert ((np.asarray(k._dev_exists) > 0) == want_exists).all()
        assert ((np.asarray(k._dev_parent) > 0) == want_parent).all()


def test_kernel_digest_index_tracks_dict_dag():
    """White-box (PR 4): KernelTusk inherits the indexed base state, so
    after arbitrary feeds (commits, window shifts, GC) the digest index
    must hold exactly the certificates currently in the dict DAG — the
    host-side seam the kernel's fallback walk and order_dag flattening
    both resolve parents through."""
    rng = random.Random(0x1DE)
    for gc_depth in (50, 6):
        certs = _random_dag_certs(rng, rounds=rng.randint(10, 20))
        k = KernelTusk(committee(), gc_depth=gc_depth, fixed_coin=True)
        feed(k, certs)
        want = {
            d: cert
            for authorities in k.state.dag.values()
            for (d, cert) in authorities.values()
        }
        assert dict(k.state.digest_index) == want


def test_kernel_support_counters_match_rescan():
    """White-box (PR 4): the incremental f+1 support counters the kernel
    inherits must equal a from-scratch rescan of each queryable leader
    round, even under the out-of-order delivery that exercises the
    leader-seeding path."""
    rng = random.Random(0x1DF)
    for trial in range(3):
        certs = _random_dag_certs(rng, rounds=rng.randint(8, 16))
        order = sorted(certs, key=lambda x: x.round + rng.uniform(-2.2, 0.0))
        k = KernelTusk(committee(), gc_depth=50, fixed_coin=True)
        feed(k, order)
        top = max(k.state.dag)
        for lr in range(k.state.last_committed_round + 2, top + 1, 2):
            got = k.leader(lr, k.state.dag)
            want = 0
            if got is not None:
                want = sum(
                    k.committee.stake(cert.origin)
                    for _, cert in k.state.dag.get(lr + 1, {}).values()
                    if got[0] in cert.header.parents
                )
            assert k._support.get(lr, 0) == want, (trial, lr)


def test_arrival_path_stages_without_device_dispatch():
    """The arrival path must be a bare staging append: no window_apply
    dispatch until a commit opportunity flushes the batch."""
    import narwhal_tpu.ops.reachability as R

    c = committee()
    names = sorted_names()
    certs, _ = make_certificates(1, 3, genesis_digests(c), names)

    k = KernelTusk(c, gc_depth=50, fixed_coin=True)
    calls = []
    real = R.window_apply

    def counting(*args, **kw):
        calls.append(1)
        return real(*args, **kw)

    R.window_apply = counting
    try:
        for cert in certs:
            k.process_certificate(cert)  # rounds 1-3: no commit possible
        assert calls == [], "insert path dispatched to the device"
        assert len(k._pending) == len(certs) + len(genesis(c))
        k._flush_pending()
        assert len(calls) >= 1
        assert k._pending == []
    finally:
        R.window_apply = real


def test_kernel_restore_far_frontier_resets_window():
    """Restore to a frontier ≥ W rounds ahead: _win_shift must take the
    d ≥ W reset path (fresh zero buffers) and the kernel must then track
    the golden instance on new rounds."""
    c = committee()
    names = sorted_names()
    gc_depth = 6  # W = 8
    certs, parents = make_certificates(1, 20, genesis_digests(c), names)
    _, trigger = mock_certificate(names[0], 21, parents)

    golden = Tusk(c, gc_depth=gc_depth, fixed_coin=True)
    assert feed(golden, certs + [trigger])
    blob = golden.state.snapshot_bytes()
    assert golden.state.last_committed_round >= 8  # d >= W on restore

    kernel = KernelTusk(c, gc_depth=gc_depth, fixed_coin=True)
    kernel.state.restore(blob)
    kernel._win_shift()  # what Consensus.__init__ does after a restore
    assert kernel._win_base == golden.state.last_committed_round
    # Catch-up replay of pre-crash history: nothing may be re-delivered.
    assert feed(kernel, certs + [trigger]) == []

    more, tail_parents = make_certificates(21, 26, parents, names)
    more = more[1:]  # round-21 leader already exists as `trigger`
    _, trigger2 = mock_certificate(names[0], 27, tail_parents)
    got = feed(kernel, more + [trigger2])
    want = feed(golden, more + [trigger2])
    assert [x.digest() for x in got] == [x.digest() for x in want]
    assert got


def test_kernel_restore_resumes_like_golden():
    """Checkpoint restore under the device kernel: a KernelTusk restored
    from a golden instance's frontier (Consensus realigns the dense
    window via _win_shift, consensus/tusk.py) must skip a full catch-up
    replay of committed history and then commit new rounds identically
    to the uninterrupted golden instance."""
    c = committee()
    names = sorted_names()
    certs, next_parents = make_certificates(1, 4, genesis_digests(c), names)
    _, trigger = mock_certificate(names[0], 5, next_parents)

    golden = Tusk(c, gc_depth=50, fixed_coin=True)
    assert feed(golden, certs + [trigger])
    blob = golden.state.snapshot_bytes()

    kernel = KernelTusk(c, gc_depth=50, fixed_coin=True)
    kernel.state.restore(blob)
    kernel._win_shift()  # what Consensus.__init__ does after a restore
    assert kernel._win_base == golden.state.last_committed_round
    assert feed(kernel, certs + [trigger]) == []

    more, tail_parents = make_certificates(5, 8, next_parents, names)
    more = more[1:]  # round-5 leader already exists as `trigger`
    _, trigger2 = mock_certificate(names[0], 9, tail_parents)
    got = feed(kernel, more + [trigger2])
    want = feed(golden, more + [trigger2])
    assert [x.digest() for x in got] == [x.digest() for x in want]
    assert got
