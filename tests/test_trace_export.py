"""Trace-exporter tests (benchmark/trace_export.py): a synthetic 4-node
committee dump round-trips into schema-valid Chrome trace JSON — process
row per node, stage/round slices, cross-process digest flows, flight/
health instants, profiler CPU slices — and logs_merge --trace interleaves
merged log lines onto the same timeline.  (The real-bench round-trip over
live node snapshots is asserted by tests/test_health_bench.py.)"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmark import logs_merge, trace_export  # noqa: E402
from narwhal_tpu.metrics import ROUND_STAGES, STAGES  # noqa: E402

T0 = 1_700_000_000.0
DIGEST = "ab" * 32


def _committee_snapshots():
    """A minimal-but-complete 4-node × (primary + worker) committee dump:
    one digest sealed on worker-0-0, proposed/certified on primary-0,
    committed on every primary — the real snapshot shape end to end."""
    snaps = []
    for i in range(4):
        ptrace = {
            "cert_inserted": T0 + 0.06,
            "commit_trigger": T0 + 0.07,
            "walk_done": T0 + 0.071,
            "commit": T0 + 0.08 + i * 0.001,
        }
        if i == 0:
            ptrace.update({
                "digest_at_primary": T0 + 0.02,
                "header": T0 + 0.03,
                "cert": T0 + 0.05,
            })
        snaps.append((f"primary-{i}", {
            "enabled": True,
            "trace": {DIGEST: ptrace},
            "round_trace": {
                "3": {
                    s: T0 + 0.02 + 0.005 * j
                    for j, s in enumerate(ROUND_STAGES)
                }
            },
            "detail": {
                "flight.ring": {"events": [
                    {"t": T0 + 0.055, "kind": "round_advance", "round": 4},
                    {"t": T0 + 0.08, "kind": "commit", "certs": 1,
                     "batches": 1, "round": 2, "walk_ms": 1.0},
                    {"t": T0 + 0.5, "kind": "tick",
                     "d": {"wire_out_b": 1234.0, "commits": 1.0},
                     "round": 4},
                ]},
                "profile.timeline": [
                    [T0, T0 + 0.4, 27, "_ed25519_py.py:verify"],
                ],
            },
            "health": {"events": [
                {"t": T0 + 0.3, "rule": "commit_stall", "event": "FIRING",
                 "subject": "", "detail": {"seconds_without_commit": 11}},
            ]} if i == 1 else {},
        }))
        snaps.append((f"worker-{i}-0", {
            "enabled": True,
            "trace": (
                {DIGEST: {"seal": T0, "quorum": T0 + 0.01, "bytes": 400}}
                if i == 0
                else {}
            ),
            "round_trace": {},
            "detail": {},
        }))
    return snaps


def _validate_schema(trace):
    assert set(trace) >= {"traceEvents", "displayTimeUnit", "metadata"}
    for ev in trace["traceEvents"]:
        assert {"ph", "pid", "ts"} <= set(ev) or ev["ph"] == "M", ev
        assert isinstance(ev["pid"], int)
        if ev["ph"] == "X":
            assert ev["dur"] >= 1 and ev["ts"] >= 0, ev
        if ev["ph"] in "stf":
            assert "id" in ev, ev
    # The whole document must be JSON-serializable as-is.
    json.dumps(trace)


def test_four_node_dump_round_trips_with_rows_and_flows():
    trace = trace_export.build_trace(_committee_snapshots())
    _validate_schema(trace)

    # All 8 process rows, named, primaries sorted first — plus the
    # PR 17 committee row carrying the critical-path track (present
    # because the synthetic snapshots join into one full stage chain).
    names = {
        ev["args"]["name"]: ev["pid"]
        for ev in trace["traceEvents"]
        if ev["ph"] == "M" and ev["name"] == "process_name"
    }
    assert set(names) == (
        {f"primary-{i}" for i in range(4)}
        | {f"worker-{i}-0" for i in range(4)}
        | {"committee"}
    )
    committee_pid = names.pop("committee")
    assert names == trace["metadata"]["node_pids"]
    cp_slices = [
        ev for ev in trace["traceEvents"]
        if ev["ph"] == "X" and ev["pid"] == committee_pid
    ]
    assert cp_slices, "committee row has no critical-path slices"
    assert all(ev["cat"] == "critical-path" for ev in cp_slices)
    assert trace["metadata"]["critical_path"]["full_chains"] >= 1
    assert all(names[f"primary-{i}"] < names["worker-0-0"] for i in range(4))

    # ≥1 cross-process digest flow: s on the sealing worker, f elsewhere.
    flows = [ev for ev in trace["traceEvents"] if ev["ph"] in "stf"]
    by_id = {}
    for ev in flows:
        by_id.setdefault(ev["id"], []).append(ev)
    assert trace["metadata"]["flows_emitted"] == 1
    chain = by_id[DIGEST[:16]]
    phases = [ev["ph"] for ev in chain]
    assert phases[0] == "s" and phases[-1] == "f"
    assert all(p == "t" for p in phases[1:-1])
    assert chain[0]["pid"] == names["worker-0-0"]  # starts at the seal
    assert chain[-1]["pid"] != chain[0]["pid"]  # ends across processes
    # Time-ordered within the chain, ts rebased to the trace origin.
    tss = [ev["ts"] for ev in chain]
    assert tss == sorted(tss) and tss[0] == 0

    # Stage leg slices exist on both planes of authority 0.
    slices = [ev for ev in trace["traceEvents"] if ev["ph"] == "X"]
    leg_names = {ev["name"] for ev in slices}
    assert "seal→quorum" in leg_names
    assert "digest_at_primary→header" in leg_names
    assert "walk_done→commit" in leg_names

    # Round slices: the parent span and its cadence legs.
    assert "round 3" in leg_names
    assert f"{ROUND_STAGES[0]}→{ROUND_STAGES[1]}" in leg_names

    # Flight landmarks became instants; ticks became counter samples.
    instants = [ev for ev in trace["traceEvents"] if ev["ph"] == "i"]
    assert any(ev["name"] == "flight:commit" for ev in instants)
    counters = [ev for ev in trace["traceEvents"] if ev["ph"] == "C"]
    assert any(ev["args"].get("wire_out_b") == 1234.0 for ev in counters)

    # Health transition instant (node 1's snapshot events).
    assert any(
        ev["name"] == "health:commit_stall:FIRING"
        and ev["pid"] == names["primary-1"]
        for ev in instants
    )

    # Profiler CPU track: the verify run as a slice on tid 4.
    cpu = [ev for ev in slices if ev["tid"] == trace_export.TID_CPU]
    assert cpu and cpu[0]["name"] == "_ed25519_py.py:verify"
    assert cpu[0]["args"]["samples"] == 27


def test_flow_cap_samples_not_truncates():
    snaps = _committee_snapshots()
    # Mint 40 committed digests across worker-0-0 and primary-0.
    names = {n: s for n, s in snaps}
    for k in range(40):
        d = f"{k:02x}" * 32
        names["worker-0-0"]["trace"][d] = {
            "seal": T0 + k, "quorum": T0 + k + 0.01,
        }
        names["primary-0"]["trace"][d] = {
            "header": T0 + k + 0.02, "cert": T0 + k + 0.03,
            "commit": T0 + k + 0.05,
        }
    trace = trace_export.build_trace(list(names.items()), max_flows=10)
    md = trace["metadata"]
    assert md["flows_emitted"] == 10
    assert md["flows_total"] >= 40
    assert md["flows_dropped"] == md["flows_total"] - 10
    _validate_schema(trace)


def test_newest_flight_ring_wins():
    """Scraped-at-quiesce vs snapshot copies of the same bounded ring:
    whichever carries the newest event is the one exported — the scrape
    wins only for a node whose snapshot went stale (SIGKILL mid-run),
    never in the normal scrape→SIGTERM→final-flush order where the
    snapshot holds the shutdown tail."""

    def flight_names(trace, node):
        pid = trace["metadata"]["node_pids"][node]
        return [
            ev["name"] for ev in trace["traceEvents"]
            if ev["ph"] == "i" and ev["pid"] == pid
            and ev.get("cat") == "flight"
        ]

    fresh = {"events": [
        {"t": T0 + 1.0, "kind": "shutdown", "signal": "SIGTERM"},
    ]}
    trace = trace_export.build_trace(
        _committee_snapshots(), flight={"primary-0": fresh}
    )
    assert flight_names(trace, "primary-0") == ["flight:shutdown"]

    # An OLDER scraped ring must NOT displace the snapshot's superset.
    stale = {"events": [{"t": T0 - 5.0, "kind": "round_advance"}]}
    trace = trace_export.build_trace(
        _committee_snapshots(), flight={"primary-0": stale}
    )
    assert flight_names(trace, "primary-0") == [
        "flight:round_advance", "flight:commit",
    ]


def test_timeline_adds_rate_counters_and_events():
    timeline = {
        "nodes": {"primary-2": [
            {"t": T0 + 1, "commit_rate_per_s": 3.5, "pending_acks": 7},
        ]},
        "events": [
            {"node": "primary-3", "t": T0 + 2, "rule": "peer_unreachable",
             "event": "FIRING", "subject": "10.0.0.1:7001", "detail": {}},
        ],
    }
    trace = trace_export.build_trace(
        _committee_snapshots(), timeline=timeline
    )
    names = trace["metadata"]["node_pids"]
    assert any(
        ev["ph"] == "C" and ev["pid"] == names["primary-2"]
        and ev["args"].get("commit_rate_per_s") == 3.5
        for ev in trace["traceEvents"]
    )
    assert any(
        ev["ph"] == "i" and ev["pid"] == names["primary-3"]
        and ev["name"] == "health:peer_unreachable:FIRING"
        for ev in trace["traceEvents"]
    )


def test_export_writes_atomically_and_workdir_loads(tmp_path):
    workdir = tmp_path / "bench"
    workdir.mkdir()
    for name, snap in _committee_snapshots():
        (workdir / f"metrics-{name}.json").write_text(json.dumps(snap))
    (workdir / "timeline.json").write_text(json.dumps({"nodes": {}}))
    snaps, timeline = trace_export.load_workdir(str(workdir))
    assert len(snaps) == 8 and timeline == {"nodes": {}}
    out = tmp_path / "trace.json"
    trace_export.export(snaps, str(out), timeline=timeline, quiet=True)
    trace = json.loads(out.read_text())
    _validate_schema(trace)
    assert len(trace["metadata"]["node_pids"]) == 8


def test_logs_merge_injects_instants_onto_node_rows(tmp_path):
    out = tmp_path / "trace.json"
    trace_export.export(
        _committee_snapshots(), str(out), quiet=True
    )
    # Two node streams + a client stream: the bench-workdir shape.  The
    # primary's records carry the RUNTIME node id (role-keyprefix, what
    # --log-json actually stamps) and must map onto the trace row via
    # the source FILE stem; the worker's carry a row-matching id (maps
    # directly); the client's match neither and are dropped counted.
    log_a = tmp_path / "primary-0.log"
    log_a.write_text(
        json.dumps({"ts": T0 + 0.04, "level": "INFO",
                    "logger": "narwhal.primary", "msg": "Created H3",
                    "node": "primary-ab12cd34"}) + "\n"
    )
    log_b = tmp_path / "worker-0-0.log"
    log_b.write_text(
        json.dumps({"ts": T0 + 0.005, "level": "WARNING",
                    "logger": "narwhal.worker", "msg": "QueueFull",
                    "node": "worker-0-0"}) + "\n"
    )
    log_c = tmp_path / "client-9.log"
    log_c.write_text(
        json.dumps({"ts": T0 + 0.006, "level": "INFO",
                    "msg": "from nowhere", "node": "client-9"}) + "\n"
    )
    rc = logs_merge.main(
        [str(log_a), str(log_b), str(log_c), "--trace", str(out)]
    )
    assert rc == 0
    trace = json.loads(out.read_text())
    names = trace["metadata"]["node_pids"]
    logs = [
        ev for ev in trace["traceEvents"] if ev.get("cat") == "log"
    ]
    assert len(logs) == 2
    by_pid = {ev["pid"]: ev for ev in logs}
    assert by_pid[names["primary-0"]]["args"]["msg"] == "Created H3"
    assert by_pid[names["worker-0-0"]]["name"] == "log:WARNING"
    assert trace["metadata"]["logs_injected"] == 2
    assert trace["metadata"]["logs_dropped"] == 1
    _validate_schema(trace)
