"""Wire-format v2 tests (ISSUE 13): per-peer frame coalescing keeps the
first-transmission-vs-retransmit ledger exact under mid-flush segment
loss and never double-resolves a delivery future; the per-connection
digest dictionary evicts oldest-first, resets on reconnect, and turns
corrupt/out-of-range references into typed FrameErrors counted into
``wire.in.*``; and a seeded fuzz round-trip proves the v2 arm decodes to
the same messages as the legacy arm."""

import asyncio
import contextlib
import random

import pytest

from narwhal_tpu import metrics
from narwhal_tpu.crypto import Digest, PublicKey
from narwhal_tpu.faults import netem
from narwhal_tpu.messages import (
    encode_batch_digest,
    encode_batch_request,
    set_wire_committee,
)
from narwhal_tpu.network import Receiver, ReliableSender
from narwhal_tpu.network import wirev2
from narwhal_tpu.network.framing import FrameError, frame, write_frame
from narwhal_tpu.primary.messages import (
    PRIMARY_FRAME_TYPES,
    decode_primary_message,
    encode_primary_message,
)
from narwhal_tpu.messages import frame_classifier
from tests.common import (
    RecordingAckHandler,
    committee,
    keys,
    make_certificate,
    make_header,
    make_vote,
)


def run(coro, timeout=30):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def cnt(name: str) -> float:
    c = metrics.registry().counters.get(name)
    return c.value if c is not None else 0


def hist(name: str):
    h = metrics.registry().histograms.get(name)
    return (h.sum, h.count) if h is not None else (0.0, 0)


@contextlib.contextmanager
def v2_wire():
    wirev2.set_enabled(True)
    try:
        yield
    finally:
        wirev2.set_enabled(None)


# --- dictionary semantics ----------------------------------------------------


def test_digest_dict_evicts_oldest_first():
    d = wirev2.DigestDict(cap=4)
    spans = [bytes([i]) * 32 for i in range(6)]
    for s in spans:
        d.add(s)
    # Newest has age 0; the two oldest fell out of the bounded window.
    assert d.ref_for(spans[5]) == 0
    assert d.ref_for(spans[2]) == 3
    assert d.ref_for(spans[1]) is None
    assert d.ref_for(spans[0]) is None
    assert d.get(0) == spans[5]
    assert d.get(3) == spans[2]


def test_out_of_range_reference_is_frame_error():
    d = wirev2.DigestDict(cap=4)
    with pytest.raises(FrameError):
        d.get(0)  # empty dictionary
    d.add(b"a" * 32)
    with pytest.raises(FrameError):
        d.get(1)


def test_decompress_rejects_malformed_frames():
    d = wirev2.DigestDict()
    with pytest.raises(FrameError):
        wirev2.decompress(b"", d)
    with pytest.raises(FrameError):
        wirev2.decompress(b"\x00rest", d)  # bad tag
    # Truncated varint: continuation bit set, stream ends.
    with pytest.raises(FrameError):
        wirev2.decompress(bytes([wirev2.TAG_PLAIN, 0x80]), d)
    # One REF op pointing into an empty dictionary.
    with pytest.raises(FrameError):
        wirev2.decompress(bytes([wirev2.TAG_PLAIN, 1, 0, 1]), d)
    # ADD op with fewer than 32 residual bytes left.
    with pytest.raises(FrameError):
        wirev2.decompress(
            bytes([wirev2.TAG_PLAIN, 1, 0, 0]) + b"short", d
        )
    # Corrupt deflate residual.
    with pytest.raises(FrameError):
        wirev2.decompress(
            bytes([wirev2.TAG_DEFLATE, 0]) + b"notzlib", d
        )


def test_compress_roundtrip_updates_both_dicts_identically():
    enc, dec = wirev2.DigestDict(), wirev2.DigestDict()
    digest = bytes(range(32))
    frame1 = bytes([0]) + digest + b"tail"
    frame2 = bytes([1]) + digest + b"other"
    wirev2.register_spans("_t_span", lambda d: [1])
    c1 = wirev2.compress(frame1, "_t_span", enc)
    c2 = wirev2.compress(frame2, "_t_span", enc)
    # Second frame back-references the digest: strictly smaller than a
    # literal re-carry.
    assert len(c2) < len(frame2)
    assert wirev2.decompress(c1, dec) == frame1
    assert wirev2.decompress(c2, dec) == frame2
    assert enc.count == dec.count == 1


# --- fuzz round-trip: v2 arm decodes to the legacy arm's messages ------------


def test_fuzz_roundtrip_v2_decodes_equal_to_legacy_arm():
    """Seeded fuzz over real protocol messages: the v2 encoding (compact
    bodies + dictionary compression through a live connection-shaped
    dict pair) must decode to messages equal to what the legacy arm
    decodes from ITS encoding of the same objects."""
    rng = random.Random(1307)
    c = committee()
    kps = keys()
    objs = []
    for i in range(24):
        kp = kps[rng.randrange(4)]
        payload = {
            Digest(bytes([rng.randrange(256) for _ in range(32)])): rng.randrange(4)
            for _ in range(rng.randrange(3))
        }
        parents = {
            Digest(bytes([rng.randrange(256) for _ in range(32)]))
            for _ in range(rng.randrange(4))
        }
        h = make_header(kp, round_=rng.randrange(1, 100), payload=payload,
                        parents=parents)
        objs.append(h)
        if rng.random() < 0.7:
            objs.append(make_vote(h, kps[rng.randrange(4)]))
        if rng.random() < 0.7:
            objs.append(make_certificate(h))

    # Legacy arm: plain encode/decode.
    wirev2.set_enabled(False)
    try:
        legacy_decoded = [
            decode_primary_message(encode_primary_message(o)) for o in objs
        ]
    finally:
        wirev2.set_enabled(None)

    # v2 arm: compact encode, then dictionary-compress through one
    # shared connection (enc/dec dict pair), then decode.
    with v2_wire():
        set_wire_committee(c)
        enc, dec = wirev2.DigestDict(), wirev2.DigestDict()
        v2_decoded = []
        for o in objs:
            o.__dict__.pop("_wire", None)  # serialize memo is per-arm
            data = encode_primary_message(o)
            msg_type = PRIMARY_FRAME_TYPES[data[0]]
            compressed = wirev2.compress(data, msg_type, enc)
            restored = wirev2.decompress(compressed, dec)
            assert restored == data
            v2_decoded.append(decode_primary_message(restored))
        for o in objs:
            o.__dict__.pop("_wire", None)

    assert len(legacy_decoded) == len(v2_decoded)
    for (k1, m1), (k2, m2) in zip(
        [d[:2] for d in legacy_decoded], [d[:2] for d in v2_decoded]
    ):
        assert k1 == k2
        if k1 == "header":
            assert m1.id == m2.id
            assert m1.author == m2.author
            assert m1.round == m2.round
            assert m1.payload == m2.payload
            assert m1.parents == m2.parents
            assert m1.signature == m2.signature
        elif k1 == "vote":
            assert m1.digest() == m2.digest()
            assert m1.author == m2.author
        else:
            assert m1 == m2


def test_rogue_key_escapes_to_literal():
    """A key outside the committee (the wrong_key Byzantine arm mints
    these) still encodes under v2 — as a literal, not an index."""
    from narwhal_tpu.crypto import KeyPair

    with v2_wire():
        set_wire_committee(committee())
        outsider = KeyPair.generate(bytes([7]) * 32)
        data = encode_batch_request(
            [Digest(b"d" * 32)], outsider.name
        )
        from narwhal_tpu.messages import decode_worker_message

        kind, digests, requestor = decode_worker_message(data)
        assert requestor == outsider.name


# --- live-socket behavior ----------------------------------------------------


def test_hello_negotiation_not_dispatched_and_typed():
    """The v2 HELLO switches the connection to v2 decode, is never
    handed to the handler, and is typed `wire_hello` in the ledger on
    both sides."""

    async def go():
        addr = "127.0.0.1:12410"
        handler = RecordingAckHandler()
        recv = await Receiver.spawn(
            addr, handler, classify=frame_classifier(PRIMARY_FRAME_TYPES)
        )
        sender = ReliableSender()
        before = (
            cnt("wire.out.frames.wire_hello"),
            cnt("wire.in.frames.wire_hello"),
        )
        msg = encode_primary_message(make_header(keys()[0]))
        await sender.send(addr, msg, "header")
        assert cnt("wire.out.frames.wire_hello") == before[0] + 1
        assert cnt("wire.in.frames.wire_hello") == before[1] + 1
        # The handler saw exactly the protocol frame, decompressed.
        assert handler.received == [msg]
        sender.close()
        await recv.shutdown()

    with v2_wire():
        run(go())


def test_coalesced_flush_batches_buffered_frames():
    """Messages queued while the connection is still being established
    leave in ONE flush: the frames_per_flush histogram observes the
    whole burst, and every frame is typed/accounted individually."""

    async def go():
        addr = "127.0.0.1:12420"
        handler = RecordingAckHandler()
        recv = await Receiver.spawn(
            addr, handler, classify=frame_classifier(PRIMARY_FRAME_TYPES)
        )
        sender = ReliableSender()
        f_before = cnt("wire.out.flushes")
        s_before, c_before = hist("wire.out.frames_per_flush")
        frames_before = cnt("wire.out.frames.vote")
        n = 12
        h = make_header(keys()[0])
        futs = [
            sender.send(
                addr,
                encode_primary_message(make_vote(h, keys()[i % 4])),
                "vote",
            )
            for i in range(n)
        ]
        await asyncio.gather(*futs)
        s_after, c_after = hist("wire.out.frames_per_flush")
        flushes = cnt("wire.out.flushes") - f_before
        assert cnt("wire.out.frames.vote") - frames_before == n
        assert s_after - s_before == n  # every frame rode some flush
        # The burst was queued before the TCP connect finished, so it
        # cannot have taken one syscall per frame.
        assert flushes < n
        assert (s_after - s_before) / (c_after - c_before) > 1.5
        # ACK replies coalesced too.
        assert len(handler.received) == n
        sender.close()
        await recv.shutdown()

    with v2_wire():
        run(go())


def test_loss_mid_flush_keeps_accounting_exact_and_futures_single():
    """50% netem segment loss kills whole coalesced flushes mid-stream:
    every message must still be ACKed exactly once, charged exactly one
    first transmission (frames counter == message count), with every
    extra write in the retransmit counters — and no future is ever
    double-resolved (resolved-then-cancelled-then-resolved would raise
    InvalidStateError inside the sender and wedge the run)."""

    async def go():
        addr = "127.0.0.1:12430"
        n_msgs = 10
        handler = RecordingAckHandler()
        recv = await Receiver.spawn(
            addr, handler, classify=frame_classifier(PRIMARY_FRAME_TYPES)
        )
        netem.install(
            netem.NetEmulator(
                {addr: netem.Shape(loss=0.5)}, None, [], seed=23
            )
        )
        sender = ReliableSender()
        before_first = cnt("wire.out.frames.certificate")
        before_re = cnt("wire.out.retransmit_frames.certificate")
        before_requeue = cnt("net.reliable.retransmissions")
        payloads = []
        try:
            results = []
            # Phase 1 — sequential: each message rides its own flush, so
            # the seeded 50% loss draws once per flush and some flushes
            # MUST die mid-stream (p(no loss) = 2^-n).
            for i in range(n_msgs):
                cert = make_certificate(make_header(keys()[i % 4], round_=i + 1))
                data = encode_primary_message(cert)
                payloads.append(data)
                results.append(
                    await asyncio.wait_for(
                        sender.send(addr, data, "certificate"), 30
                    )
                )
            # Phase 2 — pipelined: a burst in flight when a flush dies
            # leaves fully-written (accounted) frames un-ACKed; their
            # rewrite is what the ledger's retransmit counters charge.
            futs = []
            for i in range(n_msgs):
                cert = make_certificate(
                    make_header(keys()[i % 4], round_=100 + i)
                )
                data = encode_primary_message(cert)
                payloads.append(data)
                futs.append(sender.send(addr, data, "certificate"))
            results += await asyncio.gather(*futs)
        finally:
            netem.reset()
            sender.close()
            await recv.shutdown()
        assert all(r == b"Ack" for r in results)
        # EXACTNESS: one first transmission per message, never more — a
        # flush that died mid-stream charged nothing, and its rewrite is
        # the (single) first transmission; a fully-written frame rewritten
        # after a reconnect lands in the retransmit counters instead.
        assert (
            cnt("wire.out.frames.certificate") - before_first == 2 * n_msgs
        )
        assert cnt("wire.out.retransmit_frames.certificate") >= before_re
        # The seeded 50% loss killed whole coalesced flushes: the
        # reconnect path re-offered their frames.
        assert cnt("net.reliable.retransmissions") - before_requeue > 0
        # The receiver decoded every original frame at least once, all
        # byte-identical to what was sent (dictionary reset on every
        # reconnect kept references consistent).
        received = set(handler.received)
        for p in payloads:
            assert p in received

    with v2_wire():
        run(go())


def test_reconnect_resets_dictionary_no_stale_references():
    """Kill the receiver after frames that populated the dictionary,
    restart it on the same port, and send frames re-carrying the same
    digests: the fresh connection must re-ADD them (no stale
    back-references), and every frame decodes byte-identically."""

    async def go():
        port = 12440
        addr = f"127.0.0.1:{port}"
        h = make_header(keys()[0], round_=3)
        header_frame = encode_primary_message(h)
        cert_frame = encode_primary_message(make_certificate(h))

        handler1 = RecordingAckHandler()
        recv1 = await Receiver.spawn(
            addr, handler1, classify=frame_classifier(PRIMARY_FRAME_TYPES)
        )
        sender = ReliableSender()
        await sender.send(addr, header_frame, "header")
        await sender.send(addr, cert_frame, "certificate")
        assert handler1.received == [header_frame, cert_frame]
        await recv1.shutdown()

        handler2 = RecordingAckHandler()
        recv2 = await Receiver.spawn(
            addr, handler2, classify=frame_classifier(PRIMARY_FRAME_TYPES)
        )
        # The same cert frame again: its digests were in the OLD
        # connection's dictionary; the new connection must not reference
        # them.
        await asyncio.wait_for(
            sender.send(addr, cert_frame, "certificate"), 20
        )
        assert handler2.received == [cert_frame]
        sender.close()
        await recv2.shutdown()

    with v2_wire():
        run(go())


def test_corrupt_reference_on_the_wire_is_counted_and_kills_connection():
    """A hostile/corrupt v2 frame (reference into an empty dictionary)
    is a typed FrameError: counted under wire.in.frame_error and the
    connection dies (dictionaries may have diverged — only a reconnect,
    which resets both, is safe)."""

    async def go():
        addr = "127.0.0.1:12450"
        handler = RecordingAckHandler()
        recv = await Receiver.spawn(
            addr, handler, classify=frame_classifier(PRIMARY_FRAME_TYPES)
        )
        before_err = cnt("wire.in.frames.frame_error")
        before_bad = cnt("net.recv.bad_frames")
        reader, writer = await asyncio.open_connection("127.0.0.1", 12450)
        await write_frame(writer, wirev2.HELLO)
        # REF(age 0) against an empty dictionary.
        await write_frame(
            writer, bytes([wirev2.TAG_PLAIN, 1, 0, 1])
        )
        # The receiver kills the connection: EOF on our side.
        assert await reader.read(64) == b""
        assert cnt("wire.in.frames.frame_error") == before_err + 1
        assert cnt("net.recv.bad_frames") == before_bad + 1
        assert handler.received == []
        writer.close()
        await recv.shutdown()

    with v2_wire():
        run(go())


def test_legacy_connection_still_served_when_v2_enabled():
    """SimpleSender-style raw connections (no HELLO) keep working on a
    v2-enabled listener — classification and dispatch unchanged."""

    async def go():
        addr = "127.0.0.1:12460"
        handler = RecordingAckHandler()
        recv = await Receiver.spawn(
            addr, handler, classify=frame_classifier(PRIMARY_FRAME_TYPES)
        )
        reader, writer = await asyncio.open_connection("127.0.0.1", 12460)
        msg = encode_primary_message(make_header(keys()[1]))
        await write_frame(writer, msg)
        from narwhal_tpu.network.framing import read_frame

        assert await read_frame(reader) == b"Ack"
        assert handler.received == [msg]
        writer.close()
        await recv.shutdown()

    with v2_wire():
        run(go())
