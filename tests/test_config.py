import os

from narwhal_tpu.config import Parameters
from tests.common import committee, keys


def test_quorum_math():
    c = committee()
    assert c.total_stake() == 4
    assert c.quorum_threshold() == 3  # 2f+1 with n=4, f=1
    assert c.validity_threshold() == 2  # f+1


def test_quorum_math_large():
    c = committee(n=10)
    assert c.quorum_threshold() == 7
    assert c.validity_threshold() == 4
    c = committee(n=50)
    assert c.quorum_threshold() == 34
    assert c.validity_threshold() == 17


def test_address_lookups():
    c = committee(base_port=6000, workers=2)
    me = keys()[0].name
    assert len(c.others_primaries(me)) == 3
    assert len(c.our_workers(me)) == 2
    others = c.others_workers(me, 1)
    assert len(others) == 3
    assert all(name != me for name, _ in others)


def test_committee_json_roundtrip(tmp_path):
    c = committee(base_port=6100, workers=2)
    path = os.path.join(tmp_path, "committee.json")
    c.export(path)
    c2 = type(c).load(path)
    assert c2.to_json() == c.to_json()
    assert c2.quorum_threshold() == c.quorum_threshold()


def test_parameters_roundtrip(tmp_path):
    p = Parameters(header_size=32, max_header_delay=50)
    path = os.path.join(tmp_path, "parameters.json")
    p.export(path)
    p2 = Parameters.load(path)
    assert p2 == p
    assert p2.gc_depth == 50
