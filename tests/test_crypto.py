"""Analog of reference crypto/src/tests/crypto_tests.rs: sig round-trip,
invalid sig, valid/invalid batch verification, SignatureService."""

import asyncio

from narwhal_tpu.crypto import (
    Digest,
    KeyPair,
    Signature,
    SignatureService,
    digest32,
    verify,
    verify_batch,
    verify_batch_mask,
)


def test_digest():
    d = digest32(b"hello")
    assert len(d) == 32
    assert d == digest32(b"hello")
    assert d != digest32(b"world")


def test_deterministic_keygen():
    a = KeyPair.generate(bytes(32))
    b = KeyPair.generate(bytes(32))
    assert a.name == b.name and a.secret == b.secret


def test_import_export():
    kp = KeyPair.generate(bytes([1]) * 32)
    kp2 = KeyPair.from_json(kp.to_json())
    assert kp2.name == kp.name and kp2.secret == kp.secret


def test_verify_valid_signature():
    kp = KeyPair.generate(bytes([2]) * 32)
    d = digest32(b"Hello, world!")
    sig = kp.sign(d)
    assert verify(bytes(d), kp.name, sig)


def test_verify_invalid_signature():
    kp = KeyPair.generate(bytes([2]) * 32)
    d = digest32(b"Hello, world!")
    bad = digest32(b"tampered")
    sig = kp.sign(d)
    assert not verify(bytes(bad), kp.name, sig)
    assert not verify(bytes(d), kp.name, Signature.default())


def test_verify_valid_batch():
    d = digest32(b"Hello, batch!")
    kps = [KeyPair.generate(bytes([i]) * 32) for i in range(5)]
    sigs = [kp.sign(d) for kp in kps]
    assert verify_batch(d, [kp.name for kp in kps], sigs)


def test_verify_invalid_batch():
    d = digest32(b"Hello, batch!")
    kps = [KeyPair.generate(bytes([i]) * 32) for i in range(5)]
    sigs = [kp.sign(d) for kp in kps]
    sigs[2] = Signature.default()
    assert not verify_batch(d, [kp.name for kp in kps], sigs)
    mask = verify_batch_mask(
        [bytes(d)] * 5, [kp.name for kp in kps], sigs
    )
    assert mask == [True, True, False, True, True]


def test_signature_service():
    async def go():
        kp = KeyPair.generate(bytes([3]) * 32)
        service = SignatureService(kp)
        d = digest32(b"service")
        sig = await service.request_signature(d)
        assert verify(bytes(d), kp.name, sig)

    asyncio.run(go())
