"""Analog of reference crypto/src/tests/crypto_tests.rs: sig round-trip,
invalid sig, valid/invalid batch verification, SignatureService."""

import asyncio

from narwhal_tpu.crypto import (
    Digest,
    KeyPair,
    Signature,
    SignatureService,
    digest32,
    verify,
    verify_batch,
    verify_batch_mask,
)


def test_digest():
    d = digest32(b"hello")
    assert len(d) == 32
    assert d == digest32(b"hello")
    assert d != digest32(b"world")


def test_deterministic_keygen():
    a = KeyPair.generate(bytes(32))
    b = KeyPair.generate(bytes(32))
    assert a.name == b.name and a.secret == b.secret


def test_import_export():
    kp = KeyPair.generate(bytes([1]) * 32)
    kp2 = KeyPair.from_json(kp.to_json())
    assert kp2.name == kp.name and kp2.secret == kp.secret


def test_verify_valid_signature():
    kp = KeyPair.generate(bytes([2]) * 32)
    d = digest32(b"Hello, world!")
    sig = kp.sign(d)
    assert verify(bytes(d), kp.name, sig)


def test_verify_invalid_signature():
    kp = KeyPair.generate(bytes([2]) * 32)
    d = digest32(b"Hello, world!")
    bad = digest32(b"tampered")
    sig = kp.sign(d)
    assert not verify(bytes(bad), kp.name, sig)
    assert not verify(bytes(d), kp.name, Signature.default())


def test_verify_valid_batch():
    d = digest32(b"Hello, batch!")
    kps = [KeyPair.generate(bytes([i]) * 32) for i in range(5)]
    sigs = [kp.sign(d) for kp in kps]
    assert verify_batch(d, [kp.name for kp in kps], sigs)


def test_verify_invalid_batch():
    d = digest32(b"Hello, batch!")
    kps = [KeyPair.generate(bytes([i]) * 32) for i in range(5)]
    sigs = [kp.sign(d) for kp in kps]
    sigs[2] = Signature.default()
    assert not verify_batch(d, [kp.name for kp in kps], sigs)
    mask = verify_batch_mask(
        [bytes(d)] * 5, [kp.name for kp in kps], sigs
    )
    assert mask == [True, True, False, True, True]


def test_signature_service():
    async def go():
        kp = KeyPair.generate(bytes([3]) * 32)
        service = SignatureService(kp)
        d = digest32(b"service")
        sig = await service.request_signature(d)
        assert verify(bytes(d), kp.name, sig)

    asyncio.run(go())


def test_pure_python_ed25519_rfc8032_vectors():
    """The dependency-free fallback signer (crypto/_ed25519_py) against
    RFC 8032 §7.1 test vectors 1 and 3 — the ground truth that holds on
    hosts with no OpenSSL to differential-test against."""
    from narwhal_tpu.crypto import _ed25519_py as E

    sk1 = bytes.fromhex(
        "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60"
    )
    assert E.secret_to_public(sk1).hex() == (
        "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a"
    )
    sig1 = E.sign(sk1, b"")
    assert sig1.hex() == (
        "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e06522490155"
        "5fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b"
    )
    assert E.verify(E.secret_to_public(sk1), b"", sig1)

    sk3 = bytes.fromhex(
        "c5aa8df43f9f837bedb7442f31dcb7b166d38535076f094b85ce3a2e0b4458f7"
    )
    msg3 = bytes.fromhex("af82")
    assert E.secret_to_public(sk3).hex() == (
        "fc51cd8e6218a1a38da47ed00230f0580816ed13ba3303ac5deb911548908025"
    )
    sig3 = E.sign(sk3, msg3)
    assert sig3.hex() == (
        "6291d657deec24024827e69c3abe01a30ce548a284743a445e3680d7db5ac3ac"
        "18ff9b538d16f290ae67f760984dc6594a7c15e9716ed28dc027beceea1ec40a"
    )
    assert E.verify(E.secret_to_public(sk3), msg3, sig3)
    # Rejections: tampered message, tampered sig, s >= L, bad point.
    assert not E.verify(E.secret_to_public(sk3), b"x" + msg3, sig3)
    assert not E.verify(E.secret_to_public(sk3), msg3, sig3[:32] + bytes(32))
    s_ge_l = sig3[:32] + (E.L).to_bytes(32, "little")
    assert not E.verify(E.secret_to_public(sk3), msg3, s_ge_l)
    assert not E.verify(bytes(31) + b"\xff", msg3, sig3)


def test_pure_python_ed25519_matches_openssl():
    """Where OpenSSL is available, the fallback signer must produce
    byte-identical signatures (ed25519 signing is deterministic) and agree
    on verification."""
    import pytest

    cryptography = pytest.importorskip("cryptography")  # noqa: F841
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
    )
    from narwhal_tpu.crypto import _ed25519_py as E

    for seed_byte in (0, 7, 42):
        seed = bytes([seed_byte]) * 32
        sk = Ed25519PrivateKey.from_private_bytes(seed)
        assert E.secret_to_public(seed) == sk.public_key().public_bytes_raw()
        msg = b"message-%d" % seed_byte
        assert E.sign(seed, msg) == sk.sign(msg)
        assert E.verify(E.secret_to_public(seed), msg, sk.sign(msg))
