"""Unit tests for metrics.InstrumentedQueue (ISSUE 17): the per-channel
backpressure accounting every inter-task channel is built from.  Covers
the counter/gauge bookkeeping through both the awaiting and *_nowait
paths, blocked-put wait observation, FIFO residence pairing, QueueFull
accounting, the NARWHAL_METRICS=0 no-op arm, and depth/high-water under
concurrent producers."""

import asyncio
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from narwhal_tpu import metrics  # noqa: E402
from narwhal_tpu.metrics import InstrumentedQueue, Registry  # noqa: E402


@pytest.fixture
def reg(monkeypatch):
    """A fresh enabled registry swapped in for the module global, so each
    test sees only its own queue.* instruments."""
    fresh = Registry(enabled=True)
    monkeypatch.setattr(metrics, "_REGISTRY", fresh)
    return fresh


def test_basic_accounting_both_paths(reg):
    async def go():
        q = InstrumentedQueue(4, channel="t.chan")
        await q.put("a")       # awaiting path
        q.put_nowait("b")      # nowait path
        await q.put("c")
        assert reg.gauges["queue.t.chan.capacity"].value == 4.0
        assert reg.gauges["queue.t.chan.depth"].value == 3.0
        assert reg.gauges["queue.t.chan.high_water"].value == 3.0
        assert reg.counters["queue.t.chan.enqueued"].value == 3
        assert q.get_nowait() == "a"   # FIFO preserved
        assert await q.get() == "b"
        assert reg.counters["queue.t.chan.dequeued"].value == 2
        assert reg.gauges["queue.t.chan.depth"].value == 1.0
        # High-water is monotone: draining must not lower it.
        assert reg.gauges["queue.t.chan.high_water"].value == 3.0
        # Residence observed once per dequeued item.
        res = reg.histograms["queue.t.chan.residence_seconds"]
        assert res.count == 2

    asyncio.run(go())


def test_put_wait_observed_only_when_blocked(reg):
    async def go():
        q = InstrumentedQueue(1, channel="t.block")
        await q.put(1)  # fits: must NOT be observed as a wait
        assert reg.histograms["queue.t.block.put_wait_seconds"].count == 0

        async def consume_later():
            await asyncio.sleep(0.05)
            return await q.get()

        consumer = asyncio.ensure_future(consume_later())
        await q.put(2)  # queue full: blocks until the consumer drains
        await consumer
        pw = reg.histograms["queue.t.block.put_wait_seconds"]
        assert pw.count == 1
        assert pw.sum >= 0.04

    asyncio.run(go())


def test_queuefull_counted_and_reraised(reg):
    async def go():
        q = InstrumentedQueue(2, channel="t.full")
        q.put_nowait(1)
        q.put_nowait(2)
        with pytest.raises(asyncio.QueueFull):
            q.put_nowait(3)
        with pytest.raises(asyncio.QueueFull):
            q.put_nowait(4)
        assert reg.counters["queue.t.full.full"].value == 2
        # Rejected items never count as enqueued.
        assert reg.counters["queue.t.full.enqueued"].value == 2

    asyncio.run(go())


def test_disabled_registry_arm_is_plain_queue(monkeypatch):
    """With NARWHAL_METRICS=0 the constructor registers nothing and the
    queue behaves exactly like asyncio.Queue — the stubbed arm of the
    overhead A/B."""
    stub = Registry(enabled=False)
    monkeypatch.setattr(metrics, "_REGISTRY", stub)

    async def go():
        q = InstrumentedQueue(2, channel="t.noop")
        await q.put("a")
        q.put_nowait("b")
        with pytest.raises(asyncio.QueueFull):
            q.put_nowait("c")
        assert await q.get() == "a"
        assert q.get_nowait() == "b"
        assert q.empty()
        snap = stub.snapshot()
        assert snap["gauges"] == {}
        assert snap["counters"] == {}
        assert snap["histograms"] == {}

    asyncio.run(go())


def test_concurrent_producers_depth_and_high_water(reg):
    """Eight producers against a capacity-4 queue and one slow consumer:
    high-water pegs at capacity, totals balance, and the final depth
    gauge reads empty."""
    total = 24

    async def go():
        q = InstrumentedQueue(4, channel="t.conc")

        async def producer(k):
            for i in range(total // 8):
                await q.put((k, i))

        async def consumer():
            for _ in range(total):
                await q.get()
                await asyncio.sleep(0.001)

        await asyncio.gather(
            consumer(), *(producer(k) for k in range(8))
        )
        assert reg.counters["queue.t.conc.enqueued"].value == total
        assert reg.counters["queue.t.conc.dequeued"].value == total
        assert reg.gauges["queue.t.conc.depth"].value == 0.0
        assert reg.gauges["queue.t.conc.high_water"].value == 4.0
        assert reg.histograms["queue.t.conc.residence_seconds"].count == total
        # Producers outnumber capacity: blocked puts were observed.
        assert reg.histograms["queue.t.conc.put_wait_seconds"].count > 0

    asyncio.run(go())
