"""Proposer tests (analog of reference proposer_tests.rs:7-68): empty header
on timeout; payload header by size."""

import asyncio

import pytest

from narwhal_tpu.crypto import SignatureService, digest32
from narwhal_tpu.primary.messages import genesis
from narwhal_tpu.primary.proposer import Proposer
from tests.common import committee, keys


@pytest.fixture
def run():
    def _run(coro):
        return asyncio.run(asyncio.wait_for(coro, 15))

    return _run


def make_proposer(c, kp, header_size=1_000, delay_ms=50):
    rx_core, rx_workers, tx_core = (
        asyncio.Queue(),
        asyncio.Queue(),
        asyncio.Queue(),
    )
    p = Proposer(
        kp.name,
        c,
        SignatureService(kp),
        header_size,
        delay_ms,
        rx_core,
        rx_workers,
        tx_core,
    )
    return p, rx_core, rx_workers, tx_core


def test_empty_header_on_timeout(run):
    async def go():
        c = committee()
        kp = keys()[0]
        p, _, _, tx_core = make_proposer(c, kp, header_size=1_000, delay_ms=50)
        task = asyncio.ensure_future(p.run())
        header = await asyncio.wait_for(tx_core.get(), 5)
        assert header.round == 1 and header.payload == {}
        assert header.parents == {x.digest() for x in genesis(c)}
        header.verify(c)
        task.cancel()

    run(go())


def test_payload_header_by_size(run):
    async def go():
        c = committee()
        kp = keys()[0]
        # Huge delay: sealing must be triggered by payload size alone.
        p, _, rx_workers, tx_core = make_proposer(
            c, kp, header_size=32, delay_ms=60_000
        )
        task = asyncio.ensure_future(p.run())
        digest = digest32(b"batch")
        await rx_workers.put((digest, 3))
        header = await asyncio.wait_for(tx_core.get(), 5)
        assert header.payload == {digest: 3} and header.round == 1
        header.verify(c)
        task.cancel()

    run(go())


def test_round_advance_requires_parents(run):
    async def go():
        c = committee()
        kp = keys()[0]
        p, rx_core, _, tx_core = make_proposer(c, kp, header_size=1_000, delay_ms=50)
        task = asyncio.ensure_future(p.run())
        first = await asyncio.wait_for(tx_core.get(), 5)
        assert first.round == 1
        # No parents delivered: proposer must NOT mint round-2 headers.
        await asyncio.sleep(0.3)
        assert tx_core.empty()
        # Parents for round 1 arrive: round advances and a header appears.
        parents = [digest32(bytes([i]) * 3) for i in range(3)]
        await rx_core.put((parents, 1))
        second = await asyncio.wait_for(tx_core.get(), 5)
        assert second.round == 2 and second.parents == set(parents)
        task.cancel()

    run(go())
