"""Proposer tests (analog of reference proposer_tests.rs:7-68): empty header
on timeout; payload header by size."""

import asyncio

import pytest

from narwhal_tpu.crypto import SignatureService, digest32
from narwhal_tpu.primary.messages import genesis
from narwhal_tpu.primary.proposer import Proposer
from tests.common import committee, keys


@pytest.fixture
def run():
    def _run(coro):
        return asyncio.run(asyncio.wait_for(coro, 15))

    return _run


def make_proposer(
    c, kp, header_size=1_000, delay_ms=50, min_delay_ms=0, linger_ms=0
):
    rx_core, rx_workers, tx_core = (
        asyncio.Queue(),
        asyncio.Queue(),
        asyncio.Queue(),
    )
    p = Proposer(
        kp.name,
        c,
        SignatureService(kp),
        header_size,
        delay_ms,
        rx_core,
        rx_workers,
        tx_core,
        min_header_delay_ms=min_delay_ms,
        header_linger_ms=linger_ms,
    )
    return p, rx_core, rx_workers, tx_core


def test_empty_header_on_timeout(run):
    async def go():
        c = committee()
        kp = keys()[0]
        p, _, _, tx_core = make_proposer(c, kp, header_size=1_000, delay_ms=50)
        task = asyncio.ensure_future(p.run())
        header = await asyncio.wait_for(tx_core.get(), 5)
        assert header.round == 1 and header.payload == {}
        assert header.parents == {x.digest() for x in genesis(c)}
        header.verify(c)
        task.cancel()

    run(go())


def test_payload_header_by_size(run):
    async def go():
        c = committee()
        kp = keys()[0]
        # Huge delay: sealing must be triggered by payload size alone.
        p, _, rx_workers, tx_core = make_proposer(
            c, kp, header_size=32, delay_ms=60_000
        )
        task = asyncio.ensure_future(p.run())
        digest = digest32(b"batch")
        await rx_workers.put((digest, 3))
        header = await asyncio.wait_for(tx_core.get(), 5)
        assert header.payload == {digest: 3} and header.round == 1
        header.verify(c)
        task.cancel()

    run(go())


def test_round_advance_requires_parents(run):
    async def go():
        c = committee()
        kp = keys()[0]
        p, rx_core, _, tx_core = make_proposer(c, kp, header_size=1_000, delay_ms=50)
        task = asyncio.ensure_future(p.run())
        first = await asyncio.wait_for(tx_core.get(), 5)
        assert first.round == 1
        # No parents delivered: proposer must NOT mint round-2 headers.
        await asyncio.sleep(0.3)
        assert tx_core.empty()
        # Parents for round 1 arrive: round advances and a header appears.
        parents = [digest32(bytes([i]) * 3) for i in range(3)]
        await rx_core.put((parents, 1))
        second = await asyncio.wait_for(tx_core.get(), 5)
        assert second.round == 2 and second.parents == set(parents)
        task.cancel()

    run(go())


# --- round-cadence edges (ISSUE r10) -----------------------------------------


def test_parents_after_expired_deadline_mint_immediately(run):
    """Parents arriving AFTER max_header_delay already expired must mint
    the next header right away, not re-arm a fresh full delay."""

    async def go():
        c = committee()
        kp = keys()[0]
        p, rx_core, _, tx_core = make_proposer(
            c, kp, header_size=1_000, delay_ms=50
        )
        task = asyncio.ensure_future(p.run())
        first = await asyncio.wait_for(tx_core.get(), 5)
        assert first.round == 1
        # Let the deadline expire several times over with no parents.
        await asyncio.sleep(0.4)
        assert tx_core.empty()
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        await rx_core.put(([digest32(bytes([i]) * 3) for i in range(3)], 1))
        second = await asyncio.wait_for(tx_core.get(), 5)
        # Immediate (empty-payload, expired timer): far less than a fresh
        # 50 ms delay, with slack for a loaded host.
        assert loop.time() - t0 < 2.0
        assert second.round == 2
        task.cancel()

    run(go())


def test_min_header_delay_proposes_partial_payload(run):
    """With the min-delay cadence on, a parent quorum plus ANY payload
    proposes after min_header_delay instead of riding max_header_delay
    (here: effectively never) waiting for header_size bytes."""

    async def go():
        c = committee()
        kp = keys()[0]
        # max delay far beyond the test timeout: only the min-delay path
        # can mint this header.
        p, _, rx_workers, tx_core = make_proposer(
            c, kp, header_size=1_000_000, delay_ms=60_000, min_delay_ms=10
        )
        task = asyncio.ensure_future(p.run())
        digest = digest32(b"one small batch")
        await rx_workers.put((digest, 0))
        header = await asyncio.wait_for(tx_core.get(), 5)
        assert header.round == 1 and header.payload == {digest: 0}
        task.cancel()

    run(go())


def test_min_header_delay_empty_rounds_still_wait_max(run):
    """Empty-payload rounds must NOT fire at the min cadence — an idle
    committee rides max_header_delay exactly as before the knob."""

    async def go():
        c = committee()
        kp = keys()[0]
        p, _, _, tx_core = make_proposer(
            c, kp, header_size=1_000, delay_ms=400, min_delay_ms=10
        )
        task = asyncio.ensure_future(p.run())
        # Well past several min periods, still inside max: no header.
        await asyncio.sleep(0.15)
        assert tx_core.empty()
        header = await asyncio.wait_for(tx_core.get(), 5)
        assert header.round == 1 and header.payload == {}
        task.cancel()

    run(go())


def test_min_header_delay_rate_limits_full_payload(run):
    """min_header_delay is also the round-cadence floor: two consecutive
    size-triggered headers must be at least min_header_delay apart."""

    async def go():
        c = committee()
        kp = keys()[0]
        p, rx_core, rx_workers, tx_core = make_proposer(
            c, kp, header_size=16, delay_ms=60_000, min_delay_ms=200
        )
        task = asyncio.ensure_future(p.run())
        loop = asyncio.get_running_loop()
        await rx_workers.put((digest32(b"a"), 0))
        first = await asyncio.wait_for(tx_core.get(), 5)
        t1 = loop.time()
        assert first.round == 1
        # Round 2 payload + parents are ready almost immediately...
        await rx_workers.put((digest32(b"b"), 0))
        await rx_core.put(([digest32(bytes([i]) * 3) for i in range(3)], 1))
        second = await asyncio.wait_for(tx_core.get(), 5)
        # ...but the mint waits out the min delay.
        assert loop.time() - t1 >= 0.15
        assert second.round == 2
        task.cancel()

    run(go())


def test_round_advance_observed_exactly_once_per_advance(run):
    """primary.round_advance_seconds gets exactly one observation per
    actual advance — duplicate or stale parent deliveries (queue path or
    the direct deliver_parents callback) observe nothing."""

    async def go():
        from narwhal_tpu import metrics

        c = committee()
        kp = keys()[0]
        p, rx_core, _, tx_core = make_proposer(c, kp, header_size=1_000, delay_ms=50)
        hist = metrics.histogram("primary.round_advance_seconds")
        base = hist.count
        task = asyncio.ensure_future(p.run())
        parents = [digest32(bytes([i]) * 3) for i in range(3)]

        # First advance (1 -> 2): arms _last_advance, no period yet.
        p.deliver_parents(parents, 1)
        assert p.round == 2 and hist.count == base
        # Second advance (2 -> 3): one observation.
        p.deliver_parents(parents, 2)
        assert p.round == 3 and hist.count == base + 1
        # Stale and duplicate deliveries: no advance, no observation.
        p.deliver_parents(parents, 2)
        p.deliver_parents(parents, 1)
        assert p.round == 3 and hist.count == base + 1
        # The queue path shares the same dedupe.
        await rx_core.put((parents, 2))
        await asyncio.sleep(0.1)
        assert p.round == 3 and hist.count == base + 1
        await rx_core.put((parents, 3))
        await asyncio.sleep(0.1)
        assert p.round == 4 and hist.count == base + 2
        task.cancel()

    run(go())


def test_deliver_parents_wakes_run_loop_and_stamps_round_trace(run):
    """The Core's direct callback must wake the proposer out of its queue
    wait (minting the next header without a queue round-trip) and stamp
    the round-cadence trace (header_proposed + round_advance)."""

    async def go():
        from narwhal_tpu import metrics

        c = committee()
        kp = keys()[0]
        p, _, _, tx_core = make_proposer(c, kp, header_size=1_000, delay_ms=50)
        task = asyncio.ensure_future(p.run())
        first = await asyncio.wait_for(tx_core.get(), 5)
        assert first.round == 1
        parents = [digest32(bytes([i]) * 3) for i in range(3)]
        p.deliver_parents(parents, 1)
        second = await asyncio.wait_for(tx_core.get(), 5)
        assert second.round == 2 and second.parents == set(parents)
        rt = metrics.round_trace().entries
        assert "header_proposed" in rt.get("1", {})
        assert "round_advance" in rt.get("1", {})
        assert "header_proposed" in rt.get("2", {})
        task.cancel()

    run(go())


def test_min_header_delay_clamped_to_max(run):
    """min_header_delay above max_header_delay is incoherent (payload
    rounds would cycle SLOWER than empty ones) — it clamps to the max."""

    async def go():
        c = committee()
        kp = keys()[0]
        p, _, _, _ = make_proposer(
            c, kp, header_size=1_000, delay_ms=100, min_delay_ms=500
        )
        assert p.min_header_delay == p.max_header_delay == 0.1

    run(go())


def test_header_linger_holds_mint_and_cites_late_parent(run):
    """With header_linger on, a round advance arms a linger window: the
    fast (payload-ready) mint path holds until it passes, and a
    post-quorum certificate forwarded via deliver_late_parent inside
    the window lands in the minted header's parent set.  Round 1 (no
    advance yet) is unaffected."""

    async def go():
        c = committee()
        kp = keys()[0]
        p, _, rx_workers, tx_core = make_proposer(
            c, kp, header_size=16, delay_ms=60_000, linger_ms=300
        )
        task = asyncio.ensure_future(p.run())
        loop = asyncio.get_running_loop()
        await rx_workers.put((digest32(b"a"), 0))
        first = await asyncio.wait_for(tx_core.get(), 5)
        assert first.round == 1  # no linger before the first advance
        parents = [digest32(bytes([i]) * 3) for i in range(3)]
        late = digest32(b"the straggler certificate")
        t0 = loop.time()
        p.deliver_parents(parents, 1)
        await rx_workers.put((digest32(b"b"), 0))
        # Payload + parents are ready, but the linger window holds...
        await asyncio.sleep(0.1)
        assert tx_core.empty()
        # ...long enough for a post-quorum certificate to be merged.
        p.deliver_late_parent(late, 1)
        second = await asyncio.wait_for(tx_core.get(), 5)
        assert loop.time() - t0 >= 0.25
        assert second.round == 2
        assert second.parents == set(parents) | {late}
        task.cancel()

    run(go())


def test_deliver_late_parent_drops_stale_duplicate_and_consumed(run):
    """The late-parent merge is citation-widening only: a stale round, a
    duplicate digest, or an already-consumed parent set are silently
    dropped."""

    async def go():
        c = committee()
        kp = keys()[0]
        p, _, _, _ = make_proposer(c, kp, linger_ms=100)
        parents = [digest32(bytes([i]) * 3) for i in range(3)]
        p.deliver_parents(parents, 1)
        assert p.round == 2
        # Stale round (certificate of round 2 while proposing round 2 —
        # only parent-round certificates, round 1, merge).
        p.deliver_late_parent(digest32(b"x"), 2)
        assert len(p.last_parents) == 3
        # Duplicate digest: no-op.
        p.deliver_late_parent(parents[0], 1)
        assert len(p.last_parents) == 3
        # Fresh parent-round digest: merged.
        extra = digest32(b"y")
        p.deliver_late_parent(extra, 1)
        assert p.last_parents[-1] == extra and len(p.last_parents) == 4
        # Consumed parent set (post-mint): no resurrection.
        p.last_parents = []
        p.deliver_late_parent(digest32(b"z"), 1)
        assert p.last_parents == []

    run(go())


def test_header_linger_clamped_to_max(run):
    """A linger window the max deadline always truncates would silently
    never run full length — it clamps to the max, loudly."""

    async def go():
        c = committee()
        kp = keys()[0]
        p, _, _, _ = make_proposer(
            c, kp, header_size=1_000, delay_ms=100, linger_ms=500
        )
        assert p.header_linger == p.max_header_delay == 0.1

    run(go())
