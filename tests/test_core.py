"""Core state-machine tests (analog of reference core_tests.rs:11-361):
vote emitted & header stored; suspension on missing parents; votes →
certificate broadcast; certificates → parents to proposer + consensus
forwarding + storage."""

import asyncio

import pytest

from narwhal_tpu.config import Parameters
from narwhal_tpu.crypto import SignatureService, digest32
from narwhal_tpu.network import Receiver
from narwhal_tpu.primary.core import AtomicRound, Core
from narwhal_tpu.primary.messages import decode_primary_message, genesis
from narwhal_tpu.primary.synchronizer import Synchronizer
from narwhal_tpu.store import Store
from tests.common import (
    RecordingAckHandler,
    committee,
    keys,
    make_certificate,
    make_header,
    make_votes,
)


@pytest.fixture
def run():
    def _run(coro):
        return asyncio.run(asyncio.wait_for(coro, 20))

    return _run


def make_core(c, me_kp, store=None):
    store = store or Store()
    qs = {
        name: asyncio.Queue()
        for name in (
            "primaries",
            "header_sync",
            "cert_sync",
            "header_loop",
            "cert_loop",
            "proposer_in",
            "consensus",
            "proposer_out",
        )
    }
    synchronizer = Synchronizer(
        me_kp.name, c, store, qs["header_sync"], qs["cert_sync"]
    )
    core = Core(
        me_kp.name,
        c,
        store,
        synchronizer,
        SignatureService(me_kp),
        AtomicRound(),
        gc_depth=50,
        rx_primaries=qs["primaries"],
        rx_header_waiter=qs["header_loop"],
        rx_certificate_waiter=qs["cert_loop"],
        rx_proposer=qs["proposer_in"],
        tx_consensus=qs["consensus"],
        tx_proposer=qs["proposer_out"],
    )
    return core, store, qs


def test_process_header_votes_and_stores(run):
    """A valid header from another authority is stored and voted for."""

    async def go():
        c = committee(base_port=13000)
        me, author = keys()[0], keys()[1]
        core, store, qs = make_core(c, me)
        # The author's primary listens for our vote.
        author_handler = RecordingAckHandler()
        recv = await Receiver.spawn(c.primary(author.name).primary_to_primary, author_handler)
        task = asyncio.ensure_future(core.run())

        header = make_header(author, c=c)
        await qs["primaries"].put(("header", header))
        await asyncio.wait_for(author_handler.arrived.wait(), 10)
        kind, vote = decode_primary_message(author_handler.received[0])
        assert kind == "vote" and vote.id == header.id and vote.author == me.name
        vote.verify(c)
        assert store.read(bytes(header.id)) is not None

        task.cancel()
        core.network.close()
        await recv.shutdown()

    run(go())


def test_process_header_suspends_on_missing_parents(run):
    async def go():
        c = committee()  # port 0: nothing dials in this test
        me, author = keys()[0], keys()[1]
        core, store, qs = make_core(c, me)
        task = asyncio.ensure_future(core.run())

        bogus_parent = digest32(b"unknown certificate")
        header = make_header(author, round_=2, parents={bogus_parent}, c=c)
        await qs["primaries"].put(("header", header))
        # The synchronizer must have scheduled a parent sync...
        kind, missing, suspended = await asyncio.wait_for(
            qs["header_sync"].get(), 5
        )
        assert kind == "sync_parents" and missing == [bogus_parent]
        assert suspended.id == header.id
        # ...and the header must NOT be stored.
        assert store.read(bytes(header.id)) is None

        task.cancel()
        core.network.close()

    run(go())


def test_process_votes_assembles_and_broadcasts_certificate(run):
    async def go():
        c = committee(base_port=13100)
        me = keys()[0]
        core, store, qs = make_core(c, me)
        # Every other primary listens for the certificate broadcast.
        listeners = []
        for _, addrs in c.others_primaries(me.name):
            h = RecordingAckHandler()
            listeners.append(
                (h, await Receiver.spawn(addrs.primary_to_primary, h))
            )
        task = asyncio.ensure_future(core.run())

        # Our own header is the current one; votes for it arrive.
        header = make_header(me, c=c)
        core.current_header = header
        for vote in make_votes(header):
            await qs["primaries"].put(("vote", vote))
        for h, _ in listeners:
            await asyncio.wait_for(h.arrived.wait(), 10)
            kind, cert = decode_primary_message(h.received[0])
            assert kind == "certificate" and cert.header.id == header.id
            cert.verify(c)

        task.cancel()
        core.network.close()
        for _, recv in listeners:
            await recv.shutdown()

    run(go())


def test_process_certificates_feeds_proposer_and_consensus(run):
    """A quorum of round-1 certificates advances the proposer and reaches
    consensus (reference core_tests.rs process_certificates)."""

    async def go():
        c = committee()  # no network use: certificates arrive via queue
        me = keys()[0]
        core, store, qs = make_core(c, me)
        task = asyncio.ensure_future(core.run())

        certs = [make_certificate(make_header(kp, c=c)) for kp in keys()[:3]]
        for cert in certs:
            await qs["primaries"].put(("certificate", cert))

        # All three reach consensus in order.
        got = [await asyncio.wait_for(qs["consensus"].get(), 5) for _ in range(3)]
        assert [g.digest() for g in got] == [x.digest() for x in certs]
        # The third certificate completes the quorum: proposer gets parents.
        parents, round_ = await asyncio.wait_for(qs["proposer_out"].get(), 5)
        assert round_ == 1 and sorted(parents) == sorted(
            x.digest() for x in certs
        )
        # All certificates are stored.
        for cert in certs:
            assert store.read(bytes(cert.digest())) is not None

        task.cancel()
        core.network.close()

    run(go())


def test_sanitize_rejects_gc_old_header(run):
    async def go():
        c = committee()
        me, author = keys()[0], keys()[1]
        core, store, qs = make_core(c, me)
        core.gc_round = 10
        header = make_header(author, round_=5, c=c)
        task = asyncio.ensure_future(core.run())
        await qs["primaries"].put(("header", header))
        await asyncio.sleep(0.2)
        assert store.read(bytes(header.id)) is None  # dropped as TooOld
        task.cancel()
        core.network.close()

    run(go())


def test_vote_on_equivocating_header_only_once(run):
    """Two different headers from the same (round, author): only the first
    gets our vote (last_voted dedupe)."""

    async def go():
        c = committee(base_port=13200)
        me, author = keys()[0], keys()[1]
        core, store, qs = make_core(c, me)
        author_handler = RecordingAckHandler()
        recv = await Receiver.spawn(
            c.primary(author.name).primary_to_primary, author_handler
        )
        task = asyncio.ensure_future(core.run())

        h1 = make_header(author, c=c)
        h2 = make_header(author, payload={digest32(b"x"): 0}, c=c)
        assert h1.id != h2.id
        await qs["primaries"].put(("header", h1))
        await qs["primaries"].put(("header", h2))
        await asyncio.sleep(0.5)
        votes = [
            decode_primary_message(m)
            for m in author_handler.received
        ]
        assert len(votes) == 1 and votes[0][1].id == h1.id
        # The first header is stored; the second suspended on its (unknown)
        # payload — batch sync scheduled, header not yet stored.
        assert store.read(bytes(h1.id)) is not None
        assert store.read(bytes(h2.id)) is None
        kind, missing, suspended = qs["header_sync"].get_nowait()
        assert kind == "sync_batches" and suspended.id == h2.id

        task.cancel()
        core.network.close()
        await recv.shutdown()

    run(go())


def test_burst_verifies_in_one_backend_call(run):
    """A drained burst of N messages goes through exactly ONE
    verify_batch_mask backend call (accumulate → batch-verify → replay,
    SURVEY.md §7), and a bad signature inside the burst only rejects its
    own message."""

    async def go():
        from narwhal_tpu.crypto import backend as cb
        from narwhal_tpu.crypto import Signature

        c = committee(base_port=13200)
        me, author = keys()[0], keys()[1]
        core, store, qs = make_core(c, me)
        author_handler = RecordingAckHandler()
        recv = await Receiver.spawn(
            c.primary(author.name).primary_to_primary, author_handler
        )

        headers = [
            make_header(author, c=c),
            make_header(keys()[2], c=c),
            make_header(keys()[3], c=c),
        ]
        # Same shape as a valid header (3-of-4 genesis parents still meet
        # quorum, all resolvable), so it WOULD be stored if the signature
        # check were broken — only the zeroed signature rejects it.
        some_parents = sorted(x.digest() for x in genesis(c))[:3]
        forged = make_header(author, parents=some_parents, c=c)
        forged.signature = Signature(bytes(64))

        calls = []
        real = cb.averify_batch_mask

        async def counting(msgs, ks, ss, site="other"):
            calls.append(len(msgs))
            return await real(msgs, ks, ss)

        cb.averify_batch_mask, orig = counting, cb.averify_batch_mask
        try:
            for h in headers:
                await qs["primaries"].put(("header", h))
            await qs["primaries"].put(("header", forged))
            task = asyncio.ensure_future(core.run())
            for _ in range(200):
                if all(store.read(bytes(h.id)) is not None for h in headers):
                    break
                await asyncio.sleep(0.02)
            assert all(store.read(bytes(h.id)) is not None for h in headers)
            assert store.read(bytes(forged.id)) is None  # rejected
            # All four messages' claims verified in one backend call.
            assert calls and calls[0] == 4, calls
            task.cancel()
        finally:
            cb.averify_batch_mask = orig
            core.network.close()
            await recv.shutdown()

    run(go())


def test_stale_burst_item_replays_fail_closed(run):
    """A stale-filtered burst item carries zero crypto claims; it must be
    replayed with sig_ok=False (fail closed), never `all([]) == True` —
    regression for the round-3 advisor finding on core.py's pre-filter."""

    async def go():
        c = committee()
        me, author = keys()[0], keys()[1]
        core, store, qs = make_core(c, me)
        core.gc_round = 10
        stale = make_header(author, round_=5, c=c)
        fresh = make_header(author, round_=12, c=c)

        seen = []

        async def recording(source, item, sig_ok):
            seen.append((item[1].id, sig_ok))

        core._handle = recording
        await core._handle_primaries_burst(
            [("header", stale), ("header", fresh)]
        )
        assert seen == [(stale.id, False), (fresh.id, True)]
        core.network.close()

    run(go())


def test_certificate_waiter_parks_until_parents_stored(run):
    """CertificateWaiter (reference certificate_waiter.rs): a certificate
    whose parents are missing parks on notify_read and loops back to the
    Core only once EVERY parent digest hits the store; GC cancels parked
    waits that fall behind the consensus round."""
    from narwhal_tpu.primary.certificate_waiter import CertificateWaiter

    async def go():
        c = committee(base_port=13400)
        kps = keys()
        store = Store()
        consensus_round = AtomicRound()
        rx, tx_core = asyncio.Queue(), asyncio.Queue()
        waiter = CertificateWaiter(
            store, consensus_round, gc_depth=50, rx_synchronizer=rx,
            tx_core=tx_core,
        )
        task = asyncio.get_running_loop().create_task(waiter.run())

        parents = {h.digest() for h in genesis(c)}
        header = make_header(kps[0], round_=1, parents=parents, c=c)
        cert = make_certificate(header)
        await rx.put(cert)
        await asyncio.sleep(0.05)
        assert tx_core.empty()  # parked: no parent is stored yet

        some = list(parents)
        store.write(bytes(some[0]), b"\x01")
        await asyncio.sleep(0.05)
        assert tx_core.empty()  # one of several parents isn't enough

        for d in some[1:]:
            store.write(bytes(d), b"\x01")
        released = await asyncio.wait_for(tx_core.get(), 5)
        assert released.digest() == cert.digest()
        assert cert.digest() not in waiter.pending

        # GC: park a second certificate, advance the consensus round past
        # the GC window, and poke the waiter — the parked task is dropped.
        header2 = make_header(kps[1], round_=1, parents=parents, c=c)
        cert2 = make_certificate(header2)
        # Remove one parent so it stays parked (fresh store key space).
        store2 = Store()
        waiter.store = store2
        await rx.put(cert2)
        await asyncio.sleep(0.05)
        assert cert2.digest() in waiter.pending
        consensus_round.value = 100  # gc_round = 50 >= cert2.round
        header3 = make_header(kps[2], round_=1, parents=parents, c=c)
        await rx.put(make_certificate(header3))  # any arrival triggers _gc
        await asyncio.sleep(0.05)
        assert cert2.digest() not in waiter.pending
        task.cancel()

    run(go())


# --- round-cadence fast path (ISSUE r10) -------------------------------------


def test_gc_sweep_per_burst_shrinks_round_maps(run):
    """The GC sweep is hoisted to once per drained burst (no longer per
    message), and per-round maps must still shrink once the shared
    consensus round moves past the GC window."""

    async def go():
        c = committee()
        me = keys()[0]
        core, store, qs = make_core(c, me)
        # Populate per-round state well below the future GC round.
        for r in range(1, 6):
            core.last_voted.setdefault(r, set()).add(me.name)
            core.processing.setdefault(r, set()).add(digest32(bytes([r])))
            core.cancel_handlers.setdefault(r, []).append(
                asyncio.get_running_loop().create_future()
            )
        task = asyncio.ensure_future(core.run())
        core.consensus_round.value = 60  # gc_depth=50 -> gc_round=10
        # Any burst triggers the sweep; a stale header is enough.
        await qs["primaries"].put(("header", make_header(keys()[1], c=c)))
        for _ in range(100):
            if core.gc_round == 10:
                break
            await asyncio.sleep(0.02)
        assert core.gc_round == 10
        assert not core.last_voted and not core.processing
        assert not core.cancel_handlers
        task.cancel()
        core.network.close()

    run(go())


def test_vote_fast_path_coalesces_header_persists(run):
    """A drained burst of N valid headers: every vote still goes out and
    every header is durably logged, but the log append happens ONCE for
    the whole burst (one writev), after which the staged votes are
    released — persist-before-vote, coalesced per burst."""

    async def go():
        import os as _os
        import tempfile

        from narwhal_tpu.store import Store as _Store

        c = committee(base_port=13500)
        me = keys()[0]
        authors = keys()[1:4]
        # File-backed: the deferred/coalesced log path only exists with a
        # log fd (memory-only stores have nothing to defer).
        tmpdir = tempfile.mkdtemp(prefix="core_fastpath_")
        store = _Store(_os.path.join(tmpdir, "store.log"))
        core, store, qs = make_core(c, me, store=store)
        assert core.fast_path  # default arm

        flushes = []
        real_flush = store.flush_deferred

        def counting_flush():
            if store._pending:
                flushes.append(len(store._pending) // 3)  # records pending
            real_flush()

        store.flush_deferred = counting_flush

        listeners = []
        for kp in authors:
            h = RecordingAckHandler()
            listeners.append(
                (h, await Receiver.spawn(
                    c.primary(kp.name).primary_to_primary, h
                ))
            )
        # Queue the whole burst BEFORE the core runs, so one drain sees
        # all three headers.
        for kp in authors:
            await qs["primaries"].put(("header", make_header(kp, c=c)))
        task = asyncio.ensure_future(core.run())
        for h, _ in listeners:
            await asyncio.wait_for(h.arrived.wait(), 10)
            kind, vote = decode_primary_message(h.received[0])
            assert kind == "vote" and vote.author == me.name
        # All three headers buffered into ONE coalesced flush, and every
        # record durably logged (persist-before-vote preserved).
        assert flushes and flushes[0] == 3, flushes
        store.close()
        replayed = _Store(_os.path.join(tmpdir, "store.log"))
        for kp in authors:
            assert replayed.read(bytes(make_header(kp, c=c).id)) is not None
        replayed.close()
        task.cancel()
        core.network.close()
        for _, recv in listeners:
            await recv.shutdown()

    run(go())


def test_legacy_arm_persists_and_votes_per_header(run):
    """fast_path=False (the bench_cadence A/B legacy arm) keeps the
    per-header persist + immediate vote send."""

    async def go():
        c = committee(base_port=13600)
        me, author = keys()[0], keys()[1]
        core, store, qs = make_core(c, me)
        core.fast_path = False
        author_handler = RecordingAckHandler()
        recv = await Receiver.spawn(
            c.primary(author.name).primary_to_primary, author_handler
        )
        task = asyncio.ensure_future(core.run())
        header = make_header(author, c=c)
        await qs["primaries"].put(("header", header))
        await asyncio.wait_for(author_handler.arrived.wait(), 10)
        kind, vote = decode_primary_message(author_handler.received[0])
        assert kind == "vote" and vote.id == header.id
        assert store.read(bytes(header.id)) is not None
        task.cancel()
        core.network.close()
        await recv.shutdown()

    run(go())


def test_parent_quorum_delivered_via_direct_callback(run):
    """With parents_cb wired (the Primary's default), a certificate
    quorum invokes the callback synchronously instead of the queue."""

    async def go():
        c = committee()
        me = keys()[0]
        core, store, qs = make_core(c, me)
        delivered = []
        core.parents_cb = lambda parents, round: delivered.append(
            (sorted(parents), round)
        )
        task = asyncio.ensure_future(core.run())
        certs = [make_certificate(make_header(kp, c=c)) for kp in keys()[:3]]
        for cert in certs:
            await qs["primaries"].put(("certificate", cert))
        got = [await asyncio.wait_for(qs["consensus"].get(), 5) for _ in range(3)]
        assert [g.digest() for g in got] == [x.digest() for x in certs]
        assert delivered == [
            (sorted(x.digest() for x in certs), 1)
        ]
        assert qs["proposer_out"].empty()  # queue path not used
        task.cancel()
        core.network.close()

    run(go())


def test_round_trace_stamped_through_header_vote_cert_cycle(run):
    """One full own-header cycle stamps the round-cadence sub-stages the
    bench attribution joins: header_broadcast, first_vote, vote_quorum,
    cert_broadcast, parent_quorum."""

    async def go():
        from narwhal_tpu import metrics

        metrics.round_trace().entries.clear()
        c = committee(base_port=13700)
        me = keys()[0]
        core, store, qs = make_core(c, me)
        listeners = []
        for _, addrs in c.others_primaries(me.name):
            h = RecordingAckHandler()
            listeners.append(
                (h, await Receiver.spawn(addrs.primary_to_primary, h))
            )
        task = asyncio.ensure_future(core.run())

        header = make_header(me, c=c)
        await qs["proposer_in"].put(header)  # own proposal path
        # The core must adopt the header before its votes are valid
        # (sanitize_vote rejects votes for a foreign current_header).
        for _ in range(200):
            if core.current_header is header:
                break
            await asyncio.sleep(0.02)
        assert core.current_header is header
        for vote in make_votes(header):
            await qs["primaries"].put(("vote", vote))
        for kp in keys()[1:4]:
            await qs["primaries"].put(
                ("certificate", make_certificate(make_header(kp, c=c)))
            )
        # Own cert + two others complete the round-1 parent quorum.
        for _ in range(200):
            if "parent_quorum" in metrics.round_trace().entries.get("1", {}):
                break
            await asyncio.sleep(0.02)
        entry = metrics.round_trace().entries.get("1", {})
        for stage in (
            "header_broadcast", "first_vote", "vote_quorum",
            "cert_broadcast", "parent_quorum",
        ):
            assert stage in entry, (stage, entry)
        task.cancel()
        core.network.close()
        for _, recv in listeners:
            await recv.shutdown()

    run(go())


def test_core_requires_a_parent_quorum_sink():
    """Neither parents_cb nor tx_proposer: fail at construction, not by
    silently discarding every parent quorum at runtime."""
    import pytest

    from narwhal_tpu.crypto import SignatureService as _SS
    from narwhal_tpu.store import Store as _Store

    c = committee()
    me = keys()[0]
    store = _Store()
    qs = [asyncio.Queue() for _ in range(6)]
    with pytest.raises(ValueError, match="parent-quorum sink"):
        Core(
            me.name, c, store,
            Synchronizer(me.name, c, store, qs[0], qs[1]),
            _SS(me), AtomicRound(), gc_depth=50,
            rx_primaries=qs[2], rx_header_waiter=qs[3],
            rx_certificate_waiter=qs[4], rx_proposer=qs[5],
            tx_consensus=asyncio.Queue(),
        )


def test_duplicate_delivery_skips_crypto_via_verified_cache(run):
    """Re-delivery of an already-verified header pays ZERO crypto (the
    verified-digest cache): during catch-up the same certificates arrive
    several times over (sync-retry responses race retransmissions), and
    at pure-Python verify speeds paying per-copy crypto is what let the
    re-request flood outrun verification in the partition-heal fault
    scenario.  A rejected forgery must NOT enter the cache."""

    async def go():
        from narwhal_tpu.crypto import backend as cb
        from narwhal_tpu.crypto import Signature

        c = committee()
        me, author = keys()[0], keys()[1]
        core, store, qs = make_core(c, me)
        header = make_header(author, c=c)
        some_parents = sorted(x.digest() for x in genesis(c))[:3]
        forged = make_header(keys()[2], parents=some_parents, c=c)
        forged.signature = Signature(bytes(64))

        seen = []

        async def recording(source, item, sig_ok):
            seen.append((item[1].id, sig_ok))

        core._handle = recording
        calls = []
        real = cb.averify_batch_mask

        async def counting(msgs, ks, ss, site="other"):
            calls.append(len(msgs))
            return await real(msgs, ks, ss)

        cb.averify_batch_mask = counting
        try:
            await core._handle_primaries_burst([("header", header)])
            # Re-delivery: replayed with sig_ok=True, zero backend calls.
            await core._handle_primaries_burst([("header", header)])
            assert calls == [1], calls
            assert seen == [(header.id, True), (header.id, True)]
            # A forgery is rejected AND stays out of the cache: its
            # re-delivery is re-verified (and re-rejected), not waved in.
            await core._handle_primaries_burst([("header", forged)])
            await core._handle_primaries_burst([("header", forged)])
            assert calls == [1, 1, 1], calls
            assert seen[-2:] == [(forged.id, False), (forged.id, False)]
        finally:
            cb.averify_batch_mask = real
        core.network.close()

    run(go())


def test_tampered_redelivery_misses_cache_and_is_rejected(run):
    """The verified cache keys on the SIGNATURE bytes, not just the
    content digest: a re-sent header/certificate whose signatures were
    tampered (same id / digest) must pay crypto again and be rejected —
    a digest-only key would wave it through with sig_ok=True and its
    store.write would replace the genuine record with bytes every
    syncing peer rejects."""

    async def go():
        from narwhal_tpu.crypto import Signature
        from narwhal_tpu.crypto import backend as cb
        from narwhal_tpu.primary.messages import Certificate, Header

        c = committee()
        me, author = keys()[0], keys()[1]
        core, store, qs = make_core(c, me)

        seen = []

        async def recording(source, item, sig_ok):
            seen.append(sig_ok)

        core._handle = recording
        calls = []
        real = cb.averify_batch_mask

        async def counting(msgs, ks, ss, site="other"):
            calls.append(len(msgs))
            return await real(msgs, ks, ss)

        cb.averify_batch_mask = counting
        try:
            # Header: same id, corrupted signature.
            header = make_header(author, c=c)
            tampered = Header(
                author=header.author, round=header.round,
                payload=dict(header.payload), parents=set(header.parents),
            )
            tampered.id = header.id
            tampered.signature = Signature(bytes(64))
            await core._handle_primaries_burst([("header", header)])
            await core._handle_primaries_burst([("header", tampered)])
            assert len(calls) == 2, calls  # tampered copy re-verified...
            assert seen == [True, False]  # ...and rejected
            # The genuine copy still rides the cache afterwards.
            await core._handle_primaries_burst([("header", header)])
            assert len(calls) == 2 and seen[-1] is True

            # Certificate: same digest, one vote signature corrupted.
            cert = make_certificate(make_header(keys()[2], c=c))
            votes = list(cert.votes)
            votes[0] = (votes[0][0], Signature(bytes(64)))
            tampered_cert = Certificate(header=cert.header, votes=votes)
            assert tampered_cert.digest() == cert.digest()
            await core._handle_primaries_burst([("certificate", cert)])
            await core._handle_primaries_burst(
                [("certificate", tampered_cert)]
            )
            assert len(calls) == 4, calls
            assert seen[-2:] == [True, False]
            await core._handle_primaries_burst([("certificate", cert)])
            assert len(calls) == 4 and seen[-1] is True
        finally:
            cb.averify_batch_mask = real
        core.network.close()

    run(go())


def test_late_vote_still_counts_toward_peer_votes(run):
    """A vote that races our next proposal (one round late) is verified
    and still reaches the receipt-time per-peer counter: an
    honest-but-slow peer is voting, and must not read as silent to
    peer_vote_silence.  Everything that is NOT a genuine, fresh vote for
    a header we actually proposed is excluded: far-late votes (2+
    rounds) skip crypto AND counting, a forged near-late vote is
    verified and excluded, a validly SELF-signed vote naming a header id
    we never proposed is excluded, and a re-delivered copy of a genuine
    vote counts once — a Byzantine node cannot keep a withholding
    accomplice's (or its own) counter warm with any of them."""

    async def go():
        from narwhal_tpu.crypto import Signature
        from narwhal_tpu.crypto import backend as cb

        c = committee()
        me = keys()[0]
        core, store, qs = make_core(c, me)
        h1 = make_header(me, c=c)
        core.current_header = make_header(me, round_=2, c=c)
        # The attribution witness process_own_header would have written.
        core.own_header_ids[1] = h1.id
        core.own_header_ids[2] = core.current_header.id

        vote = make_votes(h1)[0]  # round 1 == 2-1: late
        counter = core._peer_vote_counters[vote.author]
        before_peer = counter.value
        before_late = core._m_late_votes.value
        before_stale = core._m_stale.value

        calls = []
        real = cb.averify_batch_mask

        async def counting(msgs, ks, ss, site="other"):
            calls.append(len(msgs))
            return await real(msgs, ks, ss)

        cb.averify_batch_mask = counting
        try:
            # Near-late vote in a MIXED burst with a fresh header: the
            # vote's claim is verified alongside the header's in the one
            # batch call, and the vote is counted.
            fresh = make_header(keys()[2], c=c)
            await core._handle_primaries_burst(
                [("vote", vote), ("header", fresh)]
            )
            assert calls == [2], calls  # vote + header claims, one batch
            assert counter.value == before_peer + 1  # peer is NOT silent
            assert core._m_late_votes.value == before_late + 1
            assert core._m_stale.value == before_stale  # late ≠ replay

            # Re-delivered copy of the SAME genuine vote (retransmission,
            # or deliberate replay by the voter): once per (round, peer).
            await core._handle_primaries_burst([("vote", vote)])
            assert calls == [2, 1], calls
            assert counter.value == before_peer + 1
            assert core._m_late_votes.value == before_late + 2

            # Validly self-signed vote naming a header id we NEVER
            # proposed for its round: signature passes, attribution
            # fails, NOT counted (it is not a vote for us).
            phantom = make_header(
                me, round_=2, payload={digest32(b"phantom"): 0}, c=c
            )
            assert phantom.id != core.own_header_ids[2]
            fabricated = make_votes(phantom)[0]
            await core._handle_primaries_burst([("vote", fabricated)])
            assert calls == [2, 1, 1], calls
            assert counter.value == before_peer + 1

            # Far-late (2+ rounds behind): zero crypto, NOT counted, and
            # still within the GC window so it reads as LATE, not stale.
            core.current_header = make_header(me, round_=3, c=c)
            core.own_header_ids[3] = core.current_header.id
            await core._handle_primaries_burst([("vote", vote)])
            assert calls == [2, 1, 1], calls
            assert counter.value == before_peer + 1
            assert core._m_late_votes.value == before_late + 3

            # Forged near-late vote: verified, rejected, NOT counted
            # (the round check still classifies it late before the
            # signature gate ever matters).
            forged = make_votes(make_header(me, round_=2, c=c))[0]
            forged.signature = Signature(bytes(64))
            await core._handle_primaries_burst([("vote", forged)])
            assert calls == [2, 1, 1, 1], calls
            assert counter.value == before_peer + 1
            assert core._m_late_votes.value == before_late + 4

            # Below the GC horizon: a replayed ancient vote is REPLAY
            # material like a header/certificate — it lands in
            # stale_messages (feeding the stale_replay rule), not in
            # late_votes, and still skips crypto and counting.
            core.gc_round = 5
            core.current_header = make_header(me, round_=6, c=c)
            await core._handle_primaries_burst([("vote", vote)])
            assert calls == [2, 1, 1, 1], calls
            assert counter.value == before_peer + 1
            assert core._m_late_votes.value == before_late + 4  # unchanged
            assert core._m_stale.value == before_stale + 1
        finally:
            cb.averify_batch_mask = real
        core.network.close()

    run(go())


def test_equivocation_counted_once_per_twin(run):
    """Retransmissions and sync re-sends re-deliver the same conflicting
    header; each distinct twin must count ONCE toward
    primary.equivocations_detected, or the counter misreports attack
    magnitude.  A third distinct header for the slot is a new proven
    statement and counts again."""

    async def go():
        c = committee()
        me, author = keys()[0], keys()[1]
        core, store, qs = make_core(c, me)
        g = sorted(x.digest() for x in genesis(c))
        h1 = make_header(author, parents=set(g), c=c)
        twin = make_header(author, parents=set(g[:3]), c=c)
        third = make_header(author, parents=set(g[1:]), c=c)
        assert len({h1.id, twin.id, third.id}) == 3
        base = core._m_equivocations.value

        await core.process_header(h1)  # we vote for h1
        await core.process_header(twin)
        assert core._m_equivocations.value == base + 1
        await core.process_header(twin)  # re-delivery: no double count
        await core.process_header(twin)
        assert core._m_equivocations.value == base + 1
        await core.process_header(third)
        assert core._m_equivocations.value == base + 2
        core.network.close()

    run(go())


def test_equivocation_proven_at_verified_receipt_before_payload_sync(run):
    """The receipt-time witness (PR 15): two validly-signed headers for
    one (round, author) slot are a proven equivocation the moment both
    signatures check out — BEFORE any payload/parent sync completes.
    Both headers here reference a batch the store does not hold, so
    process_header parks them in the waiter; the vote-time witness alone
    never fires (the masking that let equivocate+withhold compositions
    sail past the `equivocation` rule at N≥10 in the sim sweep)."""

    async def go():
        c = committee()
        me, author = keys()[0], keys()[1]
        core, store, qs = make_core(c, me)
        missing = {digest32(b"never-sealed"): 0}
        g = sorted(x.digest() for x in genesis(c))
        h1 = make_header(author, payload=dict(missing), parents=set(g), c=c)
        twin = make_header(
            author, payload=dict(missing), parents=set(g[:3]), c=c
        )
        assert h1.id != twin.id
        base = core._m_equivocations.value

        await core._handle("primaries", ("header", h1), sig_ok=True)
        # Parked on the missing batch: no vote was emitted, so the
        # vote-time witness holds nothing for this slot.
        assert author not in core.last_voted.get(1, set())
        await core._handle("primaries", ("header", twin), sig_ok=True)
        assert core._m_equivocations.value == base + 1
        # Re-delivery still counts once.
        await core._handle("primaries", ("header", twin), sig_ok=True)
        assert core._m_equivocations.value == base + 1
        core.network.close()

    run(go())


def test_certificate_embedded_header_proves_equivocation(run):
    """A twin-voter that only ever received the twin DIRECTLY proves the
    equivocation when the real header's CERTIFICATE arrives (the
    embedded header's signature is one of the certificate's verified
    claims) — the evidence path that crosses the adversary's disjoint
    peer split."""

    async def go():
        c = committee()
        me, author = keys()[0], keys()[1]
        core, store, qs = make_core(c, me)
        g = sorted(x.digest() for x in genesis(c))
        real = make_header(author, parents=set(g), c=c)
        twin = make_header(author, parents=set(g[:3]), c=c)
        base = core._m_equivocations.value

        # We saw only the twin (and voted for it).
        await core._handle("primaries", ("header", twin), sig_ok=True)
        assert core._m_equivocations.value == base
        # The real header reaches us only inside its certificate.
        await core._handle(
            "primaries", ("certificate", make_certificate(real)),
            sig_ok=True,
        )
        assert core._m_equivocations.value == base + 1
        core.network.close()

    run(go())


def test_forged_header_never_feeds_the_receipt_witness(run):
    """A header whose signature FAILED verification must not seed (or
    trip) the receipt-time witness: invalid statements prove nothing."""

    async def go():
        c = committee()
        me, author = keys()[0], keys()[1]
        core, store, qs = make_core(c, me)
        g = sorted(x.digest() for x in genesis(c))
        forged = make_header(author, parents=set(g[:3]), c=c)
        real = make_header(author, parents=set(g), c=c)
        base = core._m_equivocations.value

        await core._handle("primaries", ("header", forged), sig_ok=False)
        assert core._m_invalid_sigs.value >= 1
        await core._handle("primaries", ("header", real), sig_ok=True)
        # The forged twin never entered the witness, so the real header
        # is the FIRST seen id — no equivocation.
        assert core._m_equivocations.value == base
        core.network.close()

    run(go())
