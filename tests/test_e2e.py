"""Full-system end-to-end test: a 4-node committee (primary + worker +
consensus each) in one process over loopback TCP; client transactions must
come out as committed certificates carrying their batch digest at every node
(the reference's `fab local` path as a test, SURVEY.md §7)."""

import asyncio

import pytest

from narwhal_tpu.config import Parameters
from narwhal_tpu.network.framing import parse_address, write_frame
from narwhal_tpu.node import spawn_primary_node, spawn_worker_node
from tests.common import committee, keys


@pytest.fixture
def run():
    def _run(coro):
        return asyncio.run(asyncio.wait_for(coro, 60))

    return _run


def test_four_node_commit(run):
    async def go():
        c = committee(base_port=14000)
        params = Parameters(
            header_size=32,  # propose as soon as one digest arrives
            max_header_delay=100,
            batch_size=400,
            max_batch_delay=100,
        )
        commits = {i: [] for i in range(4)}
        nodes = []
        for i, kp in enumerate(keys()):
            nodes.append(
                await spawn_primary_node(
                    kp,
                    c,
                    params,
                    on_commit=lambda cert, i=i: commits[i].append(cert),
                )
            )
            nodes.append(await spawn_worker_node(kp, 0, c, params))

        # Push transactions into node 0's worker.
        host, port = parse_address(c.worker(keys()[0].name, 0).transactions)
        _, w = await asyncio.open_connection(host, port)
        txs = [bytes([1]) + i.to_bytes(8, "little") + bytes(91) for i in range(8)]
        for tx in txs:
            await write_frame(w, tx)

        # batch_size=400 seals every 4 of our 100 B txs into one batch; wait
        # until BOTH batches commit at every node.
        from narwhal_tpu.crypto import digest32
        from narwhal_tpu.messages import encode_batch

        expected = {
            digest32(encode_batch(txs[:4])),
            digest32(encode_batch(txs[4:])),
        }

        def payload_committed(certs):
            return expected <= {
                d for cert in certs for d in cert.header.payload
            }

        for _ in range(600):
            if all(payload_committed(v) for v in commits.values()):
                break
            await asyncio.sleep(0.1)
        else:
            raise AssertionError(
                f"payload never committed: {[len(v) for v in commits.values()]}"
            )

        # All nodes commit the same certificates in the same order.
        seqs = [
            [cert.digest() for cert in commits[i]] for i in range(4)
        ]
        common = min(len(s) for s in seqs)
        assert common > 0
        for i in range(1, 4):
            assert seqs[i][:common] == seqs[0][:common]


        w.close()
        for node in nodes:
            await node.shutdown()

    run(go())
