"""Full-system end-to-end tests: committees (primary + worker + consensus
each) in one process over loopback TCP; client transactions must come out as
committed certificates carrying their batch digest at every node (the
reference's `fab local` path as a test, SURVEY.md §7), including at N=10,
with multiple workers, under a crash fault, and across a node restart."""

import asyncio

import pytest

from narwhal_tpu.config import Parameters
from narwhal_tpu.network.framing import parse_address, write_frame
from narwhal_tpu.node import spawn_primary_node, spawn_worker_node
from tests.common import committee, keys


@pytest.fixture
def run():
    def _run(coro):
        return asyncio.run(asyncio.wait_for(coro, 60))

    return _run


def test_four_node_commit(run):
    async def go():
        c = committee(base_port=14000)
        params = Parameters(
            header_size=32,  # propose as soon as one digest arrives
            max_header_delay=100,
            batch_size=400,
            max_batch_delay=100,
        )
        commits = {i: [] for i in range(4)}
        nodes = []
        for i, kp in enumerate(keys()):
            nodes.append(
                await spawn_primary_node(
                    kp,
                    c,
                    params,
                    on_commit=lambda cert, i=i: commits[i].append(cert),
                )
            )
            nodes.append(await spawn_worker_node(kp, 0, c, params))

        # Push transactions into node 0's worker.
        host, port = parse_address(c.worker(keys()[0].name, 0).transactions)
        _, w = await asyncio.open_connection(host, port)
        txs = [bytes([1]) + i.to_bytes(8, "little") + bytes(91) for i in range(8)]
        for tx in txs:
            await write_frame(w, tx)

        # batch_size=400 seals every 4 of our 100 B txs into one batch; wait
        # until BOTH batches commit at every node.
        from narwhal_tpu.crypto import digest32
        from narwhal_tpu.messages import encode_batch

        expected = {
            digest32(encode_batch(txs[:4])),
            digest32(encode_batch(txs[4:])),
        }

        def payload_committed(certs):
            return expected <= {
                d for cert in certs for d in cert.header.payload
            }

        for _ in range(600):
            if all(payload_committed(v) for v in commits.values()):
                break
            await asyncio.sleep(0.1)
        else:
            raise AssertionError(
                f"payload never committed: {[len(v) for v in commits.values()]}"
            )

        # All nodes commit the same certificates in the same order.
        seqs = [
            [cert.digest() for cert in commits[i]] for i in range(4)
        ]
        common = min(len(s) for s in seqs)
        assert common > 0
        for i in range(1, 4):
            assert seqs[i][:common] == seqs[0][:common]


        w.close()
        for node in nodes:
            await node.shutdown()

    run(go())


def test_multi_worker_commit(run):
    """Horizontal payload sharding (reference config/src/lib.rs:230-246):
    4 nodes × 2 workers; clients feed BOTH workers of node 0, and batches
    sealed by each worker id must be committed — proving the per-worker-id
    broadcast planes, digest‖worker_id payload keying, and the primary's
    payload bookkeeping work end to end."""

    async def go():
        c = committee(base_port=14200, workers=2)
        params = Parameters(
            header_size=32,
            max_header_delay=100,
            batch_size=400,
            max_batch_delay=100,
        )
        commits = {i: [] for i in range(4)}
        nodes = []
        for i, kp in enumerate(keys()):
            nodes.append(
                await spawn_primary_node(
                    kp,
                    c,
                    params,
                    on_commit=lambda cert, i=i: commits[i].append(cert),
                )
            )
            for wid in (0, 1):
                nodes.append(await spawn_worker_node(kp, wid, c, params))

        from narwhal_tpu.crypto import digest32
        from narwhal_tpu.messages import encode_batch

        expected = {}  # digest -> worker id that must have sealed it
        writers = []
        for wid in (0, 1):
            host, port = parse_address(
                c.worker(keys()[0].name, wid).transactions
            )
            _, w = await asyncio.open_connection(host, port)
            writers.append(w)
            txs = [
                bytes([1]) + (wid * 100 + i).to_bytes(8, "little") + bytes(91)
                for i in range(4)
            ]
            for tx in txs:
                await write_frame(w, tx)
            expected[digest32(encode_batch(txs))] = wid

        def committed_payload(certs):
            return {
                d: wid
                for cert in certs
                for d, wid in cert.header.payload.items()
            }

        for _ in range(600):
            if all(
                set(expected) <= set(committed_payload(v))
                for v in commits.values()
            ):
                break
            await asyncio.sleep(0.1)
        else:
            raise AssertionError(
                "multi-worker payload never committed: "
                f"{[len(v) for v in commits.values()]}"
            )

        # Every committed digest is attributed to the worker that sealed it.
        for i in range(4):
            payload = committed_payload(commits[i])
            for d, wid in expected.items():
                assert payload[d] == wid, (i, payload[d], wid)

        for w in writers:
            w.close()
        for node in nodes:
            await node.shutdown()

    run(go())


def test_restarted_node_rejoins_and_commits(run, tmp_path):
    """Crash-stop recovery (reference §5: persisted batches/headers/certs +
    ReliableSender retransmission + waiter sync): node 3 is shut down after
    the first commit and restarted from its on-disk stores; it must rejoin
    the committee — catching up its round via incoming certificates — and
    commit new transactions."""

    async def go():
        c = committee(base_port=14800)
        params = Parameters(
            header_size=32,
            max_header_delay=100,
            batch_size=400,
            max_batch_delay=100,
        )
        kps = keys()
        commits = {i: [] for i in range(4)}

        async def boot(i, kp):
            primary = await spawn_primary_node(
                kp,
                c,
                params,
                store_path=f"{tmp_path}/primary-{i}/store.log",
                on_commit=lambda cert, i=i: commits[i].append(cert),
            )
            worker = await spawn_worker_node(
                kp, 0, c, params, store_path=f"{tmp_path}/worker-{i}/store.log"
            )
            return [primary, worker]

        nodes = {i: await boot(i, kp) for i, kp in enumerate(kps)}

        from narwhal_tpu.crypto import digest32
        from narwhal_tpu.messages import encode_batch

        host, port = parse_address(c.worker(kps[0].name, 0).transactions)

        async def push(txs):
            _, w = await asyncio.open_connection(host, port)
            for tx in txs:
                await write_frame(w, tx)
            w.close()

        # Combined budget of BOTH waits stays under the run fixture's 60 s
        # wait_for, so failures raise the diagnostic AssertionError (not a
        # bare TimeoutError) and the nodes still shut down.
        async def committed_everywhere(digest, who):
            for _ in range(250):
                if all(
                    digest in {d for cert in commits[i] for d in cert.header.payload}
                    for i in who
                ):
                    return True
                await asyncio.sleep(0.1)
            return False

        txs1 = [bytes([1]) + i.to_bytes(8, "little") + bytes(91) for i in range(4)]
        await push(txs1)
        assert await committed_everywhere(
            digest32(encode_batch(txs1)), range(4)
        ), "first batch never committed"

        # Crash node 3 and restart it from its persisted stores.  The
        # consensus frontier checkpoint must already be on disk — that is
        # what the reboot below restores.  The checkpoint rewrite runs in
        # an executor AFTER the commit is delivered downstream (which is
        # what committed_everywhere observed), so on a starved host the
        # file can trail the commit by a beat — wait for it BEFORE the
        # crash rather than racing the shutdown's task cancellation.
        import os as _os

        ckpt = f"{tmp_path}/primary-3/store.log.consensus.ckpt"
        for _ in range(100):
            if _os.path.exists(ckpt):
                break
            await asyncio.sleep(0.1)
        for node in nodes[3]:
            await node.shutdown()

        assert _os.path.exists(
            ckpt
        ), "consensus checkpoint never written before the crash"
        nodes[3] = await boot(3, kps[3])

        txs2 = [bytes([2]) + i.to_bytes(8, "little") + bytes(91) for i in range(4)]
        await push(txs2)
        # The restarted node must catch up — its consensus frontier is
        # RESTORED from the checkpoint (beyond reference parity: the
        # reference leaves consensus state unpersisted,
        # consensus/src/lib.rs:18-19, and re-delivers history) — and
        # commit the new batch.
        assert await committed_everywhere(
            digest32(encode_batch(txs2)), range(4)
        ), (
            "post-restart batch never committed: "
            f"{[len(commits[i]) for i in range(4)]}"
        )
        # No double delivery across the restart — a regression guard (in
        # this healthy-peer scenario the persisted store already keeps
        # history out of consensus; the checkpoint's dedupe is
        # demonstrated directly against a catch-up replay in
        # test_consensus.py::test_checkpoint_restore_resumes_without_redelivery).
        delivered = [bytes(cert.digest()) for cert in commits[3]]
        assert len(delivered) == len(set(delivered)), (
            "restarted node re-delivered committed certificates"
        )

        for pair in nodes.values():
            for node in pair:
                await node.shutdown()

    run(go())


def test_ten_node_commit(run):
    """N=10 committee (quorum 7): the protocol must drive rounds and commit
    at a committee size where the 4-node fixtures hide nothing — larger
    vote aggregation, wider broadcast fan-out, bigger parent sets
    (BASELINE.json names 10/20/50-node configs; VERDICT r4 flagged that
    nothing ever ran above N=4)."""

    async def go():
        n = 10
        c = committee(base_port=14600, n=n)
        params = Parameters(
            header_size=32,
            max_header_delay=200,
            batch_size=400,
            max_batch_delay=100,
        )
        commits = {i: [] for i in range(n)}
        nodes = []
        for i, kp in enumerate(keys(n)):
            nodes.append(
                await spawn_primary_node(
                    kp,
                    c,
                    params,
                    on_commit=lambda cert, i=i: commits[i].append(cert),
                )
            )
            nodes.append(await spawn_worker_node(kp, 0, c, params))

        host, port = parse_address(c.worker(keys(n)[0].name, 0).transactions)
        _, w = await asyncio.open_connection(host, port)
        txs = [bytes([1]) + i.to_bytes(8, "little") + bytes(91) for i in range(4)]
        for tx in txs:
            await write_frame(w, tx)

        from narwhal_tpu.crypto import digest32
        from narwhal_tpu.messages import encode_batch

        expected = digest32(encode_batch(txs))

        def payload_committed(certs):
            return expected in {
                d for cert in certs for d in cert.header.payload
            }

        # Poll budget < the run fixture's 60 s wait_for, so on failure the
        # diagnostic AssertionError (not a bare TimeoutError) fires and the
        # nodes still shut down.
        for _ in range(400):
            if all(payload_committed(v) for v in commits.values()):
                break
            await asyncio.sleep(0.1)
        else:
            raise AssertionError(
                "payload never committed at N=10: "
                f"{[len(v) for v in commits.values()]}"
            )

        # All ten nodes agree on the commit order.
        seqs = [[cert.digest() for cert in commits[i]] for i in range(n)]
        common = min(len(s) for s in seqs)
        assert common > 0
        for i in range(1, n):
            assert seqs[i][:common] == seqs[0][:common]

        w.close()
        for node in nodes:
            await node.shutdown()

    run(go())


def test_commit_with_crash_fault(run):
    """f=1 crash fault: the last node never boots (the reference's fault
    injection, benchmark/local.py:77); the 3 live nodes (2f+1 stake) must
    still drive rounds and commit client transactions."""

    async def go():
        c = committee(base_port=14400)
        params = Parameters(
            header_size=32,
            max_header_delay=100,
            batch_size=400,
            max_batch_delay=100,
        )
        live = keys()[:3]  # node 3 is crashed from the start
        commits = {i: [] for i in range(3)}
        nodes = []
        for i, kp in enumerate(live):
            nodes.append(
                await spawn_primary_node(
                    kp,
                    c,
                    params,
                    on_commit=lambda cert, i=i: commits[i].append(cert),
                )
            )
            nodes.append(await spawn_worker_node(kp, 0, c, params))

        host, port = parse_address(c.worker(live[0].name, 0).transactions)
        _, w = await asyncio.open_connection(host, port)
        txs = [bytes([1]) + i.to_bytes(8, "little") + bytes(91) for i in range(4)]
        for tx in txs:
            await write_frame(w, tx)

        from narwhal_tpu.crypto import digest32
        from narwhal_tpu.messages import encode_batch

        expected = digest32(encode_batch(txs))

        def payload_committed(certs):
            return expected in {
                d for cert in certs for d in cert.header.payload
            }

        for _ in range(600):
            if all(payload_committed(v) for v in commits.values()):
                break
            await asyncio.sleep(0.1)
        else:
            raise AssertionError(
                "payload never committed under f=1: "
                f"{[len(v) for v in commits.values()]}"
            )

        # The live nodes agree on the commit order.
        seqs = [[cert.digest() for cert in commits[i]] for i in range(3)]
        common = min(len(s) for s in seqs)
        assert common > 0
        for i in range(1, 3):
            assert seqs[i][:common] == seqs[0][:common]

        w.close()
        for node in nodes:
            await node.shutdown()

    run(go())


def test_prewarm_cli(tmp_path, monkeypatch):
    """`node prewarm` compiles the verify kernel (and optionally the
    consensus kernel) for a committee's shapes and exits 0 — the step the
    bench harness runs before spawning TPU-flagged nodes so their boot
    warmup is a cache load (never a multi-minute compile that outlives
    the boot deadline).  Runs on the CPU jax backend here; the shape
    override keeps the compile small."""
    from narwhal_tpu.node.main import main as node_main
    from tests.common import committee

    c = committee(base_port=15200)
    path = str(tmp_path / "committee.json")
    c.export(path)
    monkeypatch.setenv("NARWHAL_TPU_WARMUP_SHAPES", "16")
    from narwhal_tpu.crypto import backend as crypto_backend

    try:
        rc = node_main(
            ["prewarm", "--committee", path, "--experimental-consensus-kernel",
             "--gc-depth", "4"]
        )
    finally:
        # prewarm selects the tpu backend process-globally; put the
        # default back so later tests in this session see cpu.
        crypto_backend.set_backend("cpu")
    assert rc == 0
