"""Differential + adversarial tests for the TPU ed25519 batch verifier.

Ground truth: OpenSSL (via the `cryptography` package) for everything the
kernel ACCEPTS (our semantics are strictly more rejecting: S ≥ L,
non-canonical encodings and small-order points are rejected even where
some libraries accept), plus hand-crafted adversarial encodings for the
rejection paths.  Reference semantics: crypto/src/lib.rs:200-219
(`verify_strict` + dalek batch verification).
"""

import hashlib
import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")
# The differential ground truth is OpenSSL; on hosts without the
# `cryptography` package this suite skips (the kernel still gets coverage
# from the pure-Python RFC 8032 cross-check in test_crypto.py).
pytest.importorskip("cryptography")

from cryptography.hazmat.primitives.asymmetric.ed25519 import (  # noqa: E402
    Ed25519PrivateKey,
    Ed25519PublicKey,
)

from narwhal_tpu.ops import ed25519 as E  # noqa: E402
from narwhal_tpu.ops import field25519 as F  # noqa: E402

rng = random.Random(7)


def keypair():
    sk = Ed25519PrivateKey.generate()
    return sk, sk.public_key().public_bytes_raw()


def openssl_ok(msg, key, sig):
    try:
        Ed25519PublicKey.from_public_bytes(bytes(key)).verify(
            bytes(sig), bytes(msg)
        )
        return True
    except Exception:
        return False


def test_valid_signatures_accepted():
    sk, pk = keypair()
    msgs = [rng.randbytes(32) for _ in range(8)]
    sigs = [sk.sign(m) for m in msgs]
    mask = E.verify_batch_arrays(msgs, [pk] * 8, sigs)
    assert mask.all()


def test_corruptions_rejected_and_never_looser_than_openssl():
    """Random bit flips across message/key/signature: our verdict must be
    False whenever OpenSSL says False, and every acceptance of ours must
    be an OpenSSL acceptance (strictness is one-sided)."""
    sk, pk = keypair()
    cases = []
    for i in range(24):
        m = rng.randbytes(32)
        s = bytearray(sk.sign(m))
        k = bytearray(pk)
        mm = bytearray(m)
        target = rng.choice(("sig", "key", "msg", "none"))
        if target == "sig":
            s[rng.randrange(64)] ^= 1 << rng.randrange(8)
        elif target == "key":
            k[rng.randrange(32)] ^= 1 << rng.randrange(8)
        elif target == "msg":
            mm[rng.randrange(32)] ^= 1 << rng.randrange(8)
        cases.append((bytes(mm), bytes(k), bytes(s)))
    mask = E.verify_batch_arrays(*zip(*cases))
    for (m, k, s), ours in zip(cases, mask):
        ssl = openssl_ok(m, k, s)
        if ours:
            assert ssl, "kernel accepted a signature OpenSSL rejects"
        if not ssl:
            assert not ours


def test_scalar_malleability_rejected():
    """S' = S + L passes naive verifiers that skip the range check; both
    the reference (dalek) and this kernel must reject it."""
    sk, pk = keypair()
    m = rng.randbytes(32)
    sig = sk.sign(m)
    s_int = int.from_bytes(sig[32:], "little")
    forged = sig[:32] + (s_int + E.L_ORDER).to_bytes(32, "little")
    mask = E.verify_batch_arrays([m, m], [pk, pk], [sig, forged])
    assert list(mask) == [True, False]


def test_non_canonical_y_rejected():
    """Public key encoding with y ≥ p must be rejected."""
    sk, pk = keypair()
    m = rng.randbytes(32)
    sig = sk.sign(m)
    y = int.from_bytes(pk, "little") & ((1 << 255) - 1)
    # Craft a key whose y-field is ≥ p (y + p fits in 255 bits only if
    # y < 19; easier: set y-field to p + small).
    bad_y = F.P + 3
    assert bad_y < (1 << 255)
    bad_key = bad_y.to_bytes(32, "little")
    mask = E.verify_batch_arrays([m], [bad_key], [sig])
    assert not mask[0]


def test_small_order_key_rejected():
    """A = identity (small order): accepted by cofactorless math for
    k·A = identity, but verify_strict semantics reject it."""
    sk, pk = keypair()
    m = rng.randbytes(32)
    # identity point encodes as y=1, sign=0
    ident = (1).to_bytes(32, "little")
    # Build a "signature" that would pass cofactorless verification with
    # A = identity: R = [s]B for any s, since [k]A = identity.
    s = 12345
    rx, ry = E._ref_scalarmult(s)
    r_bytes = (ry | ((rx & 1) << 255)).to_bytes(32, "little")
    sig = r_bytes + s.to_bytes(32, "little")
    mask = E.verify_batch_arrays([m], [ident], [sig])
    assert not mask[0]


def test_off_curve_key_rejected():
    """A y with no valid x (x² non-square) must be rejected."""
    # Find a y in [0,p) that is not on the curve.
    d = E.D_INT
    y = 2
    while True:
        u = (y * y - 1) % F.P
        v = (d * y * y + 1) % F.P
        xx = (u * pow(v, F.P - 2, F.P)) % F.P
        if pow(xx, (F.P - 1) // 2, F.P) == F.P - 1:  # non-square
            break
        y += 1
    bad_key = y.to_bytes(32, "little")
    sk, pk = keypair()
    m = rng.randbytes(32)
    sig = sk.sign(m)
    mask = E.verify_batch_arrays([m], [bad_key], [sig])
    assert not mask[0]


def test_wrong_key_rejected():
    sk1, pk1 = keypair()
    sk2, pk2 = keypair()
    m = rng.randbytes(32)
    mask = E.verify_batch_arrays([m], [pk2], [sk1.sign(m)])
    assert not mask[0]


def test_batch_positions_independent():
    """The verdict mask lines up with batch positions across a batch
    mixing valid/invalid entries and spanning a padding boundary."""
    sk, pk = keypair()
    msgs, keys, sigs, want = [], [], [], []
    for i in range(19):  # pads to 32
        m = rng.randbytes(32)
        s = sk.sign(m)
        if i % 3 == 0:
            s = s[:32] + bytes(32)  # S = 0 → [0]B = identity ≠ R
            want.append(False)
        else:
            want.append(True)
        msgs.append(m)
        keys.append(pk)
        sigs.append(s)
    mask = E.verify_batch_arrays(msgs, keys, sigs)
    assert list(mask) == want


def test_point_ops_match_python_reference():
    """Extended-coordinate add/double agree with the affine Python
    reference used to build the base table."""
    import jax.numpy as jnp

    for k1, k2 in [(3, 5), (7, 11), (123456789, 987654321)]:
        x1, y1 = E._ref_scalarmult(k1)
        x2, y2 = E._ref_scalarmult(k2)
        xs, ys = E._ref_scalarmult(k1 + k2)
        xd, yd = E._ref_scalarmult(2 * k1)
        p1 = (
            jnp.asarray(F.to_limbs(x1))[None],
            jnp.asarray(F.to_limbs(y1))[None],
            jnp.asarray(F.to_limbs(1))[None],
            jnp.asarray(F.to_limbs((x1 * y1) % F.P))[None],
        )
        p2 = (
            jnp.asarray(F.to_limbs(x2))[None],
            jnp.asarray(F.to_limbs(y2))[None],
            jnp.asarray(F.to_limbs(1))[None],
            jnp.asarray(F.to_limbs((x2 * y2) % F.P))[None],
        )
        ps = E.point_add(p1, p2)
        pd = E.point_double(p1)
        for point, (ex, ey) in ((ps, (xs, ys)), (pd, (xd, yd))):
            zinv = pow(F.from_limbs(np.asarray(F.canon(point[2]))[0]),
                       F.P - 2, F.P)
            gx = (F.from_limbs(np.asarray(F.canon(point[0]))[0]) * zinv) % F.P
            gy = (F.from_limbs(np.asarray(F.canon(point[1]))[0]) * zinv) % F.P
            assert (gx, gy) == (ex, ey)


def test_tpu_backend_class():
    from narwhal_tpu.crypto import backend as cb

    cb.set_backend("tpu")
    try:
        sk, pk = keypair()
        from narwhal_tpu.crypto.keys import PublicKey, Signature
        from narwhal_tpu.crypto.digest import Digest

        d = Digest(hashlib.sha256(b"payload").digest())
        sig = Signature(sk.sign(bytes(d)))
        assert cb.verify(bytes(d), PublicKey(pk), sig)
        assert cb.verify_batch(d, [PublicKey(pk)], [sig])
        assert not cb.verify_batch(
            d, [PublicKey(pk)], [Signature(bytes(64))]
        )
    finally:
        cb.set_backend("cpu")


def test_tpu_averify_runs_off_event_loop():
    """The async verify seam must run the device round trip on the backend's
    dispatch thread, not the event loop (VERDICT r2: a synchronous device
    call would stall the primary's networking for the device latency)."""
    import asyncio
    import threading

    from narwhal_tpu.ops.ed25519 import TpuBackend
    from narwhal_tpu.crypto.digest import Digest
    from narwhal_tpu.crypto.keys import PublicKey, Signature

    sk, pk = keypair()
    d = Digest(hashlib.sha256(b"offloop").digest())
    sig = Signature(sk.sign(bytes(d)))

    backend = TpuBackend()
    threads = []
    inner = backend.verify_batch_mask

    def recording(msgs, ks, ss):
        threads.append(threading.current_thread().name)
        return inner(msgs, ks, ss)

    backend.verify_batch_mask = recording

    async def go():
        # Loop stays responsive while the verify runs: a ticker task must
        # keep making progress during the await.
        ticks = []

        async def ticker():
            while True:
                ticks.append(1)
                await asyncio.sleep(0.001)

        t = asyncio.ensure_future(ticker())
        mask = await backend.averify_batch_mask(
            [bytes(d)] * 3, [PublicKey(pk)] * 3, [sig, Signature(bytes(64)), sig]
        )
        t.cancel()
        return mask, ticks

    mask, ticks = asyncio.run(go())
    assert mask == [True, False, True]
    assert threads and threads[0].startswith("tpu-verify"), threads
    assert ticks, "event loop starved during device verify"


def test_float32_lane_mode_field_ops():
    """The float32 lane dtype (NARWHAL_FIELD_DTYPE=float32) computes the
    dtype-sensitive pieces — field mul/sub/canon (split carries, split
    ×38 fold, ×k chunking) and the one-hot table select — exactly, in a
    subprocess so the env-selected dtype is picked up at import.  Scoped
    to ops that compile in seconds; the FULL verify kernel under f32
    (several minutes of cold CPU compile) is covered by running
    `NARWHAL_FIELD_DTYPE=float32 pytest tests/test_field25519.py
    tests/test_ed25519.py`."""
    import os
    import subprocess
    import sys

    code = """
import sys
sys.path.insert(0, %r)
# Pin the CPU backend the same way conftest does: a host sitecustomize
# may re-register an accelerator platform over JAX_PLATFORMS, and an
# unhealthy device tunnel would hang the first computation.
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from narwhal_tpu.ops import field25519 as F
assert F.FP and F.DTYPE.__name__ == "float32"
rng = np.random.default_rng(3)
P = F.P
for _ in range(8):
    x = int(rng.integers(0, 1 << 62)) * int(rng.integers(0, 1 << 62)) %% P
    y = (P - 1 - x) %% P
    xl, yl = F.to_limbs(x)[None], F.to_limbs(y)[None]
    assert F.from_limbs(np.asarray(F.mul(xl, yl))[0]) %% P == x * y %% P
    assert F.from_limbs(np.asarray(F.sub(xl, yl))[0]) %% P == (x - y) %% P
    assert F.from_limbs(np.asarray(F.mul_small(xl, 121666))[0]) %% P == (
        x * 121666 %% P)
    assert F.from_limbs(np.asarray(F.canon(xl))[0]) == x
from narwhal_tpu.ops import ed25519 as E
import jax.numpy as jnp
ws = [3, 0, 15]
pt = E._select_from_table(E._B_TABLE, jnp.asarray(ws))
for row, w in enumerate(ws):
    got = [F.from_limbs(np.asarray(c)[row]) for c in pt]
    exp_x, exp_y = E._ref_scalarmult(w)
    assert got[0] == exp_x and got[1] == exp_y and got[2] == 1, (w, got)
print("F32-OK")
""" % os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, NARWHAL_FIELD_DTYPE="float32")
    out = subprocess.run(
        [sys.executable, "-c", code], env=env, capture_output=True,
        text=True, timeout=300,
    )
    assert out.returncode == 0 and "F32-OK" in out.stdout, (
        out.stdout, out.stderr
    )
