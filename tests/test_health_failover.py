"""Tier-1 live-health failover test: a 4-node in-process committee loses
one node mid-run, and the health layer must tell the story in real time —
the paper's headline claim is that throughput SURVIVES faults, so the
observability layer has to (a) keep showing commits and (b) name the dead
peer, within one evaluation interval of its failure gauges crossing the
threshold:

- survivors keep committing client payload after the kill (f=1 of 4);
- each survivor's HealthMonitor raises a ``peer_unreachable`` anomaly
  whose subject is the dead node's primary address, on the FIRST
  evaluation after the condition becomes observable (for_intervals=1);
- ``/healthz`` flips to 503 listing that rule, and back to the anomaly's
  detail is carried in the body.

All four nodes share one process (and therefore one registry): per-peer
instruments are keyed by peer ADDRESS, so the three survivors' senders
converge on the same ``net.reliable.peer.consecutive_failures.<dead>``
gauge — exactly what a per-process monitor reads in a real deployment.
"""

import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from narwhal_tpu import metrics
from narwhal_tpu.config import Parameters
from narwhal_tpu.crypto import digest32
from narwhal_tpu.messages import encode_batch
from narwhal_tpu.metrics import HealthMonitor, MetricsServer, default_rules
from narwhal_tpu.network.framing import parse_address, write_frame
from narwhal_tpu.node import spawn_primary_node, spawn_worker_node
from tests.common import committee, keys


def _tx(i: int) -> bytes:
    return bytes([1]) + (0xBEEF00 + i).to_bytes(8, "little") + bytes(91)


def test_kill_one_node_survivors_flag_it_and_keep_committing():
    reg = metrics.registry()
    reg.reset()
    PEER_FAILURES = 2

    async def go():
        c = committee(base_port=15600)
        params = Parameters(
            header_size=32,
            max_header_delay=100,
            batch_size=400,
            max_batch_delay=100,
        )
        kps = keys()
        commits = {i: [] for i in range(4)}
        primaries, workers = [], []
        for i, kp in enumerate(kps):
            primaries.append(
                await spawn_primary_node(
                    kp,
                    c,
                    params,
                    on_commit=lambda cert, i=i: commits[i].append(cert),
                )
            )
            workers.append(await spawn_worker_node(kp, 0, c, params))

        # One HealthMonitor per survivor, evaluated manually so "within
        # one evaluation interval" is pinned down deterministically.
        monitors = [
            HealthMonitor(
                reg,
                rules=default_rules(
                    {"NARWHAL_HEALTH_PEER_FAILURES": str(PEER_FAILURES)}
                ),
                interval_s=0.5,
            )
            for _ in range(3)
        ]
        reg.health = monitors[0]
        server = await MetricsServer.spawn(reg, 0, host="127.0.0.1")

        async def send_txs(ids):
            host, port = parse_address(c.worker(kps[0].name, 0).transactions)
            _, w = await asyncio.open_connection(host, port)
            txs = [_tx(i) for i in ids]
            for tx in txs:
                await write_frame(w, tx)
            w.close()
            return txs

        def committed_digests(node_idx):
            return {
                d
                for cert in commits[node_idx]
                for d in cert.header.payload
            }

        async def wait_commit(expected, nodes_idx, timeout_s=60):
            for _ in range(int(timeout_s / 0.1)):
                if all(
                    expected <= committed_digests(i) for i in nodes_idx
                ):
                    return
                await asyncio.sleep(0.1)
            raise AssertionError(
                f"payload never committed on {nodes_idx}: "
                f"{[len(commits[i]) for i in nodes_idx]}"
            )

        # Healthy phase: all four nodes commit the first batch, and no
        # monitor sees anything wrong.
        txs = await send_txs(range(4))
        batch1 = {bytes(digest32(encode_batch(txs))).hex()}
        batch1_raw = {digest32(encode_batch(txs))}
        await wait_commit(batch1_raw, range(4))
        for mon in monitors:
            assert mon.evaluate() == [], "anomaly on a healthy committee"

        # GET /healthz while healthy: 200.
        ok = await _http_get(server.port, "/healthz")
        assert b"200 OK" in ok

        # Kill authority 3 (primary + worker): its listeners close, so
        # every survivor's reliable sender starts failing reconnects to
        # its addresses.
        dead_primary_addr = c.primary(kps[3].name).primary_to_primary
        await primaries[3].shutdown()
        await workers[3].shutdown()
        t_kill = time.monotonic()

        # Wait until the failure condition is OBSERVABLE (the shared
        # per-peer gauge crosses the threshold), then a single
        # evaluation — one interval — must raise the anomaly.
        gauge_name = (
            f"net.reliable.peer.consecutive_failures.{dead_primary_addr}"
        )
        for _ in range(400):
            g = reg.gauges.get(gauge_name)
            if g is not None and g.value >= PEER_FAILURES:
                break
            await asyncio.sleep(0.05)
        else:
            raise AssertionError(
                f"consecutive-failure gauge for {dead_primary_addr} "
                "never crossed the threshold"
            )
        detect_lag = time.monotonic() - t_kill

        for mon in monitors:
            firing = mon.evaluate()
            subjects = {
                f["subject"] for f in firing if f["rule"] == "peer_unreachable"
            }
            assert dead_primary_addr in subjects, (
                f"survivor monitor did not name the dead peer in one "
                f"evaluation: firing={firing}"
            )

        # /healthz flips to 503 and lists the rule + dead peer.
        bad = await _http_get(server.port, "/healthz")
        assert b"503" in bad
        body = json.loads(bad.split(b"\r\n\r\n", 1)[1])
        assert body["status"] == "failing"
        assert any(
            f["rule"] == "peer_unreachable"
            and f["subject"] == dead_primary_addr
            for f in body["firing"]
        )

        # Survivors keep committing NEW payload after the kill (f=1).
        txs2 = await send_txs(range(100, 104))
        batch2_raw = {digest32(encode_batch(txs2))}
        await wait_commit(batch2_raw, range(3))

        await server.shutdown()
        for node in primaries[:3] + workers[:3]:
            await node.shutdown()
        return detect_lag, batch1

    detect_lag, _ = asyncio.run(asyncio.wait_for(go(), 120))
    # The gauge crossing itself must be prompt (reconnect backoff starts
    # at 200 ms): generous bound for loaded CI hosts, but catches a
    # detection path that silently degraded to tens of seconds.
    assert detect_lag < 30, f"failure detection took {detect_lag:.1f}s"


async def _http_get(port, target):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(f"GET {target} HTTP/1.1\r\nHost: x\r\n\r\n".encode())
    await writer.drain()
    data = await reader.read()
    writer.close()
    return data
