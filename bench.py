#!/usr/bin/env python3
"""Driver benchmark: one JSON line {"metric", "value", "unit", "vs_baseline"}.

Runs the reference's `fab local` analog — a full 4-node committee with one
worker each plus open-loop clients on localhost (benchmark/local_bench.py) —
and reports end-to-end committed TPS against the reference's local baseline
(46,149 tx/s e2e, README.md:42-58, mirrored in BASELINE.md).

Environment knobs: BENCH_DURATION (s, default 25), BENCH_RATE (starting
probe rate, default 90000), BENCH_NODES (default 4), BENCH_BATCH (bytes,
default 500000), BENCH_LATENCY_CAP_MS (sustained-point gate, default 1500),
BENCH_MAX_PROBES (default 4).

Saturation is PROBED PER RUN, not replayed from a previous round's
measurement: this host's capacity swings ±30% between hours (BASELINE.md
variance caveat), and offering a fixed rate measured in a fast window
floods the queues of a slow one — round 5 measured 32.6k tx/s at 3,037 ms
e2e latency exactly that way (VERDICT.md §1).  The probe steps the offered
rate DOWN from BENCH_RATE (factor 0.7) until a run commits with e2e latency
under the cap — i.e. the committee is saturated but not drowning — then
re-runs the chosen rate for the median.  Every probe run is listed in the
JSON.  Batch size stays at the reference's 500 kB — the earlier 125 kB
"tuned" default quartered throughput by quadrupling per-batch overheads
(broadcast frames, ACK round trips, digests, store records).
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

# The reference's local-bench e2e TPS (4 nodes, 1 worker, 512 B tx).
BASELINE_E2E_TPS = 46_149.0


def main() -> None:
    from benchmark.local_bench import run_bench

    duration = int(os.environ.get("BENCH_DURATION", "25"))
    start_rate = int(os.environ.get("BENCH_RATE", "90000"))
    nodes = int(os.environ.get("BENCH_NODES", "4"))
    batch = int(os.environ.get("BENCH_BATCH", "500000"))
    runs = int(os.environ.get("BENCH_RUNS", "3"))
    lat_cap = float(os.environ.get("BENCH_LATENCY_CAP_MS", "1500"))
    max_probes = int(os.environ.get("BENCH_MAX_PROBES", "4"))

    def one_run(rate):
        return run_bench(
            nodes=nodes,
            workers=1,
            rate=rate,
            tx_size=512,
            duration=duration,
            base_port=7100,
            batch_size=batch,
            quiet=True,
        )

    def sustained(r):
        # Saturated-but-not-drowning: commits flow and the e2e latency is
        # bounded (an open-loop client over capacity inflates latency
        # without bound — the round-5 3 s failure mode).
        return r.end_to_end_tps > 0 and r.end_to_end_latency_ms <= lat_cap

    # Step the offered rate down from the optimistic start until one run
    # sustains; a slow host window then reports its true sustained point
    # instead of a queue-flooded one.
    probes = []  # (rate, result)
    rate = start_rate
    for _ in range(max(1, max_probes)):
        r = one_run(rate)
        probes.append((rate, r))
        if sustained(r):
            break
        rate = max(int(rate * 0.7), 5_000)

    # Chosen rate: the first sustained probe, else the best-TPS probe
    # (reported as-is — the artifact shows its over-cap latency).
    chosen_rate = next(
        (rt for rt, r in probes if sustained(r)),
        max(probes, key=lambda p: p[1].end_to_end_tps)[0],
    )
    # Re-run the chosen rate up to BENCH_RUNS total and report the MEDIAN
    # run (robust against one lucky or one degraded run; unlike max-of-N
    # it does not inflate with more runs), listing every run in the JSON.
    results = [r for rt, r in probes if rt == chosen_rate]
    while len(results) < max(1, runs):
        results.append(one_run(chosen_rate))
    ranked = sorted(results, key=lambda r: r.end_to_end_tps)
    result = ranked[len(ranked) // 2]

    # North-star microbenchmark (BASELINE.json): ed25519 verifies/sec/chip
    # on the real device, captured in the same driver artifact.  Runs in a
    # subprocess so the bench processes' environment stays untouched;
    # non-fatal (the e2e number above is reported either way).
    crypto: dict = {}
    if os.environ.get("BENCH_CRYPTO", "1") == "1":
        import subprocess

        # Cheap device probe first: a wedged tunnel (e.g. a chip grant lost
        # to a killed client) makes jax.devices() hang, and the crypto
        # microbench would eat its whole 540 s timeout discovering that.
        # NEVER SIGKILL the probe (subprocess.run's timeout would): killing
        # a child mid-chip-claim is itself what wedges the grant.  SIGTERM
        # and, if it still won't die, leave it to finish claiming and exit
        # on its own — crypto is skipped either way.
        probe = subprocess.Popen(
            [sys.executable, "-c", "import jax; jax.devices()"],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        try:
            device_ok = probe.wait(timeout=90) == 0
        except subprocess.TimeoutExpired:
            device_ok = False
            probe.terminate()
            try:
                probe.wait(timeout=30)
            except subprocess.TimeoutExpired:
                pass
        if not device_ok:
            print(
                "WARNING: TPU device probe failed/hung; skipping crypto "
                "microbench",
                file=sys.stderr,
            )
    else:
        device_ok = False
    if device_ok:
        try:
            out = subprocess.run(
                [
                    sys.executable,
                    os.path.join(REPO, "bench_crypto.py"),
                    "--batches",
                    "16384",
                    "--iters",
                    "3",
                    "--cpu-budget",
                    "0.5",
                ],
                capture_output=True,
                text=True,
                timeout=540,
            )
            last = [
                ln
                for ln in out.stdout.splitlines()
                if ln.startswith("{") and "ed25519" in ln
            ]
            if last:
                cr = json.loads(last[-1])
                crypto = {
                    "ed25519_verifies_per_sec_chip": cr["value"],
                    "ed25519_vs_cpu_core": cr["vs_baseline"],
                }
        except Exception:
            pass
    if result.end_to_end_tps > 0:
        metric, tps, baseline = (
            "end_to_end_tps_local_4n",
            result.end_to_end_tps,
            BASELINE_E2E_TPS,
        )
    else:
        # No sample join succeeded: report the consensus metric honestly
        # against the reference's consensus baseline (46,478 tx/s).
        metric, tps, baseline = (
            "consensus_tps_local_4n",
            result.consensus_tps,
            46_478.0,
        )
    # Errors are part of the artifact: a bench that publishes 0.0 with a
    # clean rc is worse than one that fails loudly (rounds 3-4 did exactly
    # that).  Zero committed transactions = failed measurement = rc 1.
    errors = [e for r in results for e in r.errors]
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(tps, 1),
                "unit": "tx/s",
                "vs_baseline": round(tps / baseline, 4),
                "offered_rate": chosen_rate,
                "probe_history": [
                    {
                        "rate": rt,
                        "e2e_tps": round(r.end_to_end_tps, 1),
                        "e2e_latency_ms": round(r.end_to_end_latency_ms, 1),
                        "sustained": sustained(r),
                    }
                    for rt, r in probes
                ],
                "runs_e2e_tps": [round(r.end_to_end_tps, 1) for r in results],
                "consensus_latency_ms": round(result.consensus_latency_ms, 1),
                "end_to_end_latency_ms": round(result.end_to_end_latency_ms, 1),
                # From the node metrics snapshots (narwhal_tpu/metrics.py):
                # where the pipeline latency actually accrues, and the
                # metrics-vs-log committed-tx cross-check of the median run.
                "stages_ms": result.stages_ms,
                "metrics_committed_tx": round(result.metrics_committed_tx, 1),
                "metrics_disagreement": result.metrics_disagreement,
                # Support-quorum spread headline (gated in
                # benchmark/trajectory.py like cert_to_commit_ms) plus
                # the slowest causal chain and who-closed-the-quorum
                # table of the median run.
                "support_arrival_ms": (
                    result.stragglers.get("gaps", {})
                    .get("support_arrival_ms", {})
                    .get("mean")
                ),
                "critical_path": result.critical_path,
                "stragglers": result.stragglers,
                # Wire-goodput & crypto-cost headline (median run): the
                # cross-revision numbers benchmark/trajectory.py tracks.
                "goodput_ratio": result.wire.get("goodput_ratio"),
                "cert_sig_bytes_fraction": result.wire.get(
                    "cert_sig_bytes_fraction"
                ),
                "empty_cert_overhead_per_committed_byte": result.wire.get(
                    "empty_cert_overhead_per_committed_byte"
                ),
                "wire_totals": result.wire.get("totals", {}),
                "crypto_verify": {
                    site: d.get("ops")
                    for site, d in result.crypto.get("verify", {}).items()
                },
                **({"errors": errors[:10]} if errors else {}),
                **crypto,
            }
        )
    )
    if result.committed_batches == 0 or tps <= 0:
        print(
            "BENCH FAILED: no committed transactions measured; "
            f"errors={errors[:10]}",
            file=sys.stderr,
        )
        sys.exit(1)


if __name__ == "__main__":
    main()
