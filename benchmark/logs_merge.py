"""Merge per-node ``--log-json`` streams into one committee-wide JSONL.

Every node run with ``--log-json`` emits one-line-JSON records
({ts, level, logger, msg, node}, ts = unix epoch seconds — see
node/main.py JsonLogFormatter), but each process writes its own file and
nothing joined them: reconstructing "what did the committee do at t?"
meant eyeballing 8+ files side by side (the ROADMAP observability
follow-up).  This tool is the join: a k-way heap merge by timestamp into
a single time-sorted JSONL stream, one record per line, each tagged with
its node id.

    python benchmark/logs_merge.py .bench/primary-*.log -o committee.jsonl
    python benchmark/logs_merge.py .bench/*.log | jq 'select(.level=="WARNING")'

Robustness rules (a merged stream that silently drops lines is worse
than none):

- A record missing ``node`` inherits the source file's stem, so plain
  ``--log-json`` output that predates the node tag still merges.
- A non-JSON line (tracebacks from the logging machinery itself, stray
  prints) is wrapped as ``{"ts": <last seen ts in that file>, "level":
  "RAW", "msg": <line>, "node": <stem>}`` and sorts at its neighbor's
  position instead of being dropped.
- A record missing ``ts`` sorts with the file's last seen timestamp
  (0.0 at file start), keeping it adjacent to its context.

The merge is streaming (heapq.merge over lazy per-file iterators): a
committee-day of logs never loads into memory at once.
"""

from __future__ import annotations

import argparse
import heapq
import json
import os
import sys
from typing import Iterable, Iterator, List, TextIO, Tuple


def _records(path: str, text: Iterable[str]) -> Iterator[Tuple[float, dict]]:
    """(ts, record) per line of one node's stream."""
    stem = os.path.splitext(os.path.basename(path))[0]
    last_ts = 0.0
    for line in text:
        line = line.rstrip("\n")
        if not line:
            continue
        try:
            rec = json.loads(line)
            if not isinstance(rec, dict):
                raise ValueError("not an object")
        except ValueError:
            rec = {"ts": last_ts, "level": "RAW", "msg": line}
        ts = rec.get("ts")
        if isinstance(ts, (int, float)):
            last_ts = float(ts)
        else:
            rec["ts"] = last_ts
        rec.setdefault("node", stem)
        yield (rec["ts"], rec)


def merge_streams(
    named_texts: List[Tuple[str, Iterable[str]]], out: TextIO
) -> int:
    """K-way timestamp merge; returns the number of records written.
    ``named_texts`` is [(source name, line iterable), …] — file handles,
    lists of lines in tests, anything iterable.  heapq.merge with a key
    is stable, so same-timestamp records keep within-file order and the
    record dicts themselves are never compared."""
    streams = [_records(name, text) for name, text in named_texts]
    n = 0
    for _, rec in heapq.merge(*streams, key=lambda t: t[0]):
        out.write(json.dumps(rec) + "\n")
        n += 1
    return n


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Merge per-node --log-json files into one time-sorted "
        "committee-wide JSONL stream (node tag per line)."
    )
    parser.add_argument("logs", nargs="+", help="per-node JSONL log files")
    parser.add_argument(
        "-o",
        "--output",
        default=None,
        help="output path (default: stdout)",
    )
    args = parser.parse_args(argv)

    handles = [open(p) for p in args.logs]
    try:
        if args.output:
            with open(args.output, "w") as out:
                n = merge_streams(list(zip(args.logs, handles)), out)
            print(
                f"merged {n} records from {len(args.logs)} node(s) "
                f"into {args.output}",
                file=sys.stderr,
            )
        else:
            merge_streams(list(zip(args.logs, handles)), sys.stdout)
    finally:
        for h in handles:
            h.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
