"""Merge per-node ``--log-json`` streams into one committee-wide JSONL.

Every node run with ``--log-json`` emits one-line-JSON records
({ts, level, logger, msg, node}, ts = unix epoch seconds — see
node/main.py JsonLogFormatter), but each process writes its own file and
nothing joined them: reconstructing "what did the committee do at t?"
meant eyeballing 8+ files side by side (the ROADMAP observability
follow-up).  This tool is the join: a k-way heap merge by timestamp into
a single time-sorted JSONL stream, one record per line, each tagged with
its node id.

    python benchmark/logs_merge.py .bench/primary-*.log -o committee.jsonl
    python benchmark/logs_merge.py .bench/*.log | jq 'select(.level=="WARNING")'

Robustness rules (a merged stream that silently drops lines is worse
than none):

- A record missing ``node`` inherits the source file's stem, so plain
  ``--log-json`` output that predates the node tag still merges.
- A non-JSON line (tracebacks from the logging machinery itself, stray
  prints) is wrapped as ``{"ts": <last seen ts in that file>, "level":
  "RAW", "msg": <line>, "node": <stem>}`` and sorts at its neighbor's
  position instead of being dropped.
- A record missing ``ts`` sorts with the file's last seen timestamp
  (0.0 at file start), keeping it adjacent to its context.

The merge is streaming (heapq.merge over lazy per-file iterators): a
committee-day of logs never loads into memory at once.
"""

from __future__ import annotations

import argparse
import heapq
import json
import os
import sys
from typing import Iterable, Iterator, List, Optional, TextIO, Tuple


def _records(
    path: str, text: Iterable[str]
) -> Iterator[Tuple[float, dict, str]]:
    """(ts, record, source stem) per line of one node's stream.  The
    stem rides alongside (never in the output record): it is the name
    the bench workdir uses for the node ('primary-0'), which is how
    --trace maps records onto trace rows when the in-record node id is
    the runtime form ('primary-<keyprefix>')."""
    stem = os.path.splitext(os.path.basename(path))[0]
    last_ts = 0.0
    for line in text:
        line = line.rstrip("\n")
        if not line:
            continue
        try:
            rec = json.loads(line)
            if not isinstance(rec, dict):
                raise ValueError("not an object")
        except ValueError:
            rec = {"ts": last_ts, "level": "RAW", "msg": line}
        ts = rec.get("ts")
        if isinstance(ts, (int, float)):
            last_ts = float(ts)
        else:
            rec["ts"] = last_ts
        rec.setdefault("node", stem)
        yield (rec["ts"], rec, stem)


def merge_streams(
    named_texts: List[Tuple[str, Iterable[str]]],
    out: Optional[TextIO],
    on_record=None,
) -> int:
    """K-way timestamp merge; returns the number of records written.
    ``named_texts`` is [(source name, line iterable), …] — file handles,
    lists of lines in tests, anything iterable.  heapq.merge with a key
    is stable, so same-timestamp records keep within-file order and the
    record dicts themselves are never compared.  ``out=None`` skips the
    JSONL output (trace-annotation-only runs); ``on_record`` sees every
    merged record (the ``--trace`` hook)."""
    streams = [_records(name, text) for name, text in named_texts]
    n = 0
    for _, rec, stem in heapq.merge(*streams, key=lambda t: t[0]):
        if out is not None:
            out.write(json.dumps(rec) + "\n")
        if on_record is not None:
            on_record(rec, stem)
        n += 1
    return n


# Beyond this many log instants, the injected lines are level-filtered
# then evenly sampled — a DEBUG-level committee day would otherwise bury
# the trace UI; `logs_dropped` in the trace metadata records the cut.
MAX_LOG_EVENTS = 20_000


def inject_into_trace(
    trace_path: str,
    records: List[Tuple[dict, str]],
    max_events: int = MAX_LOG_EVENTS,
) -> Tuple[int, int]:
    """Interleave merged log records into an exported Chrome trace
    (benchmark/trace_export.py) as instant events on each node's row —
    log context and stage spans on ONE timeline.  ``records`` is
    ``[(record, source stem), …]``: a record maps onto a trace row by
    its in-record node id when that matches directly, else by its
    source FILE stem — bench workdirs name both the log file and the
    metrics snapshot (hence the trace row) 'primary-0', while the
    --log-json records themselves carry the runtime id
    'primary-<keyprefix>', which no trace knows.  Records matching
    neither way (e.g. client logs) are dropped with a count.  Returns
    (injected, dropped).  The trace is rewritten atomically."""
    with open(trace_path) as f:
        trace = json.load(f)
    meta = trace.get("metadata") or {}
    pids = meta.get("node_pids") or {}
    t0 = meta.get("epoch_t0") or 0.0
    if not pids:
        raise SystemExit(
            f"{trace_path} carries no metadata.node_pids — was it "
            "exported by benchmark/trace_export.py?"
        )

    candidates = []
    dropped = 0
    for rec, stem in records:
        pid = pids.get(str(rec.get("node", ""))) or pids.get(stem)
        ts = rec.get("ts")
        if pid is None or not isinstance(ts, (int, float)):
            dropped += 1
            continue
        candidates.append((pid, ts, rec))
    if len(candidates) > max_events:
        keep = [
            c for c in candidates
            if c[2].get("level") not in ("DEBUG", "RAW")
        ]
        if len(keep) > max_events:
            step = len(keep) / max_events
            keep = [keep[int(i * step)] for i in range(max_events)]
        dropped += len(candidates) - len(keep)
        candidates = keep
    for pid, ts, rec in candidates:
        trace["traceEvents"].append({
            "ph": "i", "pid": pid, "tid": 3, "s": "t",  # TID_EVENTS row
            "name": f"log:{rec.get('level', '?')}",
            "cat": "log",
            "ts": int(round((ts - t0) * 1e6)),
            "args": {
                "logger": rec.get("logger"),
                "msg": str(rec.get("msg", ""))[:2000],
            },
        })
    meta["logs_injected"] = meta.get("logs_injected", 0) + len(candidates)
    meta["logs_dropped"] = meta.get("logs_dropped", 0) + dropped
    trace["metadata"] = meta
    tmp = trace_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(trace, f)
    os.replace(tmp, trace_path)
    return len(candidates), dropped


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Merge per-node --log-json files into one time-sorted "
        "committee-wide JSONL stream (node tag per line)."
    )
    parser.add_argument("logs", nargs="+", help="per-node JSONL log files")
    parser.add_argument(
        "-o",
        "--output",
        default=None,
        help="output path (default: stdout; with --trace and no -o, the "
        "JSONL output is skipped and only the trace is annotated)",
    )
    parser.add_argument(
        "--trace",
        default=None,
        help="ALSO interleave the merged records into this exported "
        "Chrome trace (benchmark/trace_export.py output) as instant "
        "events on each node's row, so log context and stage spans "
        "live on one timeline (rewritten atomically)",
    )
    args = parser.parse_args(argv)

    collected: List[Tuple[dict, str]] = []
    handles = [open(p) for p in args.logs]
    try:
        on_record = (
            (lambda rec, stem: collected.append((rec, stem)))
            if args.trace
            else None
        )
        if args.output:
            with open(args.output, "w") as out:
                n = merge_streams(
                    list(zip(args.logs, handles)), out, on_record
                )
            print(
                f"merged {n} records from {len(args.logs)} node(s) "
                f"into {args.output}",
                file=sys.stderr,
            )
        elif args.trace:
            merge_streams(list(zip(args.logs, handles)), None, on_record)
        else:
            merge_streams(list(zip(args.logs, handles)), sys.stdout)
    finally:
        for h in handles:
            h.close()
    if args.trace:
        injected, dropped = inject_into_trace(args.trace, collected)
        print(
            f"injected {injected} log instant(s) into {args.trace}"
            + (f" ({dropped} dropped: unknown node / past cap)"
               if dropped else ""),
            file=sys.stderr,
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
