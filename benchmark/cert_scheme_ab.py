"""Paired interleaved cert-sig-scheme A/B: individual vs halfagg at N=4.

The socketed leg of the ISSUE 20 measurement ladder (the sim pricing
at N=10/20/50 lives in benchmark/cert_scheme_gate.py): real processes,
real ed25519 (pure-Python on this host), real sockets, arms
interleaved (individual, halfagg, individual, ...) so slow host drift
hits both equally — the r09/r10 A/B convention.

Ledger-read gates:

* zero run errors and ``protocol_check`` within 5% on BOTH arms — the
  claims arithmetic is scheme-aware (2 claims/cert under halfagg vs
  quorum+1), so a drifting ratio means the assembly or the summary
  lies about the scheme;
* the halfagg arm's ``cert_sig_bytes_per_cert`` must match the scheme
  formula exactly (wire anatomy is deterministic) and shrink vs the
  individual arm;
* halfagg median committed TPS no worse than ``--tps-tolerance``
  below individual (N=4/q=3 is the WORST case for halfagg — one
  multiexp vs only 3 serial verifies — so this is a no-regression
  floor, not a win claim; the win is the wire bytes and the N>=20
  verify collapse priced by the sim captures).

Artifact shape follows wire_ab.py: ``runs`` carries the halfagg arm,
``individual_runs`` the baseline.

    python benchmark/cert_scheme_ab.py --pairs 2 --duration 8 \
        --artifact artifacts/cert_scheme_ab_r24.json
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmark.local_bench import run_bench  # noqa: E402
from narwhal_tpu.crypto.aggregate import cert_sig_wire_bytes  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _one_run(arm: str, idx: int, args) -> dict:
    result = run_bench(
        nodes=args.nodes,
        workers=1,
        rate=args.rate,
        tx_size=args.tx_size,
        duration=args.duration,
        base_port=args.base_port,
        workdir=os.path.join(REPO, ".bench_cert_scheme_ab"),
        quiet=True,
        progress_wait=args.progress_wait,
        cert_sig_scheme=arm,
    )
    wire = result.wire or {}
    return {
        "arm": arm,
        "run": idx,
        "errors": result.errors,
        "consensus_tps": result.consensus_tps,
        "consensus_latency_ms": result.consensus_latency_ms,
        "end_to_end_tps": result.end_to_end_tps,
        "end_to_end_latency_ms": result.end_to_end_latency_ms,
        "wire": wire,
        "round_stages_ms": result.round_stages_ms,
        "crypto": {
            "protocol_check": (result.crypto or {}).get("protocol_check"),
            "verify": (result.crypto or {}).get("verify"),
        },
    }


def _median(runs, key, default=0.0):
    vals = [r.get(key) or 0.0 for r in runs]
    return statistics.median(vals) if vals else default


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--pairs", type=int, default=2)
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--rate", type=int, default=2_000)
    ap.add_argument("--tx-size", type=int, default=512)
    ap.add_argument("--duration", type=int, default=8)
    ap.add_argument("--base-port", type=int, default=7900)
    ap.add_argument("--progress-wait", type=float, default=30.0)
    ap.add_argument(
        "--tps-tolerance", type=float, default=0.25,
        help="halfagg median committed TPS may be at most this fraction "
        "below the individual arm's (shared-core noise floor)",
    )
    ap.add_argument(
        "--artifact", default="artifacts/cert_scheme_ab_r24.json"
    )
    args = ap.parse_args(argv)

    runs_hag, runs_ind = [], []
    for i in range(args.pairs):
        for arm, into in (("individual", runs_ind), ("halfagg", runs_hag)):
            print(
                f"== cert-scheme A/B pair {i + 1}/{args.pairs}: "
                f"{arm} arm =="
            )
            r = _one_run(arm, i, args)
            into.append(r)
            print(
                f"   committed TPS {r['consensus_tps']:,.0f}, "
                f"scheme {r['wire'].get('cert_sig_scheme')}, cert sig "
                f"B/cert {r['wire'].get('cert_sig_bytes_per_cert')}"
            )

    failures = []
    quorum = 2 * args.nodes // 3 + 1
    for r in runs_hag + runs_ind:
        if r["errors"]:
            failures.append(f"{r['arm']} run {r['run']}: {r['errors'][:3]}")
        scheme = r["wire"].get("cert_sig_scheme")
        if scheme != r["arm"]:
            failures.append(
                f"{r['arm']} run {r['run']}: ledger says scheme {scheme}"
            )
        wv = r["wire"].get("format_version") or 1
        want = cert_sig_wire_bytes(r["arm"], quorum, wv)
        got = r["wire"].get("cert_sig_bytes_per_cert")
        if got != want:
            failures.append(
                f"{r['arm']} run {r['run']}: cert_sig_bytes_per_cert "
                f"{got} != formula {want} (q={quorum}, wire v{wv})"
            )
        check = (r["crypto"] or {}).get("protocol_check") or {}
        for kind in ("votes", "certificates"):
            ratio = (check.get(kind) or {}).get("ratio")
            if ratio is None or abs(ratio - 1.0) > 0.05:
                failures.append(
                    f"{r['arm']} run {r['run']}: protocol_check.{kind} "
                    f"ratio {ratio}"
                )

    tps_ind = _median(runs_ind, "consensus_tps")
    tps_hag = _median(runs_hag, "consensus_tps")
    if tps_ind and tps_hag < tps_ind * (1 - args.tps_tolerance):
        failures.append(
            f"halfagg median committed TPS {tps_hag:,.0f} more than "
            f"{args.tps_tolerance:.0%} below individual {tps_ind:,.0f}"
        )

    sig_ind = _median([r["wire"] for r in runs_ind], "cert_sig_bytes_per_cert")
    sig_hag = _median([r["wire"] for r in runs_hag], "cert_sig_bytes_per_cert")
    if sig_ind and sig_hag >= sig_ind:
        failures.append(
            f"halfagg cert sig bytes {sig_hag} not below individual "
            f"{sig_ind}"
        )

    def _agg_site(runs):
        mids = [
            ((r["crypto"] or {}).get("verify") or {}).get("certificate_agg")
            for r in runs
        ]
        return [m for m in mids if m]

    agg_sites = _agg_site(runs_hag)
    ops_per_cert = None
    if agg_sites:
        tot_ops = sum(s.get("ops", 0) for s in agg_sites)
        tot_calls = sum(s.get("calls", 0) for s in agg_sites)
        ops_per_cert = round(tot_ops / tot_calls, 4) if tot_calls else None
    if ops_per_cert != 1.0:
        failures.append(
            f"halfagg verify ops per certificate_agg call = "
            f"{ops_per_cert}, expected exactly 1"
        )
    if _agg_site(runs_ind):
        failures.append(
            "individual arm recorded certificate_agg ops (scheme leak)"
        )

    summary = {
        "consensus_tps": {"individual": tps_ind, "halfagg": tps_hag},
        "cert_sig_bytes_per_cert": {
            "individual": sig_ind, "halfagg": sig_hag,
        },
        "cert_sig_bytes_fraction": {
            "individual": _median(
                [r["wire"] for r in runs_ind], "cert_sig_bytes_fraction"
            ),
            "halfagg": _median(
                [r["wire"] for r in runs_hag], "cert_sig_bytes_fraction"
            ),
        },
        "halfagg_verify_ops_per_cert": ops_per_cert,
        "consensus_latency_ms": {
            "individual": _median(runs_ind, "consensus_latency_ms"),
            "halfagg": _median(runs_hag, "consensus_latency_ms"),
        },
        "gates_failed": failures,
    }

    artifact = {
        "what": (
            "Paired interleaved cert-sig-scheme A/B (ISSUE 20): "
            "individual vs halfagg on a "
            f"{args.nodes}-node local_bench, rate {args.rate}, "
            f"{args.tx_size} B tx, {args.duration} s windows, real "
            "ed25519 (pure-Python signer on this host).  N=4/q=3 is "
            "halfagg's WORST case (one multiexp vs 3 serial verifies), "
            "so the TPS gate is a no-regression floor; the wire and "
            "verify-collapse wins are priced at N=10/20/50 by "
            "artifacts/cert_scheme_price_n*_r24.json.  `runs` is the "
            "halfagg arm; the individual arm is `individual_runs` "
            "(key ignored by the trajectory loader on purpose — the "
            "halfagg arm is not the default scheme and must not set "
            "the TPS series)."
        ),
        "runs_excluded_from_trajectory": runs_hag,
        "individual_runs": runs_ind,
        "summary": summary,
    }
    os.makedirs(os.path.dirname(args.artifact) or ".", exist_ok=True)
    with open(args.artifact, "w") as f:
        json.dump(artifact, f, indent=1)

    print("== cert-scheme A/B summary ==")
    print(json.dumps(summary, indent=1))
    if failures:
        print(f"cert-scheme A/B FAILED: {failures}", file=sys.stderr)
        return 1
    print(
        f"cert-scheme A/B ok: cert sig bytes {sig_ind:.0f} -> "
        f"{sig_hag:.0f} per cert at committed TPS {tps_ind:,.0f} -> "
        f"{tps_hag:,.0f}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
