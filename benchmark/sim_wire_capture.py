"""Committee-at-scale wire-ledger capture via the deterministic sim
(ISSUE 14 satellite; the before-number ROADMAP item 4 needs).

Runs one CLEAN simulated committee at ``--nodes`` (default 20) on the
virtual clock, then reads the shared metrics registry's wire/crypto
ledgers through the same ``wire_crypto_summary`` join the socketed
benches use.  The aggregate-signature item prices itself off
``cert_sig_bytes_fraction`` and cert bytes/frame — today only the N=4
numbers exist (0.59 legacy r12 / the v2-raw figure from r18); this
captures the large-committee point where a certificate carries
2f+1 = 14 votes and the signature fraction dominates the frame.

Fidelity caveats, recorded in the artifact: the sim signs with the
sim-MAC (64-byte signatures — same wire size as ed25519, so frame
anatomy is exact) and its in-memory transport carries the v2 COMPACT
BODY encodings but not the per-connection dictionary/deflate stages
(those live in the socketed senders), so byte counts are raw-frame
figures — exactly what ``cert_sig_bytes_fraction`` is defined over.

    python benchmark/sim_wire_capture.py --nodes 20 \
        --artifact artifacts/wire_n20_r19.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from narwhal_tpu import metrics  # noqa: E402
from narwhal_tpu.faults.spec import parse_scenario  # noqa: E402
from narwhal_tpu.sim.committee import run_sim_scenario  # noqa: E402
from benchmark.metrics_check import wire_crypto_summary  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def capture(nodes: int, duration: int, rate: int, seed: int,
            workdir: str, cert_sig_scheme: str | None = None,
            commit_rule: str | None = None) -> dict:
    obj = {
        "name": f"wire_capture_n{nodes}"
        + (f"_{cert_sig_scheme}" if cert_sig_scheme else ""),
        "nodes": nodes,
        "workers": 1,
        "rate": rate,
        "tx_size": 512,
        "duration": duration,
        "seed": seed,
    }
    if cert_sig_scheme is not None:
        # The sim committee scopes NARWHAL_CERT_SIG_SCHEME from the
        # scenario env to the run (saved/restored like the sim-MAC
        # bracket), so paired arms can share one process.
        obj["env"] = {"NARWHAL_CERT_SIG_SCHEME": cert_sig_scheme}
    scenario = parse_scenario(obj, env={})
    from narwhal_tpu.crypto.aggregate import (
        resolve_scheme,
        scheme_override,
        set_scheme,
    )

    # The registry snapshot (and its crypto.cert_sig_scheme gauge_fn)
    # is taken AFTER the sim's run bracket restores the process scheme,
    # so hold the arm's scheme across run + snapshot + summary or the
    # frame anatomy prices the wrong formula.
    prev_scheme = scheme_override()
    if cert_sig_scheme is not None:
        set_scheme(resolve_scheme(cert_sig_scheme))
    try:
        art = run_sim_scenario(
            scenario, seed + 1, workdir, commit_rule=commit_rule
        )
        # The sim committee shares ONE registry; its post-run snapshot
        # is the committee-aggregated ledger (the reset happens at the
        # START of the next run, so the counters are intact here).
        snap = metrics.registry().snapshot()
        quorum = 2 * nodes // 3 + 1  # Committee.quorum_threshold
        wc = wire_crypto_summary([snap], quorum_weight=quorum)
    finally:
        set_scheme(prev_scheme)
    return {
        "what": (
            f"Clean simulated N={nodes} committee wire/crypto ledger "
            f"({duration} virtual s, rate {rate}, seed {seed}) — the "
            "ROADMAP item 4 before-number at committee scale.  Raw-"
            "frame anatomy (sim transport: v2 compact bodies, no "
            "per-connection dictionary/deflate); sim-MAC signatures "
            "(64 B, wire-size-exact)."
        ),
        "nodes": nodes,
        "quorum": quorum,
        "commit_rule": commit_rule or "classic",
        "verdicts_ok": art["ok"],
        "schedule": art["schedule"],
        "wall": art["wall"],
        # Per-leader first→2f+1 direct-support arrival spread on the
        # virtual clock — the number that decides whether smaller
        # certificate frames (halfagg) loosen the ISSUE 19 N>=10
        # support-spread wall.
        "support_arrival": art.get("support_arrival"),
        "wire": wc["wire"],
        "crypto": wc["crypto"],
        "headline": {
            "cert_sig_scheme": wc["wire"].get("cert_sig_scheme"),
            "cert_sig_bytes_fraction": wc["wire"].get(
                "cert_sig_bytes_fraction"
            ),
            "cert_sig_bytes_per_cert": wc["wire"].get(
                "cert_sig_bytes_per_cert"
            ),
            "cert_bytes_per_frame": (
                round(
                    wc["wire"]["out"]["certificate"]["bytes"]
                    / wc["wire"]["out"]["certificate"]["frames"],
                    1,
                )
                if wc["wire"].get("out", {}).get("certificate", {}).get(
                    "frames"
                )
                else None
            ),
        },
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--nodes", type=int, default=20)
    ap.add_argument("--duration", type=int, default=30)
    ap.add_argument("--rate", type=int, default=600)
    ap.add_argument("--seed", type=int, default=90_000)
    ap.add_argument(
        "--workdir", default=os.path.join(REPO, ".sim_wire_capture")
    )
    ap.add_argument(
        "--cert-sig-scheme",
        choices=["individual", "halfagg"],
        default=None,
        help="pin the certificate-signature scheme for this capture "
        "(scoped to the run via the scenario env; default: whatever "
        "the process/NARWHAL_CERT_SIG_SCHEME setting is)",
    )
    ap.add_argument(
        "--commit-rule",
        choices=["classic", "lowdepth", "multileader"],
        default=None,
        help="consensus commit rule for the committee (default: classic)",
    )
    ap.add_argument("--artifact", default="artifacts/wire_n20_r19.json")
    args = ap.parse_args(argv)

    art = capture(
        args.nodes, args.duration, args.rate, args.seed, args.workdir,
        cert_sig_scheme=args.cert_sig_scheme,
        commit_rule=args.commit_rule,
    )
    os.makedirs(os.path.dirname(args.artifact) or ".", exist_ok=True)
    with open(args.artifact, "w") as f:
        json.dump(art, f, indent=1)
    print(json.dumps(art["headline"], indent=1))
    if not art["verdicts_ok"]:
        print("WARNING: sim verdicts not all ok — capture still "
              "recorded, inspect the artifact", file=sys.stderr)
        return 1
    certs = art["wire"].get("out", {}).get("certificate", {})
    print(
        f"n={args.nodes}: {certs.get('frames', 0):,} cert frames, "
        f"{art['headline']['cert_bytes_per_frame']} B/frame, "
        f"sig fraction {art['headline']['cert_sig_bytes_fraction']}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
