"""Fault-scenario runner: drive a local committee through a declared fault
and emit one artifact with three machine-checked verdicts.

    python benchmark/fault_bench.py --scenario benchmark/scenarios/byz_wrong_key.json \
        --artifact artifacts/fault_byz_wrong_key.json

Per scenario (narwhal_tpu/faults/spec.py) the runner launches a
local_bench-style committee with the scenario's fault planes wired in
(Byzantine plans via ``--fault-plan``/NARWHAL_FAULT_PLAN — handed to the
authority's primary AND its workers, each role acting on its own plane's
behaviors; WAN shaping via NARWHAL_FAULT_NETEM; crash/restart
orchestrated from here with SIGKILL + respawn over the same store),
scrapes every node throughout, and then judges:

- **safety** — every honest node's consensus audit segments replayed
  through the frozen golden oracle (consensus/replay.py): byte-identical
  commit sequences, certificate-uniqueness and causal-history invariants,
  and cross-node prefix consistency;
- **liveness** — honest survivors keep committing client payload AFTER
  the fault settles (scraped ``consensus.committed_batch_digests``
  deltas; the same payload-progress gate local_bench uses);
- **detection** — every rule in ``expect.rules`` FIRES into the timeline
  ``events`` track, and (unless ``--skip-control``) a control arm with
  all fault planes stripped fires NOTHING.

``--fuzz-seed N`` (repeatable) generates a scenario from
narwhal_tpu/faults/fuzz.py instead of a file, dumping it as a normal
``<name>.spec.json`` beside the artifact BEFORE running it, so any fuzz
catch replays byte-for-byte via ``--scenario`` with no fuzzer in the
loop.

The scenario clock starts when the committee is launched (netem's
``start_ts`` anchor): crash/partition offsets must leave a few seconds of
boot slack.  Exit code is non-zero if any verdict fails — the CI
fault-smoke / fault-fuzz-smoke gates.
"""

from __future__ import annotations

import argparse
import dataclasses
import datetime
import glob
import json
import os
import shutil
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from narwhal_tpu.config import Parameters, export_keypair  # noqa: E402
from narwhal_tpu.consensus.replay import (  # noqa: E402
    cross_node_prefix,
    replay_segments,
)
from narwhal_tpu.crypto import KeyPair  # noqa: E402
from narwhal_tpu.faults.spec import FaultScenario, load_scenario  # noqa: E402
from benchmark.local_bench import (  # noqa: E402
    build_committee,
    client_command,
    kill_stale_nodes,
    metrics_port,
    share_rate,
    wait_for_boot,
)
from benchmark.metrics_check import build_timeline  # noqa: E402
from benchmark.scraper import Scraper  # noqa: E402

# Seconds of boot + store-replay + catch-up the liveness settle-point
# allows a restarted node (pure-Python ed25519 makes catch-up verify
# slow on shared-core hosts).
_RESTART_SETTLE_S = 10.0
# Settle margin after a partition heals (reconnect backoff, resync).
_HEAL_SETTLE_S = 3.0


def compile_netem(
    scenario: FaultScenario, committee, keypairs, start_ts: float
) -> Optional[dict]:
    """Resolve the scenario's ``wan`` plane into the per-node config file
    narwhal_tpu/faults/netem.py loads (addresses instead of indices).
    The resolution itself is the shared
    ``faults/netem.py::resolve_wan_plane`` — the same table the sim
    transport consumes — wrapped in this runner's file envelope."""
    if scenario.wan is None:
        return None
    from narwhal_tpu.faults.netem import resolve_wan_plane

    nodes = resolve_wan_plane(
        scenario, committee, [kp.name for kp in keypairs]
    )
    return {"seed": scenario.seed, "start_ts": start_ts, "nodes": nodes}


def _log_commits_after(
    log_paths: List[str],
    settle_ts: float,
    state: Optional[dict] = None,
) -> int:
    """Count payload-digest ``Committed B... -> ...`` log lines at/after
    the settle point across
    a primary's per-incarnation logs — the scrape-independent liveness
    fallback.  A survivor grinding through a post-heal catch-up flood can
    stall its event loop past the scraper's timeout for every tick
    (pure-Python batch signature verification), yet its synchronous
    commit log lines are ground truth that it kept committing.

    ``state`` (path → (byte offset, running count)) makes repeated
    polling incremental: each call scans only the bytes appended since
    the last — the grace loop polls every second against logs that grow
    to tens of MB, and a full rescan per tick is exactly the kind of
    load the loop exists to ride out.  A partially written last line is
    left for the next call."""
    n = 0
    for path in log_paths:
        off, cnt = state.get(path, (0, 0)) if state is not None else (0, 0)
        try:
            with open(path, "rb") as f:
                f.seek(off)
                for raw in f:
                    if not raw.endswith(b"\n"):
                        break  # torn tail mid-write: re-scan next call
                    off += len(raw)
                    line = raw.decode(errors="replace")
                    # Only per-payload-digest commit lines ("Committed
                    # B{round}(...) -> {digest}", emitted because the
                    # primaries run with --benchmark): a survivor
                    # committing nothing but EMPTY headers post-settle
                    # must not read as live — the verdict is about
                    # client payload, matching the scraped
                    # payload-batch gate.
                    if " Committed B" not in line or " -> " not in line:
                        continue
                    try:
                        # node/main.py formats %(asctime)s with logging's
                        # default LOCALTIME converter (the trailing 'Z' is
                        # cosmetic) — a naive strptime + .timestamp() reads
                        # it back in local time.  Parsing it as UTC instead
                        # shifts every stamp by the host's UTC offset and
                        # silently inverts the verdict off-UTC hosts.
                        ts = datetime.datetime.strptime(
                            line.split(" ", 1)[0], "%Y-%m-%dT%H:%M:%S.%fZ"
                        ).timestamp()
                    except ValueError:
                        continue
                    if ts >= settle_ts:
                        cnt += 1
        except OSError:
            pass  # unreadable now; the retained count still stands
        if state is not None:
            state[path] = (off, cnt)
        n += cnt
    return n


def _post_settle_delta(samples, node_idx: int, settle_ts: float):
    """(sample count, committed-batch delta) for one primary over its
    scraped samples at/after the settle point — the liveness signal."""
    series = [
        s["counters"].get("consensus.committed_batch_digests", 0)
        for s in samples
        if s["node"] == f"primary-{node_idx}" and s["t"] >= settle_ts
    ]
    delta = series[-1] - series[0] if len(series) >= 2 else 0
    return len(series), delta


def run_scenario(
    scenario: FaultScenario,
    workdir: str,
    base_port: int = 9200,
    quiet: bool = False,
    trace_out: Optional[str] = None,
) -> dict:
    """Run one arm; returns the artifact fragment for it."""
    kill_stale_nodes()
    shutil.rmtree(workdir, ignore_errors=True)
    os.makedirs(workdir, exist_ok=True)
    storedir = workdir
    if os.path.isdir("/dev/shm"):
        storedir = f"/dev/shm/narwhal_fault_{os.path.basename(workdir)}"
        shutil.rmtree(storedir, ignore_errors=True)
        os.makedirs(storedir, exist_ok=True)

    keypairs = [KeyPair.generate() for _ in range(scenario.nodes)]
    committee = build_committee(keypairs, base_port, scenario.workers)
    committee.export(f"{workdir}/committee.json")
    params = Parameters(**scenario.parameters)
    params.export(f"{workdir}/parameters.json")
    for i, kp in enumerate(keypairs):
        export_keypair(kp, f"{workdir}/node-{i}.json")

    # Byzantine plans: one JSON per adversarial node, target indices
    # resolved to base64 keys (the on-disk committee is re-sorted, so
    # index order only exists here, where the keypair list is).
    plan_paths: Dict[int, str] = {}
    for b in scenario.byzantine:
        plan = {
            "behaviors": b.behaviors,
            "seed": scenario.seed ^ (b.node + 1),
            "replay_interval_ms": b.replay_interval_ms,
            "flood_interval_ms": b.flood_interval_ms,
            "garbage_bytes": b.garbage_bytes,
        }
        if b.targets:
            plan["withhold_targets"] = [
                keypairs[t].name.encode_base64() for t in b.targets
            ]
        path = f"{workdir}/byzantine-{b.node}.json"
        with open(path, "w") as f:
            json.dump(plan, f, indent=1)
        plan_paths[b.node] = path

    # The scenario clock: partition windows and crash offsets both anchor
    # here, just before the committee launches.
    start_ts = time.time()
    netem_path = None
    netem_cfg = compile_netem(scenario, committee, keypairs, start_ts)
    if netem_cfg is not None:
        netem_path = f"{workdir}/netem.json"
        with open(netem_path, "w") as f:
            json.dump(netem_cfg, f, indent=1)

    # Flight-recorder dumps (503 transition / SIGTERM / task death) land
    # here; a failed verdict attaches them to the artifact below.
    flight_dir = f"{workdir}/flight"
    base_env = dict(
        os.environ,
        PYTHONPATH=REPO,
        NARWHAL_FAULT_SEED=str(scenario.seed),
        NARWHAL_FLIGHT_DIR=flight_dir,
        **scenario.env,
    )

    procs: List[Tuple[subprocess.Popen, object]] = []
    procs_by_auth: Dict[int, List[subprocess.Popen]] = {}
    audit_segments: Dict[int, List[str]] = {}
    primary_logs: Dict[int, List[str]] = {}
    incarnation: Dict[int, int] = {}
    scrape_targets = []
    metrics_paths: List[str] = []

    def spawn(cmd, logfile, env) -> subprocess.Popen:
        f = open(logfile, "w")
        p = subprocess.Popen(
            cmd, stdout=f, stderr=subprocess.STDOUT, env=env, cwd=REPO
        )
        procs.append((p, f))
        return p

    def node_env(label: str, extra: Dict[str, str]) -> dict:
        env = dict(base_env, NARWHAL_FAULT_NODE=label, **extra)
        if netem_path:
            env["NARWHAL_FAULT_NETEM"] = netem_path
        return env

    def spawn_authority(i: int) -> List[str]:
        """Launch authority i's primary + workers; returns log paths."""
        inc = incarnation.get(i, 0)
        incarnation[i] = inc + 1
        suffix = "" if inc == 0 else f".r{inc}"
        logs = []
        audit = f"{workdir}/audit-primary-{i}.seg{inc}.bin"
        audit_segments.setdefault(i, []).append(audit)
        label = f"primary-{i}"
        log_path = f"{workdir}/primary-{i}{suffix}.log"
        logs.append(log_path)
        primary_logs.setdefault(i, []).append(log_path)
        mport = metrics_port(base_port, scenario.nodes, scenario.workers, i)
        if inc == 0:
            scrape_targets.append((label, "127.0.0.1", mport))
        # Post-mortem snapshot per INCARNATION: the trace exporter joins
        # stage/round traces + flight rings across every file, so a
        # crashed-and-restarted node contributes both lives to the trace.
        mpath = f"{workdir}/metrics-{label}{suffix}.json"
        metrics_paths.append(mpath)
        cmd = [
            sys.executable, "-m", "narwhal_tpu.node", "run",
            "--keys", f"{workdir}/node-{i}.json",
            "--committee", f"{workdir}/committee.json",
            "--parameters", f"{workdir}/parameters.json",
            "--store", f"{storedir}/db-primary-{i}",
            "--benchmark",
            "--metrics-port", str(mport),
            "--metrics-path", mpath,
        ]
        extra = {"NARWHAL_CONSENSUS_AUDIT": audit}
        if i in plan_paths:
            cmd += ["--fault-plan", plan_paths[i]]
        cmd.append("primary")
        p = spawn(cmd, log_path, node_env(label, extra))
        procs_by_auth.setdefault(i, []).append(p)
        for wid in range(scenario.workers):
            label = f"worker-{i}-{wid}"
            log_path = f"{workdir}/worker-{i}-{wid}{suffix}.log"
            logs.append(log_path)
            mport = metrics_port(
                base_port, scenario.nodes, scenario.workers, i, wid
            )
            if inc == 0:
                scrape_targets.append((label, "127.0.0.1", mport))
            mpath = f"{workdir}/metrics-{label}{suffix}.json"
            metrics_paths.append(mpath)
            wcmd = [
                sys.executable, "-m", "narwhal_tpu.node", "run",
                "--keys", f"{workdir}/node-{i}.json",
                "--committee", f"{workdir}/committee.json",
                "--parameters", f"{workdir}/parameters.json",
                "--store", f"{storedir}/db-worker-{i}-{wid}",
                "--metrics-port", str(mport),
                "--metrics-path", mpath,
            ]
            if i in plan_paths:
                # One plan per authority, both roles: the worker acts on
                # the plan's worker-plane behaviors, the primary on the
                # primary-plane ones (each ignores the other set).
                wcmd += ["--fault-plan", plan_paths[i]]
            wcmd += ["worker", "--id", str(wid)]
            p = spawn(
                wcmd,
                log_path,
                node_env(label, {}),
            )
            procs_by_auth.setdefault(i, []).append(p)
        return logs

    boot_logs: List[str] = []
    for i in range(scenario.nodes):
        boot_logs.extend(spawn_authority(i))

    # Committee must be up before the clients open the load window.
    wait_for_boot(boot_logs, quiet=quiet)

    rate_share = share_rate(scenario.rate, scenario.nodes * scenario.workers)
    client_idx = 0
    for i in range(scenario.nodes):
        for wid in range(scenario.workers):
            addr = committee.worker(keypairs[i].name, wid).transactions
            spawn(
                client_command(addr, scenario.tx_size, rate_share,
                               client_idx),
                f"{workdir}/client-{i}-{wid}.log",
                dict(base_env),
            )
            client_idx += 1

    scraper = Scraper(scrape_targets, interval_s=1.0).start()

    # -- the measured window, with the crash/restart timeline ------------------
    events = sorted(
        [("crash", c.at_s, c.node) for c in scenario.crash]
        + [
            ("restart", c.restart_at_s, c.node)
            for c in scenario.crash
            if c.restart_at_s is not None
        ],
        key=lambda e: e[1],
    )
    end_ts = start_ts + scenario.duration
    for kind, at_s, node in events:
        delay = (start_ts + at_s) - time.time()
        if delay > 0:
            time.sleep(delay)
        if kind == "crash":
            if not quiet:
                print(f"FAULT: SIGKILL authority {node}", file=sys.stderr)
            for p in procs_by_auth.get(node, []):
                try:
                    p.kill()  # SIGKILL: the torn-tail path is the point
                except ProcessLookupError:
                    pass
            procs_by_auth[node] = []
        else:
            if not quiet:
                print(f"FAULT: restarting authority {node}", file=sys.stderr)
            spawn_authority(node)
    remaining = end_ts - time.time()
    if remaining > 0:
        time.sleep(remaining)

    live_ok = scraper.wait_for_payload_commits(
        scenario.progress_wait, quiet=quiet
    )

    byz = set(scenario.byzantine_nodes())
    dead_forever = {
        c.node for c in scenario.crash if c.restart_at_s is None
    }
    honest = [i for i in range(scenario.nodes) if i not in byz]
    survivors = [i for i in honest if i not in dead_forever]
    settle_s = 0.0
    for c in scenario.crash:
        settle_s = max(
            settle_s,
            (c.restart_at_s + _RESTART_SETTLE_S)
            if c.restart_at_s is not None
            else c.at_s,
        )
    if scenario.wan:
        for part in scenario.wan.partitions:
            if part.until_s is not None:
                settle_s = max(settle_s, part.until_s + _HEAL_SETTLE_S)
    settle_ts = start_ts + settle_s

    # A healed/restarted survivor may still be catching up (slow
    # pure-Python verify on a shared core; its metrics endpoint starves
    # too) when the window closes — keep scraping, bounded by
    # progress_wait, until EVERY survivor shows post-settle commit
    # progress, so the liveness verdict measures the protocol rather
    # than this host's scheduling.
    grace_deadline = time.time() + scenario.progress_wait
    log_scan_state: dict = {}
    while time.time() < grace_deadline:
        lagging = [
            i for i in survivors
            if _post_settle_delta(scraper.samples, i, settle_ts)[1] <= 0
            and _log_commits_after(
                primary_logs.get(i, []), settle_ts, log_scan_state
            ) == 0
        ]
        if not lagging:
            break
        time.sleep(1.0)

    healthz = scraper.healthz_all()
    # Every node's flight ring at quiesce: even a clean arm's artifact
    # carries the committee's last-seconds event history.
    flight_rings = scraper.flight_all()
    scraper.stop()

    # Graceful teardown (SIGTERM flushes final snapshots + audit tails).
    for p, f in procs:
        try:
            p.send_signal(signal.SIGTERM)
        except ProcessLookupError:
            pass
    for p, f in procs:
        try:
            p.wait(timeout=15)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()
        f.close()

    if storedir != workdir:
        # The tmpfs store is per-arm scratch: leaving it would leak a
        # committee's worth of batch logs into /dev/shm per arm forever
        # (the audit segments live in workdir, not here).
        shutil.rmtree(storedir, ignore_errors=True)

    timeline = build_timeline(scraper.samples, interval_s=1.0, healthz=healthz)

    # -- verdicts --------------------------------------------------------------
    # Safety: golden-oracle replay per honest node + cross-node prefix.
    safety_nodes = {}
    sequences = {}
    for i in honest:
        verdict = replay_segments(
            committee, params.gc_depth, audit_segments.get(i, [])
        )
        sequences[f"primary-{i}"] = verdict.pop("commit_digests")
        safety_nodes[f"primary-{i}"] = verdict
    cross = cross_node_prefix(sequences)
    safety = {
        "ok": cross["ok"] and all(v["ok"] for v in safety_nodes.values()),
        "nodes": safety_nodes,
        "cross_node": cross,
    }

    # Liveness: payload commits strictly progress after the fault settles.
    # Scraped counter deltas are the primary signal; the node's own commit
    # log lines are the fallback when catch-up load starves its metrics
    # endpoint (see _log_commits_after).
    liveness_nodes = {}
    for i in survivors:
        samples_n, delta = _post_settle_delta(
            scraper.samples, i, settle_ts
        )
        log_commits = _log_commits_after(
            primary_logs.get(i, []), settle_ts, log_scan_state
        )
        liveness_nodes[f"primary-{i}"] = {
            "post_settle_samples": samples_n,
            "committed_batches_delta": delta,
            "log_commits_post_settle": log_commits,
            "ok": delta > 0 or log_commits > 0,
        }
    liveness = {
        "ok": bool(liveness_nodes)
        and all(v["ok"] for v in liveness_nodes.values())
        and live_ok,
        "payload_commits_observed": live_ok,
        "settle_offset_s": settle_s,
        "nodes": liveness_nodes,
    }

    # Detection: expected rules FIRING in the committee-wide events track.
    fired = sorted(
        {
            e["rule"]
            for e in timeline.get("events", [])
            if e.get("event") == "FIRING"
        }
    )
    missing = [r for r in scenario.expect_rules if r not in fired]
    detection = {
        "ok": not missing,
        "expected": scenario.expect_rules,
        "fired": fired,
        "missing": missing,
    }

    # Fault arms tolerate extra firings (a crash legitimately trips
    # several rules); the CONTROL arm's zero-firing assertion is what
    # pins down false positives.
    if scenario.is_clean():
        detection["ok"] = not fired
        detection["expected"] = []

    arm = {
        "scenario": dataclasses.asdict(scenario),
        "seed": scenario.seed,
        "verdicts": {
            "safety": safety,
            "liveness": liveness,
            "detection": detection,
        },
        "ok": safety["ok"] and liveness["ok"] and detection["ok"],
        "timeline": timeline,
        "flight": flight_rings,
        "audit_segments": {
            str(i): segs for i, segs in sorted(audit_segments.items())
        },
    }
    if not arm["ok"]:
        # A failed verdict ships the nodes' own dump files (503
        # transition / SIGTERM / task death) alongside the scraped
        # rings: the black boxes ARE the post-mortem.
        dumps = {}
        for path in sorted(glob.glob(f"{flight_dir}/flight-*.json")):
            try:
                with open(path) as f:
                    dumps[os.path.basename(path)] = json.load(f)
            except (OSError, ValueError):
                continue
        arm["flight_dumps"] = dumps
    if trace_out:
        from benchmark import trace_export

        trace_export.export(
            trace_export.load_named_snapshots(metrics_paths),
            trace_out,
            timeline=timeline,
            flight=flight_rings,
            quiet=quiet,
        )
    return arm


def run(
    scenario: FaultScenario,
    workdir_root: str,
    base_port: int = 9200,
    control: bool = True,
    quiet: bool = False,
    trace_out: Optional[str] = None,
) -> dict:
    """Fault arm + (optionally) clean-control arm; one artifact dict.
    ``trace_out`` exports the FAULT arm as a Perfetto trace (the control
    arm is a baseline, not a story worth a timeline)."""
    if not quiet:
        print(f"=== scenario {scenario.name} (fault arm)", file=sys.stderr)
    fault_arm = run_scenario(
        scenario, os.path.join(workdir_root, scenario.name), base_port,
        quiet, trace_out=trace_out,
    )
    artifact = {
        "name": scenario.name,
        "generated_by": "benchmark/fault_bench.py",
        "fault_arm": fault_arm,
        "ok": fault_arm["ok"],
    }
    if control and not scenario.is_clean():
        ctrl = scenario.control_arm()
        if not quiet:
            print(f"=== scenario {scenario.name} (control arm)", file=sys.stderr)
        control_arm = run_scenario(
            ctrl, os.path.join(workdir_root, ctrl.name), base_port, quiet
        )
        artifact["control_arm"] = control_arm
        artifact["ok"] = artifact["ok"] and control_arm["ok"]
    return artifact


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--scenario", action="append", default=[],
                        help="scenario JSON path (repeatable)")
    parser.add_argument("--fuzz-seed", type=int, action="append", default=[],
                        help="generate a scenario from this seed "
                        "(narwhal_tpu/faults/fuzz.py; repeatable).  The "
                        "generated spec is dumped as <name>.spec.json next "
                        "to the artifact (or into --workdir), so any fuzz "
                        "catch replays byte-for-byte via --scenario")
    parser.add_argument("--artifact", default=None,
                        help="write the artifact JSON here (one scenario) "
                        "or use it as a '{name}' template (several)")
    parser.add_argument("--trace-out", default=None,
                        help="export the fault arm as a Perfetto-loadable "
                        "Chrome trace to this path (one scenario) or a "
                        "'{name}' template (several) — see "
                        "benchmark/trace_export.py")
    parser.add_argument("--workdir", default=os.path.join(REPO, ".fault_bench"))
    parser.add_argument("--base-port", type=int, default=9200)
    parser.add_argument("--skip-control", action="store_true",
                        help="skip the clean-control arm (faster; loses the "
                        "no-false-positive half of the detection verdict)")
    parser.add_argument("--quiet", action="store_true")
    args = parser.parse_args()

    if not args.scenario and not args.fuzz_seed:
        parser.error("need at least one --scenario or --fuzz-seed")
    n_runs = len(args.scenario) + len(args.fuzz_seed)
    if args.artifact and n_runs > 1 and "{name}" not in args.artifact:
        parser.error(
            "--artifact must contain '{name}' when several --scenario/"
            "--fuzz-seed flags are given (a fixed path would silently "
            "overwrite each scenario's artifact with the next)"
        )
    if args.trace_out and n_runs > 1 and "{name}" not in args.trace_out:
        parser.error(
            "--trace-out must contain '{name}' when several --scenario/"
            "--fuzz-seed flags are given (same overwrite hazard as "
            "--artifact)"
        )

    # (scenario, generated-spec object or None) in CLI order.
    scenarios = [(load_scenario(path), None) for path in args.scenario]
    if args.fuzz_seed:
        from narwhal_tpu.faults.fuzz import generate
        from narwhal_tpu.faults.spec import parse_scenario

        for seed in args.fuzz_seed:
            # Committee-size pool pinned to N=4: the socketed runner
            # pays 3 real processes per authority and its detection
            # contracts were timed on a 4-node host; the full size pool
            # (7/10/20) is the sim sweep's (benchmark/sim_bench.py).
            obj = generate(seed, sizes=(4,))
            scenarios.append((parse_scenario(obj), obj))

    # The '{name}' template only prevents collisions between DISTINCT
    # names — a repeated --fuzz-seed, or a --scenario replay of a dumped
    # fuzz spec alongside its generating seed, resolves to the same name
    # and would silently overwrite the first run's artifact and spec.
    names = [s.name for s, _ in scenarios]
    dupes = sorted({n for n in names if names.count(n) > 1})
    if dupes:
        parser.error(
            f"scenario name(s) {dupes} appear more than once across "
            "--scenario/--fuzz-seed; later runs would overwrite the "
            "earlier artifacts"
        )

    failures = 0
    for scenario, fuzz_spec in scenarios:
        if fuzz_spec is not None:
            # The replayable spec is written BEFORE the run: a fuzz draw
            # that crashes the runner must still be reproducible.
            spec_dir = (
                os.path.dirname(args.artifact) if args.artifact
                else args.workdir
            )
            os.makedirs(spec_dir or ".", exist_ok=True)
            spec_path = os.path.join(
                spec_dir, f"{scenario.name}.spec.json"
            )
            with open(spec_path, "w") as f:
                json.dump(fuzz_spec, f, indent=1)
            if not args.quiet:
                print(f"fuzz spec -> {spec_path}", file=sys.stderr)
        artifact = run(
            scenario,
            args.workdir,
            base_port=args.base_port,
            control=not args.skip_control,
            quiet=args.quiet,
            trace_out=(
                args.trace_out.replace("{name}", scenario.name)
                if args.trace_out
                else None
            ),
        )
        out = args.artifact
        if out:
            out = out.replace("{name}", scenario.name)
            os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
            with open(out, "w") as f:
                json.dump(artifact, f, indent=1)
        verdicts = artifact["fault_arm"]["verdicts"]
        print(
            f"{scenario.name}: "
            + " ".join(
                f"{k}={'PASS' if v['ok'] else 'FAIL'}"
                for k, v in verdicts.items()
            )
            + (
                ""
                if "control_arm" not in artifact
                else (
                    " control="
                    + (
                        "PASS"
                        if artifact["control_arm"]["ok"]
                        else "FAIL"
                    )
                )
            )
        )
        if not artifact["ok"]:
            failures += 1
            for k, v in verdicts.items():
                if not v["ok"]:
                    print(f"  {k} FAILED: {json.dumps(v)[:2000]}",
                          file=sys.stderr)
            if "control_arm" in artifact and not artifact["control_arm"]["ok"]:
                print(
                    "  control FAILED: "
                    + json.dumps(
                        artifact["control_arm"]["verdicts"]
                    )[:2000],
                    file=sys.stderr,
                )
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
