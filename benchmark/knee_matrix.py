"""Saturation-knee matrix: rate sweep × committee size, queue-attributed.

Mysticeti's framing (PAPERS.md, arXiv:2310.14821): a DAG-BFT latency
claim is meaningless without the load-vs-latency knee, and the knee is a
queueing phenomenon.  This harness produces the knee as ONE artifact:
for each committee size it sweeps offered load, records TPS + latency +
the per-channel queue accounting (``metrics_check.queue_pressure_summary``
via ``metrics.InstrumentedQueue``), locates the knee — the last rate
step whose marginal throughput still pays for its offered load — and
names the FIRST-SATURATING channel at each knee point, which is what
makes the matrix explanatory (``node.tx_output`` filling is an
application-sink wall; ``worker.to_quorum`` is admission; etc).

Two measurement modes ride the same artifact:

* ``socketed`` (N=4): real processes + TCP via ``local_bench.run_bench``
  — wall-clock TPS/latency, scraper-timeline ``first_saturating``.
  Points at/past the knee legitimately carry harness errors (quiesce
  health firing, cross-check drift): they are RECORDED per point, not
  fatal — measuring past the knee is the point of the sweep.
* ``sim`` (N=10/20): the deterministic in-process committee
  (``run_sim_scenario`` with both stock rate clamps lifted — the
  600/s global and 60/s large-N caps would flatten the sweep; here
  driving past the knee is the point).  Latency is virtual-clock
  cert→commit (pure protocol cadence); throughput is committed
  certificates per virtual second; queue attribution uses the
  high-water fallback (no scrape timeline in-process).

Usage:
    python -m benchmark.knee_matrix                  # full N=4/10/20 matrix
    python -m benchmark.knee_matrix --smoke          # 2-point N=4 CI arm
    make knee-matrix

The artifact lands in ``artifacts/knee_matrix_<rev>.json`` (override
with ``--out``) and is recognized by ``benchmark/trajectory.py`` as
``knee.n<N>.*`` attribution metrics (``attr.``-namespaced — never part
of the gated saturation-probe series).  ``--smoke`` exits nonzero when
no point produced a queue attribution: the CI gate that the
backpressure observatory actually observes.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, REPO)

from benchmark.local_bench import run_bench  # noqa: E402

REVISION = "r21"

# Socketed sweep (N=4): around the stock bench rate (20k tx/s at 512 B)
# so the knee brackets the trajectory gate's operating point.
SOCKETED_RATES = (5_000, 10_000, 20_000, 40_000, 80_000)
SOCKETED_DURATION_S = 10

# Sim sweeps: offered load in tx/s at the sim's stock 512 B tx.  Both
# stock clamps (rate_cap=600, large_n_rate_cap=60) are lifted — the
# sweep's whole point is driving the committee past its knee, which for
# the sim sits where batch production outruns the quorum-ack window
# (~16k tx/s at N=10, ~32k at N=20 — probed, and cheap: the sim wall
# cost is seconds per point even there).
SIM_RATES = {10: (2_000, 8_000, 16_000, 32_000),
             20: (2_000, 8_000, 16_000, 32_000)}
SIM_DURATION_S = 10


def _hot_channels(queues: dict, top: int = 3) -> dict:
    """The ``top`` highest-utilization channels from a queues section —
    enough context per point to read the attribution without the full
    per-node tables."""
    chans = (queues or {}).get("channels") or {}
    ranked = sorted(
        chans.items(),
        key=lambda kv: (
            kv[1].get("utilization", 0.0),
            kv[1].get("high_water", 0),
        ),
        reverse=True,
    )
    return {
        ch: {
            k: v
            for k, v in a.items()
            if k in ("capacity", "high_water", "utilization", "full")
        }
        for ch, a in ranked[:top]
        if a.get("high_water")
    }


def _find_knee(points: list) -> dict:
    """Locate the knee of a sweep: the highest-TPS point, refined to the
    EARLIEST rate whose TPS is within 5% of that peak — past it, added
    offered load buys latency, not throughput.  Returns the knee point
    annotated with the saturation channel."""
    measured = [p for p in points if p.get("tps")]
    if not measured:
        return {}
    peak = max(p["tps"] for p in measured)
    knee = next(p for p in measured if p["tps"] >= 0.95 * peak)
    out = {
        "rate": knee["rate"],
        "tps": knee["tps"],
        "latency_ms": knee["latency_ms"],
    }
    # The attribution prefers the knee point's own saturating channel;
    # a knee measured just BELOW saturation borrows it from the first
    # later point that saturated (that is what the knee runs into).
    for p in [knee] + [q for q in measured if q["rate"] > knee["rate"]]:
        fs = p.get("first_saturating") or {}
        if fs.get("channel"):
            out["first_saturating"] = fs
            out["attributed_at_rate"] = p["rate"]
            break
    return out


def sweep_socketed(
    nodes: int,
    rates,
    duration_s: int,
    tx_size: int,
    base_port: int,
    quiet: bool = False,
) -> dict:
    points = []
    for i, rate in enumerate(rates):
        if not quiet:
            print(f"[knee] socketed N={nodes} rate={rate} ...", flush=True)
        workdir = tempfile.mkdtemp(prefix=f"knee-n{nodes}-r{rate}-")
        result = run_bench(
            nodes=nodes,
            workers=1,
            rate=rate,
            tx_size=tx_size,
            duration=duration_s,
            base_port=base_port + 200 * i,
            workdir=workdir,
            quiet=True,
            progress_wait=30,
        )
        queues = result.queues or {}
        point = {
            "rate": rate,
            "tps": round(result.end_to_end_tps, 1),
            "latency_ms": round(result.end_to_end_latency_ms, 1),
            "consensus_tps": round(result.consensus_tps, 1),
            "errors": len(result.errors),
            "first_saturating": queues.get("first_saturating") or {},
            "hot_channels": _hot_channels(queues),
        }
        if result.errors and not quiet:
            # Past-knee runs fail the harness's clean-run gates by
            # design; keep the first error as the point's context.
            point["first_error"] = result.errors[0][:200]
            print(f"[knee]   ({len(result.errors)} harness errors — "
                  "expected at/past the knee)", flush=True)
        points.append(point)
        if not quiet:
            fs = point["first_saturating"].get("channel", "-")
            print(
                f"[knee]   tps={point['tps']} "
                f"latency={point['latency_ms']}ms sat={fs}",
                flush=True,
            )
    return {
        "n": nodes,
        "mode": "socketed",
        "workers": 1,
        "duration_s": duration_s,
        "points": points,
        "knee": _find_knee(points),
    }


def sweep_sim(
    nodes: int, rates, duration_s: int, tx_size: int, quiet: bool = False
) -> dict:
    from narwhal_tpu.faults.spec import FaultScenario
    from narwhal_tpu.sim.committee import run_sim_scenario

    points = []
    for rate in rates:
        if not quiet:
            print(f"[knee] sim N={nodes} rate={rate} ...", flush=True)
        scenario = FaultScenario(
            name=f"knee_n{nodes}_r{rate}",
            nodes=nodes,
            workers=1,
            rate=rate,
            tx_size=tx_size,
            duration=duration_s,
            seed=7,
        )
        workdir = tempfile.mkdtemp(prefix=f"knee-sim-n{nodes}-r{rate}-")
        art = run_sim_scenario(
            scenario,
            run_seed=1,
            workdir=workdir,
            rate_cap=rate,
            large_n_rate_cap=None,
        )
        virtual_s = float(
            (art.get("schedule") or {}).get("virtual_s") or 0.0
        )
        seq = (art.get("commit_sequences") or {}).values()
        committed = max((len(s) for s in seq), default=0)
        c2c = art.get("cert_to_commit") or {}
        sa = art.get("support_arrival") or {}
        queues = art.get("queues") or {}
        point = {
            "rate": rate,
            # Committed certificates per virtual second: the sim's
            # protocol-plane throughput (client tx goodput would fold
            # host noise back in, which the sim exists to exclude).
            "tps": (
                round(committed / virtual_s, 2) if virtual_s else 0.0
            ),
            "latency_ms": (
                round(1000 * c2c["mean_virtual_s"], 1)
                if c2c.get("mean_virtual_s")
                else None
            ),
            "support_arrival_ms": sa.get("mean_virtual_ms"),
            "errors": 0 if art.get("ok") else 1,
            "first_saturating": queues.get("first_saturating") or {},
            "hot_channels": _hot_channels(queues),
        }
        points.append(point)
        if not quiet:
            fs = point["first_saturating"].get("channel", "-")
            print(
                f"[knee]   certs/s={point['tps']} "
                f"c2c={point['latency_ms']}ms sat={fs}",
                flush=True,
            )
    return {
        "n": nodes,
        "mode": "sim",
        "workers": 1,
        "duration_s": duration_s,
        "points": points,
        "knee": _find_knee(points),
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument(
        "--smoke",
        action="store_true",
        help="2-point N=4 socketed sweep; exit nonzero when no point "
        "produced a queue attribution (the CI observability gate)",
    )
    ap.add_argument("--tx-size", type=int, default=512)
    ap.add_argument("--base-port", type=int, default=7900)
    ap.add_argument(
        "--duration", type=int, default=0,
        help="per-point seconds (0 = mode default)",
    )
    ap.add_argument(
        "--out",
        default=os.path.join(
            REPO, "artifacts", f"knee_matrix_{REVISION}.json"
        ),
    )
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args()

    configs = []
    if args.smoke:
        # One below-knee point and one decisively past it: 20k is
        # host-noise-borderline (some runs commit it all with shallow
        # queues), 80k reliably pegs the admission window.
        configs.append(
            sweep_socketed(
                4,
                (2_000, 80_000),
                args.duration or 8,
                args.tx_size,
                args.base_port,
                quiet=args.quiet,
            )
        )
    else:
        configs.append(
            sweep_socketed(
                4,
                SOCKETED_RATES,
                args.duration or SOCKETED_DURATION_S,
                args.tx_size,
                args.base_port,
                quiet=args.quiet,
            )
        )
        for n, rates in sorted(SIM_RATES.items()):
            configs.append(
                sweep_sim(
                    n,
                    rates,
                    args.duration or SIM_DURATION_S,
                    args.tx_size,
                    quiet=args.quiet,
                )
            )

    artifact = {
        "what": "TPS/latency saturation knee per committee size, each "
        "knee point attributed to the first-saturating inter-task "
        "channel (InstrumentedQueue series)",
        "generated_by": "benchmark/knee_matrix",
        "revision": REVISION,
        "tx_size": args.tx_size,
        "smoke": bool(args.smoke),
        "configs": configs,
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=1)
        f.write("\n")
    print(f"[knee] wrote {args.out}")

    attributed = [
        c["n"]
        for c in configs
        if any(
            (p.get("first_saturating") or {}).get("channel")
            for p in c["points"]
        )
        or (c.get("knee") or {}).get("first_saturating", {}).get("channel")
    ]
    for c in configs:
        knee = c.get("knee") or {}
        fs = (knee.get("first_saturating") or {}).get("channel", "NONE")
        print(
            f"[knee] N={c['n']} ({c['mode']}): knee at rate="
            f"{knee.get('rate')} tps={knee.get('tps')} "
            f"latency={knee.get('latency_ms')}ms first-saturating={fs}"
        )
    if not attributed:
        print(
            "[knee] FAIL: no config produced a queue attribution — the "
            "backpressure observatory is not observing",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
