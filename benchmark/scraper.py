"""Committee-wide time-series scraper over the nodes' --metrics-port.

The snapshot files (--metrics-path) are post-mortem: one final state per
node, great for totals, blind to anything that happens DURING the run —
a peer that stalls at t=8s and recovers at t=15s leaves an unremarkable
final snapshot.  This scraper is the live channel: it polls every node's
``GET /metrics.json?trace=0`` (and ``/healthz``) at a fixed cadence from
the bench harness, accumulating a committee-wide time-series that
``benchmark.metrics_check.build_timeline`` turns into the per-node
TPS/round/commit-lag timeline and per-peer RTT matrix embedded in the
bench JSON.

Dependency-free by design (urllib over the hand-rolled MetricsServer);
runs in a daemon thread because both bench harnesses are synchronous
process-wranglers.  A node that is slow, dead, or not yet up simply
yields no sample that tick — scraping must never perturb or abort the
run it is measuring.
"""

from __future__ import annotations

import json
import sys
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

# (logical node name, host, port) — the name keys the timeline.
Target = Tuple[str, str, int]


def fetch_json(
    host: str, port: int, path: str, timeout_s: float = 2.0
) -> Tuple[Optional[int], Optional[dict]]:
    """GET http://host:port/path → (status, parsed body) — (None, None)
    when unreachable.  5xx bodies are read and parsed too: /healthz
    carries its rule list in the 503 body."""
    url = f"http://{host}:{port}{path}"
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            return resp.status, json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read().decode())
        except (ValueError, OSError):
            return e.code, None
    except (urllib.error.URLError, OSError, ValueError):
        return None, None


class Scraper:
    """Polls every target's /metrics.json at ``interval_s``, appending
    one sample dict per (tick, reachable node) to ``samples``:

        {"t": unix_ts, "node": name,
         "counters": {...}, "gauges": {...},
         "histograms": {...}, "health": {...} | None}

    ``start()``/``stop()`` bracket the measurement window; ``stop()``
    joins the thread so the sample list is final when the harness reads
    it.  ``healthz_all()`` is the quiesce gate: one /healthz round,
    {name: (status_code | None, body | None)}.
    """

    def __init__(
        self,
        targets: List[Target],
        interval_s: float = 1.0,
        timeout_s: float = 2.0,
    ) -> None:
        self.targets = list(targets)
        self.interval_s = interval_s
        self.timeout_s = timeout_s
        self.samples: List[dict] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # All targets are polled CONCURRENTLY: sequentially, one hung
        # node would cost its full timeout per tick and destroy the
        # fixed cadence for every OTHER node — exactly when the per-node
        # resolution matters most (a stalled committee).
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, min(len(self.targets), 16)),
            thread_name_prefix="metrics-scrape",
        )

    def sample_once(self) -> int:
        """One scrape round (all targets concurrently); returns how many
        nodes answered."""

        def one(target: Target) -> Optional[dict]:
            name, host, port = target
            status, snap = fetch_json(
                host, port, "/metrics.json?trace=0", self.timeout_s
            )
            if status != 200 or not isinstance(snap, dict):
                return None
            return {
                "t": snap.get("ts", time.time()),
                "node": name,
                "counters": snap.get("counters", {}),
                "gauges": snap.get("gauges", {}),
                "histograms": snap.get("histograms", {}),
                "health": snap.get("health"),
            }

        got = 0
        for sample in self._pool.map(one, self.targets):
            if sample is not None:
                self.samples.append(sample)
                got += 1
        return got

    def _run(self) -> None:
        while not self._stop.is_set():
            t0 = time.time()
            try:
                self.sample_once()
            except Exception:
                # A scrape crash must never take the bench down with it.
                pass
            # Fixed cadence net of scrape cost, so sample spacing stays
            # ~interval_s even when a node is slow to answer.
            remaining = self.interval_s - (time.time() - t0)
            if remaining > 0:
                self._stop.wait(remaining)

    def start(self) -> "Scraper":
        self._thread = threading.Thread(
            target=self._run, name="metrics-scraper", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        self._pool.shutdown(wait=False)

    def healthz_all(
        self, retries: int = 2, retry_delay_s: float = 1.0
    ) -> Dict[str, Tuple[Optional[int], Optional[dict]]]:
        """One concurrent /healthz round, re-probing only UNREACHABLE
        targets up to ``retries`` more times: on a starved core a node's
        event loop can miss one 2 s accept window while perfectly
        healthy, and a transient None must not read as a verdict."""
        out: Dict[str, Tuple[Optional[int], Optional[dict]]] = {}
        remaining = list(self.targets)
        for attempt in range(1 + max(0, retries)):
            if not remaining:
                break
            if attempt:
                time.sleep(retry_delay_s)
            verdicts = self._pool.map(
                lambda t: fetch_json(t[1], t[2], "/healthz", self.timeout_s),
                remaining,
            )
            retry = []
            for target, verdict in zip(remaining, verdicts):
                out[target[0]] = verdict
                if verdict[0] is None:
                    retry.append(target)
            remaining = retry
        return out

    def snapshot_all(self) -> Dict[str, Optional[dict]]:
        """One concurrent FULL ``/metrics.json`` round (stage/round
        traces included, unlike the periodic ``?trace=0`` ticks): the
        remote harness's stand-in for the --metrics-path post-mortem
        files when nodes quiesce on other machines.  The returned
        snapshots carry the ``clock.offset_ms.*`` gauges and trace
        tables metrics_check's skew-corrected join and critical-path
        extraction consume.  A node that cannot answer yields None."""
        out: Dict[str, Optional[dict]] = {}
        snaps = self._pool.map(
            lambda t: fetch_json(t[1], t[2], "/metrics.json", self.timeout_s),
            self.targets,
        )
        for target, (status, body) in zip(self.targets, snaps):
            out[target[0]] = body if status == 200 else None
        return out

    def flight_all(self) -> Dict[str, Optional[dict]]:
        """One concurrent ``/debug/flight`` round — each node's bounded
        event ring at quiesce, embedded in the bench JSON so even clean
        runs carry their last-seconds event history.  A node that cannot
        answer yields None (same stance as /healthz: pulling the black
        box must never fail the run)."""
        out: Dict[str, Optional[dict]] = {}
        rings = self._pool.map(
            lambda t: fetch_json(t[1], t[2], "/debug/flight", self.timeout_s),
            self.targets,
        )
        for target, (status, body) in zip(self.targets, rings):
            out[target[0]] = body if status == 200 else None
        return out

    def _max_counter(self, name: str) -> int:
        return int(
            max(
                (s["counters"].get(name, 0) for s in self.samples),
                default=0,
            )
        )

    def commits_observed(self) -> int:
        """Max committed-certificate count seen on any node so far."""
        return self._max_counter("consensus.committed_certificates")

    def payload_commits_observed(self) -> int:
        """Max committed-BATCH count seen on any node — the wall-clock
        progress signal the harnesses use to widen a measurement window
        instead of trusting one fixed sleep.  Batch digests, not
        certificates: an idle committee commits empty headers, so the
        certificate counter rises while zero client payload has landed
        (observed on a starved shared core: 32 committed certs, 0
        committed batches at window close)."""
        return self._max_counter("consensus.committed_batch_digests")

    def wait_for_payload_commits(
        self, extra_s: float, quiet: bool = True
    ) -> bool:
        """Stretch a measurement window by up to ``extra_s`` while the
        committee shows ZERO committed payload batches (the shared
        progress-check used by both bench harnesses); returns whether
        payload progress was ultimately observed."""
        if extra_s <= 0 or self.payload_commits_observed() > 0:
            return self.payload_commits_observed() > 0
        if not quiet:
            print(
                "no payload commits observed yet; extending measurement "
                f"window (up to {extra_s:.0f} s)",
                file=sys.stderr,
            )
        deadline = time.time() + extra_s
        while (
            self.payload_commits_observed() == 0 and time.time() < deadline
        ):
            time.sleep(min(2.0, max(0.5, self.interval_s)))
        return self.payload_commits_observed() > 0
