"""Multi-run aggregation and latency-vs-throughput sweeps.

The reference's aggregation subsystem (benchmark/benchmark/aggregate.py +
plot.py, ~430 LoC) averages repeated runs (mean/stdev per metric) and plots
latency-vs-throughput curves over input-rate sweeps.  This is the local
analog: run the bench at each rate N times, aggregate, and emit a summary
table plus a JSON artifact the plots can be drawn from.

    python benchmark/aggregate.py --rates 20000 40000 55000 --runs 2 \
        --duration 20 --out artifacts/sweep.json
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
from typing import Dict, List

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from benchmark.local_bench import run_bench  # noqa: E402

METRICS = [
    "consensus_tps",
    "consensus_latency_ms",
    "end_to_end_tps",
    "end_to_end_latency_ms",
]


def aggregate(results: List) -> Dict[str, Dict[str, float]]:
    """Mean/stdev per metric across repeated runs of one configuration
    (reference aggregate.py `Setup`/`Result.aggregate`)."""
    out: Dict[str, Dict[str, float]] = {}
    for m in METRICS:
        vals = [getattr(r, m) for r in results]
        out[m] = {
            "mean": round(statistics.mean(vals), 1),
            "stdev": round(statistics.stdev(vals), 1) if len(vals) > 1 else 0.0,
            "runs": [round(v, 1) for v in vals],
        }
    return out


def sweep(
    rates: List[int],
    runs: int,
    **bench_kwargs,
) -> List[Dict]:
    """Latency-vs-throughput curve: one aggregated point per input rate."""
    points = []
    for rate in rates:
        results = [
            run_bench(rate=rate, quiet=True, **bench_kwargs)
            for _ in range(runs)
        ]
        errors = [e for r in results for e in r.errors]
        point = {"rate": rate, **aggregate(results)}
        if errors:
            point["errors"] = errors[:5]
        points.append(point)
        print(json.dumps(point))
    return points


def table(points: List[Dict]) -> str:
    """Human-readable latency-vs-throughput table (the plot's data)."""
    lines = [
        f"{'rate':>8} | {'e2e tps':>9} ± {'sd':>6} | {'e2e lat ms':>10} | "
        f"{'cons tps':>9} | {'cons lat ms':>11}",
        "-" * 64,
    ]
    for p in points:
        lines.append(
            f"{p['rate']:>8,} | {p['end_to_end_tps']['mean']:>9,.0f} ± "
            f"{p['end_to_end_tps']['stdev']:>6,.0f} | "
            f"{p['end_to_end_latency_ms']['mean']:>10,.0f} | "
            f"{p['consensus_tps']['mean']:>9,.0f} | "
            f"{p['consensus_latency_ms']['mean']:>11,.0f}"
        )
    return "\n".join(lines)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rates", type=int, nargs="+", required=True)
    ap.add_argument("--runs", type=int, default=2)
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--workers", type=int, default=1)
    ap.add_argument("--duration", type=int, default=20)
    ap.add_argument("--tx-size", type=int, default=512)
    ap.add_argument("--faults", type=int, default=0)
    ap.add_argument("--batch-size", type=int, default=500_000)
    ap.add_argument("--base-port", type=int, default=7800)
    ap.add_argument("--out", type=str, default=None)
    args = ap.parse_args()

    points = sweep(
        args.rates,
        args.runs,
        nodes=args.nodes,
        workers=args.workers,
        duration=args.duration,
        tx_size=args.tx_size,
        faults=args.faults,
        batch_size=args.batch_size,
        base_port=args.base_port,
    )
    print(table(points))
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(
                {
                    "config": {
                        "nodes": args.nodes,
                        "workers": args.workers,
                        "faults": args.faults,
                        "tx_size": args.tx_size,
                        "duration": args.duration,
                        "runs_per_rate": args.runs,
                        "batch_size": args.batch_size,
                    },
                    "points": points,
                },
                f,
                indent=2,
            )


if __name__ == "__main__":
    main()
