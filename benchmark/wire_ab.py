"""Paired interleaved wire-format A/B: legacy (NARWHAL_WIRE_V2=0) vs v2.

The ROADMAP item 5 acceptance is purely ledger-read: goodput_ratio and
per-type bytes/frame before vs after at equal committed TPS, with
``sender_coverage ≈ 1.0`` and ``protocol_check`` inside its 5% gate on
BOTH arms (the wire format must change bytes, never protocol
arithmetic).  Arms are interleaved (legacy, v2, legacy, v2, ...) so
slow host drift hits both equally — the r09/r10 A/B convention.

    python benchmark/wire_ab.py --pairs 2 --duration 8 \
        --artifact artifacts/wire_v2_r18.json

Artifact shape: ``{"runs": [v2 bench results], "legacy_runs": [...],
"summary": {...}}`` — ``runs`` carries only the v2 arm so
benchmark/trajectory.py's median-of-runs loader reads this artifact as
the v2 series point; the legacy arm rides under a key the loader
ignores.  Exit status 1 when any run errored or the paired gates fail
(goodput >= --min-goodput on v2, committed TPS no worse than
--tps-tolerance below legacy, coverage/protocol checks on both arms).
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmark.local_bench import run_bench  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _one_run(arm: str, idx: int, args) -> dict:
    result = run_bench(
        nodes=args.nodes,
        workers=1,
        rate=args.rate,
        tx_size=args.tx_size,
        duration=args.duration,
        base_port=args.base_port,
        workdir=os.path.join(REPO, ".bench_wire_ab"),
        quiet=True,
        progress_wait=args.progress_wait,
        wire_v2=(arm == "v2"),
    )
    wire = result.wire or {}
    return {
        "arm": arm,
        "run": idx,
        "errors": result.errors,
        "consensus_tps": result.consensus_tps,
        "consensus_latency_ms": result.consensus_latency_ms,
        "end_to_end_tps": result.end_to_end_tps,
        "end_to_end_latency_ms": result.end_to_end_latency_ms,
        "committed_bytes": result.committed_bytes,
        "committed_batches": result.committed_batches,
        "wire": wire,
        "crypto": {
            "protocol_check": (result.crypto or {}).get("protocol_check")
        },
    }


def _per_type_frame_bytes(wire: dict) -> dict:
    out = {}
    for t, d in (wire.get("out") or {}).items():
        if d.get("frames"):
            out[t] = {
                "frames": d["frames"],
                "bytes_per_frame": round(d["bytes"] / d["frames"], 1),
                "raw_bytes_per_frame": round(
                    (d.get("raw_bytes") or d["bytes"]) / d["frames"], 1
                ),
            }
    return out


def _median(runs, key, default=0.0):
    vals = [r.get(key) or 0.0 for r in runs]
    return statistics.median(vals) if vals else default


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--pairs", type=int, default=2)
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--rate", type=int, default=2_000)
    ap.add_argument("--tx-size", type=int, default=512)
    ap.add_argument("--duration", type=int, default=8)
    ap.add_argument("--base-port", type=int, default=7800)
    ap.add_argument("--progress-wait", type=float, default=30.0)
    ap.add_argument("--min-goodput", type=float, default=0.45)
    ap.add_argument(
        "--tps-tolerance", type=float, default=0.25,
        help="v2 median committed TPS may be at most this fraction below "
        "the legacy arm's (shared-core hosts swing; equal-or-better is "
        "the claim, this is the noise floor)",
    )
    ap.add_argument("--artifact", default="artifacts/wire_v2_r18.json")
    args = ap.parse_args(argv)

    runs_v2, runs_legacy = [], []
    for i in range(args.pairs):
        for arm, into in (("legacy", runs_legacy), ("v2", runs_v2)):
            print(f"== wire A/B pair {i + 1}/{args.pairs}: {arm} arm ==")
            r = _one_run(arm, i, args)
            into.append(r)
            print(
                f"   committed TPS {r['consensus_tps']:,.0f}, goodput "
                f"{r['wire'].get('goodput_ratio')}, coverage "
                f"{(r['wire'].get('totals') or {}).get('sender_coverage')}"
            )

    failures = []
    for r in runs_v2 + runs_legacy:
        if r["errors"]:
            failures.append(f"{r['arm']} run {r['run']}: {r['errors'][:3]}")
        cov = (r["wire"].get("totals") or {}).get("sender_coverage")
        if cov is None or abs(cov - 1.0) > 0.02:
            failures.append(
                f"{r['arm']} run {r['run']}: sender_coverage {cov}"
            )
        check = (r["crypto"] or {}).get("protocol_check") or {}
        for kind in ("votes", "certificates"):
            ratio = (check.get(kind) or {}).get("ratio")
            if ratio is None or abs(ratio - 1.0) > 0.05:
                failures.append(
                    f"{r['arm']} run {r['run']}: protocol_check.{kind} "
                    f"ratio {ratio}"
                )

    g_legacy = _median(
        [r["wire"] for r in runs_legacy], "goodput_ratio"
    )
    g_v2 = _median([r["wire"] for r in runs_v2], "goodput_ratio")
    tps_legacy = _median(runs_legacy, "consensus_tps")
    tps_v2 = _median(runs_v2, "consensus_tps")
    if g_v2 < args.min_goodput:
        failures.append(
            f"v2 median goodput {g_v2} < required {args.min_goodput}"
        )
    if tps_legacy and tps_v2 < tps_legacy * (1 - args.tps_tolerance):
        failures.append(
            f"v2 median committed TPS {tps_v2:,.0f} more than "
            f"{args.tps_tolerance:.0%} below legacy {tps_legacy:,.0f}"
        )

    mid_v2 = sorted(runs_v2, key=lambda r: r["consensus_tps"])[
        len(runs_v2) // 2
    ]
    mid_legacy = sorted(runs_legacy, key=lambda r: r["consensus_tps"])[
        len(runs_legacy) // 2
    ]
    summary = {
        "goodput_ratio": {"legacy": g_legacy, "v2": g_v2},
        "consensus_tps": {"legacy": tps_legacy, "v2": tps_v2},
        "compression_ratio_v2": mid_v2["wire"].get("compression_ratio"),
        "frames_per_flush_mean_v2": mid_v2["wire"].get(
            "frames_per_flush_mean"
        ),
        "acks_per_flush_mean_v2": mid_v2["wire"].get("acks_per_flush_mean"),
        "per_type_frame_bytes": {
            "legacy": _per_type_frame_bytes(mid_legacy["wire"]),
            "v2": _per_type_frame_bytes(mid_v2["wire"]),
        },
        "gates_failed": failures,
    }

    artifact = {
        "what": (
            "Paired interleaved wire-format A/B (ISSUE 13): legacy "
            "NARWHAL_WIRE_V2=0 vs v2 on a "
            f"{args.nodes}-node local_bench, rate {args.rate}, "
            f"{args.tx_size} B tx, {args.duration} s windows. `runs` is "
            "the v2 arm (what the trajectory series reads); the legacy "
            "arm is `legacy_runs`."
        ),
        "runs": runs_v2,
        "legacy_runs": runs_legacy,
        "summary": summary,
    }
    os.makedirs(os.path.dirname(args.artifact) or ".", exist_ok=True)
    with open(args.artifact, "w") as f:
        json.dump(artifact, f, indent=1)

    print("== wire A/B summary ==")
    print(json.dumps(summary, indent=1))
    if failures:
        print(f"wire A/B FAILED: {failures}", file=sys.stderr)
        return 1
    print(
        f"wire A/B ok: goodput {g_legacy} -> {g_v2} at committed TPS "
        f"{tps_legacy:,.0f} -> {tps_v2:,.0f}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
