"""One task surface for the whole benchmark harness.

The reference drives everything through Fabric tasks (`fab local`, `fab
remote`, `fab plot`, `fab kill`, `fab logs` — reference
benchmark/fabfile.py:12-135).  Same surface here as a plain argparse
dispatcher over the existing modules:

    python -m benchmark.tasks local --nodes 4 --rate 50000 --duration 25
    python -m benchmark.tasks remote --settings benchmark/settings.example.json
    python -m benchmark.tasks aggregate --rates 25000 56000 90000 --out s.json
    python -m benchmark.tasks plot artifacts/sweep.json --out curve.png
    python -m benchmark.tasks kill [--hosts ssh://... local:...]
    python -m benchmark.tasks logs .bench --tx-size 512

`install` exists as an explicit task too (remote runs it implicitly unless
--no-install is passed).
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _task_kill(argv) -> int:
    """Kill leftover node/client processes: local ones scoped to this
    checkout, and (with --hosts) remote ones via the runners' pid files."""
    import argparse

    ap = argparse.ArgumentParser(prog="tasks.py kill")
    ap.add_argument("--hosts", nargs="*", default=[])
    args = ap.parse_args(argv)
    from benchmark.local_bench import kill_stale_nodes
    from benchmark.remote_bench import make_runner, kill_ours

    kill_stale_nodes()
    for spec in args.hosts:
        kill_ours(make_runner(spec), sig=9, clear_pidfile=True)
    print("killed stale nodes")
    return 0


def _task_logs(argv) -> int:
    """Parse an existing log directory (primary-*/worker-*/client-*.log)
    and print the summary — the reference's `fab logs`."""
    import argparse
    import glob

    ap = argparse.ArgumentParser(prog="tasks.py logs")
    ap.add_argument("logdir")
    ap.add_argument("--tx-size", type=int, default=512)
    args = ap.parse_args(argv)
    from benchmark.logs import parse_logs

    read = lambda pat: [  # noqa: E731
        open(p).read() for p in sorted(glob.glob(os.path.join(args.logdir, pat)))
    ]
    clients, workers, primaries = (
        read("client-*.log"), read("worker-*.log"), read("primary-*.log"),
    )
    if not (clients or workers or primaries):
        # A typo'd directory must not read as a successful parse of a run
        # that committed nothing.
        print(f"no *-N.log files found in {args.logdir!r}", file=sys.stderr)
        return 2
    result = parse_logs(clients, workers, primaries, args.tx_size)
    if result.errors:
        print("ERRORS detected in logs:", file=sys.stderr)
        for e in result.errors[:10]:
            print("  " + e, file=sys.stderr)
    print(result.summary(0, args.tx_size, 0, 0))
    return 1 if result.errors else 0


def _task_install(argv) -> int:
    """rsync this checkout to each ssh:// host and build its native lib."""
    import argparse

    ap = argparse.ArgumentParser(prog="tasks.py install")
    ap.add_argument("--hosts", nargs="+", required=True)
    args = ap.parse_args(argv)
    from benchmark.remote_bench import make_runner

    for spec in args.hosts:
        make_runner(spec).install()
        print(f"installed on {spec}")
    return 0


def main() -> int:
    tasks = {
        "local": lambda argv: _delegate("benchmark.local_bench", argv),
        "remote": lambda argv: _delegate("benchmark.remote_bench", argv),
        "aggregate": lambda argv: _delegate("benchmark.aggregate", argv),
        "plot": lambda argv: _delegate("benchmark.plot", argv),
        "kill": _task_kill,
        "logs": _task_logs,
        "install": _task_install,
    }
    if len(sys.argv) < 2 or sys.argv[1] in ("-h", "--help"):
        print(__doc__)
        print("tasks:", ", ".join(sorted(tasks)))
        return 0
    name, argv = sys.argv[1], sys.argv[2:]
    if name not in tasks:
        print(f"unknown task {name!r}; tasks: {', '.join(sorted(tasks))}",
              file=sys.stderr)
        return 2
    return tasks[name](argv) or 0


def _delegate(module: str, argv) -> int:
    import importlib

    mod = importlib.import_module(module)
    sys.argv = [module] + list(argv)
    rc = mod.main()
    return rc or 0


if __name__ == "__main__":
    sys.exit(main())
