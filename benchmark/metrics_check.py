"""Cross-validate the log-scraped bench numbers against node metrics.

The log parser (benchmark/logs.py) and the metrics registry
(narwhal_tpu/metrics.py) measure the same run through two independent
channels: regex over four INFO lines vs in-process counters and the
per-digest stage-trace table.  Agreement within tolerance is the check
that neither channel silently lost data — round 5 published a number a
flooded queue had quietly corrupted, and nothing cross-checked it
(VERDICT.md §1).  Disagreement beyond tolerance hard-fails the run (an
error entry, which every harness treats as fatal).

The same per-digest trace join also yields the per-stage pipeline latency
breakdown (batch-sealed → quorum → digest-at-primary → header →
certificate → commit): each process stamps wall-clock times for the
stages it owns.  On one host the stamps join directly; across hosts (or
a deliberately skewed harness) each node's stamps are first shifted by
its reconciled clock correction — the zero-mean offset vector estimated
from ReliableSender ACK round-trips (narwhal_tpu/network/clocksync.py)
and carried in every snapshot's ``clock.offset_ms.*`` gauges — so the
cross-node legs measure causality, not whose NTP daemon drifted.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, List, Optional, Tuple

# Causal stage order: the registry's definition IS the source of truth
# (a hand-copied tuple here would silently drop any future stage from
# the breakdown).
from narwhal_tpu.crypto.aggregate import (
    SCHEMES as CERT_SIG_SCHEMES,
    cert_sig_wire_bytes,
)
from narwhal_tpu.metrics import ROUND_STAGES, STAGES as STAGE_ORDER
from narwhal_tpu.network import clocksync

STAGE_LEGS: Tuple[Tuple[str, str], ...] = tuple(
    zip(STAGE_ORDER[:-1], STAGE_ORDER[1:])
)

ROUND_LEGS: Tuple[Tuple[str, str], ...] = tuple(
    zip(ROUND_STAGES[:-1], ROUND_STAGES[1:])
)


def load_snapshots(paths: List[str], errors: List[str]) -> List[dict]:
    """Load metric snapshot files, reporting (not raising on) missing or
    torn ones — the writer's atomic rewrite makes torn files a real bug,
    so they land in `errors`, but a node that died pre-boot simply has no
    file and must not mask the log-side numbers."""
    snaps = []
    for path in paths:
        if not os.path.exists(path):
            errors.append(f"metrics snapshot missing: {os.path.basename(path)}")
            continue
        try:
            with open(path) as f:
                snaps.append(json.load(f))
        except (OSError, ValueError) as e:
            errors.append(
                f"metrics snapshot unreadable: {os.path.basename(path)}: {e}"
            )
    return snaps


def loop_stall_summary(snapshots: List[dict]) -> Dict[str, dict]:
    """Per-node event-loop stall series for the bench JSON `runtime`
    section (populated when the committee ran with
    NARWHAL_LOOP_WATCHDOG_MS set — the loop-watchdog smoke arm).  Keyed
    by node pid; a node whose snapshot carries the histogram at count 0
    still appears, which is the point: "the watchdog ran and saw no
    stall" is a measurement, not an absence."""
    out: Dict[str, dict] = {}
    for snap in snapshots:
        hist = (snap.get("histograms") or {}).get("runtime.loop_stall_seconds")
        if hist is None:
            continue
        last = dict(
            (snap.get("detail") or {}).get("runtime.loop_stall_last") or {}
        )
        if "stack" in last:
            last["stack"] = str(last["stack"])[:2000]
        out[str(snap.get("pid", len(out)))] = {
            "loop_stall_seconds": {
                "count": int(hist.get("count", 0)),
                "sum_s": round(float(hist.get("sum", 0.0)), 4),
                "mean_s": round(float(hist.get("mean", 0.0)), 4),
                "buckets": hist.get("buckets", []),
            },
            "stalls": int(
                (snap.get("counters") or {}).get("runtime.loop_stalls", 0)
            ),
            "last_stall": last,
        }
    return out


# -- clock-offset correction --------------------------------------------------

_CLOCK_OFFSET_PREFIX = "clock.offset_ms."
_CLOCK_UNC_PREFIX = "clock.offset_uncertainty_ms."


def snapshot_correction_ms(snap: dict) -> float:
    """One node's reconciled wall-clock correction, from its own
    ``clock.offset_ms.*`` gauges.  Subtracting ``correction/1000`` from
    the node's stamps places them on the committee's mean clock; 0.0
    when the snapshot carries no offset gauges (pre-clocksync snapshot,
    or a node that never completed an ACK round trip), which degrades to
    the old uncorrected join rather than failing."""
    gauges = snap.get("gauges") or {}
    peers = {
        name[len(_CLOCK_OFFSET_PREFIX):]: float(v)
        for name, v in gauges.items()
        if name.startswith(_CLOCK_OFFSET_PREFIX) and v is not None
    }
    if not peers:
        return 0.0
    return clocksync.reconcile_zero_mean({"self": peers})["self"]


def clock_summary(snapshots: List[dict]) -> dict:
    """Per-node clock section for the bench JSON: the raw per-peer
    offset gauges, the reconciled correction the stage join applies, and
    the worst per-peer uncertainty bound (RTT/2 of the best sample) —
    the error bar on every cross-node leg below."""
    nodes: Dict[str, dict] = {}
    for snap in snapshots:
        if not snap.get("enabled", True):
            continue
        gauges = snap.get("gauges") or {}
        peers = {
            name[len(_CLOCK_OFFSET_PREFIX):]: round(float(v), 3)
            for name, v in gauges.items()
            if name.startswith(_CLOCK_OFFSET_PREFIX) and v is not None
        }
        if not peers:
            continue
        unc = [
            float(v)
            for name, v in gauges.items()
            if name.startswith(_CLOCK_UNC_PREFIX) and v is not None
        ]
        key = str(snap.get("pid") or snap.get("node") or len(nodes))
        nodes[key] = {
            "correction_ms": round(snapshot_correction_ms(snap), 3),
            "peer_offsets_ms": dict(sorted(peers.items())),
            "max_uncertainty_ms": round(max(unc), 3) if unc else None,
        }
    return nodes


def corrected_stage_join(
    snapshots: List[dict],
) -> Tuple[Dict[str, Dict[str, float]], Dict[str, int]]:
    """Join per-digest stage stamps across node snapshots, each node's
    stamps shifted onto the committee mean clock by its reconciled
    correction.  Earliest corrected timestamp wins per (digest, stage) —
    the same convention the log parser uses across primaries.  Returns
    (stage_ts, seal_bytes)."""
    stage_ts: Dict[str, Dict[str, float]] = {}
    seal_bytes: Dict[str, int] = {}
    for snap in snapshots:
        if not snap.get("enabled", True):
            continue
        corr_s = snapshot_correction_ms(snap) / 1000.0
        for digest, entry in snap.get("trace", {}).items():
            dst = stage_ts.setdefault(digest, {})
            for stage in STAGE_ORDER:
                t = entry.get(stage)
                if t is None:
                    continue
                t = t - corr_s
                if stage not in dst or t < dst[stage]:
                    dst[stage] = t
            b = entry.get("bytes")
            if b:
                seal_bytes.setdefault(digest, int(b))
    return stage_ts, seal_bytes


def critical_path_summary(
    stage_ts: Dict[str, Dict[str, float]], top_k: int = 3
) -> dict:
    """The slowest end-to-end causal chain through the pipeline: among
    digests carrying the full stage chain, the one with the largest
    seal→commit span, decomposed into consecutive-stage legs.  The legs
    TELESCOPE — their sum is exactly the e2e span by construction — so
    ``legs_sum_ms`` vs ``e2e_ms`` is a self-check on the join, not new
    information (the CI smoke gates on it anyway: a big gap means a
    stage was dropped from STAGE_ORDER or stamped on a different clock).
    ``slowest`` lists the top-k chains; ``path`` is the worst one."""
    chains = []
    for digest, st in stage_ts.items():
        if all(s in st for s in STAGE_ORDER):
            chains.append((st["commit"] - st["seal"], digest, st))
    chains.sort(key=lambda c: -c[0])
    out: dict = {"full_chains": len(chains)}
    slowest = []
    for e2e, digest, st in chains[:top_k]:
        legs = {
            f"{a}_to_{b}": round(1000 * (st[b] - st[a]), 3)
            for a, b in STAGE_LEGS
        }
        slowest.append(
            {
                "digest": digest,
                "e2e_ms": round(1000 * e2e, 3),
                "legs_ms": legs,
                "legs_sum_ms": round(sum(legs.values()), 3),
            }
        )
    if slowest:
        out["path"] = slowest[0]
        out["slowest"] = slowest
    return out


# -- quorum-straggler attribution ---------------------------------------------

_STRAGGLER_FAMILIES: Tuple[Tuple[str, str], ...] = (
    ("vote_quorum", "primary.quorum_straggler."),
    ("support_quorum", "consensus.support_straggler."),
)

_GAP_HISTOGRAMS: Tuple[Tuple[str, str], ...] = (
    ("vote_quorum_gap_ms", "primary.vote_quorum_gap_ms"),
    ("parent_quorum_gap_ms", "primary.parent_quorum_gap_ms"),
    ("support_arrival_ms", "consensus.support_arrival_ms"),
)


def quorum_straggler_summary(snapshots: List[dict]) -> dict:
    """Ranked who-closed-the-quorum table for the bench JSON: per
    quorum family, the authorities (by primary address) charged with
    arriving last when the quorum crossed, most-charged first, plus the
    mean first-arrival→quorum gap histograms.  A consistently-top
    authority is the committee's straggler — the node whose latency the
    quorum waits out — which is attribution the aggregate histograms
    alone cannot give."""
    counters = _agg_counters(snapshots)
    hists = _agg_histograms(snapshots)
    out: dict = {}
    for family, prefix in _STRAGGLER_FAMILIES:
        ranked = sorted(
            (
                {"address": name[len(prefix):], "count": int(v)}
                for name, v in counters.items()
                if name.startswith(prefix) and v
            ),
            key=lambda e: (-e["count"], e["address"]),
        )
        if ranked:
            out[family] = ranked
    gaps: Dict[str, dict] = {}
    for label, name in _GAP_HISTOGRAMS:
        s, c = hists.get(name, (0.0, 0))
        if c:
            gaps[label] = {"count": int(c), "mean": round(s / c, 3)}
    if gaps:
        out["gaps"] = gaps
    return out


def cross_validate(
    result,
    snapshots: List[dict],
    tx_size: int,
    tolerance: float = 0.05,
) -> dict:
    """Join stage traces across node snapshots; fill ``result``'s
    metrics fields and append a fatal error on >tolerance disagreement
    between the metrics-derived and log-scraped committed-tx totals.

    Returns the summary dict the bench JSON embeds.
    """
    # Trace-table evictions mean the stage join below is UNDER-JOINED:
    # evicted digests stamped early in the run are invisible, so the
    # breakdown is biased toward the run's tail and the metrics-side
    # committed-bytes total undercounts.  Warn loudly and annotate the
    # result instead of silently computing a biased answer.
    evictions = sum(
        int(snap.get("gauges", {}).get("metrics.trace_evictions") or 0)
        for snap in snapshots
        if snap.get("enabled", True)
    )
    if evictions > 0:
        print(
            "WARNING: stage-trace tables UNDER-JOINED — "
            f"{evictions} digest(s) evicted past NARWHAL_TRACE_CAP; "
            "the stages_ms breakdown and metrics committed-tx total are "
            "biased toward the run's tail (raise NARWHAL_TRACE_CAP or "
            "shorten the run)",
            file=sys.stderr,
        )

    # Skew-corrected earliest timestamp per (digest, stage) across every
    # snapshot — each node's stamps shifted by its reconciled offset
    # before the min-join (see corrected_stage_join).
    stage_ts, seal_bytes = corrected_stage_join(snapshots)

    committed = [d for d, st in stage_ts.items() if "commit" in st]
    metrics_bytes = sum(seal_bytes.get(d, 0) for d in committed)
    result.metrics_committed_tx = metrics_bytes / tx_size

    disagreement: Optional[float] = None
    log_tx = result.committed_bytes / tx_size
    if log_tx > 0:
        disagreement = abs(result.metrics_committed_tx - log_tx) / log_tx
        result.metrics_disagreement = disagreement
        if disagreement > tolerance:
            result.errors.append(
                "metrics cross-check FAILED: log-scraped "
                f"{log_tx:.0f} committed tx vs metrics-derived "
                f"{result.metrics_committed_tx:.0f} "
                f"({100 * disagreement:.1f}% > {100 * tolerance:.0f}% "
                "tolerance) — one measurement channel lost data"
            )
    elif committed:
        result.errors.append(
            "metrics cross-check FAILED: metrics snapshots show "
            f"{len(committed)} committed batches but the log scrape "
            "found none"
        )

    # Per-stage latency breakdown over digests carrying the full chain
    # (own-batch traces: sealed, quorum'd, proposed, certified at the
    # same authority, commit joined committee-wide).  cert→commit is now
    # subdivided (cert_inserted / commit_trigger / walk_done sub-stages),
    # but the aggregate leg stays in the output: it is the number every
    # prior artifact tracks (metrics_stage_breakdown_r07.json) and the
    # one the r09 acceptance gate compares.
    legs: Dict[str, List[float]] = {
        f"{a}_to_{b}": [] for a, b in STAGE_LEGS
    }
    cert_commit: List[float] = []
    totals: List[float] = []
    for st in stage_ts.values():
        if all(s in st for s in STAGE_ORDER):
            for a, b in STAGE_LEGS:
                legs[f"{a}_to_{b}"].append(st[b] - st[a])
            cert_commit.append(st["commit"] - st["cert"])
            totals.append(st["commit"] - st["seal"])
    if totals:
        result.stages_ms = {
            name: round(1000 * sum(v) / len(v), 2)
            for name, v in legs.items()
            if v
        }
        result.stages_ms["cert_to_commit"] = round(
            1000 * sum(cert_commit) / len(cert_commit), 2
        )
        result.stages_ms["seal_to_commit"] = round(
            1000 * sum(totals) / len(totals), 2
        )
    if evictions > 0:
        # In-band annotation next to the numbers the evictions bias.
        result.stages_ms["trace_evictions"] = float(evictions)

    # Round-cadence attribution: the per-round sub-stage legs that
    # decompose `primary.round_advance_seconds` the way the sub-stages
    # above decompose cert→commit.
    round_attr = round_attribution(snapshots)
    result.round_stages_ms = dict(round_attr.get("round_stages_ms", {}))

    return {
        "stages_ms": dict(result.stages_ms),
        "traced_full_chain": len(totals),
        "trace_evictions": evictions,
        "metrics_committed_tx": round(result.metrics_committed_tx, 1),
        "log_committed_tx": round(log_tx, 1),
        "disagreement": (
            round(disagreement, 4) if disagreement is not None else None
        ),
        "round_attribution": round_attr,
        "clock": clock_summary(snapshots),
        "critical_path": critical_path_summary(stage_ts),
        "stragglers": quorum_straggler_summary(snapshots),
    }


def round_attribution(snapshots: List[dict]) -> dict:
    """Decompose the round period from the per-round cadence traces.

    Each primary stamps ROUND_STAGES per round of its own header
    lifecycle (header_proposed → … → round_advance).  Unlike the digest
    trace these are NOT joined across nodes — every primary runs its own
    cadence loop — so legs aggregate over (node, round) pairs.  The
    leading ``advance_to_header_proposed`` leg (previous round's advance
    to this round's mint — the proposer's min/max-header-delay wait) is
    derived here, which makes the legs TELESCOPE: their sum for round r
    is exactly round_advance(r) − round_advance(r−1), the round period.
    Negative legs are meaningful — they show pipeline overlap (e.g. a
    parent quorum completing before our own certificate assembled).

    The independent cross-check is the ``primary.round_advance_seconds``
    histogram (stamped by the Proposer, not the trace): the mean of the
    telescoped per-round sums must agree with the histogram mean — a
    >10% gap means the trace is under-joined or a stage is mis-stamped,
    and is warned about loudly (bench gate material, not a run failure:
    the histogram also covers boot/tail rounds the trace join drops).
    """
    legs: Dict[str, List[float]] = {
        "advance_to_header_proposed": [],
        **{f"{a}_to_{b}": [] for a, b in ROUND_LEGS},
    }
    periods: List[float] = []
    hist_sum, hist_count = 0.0, 0
    sa_sum, sa_count = 0.0, 0
    for snap in snapshots:
        if not snap.get("enabled", True):
            continue
        h = (snap.get("histograms") or {}).get(
            "primary.round_advance_seconds"
        )
        if h and h.get("count"):
            hist_sum += h["sum"]
            hist_count += h["count"]
        sa = (snap.get("histograms") or {}).get(
            "consensus.support_arrival_ms"
        )
        if sa and sa.get("count"):
            sa_sum += sa["sum"]
            sa_count += sa["count"]
        entries: Dict[int, dict] = {}
        for key, st in (snap.get("round_trace") or {}).items():
            try:
                entries[int(key)] = st
            except (TypeError, ValueError):
                continue
        for r in sorted(entries):
            st = entries[r]
            prev = entries.get(r - 1)
            if prev is None or "round_advance" not in prev:
                continue  # no anchor for the leading leg (e.g. round 1)
            if any(s not in st for s in ROUND_STAGES):
                continue  # partial round (boot/tail) — can't telescope
            legs["advance_to_header_proposed"].append(
                st["header_proposed"] - prev["round_advance"]
            )
            for a, b in ROUND_LEGS:
                legs[f"{a}_to_{b}"].append(st[b] - st[a])
            periods.append(st["round_advance"] - prev["round_advance"])

    out: dict = {"rounds_joined": len(periods)}
    if periods:
        out["round_stages_ms"] = {
            name: round(1000 * sum(v) / len(v), 3)
            for name, v in legs.items()
            if v
        }
        out["round_period_ms"] = round(
            1000 * sum(periods) / len(periods), 3
        )
        # Telescoping makes sum(legs) == period per round by construction;
        # keep the redundant sum in the artifact as a self-check anyway.
        out["stage_sum_ms"] = round(
            1000 * sum(sum(v) for v in legs.values()) / len(periods), 3
        )
    if hist_count:
        out["round_advance_hist_ms"] = round(
            1000 * hist_sum / hist_count, 3
        )
        if periods:
            measured = out["round_advance_hist_ms"]
            if measured > 0:
                gap = abs(out["stage_sum_ms"] - measured) / measured
                out["stage_sum_vs_hist"] = round(gap, 4)
                if gap > 0.10:
                    print(
                        "WARNING: round-cadence sub-stages sum to "
                        f"{out['stage_sum_ms']:.1f} ms but the "
                        "round_advance_seconds histogram measured "
                        f"{measured:.1f} ms ({100 * gap:.1f}% apart) — "
                        "the round trace is under-joined or a stage is "
                        "mis-stamped",
                        file=sys.stderr,
                    )
    if sa_count:
        # Support-arrival spread (consensus side of the cadence story):
        # per committed-path leader, first direct supporter → the 2f+1
        # quorum-crossing arrival.  The gap between this and the round
        # period bounds what a lower-depth commit rule can save.
        out["support_arrival_ms"] = {
            "leaders": sa_count,
            "mean": round(sa_sum / sa_count, 3),
        }
    return out


# -- wire-goodput & crypto-cost ledger joins ----------------------------------

# An ed25519-signed vote inside a certificate costs a key ref (32 B
# raw key, ~1 B committee index under wire v2) + 64 B signature on the
# wire; the embedded header adds one more 64 B signature; under the
# halfagg scheme the per-vote signatures collapse to one 32·(q+1) B
# aggregate blob.  Certificates carry exactly quorum_threshold votes
# (the VotesAggregator assembles at quorum and stops), so the signature
# bytes of a cert frame are a pure function of committee size, wire
# format, and cert-sig scheme — all three are read from node gauges and
# fed to crypto.aggregate.cert_sig_wire_bytes rather than hardcoded
# here.  The fraction is computed against the RAW (pre-compression)
# cert frame size in both formats, so it keeps measuring frame anatomy,
# not deflate luck.


def _agg_counters(snapshots: List[dict]) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for snap in snapshots:
        if not snap.get("enabled", True):
            continue
        for name, v in (snap.get("counters") or {}).items():
            out[name] = out.get(name, 0) + (v or 0)
    return out


def _agg_histograms(snapshots: List[dict]) -> Dict[str, Tuple[float, int]]:
    """name -> (sum, count) across snapshots."""
    out: Dict[str, Tuple[float, int]] = {}
    for snap in snapshots:
        if not snap.get("enabled", True):
            continue
        for name, h in (snap.get("histograms") or {}).items():
            if not isinstance(h, dict):
                continue
            s, c = out.get(name, (0.0, 0))
            out[name] = (s + (h.get("sum") or 0.0), c + (h.get("count") or 0))
    return out


# -- queue & backpressure accounting ------------------------------------------

def queue_pressure_summary(
    snapshots: List[dict],
    samples: Optional[List[dict]] = None,
    saturation_ratio: float = 0.8,
) -> dict:
    """Join the per-channel ``queue.<channel>.*`` series (emitted by
    ``metrics.InstrumentedQueue``) into the bench JSON ``queues``
    section.

    ``nodes`` keys each process (by snapshot pid) to its channel table —
    capacity, final depth, high-water, enqueue/dequeue/QueueFull totals,
    mean blocked-put wait and mean queue residence.  ``channels``
    aggregates committee-wide (max high-water/utilization, summed
    counters).  ``first_saturating`` is the knee attribution: with the
    scraper's 1 Hz ``samples`` timeline it names the channel whose depth
    first crossed ``saturation_ratio`` of capacity and WHEN; without a
    timeline it falls back to the channel with the highest end-of-run
    high-water utilization, PROVIDED that utilization itself crossed
    ``saturation_ratio`` — an unsaturated run honestly reports no
    attribution rather than electing whichever channel happened to sit
    deepest.  Unbounded channels (capacity 0) never saturate and are
    reported without a utilization.  Narrow pipeline windows like
    ``worker.to_quorum`` (capacity = QUORUM_WINDOW) are deliberately
    NOT excluded here, unlike in the queue_saturated health rule: the
    admission window pegging at capacity while the wide channels stay
    empty IS a knee explanation (backpressure propagated upstream of
    the node), and the health rule's min-capacity floor exists only to
    keep steady-state alerts quiet."""
    per_node: Dict[str, dict] = {}
    for snap in snapshots:
        if not snap.get("enabled", True):
            continue
        gauges = snap.get("gauges") or {}
        counters = snap.get("counters") or {}
        hists = snap.get("histograms") or {}
        channels: Dict[str, dict] = {}
        for name, depth in gauges.items():
            if not (name.startswith("queue.") and name.endswith(".depth")):
                continue
            ch = name[len("queue."):-len(".depth")]
            base = f"queue.{ch}."
            cap = float(gauges.get(base + "capacity") or 0)
            hw = float(gauges.get(base + "high_water") or 0)
            entry = {
                "capacity": int(cap),
                "depth": int(depth or 0),
                "high_water": int(hw),
                "enqueued": int(counters.get(base + "enqueued") or 0),
                "dequeued": int(counters.get(base + "dequeued") or 0),
                "full": int(counters.get(base + "full") or 0),
            }
            if cap > 0:
                entry["utilization"] = round(hw / cap, 4)
            pw = hists.get(base + "put_wait_seconds") or {}
            if pw.get("count"):
                entry["put_waits"] = int(pw["count"])
                entry["put_wait_ms_mean"] = round(
                    1000 * pw["sum"] / pw["count"], 3
                )
            res = hists.get(base + "residence_seconds") or {}
            if res.get("count"):
                entry["residence_ms_mean"] = round(
                    1000 * res["sum"] / res["count"], 3
                )
            channels[ch] = entry
        if channels:
            # Final snapshot files carry a pid; scraped samples (the
            # remote harness's snapshot proxy) carry the node name.
            key = snap.get("pid") or snap.get("node") or len(per_node)
            per_node[str(key)] = channels

    agg: Dict[str, dict] = {}
    for channels in per_node.values():
        for ch, e in channels.items():
            a = agg.setdefault(
                ch,
                {
                    "capacity": 0, "high_water": 0,
                    "enqueued": 0, "dequeued": 0, "full": 0,
                },
            )
            a["capacity"] = max(a["capacity"], e["capacity"])
            a["high_water"] = max(a["high_water"], e["high_water"])
            for k in ("enqueued", "dequeued", "full"):
                a[k] += e[k]
            if "utilization" in e:
                a["utilization"] = max(
                    a.get("utilization", 0.0), e["utilization"]
                )

    out: dict = {"nodes": per_node, "channels": agg}

    first: Optional[Tuple[float, str, float]] = None
    t0: Optional[float] = None
    for s in samples or ():
        t = s.get("t")
        g = s.get("gauges") or {}
        if t is None:
            continue
        if t0 is None or t < t0:
            t0 = float(t)
        for name, depth in g.items():
            if not (name.startswith("queue.") and name.endswith(".depth")):
                continue
            ch = name[len("queue."):-len(".depth")]
            cap = g.get(f"queue.{ch}.capacity") or 0
            if not cap or not depth:
                continue
            if depth >= saturation_ratio * cap and (
                first is None or t < first[0]
            ):
                first = (float(t), ch, depth / cap)
    if first is not None:
        out["first_saturating"] = {
            "channel": first[1],
            # Seconds since the first scrape sample, not absolute time.
            "at_s": round(first[0] - (t0 or first[0]), 2),
            "fill_ratio": round(first[2], 3),
            "mode": "timeline",
        }
    else:
        best_ch, best_u = None, 0.0
        for ch, a in agg.items():
            if a.get("utilization", 0.0) > best_u:
                best_ch, best_u = ch, a["utilization"]
        if best_ch is not None and best_u >= saturation_ratio:
            out["first_saturating"] = {
                "channel": best_ch,
                "utilization": round(best_u, 4),
                "mode": "high_water",
            }
    return out


def wire_crypto_summary(
    snapshots: List[dict],
    committed_payload_bytes: int = 0,
    quorum_weight: Optional[int] = None,
) -> dict:
    """Join the wire-goodput and crypto-cost ledgers across node
    snapshots into the ``wire`` and ``crypto`` sections of the bench
    JSON.  ``snapshots`` may be --metrics-path post-mortem files
    (local_bench) or the scraper's final per-node samples (remote_bench)
    — both carry the same counters/histograms shape.

    Headline derived metrics:

    - ``goodput_ratio`` — committed payload bytes ÷ total outbound wire
      bytes (first transmissions + retransmissions, all nodes, all
      planes).  This is the denominator ROADMAP items 1/3/5 need: the
      paper reports goodput (committed payload), and the gap between it
      and raw wire traffic is broadcast amplification + control plane +
      retries.  Frame payload bytes only (length prefixes and tiny ACK
      replies excluded on both directions alike).
    - ``cert_sig_bytes_fraction`` — fraction of a certificate frame that
      is signature material (crypto.aggregate.cert_sig_wire_bytes under
      the scheme/format the committee ran ÷ mean cert frame size): the
      byte-level number the ``halfagg`` scheme roughly halves and a
      pairing-based aggregate would collapse to ~96 B.
    - ``empty_cert_overhead_per_committed_byte`` — control-plane bytes
      (header/vote/certificate frames) attributed to EMPTY rounds, per
      committed payload byte: the "empty certs per committed byte"
      number the min_header_delay default question reduces to (ROADMAP
      item 3).

    The crypto section's ``protocol_check`` cross-validates the ledger
    against protocol arithmetic: one verified claim per peer vote, and
    per certificate arriving over the wire either quorum+1 claims
    (2f+1 votes + 1 header sig, ``individual``) or exactly 2 (one
    aggregate + 1 header sig, ``halfagg``) — within tolerance on a
    clean run; the verify cache (re-deliveries) and in-flight teardown
    account for the residue.
    """
    counters = _agg_counters(snapshots)
    hists = _agg_histograms(snapshots)

    def typed(prefix: str) -> Dict[str, float]:
        return {
            name[len(prefix):]: v
            for name, v in counters.items()
            if name.startswith(prefix)
        }

    out_frames = typed("wire.out.frames.")
    out_bytes = typed("wire.out.bytes.")
    out_raw = typed("wire.out.raw_bytes.")
    re_frames = typed("wire.out.retransmit_frames.")
    re_bytes = typed("wire.out.retransmit_bytes.")
    in_frames = typed("wire.in.frames.")
    in_bytes = typed("wire.in.bytes.")

    # Which wire format the committee spoke (wire.format_version gauge,
    # stamped by every node): drives the format-aware signature
    # arithmetic below.  Max across nodes — the flag is committee-wide.
    wire_version = 1
    # Which certificate-signature scheme it ran (crypto.cert_sig_scheme
    # gauge, an index into crypto.aggregate.SCHEMES).  Same max-across-
    # nodes read: a mixed committee is refused at the wire, so on any
    # run that produced certificates the gauge agrees everywhere.
    scheme_index = 0
    for snap in snapshots:
        if snap.get("enabled", True):
            gauges = snap.get("gauges") or {}
            v = gauges.get("wire.format_version")
            if v:
                wire_version = max(wire_version, int(v))
            s = gauges.get("crypto.cert_sig_scheme")
            if s:
                scheme_index = max(scheme_index, int(s))
    cert_scheme = CERT_SIG_SCHEMES[
        min(scheme_index, len(CERT_SIG_SCHEMES) - 1)
    ]

    types = sorted(
        set(out_bytes) | set(in_bytes) | set(re_bytes)
    )
    first_total = sum(out_bytes.values())
    raw_total = sum(out_raw.values())
    re_total = sum(re_bytes.values())
    out_total = first_total + re_total
    in_total = sum(in_bytes.values())
    sender_total = (
        counters.get("net.reliable.bytes_sent", 0)
        + counters.get("net.simple.bytes_sent", 0)
    )
    flushes = counters.get("wire.out.flushes", 0)
    fpf_sum, fpf_count = hists.get("wire.out.frames_per_flush", (0.0, 0))
    apf_sum, apf_count = hists.get("wire.out.acks_per_flush", (0.0, 0))

    wire: dict = {
        "format_version": wire_version,
        "cert_sig_scheme": cert_scheme,
        "out": {
            t: {
                "frames": int(out_frames.get(t, 0)),
                "bytes": int(out_bytes.get(t, 0)),
                "raw_bytes": int(out_raw.get(t, 0)),
                "retransmit_frames": int(re_frames.get(t, 0)),
                "retransmit_bytes": int(re_bytes.get(t, 0)),
            }
            for t in types
        },
        "in": {
            t: {
                "frames": int(in_frames.get(t, 0)),
                "bytes": int(in_bytes.get(t, 0)),
            }
            for t in types
        },
        "totals": {
            "out_bytes": int(first_total),
            "out_raw_bytes": int(raw_total),
            "out_retransmit_bytes": int(re_total),
            "out_bytes_total": int(out_total),
            "in_bytes": int(in_total),
            "committed_payload_bytes": int(committed_payload_bytes),
            # Typed ledger bytes ÷ raw sender byte counters: ~1.0 means
            # every sent byte carries a type label (the acceptance gate's
            # "per-type wire bytes sum to total sender bytes").
            "sender_coverage": (
                round(out_total / sender_total, 4) if sender_total else None
            ),
        },
        # Receiver-side bytes ÷ sender-side bytes (first + retransmit)
        # per type: <1 when frames died with a connection (or a node was
        # torn down before draining), >1 never (the receiver cannot see
        # more than was written).
        "recv_vs_sent": {
            t: round(
                in_bytes.get(t, 0)
                / (out_bytes.get(t, 0) + re_bytes.get(t, 0)),
                4,
            )
            for t in types
            if out_bytes.get(t, 0) + re_bytes.get(t, 0) > 0
        },
    }
    if out_total > 0:
        wire["goodput_ratio"] = round(
            committed_payload_bytes / out_total, 4
        )
        # Pre-compression logical bytes ÷ wire bytes (first transmissions
        # only — raw counters don't track retransmits): >1 is the wire-v2
        # compression win, 1.0 on the legacy arm.
        if first_total > 0 and raw_total > 0:
            wire["compression_ratio"] = round(raw_total / first_total, 4)
    # Coalescing series (wire v2): syscall batching as a measured
    # distribution, not an inference.  frames_per_flush covers the
    # ReliableSender data path, acks_per_flush the receivers' replies.
    if flushes:
        wire["flushes"] = int(flushes)
        if fpf_count:
            wire["frames_per_flush_mean"] = round(fpf_sum / fpf_count, 3)
        if apf_count:
            wire["acks_per_flush_mean"] = round(apf_sum / apf_count, 3)
    # Frame-anatomy metrics read the RAW (pre-compression) series so
    # they measure encoding composition under both formats.
    cert_bytes = out_raw.get("certificate", 0) or out_bytes.get(
        "certificate", 0
    )
    cert_frames = out_frames.get("certificate", 0)
    if quorum_weight and cert_frames:
        sig_bytes = cert_sig_wire_bytes(
            cert_scheme, quorum_weight, wire_version
        )
        wire["cert_sig_bytes_per_cert"] = sig_bytes
        wire["cert_sig_bytes_fraction"] = round(
            sig_bytes / (cert_bytes / cert_frames), 4
        )
    empty_h = counters.get("primary.own_headers_empty", 0)
    payload_h = counters.get("primary.own_headers_payload", 0)
    wire["empty_headers"] = int(empty_h)
    wire["payload_headers"] = int(payload_h)
    control_bytes = sum(
        out_bytes.get(t, 0) for t in ("header", "vote", "certificate")
    )
    if empty_h + payload_h > 0 and committed_payload_bytes > 0:
        empty_fraction = empty_h / (empty_h + payload_h)
        wire["empty_cert_overhead_per_committed_byte"] = round(
            control_bytes * empty_fraction / committed_payload_bytes, 6
        )

    # -- crypto section -------------------------------------------------------

    verify_sites: dict = {}
    for site, ops in sorted(typed("crypto.verify.ops.").items()):
        wall_s, calls = hists.get(f"crypto.verify.seconds.{site}", (0.0, 0))
        bsum, bcount = hists.get(
            f"crypto.verify.batch_size.{site}", (0.0, 0)
        )
        # Async batched path only: backend compute time (host prep +
        # device round trip) vs the wall histogram above, which also
        # carries event-loop yields/executor-queue wait across the
        # await — the split that stops pipelining reading as crypto
        # cost (wall >> compute means the loop overlapped other work).
        dev_s, dev_calls = hists.get(
            f"crypto.verify.device_seconds.{site}", (0.0, 0)
        )
        verify_sites[site] = {
            "ops": int(ops),
            "calls": int(calls),
            "wall_s": round(wall_s, 3),
            "mean_batch": round(bsum / bcount, 2) if bcount else None,
        }
        if dev_calls:
            verify_sites[site]["compute_s"] = round(dev_s, 3)
            verify_sites[site]["loop_overlap_s"] = round(
                max(0.0, wall_s - dev_s), 3
            )
    sign_sites: dict = {}
    for site, ops in sorted(typed("crypto.sign.ops.").items()):
        wall_s, _calls = hists.get(f"crypto.sign.seconds.{site}", (0.0, 0))
        sign_sites[site] = {"ops": int(ops), "wall_s": round(wall_s, 3)}

    claims = {
        kind: int(v) for kind, v in typed("crypto.burst_claims.").items()
    }
    crypto: dict = {
        "verify": verify_sites,
        "sign": sign_sites,
        "burst_claims": claims,
        "verify_cache": {
            "hits": int(counters.get("primary.verify_cache_hits", 0)),
            "misses": int(counters.get("primary.verify_cache_misses", 0)),
        },
    }

    # Protocol-arithmetic cross-check (see docstring).
    votes_received = counters.get("primary.votes_received", 0)
    late_votes = counters.get("primary.late_votes", 0)
    own_headers = empty_h + payload_h
    measured_vote_claims = claims.get("vote", 0) + (
        verify_sites.get("vote", {}).get("ops", 0)
    )
    expected_vote_claims = votes_received - own_headers + late_votes
    check: dict = {}
    if expected_vote_claims > 0:
        check["votes"] = {
            "measured_claims": int(measured_vote_claims),
            "expected_claims": int(expected_vote_claims),
            "ratio": round(measured_vote_claims / expected_vote_claims, 4),
        }
    certs_in = counters.get("primary.certificates_processed", 0)
    certs_own = counters.get("primary.certificates_formed", 0)
    wire_certs = certs_in - certs_own
    if quorum_weight and wire_certs > 0:
        claims_per_cert = claims.get("certificate", 0) / wire_certs
        # individual: 2f+1 vote signatures + the embedded header's
        # signature.  halfagg: ONE aggregate claim + the header's —
        # the "2f+1 → 1 verify per cert" ledger witness.
        expected_claims = (
            2 if cert_scheme == "halfagg" else quorum_weight + 1
        )
        check["certificates"] = {
            "claims": claims.get("certificate", 0),
            "wire_certs": int(wire_certs),
            "claims_per_cert": round(claims_per_cert, 3),
            "expected_claims_per_cert": expected_claims,
            "ratio": round(claims_per_cert / expected_claims, 4),
        }
    if check:
        crypto["protocol_check"] = check
    return {"wire": wire, "crypto": crypto}


# -- committee-wide timeline from scraped samples -----------------------------

_PEER_RTT_PREFIX = "net.reliable.peer.rtt_seconds."


def build_timeline(
    samples: List[dict],
    interval_s: float = 1.0,
    healthz: Optional[Dict[str, tuple]] = None,
) -> dict:
    """Turn the scraper's raw sample stream into the timeline section of
    the bench JSON:

        {"interval_s": s,
         "nodes": {name: [{"t", "round", "commit_lag", "commits",
                           "committed_batches", "txs_sealed",
                           "pending_acks", "health_firing",
                           "commit_rate_per_s", "txs_sealed_per_s",
                           "queues": {channel: depth}}, …]},
         "events": [{"node", "t", "event": "FIRING"|"cleared", "rule",
                     "subject", "detail"}, …],   # anomaly transitions
         "rtt_ms": {name: {peer_addr: {"mean_ms", "count"}}},
         "healthz": {name: {"status": code|None, "firing": [rule names]}}}

    Per-sample rates are deltas against the node's previous sample, so a
    mid-run stall shows as a rate dip AT ITS TIME — the thing the
    post-mortem snapshot can structurally never show.  The RTT matrix
    comes from each node's LAST sample (per-peer histograms are
    cumulative, so last = whole-run mean).

    The ``events`` track is the HealthMonitor's FIRING/cleared
    transitions promoted to a first-class, committee-wide list: each
    node's snapshots carry a bounded ``health.events`` ring, and the
    scraper sees it grow tick by tick — deduplicated here by (node,
    rule, subject, event, t) since the ring is cumulative across
    samples, merged with the quiesce /healthz bodies (which can carry
    transitions after the last scrape tick), and sorted by time so rule
    firings line up against the per-node rate series they explain.
    """
    by_node: Dict[str, List[dict]] = {}
    for s in sorted(samples, key=lambda s: s.get("t", 0.0)):
        by_node.setdefault(s["node"], []).append(s)

    events: List[dict] = []
    seen_events = set()

    def collect_events(name: str, health: Optional[dict]) -> None:
        for ev in (health or {}).get("events") or []:
            key = (
                name,
                ev.get("rule"),
                ev.get("subject"),
                ev.get("event"),
                ev.get("t"),
            )
            if key in seen_events:
                continue
            seen_events.add(key)
            events.append(
                {
                    "node": name,
                    "t": ev.get("t"),
                    "event": ev.get("event"),
                    "rule": ev.get("rule"),
                    "subject": ev.get("subject"),
                    "detail": ev.get("detail") or {},
                }
            )

    nodes: Dict[str, List[dict]] = {}
    rtt_ms: Dict[str, Dict[str, dict]] = {}
    for name, node_samples in by_node.items():
        series: List[dict] = []
        prev: Optional[dict] = None
        for s in node_samples:
            counters, gauges = s["counters"], s["gauges"]
            health = s.get("health") or {}
            collect_events(name, health)
            point = {
                "t": round(s["t"], 3),
                "round": gauges.get("primary.round"),
                "commit_lag": gauges.get("consensus.commit_lag_rounds"),
                "commits": counters.get(
                    "consensus.committed_certificates"
                ),
                "committed_batches": counters.get(
                    "consensus.committed_batch_digests"
                ),
                "txs_sealed": counters.get("worker.txs_sealed"),
                "pending_acks": gauges.get("net.reliable.pending_acks"),
                "health_firing": len(health.get("firing", [])),
            }
            # Non-empty InstrumentedQueue depths at this tick: the
            # per-channel series a knee reads as a FILLING queue on the
            # timeline (and the Perfetto queue-depth counter tracks).
            qdepth = {
                g[len("queue."):-len(".depth")]: v
                for g, v in gauges.items()
                if g.startswith("queue.") and g.endswith(".depth") and v
            }
            if qdepth:
                point["queues"] = qdepth
            if prev is not None and s["t"] > prev["t"]:
                dt = s["t"] - prev["t"]
                for rate_key, src_key in (
                    ("commit_rate_per_s", "commits"),
                    ("txs_sealed_per_s", "txs_sealed"),
                ):
                    a, b = prev.get(src_key), point.get(src_key)
                    if a is not None and b is not None:
                        point[rate_key] = round((b - a) / dt, 2)
            series.append(point)
            prev = point
        nodes[name] = series

        # Per-peer RTT from the node's last sample's histograms.
        last = node_samples[-1]
        peers = {}
        for hname, h in (last.get("histograms") or {}).items():
            if hname.startswith(_PEER_RTT_PREFIX) and h.get("count"):
                peers[hname[len(_PEER_RTT_PREFIX):]] = {
                    "mean_ms": round(1000 * h["sum"] / h["count"], 3),
                    "count": h["count"],
                }
        if peers:
            rtt_ms[name] = peers

    if healthz is not None:
        # Transitions between the last scrape tick and quiesce ride in
        # the /healthz bodies' events ring.
        for name, (_, body) in healthz.items():
            collect_events(name, body)
    events.sort(key=lambda ev: (ev["t"] is None, ev["t"] or 0.0))
    out = {
        "interval_s": interval_s,
        "nodes": nodes,
        "events": events,
        "rtt_ms": rtt_ms,
    }
    if healthz is not None:
        out["healthz"] = {
            name: {
                "status": status,
                "firing": [
                    f.get("rule")
                    for f in ((body or {}).get("firing") or [])
                ],
            }
            for name, (status, body) in healthz.items()
        }
    return out


def check_quiesce_health(
    healthz: Dict[str, tuple], errors: List[str]
) -> None:
    """The harness's live-health gate: any node whose /healthz reports a
    firing rule at quiesce fails the run (error entry — fatal to every
    caller).  An unreachable endpoint is NOT a failure here: nodes
    without --metrics-port (or already torn down) simply aren't gated."""
    for name, (status, body) in sorted(healthz.items()):
        if status is not None and status != 200:
            rules = ", ".join(
                f"{f.get('rule')}[{f.get('subject')}]"
                for f in ((body or {}).get("firing") or [])
            ) or "unknown"
            errors.append(
                f"health check FAILED at quiesce: {name} /healthz "
                f"returned {status} with firing rule(s): {rules}"
            )
