"""Cross-validate the log-scraped bench numbers against node metrics.

The log parser (benchmark/logs.py) and the metrics registry
(narwhal_tpu/metrics.py) measure the same run through two independent
channels: regex over four INFO lines vs in-process counters and the
per-digest stage-trace table.  Agreement within tolerance is the check
that neither channel silently lost data — round 5 published a number a
flooded queue had quietly corrupted, and nothing cross-checked it
(VERDICT.md §1).  Disagreement beyond tolerance hard-fails the run (an
error entry, which every harness treats as fatal).

The same per-digest trace join also yields the per-stage pipeline latency
breakdown (batch-sealed → quorum → digest-at-primary → header →
certificate → commit): each process stamps wall-clock times for the
stages it owns, and since the committee runs on one host the stamps join
directly across process snapshots.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

# Causal stage order: the registry's definition IS the source of truth
# (a hand-copied tuple here would silently drop any future stage from
# the breakdown).
from narwhal_tpu.metrics import STAGES as STAGE_ORDER

STAGE_LEGS: Tuple[Tuple[str, str], ...] = tuple(
    zip(STAGE_ORDER[:-1], STAGE_ORDER[1:])
)


def load_snapshots(paths: List[str], errors: List[str]) -> List[dict]:
    """Load metric snapshot files, reporting (not raising on) missing or
    torn ones — the writer's atomic rewrite makes torn files a real bug,
    so they land in `errors`, but a node that died pre-boot simply has no
    file and must not mask the log-side numbers."""
    snaps = []
    for path in paths:
        if not os.path.exists(path):
            errors.append(f"metrics snapshot missing: {os.path.basename(path)}")
            continue
        try:
            with open(path) as f:
                snaps.append(json.load(f))
        except (OSError, ValueError) as e:
            errors.append(
                f"metrics snapshot unreadable: {os.path.basename(path)}: {e}"
            )
    return snaps


def cross_validate(
    result,
    snapshots: List[dict],
    tx_size: int,
    tolerance: float = 0.05,
) -> dict:
    """Join stage traces across node snapshots; fill ``result``'s
    metrics fields and append a fatal error on >tolerance disagreement
    between the metrics-derived and log-scraped committed-tx totals.

    Returns the summary dict the bench JSON embeds.
    """
    # Earliest timestamp per (digest, stage) across every snapshot —
    # the same convention the log parser uses across primaries.
    stage_ts: Dict[str, Dict[str, float]] = {}
    seal_bytes: Dict[str, int] = {}
    for snap in snapshots:
        if not snap.get("enabled", True):
            continue
        for digest, entry in snap.get("trace", {}).items():
            dst = stage_ts.setdefault(digest, {})
            for stage in STAGE_ORDER:
                t = entry.get(stage)
                if t is not None and (stage not in dst or t < dst[stage]):
                    dst[stage] = t
            b = entry.get("bytes")
            if b:
                seal_bytes.setdefault(digest, int(b))

    committed = [d for d, st in stage_ts.items() if "commit" in st]
    metrics_bytes = sum(seal_bytes.get(d, 0) for d in committed)
    result.metrics_committed_tx = metrics_bytes / tx_size

    disagreement: Optional[float] = None
    log_tx = result.committed_bytes / tx_size
    if log_tx > 0:
        disagreement = abs(result.metrics_committed_tx - log_tx) / log_tx
        result.metrics_disagreement = disagreement
        if disagreement > tolerance:
            result.errors.append(
                "metrics cross-check FAILED: log-scraped "
                f"{log_tx:.0f} committed tx vs metrics-derived "
                f"{result.metrics_committed_tx:.0f} "
                f"({100 * disagreement:.1f}% > {100 * tolerance:.0f}% "
                "tolerance) — one measurement channel lost data"
            )
    elif committed:
        result.errors.append(
            "metrics cross-check FAILED: metrics snapshots show "
            f"{len(committed)} committed batches but the log scrape "
            "found none"
        )

    # Per-stage latency breakdown over digests carrying the full chain
    # (own-batch traces: sealed, quorum'd, proposed, certified at the
    # same authority, commit joined committee-wide).
    legs: Dict[str, List[float]] = {
        f"{a}_to_{b}": [] for a, b in STAGE_LEGS
    }
    totals: List[float] = []
    for st in stage_ts.values():
        if all(s in st for s in STAGE_ORDER):
            for a, b in STAGE_LEGS:
                legs[f"{a}_to_{b}"].append(st[b] - st[a])
            totals.append(st["commit"] - st["seal"])
    if totals:
        result.stages_ms = {
            name: round(1000 * sum(v) / len(v), 2)
            for name, v in legs.items()
            if v
        }
        result.stages_ms["seal_to_commit"] = round(
            1000 * sum(totals) / len(totals), 2
        )

    return {
        "stages_ms": dict(result.stages_ms),
        "traced_full_chain": len(totals),
        "metrics_committed_tx": round(result.metrics_committed_tx, 1),
        "log_committed_tx": round(log_tx, 1),
        "disagreement": (
            round(disagreement, 4) if disagreement is not None else None
        ),
    }
