"""Unified committee trace: every node's story on ONE Perfetto timeline.

The observability planes built so far each answer their own question —
per-digest stage traces (where did one batch's latency go), per-round
cadence traces (where did the round period go), the health/flight event
streams (what anomalies fired), the loop-stall watchdog (who held the
loop), the sampling profiler (where did the CPU go) — but each lives in
its own JSON and its own mental model.  This exporter joins ALL of them
into one Chrome-trace-event file that ``ui.perfetto.dev`` (or
``chrome://tracing``) renders directly:

- one **process row per node process** (primary-0 … worker-3-0), with
  per-row tracks for the digest pipeline, the round cadence, instant
  events (health transitions, flight-ring landmarks, merged log lines),
  sampled CPU (the profiler's main-thread leaf timeline), and counters
  (per-tick wire/commit deltas);
- **flow arrows per committed digest** following seal → quorum →
  digest-at-primary → header → cert → commit ACROSS processes — the
  cross-process causal chain the paper's pipeline argument is about,
  drawn instead of tabulated;
- instant events carry their structured payloads in ``args``, so
  clicking a health FIRING in the UI shows the rule detail.

Inputs are the artifacts a bench run already leaves behind: the per-node
``--metrics-path`` snapshots (stage/round traces + flight ring +
profiler timeline ride in every final snapshot) and optionally the
scraped ``timeline.json``.  Both harnesses grow ``--trace-out`` to
invoke this directly; standalone:

    python -m benchmark.trace_export --workdir .bench -o trace.json
    # then open trace.json in https://ui.perfetto.dev

``benchmark/logs_merge.py --trace trace.json`` interleaves merged
``--log-json`` streams into an exported trace afterwards, so log context
and stage spans live on one timeline.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from narwhal_tpu.metrics import ROUND_STAGES, STAGES  # noqa: E402
from benchmark.metrics_check import (  # noqa: E402
    corrected_stage_join,
    critical_path_summary,
    snapshot_correction_ms,
)

# Per-process track (tid) layout.  Fixed small integers: Perfetto sorts
# tracks by tid within a process, so the pipeline sits on top.
TID_PIPELINE = 1   # per-digest stage slices + flow bindings
TID_ROUNDS = 2     # per-round cadence slices
TID_EVENTS = 3     # instants: health/flight landmarks, merged log lines
TID_CPU = 4        # sampling profiler: main-thread leaf runs
TID_CRITICAL = 5   # committee row: slowest end-to-end causal chains
_TRACK_NAMES = {
    TID_PIPELINE: "pipeline (per-digest)",
    TID_ROUNDS: "rounds (cadence)",
    TID_EVENTS: "events",
    TID_CPU: "cpu (sampled)",
}

# How many of the slowest full-chain digests get a slice chain on the
# committee critical-path row.
CRITICAL_PATHS = 3

_STAGE_IDX = {s: i for i, s in enumerate(STAGES)}

# Beyond this many committed digests, flows are sampled evenly — a
# 60 s bench commits tens of thousands and Perfetto renders arrows per
# flow; the cap keeps the file loadable while `flows_dropped` in the
# metadata says exactly what was left out (no silent truncation).
MAX_FLOWS = 512


def _us(ts: float, t0: float) -> int:
    return int(round((ts - t0) * 1e6))


class _TraceBuilder:
    def __init__(self) -> None:
        self.events: List[dict] = []

    def slice(self, pid: int, tid: int, name: str, ts_us: int,
              dur_us: int, cat: str, args: Optional[dict] = None) -> None:
        ev = {
            "ph": "X", "pid": pid, "tid": tid, "name": name, "cat": cat,
            "ts": ts_us, "dur": max(1, dur_us),
        }
        if args:
            ev["args"] = args
        self.events.append(ev)

    def instant(self, pid: int, tid: int, name: str, ts_us: int,
                cat: str, args: Optional[dict] = None) -> None:
        ev = {
            "ph": "i", "pid": pid, "tid": tid, "name": name, "cat": cat,
            "ts": ts_us, "s": "t",  # thread-scoped instant
        }
        if args:
            ev["args"] = args
        self.events.append(ev)

    def counter_track(self, pid: int, name: str, ts_us: int,
                values: Dict[str, float]) -> None:
        self.events.append({
            "ph": "C", "pid": pid, "tid": 0, "name": name,
            "cat": "counters", "ts": ts_us, "args": values,
        })

    def flow(self, ph: str, flow_id: str, pid: int, tid: int,
             ts_us: int) -> None:
        ev = {
            "ph": ph, "pid": pid, "tid": tid, "ts": ts_us,
            "name": "digest", "cat": "digest-flow", "id": flow_id,
        }
        if ph == "f":
            ev["bp"] = "e"  # bind to the enclosing slice at the sink
        self.events.append(ev)


def build_trace(
    snapshots: List[Tuple[str, dict]],
    timeline: Optional[dict] = None,
    flight: Optional[Dict[str, dict]] = None,
    max_flows: int = MAX_FLOWS,
) -> dict:
    """Join per-node registry snapshots (+ optional scraped timeline and
    /debug/flight rings) into one Chrome-trace-event JSON document.

    ``snapshots`` is ``[(node_name, snapshot_dict), …]`` — the final
    ``--metrics-path`` files of a bench run, in any order (rows sort by
    name, primaries first).  ``flight`` optionally supplies per-node
    rings scraped at quiesce for nodes whose snapshot predates theirs.
    """
    # Primaries first, then workers, each numerically ordered — the row
    # layout a reader scans top-to-bottom.
    def row_key(name: str) -> tuple:
        parts = name.replace("-", " ").split()
        nums = tuple(int(p) for p in parts if p.isdigit())
        return (0 if name.startswith("primary") else 1, nums, name)

    snapshots = sorted(snapshots, key=lambda kv: row_key(kv[0]))
    pids = {name: i + 1 for i, (name, _) in enumerate(snapshots)}
    # Synthetic committee row for cross-process surfaces (the critical
    # path spans nodes and belongs to no single process).
    committee_pid = len(snapshots) + 1
    # Events are built on ABSOLUTE epoch microseconds and rebased to the
    # earliest one at the end — no surface (profiler boots before the
    # first stage stamp) can land before the computed origin.  Each
    # node's surfaces are shifted by its reconciled clock correction
    # (passed to the emitters through the `t0` rebase argument), so
    # cross-process flows and the critical path measure causal order on
    # the committee's mean clock, not each host's raw wall clock.
    t0 = 0.0
    corrections = {
        name: snapshot_correction_ms(snap) / 1000.0
        for name, snap in snapshots
    }
    b = _TraceBuilder()

    for name, _ in snapshots:
        pid = pids[name]
        b.events.append({
            "ph": "M", "pid": pid, "tid": 0, "name": "process_name",
            "args": {"name": name},
        })
        b.events.append({
            "ph": "M", "pid": pid, "tid": 0, "name": "process_sort_index",
            "args": {"sort_index": pid},
        })
        for tid, tname in _TRACK_NAMES.items():
            b.events.append({
                "ph": "M", "pid": pid, "tid": tid, "name": "thread_name",
                "args": {"name": tname},
            })

    # -- per-node surfaces ----------------------------------------------------
    # digest -> [(pid, stage, ts, slice_start_ts)] for the flow pass;
    # slice_start_ts is the start of the slice a flow event can bind to.
    flow_anchor: Dict[str, List[Tuple[int, str, float]]] = {}
    for name, snap in snapshots:
        pid = pids[name]
        corr_s = corrections.get(name, 0.0)
        _emit_digest_slices(b, pid, snap, corr_s, flow_anchor)
        _emit_round_slices(b, pid, snap, corr_s)
        _emit_clock_counters(b, pid, snap, corr_s)
        ring = (snap.get("detail") or {}).get("flight.ring") or {}
        scraped = (flight or {}).get(name)
        if scraped and _ring_newest(scraped) > _ring_newest(ring):
            # Two copies of the same bounded deque exist: the quiesce
            # scrape and the snapshot's.  In the normal teardown order
            # (scrape → SIGTERM → final snapshot flush) the SNAPSHOT is
            # the superset — it carries the quiesce-to-shutdown tail —
            # but a node SIGKILLed mid-run has only a stale periodic
            # snapshot while the scrape saw it live.  Newest event wins.
            ring = scraped
        _emit_flight(b, pid, ring, corr_s)
        _emit_profile(b, pid, snap, corr_s)
        _emit_health_events(
            b, pid, ((snap.get("health") or {}).get("events")) or [],
            corr_s,
        )
        last_stall = (snap.get("detail") or {}).get("runtime.loop_stall_last")
        if last_stall and last_stall.get("ts"):
            b.instant(
                pid, TID_EVENTS, "loop_stall_stack",
                _us(last_stall["ts"], corr_s), "runtime",
                {k: str(v)[:2000] for k, v in last_stall.items()},
            )

    # -- committee-wide surfaces ---------------------------------------------
    if timeline:
        _emit_timeline(b, pids, timeline, t0)

    critical = _emit_critical_paths(b, committee_pid, snapshots)

    # -- cross-process digest flows -------------------------------------------
    flows, flows_total = _emit_flows(b, flow_anchor, t0, max_flows)

    # Rebase to the earliest emitted timestamp (metadata events carry no
    # ts and stay put).
    stamped = [e["ts"] for e in b.events if "ts" in e]
    origin_us = min(stamped) if stamped else 0
    for e in b.events:
        if "ts" in e:
            e["ts"] -= origin_us

    b.events.sort(key=lambda e: (e.get("ts", 0), e["ph"] != "M"))
    return {
        "traceEvents": b.events,
        "displayTimeUnit": "ms",
        "metadata": {
            "generated_by": "benchmark/trace_export.py",
            "epoch_t0": origin_us / 1e6,
            "node_pids": pids,
            "flows_emitted": flows,
            "flows_total": flows_total,
            "flows_dropped": flows_total - flows,
            "clock_corrections_ms": {
                name: round(1000 * c, 3)
                for name, c in corrections.items()
                if c
            },
            "critical_path": critical,
        },
    }


def _emit_digest_slices(b, pid, snap, t0, flow_anchor) -> None:
    """Leg slices between consecutive stage stamps a node owns, plus the
    flow anchors (digest → slice starts) the flow pass binds arrows to.
    ``t0`` is the node's clock correction; anchors are recorded in
    CORRECTED time so the cross-process flow pass (which rebases all
    rows alike) lands the arrows where the slices are."""
    for digest, entry in (snap.get("trace") or {}).items():
        stamps = sorted(
            ((s, entry[s]) for s in STAGES if s in entry),
            key=lambda kv: _STAGE_IDX[kv[0]],
        )
        if not stamps:
            continue
        short = digest[:12]
        anchors = flow_anchor.setdefault(digest, [])
        for (s_a, t_a), (s_b, t_b) in zip(stamps, stamps[1:]):
            if t_b < t_a:
                continue  # clock skew across threads; skip the leg
            b.slice(
                pid, TID_PIPELINE, f"{s_a}→{s_b}",
                _us(t_a, t0), _us(t_b, t0) - _us(t_a, t0),
                "stage", {"digest": short},
            )
            anchors.append((pid, s_a, t_a - t0))
        # A lone trailing stamp still anchors the chain's end (commit on
        # a primary whose slice ends there): bind at the LAST slice start.
        if len(stamps) == 1:
            b.instant(
                pid, TID_PIPELINE, stamps[0][0],
                _us(stamps[0][1], t0), "stage", {"digest": short},
            )
            anchors.append((pid, stamps[0][0], stamps[0][1] - t0))


def _emit_round_slices(b, pid, snap, t0) -> None:
    ridx = {s: i for i, s in enumerate(ROUND_STAGES)}
    for rnd, entry in (snap.get("round_trace") or {}).items():
        stamps = sorted(
            ((s, entry[s]) for s in ROUND_STAGES if s in entry),
            key=lambda kv: ridx[kv[0]],
        )
        if len(stamps) < 2:
            continue
        start, end = stamps[0][1], max(t for _, t in stamps)
        if end < start:
            continue
        b.slice(
            pid, TID_ROUNDS, f"round {rnd}",
            _us(start, t0), _us(end, t0) - _us(start, t0),
            "round", {"round": rnd},
        )
        for (s_a, t_a), (s_b, t_b) in zip(stamps, stamps[1:]):
            if t_b < t_a:
                continue  # pipelined overlap (legal; see round_attribution)
            b.slice(
                pid, TID_ROUNDS, f"{s_a}→{s_b}",
                _us(t_a, t0), _us(t_b, t0) - _us(t_a, t0),
                "round-leg", {"round": rnd},
            )


def _emit_clock_counters(b, pid, snap, t0) -> None:
    """Per-peer clock-offset and uncertainty gauges as counter tracks,
    stamped at the snapshot's write time: the correction layer made
    visible next to the spans it corrects (a leg that still looks
    acausal with a large offset counter underneath it is an estimator
    problem, not a pipeline one)."""
    ts = snap.get("ts")
    if not isinstance(ts, (int, float)):
        return
    gauges = snap.get("gauges") or {}
    for track, prefix in (
        ("clock offset (ms)", "clock.offset_ms."),
        ("clock uncertainty (ms)", "clock.offset_uncertainty_ms."),
    ):
        vals = {
            name[len(prefix):]: v
            for name, v in gauges.items()
            if name.startswith(prefix) and isinstance(v, (int, float))
        }
        if vals:
            b.counter_track(pid, track, _us(ts, t0), vals)


def _emit_critical_paths(b, committee_pid, snapshots) -> dict:
    """Slice chains for the slowest end-to-end digests on a dedicated
    committee row, from the skew-corrected cross-node join (the same one
    metrics_check reports).  Returns the summary for the metadata."""
    stage_ts, _ = corrected_stage_join([snap for _, snap in snapshots])
    summary = critical_path_summary(stage_ts, top_k=CRITICAL_PATHS)
    if not summary.get("slowest"):
        return summary
    b.events.append({
        "ph": "M", "pid": committee_pid, "tid": 0, "name": "process_name",
        "args": {"name": "committee"},
    })
    b.events.append({
        "ph": "M", "pid": committee_pid, "tid": 0,
        "name": "process_sort_index",
        "args": {"sort_index": committee_pid},
    })
    b.events.append({
        "ph": "M", "pid": committee_pid, "tid": TID_CRITICAL,
        "name": "thread_name", "args": {"name": "critical path"},
    })
    for rank, chain in enumerate(summary["slowest"], start=1):
        st = stage_ts[chain["digest"]]
        for a, bb in zip(STAGES[:-1], STAGES[1:]):
            if st[bb] < st[a]:
                continue  # residual skew beyond the correction
            b.slice(
                committee_pid, TID_CRITICAL, f"#{rank} {a}→{bb}",
                _us(st[a], 0.0), _us(st[bb], 0.0) - _us(st[a], 0.0),
                "critical-path",
                {
                    "digest": chain["digest"][:12],
                    "e2e_ms": chain["e2e_ms"],
                    "rank": rank,
                },
            )
    return summary


def _ring_newest(ring: dict) -> float:
    """Timestamp of a flight ring's newest event (0.0 when empty)."""
    ts = [
        ev.get("t") for ev in (ring or {}).get("events") or []
        if isinstance(ev.get("t"), (int, float))
    ]
    return max(ts) if ts else 0.0


def _emit_flight(b, pid, ring: dict, t0) -> None:
    for ev in ring.get("events") or []:
        t = ev.get("t")
        if not isinstance(t, (int, float)):
            continue
        kind = ev.get("kind", "event")
        if kind == "tick":
            # Tick deltas render as counter tracks, not instants.
            d = ev.get("d") or {}
            vals = {k: v for k, v in d.items() if isinstance(v, (int, float))}
            if vals:
                b.counter_track(pid, "flight ticks", _us(t, t0), vals)
            continue
        args = {k: v for k, v in ev.items() if k not in ("t", "kind")}
        name = f"flight:{kind}"
        # Multileader commits carry their anchor coordinates (which slot
        # of which even round anchored, plus the round's full slot
        # schedule in `slots`): put slot@round in the instant NAME so a
        # missed-slot round reads directly off the timeline — the args
        # still hold the schedule for the click-through detail.
        if kind == "commit" and "anchor_slot" in args:
            name = (
                f"flight:commit[slot{args['anchor_slot']}"
                f"@r{args.get('anchor_round', '?')}]"
            )
        b.instant(pid, TID_EVENTS, name, _us(t, t0), "flight", args or None)


def _emit_profile(b, pid, snap, t0) -> None:
    runs = (snap.get("detail") or {}).get("profile.timeline") or []
    for run in runs:
        try:
            start, end, samples, label = run
        except (TypeError, ValueError):
            continue
        if not isinstance(start, (int, float)) or end < start:
            continue
        b.slice(
            pid, TID_CPU, str(label), _us(start, t0),
            max(1, _us(end, t0) - _us(start, t0)),
            "cpu", {"samples": samples},
        )


def _emit_health_events(b, pid, events: List[dict], t0) -> None:
    for ev in events:
        t = ev.get("t")
        if not isinstance(t, (int, float)):
            continue
        b.instant(
            pid, TID_EVENTS,
            f"health:{ev.get('rule')}:{ev.get('event')}",
            _us(t, t0), "health",
            {"subject": ev.get("subject"), "detail": ev.get("detail")},
        )


def _emit_timeline(b, pids, timeline: dict, t0) -> None:
    """Scraped committee timeline: per-node rate counters plus any
    committee-wide health transitions the snapshots missed."""
    for name, series in (timeline.get("nodes") or {}).items():
        pid = pids.get(name)
        if pid is None:
            continue
        for point in series:
            t = point.get("t")
            if not isinstance(t, (int, float)):
                continue
            vals = {}
            for key in ("commit_rate_per_s", "txs_sealed_per_s",
                        "pending_acks"):
                v = point.get(key)
                if isinstance(v, (int, float)):
                    vals[key] = v
            if vals:
                b.counter_track(pid, "scraped rates", _us(t, t0), vals)
            # Per-channel InstrumentedQueue depths: their own counter
            # track per node, so a saturation knee reads as a filling
            # queue directly on the timeline.
            qvals = {
                ch: v
                for ch, v in (point.get("queues") or {}).items()
                if isinstance(v, (int, float))
            }
            if qvals:
                b.counter_track(pid, "queue depth", _us(t, t0), qvals)
    # Per-peer RTT matrix (whole-run means from each node's last scrape)
    # as a counter track per node, stamped at that node's last sample.
    for name, peers in (timeline.get("rtt_ms") or {}).items():
        pid = pids.get(name)
        series = (timeline.get("nodes") or {}).get(name) or []
        if pid is None or not series:
            continue
        t = series[-1].get("t")
        vals = {
            addr: e.get("mean_ms")
            for addr, e in peers.items()
            if isinstance(e.get("mean_ms"), (int, float))
        }
        if isinstance(t, (int, float)) and vals:
            b.counter_track(pid, "peer rtt (ms)", _us(t, t0), vals)
    for ev in timeline.get("events") or []:
        pid = pids.get(ev.get("node"))
        t = ev.get("t")
        if pid is None or not isinstance(t, (int, float)):
            continue
        b.instant(
            pid, TID_EVENTS,
            f"health:{ev.get('rule')}:{ev.get('event')}",
            _us(t, t0), "health",
            {"subject": ev.get("subject"), "detail": ev.get("detail")},
        )


def _emit_flows(b, flow_anchor, t0, max_flows: int) -> Tuple[int, int]:
    """One s/t…t/f flow chain per committed digest, bound to the slice
    starts recorded as anchors; returns (emitted, eligible).  Eligible =
    digests whose chain actually crosses processes — a batch sealed but
    never committed (teardown in flight) has anchors on one row only and
    is no flow, not a capped one."""
    committed = {
        d: anchors
        for d, anchors in flow_anchor.items()
        if len({pid for pid, _, _ in anchors}) >= 2  # crosses processes
        and any(s == "seal" for _, s, _ in anchors)
    }
    digests = sorted(committed)
    if len(digests) > max_flows:
        step = len(digests) / max_flows
        digests = [digests[int(i * step)] for i in range(max_flows)]
    for digest in digests:
        # Chain in causal-stage then time order, ONE anchor per
        # (pid, stage): zigzag across rows is the point.
        anchors = sorted(
            {(pid, s): t for pid, s, t in committed[digest]}.items(),
            key=lambda kv: (_STAGE_IDX[kv[0][1]], kv[1]),
        )
        if len(anchors) < 2:
            continue
        flow_id = digest[:16]
        for i, ((pid, _), t) in enumerate(anchors):
            ph = "s" if i == 0 else ("f" if i == len(anchors) - 1 else "t")
            b.flow(ph, flow_id, pid, TID_PIPELINE, _us(t, t0))
    return len(digests), len(committed)


# -- harness entry points ------------------------------------------------------

def load_named_snapshots(paths: List[str]) -> List[Tuple[str, dict]]:
    """[(node name, snapshot dict)] from ``metrics-<node>.json`` paths —
    ONE definition of the stem→row-name convention for both harnesses
    and the workdir loader (a naming change updating only one copy would
    silently mis-row the trace).  Missing/torn files are skipped (the
    harnesses' load_snapshots already reported those into
    result.errors)."""
    out = []
    for p in paths:
        name = os.path.basename(p)
        if name.startswith("metrics-"):
            name = name[len("metrics-"):]
        if name.endswith(".json"):
            name = name[: -len(".json")]
        try:
            with open(p) as f:
                out.append((name, json.load(f)))
        except (OSError, ValueError) as e:
            print(f"WARNING: skipping {p}: {e}", file=sys.stderr)
    return out


def load_workdir(workdir: str) -> Tuple[List[Tuple[str, dict]], Optional[dict]]:
    """(snapshots, timeline) from a bench workdir: every
    ``metrics-<node>.json`` plus ``timeline.json`` when present."""
    snapshots = load_named_snapshots(
        sorted(glob.glob(os.path.join(workdir, "metrics-*.json")))
    )
    timeline = None
    tpath = os.path.join(workdir, "timeline.json")
    if os.path.exists(tpath):
        try:
            with open(tpath) as f:
                timeline = json.load(f)
        except (OSError, ValueError) as e:
            print(f"WARNING: skipping {tpath}: {e}", file=sys.stderr)
    return snapshots, timeline


def export(
    snapshots: List[Tuple[str, dict]],
    out_path: str,
    timeline: Optional[dict] = None,
    flight: Optional[Dict[str, dict]] = None,
    quiet: bool = False,
) -> dict:
    """Build and atomically write one trace; returns the trace dict."""
    trace = build_trace(snapshots, timeline=timeline, flight=flight)
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(trace, f)
    os.replace(tmp, out_path)
    if not quiet:
        md = trace["metadata"]
        print(
            f"trace -> {out_path}: {len(trace['traceEvents'])} events, "
            f"{len(md['node_pids'])} process rows, "
            f"{md['flows_emitted']}/{md['flows_total']} digest flows"
            + (
                f" ({md['flows_dropped']} dropped past the "
                f"{MAX_FLOWS}-flow cap)"
                if md["flows_dropped"]
                else ""
            )
            + " — open in https://ui.perfetto.dev",
            file=sys.stderr,
        )
    return trace


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Join a bench run's per-node metrics snapshots into "
        "one Perfetto-loadable Chrome trace (process row per node, flow "
        "arrows per committed digest)."
    )
    parser.add_argument(
        "--workdir", default=None,
        help="bench workdir holding metrics-*.json (+ timeline.json), "
        "e.g. .bench",
    )
    parser.add_argument(
        "--snapshot", action="append", default=[],
        help="explicit name=path snapshot (repeatable; alternative to "
        "--workdir)",
    )
    parser.add_argument("--timeline", default=None,
                        help="scraped timeline.json (optional)")
    parser.add_argument("-o", "--out", required=True)
    args = parser.parse_args(argv)

    snapshots: List[Tuple[str, dict]] = []
    timeline = None
    if args.workdir:
        snapshots, timeline = load_workdir(args.workdir)
    for spec in args.snapshot:
        name, _, path = spec.partition("=")
        if not path:
            parser.error(f"--snapshot wants name=path, got {spec!r}")
        with open(path) as f:
            snapshots.append((name, json.load(f)))
    if args.timeline:
        with open(args.timeline) as f:
            timeline = json.load(f)
    if not snapshots:
        parser.error("no snapshots found (--workdir empty? --snapshot?)")
    export(snapshots, args.out, timeline=timeline)
    return 0


if __name__ == "__main__":
    sys.exit(main())
