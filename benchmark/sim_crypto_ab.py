"""Committee-scale verify-batch window A/B via the deterministic sim
(ISSUE 14): serial per-burst dispatch vs NARWHAL_VERIFY_BATCH_WINDOW_MS
on a clean N=20 simulated committee, same scenario and seed.

Why the sim arm exists alongside benchmark/crypto_ab.py's socketed N=4
pairs: batch depth is bounded by the claims that EXIST per round.  At
N=4 a primary sees ~18 claims per round arriving cadence-paced
(3 headers + 3 votes + 3 certs x quorum+1), so a short window cannot
reach the ISSUE 14 bar of mean >= 16 without spanning a whole round
period — a latency trade the socketed artifact records honestly.  At
N=20 one round carries ~320 claims (19 certs x 15 each), which is the
regime the device backend is FOR — and the sim runs that committee
single-process on the virtual clock in seconds, with the same Core
burst seam and crypto ledger (sim-MAC signatures change op cost, never
batch shape).

Arms are judged by the same three-verdict engine (a window that broke
safety/liveness would fail loudly) and compared on the ledger:
``crypto.verify.batch_size.batch_burst`` mean, claims by kind, and
committed certificates over the same virtual duration.

    python benchmark/sim_crypto_ab.py --nodes 20 \
        --artifact artifacts/sim_crypto_window_r19.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from narwhal_tpu import metrics  # noqa: E402
from narwhal_tpu.faults.spec import parse_scenario  # noqa: E402
from narwhal_tpu.sim.committee import run_sim_scenario  # noqa: E402
from benchmark.metrics_check import wire_crypto_summary  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_arm(arm: str, nodes: int, duration: int, seed: int, workdir: str,
            window_ms: float) -> dict:
    obj = {
        "name": f"crypto_window_{arm}_n{nodes}",
        "nodes": nodes,
        "workers": 1,
        "rate": 600,
        "tx_size": 512,
        "duration": duration,
        "seed": seed,
    }
    scenario = parse_scenario(obj, env={})
    # The sim committee boots in THIS process, so the Core construction
    # knob must ride the process env (the socketed harness passes it to
    # children via their env dicts instead); removed in the finally so
    # later suites see the default again.
    # lint: allow-env(in-process sim committee: the knob must reach Core.__init__'s typed accessor inside this very process, and is removed in the finally below)
    os.environ["NARWHAL_VERIFY_BATCH_WINDOW_MS"] = (
        str(window_ms) if arm == "batched" else "0"
    )
    try:
        art = run_sim_scenario(scenario, seed + 7, workdir)
    finally:
        # lint: allow-env(restore: later suites must see the default)
        os.environ.pop("NARWHAL_VERIFY_BATCH_WINDOW_MS", None)
    snap = metrics.registry().snapshot()
    quorum = 2 * nodes // 3 + 1
    wc = wire_crypto_summary([snap], quorum_weight=quorum)
    committed = sum(len(v) for v in art["commit_sequences"].values())
    return {
        "arm": arm,
        "window_ms": window_ms if arm == "batched" else 0,
        "verdicts_ok": art["ok"],
        "verdicts": {
            k: v["ok"] for k, v in art["verdicts"].items()
        },
        "committed_certificates_all_nodes": committed,
        "crypto": wc["crypto"],
        "schedule": art["schedule"],
        "wall": art["wall"],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--nodes", type=int, default=20)
    ap.add_argument("--duration", type=int, default=30)
    ap.add_argument("--seed", type=int, default=91_000)
    ap.add_argument("--window-ms", type=float, default=25.0)
    ap.add_argument(
        "--min-batch-mean", type=float, default=16.0,
        help="Required batched-arm batch_burst mean (ISSUE 14 bar)",
    )
    ap.add_argument(
        "--workdir", default=os.path.join(REPO, ".sim_crypto_ab")
    )
    ap.add_argument(
        "--artifact", default="artifacts/sim_crypto_window_r19.json"
    )
    args = ap.parse_args(argv)

    arms = {}
    for arm in ("serial", "batched"):
        arms[arm] = run_arm(
            arm, args.nodes, args.duration, args.seed,
            os.path.join(args.workdir, arm), args.window_ms,
        )
        burst = (arms[arm]["crypto"].get("verify") or {}).get(
            "batch_burst"
        ) or {}
        print(
            f"[{arm}] ok={arms[arm]['verdicts_ok']} "
            f"batch_burst mean {burst.get('mean_batch')} over "
            f"{burst.get('calls')} calls, "
            f"{arms[arm]['committed_certificates_all_nodes']} commits"
        )

    failures = []
    for arm, a in arms.items():
        if not a["verdicts_ok"]:
            failures.append(f"{arm} arm failed a sim verdict: "
                            f"{a['verdicts']}")
    mean = {
        arm: ((a["crypto"].get("verify") or {}).get("batch_burst") or {})
        .get("mean_batch")
        for arm, a in arms.items()
    }
    if mean["serial"] is None or mean["batched"] is None:
        failures.append(f"batch_burst mean missing: {mean}")
    else:
        if mean["batched"] < max(mean["serial"], args.min_batch_mean):
            failures.append(
                f"batched mean {mean['batched']} below required "
                f"max(serial {mean['serial']}, {args.min_batch_mean})"
            )
    c_serial = arms["serial"]["committed_certificates_all_nodes"]
    c_batched = arms["batched"]["committed_certificates_all_nodes"]
    if c_serial and c_batched < 0.75 * c_serial:
        failures.append(
            f"batched arm committed {c_batched} certs vs serial "
            f"{c_serial} over the same virtual duration — the window "
            "is taxing cadence more than the noise floor"
        )

    summary = {
        "nodes": args.nodes,
        "window_ms": args.window_ms,
        "batch_burst_mean": mean,
        "committed_certificates": {
            "serial": c_serial, "batched": c_batched,
        },
        "gates_failed": failures,
    }
    artifact = {
        "what": (
            f"Verify-batch window A/B on a clean simulated N={args.nodes} "
            f"committee ({args.duration} virtual s, shared seed "
            f"{args.seed}): serial per-burst dispatch vs "
            f"NARWHAL_VERIFY_BATCH_WINDOW_MS={args.window_ms}.  Same "
            "three-verdict judging as every sim run; crypto ledger "
            "read from the committee-shared registry (sim-MAC op cost, "
            "real batch shapes)."
        ),
        "arms": arms,
        "summary": summary,
    }
    os.makedirs(os.path.dirname(args.artifact) or ".", exist_ok=True)
    with open(args.artifact, "w") as f:
        json.dump(artifact, f, indent=1)
    print(json.dumps(summary, indent=1))
    if failures:
        print(f"sim crypto A/B FAILED: {failures}", file=sys.stderr)
        return 1
    print(
        f"sim crypto A/B ok: batch_burst mean {mean['serial']} -> "
        f"{mean['batched']} at {c_serial} -> {c_batched} commits"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
