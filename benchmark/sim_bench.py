"""sim_bench: the (seed × fuzzed fault × committee size) simulation sweep.

    python benchmark/sim_bench.py --points 200 \
        --artifact .ci-artifacts/sim-smoke.json

Every point generates a fuzzed fault scenario (``narwhal_tpu/faults/
fuzz.py`` — committee sizes 4/7/10/20, duration/behavior/crash/WAN
draws), dumps it as a replayable ``.spec.json`` BEFORE running, executes
the whole committee single-process on the virtual clock
(``narwhal_tpu.sim.run_sim_scenario``), and judges it with the three
machine-checked verdicts: golden-replay safety, payload-commit liveness
in virtual time, and health-rule detection.  Alongside the sweep:

- **controls** — one clean (fault-free) arm per committee size touched,
  gated on ZERO firing rules (the false-positive half of detection);
- **determinism pin** — the first point re-run; its deterministic
  artifact (commit sequences + verdicts + events + schedule, wall-clock
  section excluded) must be byte-identical;
- **mutation arms** (the PR 8/10 honesty pattern) — per commit-rule
  arm, a committee whose node 0 runs the planted ``CorruptingConsensus``
  (deterministic dropped + re-committed certificates, the two bug
  classes the PR 6 fault suite caught for real) must FAIL a safety
  verdict on the FIRST schedule, and a fuzzed Byzantine draw run with
  its expectations STRIPPED must still light up its contract rules (the
  harness detects what it claims, without being told what to find).
  The schedule-DEPENDENT ``RacyConsensus`` plant additionally must be
  caught in at least one arm of the sweep: its corruption needs the
  commit backlog to outrun the capacity-1 output puts, which classic's
  deep commit bursts produce under nearly every schedule while
  lowdepth's prompt shallow bursts do not at sim exploration intensity
  — ``race_explore.py --commit-rule lowdepth`` (~40× the permutation
  pressure) is the instrument that manifests and catches it per rule;
- **acceptance arm** — a 60-virtual-second N=20 committee with a fuzzed
  fault composition; its wall seconds and compression ratio are
  measured and reported (ROADMAP item 6's 100-1000× wall-clock
  compression claim, priced honestly on whatever host runs this).

Any failing point dumps a replayable ``<artifact>.repro-<name>.json``
carrying the full (seed, spec) pair; replay exactly that point with
``--replay <repro-or-spec.json> [--run-seed N]``.

Exit code is non-zero on any gate failure — the CI ``sim-smoke`` job.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from narwhal_tpu.faults.fuzz import SIZES, generate  # noqa: E402
from narwhal_tpu.faults.spec import parse_scenario  # noqa: E402
from narwhal_tpu.sim import run_sim_scenario  # noqa: E402
from narwhal_tpu.sim.committee import deterministic_blob  # noqa: E402
from narwhal_tpu.utils.env import env_int  # noqa: E402

# CI floor for the acceptance arm's wall-clock compression; the measured
# ratio is reported either way.  Reference points on the (syscall-
# sandboxed, shared-core) dev container: unshaped N=20/60 s ≈ 13×;
# the fuzzed WAN-lossy composition ≈ 8× — the floor sits under both
# with margin for slower shared CI runners.
_MIN_COMPRESSION = 6.0


def _point_summary(art: dict) -> dict:
    v = art["verdicts"]
    return {
        "name": art["name"],
        "nodes": art["nodes"],
        "scenario_seed": art["scenario_seed"],
        "run_seed": art["run_seed"],
        "commit_rule": art.get("commit_rule", "classic"),
        "cert_to_commit": art.get("cert_to_commit"),
        "observers": v["detection"].get("observers", {}),
        "ok": art["ok"],
        "safety": v["safety"]["ok"],
        "liveness": v["liveness"]["ok"],
        "detection": v["detection"]["ok"],
        "fired": v["detection"]["fired"],
        "commits": len(next(iter(art["commit_sequences"].values()), [])),
        "virtual_s": art["schedule"]["virtual_s"],
        "wall_s": art["wall"]["wall_s"],
        "compression": art["wall"]["compression"],
    }


def _dump_repro(artifact_path: Optional[str], name: str, obj: dict,
                run_seed: int, art: dict) -> str:
    base = artifact_path or os.path.join(".sim_bench", "sim.json")
    path = f"{base}.repro-{name}.json"
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    # The arm is part of the repro: a flag-flip sweep dumps failures
    # from BOTH rules, and a lowdepth-arm failure replayed under the
    # classic default would judge against the wrong oracle and not
    # reproduce.  run_replay prefers this recorded rule.
    rule = art.get("commit_rule", "classic")
    with open(path, "w") as f:
        json.dump(
            {
                "spec": obj,
                "run_seed": run_seed,
                "commit_rule": rule,
                "verdicts": art["verdicts"],
                "replay": "python benchmark/sim_bench.py --replay "
                f"{path} --run-seed {run_seed} --commit-rule {rule}",
            },
            f, indent=1,
        )
    return path


def run_sweep(args) -> int:
    os.makedirs(args.workdir, exist_ok=True)
    spec_dir = (
        os.path.dirname(args.artifact) if args.artifact else args.workdir
    )
    os.makedirs(spec_dir or ".", exist_ok=True)

    base = args.seed_base
    env_base = env_int("NARWHAL_SIM_SEED")
    if env_base is not None:
        base = int(env_base)

    failures: List[str] = []
    points: List[dict] = []
    sizes_seen: set = set()
    # (obj, run_seed, blob) per arm for the determinism pin.
    first: Dict[str, tuple] = {}
    # Single rule | both | all — `both` is the commit-rule FLAG-FLIP
    # sweep (ROADMAP item 2): every fuzzed point runs under each rule,
    # each arm judged by all three verdicts (safety against the arm's
    # own frozen oracle via the audit rule marker), and the virtual-time
    # cert→commit means price the latency claim per arm.  `all` adds the
    # multileader arm (ISSUE r19) on top of the original pair.
    if args.commit_rule == "both":
        arms = ["classic", "lowdepth"]
    elif args.commit_rule == "all":
        arms = ["classic", "lowdepth", "multileader"]
    else:
        arms = [args.commit_rule or "classic"]

    # -- the sweep -------------------------------------------------------------
    specs = []
    for k in range(args.points):
        obj = generate(base + k)
        specs.append((base + k, obj))
    if not any(o["nodes"] == 20 for _, o in specs):
        # The sweep must include committee-at-scale: force one N=20 draw
        # (still pure-seed-derived, just a pruned size pool).
        specs.append((base + args.points, generate(base + args.points,
                                                   sizes=(20,))))

    spec_dump = os.path.join(spec_dir, "sim-sweep-specs.json")
    with open(spec_dump, "w") as f:
        json.dump([o for _, o in specs], f, indent=1)

    def guarded(scenario, run_seed: int, workdir: str, **kw) -> dict:
        """One crashed/cancelled point (e.g. the wall backstop firing on
        a busy livelock) must cost THAT point, not the sweep: record a
        failing artifact shape and keep going."""
        try:
            return run_sim_scenario(scenario, run_seed, workdir, **kw)
        except KeyboardInterrupt:
            raise
        except BaseException as exc:  # noqa: BLE001 (recorded, re-gated)
            return {
                "name": scenario.name,
                "nodes": scenario.nodes,
                "workers": scenario.workers,
                "scenario_seed": scenario.seed,
                "run_seed": run_seed,
                "commit_rule": kw.get("commit_rule") or "classic",
                "cert_to_commit": {"count": 0, "mean_virtual_s": None},
                "ok": False,
                "crashed": f"{type(exc).__name__}: {exc}",
                "verdicts": {
                    "safety": {"ok": False, "nodes": {}, "cross_node": {}},
                    "liveness": {"ok": False, "nodes": {}},
                    "detection": {"ok": False, "expected": [], "fired": [],
                                  "missing": []},
                },
                "commit_sequences": {},
                "events": [],
                "schedule": {"seed": run_seed, "ticks": 0,
                             "permutations": 0, "jumps": 0,
                             "virtual_s": None},
                "wall": {"wall_s": None, "compression": None,
                         "capped_jumps": 0},
            }

    for k, (fuzz_seed, obj) in enumerate(specs):
        for arm in arms:
            scenario = parse_scenario(obj, env={})
            run_seed = base + 10_000 + k
            art = guarded(
                scenario, run_seed,
                os.path.join(args.workdir, f"pt{k}-{arm}-{scenario.name}"),
                commit_rule=arm,
            )
            sizes_seen.add(scenario.nodes)
            summary = _point_summary(art)
            points.append(summary)
            if arm not in first:
                first[arm] = (obj, run_seed, deterministic_blob(art))
            status = "ok" if art["ok"] else "FAILED"
            if not args.quiet:
                # wall_s/compression are None on the virtual-timeout path
                # — exactly the point whose progress line must not crash
                # before its repro is dumped below.
                wall = summary["wall_s"]
                c2c = (summary["cert_to_commit"] or {}).get("mean_virtual_s")
                print(
                    f"[{k + 1}/{len(specs)}] {scenario.name}"
                    f" n={scenario.nodes} arm={arm}"
                    f" run_seed={run_seed}: {status}"
                    f" ({'timeout' if wall is None else f'{wall:.1f}s wall'},"
                    f" {summary['compression']}x, c2c {c2c}s)"
                )
            if not art["ok"]:
                failures.append(
                    f"point {scenario.name} ({arm} arm) failed its verdicts"
                )
                path = _dump_repro(
                    args.artifact, f"{scenario.name}-{arm}-{run_seed}", obj,
                    run_seed, art,
                )
                print(f"  repro: {path}", file=sys.stderr)

    # -- clean controls per size per arm ---------------------------------------
    controls = []
    for n in sorted(sizes_seen):
        for arm in arms:
            obj = {
                "name": f"sim_control_n{n}", "nodes": n, "workers": 1,
                "rate": 600, "tx_size": 512,
                "duration": 25, "seed": base ^ n,
            }
            scenario = parse_scenario(obj, env={})
            art = guarded(
                scenario, base + 20_000 + n,
                os.path.join(args.workdir, f"control-{arm}-n{n}"),
                commit_rule=arm,
            )
            controls.append(_point_summary(art))
            if not art["ok"]:
                failures.append(
                    f"control n={n} ({arm} arm) failed (fired: "
                    f"{art['verdicts']['detection']['fired']})"
                )
                _dump_repro(args.artifact, f"control-{arm}-n{n}", obj,
                            base + 20_000 + n, art)
            if not args.quiet:
                print(
                    f"[control n={n} {arm}] "
                    f"{'ok' if art['ok'] else 'FAILED'}"
                )

    # -- determinism pin per arm -----------------------------------------------
    determinism = []
    for arm in arms:
        if arm not in first:
            continue
        obj, run_seed, blob = first[arm]
        again = run_sim_scenario(
            parse_scenario(obj, env={}), run_seed,
            os.path.join(args.workdir, f"determinism-rerun-{arm}"),
            commit_rule=arm,
        )
        pin = {
            "name": obj["name"],
            "commit_rule": arm,
            "run_seed": run_seed,
            "bit_identical": deterministic_blob(again) == blob,
        }
        determinism.append(pin)
        if not pin["bit_identical"]:
            failures.append(
                f"determinism pin: two runs of ({obj['name']}, "
                f"run_seed={run_seed}, {arm} arm) produced different "
                "artifacts"
            )
        if not args.quiet:
            print(
                f"[determinism {arm}] bit_identical={pin['bit_identical']}"
            )

    # -- mutation arms (per commit rule: each arm's oracle must catch a
    # planted sequence corruption, or a flag-flip sweep's safety gate is
    # vacuous for that arm.  The schedule-dependent racy plant gates at
    # sweep level — see the module docstring for why its window shape is
    # rule-dependent and which harness manifests it per rule) ------------------
    mutation = []
    if not args.skip_mutation:
        for arm in arms:
            m = run_mutation_arms(args, base, arm)
            mutation.append(m)
            if not m["corruption_caught"]:
                failures.append(
                    f"mutation arm ({arm}): planted CorruptingConsensus "
                    "(deterministic dropped + re-committed certificates) "
                    "was not caught by a safety verdict — this arm's "
                    "oracle is not judging its own sequences"
                )
            if not m["byzantine_caught"]:
                failures.append(
                    f"mutation arm ({arm}): fuzzed Byzantine draw with "
                    "stripped expectations fired none of its contract "
                    "rules"
                )
        if mutation and not any(m["racy_caught"] for m in mutation):
            failures.append(
                "mutation arms: planted RacyConsensus was caught under "
                "NO commit-rule arm — the explored schedules lost the "
                "await-window race entirely (race_explore.py is the "
                "dedicated instrument if this regresses)"
            )

    # -- acceptance arm: N=20, 60 virtual seconds, per commit rule -------------
    acceptance = []
    if not args.skip_acceptance:
        for arm in arms:
            obj = generate(base + 31_337, sizes=(20,))
            obj["name"] = "sim_accept_n20_60s"
            obj["duration"] = max(60, obj["duration"])
            scenario = parse_scenario(obj, env={})
            art = guarded(
                scenario, base + 31_337,
                os.path.join(args.workdir, f"accept-{arm}-n20"),
                commit_rule=arm,
            )
            acc = _point_summary(art)
            acc["behaviors"] = [b.behaviors for b in scenario.byzantine]
            acceptance.append(acc)
            if not art["ok"]:
                failures.append(
                    f"acceptance arm (N=20, 60 virtual s, {arm}) failed "
                    "its verdicts"
                )
                _dump_repro(args.artifact, f"accept-{arm}-n20", obj,
                            base + 31_337, art)
            comp = acc["compression"] or 0.0
            if comp < _MIN_COMPRESSION:
                failures.append(
                    f"acceptance arm ({arm}) compression {comp}x is below "
                    f"the {_MIN_COMPRESSION}x floor"
                )
            if not args.quiet:
                wall = acc["wall_s"]
                print(
                    f"[acceptance {arm}] N=20 60 virtual s: "
                    + ("timeout" if wall is None else f"{wall:.2f}s wall")
                    + f", {comp}x compression"
                )

    # -- virtual-time latency pricing ------------------------------------------
    # Weighted committee-wide mean cert→commit per arm over every sweep
    # point (weights = per-point commit counts).  Virtual time carries no
    # host noise, so the ratio IS the protocol-cadence claim.
    latency = {}
    for arm in arms:
        total_s, total_n = 0.0, 0
        for s in points:
            if s["commit_rule"] != arm:
                continue
            c2c = s.get("cert_to_commit") or {}
            if c2c.get("mean_virtual_s") is not None:
                total_s += c2c["mean_virtual_s"] * c2c["count"]
                total_n += c2c["count"]
        latency[arm] = {
            "commits": total_n,
            "mean_virtual_s": (
                round(total_s / total_n, 6) if total_n else None
            ),
        }
    if len(arms) > 1 and latency.get("classic", {}).get("mean_virtual_s"):
        # One speedup ratio per non-classic arm; >1.0 means the arm
        # commits faster than classic in virtual time.
        for arm in arms:
            if arm == "classic" or not latency[arm]["mean_virtual_s"]:
                continue
            latency[f"classic_over_{arm}"] = round(
                latency["classic"]["mean_virtual_s"]
                / latency[arm]["mean_virtual_s"],
                3,
            )
    if not args.quiet and latency:
        print(f"[latency] {json.dumps(latency)}")

    artifact = {
        "generated_by": "benchmark/sim_bench.py",
        "ok": not failures,
        "failures": failures,
        "commit_rule_arms": arms,
        "points_explored": len(points),
        "latency": latency,
        "sizes": sorted(sizes_seen),
        "points": points,
        "controls": controls,
        "determinism": determinism,
        "mutation": mutation,
        "acceptance": acceptance,
        "spec_dump": spec_dump,
    }
    if args.artifact:
        os.makedirs(os.path.dirname(args.artifact) or ".", exist_ok=True)
        with open(args.artifact, "w") as f:
            json.dump(artifact, f, indent=1)
        print(f"artifact -> {args.artifact}")

    if failures:
        print("sim-bench: FAILED", file=sys.stderr)
        for msg in failures:
            print(f"  - {msg}", file=sys.stderr)
        return 1
    print(
        f"sim-bench: {len(points)} points across sizes {sorted(sizes_seen)} "
        "all judged ok; controls clean; determinism pinned; mutations caught"
    )
    return 0


class CorruptingConsensus:
    """Deterministic commit-sequence corruption: the per-rule safety
    judge's non-vacuity article.  Wraps the live rule's
    ``process_certificate`` to DROP the first certificate of the third
    non-empty commit burst and RE-COMMIT a stale certificate on the
    fifth — the two corruption classes the golden-replay judge exists
    for (PR 6's restart left a permanent commit-log hole = a drop; a
    racy staging list re-delivered a burst = a duplicate).  Both flow
    through the real audit ('C' records) and delivery path, so the
    arm's segment MUST diverge from the arm's own frozen oracle on the
    FIRST schedule — under either commit rule, which is what the
    schedule-dependent RacyConsensus plant cannot guarantee (see the
    module docstring).

    Built as a mixin-style factory rather than a subclass of Consensus:
    the corruption point is the rule output, not the event loop."""

    def __new__(cls, *args, **kwargs):
        from narwhal_tpu.consensus import Consensus

        self = Consensus(*args, **kwargs)
        inner = self.tusk.process_certificate
        state = {"bursts": 0, "stale": None}

        def corrupt(certificate):
            seq = inner(certificate)
            if seq:
                state["bursts"] += 1
                if state["stale"] is None:
                    state["stale"] = seq[0]
                if state["bursts"] == 3:
                    seq = seq[1:]            # dropped commit
                elif state["bursts"] == 5:
                    seq = seq + [state["stale"]]  # re-commit
            return seq

        self.tusk.process_certificate = corrupt
        return self


def run_mutation_arms(args, base: int, commit_rule: str = "classic") -> dict:
    """The non-vacuity proof: the harness must CATCH what it claims to.

    (a) corrupting consensus — node 0 runs ``CorruptingConsensus``
    (deterministic dropped + re-committed certificates) and the FIRST
    schedule must fail a safety verdict, per arm — the proof that THIS
    arm's oracle judges its own sequences;
    (b) racy consensus — node 0 runs ``RacyConsensus`` (the PR 10
    found-race shape, imported from race_explore so the two harnesses
    can never drift apart); whether an explored schedule manifests it
    is recorded per arm, gated at sweep level (module docstring);
    (c) planted Byzantine — a fuzzed adversarial draw runs with its
    ``expect.rules`` stripped, and the detection plane must fire its
    contract rules anyway."""
    from benchmark.race_explore import RacyConsensus

    corrupt_obj = {
        "name": "sim_mut_corrupt", "nodes": 4, "workers": 1, "rate": 600,
        "tx_size": 256, "duration": 15, "seed": base ^ 0xC0DE,
    }
    corrupt_art = run_sim_scenario(
        parse_scenario(corrupt_obj, env={}), base + 29_000,
        os.path.join(args.workdir, f"mut-corrupt-{commit_rule}"),
        consensus_cls_by_node={0: CorruptingConsensus},
        commit_rule=commit_rule,
    )
    corruption_caught = not corrupt_art["verdicts"]["safety"]["ok"]

    racy_runs = []
    racy_hit = None
    clean_obj = {
        "name": "sim_mut_racy", "nodes": 4, "workers": 1, "rate": 600,
        "tx_size": 256, "duration": 15, "seed": base ^ 0xACE,
    }
    for attempt in range(args.mutation_seeds):
        run_seed = base + 30_000 + attempt
        art = run_sim_scenario(
            parse_scenario(clean_obj, env={}), run_seed,
            os.path.join(args.workdir, f"mut-racy-{commit_rule}-{attempt}"),
            consensus_cls_by_node={0: RacyConsensus},
            commit_rule=commit_rule,
        )
        racy_runs.append({
            "run_seed": run_seed,
            "safety_ok": art["verdicts"]["safety"]["ok"],
        })
        if not art["verdicts"]["safety"]["ok"]:
            racy_hit = run_seed
            break

    byz_obj = None
    probe = 0
    while byz_obj is None:
        candidate = generate(base + 40_000 + probe, sizes=(4,))
        if candidate.get("byzantine") and "crash" not in candidate:
            byz_obj = candidate
        probe += 1
    expected = list(byz_obj["expect"]["rules"])
    stripped = dict(byz_obj, name="sim_mut_byz", expect={"rules": []})
    art = run_sim_scenario(
        parse_scenario(stripped, env={}), base + 41_000,
        os.path.join(args.workdir, f"mut-byz-{commit_rule}"),
        commit_rule=commit_rule,
    )
    fired = art["verdicts"]["detection"]["fired"]
    byz_caught = bool(set(expected) & set(fired))

    if not args.quiet:
        print(
            f"[mutation {commit_rule}] corruption: "
            + ("caught" if corruption_caught else "NOT caught")
            + "; racy: "
            + (f"caught at run_seed {racy_hit}" if racy_hit is not None
               else f"NOT caught in {len(racy_runs)} schedules")
            + f"; byzantine (stripped {expected}): fired {fired}"
        )
    return {
        "commit_rule": commit_rule,
        "corruption_caught": corruption_caught,
        "corruption_violations": [
            v
            for _, nv in sorted(
                corrupt_art["verdicts"]["safety"]["nodes"].items()
            )
            for v in nv.get("violations", [])
        ][:4],
        "racy_runs": racy_runs,
        "racy_caught": racy_hit is not None,
        "racy_seed": racy_hit,
        "byzantine_spec": byz_obj["name"],
        "byzantine_expected": expected,
        "byzantine_fired": fired,
        "byzantine_caught": byz_caught,
    }


def run_replay(args) -> int:
    """Re-run one dumped point (a repro file or a bare spec JSON)."""
    with open(args.replay) as f:
        obj = json.load(f)
    run_seed = args.run_seed
    # Explicit --commit-rule wins; else the rule RECORDED in the repro
    # (the arm that failed); else the resolver default.  `both` is a
    # sweep concept, not a single replay's.
    rule = (
        None if args.commit_rule in ("both", "all") else args.commit_rule
    )
    if "spec" in obj and isinstance(obj["spec"], dict):
        if run_seed is None and "run_seed" in obj:
            run_seed = int(obj["run_seed"])
        if rule is None and obj.get("commit_rule") in (
            "classic", "lowdepth", "multileader",
        ):
            rule = obj["commit_rule"]
        obj = obj["spec"]
    scenario = parse_scenario(obj, env={})
    art = run_sim_scenario(
        scenario, run_seed if run_seed is not None else 0,
        os.path.join(args.workdir, f"replay-{scenario.name}"),
        commit_rule=rule,
    )
    print(json.dumps(_point_summary(art), indent=1))
    for k, v in art["verdicts"].items():
        if not v["ok"]:
            print(f"{k} FAILED: {json.dumps(v)[:2000]}", file=sys.stderr)
    return 0 if art["ok"] else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="sim-bench")
    ap.add_argument("--points", type=int, default=200,
                    help="fuzzed sweep points (seed x fault x size)")
    ap.add_argument("--seed-base", type=int, default=7_000,
                    help="base seed (NARWHAL_SIM_SEED overrides)")
    ap.add_argument("--artifact", default=None)
    ap.add_argument("--workdir", default=".sim_bench")
    ap.add_argument("--mutation-seeds", type=int, default=12,
                    help="max schedules to try for the racy arm")
    ap.add_argument(
        "--commit-rule",
        choices=["classic", "lowdepth", "multileader", "both", "all"],
        default=None,
        help="Commit rule for every committee in the sweep; `both` "
        "(classic+lowdepth) and `all` (classic+lowdepth+multileader) "
        "are the flag-flip sweeps — every fuzzed point, control, "
        "mutation and acceptance arm runs under EACH rule, safety "
        "judged against the arm's own frozen oracle, with per-arm "
        "virtual-time cert→commit means pricing the latency claim",
    )
    ap.add_argument("--skip-mutation", action="store_true")
    ap.add_argument("--skip-acceptance", action="store_true")
    ap.add_argument("--replay", default=None,
                    help="re-run one repro/spec JSON instead of sweeping")
    ap.add_argument("--run-seed", type=int, default=None,
                    help="with --replay: the schedule seed to replay")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)
    if args.replay:
        return run_replay(args)
    return run_sweep(args)


if __name__ == "__main__":
    sys.exit(main())
