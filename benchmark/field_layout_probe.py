"""A/B the GF(2^255-19) limb layouts — the probe that mis-predicted, kept
as the cautionary record.

field25519 stores an element limbs-MINOR, int32[..., 32] with the limb
axis on the VPU lane dimension.  A mid-round-5 refactor flipped it to
limbs-major int32[32, B] on this probe's CPU-backend evidence (~4-5× for
the mul chain, 78→390 verifies/s for the full kernel): with the batch
minor-most every lane does useful work, where limbs-minor fills only 63 of
128 lanes during the convolution.  The real chip then measured the full
verify kernel 2× SLOWER limbs-major (168 → 317 ms/2048-batch; a
[32, B/128, 128] batch-blocked variant recovered only to 211 ms — both
runs recorded in artifacts/crypto_bench_r05_limbs_major.json, the
restored-layout run in artifacts/crypto_bench_r05.json).  Lane occupancy
is not the binding constraint
on v5e — locality is: limbs-minor keeps a field element's entire 63-limb
convolution row inside one (8, 128) tile, so the 32 shifted accumulates
stay register-resident, while any limbs-major variant spreads one element
across 32+ tiles and pays tile traffic per accumulate.  The CPU backend
rewards exactly the opposite (contiguous batch vectorization), which is
why it was a bad proxy.  field25519 was restored to limbs-minor; this
probe now measures the live limbs-minor mul against a verbatim copy of
the limbs-major one, as a jitted chain of K dependent field multiplies,
timed via result fetch (the tunnel's ~69 ms fetch floor is reported
separately and subtracted).

    python benchmark/field_layout_probe.py --batch 8192 --chain 256 \
        --out artifacts/field_layout_probe_r05.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from narwhal_tpu.utils.env import env_str  # noqa: E402

BITS, LIMBS, MASK, FOLD = 8, 32, 255, 38


def _mul_limbs_minor(a, b):
    """The LIVE layout (kept as a verbatim inline copy so the probe's two
    arms stay symmetric): limbs on the minor axis, [..., 32] — what
    field25519.mul is."""
    import jax.numpy as jnp

    conv = jnp.zeros(a.shape[:-1] + (2 * LIMBS - 1,), jnp.int32)
    pad_base = [(0, 0)] * (b.ndim - 1)
    for i in range(LIMBS):
        conv = conv + a[..., i : i + 1] * jnp.pad(
            b, pad_base + [(i, LIMBS - 1 - i)]
        )
    hi, lo = conv[..., LIMBS:], conv[..., :LIMBS]
    c = lo.at[..., : LIMBS - 1].add(hi * FOLD)
    for _ in range(4):
        h = c >> BITS
        c = (c & MASK).at[..., 1:].add(h[..., :-1])
        c = c.at[..., 0].add(h[..., -1] * FOLD)
    return c


def _mul_limbs_major(a, b):
    """The abandoned limbs-major layout, reproduced verbatim from the
    reverted refactor: element is [32, batch...], each convolution term a
    scalar-slice times the whole operand at limb offset i."""
    import jax.numpy as jnp

    conv = jnp.zeros((2 * LIMBS - 1,) + a.shape[1:], jnp.int32)
    for i in range(LIMBS):
        conv = conv.at[i : i + LIMBS].add(a[i][None] * b)
    hi, lo = conv[LIMBS:], conv[:LIMBS]
    c = lo.at[: LIMBS - 1].add(hi * FOLD)
    for _ in range(4):
        h = c >> BITS
        c = (c & MASK).at[1:].add(h[:-1])
        c = c.at[0].add(h[-1] * FOLD)
    return c


def _chain(mul, k):
    import jax
    import jax.numpy as jnp
    from jax import lax

    @jax.jit
    def run(a, b):
        def step(c, _):
            return mul(c, b), None

        c, _ = lax.scan(step, a, None, length=k)
        return c

    return run


def _time_fetch(fn, args, reps):
    np.asarray(fn(*args))  # warm/compile
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        np.asarray(fn(*args))
        ts.append(time.perf_counter() - t0)
    return sorted(ts)[len(ts) // 2]


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=8192)
    ap.add_argument("--chain", type=int, default=256)
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    a = rng.integers(0, 256, (args.batch, LIMBS), dtype=np.int32)
    b = rng.integers(0, 256, (args.batch, LIMBS), dtype=np.int32)

    # Drift guard (ADVICE.md r05): _mul_limbs_minor is a hand-maintained
    # copy of the live field25519.mul int32 path; any future edit to the
    # live mul would silently desynchronize the A/B arms.  Cross-check the
    # copy against the LIVE mul on a random sub-batch before measuring, so
    # drift fails loudly here instead of corrupting layout comparisons.
    if env_str("NARWHAL_FIELD_DTYPE") == "int32":
        from narwhal_tpu.ops import field25519 as F

        k = min(args.batch, 512)
        live = np.asarray(F.mul(jnp.asarray(a[:k]), jnp.asarray(b[:k])))
        copy = np.asarray(
            jax.jit(_mul_limbs_minor)(jnp.asarray(a[:k]), jnp.asarray(b[:k]))
        )
        if not (live == copy).all():
            raise SystemExit(
                "field_layout_probe: _mul_limbs_minor has DRIFTED from the "
                "live field25519.mul — update the inline copy before "
                "trusting any layout measurement from this probe"
            )
    else:
        print(
            "NOTE: NARWHAL_FIELD_DTYPE != int32; live-mul drift guard "
            "skipped (the probe's arms are the int32 layouts)",
            file=sys.stderr,
        )

    # Fetch floor: trivial jitted compute + fetch.
    f = jax.jit(lambda x: x + 1)
    x = jnp.zeros(8, jnp.int32)
    floor = _time_fetch(f, (x,), args.reps)

    minor = _chain(_mul_limbs_minor, args.chain)
    t_minor = _time_fetch(minor, (jnp.asarray(a), jnp.asarray(b)), args.reps)

    major = _chain(_mul_limbs_major, args.chain)
    t_major = _time_fetch(
        major, (jnp.asarray(a.T.copy()), jnp.asarray(b.T.copy())), args.reps
    )

    # Cross-check the layouts agree.
    got_minor = np.asarray(minor(jnp.asarray(a), jnp.asarray(b)))
    got_major = np.asarray(
        major(jnp.asarray(a.T.copy()), jnp.asarray(b.T.copy()))
    ).T
    assert (got_minor == got_major).all(), "layouts disagree"

    per_mul = lambda t: (t - floor) / args.chain * 1e6  # noqa: E731
    result = {
        "device": str(jax.devices()[0]),
        "batch": args.batch,
        "chain_muls": args.chain,
        "fetch_floor_ms": round(floor * 1e3, 2),
        "limbs_minor_us_per_batched_mul": round(per_mul(t_minor), 2),
        "limbs_major_us_per_batched_mul": round(per_mul(t_major), 2),
        "major_over_minor_speedup": round(
            (t_minor - floor) / max(t_major - floor, 1e-9), 2
        ),
    }
    print(json.dumps(result))
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f_:
            json.dump(result, f_, indent=2)


if __name__ == "__main__":
    main()
