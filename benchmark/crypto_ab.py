"""Paired interleaved crypto A/B: serial per-burst verify vs the
batched arm (ROADMAP item 1, the r09/r10 A/B methodology).

Arms differ ONLY in the crypto plane:

- **serial** — today's live path: ``--crypto-backend cpu`` with the
  verify-batch window off (one ``averify_batch_mask`` per drained Core
  burst; r12 measured mean batch 3.6).
- **batched** — the deepened path: ``NARWHAL_VERIFY_BATCH_WINDOW_MS``
  coalescing cross-message-type claims from multiple drains into one
  backend dispatch through the pipelined Core verify stage, on the
  backend picked by ``--batched-backend`` (``jax``/``tpu`` = the
  device verifier; ``cpu`` = the same serial crypto in device-sized
  batches — the arm for hosts where no chip is reachable and the
  jax-cpu kernel measures slower than pure Python, the honest-verdict
  fallback this repo's r06 kernel demotion set the precedent for).

Arms are interleaved (serial, batched, serial, batched, ...) so slow
host drift hits both equally.  Gates, all ledger-read:

- zero run errors and ``protocol_check`` within 5% on BOTH arms (the
  batching must change dispatch shape, never protocol arithmetic);
- the batched arm's ``crypto.verify.batch_size.batch_burst`` mean must
  be >= the serial arm's, and is compared against ``--min-batch-mean``
  (default 16, the ISSUE 14 acceptance bar over the r12 baseline 3.6);
- batched committed TPS no worse than serial beyond ``--tps-tolerance``.

The artifact records both arms' crypto ledgers, the round_attribution
verify legs (header_broadcast→first_vote and
cert_broadcast→parent_quorum — the two peer-verify round-trip legs the
r10 attribution blamed for 72-75% of the round period), and the gate
verdicts.  Keys are ``serial_runs``/``batched_runs`` — deliberately NOT
``runs`` so benchmark/trajectory.py does not read a fixed-rate A/B as a
saturation-series point.

    python benchmark/crypto_ab.py --pairs 2 --duration 10 \
        --artifact artifacts/crypto_ab_r19.json
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmark.local_bench import run_bench  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The two ROUND_STAGES legs that contain the peer signature-verify round
# trips (r10 attribution): our header broadcast -> first peer vote back,
# and our cert broadcast -> the parent quorum completing.
VERIFY_LEGS = (
    "header_broadcast_to_first_vote",
    "cert_broadcast_to_parent_quorum",
)


def _one_run(arm: str, idx: int, args) -> dict:
    batched = arm == "batched"
    result = run_bench(
        nodes=args.nodes,
        workers=1,
        rate=args.rate,
        tx_size=args.tx_size,
        duration=args.duration,
        base_port=args.base_port,
        workdir=os.path.join(REPO, ".bench_crypto_ab"),
        quiet=True,
        progress_wait=args.progress_wait,
        crypto_backend=(args.batched_backend if batched else "cpu"),
        verify_window_ms=(args.window_ms if batched else 0.0),
    )
    crypto = result.crypto or {}
    burst = (crypto.get("verify") or {}).get("batch_burst") or {}
    return {
        "arm": arm,
        "run": idx,
        "errors": result.errors,
        "consensus_tps": result.consensus_tps,
        "consensus_latency_ms": result.consensus_latency_ms,
        "end_to_end_tps": result.end_to_end_tps,
        "end_to_end_latency_ms": result.end_to_end_latency_ms,
        "committed_bytes": result.committed_bytes,
        "batch_burst": burst,
        "crypto": crypto,
        "round_stages_ms": result.round_stages_ms,
        "verify_legs_ms": {
            leg: (result.round_stages_ms or {}).get(leg)
            for leg in VERIFY_LEGS
        },
    }


def _median(vals):
    vals = [v for v in vals if v is not None]
    return round(statistics.median(vals), 3) if vals else None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--pairs", type=int, default=2)
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--rate", type=int, default=3_000)
    ap.add_argument("--tx-size", type=int, default=512)
    ap.add_argument("--duration", type=int, default=10)
    ap.add_argument("--base-port", type=int, default=7600)
    ap.add_argument("--progress-wait", type=float, default=30.0)
    ap.add_argument(
        "--batched-backend", choices=["jax", "tpu", "cpu"], default="jax",
        help="Backend for the batched arm: jax/tpu = the device "
        "verifier (requires a reachable chip or a usably fast jax-cpu); "
        "cpu = window-deepened serial crypto (deviceless hosts)",
    )
    ap.add_argument(
        "--window-ms", type=float, default=25.0,
        help="NARWHAL_VERIFY_BATCH_WINDOW_MS for the batched arm",
    )
    ap.add_argument(
        "--min-batch-mean", type=float, default=16.0,
        help="Required batched-arm crypto.verify.batch_size.batch_burst "
        "mean (ISSUE 14 acceptance bar; r12 serial baseline 3.6)",
    )
    ap.add_argument(
        "--tps-tolerance", type=float, default=0.25,
        help="Batched median committed TPS may be at most this fraction "
        "below serial (shared-core noise floor)",
    )
    ap.add_argument(
        "--verdict-note", default=None,
        help="Free-text honest-verdict note recorded as the artifact's "
        "`host_verdict` (the r09/r10 convention for gates the host "
        "cannot meet: say WHY, with the measurements)",
    )
    ap.add_argument("--artifact", default="artifacts/crypto_ab_r19.json")
    args = ap.parse_args(argv)

    runs = {"serial": [], "batched": []}
    for i in range(args.pairs):
        for arm in ("serial", "batched"):
            print(f"== crypto A/B pair {i + 1}/{args.pairs}: {arm} arm ==")
            r = _one_run(arm, i, args)
            runs[arm].append(r)
            print(
                f"   committed TPS {r['consensus_tps']:,.0f}, "
                f"batch_burst mean {r['batch_burst'].get('mean_batch')}, "
                f"verify legs {r['verify_legs_ms']}"
            )

    failures = []
    for r in runs["serial"] + runs["batched"]:
        if r["errors"]:
            failures.append(f"{r['arm']} run {r['run']}: {r['errors'][:3]}")
        check = (r["crypto"] or {}).get("protocol_check") or {}
        for kind in ("votes", "certificates"):
            ratio = (check.get(kind) or {}).get("ratio")
            if ratio is None or abs(ratio - 1.0) > 0.05:
                failures.append(
                    f"{r['arm']} run {r['run']}: protocol_check.{kind} "
                    f"ratio {ratio}"
                )

    mean_serial = _median(
        [r["batch_burst"].get("mean_batch") for r in runs["serial"]]
    )
    mean_batched = _median(
        [r["batch_burst"].get("mean_batch") for r in runs["batched"]]
    )
    tps_serial = _median([r["consensus_tps"] for r in runs["serial"]])
    tps_batched = _median([r["consensus_tps"] for r in runs["batched"]])
    if mean_serial is None or mean_batched is None:
        failures.append("batch_burst mean missing from an arm's ledger")
    else:
        if mean_batched < mean_serial:
            failures.append(
                f"batched batch_burst mean {mean_batched} < serial "
                f"{mean_serial} — the window did not deepen bursts"
            )
        if mean_batched < args.min_batch_mean:
            failures.append(
                f"batched batch_burst mean {mean_batched} < required "
                f"{args.min_batch_mean}"
            )
    if tps_serial and tps_batched is not None and (
        tps_batched < tps_serial * (1 - args.tps_tolerance)
    ):
        failures.append(
            f"batched median committed TPS {tps_batched:,.0f} more than "
            f"{args.tps_tolerance:.0%} below serial {tps_serial:,.0f}"
        )

    summary = {
        "batched_backend": args.batched_backend,
        "window_ms": args.window_ms,
        "batch_burst_mean": {"serial": mean_serial, "batched": mean_batched},
        "consensus_tps": {"serial": tps_serial, "batched": tps_batched},
        "verify_legs_ms": {
            arm: {
                leg: _median(
                    [r["verify_legs_ms"].get(leg) for r in arm_runs]
                )
                for leg in VERIFY_LEGS
            }
            for arm, arm_runs in runs.items()
        },
        "gates_failed": failures,
    }

    artifact = {
        "what": (
            "Paired interleaved crypto A/B (ISSUE 14): serial per-burst "
            "verify (cpu backend, window off) vs the batched arm "
            f"(backend {args.batched_backend}, "
            f"NARWHAL_VERIFY_BATCH_WINDOW_MS={args.window_ms}) on a "
            f"{args.nodes}-node local_bench, rate {args.rate}, "
            f"{args.tx_size} B tx, {args.duration} s windows."
        ),
        "serial_runs": runs["serial"],
        "batched_runs": runs["batched"],
        "summary": summary,
    }
    if args.verdict_note:
        artifact["host_verdict"] = args.verdict_note
    os.makedirs(os.path.dirname(args.artifact) or ".", exist_ok=True)
    with open(args.artifact, "w") as f:
        json.dump(artifact, f, indent=1)

    print("== crypto A/B summary ==")
    print(json.dumps(summary, indent=1))
    if failures:
        print(f"crypto A/B FAILED: {failures}", file=sys.stderr)
        return 1
    print(
        f"crypto A/B ok: batch_burst mean {mean_serial} -> {mean_batched} "
        f"at committed TPS {tps_serial:,.0f} -> {tps_batched:,.0f}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
