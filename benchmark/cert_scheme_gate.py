"""Paired per-scheme sim wire captures + the cert-scheme flip gate
(ISSUE 20 tentpole pricing).

Runs TWO deterministic sim captures at ``--nodes`` — one per
certificate-signature scheme (``individual`` then ``halfagg``), same
seed/rate/duration — through :mod:`benchmark.sim_wire_capture`, then
gates the pair:

* ``halfagg`` verify ops/cert must be exactly 1 (the one
  ``certificate_agg`` multiexp per certificate — the whole point);
* ``cert_sig_bytes_fraction`` under ``halfagg`` must be <= 0.5;
* cert bytes/frame under ``halfagg`` must be < 0.75x ``individual``.

HONEST-THRESHOLD NOTE (read before "fixing" these numbers): ISSUE 20
asks for fraction <= 0.25 and frame ratio < 0.6x.  Those targets price
a *pairing-based* aggregate (one 48/96-byte BLS blob regardless of
quorum).  This container has no pairing library and the no-new-deps
rule stands, so the shipped scheme is CGKN ed25519 half-aggregation:
the scalar halves fold into one 32-byte value but every nonce
commitment R_i must ship, giving 32*(q+1)+64 signature bytes per cert
against q*68+64 individual (wire v2, key-ref'd signers).  At N=20
(q=14) that is 558 vs 974 B — fraction ~0.49, frame ratio ~0.73x —
which is the cryptographic floor for half-aggregation, not a tuning
shortfall.  The gate therefore holds the scheme to ITS OWN floor
(<=0.5 / <0.75x) instead of silently passing a target it cannot
mathematically reach; the 0.25/0.6 figures stay recorded in
``benchmark/trajectory_gate.json`` as the pairing-backend follow-up.

    python benchmark/cert_scheme_gate.py --nodes 20 \
        --artifact .ci-artifacts/cert_scheme_gate_n20.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmark.sim_wire_capture import capture  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The half-aggregation floor (see module docstring) — NOT the ISSUE 20
# pairing-backend targets.
MAX_SIG_FRACTION_HALFAGG = 0.5
MAX_FRAME_RATIO = 0.75


def _cert_frame_bytes(art: dict) -> float | None:
    certs = art["wire"].get("out", {}).get("certificate", {})
    if not certs.get("frames"):
        return None
    return certs["bytes"] / certs["frames"]


def _agg_verify_ops_per_cert(art: dict) -> float | None:
    """ops at the certificate_agg site / certificates verified."""
    sites = (art.get("crypto") or {}).get("verify") or {}
    agg = sites.get("certificate_agg") or {}
    ops = agg.get("ops")
    calls = agg.get("calls")
    if not calls:
        return None
    return ops / calls


def run_gate(nodes: int, duration: int, rate: int, seed: int,
             workdir: str) -> dict:
    arms = {}
    for scheme in ("individual", "halfagg"):
        arms[scheme] = capture(
            nodes, duration, rate, seed, workdir,
            cert_sig_scheme=scheme,
        )

    ind, hag = arms["individual"], arms["halfagg"]
    ind_bpf = _cert_frame_bytes(ind)
    hag_bpf = _cert_frame_bytes(hag)
    frame_ratio = (
        round(hag_bpf / ind_bpf, 4) if ind_bpf and hag_bpf else None
    )
    hag_fraction = hag["headline"]["cert_sig_bytes_fraction"]
    ops_per_cert = _agg_verify_ops_per_cert(hag)

    checks = {
        "halfagg_verify_ops_per_cert_is_1": (
            ops_per_cert is not None and abs(ops_per_cert - 1.0) < 1e-9
        ),
        "both_arms_verdicts_ok": bool(
            ind["verdicts_ok"] and hag["verdicts_ok"]
        ),
        "scheme_gauges_distinct": (
            ind["headline"]["cert_sig_scheme"] == "individual"
            and hag["headline"]["cert_sig_scheme"] == "halfagg"
        ),
    }
    # The byte thresholds are committee-size-dependent (the non-
    # signature frame overhead — parents, payload digests — shrinks
    # relative to the signature block as N grows; at N=10 the halfagg
    # FLOOR itself sits at fraction ~0.52 / ratio ~0.77).  They gate
    # at N>=20 — the size the ROADMAP item prices — and are recorded
    # but non-binding below it.
    size_checks = {
        "halfagg_sig_fraction_le_0.5": (
            hag_fraction is not None
            and hag_fraction <= MAX_SIG_FRACTION_HALFAGG
        ),
        "halfagg_frame_lt_0.75x_individual": (
            frame_ratio is not None and frame_ratio < MAX_FRAME_RATIO
        ),
    }
    size_thresholds_apply = nodes >= 20
    if size_thresholds_apply:
        checks.update(size_checks)
    return {
        "generated_by": "benchmark/cert_scheme_gate",
        "what": (
            f"Paired per-scheme sim wire captures at N={nodes} "
            "(same seed/rate/duration) + the cert-scheme flip gate. "
            "Thresholds are the ed25519 half-aggregation floor "
            "(<=0.5 sig fraction, <0.75x frame) — the ISSUE 20 "
            "0.25/0.6 targets need a pairing aggregate; see the "
            "module docstring and trajectory_gate.json."
        ),
        "nodes": nodes,
        "headline": {
            "individual": ind["headline"],
            "halfagg": hag["headline"],
            "cert_bytes_per_frame_ratio": frame_ratio,
            "halfagg_verify_ops_per_cert": ops_per_cert,
        },
        "checks": checks,
        "size_thresholds_apply": size_thresholds_apply,
        "size_checks_informational": (
            None if size_thresholds_apply else size_checks
        ),
        "ok": all(checks.values()),
        "arms": arms,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--nodes", type=int, default=20)
    ap.add_argument("--duration", type=int, default=30)
    ap.add_argument("--rate", type=int, default=600)
    ap.add_argument("--seed", type=int, default=90_000)
    ap.add_argument(
        "--workdir", default=os.path.join(REPO, ".sim_wire_capture")
    )
    ap.add_argument(
        "--artifact",
        default=".ci-artifacts/cert_scheme_gate_n20.json",
    )
    args = ap.parse_args(argv)

    art = run_gate(
        args.nodes, args.duration, args.rate, args.seed, args.workdir
    )
    os.makedirs(os.path.dirname(args.artifact) or ".", exist_ok=True)
    with open(args.artifact, "w") as f:
        json.dump(art, f, indent=1)
    print(json.dumps(
        {"headline": art["headline"], "checks": art["checks"]}, indent=1
    ))
    if not art["ok"]:
        print("cert-scheme gate FAILED", file=sys.stderr)
        return 1
    print(f"cert-scheme gate ok at N={args.nodes}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
