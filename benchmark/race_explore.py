"""narwhal-race schedule explorer: run the protocol under N seeded task
interleavings and let the frozen golden oracle judge every outcome.

    python benchmark/race_explore.py --seeds 16 --committee-seeds 4 \
        --artifact artifacts/race_explore.json

Three arms, each independently gated (exit nonzero on any failure):

- **pipeline** (the reference scenario, N ≥ 16 seeds): a 4-authority
  certificate pipeline — a live ``Consensus`` runner, its audit segment,
  a feeder with a FIXED insert order, and the output drains — executed
  under ``ExploringEventLoop(seed)``, which permutes same-tick ready-
  callback order deterministically per seed.  Because the insert order
  is fixed, the commit-rule determinism the whole repo leans on (golden
  oracle, Tusk replay, fault-suite safety verdicts) demands a
  byte-identical commit sequence under EVERY schedule: each seed's
  output is compared byte-for-byte against the golden walk, and the
  recorded audit segment is replayed through ``consensus/replay.py``.
  One seed is additionally run twice to pin determinism (same seed →
  same schedule → same bytes).

- **committee** (socketed arm, default 4 seeds): a full 4-node
  in-process committee — primaries, workers, real TCP, client payload —
  on the exploring loop (the ``tests/test_health_failover.py`` harness
  shape).  Wall-clock and socket timing make cross-seed byte-equality
  meaningless here, so the gate is the safety verdict: per-node
  golden-oracle audit replay plus committee-wide commit-prefix
  consistency, per seed.

- **mutation** (the non-vacuity proof): one *found-race shape* —
  commit batches handed to fire-into-background tasks that share a
  staging list through an await window (``RacyConsensus`` below) — is
  (a) appended to ``consensus/tusk.py`` as an in-memory overlay and
  must be flagged by the static ``interleave-window`` rule, and (b) run
  through the pipeline scenario where at least one seed must produce a
  DIVERGENT commit sequence.  A race detector that cannot catch a
  planted race is dead weight; this arm is what proves both halves are
  alive.

Any divergence dumps the seed plus the diverging prefix into the
artifact (and a ``<artifact>.repro-<seed>.json`` beside it);
``--repro SEED [--mutated]`` re-runs exactly that schedule.
"""

from __future__ import annotations

import argparse
import inspect
import json
import os
import sys
from typing import Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import asyncio  # noqa: E402

from narwhal_tpu import metrics  # noqa: E402
from narwhal_tpu.analysis import run_lint  # noqa: E402
from narwhal_tpu.analysis.schedule import run_with_seed  # noqa: E402
from narwhal_tpu.config import (  # noqa: E402
    Authority,
    Committee,
    Parameters,
    PrimaryAddresses,
    WorkerAddresses,
)
from narwhal_tpu.consensus import Consensus  # noqa: E402
from narwhal_tpu.consensus.golden import GoldenTusk  # noqa: E402
from narwhal_tpu.consensus.golden_lowdepth import (  # noqa: E402
    GoldenLowDepthTusk,
)
from narwhal_tpu.consensus.golden_multileader import (  # noqa: E402
    GoldenMultiLeaderTusk,
)
from narwhal_tpu.consensus.replay import (  # noqa: E402
    cross_node_prefix,
    replay_segments,
)
from narwhal_tpu.crypto import KeyPair, digest32  # noqa: E402
from narwhal_tpu.messages import encode_batch  # noqa: E402
from narwhal_tpu.network.framing import parse_address, write_frame  # noqa: E402
from narwhal_tpu.primary.messages import (  # noqa: E402
    Certificate,
    Header,
    genesis,
)
from narwhal_tpu.utils.tasks import spawn  # noqa: E402

GC_DEPTH = 50
STREAM_ROUNDS = 24
# The committee arm cycles through a handful of port bases below this
# host's ip_local_port_range floor (16000 — see the PR 9 note), so
# sequential seeds never race the OS's outgoing source ports.
PORT_BASES = [15200 + i * 40 for i in range(8)]


# -- fixtures (self-contained: benchmark/ must not depend on tests/) ----------

def fixture_keys(n: int = 4) -> List[KeyPair]:
    return [KeyPair.generate(bytes([i]) * 32) for i in range(n)]


def fixture_committee(base_port: int = 0, workers: int = 1) -> Committee:
    authorities = {}
    port = base_port

    def addr() -> str:
        nonlocal port
        a = f"127.0.0.1:{port}"
        if base_port != 0:
            port += 1
        return a

    for kp in fixture_keys():
        primary = PrimaryAddresses(
            primary_to_primary=addr(), worker_to_primary=addr()
        )
        ws = {
            wid: WorkerAddresses(
                transactions=addr(),
                worker_to_worker=addr(),
                primary_to_worker=addr(),
            )
            for wid in range(workers)
        }
        authorities[kp.name] = Authority(stake=1, primary=primary, workers=ws)
    return Committee(authorities)


def build_stream(committee: Committee) -> List[Certificate]:
    """Fixed certificate stream: one cert per authority for rounds
    1..STREAM_ROUNDS plus a trigger — the closed workload whose commit
    sequence is schedule-independent by protocol contract."""
    names = sorted(kp.name for kp in fixture_keys())
    parents = {c.digest() for c in genesis(committee)}
    stream: List[Certificate] = []
    for round_ in range(1, STREAM_ROUNDS + 1):
        next_parents = set()
        for name in names:
            cert = Certificate(
                header=Header(
                    author=name, round=round_, payload={},
                    parents=set(parents),
                )
            )
            stream.append(cert)
            next_parents.add(cert.digest())
        parents = next_parents
    stream.append(
        Certificate(
            header=Header(
                author=names[0], round=STREAM_ROUNDS + 1, payload={},
                parents=set(parents),
            )
        )
    )
    return stream


def golden_sequence(
    committee: Committee, stream: List[Certificate], rule: str = "classic"
) -> List[bytes]:
    oracle_cls = {
        "lowdepth": GoldenLowDepthTusk,
        "multileader": GoldenMultiLeaderTusk,
    }.get(rule, GoldenTusk)
    golden = oracle_cls(committee, GC_DEPTH, fixed_coin=False)
    out: List[bytes] = []
    for cert in stream:
        out.extend(bytes(x.digest()) for x in golden.process_certificate(cert))
    return out


# -- the reintroduced race (mutation arm) -------------------------------------
#
# This class is BOTH halves' test article: its source is appended to
# consensus/tusk.py as an overlay for the static rule (one source of
# truth — inspect.getsource — so the linted shape and the executed shape
# cannot drift), and it runs live in the pipeline scenario for the
# dynamic half.  The race is the exact window shape the interleave rule
# encodes: the commit backlog is read before the output puts suspend and
# overwritten after they resume, while a second in-flight batch task
# (spawned from inside the drain loop — self-concurrent root) stages its
# own commits into the same list.

class RacyConsensus(Consensus):
    """Reintroduced found-race: background commit-batch tasks sharing one
    staging list across an await window."""

    MAX_DRAIN = 4  # small bursts: keeps several batch tasks in flight

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._committing: List[Certificate] = []

    async def run(self) -> None:
        while True:
            batch = [await self.rx_primary.get()]
            while len(batch) < self.MAX_DRAIN:
                try:
                    batch.append(self.rx_primary.get_nowait())
                except asyncio.QueueEmpty:
                    break
            spawn(self._process_batch(batch), name="racy-commit-batch")

    async def _process_batch(self, batch) -> None:
        for certificate in batch:
            if self._audit is not None:
                self._audit.insert(certificate)
            self._committing.extend(
                self.tusk.process_certificate(certificate)
            )
        backlog = self._committing  # read: aliases the shared list
        for committed in list(backlog):
            if self._audit is not None:
                self._audit.commit(committed)
            await self.tx_primary.put(committed)   # suspends mid-window
            await self.tx_output.put(committed)
        if self._audit is not None:
            self._audit.flush()
        self._committing = []  # write: drops a concurrent task's staging


def static_mutation_findings() -> List[str]:
    """Lint the live tree with RacyConsensus overlaid into tusk.py; the
    interleave-window rule must flag the planted race."""
    rel = "narwhal_tpu/consensus/tusk.py"
    with open(os.path.join(REPO, rel), "r", encoding="utf-8") as f:
        src = f.read()
    overlay = src + "\n\n" + inspect.getsource(RacyConsensus)
    return [
        f.render()
        for f in run_lint(REPO, overlay={rel: overlay})
        if f.rule == "interleave-window" and f.path == rel
    ]


# -- pipeline scenario ---------------------------------------------------------

async def _pipeline(
    consensus_cls,
    committee: Committee,
    stream: List[Certificate],
    audit_path: Optional[str],
    rule: str = "classic",
) -> List[bytes]:
    rx: asyncio.Queue = asyncio.Queue()
    # Capacity 1: every commit-burst put genuinely SUSPENDS (a put into a
    # queue with room returns without yielding, which would keep await
    # windows shut and the whole exploration vacuous).
    tx_primary: asyncio.Queue = asyncio.Queue(maxsize=1)
    tx_output: asyncio.Queue = asyncio.Queue(maxsize=1)
    cons = consensus_cls(
        committee, GC_DEPTH,
        rx_primary=rx, tx_primary=tx_primary, tx_output=tx_output,
        audit_path=audit_path,
        commit_rule=rule,
    )
    loop = asyncio.get_running_loop()
    runner = loop.create_task(cons.run())
    committed: List[bytes] = []

    async def drain_output() -> None:
        while True:
            committed.append(bytes((await tx_output.get()).digest()))

    async def drain_feedback() -> None:
        while True:
            await tx_primary.get()

    drains = [
        loop.create_task(drain_output()),
        loop.create_task(drain_feedback()),
    ]

    async def feeder() -> None:
        for cert in stream:
            await rx.put(cert)
            await asyncio.sleep(0)  # one scheduling point per insert

    feed = loop.create_task(feeder())
    # Quiesce detection runs on VIRTUAL time (the loop is a
    # VirtualClockLoop): each poll is a 1 ms simulated timer, which only
    # fires when every workload task has quiesced — so the poll can
    # never interleave into a busy schedule, and both the idle counting
    # and the deadlock guard below are pure functions of the seed.  The
    # run is done when the feeder finished, every queue drained, every
    # background batch task died, and the commit count held still for 50
    # consecutive quiesce polls.  The guard is a virtual deadline: a
    # schedule-induced hang reaches it in microseconds of wall time and
    # ALWAYS at the same virtual instant for a given seed — a
    # deterministic finding, not a host-speed artifact.
    from narwhal_tpu.utils import tasks as task_util

    guard = loop.time() + 45  # virtual seconds
    guard_tripped = False
    idle, prev = 0, None
    while idle < 50:
        if loop.time() >= guard:
            guard_tripped = True
            break
        await asyncio.sleep(0.001)
        snapshot = (
            len(committed), feed.done(), rx.qsize(),
            tx_primary.qsize(), tx_output.qsize(),
            task_util.alive_count(),
        )
        if (
            snapshot == prev
            and feed.done()
            and rx.qsize() == 0
            and task_util.alive_count() == 0
        ):
            idle += 1
        else:
            idle = 0
        prev = snapshot
    for task in [runner, feed] + drains:
        task.cancel()
    await asyncio.gather(runner, feed, *drains, return_exceptions=True)
    if cons._audit is not None:
        cons._audit.close()
    return committed, guard_tripped


def run_pipeline_seed(
    seed: int, workdir: str, mutated: bool = False, rule: str = "classic"
) -> Dict:
    committee = fixture_committee()
    stream = build_stream(committee)
    want = golden_sequence(committee, stream, rule)
    audit = os.path.join(
        workdir, f"pipeline-{rule}-{'mut-' if mutated else ''}{seed}.audit.bin"
    )
    if os.path.exists(audit):
        os.remove(audit)
    cls = RacyConsensus if mutated else Consensus
    (committed, guard_tripped), stats = run_with_seed(
        lambda: _pipeline(cls, committee, stream, audit, rule),
        seed,
        timeout=90,  # virtual seconds — deterministic per seed
        virtual_time=True,
    )
    verdict = replay_segments(committee, GC_DEPTH, [audit])
    identical = committed == want
    diverged_at = next(
        (i for i, (a, b) in enumerate(zip(committed, want)) if a != b),
        min(len(committed), len(want))
        if len(committed) != len(want)
        else None,
    )
    import hashlib

    return {
        "seed": seed,
        "commit_rule": rule,
        "mutated": mutated,
        "schedule": stats,
        "guard_tripped": guard_tripped,
        "sequence_sha": hashlib.sha256(b"".join(committed)).hexdigest(),
        "commits": len(committed),
        "expected": len(want),
        "identical_to_golden": identical,
        "diverged_at": None if identical else diverged_at,
        "got_at_divergence": (
            None if identical or diverged_at is None
            else [
                d.hex() for d in committed[diverged_at:diverged_at + 3]
            ]
        ),
        "want_at_divergence": (
            None if identical or diverged_at is None
            else [d.hex() for d in want[diverged_at:diverged_at + 3]]
        ),
        "audit_replay_ok": verdict["ok"],
        "audit_violations": verdict["violations"][:5],
        "ok": identical and verdict["ok"],
    }


# -- committee scenario --------------------------------------------------------

def _tx(i: int) -> bytes:
    return bytes([1]) + (0xACE000 + i).to_bytes(8, "little") + bytes(91)


async def _committee(base_port: int, audit_dir: str, rule: str) -> Dict:
    # Imported here: node wiring pulls the crypto backend, which the
    # pipeline-only invocations never need.
    from narwhal_tpu.node import spawn_primary_node, spawn_worker_node

    reg = metrics.registry()
    reg.reset()
    committee = fixture_committee(base_port=base_port)
    params = Parameters(
        header_size=32,
        max_header_delay=100,
        batch_size=400,
        max_batch_delay=100,
    )
    kps = fixture_keys()
    commits: Dict[int, List] = {i: [] for i in range(4)}
    segments: Dict[str, str] = {}
    primaries, workers = [], []
    for i, kp in enumerate(kps):
        audit = os.path.join(audit_dir, f"node{i}.audit.bin")
        if os.path.exists(audit):
            os.remove(audit)
        segments[f"node{i}"] = audit
        primaries.append(
            await spawn_primary_node(
                kp, committee, params,
                on_commit=lambda cert, i=i: commits[i].append(cert),
                audit_path=audit,
                commit_rule=rule,
            )
        )
        workers.append(await spawn_worker_node(kp, 0, committee, params))

    host, port = parse_address(committee.worker(kps[0].name, 0).transactions)
    _, w = await asyncio.open_connection(host, port)
    txs = [_tx(i) for i in range(4)]
    for tx in txs:
        await write_frame(w, tx)
    w.close()
    target = digest32(encode_batch(txs))

    def committed_payload(i: int) -> bool:
        return any(
            target in cert.header.payload for cert in commits[i]
        )

    loop = asyncio.get_running_loop()
    deadline = loop.time() + 90
    while not all(committed_payload(i) for i in range(4)):
        if loop.time() >= deadline:
            break
        await asyncio.sleep(0.1)
    landed = [i for i in range(4) if committed_payload(i)]
    for node in primaries + workers:
        await node.shutdown()
    return {"segments": segments, "payload_committed_on": landed}


def run_committee_seed(
    seed: int, workdir: str, base_port: int, rule: str = "classic"
) -> Dict:
    audit_dir = os.path.join(workdir, f"committee-{rule}-{seed}")
    os.makedirs(audit_dir, exist_ok=True)
    committee = fixture_committee()  # replay needs only keys/stakes
    result, stats = run_with_seed(
        lambda: _committee(base_port, audit_dir, rule), seed, timeout=150
    )
    per_node: Dict[str, List[str]] = {}
    verdicts = {}
    for node, seg in result["segments"].items():
        v = replay_segments(committee, GC_DEPTH, [seg])
        verdicts[node] = {
            "ok": v["ok"],
            "violations": v["violations"][:5],
            "recorded_commits": v["recorded_commits"],
        }
        per_node[node] = v["commit_digests"]
    prefix = cross_node_prefix(per_node)
    all_payload = len(result["payload_committed_on"]) == 4
    ok = (
        all(v["ok"] for v in verdicts.values())
        and prefix["ok"]
        and all_payload
    )
    return {
        "seed": seed,
        "commit_rule": rule,
        "base_port": base_port,
        "schedule": stats,
        "payload_committed_on": result["payload_committed_on"],
        "replay": verdicts,
        "prefix": prefix,
        "ok": ok,
    }


# -- driver --------------------------------------------------------------------

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="race-explore")
    ap.add_argument("--seeds", type=int, default=16,
                    help="pipeline-scenario seed count (the N>=16 gate)")
    ap.add_argument("--seed-base", type=int, default=1000)
    ap.add_argument("--committee-seeds", type=int, default=4,
                    help="socketed committee-scenario seed count")
    ap.add_argument(
        "--commit-rule",
        choices=["classic", "lowdepth", "multileader"],
        default="classic",
        help="Judge every arm against this commit rule's oracle and run "
        "the committee/pipeline Consensus under it — every non-classic "
        "rule must survive the same ≥16-seed schedule exploration "
        "against ITS golden walk before it can ship (ROADMAP item 2)",
    )
    ap.add_argument("--skip-mutation", action="store_true")
    ap.add_argument("--artifact", default=None)
    ap.add_argument("--workdir", default=".race_explore")
    ap.add_argument("--repro", type=int, default=None,
                    help="re-run ONE pipeline seed and print its outcome")
    ap.add_argument("--mutated", action="store_true",
                    help="with --repro: run the mutation arm's schedule")
    args = ap.parse_args(argv)
    os.makedirs(args.workdir, exist_ok=True)

    if args.repro is not None:
        report = run_pipeline_seed(
            args.repro, args.workdir, args.mutated, rule=args.commit_rule
        )
        print(json.dumps(report, indent=1))
        return 0 if report["ok"] or args.mutated else 1

    artifact: Dict = {
        "commit_rule": args.commit_rule,
        "pipeline": [], "committee": [], "mutation": None,
    }
    failures: List[str] = []

    def guarded(fn, seed, *a, **kw) -> Dict:
        """One hung/crashed seed must cost THAT seed, not the harness:
        schedule.py promises a deadlock becomes 'a failure with the seed
        attached', so a TimeoutError (or any crash) out of one run is
        recorded as a failing report and the remaining seeds — plus the
        artifact and every repro already found — still land."""
        try:
            return fn(seed, *a, **kw)
        except BaseException as exc:  # noqa: BLE001 (recorded, re-gated)
            if isinstance(exc, KeyboardInterrupt):
                raise
            return {
                "seed": seed,
                "ok": False,
                "crashed": f"{type(exc).__name__}: {exc}",
                "schedule": {"seed": seed, "ticks": 0, "permutations": 0},
                "commits": 0,
                "expected": None,
                "identical_to_golden": False,
                "audit_replay_ok": False,
                "sequence_sha": "",
                "guard_tripped": True,
            }

    # Arm 1: pipeline, byte-identical across every seed.
    seeds = [args.seed_base + i for i in range(args.seeds)]
    for seed in seeds:
        report = guarded(
            run_pipeline_seed, seed, args.workdir, rule=args.commit_rule
        )
        artifact["pipeline"].append(report)
        status = (
            f"CRASHED ({report['crashed']})" if report.get("crashed")
            else "ok" if report["ok"] else "DIVERGED"
        )
        print(
            f"[pipeline] seed {seed}: {report['commits']}/"
            f"{report['expected']} commits, "
            f"{report['schedule']['permutations']} permuted ticks — "
            f"{status}"
        )
        if not report["ok"]:
            failures.append(
                f"pipeline seed {seed} "
                + ("crashed/hung" if report.get("crashed") else "diverged")
            )
            _dump_repro(args.artifact, report)
        if (
            not report.get("crashed")
            and report["schedule"]["permutations"] < 10
        ):
            failures.append(
                f"pipeline seed {seed} explored only "
                f"{report['schedule']['permutations']} permuted ticks — "
                "the scenario has gone vacuous"
            )
    # Determinism pin: the first seed, twice, must produce the same
    # commit bytes (tick counts vary with wall-clock wait polling and
    # are deliberately excluded).
    if seeds:
        again = guarded(
            run_pipeline_seed, seeds[0], args.workdir, rule=args.commit_rule
        )
        pin_keys = ("sequence_sha", "commits", "identical_to_golden",
                    "audit_replay_ok")
        artifact["determinism_rerun"] = {
            "seed": seeds[0],
            "agrees": all(
                again[k] == artifact["pipeline"][0][k] for k in pin_keys
            ),
        }
        if not artifact["determinism_rerun"]["agrees"]:
            failures.append(
                f"seed {seeds[0]} is not reproducible: two runs of the "
                "same schedule disagreed"
            )

    # Arm 2: socketed committee, safety verdicts per seed.
    for i in range(args.committee_seeds):
        seed = args.seed_base + 500 + i
        base_port = PORT_BASES[i % len(PORT_BASES)]
        report = guarded(
            run_committee_seed, seed, args.workdir, base_port,
            rule=args.commit_rule,
        )
        artifact["committee"].append(report)
        if report.get("crashed"):
            print(f"[committee] seed {seed}: CRASHED ({report['crashed']})")
        else:
            print(
                f"[committee] seed {seed}: payload on "
                f"{report['payload_committed_on']}, prefix "
                f"{'ok' if report['prefix']['ok'] else 'VIOLATED'}, replay "
                f"{'ok' if report['ok'] else 'FAILED'}"
            )
        if not report["ok"]:
            failures.append(f"committee seed {seed} failed its verdict")
            _dump_repro(args.artifact, report)

    # Arm 3: mutation must be caught by BOTH halves.
    if not args.skip_mutation:
        static = static_mutation_findings()
        caught_dynamic = []
        for seed in seeds:
            report = guarded(
                run_pipeline_seed, seed, args.workdir, mutated=True,
                rule=args.commit_rule,
            )
            caught_dynamic.append(report)
            if not report["ok"] and not report.get("crashed"):
                break  # one divergent schedule proves the dynamic half
        dynamic_hit = next(
            (r for r in caught_dynamic
             if not r["ok"] and not r.get("crashed")),
            None,
        )
        artifact["mutation"] = {
            "static_findings": static,
            "dynamic_runs": caught_dynamic,
            "static_caught": bool(static),
            "dynamic_caught": dynamic_hit is not None,
            "dynamic_seed": dynamic_hit["seed"] if dynamic_hit else None,
        }
        print(
            f"[mutation] static: {len(static)} finding(s); dynamic: "
            + (
                f"diverged at seed {dynamic_hit['seed']}"
                if dynamic_hit
                else f"NO divergence in {len(caught_dynamic)} seeds"
            )
        )
        if not static:
            failures.append(
                "mutation arm: the static interleave rule did NOT flag "
                "the planted race"
            )
        if dynamic_hit is None:
            failures.append(
                "mutation arm: no seed produced a divergent schedule "
                "for the planted race"
            )

    if args.artifact:
        os.makedirs(os.path.dirname(args.artifact) or ".", exist_ok=True)
        with open(args.artifact, "w", encoding="utf-8") as f:
            json.dump(
                {"ok": not failures, "failures": failures, **artifact},
                f, indent=1,
            )
        print(f"artifact -> {args.artifact}")

    if failures:
        print("race-explore: FAILED", file=sys.stderr)
        for f_ in failures:
            print(f"  - {f_}", file=sys.stderr)
        return 1
    print("race-explore: all schedules agree; mutation caught")
    return 0


def _dump_repro(artifact_path: Optional[str], report: Dict) -> None:
    """A divergent seed becomes a standalone replayable repro file."""
    base = artifact_path or os.path.join(".race_explore", "race.json")
    path = f"{base}.repro-{report['seed']}.json"
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=1)
    # The printed command must carry the report's rule (and mutation
    # flag): `--repro` re-derives everything from the seed, so a
    # lowdepth divergence replayed under the classic default would judge
    # against the wrong oracle and silently pass.
    replay = (
        f"python benchmark/race_explore.py --repro {report['seed']} "
        f"--commit-rule {report.get('commit_rule', 'classic')}"
    )
    if report.get("mutated"):
        replay += " --mutated"
    print(f"  repro: {path} (replay with `{replay}`)")


if __name__ == "__main__":
    sys.exit(main())
