"""Live-node /healthz smoke check (CI: `make metrics-smoke`).

Boots one real node process (`python -m narwhal_tpu.node run … primary`)
with --metrics-port, curls its /healthz, and fails on anything but 200 —
the cheapest end-to-end proof that the health plane actually comes up on
a production-shaped node: monitor attached, rules evaluating, endpoint
answering.  (Rule LOGIC is covered by tests/test_health*.py; this guards
the wiring in node/main.py that no in-process test exercises.)

    python benchmark/health_smoke.py [--base-port 7990]
"""

from __future__ import annotations

import argparse
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from narwhal_tpu.config import Parameters, export_keypair  # noqa: E402
from narwhal_tpu.crypto import KeyPair  # noqa: E402
from benchmark.local_bench import build_committee  # noqa: E402
from benchmark.scraper import fetch_json  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--base-port", type=int, default=7990)
    ap.add_argument("--timeout", type=float, default=60.0)
    args = ap.parse_args()

    workdir = tempfile.mkdtemp(prefix="health_smoke_")
    metrics_port = args.base_port + 100
    proc = None
    try:
        kp = KeyPair.generate()
        build_committee([kp], args.base_port, workers=1).export(
            f"{workdir}/committee.json"
        )
        Parameters().export(f"{workdir}/parameters.json")
        export_keypair(kp, f"{workdir}/node.json")

        logpath = f"{workdir}/primary.log"
        with open(logpath, "w") as logf:
            proc = subprocess.Popen(
                [
                    sys.executable, "-m", "narwhal_tpu.node", "run",
                    "--keys", f"{workdir}/node.json",
                    "--committee", f"{workdir}/committee.json",
                    "--parameters", f"{workdir}/parameters.json",
                    "--store", f"{workdir}/db",
                    "--metrics-port", str(metrics_port),
                    "primary",
                ],
                stdout=logf,
                stderr=subprocess.STDOUT,
                env=dict(os.environ, PYTHONPATH=REPO),
                cwd=REPO,
            )

        deadline = time.time() + args.timeout
        status, body = None, None
        while time.time() < deadline:
            if proc.poll() is not None:
                print(open(logpath).read(), file=sys.stderr)
                print(
                    f"FAIL: node exited {proc.returncode} before answering",
                    file=sys.stderr,
                )
                return 1
            status, body = fetch_json(
                "127.0.0.1", metrics_port, "/healthz", timeout_s=2.0
            )
            if status is not None:
                break
            time.sleep(0.5)

        print(f"/healthz -> {status}: {body}")
        if status != 200:
            print(open(logpath).read(), file=sys.stderr)
            print(
                f"FAIL: expected 200 from /healthz, got {status} "
                f"(firing: {(body or {}).get('firing')})",
                file=sys.stderr,
            )
            return 1
        if (body or {}).get("status") != "ok":
            print(f"FAIL: health body not ok: {body}", file=sys.stderr)
            return 1
        # The endpoint answering is half the proof; the rule loop
        # actually ticking is the other half.  Fresh budget (the boot
        # wait may have consumed the whole first deadline), and the
        # answer already in hand may suffice.
        eval_deadline = time.time() + 15
        while (body or {}).get("evaluations", 0) == 0:
            if time.time() >= eval_deadline:
                print(
                    f"FAIL: monitor never evaluated: {body}", file=sys.stderr
                )
                return 1
            time.sleep(0.5)
            status, body = fetch_json(
                "127.0.0.1", metrics_port, "/healthz", timeout_s=2.0
            )
            if status is not None and status != 200:
                print(
                    f"FAIL: /healthz flapped to {status}: {body}",
                    file=sys.stderr,
                )
                return 1
        print(
            "OK: live node answers /healthz 200 with zero firing rules "
            f"after {body['evaluations']} evaluation(s)"
        )
        return 0
    finally:
        if proc is not None and proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
        shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
