"""Local benchmark: run a full committee + clients on localhost and measure.

Reference benchmark/benchmark/local.py (`fab local`): generate keys/committee/
parameters files, launch every primary/worker/client as its own OS process,
run for `duration` seconds, kill, parse logs, print the summary.

    python benchmark/local_bench.py --nodes 4 --workers 1 --rate 20000 \
        --tx-size 512 --duration 20
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from narwhal_tpu.config import (  # noqa: E402
    Authority,
    Committee,
    Parameters,
    PrimaryAddresses,
    WorkerAddresses,
    export_keypair,
)
from narwhal_tpu.crypto import KeyPair  # noqa: E402
from benchmark.logs import parse_logs  # noqa: E402


def build_committee(keypairs, base_port, workers):
    port = base_port
    auths = {}
    for kp in keypairs:
        def nxt():
            nonlocal port
            a = f"127.0.0.1:{port}"
            port += 1
            return a

        primary = PrimaryAddresses(nxt(), nxt())
        ws = {
            wid: WorkerAddresses(nxt(), nxt(), nxt()) for wid in range(workers)
        }
        auths[kp.name] = Authority(stake=1, primary=primary, workers=ws)
    return Committee(auths)


def kill_stale_nodes() -> None:
    """Kill node/client processes left over from a previous run of THIS
    checkout — the reference harness does the same by killing its old tmux
    testbed (reference benchmark/benchmark/local.py:26-29).  Stale nodes
    squat on ports and burn CPU, silently corrupting the next measurement.
    Scoped by process cwd == this repo, so concurrent harnesses in other
    checkouts are left alone."""
    me = os.getpid()
    for pid_s in os.listdir("/proc"):
        if not pid_s.isdigit() or int(pid_s) == me:
            continue
        try:
            with open(f"/proc/{pid_s}/cmdline", "rb") as f:
                cmd = f.read().replace(b"\x00", b" ")
            if (b"-m narwhal_tpu.node" not in cmd
                    and b"narwhal_tpu.node.benchmark_client" not in cmd):
                continue
            if os.readlink(f"/proc/{pid_s}/cwd") != REPO:
                continue
            os.kill(int(pid_s), signal.SIGKILL)
        except OSError:
            continue


def run_bench(
    nodes: int = 4,
    workers: int = 1,
    rate: int = 20_000,
    tx_size: int = 512,
    duration: int = 20,
    base_port: int = 7000,
    faults: int = 0,
    header_size: int = 1_000,
    batch_size: int = 500_000,
    max_header_delay: int = 100,
    max_batch_delay: int = 100,
    workdir: str = None,
    keep_logs: bool = False,
    quiet: bool = False,
    crypto_backend: str = None,
    consensus_kernel: bool = False,
):
    kill_stale_nodes()
    workdir = workdir or os.path.join(REPO, ".bench")
    shutil.rmtree(workdir, ignore_errors=True)
    os.makedirs(workdir, exist_ok=True)
    # Node stores go on tmpfs when available: a 25 s run writes several GB
    # of batch logs, and on a shared-core host the disk writeback of run N
    # steals the core from run N+1 (kworker/flush), corrupting the
    # measurement.  The reference benches on local NVMe where this doesn't
    # bite; tmpfs gives the same effective behavior here.
    storedir = workdir
    if os.path.isdir("/dev/shm"):
        storedir = "/dev/shm/narwhal_bench"
        shutil.rmtree(storedir, ignore_errors=True)
        os.makedirs(storedir, exist_ok=True)

    keypairs = [KeyPair.generate() for _ in range(nodes)]
    committee = build_committee(keypairs, base_port, workers)
    committee.export(f"{workdir}/committee.json")
    params = Parameters(
        header_size=header_size,
        batch_size=batch_size,
        max_header_delay=max_header_delay,
        max_batch_delay=max_batch_delay,
    )
    params.export(f"{workdir}/parameters.json")
    for i, kp in enumerate(keypairs):
        export_keypair(kp, f"{workdir}/node-{i}.json")

    # Prepend (not overwrite) PYTHONPATH: the host environment may inject
    # interpreter-startup hooks through it (e.g. the TPU platform plugin
    # registers via a sitecustomize on PYTHONPATH — dropping it leaves
    # JAX_PLATFORMS pointing at a platform that never loads).
    pythonpath = os.pathsep.join(
        p for p in [REPO, os.environ.get("PYTHONPATH", "")] if p
    )
    env = dict(os.environ, PYTHONPATH=pythonpath)
    procs = []
    primary_logs, worker_logs, client_logs = [], [], []

    def spawn(cmd, logfile):
        f = open(logfile, "w")
        p = subprocess.Popen(
            cmd, stdout=f, stderr=subprocess.STDOUT, env=env, cwd=REPO
        )
        procs.append((p, f))
        return p

    node_flags = []
    if crypto_backend:
        node_flags += ["--crypto-backend", crypto_backend]
    if consensus_kernel:
        node_flags += ["--consensus-kernel"]

    alive = nodes - faults  # crash faults: the last `faults` nodes never boot
    for i in range(alive):
        log = f"{workdir}/primary-{i}.log"
        primary_logs.append(log)
        spawn(
            [
                sys.executable,
                "-m",
                "narwhal_tpu.node",
                "run",
                "--keys",
                f"{workdir}/node-{i}.json",
                "--committee",
                f"{workdir}/committee.json",
                "--parameters",
                f"{workdir}/parameters.json",
                "--store",
                f"{storedir}/db-primary-{i}",
                "--benchmark",
                *node_flags,
                "primary",
            ],
            log,
        )
        for wid in range(workers):
            log = f"{workdir}/worker-{i}-{wid}.log"
            worker_logs.append(log)
            spawn(
                [
                    sys.executable,
                    "-m",
                    "narwhal_tpu.node",
                    "run",
                    "--keys",
                    f"{workdir}/node-{i}.json",
                    "--committee",
                    f"{workdir}/committee.json",
                    "--parameters",
                    f"{workdir}/parameters.json",
                    "--store",
                    f"{storedir}/db-worker-{i}-{wid}",
                    "--benchmark",
                    "worker",
                    "--id",
                    str(wid),
                ],
                log,
            )

    # TPU-backed nodes spend tens of seconds warming the XLA kernels at
    # boot; don't start the measured load until every primary reports
    # booted, or the warmup eats the run window.
    if crypto_backend == "tpu" or consensus_kernel:
        deadline = time.time() + 600
        pending = set(primary_logs)
        while pending and time.time() < deadline:
            for p in list(pending):
                try:
                    if "successfully booted" in open(p).read():
                        pending.discard(p)
                except OSError:
                    pass
            if pending:
                time.sleep(2)
        if pending and not quiet:
            print(f"WARNING: primaries never booted: {pending}", file=sys.stderr)

    # One client per live worker, rate split evenly (reference local.py:78).
    committee_obj = committee
    rate_share = max(1, rate // max(1, alive * workers))
    client_idx = 0
    for i in range(alive):
        kp = keypairs[i]
        for wid in range(workers):
            addr = committee_obj.worker(kp.name, wid).transactions
            log = f"{workdir}/client-{i}-{wid}.log"
            client_logs.append(log)
            spawn(
                [
                    sys.executable,
                    "-m",
                    "narwhal_tpu.node.benchmark_client",
                    addr,
                    "--size",
                    str(tx_size),
                    "--rate",
                    str(rate_share),
                    "--sample-offset",
                    str(client_idx << 32),
                    "--nodes",
                    addr,
                ],
                log,
            )
            client_idx += 1

    if not quiet:
        print(f"Running benchmark ({duration} s)...", file=sys.stderr)
    time.sleep(duration)

    # SIGTERM first (lets NARWHAL_PROFILE dumps flush), then SIGKILL.
    for p, f in procs:
        try:
            p.send_signal(signal.SIGTERM)
        except ProcessLookupError:
            pass
    deadline = time.time() + 3
    for p, f in procs:
        try:
            p.wait(timeout=max(0.1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()
        f.close()

    read = lambda paths: [open(p).read() for p in paths]  # noqa: E731
    result = parse_logs(
        read(client_logs), read(worker_logs), read(primary_logs), tx_size
    )
    if not keep_logs:
        for i in range(alive):
            shutil.rmtree(f"{storedir}/db-primary-{i}", ignore_errors=True)
            for wid in range(workers):
                shutil.rmtree(
                    f"{storedir}/db-worker-{i}-{wid}", ignore_errors=True
                )
    return result


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--nodes", type=int, default=4)
    parser.add_argument("--workers", type=int, default=1)
    parser.add_argument("--rate", type=int, default=20_000)
    parser.add_argument("--tx-size", type=int, default=512)
    parser.add_argument("--duration", type=int, default=20)
    parser.add_argument("--faults", type=int, default=0)
    parser.add_argument("--base-port", type=int, default=7000)
    parser.add_argument("--json", action="store_true")
    parser.add_argument("--crypto-backend", choices=["cpu", "tpu"], default=None)
    parser.add_argument("--consensus-kernel", action="store_true")
    args = parser.parse_args()

    result = run_bench(
        nodes=args.nodes,
        workers=args.workers,
        rate=args.rate,
        tx_size=args.tx_size,
        duration=args.duration,
        faults=args.faults,
        base_port=args.base_port,
        crypto_backend=args.crypto_backend,
        consensus_kernel=args.consensus_kernel,
    )
    if result.errors:
        print("ERRORS detected in logs:", file=sys.stderr)
        for e in result.errors[:10]:
            print("  " + e, file=sys.stderr)
        sys.exit(1)
    if args.json:
        print(
            json.dumps(
                {
                    "consensus_tps": result.consensus_tps,
                    "consensus_latency_ms": result.consensus_latency_ms,
                    "end_to_end_tps": result.end_to_end_tps,
                    "end_to_end_latency_ms": result.end_to_end_latency_ms,
                    "committed_bytes": result.committed_bytes,
                    "samples": result.samples,
                }
            )
        )
    else:
        print(result.summary(args.rate, args.tx_size, args.nodes, args.workers))


if __name__ == "__main__":
    main()
