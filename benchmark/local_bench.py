"""Local benchmark: run a full committee + clients on localhost and measure.

Reference benchmark/benchmark/local.py (`fab local`): generate keys/committee/
parameters files, launch every primary/worker/client as its own OS process,
run for `duration` seconds, kill, parse logs, print the summary.

    python benchmark/local_bench.py --nodes 4 --workers 1 --rate 20000 \
        --tx-size 512 --duration 20
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from narwhal_tpu.utils.env import env_flag  # noqa: E402
from narwhal_tpu.config import (  # noqa: E402
    Authority,
    Committee,
    Parameters,
    PrimaryAddresses,
    WorkerAddresses,
    export_keypair,
)
from narwhal_tpu.crypto import KeyPair  # noqa: E402
from benchmark.logs import parse_logs  # noqa: E402
from benchmark.metrics_check import (  # noqa: E402
    loop_stall_summary,
    build_timeline,
    check_quiesce_health,
    cross_validate,
    load_snapshots,
    queue_pressure_summary,
    wire_crypto_summary,
)
from benchmark.scraper import Scraper  # noqa: E402


def build_committee(keypairs, base_port, workers, ips=None, worker_ips=None):
    """Sequential port allocation, one block of 2+3W ports per authority
    (reference config.py:63-86).  ``ips`` optionally maps authority index →
    IP for multi-host committees; ``worker_ips[i][wid]`` additionally puts
    authority i's worker wid on its own host (the reference's
    ``collocate=False`` placement, remote.py:108-130) — default is the
    authority IP for every role, all-loopback if ``ips`` is unset."""
    port = base_port
    auths = {}
    for i, kp in enumerate(keypairs):
        primary_ip = ips[i] if ips else "127.0.0.1"

        def nxt(ip):
            nonlocal port
            a = f"{ip}:{port}"
            port += 1
            return a

        primary = PrimaryAddresses(nxt(primary_ip), nxt(primary_ip))
        ws = {}
        for wid in range(workers):
            wip = worker_ips[i][wid] if worker_ips else primary_ip
            ws[wid] = WorkerAddresses(nxt(wip), nxt(wip), nxt(wip))
        auths[kp.name] = Authority(stake=1, primary=primary, workers=ws)
    return Committee(auths)


def metrics_port(base_port, nodes, workers, node, worker=None):
    """Metrics port for one process, in the block directly above the
    committee's own ports (``build_committee`` consumes 2+3W consecutive
    ports per authority starting at ``base_port``).  One definition for
    every harness: a layout change that only updated one copy would
    silently collide metrics ports with committee ports in the other.
    ``worker=None`` addresses authority ``node``'s primary; otherwise
    its worker ``worker``."""
    mbase = base_port + nodes * (2 + 3 * workers)
    if worker is None:
        return mbase + node
    return mbase + nodes + node * workers + worker


def kill_stale_nodes() -> None:
    """Kill node/client processes left over from a previous run of THIS
    checkout — the reference harness does the same by killing its old tmux
    testbed (reference benchmark/benchmark/local.py:26-29).  Stale nodes
    squat on ports and burn CPU, silently corrupting the next measurement.
    Scoped by process cwd == this repo, so concurrent harnesses in other
    checkouts are left alone.  SIGTERM with a grace period, not SIGKILL:
    a stale node may hold the device, and killing a chip-holder wedges
    the grant server-side (see the teardown comment in run_bench)."""
    me = os.getpid()
    stale = []
    for pid_s in os.listdir("/proc"):
        if not pid_s.isdigit() or int(pid_s) == me:
            continue
        try:
            with open(f"/proc/{pid_s}/cmdline", "rb") as f:
                cmd = f.read().replace(b"\x00", b" ")
            if (b"-m narwhal_tpu.node" not in cmd
                    and b"narwhal_tpu.node.benchmark_client" not in cmd):
                continue
            if os.readlink(f"/proc/{pid_s}/cwd") != REPO:
                continue
            os.kill(int(pid_s), signal.SIGTERM)
            stale.append(int(pid_s))
        except OSError:
            continue
    # Same 75 s grace as run_bench's teardown: a stale node may be
    # mid-device-call, and its graceful release can take that long.
    deadline = time.time() + 75
    for pid in stale:
        while time.time() < deadline:
            try:
                os.kill(pid, 0)
            except OSError:
                break  # gone
            time.sleep(0.2)
        else:
            try:
                os.kill(pid, signal.SIGKILL)
            except OSError:
                pass


def wait_for_boot(log_paths, deadline_s: float = 60, quiet: bool = False):
    """Block until every log in ``log_paths`` contains the node boot
    sentinel ("successfully booted"), up to ``deadline_s``.  Never start
    the measured load against a committee that hasn't booted: the e2e
    window opens at the first client's "Start sending" line, so any boot
    time the clients outrun is charged to the measurement (the round-3/4
    failure measured a committee that never came up at all).  Shared with
    fault_bench so both harnesses watch the same sentinel."""
    deadline = time.time() + deadline_s
    pending = set(log_paths)
    while pending and time.time() < deadline:
        for p in list(pending):
            try:
                if "successfully booted" in open(p).read():
                    pending.discard(p)
            except OSError:
                pass
        if pending:
            time.sleep(0.2)
    if pending and not quiet:
        print(f"WARNING: nodes never booted: {pending}", file=sys.stderr)
    return not pending


def share_rate(rate: int, n_clients: int) -> int:
    """Per-client tx rate: the committee-wide rate split evenly, floor 1
    (reference local.py:78)."""
    return max(1, rate // max(1, n_clients))


def client_command(addr: str, tx_size: int, rate_share: int,
                   client_idx: int):
    """argv for one benchmark client against worker ``addr``.  The
    sample-offset keys each client's latency samples into its own id
    space so merged logs never collide.  Shared with fault_bench so the
    fault-arm load is flag-identical to the bench load."""
    return [
        sys.executable,
        "-m",
        "narwhal_tpu.node.benchmark_client",
        addr,
        "--size",
        str(tx_size),
        "--rate",
        str(rate_share),
        "--sample-offset",
        str(client_idx << 32),
        "--nodes",
        addr,
    ]


def run_bench(
    nodes: int = 4,
    workers: int = 1,
    rate: int = 20_000,
    tx_size: int = 512,
    duration: int = 20,
    base_port: int = 7000,
    faults: int = 0,
    header_size: int = 1_000,
    batch_size: int = 500_000,
    max_header_delay: int = 100,
    min_header_delay: int = 0,
    header_linger: int = 0,
    max_batch_delay: int = 100,
    workdir: str = None,
    keep_logs: bool = False,
    quiet: bool = False,
    crypto_backend: str = None,
    consensus_kernel: bool = False,
    tpu_primaries: int = None,
    scrape_interval: float = 1.0,
    progress_wait: float = 0.0,
    loop_watchdog_ms: int = 0,
    trace_out: str = None,
    wire_v2: bool = None,
    verify_window_ms: float = None,
    commit_rule: str = None,
    cert_sig_scheme: str = None,
):
    """Run one committee + clients on localhost; return the ParseResult.

    ``tpu_primaries`` limits the TPU flags (``crypto_backend="tpu"`` /
    ``consensus_kernel``) to the first N primaries: a single host has one
    chip, so a mixed committee (one device-backed primary, the rest CPU)
    is the honest way to exercise the device path end-to-end.  ``None``
    means every primary gets the flags (all-CPU or all-TPU runs).

    ``progress_wait``: extra seconds (beyond ``duration``) the window may
    stretch while the scraped metrics show zero committed PAYLOAD batches
    — on a starved shared core the clients can ramp so late that the
    fixed window closes before the first client batch commits (empty
    headers commit throughout, so certificate counts can't gate this).
    0 keeps the fixed-duration behavior; requires metrics enabled.
    """
    kill_stale_nodes()
    workdir = workdir or os.path.join(REPO, ".bench")
    shutil.rmtree(workdir, ignore_errors=True)
    os.makedirs(workdir, exist_ok=True)
    # Node stores go on tmpfs when available: a 25 s run writes several GB
    # of batch logs, and on a shared-core host the disk writeback of run N
    # steals the core from run N+1 (kworker/flush), corrupting the
    # measurement.  The reference benches on local NVMe where this doesn't
    # bite; tmpfs gives the same effective behavior here.
    storedir = workdir
    if os.path.isdir("/dev/shm"):
        storedir = "/dev/shm/narwhal_bench"
        shutil.rmtree(storedir, ignore_errors=True)
        os.makedirs(storedir, exist_ok=True)

    keypairs = [KeyPair.generate() for _ in range(nodes)]
    committee = build_committee(keypairs, base_port, workers)
    committee.export(f"{workdir}/committee.json")
    params = Parameters(
        header_size=header_size,
        batch_size=batch_size,
        max_header_delay=max_header_delay,
        min_header_delay=min_header_delay,
        header_linger=header_linger,
        max_batch_delay=max_batch_delay,
    )
    params.export(f"{workdir}/parameters.json")
    for i, kp in enumerate(keypairs):
        export_keypair(kp, f"{workdir}/node-{i}.json")

    # Child PYTHONPATH: REPO only.  The host environment may carry
    # interpreter-startup hooks on PYTHONPATH (the TPU platform plugin
    # registers via a sitecustomize); on a shared-core host that hook costs
    # ~2 s of CPU per interpreter start, and forwarding it to 12 CPU-only
    # children serializes ~25 s of boot into the measurement window — the
    # round-3/4 "0.0 TPS" failure.  Only children that actually need the
    # device (TPU-flagged primaries) get the host path appended.
    cpu_env = dict(os.environ, PYTHONPATH=REPO)
    host_pp = os.environ.get("PYTHONPATH", "")
    tpu_pp = os.pathsep.join(p for p in [REPO, host_pp] if p)
    tpu_env = dict(os.environ, PYTHONPATH=tpu_pp)
    if loop_watchdog_ms:
        # Loop-stall watchdog smoke arm: every node measures its own
        # event-loop stalls into runtime.loop_stall_seconds; the bench
        # JSON's `runtime` section joins them per node after the run.
        cpu_env["NARWHAL_LOOP_WATCHDOG_MS"] = str(loop_watchdog_ms)
        tpu_env["NARWHAL_LOOP_WATCHDOG_MS"] = str(loop_watchdog_ms)
    if wire_v2 is not None:
        # Paired wire-format A/B arm pin: the whole committee speaks one
        # format (mixed-version committees are unsupported), so the flag
        # goes to every child uniformly; None inherits the environment.
        cpu_env["NARWHAL_WIRE_V2"] = "1" if wire_v2 else "0"
        tpu_env["NARWHAL_WIRE_V2"] = "1" if wire_v2 else "0"
    if verify_window_ms is not None:
        # Verify-batch accumulation window (crypto A/B batched arm):
        # every primary coalesces drained bursts into one backend
        # dispatch within this window; None inherits the environment.
        cpu_env["NARWHAL_VERIFY_BATCH_WINDOW_MS"] = str(verify_window_ms)
        tpu_env["NARWHAL_VERIFY_BATCH_WINDOW_MS"] = str(verify_window_ms)
    if commit_rule is not None:
        # Commit-rule A/B arm pin: committee-wide like the wire format
        # (a mixed-rule committee diverges by design); every child gets
        # the env knob, and each primary's boot log records the rule.
        cpu_env["NARWHAL_COMMIT_RULE"] = commit_rule
        tpu_env["NARWHAL_COMMIT_RULE"] = commit_rule
    if cert_sig_scheme is not None:
        # Cert-sig-scheme A/B arm pin: committee-wide like the commit
        # rule — a mixed-scheme committee refuses each other's
        # certificate frames by design (SchemeMismatch).
        cpu_env["NARWHAL_CERT_SIG_SCHEME"] = cert_sig_scheme
        tpu_env["NARWHAL_CERT_SIG_SCHEME"] = cert_sig_scheme
    procs = []
    primary_logs, worker_logs, client_logs = [], [], []
    metrics_paths = []
    # NARWHAL_METRICS=0 stubs the registry in every child — the knob the
    # overhead measurement flips; cross-validation is skipped since the
    # snapshots would be empty.
    metrics_on = env_flag("NARWHAL_METRICS")
    # Live scrape plane: every node also gets a --metrics-port in the
    # block directly after the committee's own ports (metrics_port), and
    # the harness polls them all during the run (benchmark/scraper.py)
    # to build the committee timeline and gate on /healthz at quiesce.
    scrape_targets = []  # (name, host, port)

    def spawn(cmd, logfile, env=cpu_env, tpu=False):
        f = open(logfile, "w")
        p = subprocess.Popen(
            cmd, stdout=f, stderr=subprocess.STDOUT, env=env, cwd=REPO
        )
        procs.append((p, f, tpu))
        return p

    # Device-requiring flags go only to the TPU-designated primaries; any
    # other explicitly requested flag (e.g. --crypto-backend cpu) goes to
    # every node unconditionally.  "jax" counts as a device flag too —
    # it may resolve to jax-cpu (the A/B fallback arm) but still pays
    # XLA warmup at boot, so it gets the same prewarm + long deadline.
    base_flags, device_flags = [], []
    if crypto_backend in ("tpu", "jax"):
        device_flags += ["--crypto-backend", crypto_backend]
    elif crypto_backend:
        base_flags += ["--crypto-backend", crypto_backend]
    if consensus_kernel:
        device_flags += ["--experimental-consensus-kernel"]

    alive = nodes - faults  # crash faults: the last `faults` nodes never boot
    any_tpu = bool(device_flags)
    # Populate the persistent XLA cache BEFORE spawning the committee: a
    # cold-cache node spends minutes compiling warmup shapes over the
    # tunnel — it misses the boot deadline, the run measures a committee
    # without it, and tearing it down mid-compile wedges the chip grant
    # server-side (observed: jax.devices() hung for hours afterwards).
    # The prewarm subprocess compiles the exact same shapes (shared
    # derive_max_claims sizing), is never killed, and makes the node's own
    # warmup a cache load.
    if any_tpu:
        if not quiet:
            print("Prewarming device kernels...", file=sys.stderr)
        warm_cmd = [
            sys.executable,
            "-m",
            "narwhal_tpu.node",
            "prewarm",
            "--committee",
            f"{workdir}/committee.json",
        ]
        if consensus_kernel:
            warm_cmd.append("--experimental-consensus-kernel")
        if crypto_backend not in ("tpu", "jax"):
            # Consensus-kernel-only run: the nodes keep CPU crypto, so
            # compiling the verify shapes would be pure waste.
            warm_cmd.append("--skip-verify")
        # tpu_env already carries the verify-window knob, so the prewarm
        # subprocess sizes its shapes from the same env the committee
        # will run under (derive_max_claims reads the window knobs).
        warm = subprocess.run(warm_cmd, env=tpu_env, cwd=REPO, check=False)
        if warm.returncode != 0:
            # Loud but non-fatal: the nodes will still try to boot (their
            # own warmup compiles cold), and the boot-deadline wait below
            # plus the parser's error hard-fail surface the consequences.
            print(
                "WARNING: device prewarm exited "
                f"{warm.returncode}; TPU nodes will compile cold and may "
                "miss the boot deadline",
                file=sys.stderr,
            )
    for i in range(alive):
        on_tpu = any_tpu and (tpu_primaries is None or i < tpu_primaries)
        log = f"{workdir}/primary-{i}.log"
        primary_logs.append(log)
        mpath = f"{workdir}/metrics-primary-{i}.json"
        metrics_paths.append(mpath)
        mport = metrics_port(base_port, nodes, workers, i)
        scrape_targets.append((f"primary-{i}", "127.0.0.1", mport))
        spawn(
            [
                sys.executable,
                "-m",
                "narwhal_tpu.node",
                "run",
                "--keys",
                f"{workdir}/node-{i}.json",
                "--committee",
                f"{workdir}/committee.json",
                "--parameters",
                f"{workdir}/parameters.json",
                "--store",
                f"{storedir}/db-primary-{i}",
                "--benchmark",
                "--metrics-path",
                mpath,
                "--metrics-port",
                str(mport),
                *base_flags,
                *(device_flags if on_tpu else []),
                "primary",
            ],
            log,
            env=tpu_env if on_tpu else cpu_env,
            tpu=on_tpu,
        )
        for wid in range(workers):
            log = f"{workdir}/worker-{i}-{wid}.log"
            worker_logs.append(log)
            mpath = f"{workdir}/metrics-worker-{i}-{wid}.json"
            metrics_paths.append(mpath)
            mport = metrics_port(base_port, nodes, workers, i, wid)
            scrape_targets.append((f"worker-{i}-{wid}", "127.0.0.1", mport))
            spawn(
                [
                    sys.executable,
                    "-m",
                    "narwhal_tpu.node",
                    "run",
                    "--keys",
                    f"{workdir}/node-{i}.json",
                    "--committee",
                    f"{workdir}/committee.json",
                    "--parameters",
                    f"{workdir}/parameters.json",
                    "--store",
                    f"{storedir}/db-worker-{i}-{wid}",
                    "--benchmark",
                    "--metrics-path",
                    mpath,
                    "--metrics-port",
                    str(mport),
                    "worker",
                    "--id",
                    str(wid),
                ],
                log,
            )

    # TPU-backed nodes spend tens of seconds warming XLA kernels, hence
    # the much longer boot deadline.
    wait_for_boot(
        primary_logs + worker_logs,
        deadline_s=(600 if any_tpu else 60),
        quiet=quiet,
    )

    # One client per live worker, rate split evenly (reference local.py:78).
    committee_obj = committee
    rate_share = share_rate(rate, alive * workers)
    client_idx = 0
    for i in range(alive):
        kp = keypairs[i]
        for wid in range(workers):
            addr = committee_obj.worker(kp.name, wid).transactions
            log = f"{workdir}/client-{i}-{wid}.log"
            client_logs.append(log)
            spawn(client_command(addr, tx_size, rate_share, client_idx), log)
            client_idx += 1

    if not quiet:
        print(f"Running benchmark ({duration} s)...", file=sys.stderr)
    # The scraper runs across the whole measurement window, building the
    # committee time-series the post-mortem snapshots cannot: per-node
    # progress at each tick, so mid-run stalls have a timestamp.
    scraper = None
    healthz = {}
    flight_rings = {}
    if metrics_on:
        scraper = Scraper(scrape_targets, interval_s=scrape_interval).start()
    time.sleep(duration)
    if scraper is not None:
        scraper.wait_for_payload_commits(progress_wait, quiet=quiet)
    if scraper is not None:
        # Quiesce gate BEFORE teardown: a firing health rule on any live
        # node fails the run (appended to result.errors below).
        healthz = scraper.healthz_all()
        # The flight rings ride along: even a clean run's bench JSON
        # carries each node's last-seconds event history.
        flight_rings = scraper.flight_all()
        scraper.stop()

    # SIGTERM first (lets NARWHAL_PROFILE dumps flush), then SIGKILL.
    # Chip-holding children get a much longer grace period: SIGKILLing a
    # process mid-device-call wedges the chip grant server-side (the
    # tunnel's jax.devices() then hangs for hours) — the graceful SIGTERM
    # path releases the claim.
    for p, f, tpu in procs:
        try:
            p.send_signal(signal.SIGTERM)
        except ProcessLookupError:
            pass
    # PER-PROCESS grace, not one shared deadline: the SIGTERM path is also
    # what flushes each node's final metrics snapshot (the only one
    # guaranteed to carry the full stage trace), and on a loaded shared
    # core one slow shutdown must not eat the whole budget and get the
    # remaining nodes SIGKILLed un-flushed — that would undercount the
    # metrics side and spuriously hard-fail the cross-check.
    # 15 s, not the old 3: a healthy node flushes and exits in <2 s, so
    # the budget is only consumed by pathological shutdowns — and a node
    # SIGKILLed pre-flush leaves a snapshot whose trace is up to
    # trace_every×interval stale, which undercounts the metrics side of
    # the cross-check and fails a healthy run.
    for p, f, tpu in procs:
        try:
            p.wait(timeout=75 if tpu else 15)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()
        f.close()

    read = lambda paths: [open(p).read() for p in paths]  # noqa: E731
    names = lambda paths: [os.path.basename(p) for p in paths]  # noqa: E731
    result = parse_logs(
        read(client_logs),
        read(worker_logs),
        read(primary_logs),
        tx_size,
        client_names=names(client_logs),
        worker_names=names(worker_logs),
        primary_names=names(primary_logs),
    )
    # Cross-check the log-scraped totals against the nodes' own metrics
    # snapshots and derive the per-stage pipeline latency breakdown.  A
    # >5% disagreement between the two measurement channels appends a
    # fatal error (every caller treats result.errors as run failure).
    if metrics_on:
        snapshots = load_snapshots(metrics_paths, result.errors)
        mc = cross_validate(result, snapshots, tx_size)
        # Clock model + causal attribution sections: the reconciled
        # per-node corrections the stage join applied, the slowest
        # end-to-end chain(s), and the ranked quorum-straggler table.
        result.clock = mc.get("clock", {})
        result.critical_path = mc.get("critical_path", {})
        result.stragglers = mc.get("stragglers", {})
        # Wire-goodput + crypto-cost ledger sections (the `wire` and
        # `crypto` keys of the bench JSON).
        result.runtime = loop_stall_summary(snapshots)
        wc = wire_crypto_summary(
            snapshots,
            committed_payload_bytes=result.committed_bytes,
            quorum_weight=committee.quorum_threshold(),
        )
        result.wire, result.crypto = wc["wire"], wc["crypto"]
        # Per-channel backpressure accounting: the scraper's 1 Hz sample
        # timeline gives first_saturating a WHEN; the final snapshots
        # give every channel its totals either way.
        result.queues = queue_pressure_summary(
            snapshots, scraper.samples if scraper else []
        )
        check_quiesce_health(healthz, result.errors)
        result.timeline = build_timeline(
            scraper.samples if scraper else [],
            interval_s=scrape_interval,
            healthz=healthz,
        )
        result.flight = flight_rings
        with open(f"{workdir}/timeline.json", "w") as f:
            json.dump(result.timeline, f, indent=1)
        if trace_out:
            # One Perfetto-loadable trace of the whole committee run:
            # the final snapshots carry the stage/round traces, flight
            # rings and profiler timelines; the scraped timeline adds
            # the committee-wide rate counters and health transitions.
            from benchmark import trace_export

            trace_export.export(
                trace_export.load_named_snapshots(metrics_paths),
                trace_out,
                timeline=result.timeline,
                flight=flight_rings,
                quiet=quiet,
            )
    if not keep_logs:
        for i in range(alive):
            shutil.rmtree(f"{storedir}/db-primary-{i}", ignore_errors=True)
            for wid in range(workers):
                shutil.rmtree(
                    f"{storedir}/db-worker-{i}-{wid}", ignore_errors=True
                )
    return result


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--nodes", type=int, default=4)
    parser.add_argument("--workers", type=int, default=1)
    parser.add_argument("--rate", type=int, default=20_000)
    parser.add_argument("--tx-size", type=int, default=512)
    parser.add_argument("--duration", type=int, default=20)
    parser.add_argument("--faults", type=int, default=0)
    parser.add_argument("--base-port", type=int, default=7000)
    parser.add_argument(
        "--min-header-delay",
        type=int,
        default=0,
        help="Sui-style cadence floor (ms): a parent quorum plus any "
        "payload proposes after this delay instead of riding "
        "--max-header-delay; 0 = reference behavior",
    )
    parser.add_argument(
        "--header-linger",
        type=int,
        default=0,
        help="Parent-linger window (ms): a just-advanced round holds its "
        "header open this long so post-quorum parent certificates are "
        "still cited — the proposer half of the multileader commit "
        "rule; 0 = reference behavior",
    )
    parser.add_argument("--max-header-delay", type=int, default=100)
    parser.add_argument("--json", action="store_true")
    parser.add_argument(
        "--loop-watchdog-ms",
        type=int,
        default=0,
        help="Arm the event-loop stall watchdog on every node "
        "(NARWHAL_LOOP_WATCHDOG_MS) and emit the per-node `runtime` "
        "section (runtime.loop_stall_seconds series) in the bench JSON; "
        "0 = off",
    )
    parser.add_argument(
        "--trace-out",
        default=None,
        help="Export the whole run as ONE Perfetto-loadable Chrome trace "
        "(process row per node, flow arrows per committed digest, health/"
        "flight instants, sampled-CPU track) to this path — see "
        "benchmark/trace_export.py",
    )
    parser.add_argument(
        "--crypto-backend", choices=["cpu", "tpu", "jax"], default=None,
        help="Primary verification backend: jax/tpu run the batched "
        "device verifier (jax works on jax-cpu for the A/B fallback "
        "arm); default inherits NARWHAL_CRYPTO_BACKEND, else cpu",
    )
    parser.add_argument(
        "--verify-window-ms", type=float, default=None,
        help="Verify-batch accumulation window for every primary "
        "(NARWHAL_VERIFY_BATCH_WINDOW_MS): coalesce drained bursts "
        "arriving within this many ms into one backend dispatch; "
        "unset inherits the environment (default off)",
    )
    parser.add_argument(
        "--commit-rule",
        choices=["classic", "lowdepth", "multileader"],
        default=None,
        help="Consensus commit rule for the whole committee "
        "(NARWHAL_COMMIT_RULE): classic = Tusk depth-3 commits, "
        "lowdepth = Mysticeti-style direct commits one round after the "
        "leader, multileader = 3 leader slots per even round anchoring "
        "on the lowest supported slot; unset inherits the environment "
        "(default classic)",
    )
    parser.add_argument(
        "--experimental-consensus-kernel",
        dest="consensus_kernel",
        action="store_true",
        help="EXPERIMENTAL: run the committee with the device-resident "
        "consensus kernel (correct but measured slower than the Python "
        "walk; artifacts/consensus_bench_r06.json)",
    )
    parser.add_argument(
        "--tpu-primaries",
        type=int,
        default=None,
        help="Apply the TPU flags to only the first N primaries "
        "(single-chip hosts: use 1)",
    )
    args = parser.parse_args()

    result = run_bench(
        nodes=args.nodes,
        workers=args.workers,
        rate=args.rate,
        tx_size=args.tx_size,
        duration=args.duration,
        faults=args.faults,
        base_port=args.base_port,
        min_header_delay=args.min_header_delay,
        header_linger=args.header_linger,
        max_header_delay=args.max_header_delay,
        crypto_backend=args.crypto_backend,
        consensus_kernel=args.consensus_kernel,
        tpu_primaries=args.tpu_primaries,
        loop_watchdog_ms=args.loop_watchdog_ms,
        trace_out=args.trace_out,
        verify_window_ms=args.verify_window_ms,
        commit_rule=args.commit_rule,
    )
    if result.errors:
        print("ERRORS detected in logs:", file=sys.stderr)
        for e in result.errors[:10]:
            print("  " + e, file=sys.stderr)
        sys.exit(1)
    if args.json:
        print(
            json.dumps(
                {
                    "consensus_tps": result.consensus_tps,
                    "consensus_latency_ms": result.consensus_latency_ms,
                    "end_to_end_tps": result.end_to_end_tps,
                    "end_to_end_latency_ms": result.end_to_end_latency_ms,
                    "committed_bytes": result.committed_bytes,
                    "samples": result.samples,
                    # Metrics-channel numbers: per-stage latency breakdown
                    # (seal → quorum → digest-at-primary → header → cert →
                    # commit, mean ms per leg) and the cross-check of the
                    # two measurement channels.
                    "stages_ms": result.stages_ms,
                    # Round-cadence attribution: mean ms per ROUND_STAGES
                    # sub-leg (telescoping to the round period).
                    "round_stages_ms": result.round_stages_ms,
                    "metrics_committed_tx": round(
                        result.metrics_committed_tx, 1
                    ),
                    "metrics_disagreement": result.metrics_disagreement,
                    # Wire-goodput & crypto-cost ledgers: per-type
                    # bandwidth (retransmits split out), goodput ratio,
                    # per-site sign/verify attribution + protocol check.
                    "wire": result.wire,
                    # Loop-stall watchdog series (when the run armed it):
                    # per-node runtime.loop_stall_seconds + last stack.
                    "runtime": result.runtime,
                    "crypto": result.crypto,
                    # Live committee timeline (scraper): per-node series,
                    # per-peer RTT matrix, /healthz verdicts at quiesce.
                    "timeline": result.timeline,
                    # Per-node flight-recorder rings pulled at quiesce
                    # (/debug/flight): the last-seconds event history.
                    "flight": result.flight,
                    # Per-channel queue backpressure accounting + the
                    # first-saturating attribution (knee matrix input).
                    "queues": result.queues,
                    # Clock model: per-node reconciled corrections (from
                    # the ACK-piggybacked offset estimator) applied to
                    # the cross-node stage join above.
                    "clock": result.clock,
                    # Slowest end-to-end causal chain(s): per-leg ms,
                    # telescoping to the e2e span.
                    "critical_path": result.critical_path,
                    # Ranked who-closed-the-quorum attribution + gap
                    # histogram means.
                    "stragglers": result.stragglers,
                }
            )
        )
    else:
        print(result.summary(args.rate, args.tx_size, args.nodes, args.workers))
        if result.stages_ms:
            print(" + PIPELINE STAGES (mean ms):")
            for name, ms in result.stages_ms.items():
                print(f"   {name}: {ms:,.1f} ms")
        if result.round_stages_ms:
            print(" + ROUND CADENCE (mean ms per sub-leg):")
            for name, ms in result.round_stages_ms.items():
                print(f"   {name}: {ms:,.2f} ms")
        path = result.critical_path.get("path")
        if path:
            print(
                " + CRITICAL PATH (slowest committed digest, "
                f"{path['e2e_ms']:,.1f} ms e2e):"
            )
            for name, ms in path["legs_ms"].items():
                print(f"   {name}: {ms:,.1f} ms")
        for family, label in (
            ("vote_quorum", "vote quorum"),
            ("support_quorum", "support quorum"),
        ):
            ranked = result.stragglers.get(family)
            if ranked:
                print(f" + QUORUM STRAGGLERS ({label}, most-charged first):")
                for e in ranked:
                    print(f"   {e['address']}: {e['count']:,}")
        if result.wire:
            totals = result.wire.get("totals", {})
            print(" + WIRE LEDGER:")
            print(
                f"   goodput ratio: {result.wire.get('goodput_ratio')}"
                f" ({totals.get('committed_payload_bytes', 0):,} committed B"
                f" / {totals.get('out_bytes_total', 0):,} wire B;"
                f" {totals.get('out_retransmit_bytes', 0):,} B retransmit)"
            )
            for t, d in sorted(result.wire.get("out", {}).items()):
                print(
                    f"   {t}: {d['frames']:,} frames / {d['bytes']:,} B out"
                    + (
                        f" (+{d['retransmit_bytes']:,} B retrans)"
                        if d["retransmit_bytes"]
                        else ""
                    )
                )
            if "compression_ratio" in result.wire:
                print(
                    f"   compression ratio: {result.wire['compression_ratio']}"
                    f" (raw {totals.get('out_raw_bytes', 0):,} B"
                    f" -> wire {totals.get('out_bytes', 0):,} B)"
                )
            if "frames_per_flush_mean" in result.wire:
                print(
                    f"   coalescing: {result.wire.get('flushes', 0):,}"
                    " flushes, mean frames/flush "
                    f"{result.wire['frames_per_flush_mean']}"
                    + (
                        f", mean acks/flush {result.wire['acks_per_flush_mean']}"
                        if "acks_per_flush_mean" in result.wire
                        else ""
                    )
                )
            if "cert_sig_bytes_fraction" in result.wire:
                print(
                    "   cert signature bytes fraction: "
                    f"{result.wire['cert_sig_bytes_fraction']}"
                )
            if "empty_cert_overhead_per_committed_byte" in result.wire:
                print(
                    "   empty-cert overhead per committed byte: "
                    f"{result.wire['empty_cert_overhead_per_committed_byte']}"
                )
        if result.crypto:
            print(" + CRYPTO LEDGER (verify ops by call site):")
            for site, d in result.crypto.get("verify", {}).items():
                split = (
                    f", {d['compute_s']:.2f} s compute"
                    if "compute_s" in d
                    else ""
                )
                print(
                    f"   {site}: {d['ops']:,} ops / {d['calls']:,} calls"
                    f" / {d['wall_s']:.2f} s wall{split}"
                    f" (mean batch {d['mean_batch']})"
                )
            cache = result.crypto.get("verify_cache", {})
            print(
                f"   verify cache: {cache.get('hits', 0):,} hits / "
                f"{cache.get('misses', 0):,} misses"
            )
        # Outside the stages guard: the disagreement matters MOST when the
        # stage join came up empty (missed flush, eviction).
        if result.metrics_disagreement is not None:
            print(
                f"   metrics vs log committed-tx disagreement: "
                f"{100 * result.metrics_disagreement:.2f}%"
            )
        if result.timeline.get("nodes"):
            n_samples = sum(
                len(v) for v in result.timeline["nodes"].values()
            )
            print(
                f" + TIMELINE: {n_samples} scrape samples across "
                f"{len(result.timeline['nodes'])} nodes, RTT matrix for "
                f"{len(result.timeline.get('rtt_ms', {}))} nodes "
                "(full series in .bench/timeline.json)"
            )
        if result.queues.get("channels"):
            fs = result.queues.get("first_saturating") or {}
            hot = sorted(
                result.queues["channels"].items(),
                key=lambda kv: kv[1].get("utilization", 0.0),
                reverse=True,
            )[:3]
            print(
                f" + QUEUES: {len(result.queues['channels'])} channels"
                + (
                    f", most pressured {fs['channel']} ({fs['mode']})"
                    if fs
                    else ""
                )
            )
            for ch, a in hot:
                if not a.get("high_water"):
                    continue
                print(
                    f"   {ch}: high-water {a['high_water']}/"
                    f"{a['capacity'] or '∞'}"
                    f" ({a.get('utilization', 0.0):.0%}),"
                    f" {a['enqueued']:,} enq, {a['full']:,} full"
                )


if __name__ == "__main__":
    main()
