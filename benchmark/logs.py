"""Log parser: turn node/client logs into TPS and latency numbers.

Faithful to the reference measurement system (benchmark/benchmark/logs.py,
259 LoC) so numbers are directly comparable with BASELINE.md:

- proposals  = `Created B{round}({header}) -> {digest}` lines from primary
  logs, earliest timestamp per digest across nodes (logs.py:101-103,70-77)
- commits    = `Committed B{round}({header}) -> {digest}` lines, earliest
  per digest (logs.py:105-107)
- consensus TPS = committed batch bytes / (first proposal → last commit)
  (logs.py:155-163); consensus latency = mean(commit − proposal) per
  committed digest (logs.py:165-167)
- end-to-end TPS = committed batch bytes / (first client start → last
  commit) (logs.py:179-186); end-to-end latency = sample-tx client-send →
  commit of its containing batch (logs.py:188-198)
- config echo-back: every primary must echo the full parameter set at boot
  and all echoes must agree (logs.py:109-131)
- hard-fails if any log contains an error marker (logs.py:98,138)

Log lines joined (emitted by this framework under --benchmark):
  client:    Start sending transactions / Transactions size|rate /
             Sending sample transaction {id} / rate too high
  worker:    Batch {digest} contains sample tx {id}
             Batch {digest} contains {n} B
  primary:   Created B{round}({header}) -> {batch_digest}
  consensus: Committed B{round}({header}) -> {batch_digest}
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from datetime import datetime
from typing import Dict, List

_TS = r"(\d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2}\.\d{3})Z"

# Parameters.log echo lines (narwhal_tpu/config.py, reference
# config/src/lib.rs:100-110) parsed back as a consistency check.
_CONFIG_PATTERNS = [
    ("header_size", r"Header size set to (\d+) B"),
    ("max_header_delay", r"Max header delay set to (\d+) ms"),
    ("gc_depth", r"Garbage collection depth set to (\d+) rounds"),
    ("sync_retry_delay", r"Sync retry delay set to (\d+) ms"),
    ("sync_retry_nodes", r"Sync retry nodes set to (\d+) nodes"),
    ("batch_size", r"Batch size set to (\d+) B"),
    ("max_batch_delay", r"Max batch delay set to (\d+) ms"),
]


def _ts(s: str) -> float:
    return datetime.strptime(s, "%Y-%m-%dT%H:%M:%S.%f").timestamp()


class BenchError(Exception):
    pass


@dataclass
class ParseResult:
    consensus_tps: float = 0.0
    consensus_bps: float = 0.0
    consensus_latency_ms: float = 0.0
    end_to_end_tps: float = 0.0
    end_to_end_bps: float = 0.0
    end_to_end_latency_ms: float = 0.0
    committed_bytes: int = 0
    committed_batches: int = 0
    duration_s: float = 0.0
    samples: int = 0
    rate_misses: int = 0
    config: Dict[str, int] = field(default_factory=dict)
    errors: List[str] = field(default_factory=list)

    def summary(self, rate: int, tx_size: int, nodes: int, workers: int) -> str:
        return (
            "\n-----------------------------------------\n"
            " SUMMARY:\n"
            "-----------------------------------------\n"
            " + CONFIG:\n"
            f"   Committee size: {nodes} nodes\n"
            f"   Workers per node: {workers}\n"
            f"   Input rate: {rate:,} tx/s\n"
            f"   Transaction size: {tx_size} B\n"
            f"   Execution time: {self.duration_s:,.0f} s\n"
            "\n + RESULTS:\n"
            f"   Consensus TPS: {self.consensus_tps:,.0f} tx/s\n"
            f"   Consensus BPS: {self.consensus_bps:,.0f} B/s\n"
            f"   Consensus latency: {self.consensus_latency_ms:,.0f} ms\n"
            "\n"
            f"   End-to-end TPS: {self.end_to_end_tps:,.0f} tx/s\n"
            f"   End-to-end BPS: {self.end_to_end_bps:,.0f} B/s\n"
            f"   End-to-end latency: {self.end_to_end_latency_ms:,.0f} ms\n"
            "-----------------------------------------\n"
        )


def _merge_earliest(dst: Dict[str, float], key: str, t: float) -> None:
    if key not in dst or t < dst[key]:
        dst[key] = t


def parse_logs(
    client_logs: List[str],
    worker_logs: List[str],
    primary_logs: List[str],
    tx_size: int,
) -> ParseResult:
    result = ParseResult()

    # Crash detection: any hard error in any log fails the run.
    for text in client_logs + worker_logs + primary_logs:
        for marker in ("ERROR", "CRITICAL", "Traceback", "panicked"):
            if marker in text:
                line = next(
                    (ln for ln in text.splitlines() if marker in ln), marker
                )
                result.errors.append(line)

    # Clients: start times, sample send times, missed-rate warnings.
    client_starts: List[float] = []
    sample_sent: Dict[int, float] = {}
    for text in client_logs:
        m = re.search(_TS + r".* Start sending transactions", text)
        if m:
            client_starts.append(_ts(m.group(1)))
        result.rate_misses += len(re.findall(r"rate too high", text))
        for m in re.finditer(_TS + r".* Sending sample transaction (\d+)", text):
            sample_sent.setdefault(int(m.group(2)), _ts(m.group(1)))

    # Workers: batch sizes and contained samples.
    batch_bytes: Dict[str, int] = {}
    batch_samples: Dict[str, List[int]] = {}
    for text in worker_logs:
        for m in re.finditer(_TS + r".* Batch (\S+) contains (\d+) B", text):
            batch_bytes.setdefault(m.group(2), int(m.group(3)))
        for m in re.finditer(_TS + r".* Batch (\S+) contains sample tx (\d+)", text):
            batch_samples.setdefault(m.group(2), []).append(int(m.group(3)))

    # Primaries: proposal (Created) and commit times, earliest across nodes.
    batch_proposed: Dict[str, float] = {}
    batch_committed: Dict[str, float] = {}
    for text in primary_logs:
        for m in re.finditer(_TS + r".* Created B\d+\(\S+\) -> (\S+)", text):
            _merge_earliest(batch_proposed, m.group(2), _ts(m.group(1)))
        for m in re.finditer(_TS + r".* Committed B\d+\(\S+\) -> (\S+)", text):
            _merge_earliest(batch_committed, m.group(2), _ts(m.group(1)))

    # Config echo-back verification (reference logs.py:109-131): every
    # primary log must carry the full parameter echo and all must agree.
    configs: List[Dict[str, int]] = []
    for text in primary_logs:
        cfg = {}
        for key, pat in _CONFIG_PATTERNS:
            m = re.search(pat, text)
            if m:
                cfg[key] = int(m.group(1))
        configs.append(cfg)
    if configs:
        complete = [c for c in configs if len(c) == len(_CONFIG_PATTERNS)]
        if len(complete) != len(configs):
            result.errors.append("config echo missing from primary log(s)")
        elif any(c != configs[0] for c in configs):
            result.errors.append("config echo differs between primaries")
        else:
            result.config = configs[0]

    committed = list(batch_committed)
    if not committed:
        return result

    result.committed_batches = len(committed)
    result.committed_bytes = sum(batch_bytes.get(d, 0) for d in committed)

    # Consensus: first proposal → last commit (reference logs.py:155-167).
    with_proposal = [d for d in committed if d in batch_proposed]
    if len(with_proposal) != len(committed):
        result.errors.append(
            f"{len(committed) - len(with_proposal)} committed digest(s) "
            "have no Created line in any primary log"
        )
    if with_proposal:
        start = min(batch_proposed[d] for d in with_proposal)
        end = max(batch_committed[d] for d in with_proposal)
        duration = max(end - start, 1e-6)
        result.duration_s = duration
        result.consensus_bps = result.committed_bytes / duration
        result.consensus_tps = result.consensus_bps / tx_size
        lats = [
            batch_committed[d] - batch_proposed[d] for d in with_proposal
        ]
        result.consensus_latency_ms = 1000 * sum(lats) / len(lats)

    # End-to-end: client start → last commit; latency joins sample send →
    # containing batch → commit (reference logs.py:179-198).
    e2e = []
    for digest in committed:
        for sample_id in batch_samples.get(digest, []):
            sent = sample_sent.get(sample_id)
            if sent is not None:
                e2e.append(batch_committed[digest] - sent)
    result.samples = len(e2e)
    starts = client_starts or (
        [min(sample_sent.values())] if sample_sent else []
    )
    if e2e and starts:
        end = max(batch_committed[d] for d in committed)
        e2e_duration = max(end - min(starts), 1e-6)
        result.end_to_end_bps = result.committed_bytes / e2e_duration
        result.end_to_end_tps = result.end_to_end_bps / tx_size
        result.end_to_end_latency_ms = 1000 * sum(e2e) / len(e2e)
    return result
