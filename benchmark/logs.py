"""Log parser: turn node/client logs into TPS and latency numbers.

Faithful to the reference measurement system (benchmark/benchmark/logs.py,
259 LoC) so numbers are directly comparable with BASELINE.md:

- proposals  = `Created B{round}({header}) -> {digest}` lines from primary
  logs, earliest timestamp per digest across nodes (logs.py:101-103,70-77)
- commits    = `Committed B{round}({header}) -> {digest}` lines, earliest
  per digest (logs.py:105-107)
- consensus TPS = committed batch bytes / (first proposal → last commit)
  (logs.py:155-163); consensus latency = mean(commit − proposal) per
  committed digest (logs.py:165-167)
- end-to-end TPS = committed batch bytes / (first client start → last
  commit) (logs.py:179-186); end-to-end latency = sample-tx client-send →
  commit of its containing batch (logs.py:188-198)
- config echo-back: every primary must echo the full parameter set at boot
  and all echoes must agree (logs.py:109-131)
- hard-fails if any log contains an error marker (logs.py:98,138)

Log lines joined (emitted by this framework under --benchmark):
  client:    Start sending transactions / Transactions size|rate /
             Sending sample transaction {id} / rate too high
  worker:    Batch {digest} contains sample tx {id}
             Batch {digest} contains {n} B
  primary:   Created B{round}({header}) -> {batch_digest}
  consensus: Committed B{round}({header}) -> {batch_digest}
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from datetime import datetime
from typing import Dict, List, Optional

_TS = r"(\d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2}\.\d{3})Z"

# Parameters.log echo lines (narwhal_tpu/config.py, reference
# config/src/lib.rs:100-110) parsed back as a consistency check.
_CONFIG_PATTERNS = [
    ("header_size", r"Header size set to (\d+) B"),
    ("max_header_delay", r"Max header delay set to (\d+) ms"),
    ("min_header_delay", r"Min header delay set to (\d+) ms"),
    ("header_linger", r"Header linger set to (\d+) ms"),
    ("gc_depth", r"Garbage collection depth set to (\d+) rounds"),
    ("sync_retry_delay", r"Sync retry delay set to (\d+) ms"),
    ("sync_retry_nodes", r"Sync retry nodes set to (\d+) nodes"),
    ("batch_size", r"Batch size set to (\d+) B"),
    ("max_batch_delay", r"Max batch delay set to (\d+) ms"),
]


def _ts(s: str) -> float:
    return datetime.strptime(s, "%Y-%m-%dT%H:%M:%S.%f").timestamp()


def _line_of(m: "re.Match") -> str:
    """The full log line containing match `m`, truncated for error text."""
    text = m.string
    start = text.rfind("\n", 0, m.start()) + 1
    end = text.find("\n", m.end())
    line = text[start : end if end != -1 else len(text)]
    return line[:200]


def _named(logs: List[str], names: Optional[List[str]], prefix: str):
    """Pair each log text with a human-readable source name, so every
    parse error can say WHICH file broke (a mis-scrape used to cost a
    full re-run to even locate)."""
    if names and len(names) == len(logs):
        return list(zip(names, logs))
    return [(f"{prefix}[{i}]", text) for i, text in enumerate(logs)]


class BenchError(Exception):
    pass


@dataclass
class ParseResult:
    consensus_tps: float = 0.0
    consensus_bps: float = 0.0
    consensus_latency_ms: float = 0.0
    end_to_end_tps: float = 0.0
    end_to_end_bps: float = 0.0
    end_to_end_latency_ms: float = 0.0
    committed_bytes: int = 0
    committed_batches: int = 0
    duration_s: float = 0.0
    samples: int = 0
    rate_misses: int = 0
    config: Dict[str, int] = field(default_factory=dict)
    errors: List[str] = field(default_factory=list)
    # Filled by the bench harness from node metrics snapshots (not by the
    # log parser): metrics-derived committed-tx total, its disagreement
    # with the log-scraped total (fraction, e.g. 0.012 = 1.2%), and the
    # per-stage pipeline latency breakdown in milliseconds.
    metrics_committed_tx: float = 0.0
    metrics_disagreement: float | None = None
    stages_ms: Dict[str, float] = field(default_factory=dict)
    # Round-cadence attribution (per-round ROUND_STAGES legs aggregated
    # across primaries — see metrics_check.round_attribution): mean ms per
    # sub-leg plus the telescoped round period they sum to.
    round_stages_ms: Dict[str, float] = field(default_factory=dict)
    # Committee-wide time-series scraped live from every node's
    # --metrics-port during the run (benchmark/scraper.py →
    # metrics_check.build_timeline): per-node TPS/round/commit-lag over
    # time, per-peer RTT matrix, and the /healthz verdicts at quiesce.
    timeline: Dict = field(default_factory=dict)
    # Wire-goodput and crypto-cost ledgers joined across node snapshots
    # (metrics_check.wire_crypto_summary): per-message-type bandwidth
    # with retransmissions split out + goodput ratio, and per-call-site
    # sign/verify attribution with the protocol-arithmetic cross-check.
    wire: Dict = field(default_factory=dict)
    crypto: Dict = field(default_factory=dict)
    # Per-node event-loop stall series (metrics_check.loop_stall_summary),
    # populated when the run armed the loop-stall watchdog
    # (NARWHAL_LOOP_WATCHDOG_MS / local_bench --loop-watchdog-ms).
    runtime: Dict = field(default_factory=dict)
    # Per-node flight-recorder rings pulled from /debug/flight at quiesce
    # (benchmark/scraper.py flight_all): {node: {"events": […], …}} —
    # the last-seconds event history every run carries, clean or not.
    flight: Dict = field(default_factory=dict)
    # Per-channel InstrumentedQueue backpressure accounting
    # (metrics_check.queue_pressure_summary): per-node channel tables,
    # committee-wide aggregates, and the first-saturating attribution.
    queues: Dict = field(default_factory=dict)
    # Wall-clock model sections (metrics_check): per-node reconciled
    # clock corrections applied to the cross-node stage join, the
    # slowest end-to-end causal chain(s) through the pipeline, and the
    # ranked who-closed-the-quorum straggler attribution.
    clock: Dict = field(default_factory=dict)
    critical_path: Dict = field(default_factory=dict)
    stragglers: Dict = field(default_factory=dict)

    def summary(self, rate: int, tx_size: int, nodes: int, workers: int) -> str:
        return (
            "\n-----------------------------------------\n"
            " SUMMARY:\n"
            "-----------------------------------------\n"
            " + CONFIG:\n"
            f"   Committee size: {nodes} nodes\n"
            f"   Workers per node: {workers}\n"
            f"   Input rate: {rate:,} tx/s\n"
            f"   Transaction size: {tx_size} B\n"
            f"   Execution time: {self.duration_s:,.0f} s\n"
            "\n + RESULTS:\n"
            f"   Consensus TPS: {self.consensus_tps:,.0f} tx/s\n"
            f"   Consensus BPS: {self.consensus_bps:,.0f} B/s\n"
            f"   Consensus latency: {self.consensus_latency_ms:,.0f} ms\n"
            "\n"
            f"   End-to-end TPS: {self.end_to_end_tps:,.0f} tx/s\n"
            f"   End-to-end BPS: {self.end_to_end_bps:,.0f} B/s\n"
            f"   End-to-end latency: {self.end_to_end_latency_ms:,.0f} ms\n"
            "-----------------------------------------\n"
        )


def _merge_earliest(dst: Dict[str, float], key: str, t: float) -> None:
    if key not in dst or t < dst[key]:
        dst[key] = t


def parse_logs(
    client_logs: List[str],
    worker_logs: List[str],
    primary_logs: List[str],
    tx_size: int,
    client_names: Optional[List[str]] = None,
    worker_names: Optional[List[str]] = None,
    primary_names: Optional[List[str]] = None,
) -> ParseResult:
    """Parse node/client logs into a ParseResult.  The optional ``*_names``
    lists label each log (file basenames from the harness) so every error
    reports the offending source and a line excerpt instead of a bare
    hard-fail."""
    result = ParseResult()
    clients = _named(client_logs, client_names, "client")
    workers = _named(worker_logs, worker_names, "worker")
    primaries = _named(primary_logs, primary_names, "primary")

    def ts_of(m: "re.Match", source: str) -> Optional[float]:
        """Timestamp of a matched line, or None with a located error.
        The _TS regex makes this near-impossible to hit, but a mis-scrape
        here used to cost a full re-run to even find the bad file."""
        try:
            return _ts(m.group(1))
        except ValueError:
            result.errors.append(
                f"{source}: unparseable timestamp: {_line_of(m)}"
            )
            return None

    # Crash detection: any hard error in any log fails the run — and names
    # the log it came from.
    for source, text in clients + workers + primaries:
        for marker in ("ERROR", "CRITICAL", "Traceback", "panicked"):
            if marker in text:
                line = next(
                    (ln for ln in text.splitlines() if marker in ln), marker
                )
                result.errors.append(f"{source}: {line[:200]}")

    # Clients: start times, sample send times, missed-rate warnings.
    client_starts: List[float] = []
    sample_sent: Dict[int, float] = {}
    for source, text in clients:
        m = re.search(_TS + r".* Start sending transactions", text)
        if m:
            t = ts_of(m, source)
            if t is not None:
                client_starts.append(t)
        result.rate_misses += len(re.findall(r"rate too high", text))
        for m in re.finditer(_TS + r".* Sending sample transaction (\d+)", text):
            t = ts_of(m, source)
            if t is not None:
                sample_sent.setdefault(int(m.group(2)), t)

    # Workers: batch sizes and contained samples.
    batch_bytes: Dict[str, int] = {}
    batch_samples: Dict[str, List[int]] = {}
    for source, text in workers:
        for m in re.finditer(_TS + r".* Batch (\S+) contains (\d+) B", text):
            batch_bytes.setdefault(m.group(2), int(m.group(3)))
        for m in re.finditer(_TS + r".* Batch (\S+) contains sample tx (\d+)", text):
            batch_samples.setdefault(m.group(2), []).append(int(m.group(3)))

    # Primaries: proposal (Created) and commit times, earliest across
    # nodes; remember one source per digest for error attribution.
    batch_proposed: Dict[str, float] = {}
    batch_committed: Dict[str, float] = {}
    committed_source: Dict[str, str] = {}
    for source, text in primaries:
        for m in re.finditer(_TS + r".* Created B\d+\(\S+\) -> (\S+)", text):
            t = ts_of(m, source)
            if t is not None:
                _merge_earliest(batch_proposed, m.group(2), t)
        for m in re.finditer(_TS + r".* Committed B\d+\(\S+\) -> (\S+)", text):
            t = ts_of(m, source)
            if t is not None:
                _merge_earliest(batch_committed, m.group(2), t)
                committed_source.setdefault(m.group(2), source)

    # Config echo-back verification (reference logs.py:109-131): every
    # primary log must carry the full parameter echo and all must agree.
    configs: List[Dict[str, int]] = []
    for source, text in primaries:
        cfg = {}
        for key, pat in _CONFIG_PATTERNS:
            m = re.search(pat, text)
            if m:
                cfg[key] = int(m.group(1))
        configs.append(cfg)
    if configs:
        complete = [c for c in configs if len(c) == len(_CONFIG_PATTERNS)]
        if len(complete) != len(configs):
            missing = [
                f"{src} (missing "
                f"{sorted(set(k for k, _ in _CONFIG_PATTERNS) - set(cfg))})"
                for (src, _), cfg in zip(primaries, configs)
                if len(cfg) != len(_CONFIG_PATTERNS)
            ]
            result.errors.append(
                "config echo missing from primary log(s): "
                + "; ".join(missing)
            )
        elif any(c != configs[0] for c in configs):
            diff = [
                src
                for (src, _), cfg in zip(primaries, configs)
                if cfg != configs[0]
            ]
            result.errors.append(
                "config echo differs between primaries: "
                f"{diff} disagree with {primaries[0][0]}"
            )
        else:
            result.config = configs[0]

    committed = list(batch_committed)
    if not committed:
        return result

    result.committed_batches = len(committed)
    result.committed_bytes = sum(batch_bytes.get(d, 0) for d in committed)

    # Consensus: first proposal → last commit (reference logs.py:155-167).
    with_proposal = [d for d in committed if d in batch_proposed]
    if len(with_proposal) != len(committed):
        orphans = [d for d in committed if d not in batch_proposed]
        examples = ", ".join(
            f"{d} (Committed in {committed_source.get(d, '?')})"
            for d in orphans[:3]
        )
        result.errors.append(
            f"{len(orphans)} committed digest(s) "
            f"have no Created line in any primary log; e.g. {examples}"
        )
    if with_proposal:
        start = min(batch_proposed[d] for d in with_proposal)
        end = max(batch_committed[d] for d in with_proposal)
        duration = max(end - start, 1e-6)
        result.duration_s = duration
        result.consensus_bps = result.committed_bytes / duration
        result.consensus_tps = result.consensus_bps / tx_size
        lats = [
            batch_committed[d] - batch_proposed[d] for d in with_proposal
        ]
        result.consensus_latency_ms = 1000 * sum(lats) / len(lats)

    # End-to-end: client start → last commit; latency joins sample send →
    # containing batch → commit (reference logs.py:179-198).
    e2e = []
    for digest in committed:
        for sample_id in batch_samples.get(digest, []):
            sent = sample_sent.get(sample_id)
            if sent is not None:
                e2e.append(batch_committed[digest] - sent)
    result.samples = len(e2e)
    starts = client_starts or (
        [min(sample_sent.values())] if sample_sent else []
    )
    if e2e and starts:
        end = max(batch_committed[d] for d in committed)
        e2e_duration = max(end - min(starts), 1e-6)
        result.end_to_end_bps = result.committed_bytes / e2e_duration
        result.end_to_end_tps = result.end_to_end_bps / tx_size
        result.end_to_end_latency_ms = 1000 * sum(e2e) / len(e2e)
    return result
