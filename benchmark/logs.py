"""Log parser: turn node/client logs into TPS and latency numbers.

Reference benchmark/benchmark/logs.py (259 LoC) — the measurement system:
- consensus TPS   = committed batch bytes / (first batch creation → last
                    commit) / tx size
- consensus latency = commit time − batch creation time, averaged
- end-to-end latency = sample-tx client-send → commit of its batch
- hard-fails if any log contains an error marker (logs.py:98,138)

Log lines joined (emitted by this framework under --benchmark):
  client:    Sending sample transaction {id}
  worker:    Batch {digest} contains sample tx {id}
             Batch {digest} contains {n} B
  primary:   Created B{round}({header}) -> {batch_digest}
  consensus: Committed B{round}({header}) -> {batch_digest}
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from datetime import datetime
from typing import Dict, List

_TS = r"(\d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2}\.\d{3})Z"


def _ts(s: str) -> float:
    return datetime.strptime(s, "%Y-%m-%dT%H:%M:%S.%f").timestamp()


class BenchError(Exception):
    pass


@dataclass
class ParseResult:
    consensus_tps: float = 0.0
    consensus_bps: float = 0.0
    consensus_latency_ms: float = 0.0
    end_to_end_tps: float = 0.0
    end_to_end_bps: float = 0.0
    end_to_end_latency_ms: float = 0.0
    committed_bytes: int = 0
    committed_batches: int = 0
    duration_s: float = 0.0
    samples: int = 0
    errors: List[str] = field(default_factory=list)

    def summary(self, rate: int, tx_size: int, nodes: int, workers: int) -> str:
        return (
            "\n-----------------------------------------\n"
            " SUMMARY:\n"
            "-----------------------------------------\n"
            " + CONFIG:\n"
            f"   Committee size: {nodes} nodes\n"
            f"   Workers per node: {workers}\n"
            f"   Input rate: {rate:,} tx/s\n"
            f"   Transaction size: {tx_size} B\n"
            f"   Execution time: {self.duration_s:,.0f} s\n"
            "\n + RESULTS:\n"
            f"   Consensus TPS: {self.consensus_tps:,.0f} tx/s\n"
            f"   Consensus BPS: {self.consensus_bps:,.0f} B/s\n"
            f"   Consensus latency: {self.consensus_latency_ms:,.0f} ms\n"
            "\n"
            f"   End-to-end TPS: {self.end_to_end_tps:,.0f} tx/s\n"
            f"   End-to-end BPS: {self.end_to_end_bps:,.0f} B/s\n"
            f"   End-to-end latency: {self.end_to_end_latency_ms:,.0f} ms\n"
            "-----------------------------------------\n"
        )


def parse_logs(
    client_logs: List[str],
    worker_logs: List[str],
    primary_logs: List[str],
    tx_size: int,
) -> ParseResult:
    result = ParseResult()

    # Crash detection: any hard error in any log fails the run.
    for text in client_logs + worker_logs + primary_logs:
        for marker in ("ERROR", "CRITICAL", "Traceback", "panicked"):
            if marker in text:
                line = next(
                    (ln for ln in text.splitlines() if marker in ln), marker
                )
                result.errors.append(line)

    # Client: sample send times.
    sample_sent: Dict[int, float] = {}
    for text in client_logs:
        for m in re.finditer(_TS + r".* Sending sample transaction (\d+)", text):
            sample_sent.setdefault(int(m.group(2)), _ts(m.group(1)))

    # Workers: batch creation time, size, contained samples.
    batch_created: Dict[str, float] = {}
    batch_bytes: Dict[str, int] = {}
    batch_samples: Dict[str, List[int]] = {}
    for text in worker_logs:
        for m in re.finditer(_TS + r".* Batch (\S+) contains (\d+) B", text):
            digest = m.group(2)
            batch_created.setdefault(digest, _ts(m.group(1)))
            batch_bytes.setdefault(digest, int(m.group(3)))
        for m in re.finditer(_TS + r".* Batch (\S+) contains sample tx (\d+)", text):
            batch_samples.setdefault(m.group(2), []).append(int(m.group(3)))

    # Primaries: commit times (first node to commit wins the timestamp).
    batch_committed: Dict[str, float] = {}
    for text in primary_logs:
        for m in re.finditer(_TS + r".* Committed B\d+\(\S+\) -> (\S+)", text):
            t = _ts(m.group(1))
            d = m.group(2)
            if d not in batch_committed or t < batch_committed[d]:
                batch_committed[d] = t

    committed = [d for d in batch_committed if d in batch_created]
    if not committed:
        return result

    result.committed_batches = len(committed)
    result.committed_bytes = sum(batch_bytes.get(d, 0) for d in committed)
    start = min(batch_created[d] for d in committed)
    end = max(batch_committed[d] for d in committed)
    duration = max(end - start, 1e-6)
    result.duration_s = duration
    result.consensus_bps = result.committed_bytes / duration
    result.consensus_tps = result.consensus_bps / tx_size
    lats = [batch_committed[d] - batch_created[d] for d in committed]
    result.consensus_latency_ms = 1000 * sum(lats) / len(lats)

    # End-to-end: join sample send → containing batch → commit.
    e2e = []
    for digest in committed:
        for sample_id in batch_samples.get(digest, []):
            sent = sample_sent.get(sample_id)
            if sent is not None:
                e2e.append(batch_committed[digest] - sent)
    result.samples = len(e2e)
    if e2e and sample_sent:
        first_send = min(sample_sent.values())
        e2e_duration = max(end - first_send, 1e-6)
        result.end_to_end_bps = result.committed_bytes / e2e_duration
        result.end_to_end_tps = result.end_to_end_bps / tx_size
        result.end_to_end_latency_ms = 1000 * sum(e2e) / len(e2e)
    return result
