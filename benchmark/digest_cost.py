"""Measure the Processor's others'-batch digest cost and settle SURVEY §7
hot-spot 3 (reference worker/src/processor.rs:35) with data.

The worker hashes every batch it stores: its own batches reuse the digest
computed at seal time in the C data plane, so the per-batch SHA-256 on the
Python side only runs for the (N-1)/N share of traffic arriving from peer
workers (narwhal_tpu/worker/processor.py).  This harness measures the
host's actual SHA-256 throughput at batch granularity and converts it into
CPU share at the driver benchmark's measured committed rate — if that share
is small, a device/batched digest hook buys nothing and the plan item
closes; if large, it motivates the hook.

    python benchmark/digest_cost.py --tps 55000 --tx-size 512 --nodes 4 \
        --batch-size 500000 --out artifacts/processor_digest_cost_r05.json
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import time


def sha256_throughput(batch_size: int, seconds: float = 2.0) -> float:
    """Bytes/s of hashlib.sha256 over batch-sized buffers."""
    buf = os.urandom(batch_size)
    n = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        hashlib.sha256(buf).digest()
        n += 1
    return n * batch_size / (time.perf_counter() - t0)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tps", type=float, required=True,
                    help="committed e2e tx/s from the driver bench")
    ap.add_argument("--tx-size", type=int, default=512)
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--batch-size", type=int, default=500_000)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    hash_bps = sha256_throughput(args.batch_size)
    total_bps = args.tps * args.tx_size
    # Each worker originates T/N of the committee's committed bytes and
    # receives every peer's batches: it hashes (N-1)/N · T per second
    # (own batches reuse the seal-time digest from the C data plane).
    per_worker_bps = total_bps / args.nodes * (args.nodes - 1)
    cpu_share = per_worker_bps / hash_bps

    result = {
        "sha256_bytes_per_sec": round(hash_bps),
        "committed_tx_per_sec": args.tps,
        "others_batch_bytes_per_sec_per_worker": round(per_worker_bps),
        "digest_cpu_share_per_worker": round(cpu_share, 4),
        "decision": (
            "close" if cpu_share < 0.02 else "implement-batched-digest-hook"
        ),
        "note": (
            "own batches reuse the C data plane's seal-time digest; this is "
            "the per-worker CPU share of hashing peers' batches at the "
            "driver-measured committed rate (SURVEY §7 hot spot 3 "
            "threshold: <2% closes the item)"
        ),
    }
    print(json.dumps(result))
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)


if __name__ == "__main__":
    main()
