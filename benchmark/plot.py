"""Latency-vs-throughput plots from aggregate.py sweep artifacts.

The reference renders its benchmark sweeps as latency-vs-throughput curves
with a max-latency cutoff (benchmark/benchmark/plot.py:1-203: one curve per
configuration, x = committed TPS, y = latency, points past the cutoff
dropped — that cutoff is how the paper defines "saturation").  Same contract
here, drawn from the JSON artifacts `benchmark/aggregate.py --out` writes:

    python benchmark/plot.py artifacts/sweep_4n.json artifacts/sweep_20n.json \
        --metric e2e --max-latency 8000 --out artifacts/latency_vs_tps.png
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

_METRICS = {
    "e2e": ("end_to_end_tps", "end_to_end_latency_ms", "End-to-end"),
    "consensus": ("consensus_tps", "consensus_latency_ms", "Consensus"),
}


def curve(artifact: dict, metric: str, max_latency_ms: float):
    """(xs, ys, yerr, label) for one sweep artifact, cutoff applied."""
    tps_key, lat_key, _ = _METRICS[metric]
    xs, ys, yerr = [], [], []
    for p in sorted(artifact["points"], key=lambda p: p["rate"]):
        lat = p[lat_key]["mean"]
        if lat <= 0 or lat > max_latency_ms:
            continue  # past saturation (reference plot.py max-latency cutoff)
        xs.append(p[tps_key]["mean"])
        ys.append(lat)
        yerr.append(p[lat_key]["stdev"])
    cfg = artifact.get("config", {})
    label = (
        f"{cfg.get('nodes', '?')} nodes, {cfg.get('workers', '?')} wkr"
        + (f", {cfg['faults']} faults" if cfg.get("faults") else "")
    )
    return xs, ys, yerr, label


def plot(paths, metric: str, max_latency_ms: float, out: str) -> None:
    import matplotlib

    matplotlib.use("Agg")  # headless
    import matplotlib.pyplot as plt

    _, _, title = _METRICS[metric]
    fig, ax = plt.subplots(figsize=(7, 4.5))
    for path in paths:
        with open(path) as f:
            artifact = json.load(f)
        xs, ys, yerr, label = curve(artifact, metric, max_latency_ms)
        if not xs:
            print(f"WARNING: no points under cutoff in {path}", file=sys.stderr)
            continue
        ax.errorbar(xs, ys, yerr=yerr, marker="o", capsize=3, label=label)
    ax.set_xlabel(f"{title} throughput (tx/s)")
    ax.set_ylabel(f"{title} latency (ms)")
    ax.set_title(f"{title} latency vs throughput")
    ax.grid(True, alpha=0.3)
    ax.legend()
    fig.tight_layout()
    os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
    fig.savefig(out, dpi=150)
    print(f"wrote {out}")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("artifacts", nargs="+", help="aggregate.py --out JSONs")
    ap.add_argument("--metric", choices=sorted(_METRICS), default="e2e")
    ap.add_argument(
        "--max-latency",
        type=float,
        default=10_000,
        help="drop points slower than this (ms) — the saturation cutoff",
    )
    ap.add_argument("--out", default="artifacts/latency_vs_tps.png")
    args = ap.parse_args()
    plot(args.artifacts, args.metric, args.max_latency, args.out)


if __name__ == "__main__":
    main()
