"""Paired interleaved A/B: profiler + flight recorder overhead (ISSUE 11).

The "always-on" in the sampling profiler's charter is only honest if the
committee pays ~nothing for it, so this driver measures exactly that the
way PRs 2/7 measured their instrument overhead: N interleaved pairs of
identical local_bench runs — the ON arm with the defaults
(NARWHAL_PROFILE_HZ≈67, flight recorder enabled), the OFF arm with both
stubbed (NARWHAL_PROFILE_HZ=0, NARWHAL_FLIGHT=0) — alternating arms so
host drift hits both equally, medians compared against the ≤5% committee
TPS acceptance gate.

The ON arm's final snapshots also yield the OTHER acceptance number: the
profiler's aggregated top-N self-time table, which must independently
reproduce the crypto ledger's "verify dominates" finding with zero
hand-placed instrumentation (on this host the pure-Python ed25519
fallback is the committee's compute, so `_ed25519_py.py` frames must
lead).

    python benchmark/trace_profile_ab.py --pairs 4 \
        --artifact artifacts/trace_profile_r16.json
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from benchmark.local_bench import run_bench  # noqa: E402

_OFF_ENV = {"NARWHAL_PROFILE_HZ": "0", "NARWHAL_FLIGHT": "0"}


def _one_run(arm: str, idx: int, args) -> dict:
    """One bench run under the arm's env; returns the headline numbers
    (+ the aggregated profiler table on ON arms)."""
    saved = {k: os.environ.get(k) for k in _OFF_ENV}
    if arm == "off":
        os.environ.update(_OFF_ENV)
    else:
        for k in _OFF_ENV:
            os.environ.pop(k, None)
    workdir = os.path.join(REPO, ".bench_ab", f"{arm}-{idx}")
    try:
        result = run_bench(
            nodes=args.nodes,
            workers=1,
            rate=args.rate,
            tx_size=args.tx_size,
            duration=args.duration,
            base_port=args.base_port,
            workdir=workdir,
            quiet=True,
            progress_wait=45,
            trace_out=(
                os.path.join(workdir, "trace.json") if arm == "on" else None
            ),
        )
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    out = {
        "arm": arm,
        "errors": result.errors,
        "consensus_tps": result.consensus_tps,
        "consensus_latency_ms": result.consensus_latency_ms,
        "end_to_end_tps": result.end_to_end_tps,
        "end_to_end_latency_ms": result.end_to_end_latency_ms,
    }
    if arm == "on":
        out["profile_top"] = _aggregate_profile_top(workdir)
        out["trace_path"] = os.path.join(workdir, "trace.json")
        out["flight_nodes"] = sorted(
            n for n, ring in (result.flight or {}).items() if ring
        )
    return out


def _aggregate_profile_top(workdir: str, n: int = 20) -> list:
    """Committee-wide self-time table: the per-node `profile.top` tables
    of every PRIMARY snapshot summed by frame (workers mostly idle at
    bench rates; the primaries are where the paper's compute lives)."""
    agg: dict = {}
    import glob

    for path in glob.glob(os.path.join(workdir, "metrics-primary-*.json")):
        try:
            with open(path) as f:
                snap = json.load(f)
        except (OSError, ValueError):
            continue
        for row in (snap.get("detail") or {}).get("profile.top") or []:
            rec = agg.setdefault(row["frame"], {"self": 0, "total": 0})
            rec["self"] += row.get("self", 0)
            rec["total"] += row.get("total", 0)
    total_self = sum(r["self"] for r in agg.values()) or 1
    rows = sorted(agg.items(), key=lambda kv: kv[1]["self"], reverse=True)
    return [
        {
            "frame": frame,
            "self": rec["self"],
            "total": rec["total"],
            "self_frac": round(rec["self"] / total_self, 4),
        }
        for frame, rec in rows[:n]
    ]


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--pairs", type=int, default=4)
    parser.add_argument("--nodes", type=int, default=4)
    parser.add_argument("--rate", type=int, default=3000)
    parser.add_argument("--tx-size", type=int, default=512)
    parser.add_argument("--duration", type=int, default=15)
    parser.add_argument("--base-port", type=int, default=7200)
    parser.add_argument("--gate", type=float, default=0.05,
                        help="max tolerated median consensus-TPS overhead")
    parser.add_argument("--artifact", required=True)
    args = parser.parse_args()

    runs = []
    for i in range(args.pairs):
        for arm in ("on", "off") if i % 2 == 0 else ("off", "on"):
            print(f"=== pair {i + 1}/{args.pairs}, arm {arm}",
                  file=sys.stderr)
            runs.append(_one_run(arm, i, args))

    def med(arm, key):
        vals = [
            r[key] for r in runs
            if r["arm"] == arm and not r["errors"] and r[key] > 0
        ]
        return statistics.median(vals) if vals else None

    on_tps, off_tps = med("on", "consensus_tps"), med("off", "consensus_tps")
    # The gated statistic is the MEDIAN OF PER-PAIR overheads: each pair's
    # two arms run back to back, so slow host drift (this box swings tens
    # of percent across minutes — the r09/r10 verdicts measured it)
    # cancels within a pair where it cannot cancel across arm medians.
    pair_overheads = []
    for i in range(0, len(runs) - 1, 2):
        a, c = runs[i], runs[i + 1]
        on = a if a["arm"] == "on" else c
        off = a if a["arm"] == "off" else c
        if (
            not on["errors"] and not off["errors"]
            and on["consensus_tps"] > 0 and off["consensus_tps"] > 0
        ):
            pair_overheads.append(
                round(
                    (off["consensus_tps"] - on["consensus_tps"])
                    / off["consensus_tps"],
                    4,
                )
            )
    overhead = (
        statistics.median(pair_overheads) if pair_overheads else None
    )
    profile_top = next(
        (r["profile_top"] for r in reversed(runs)
         if r["arm"] == "on" and r.get("profile_top")),
        [],
    )
    # The dominance verdict is per-FRAME (the acceptance's literal
    # claim): the table's top self-time frame must be ed25519 verify
    # math — `_point_mul` is the double-scalar multiplication only the
    # verify path runs (sign uses the `_point_mul_base` comb).  The
    # per-file aggregation rides in the artifact too, for the honest
    # caveat it carries: summing BOTH asyncio socket frames
    # (write + _read_ready) lands within a few percent of the ed25519
    # module on this host at bench rates — the one-syscall-per-frame
    # cost ROADMAP item 5 already names, independently rediscovered by
    # the sampler with zero instrumentation.
    verify_dominates = bool(
        profile_top and profile_top[0]["frame"].startswith("_ed25519_py.py:")
    )
    by_file: dict = {}
    for row in profile_top:
        fname = row["frame"].split(":", 1)[0]
        by_file[fname] = by_file.get(fname, 0) + row["self"]
    top_by_file = sorted(
        by_file.items(), key=lambda kv: kv[1], reverse=True
    )
    artifact = {
        "generated_by": "benchmark/trace_profile_ab.py",
        "config": {
            "pairs": args.pairs, "nodes": args.nodes, "rate": args.rate,
            "tx_size": args.tx_size, "duration": args.duration,
            "on_env": "defaults (NARWHAL_PROFILE_HZ=67, NARWHAL_FLIGHT=1)",
            "off_env": _OFF_ENV,
        },
        "runs": runs,
        "medians": {
            "on": {
                "consensus_tps": on_tps,
                "e2e_tps": med("on", "end_to_end_tps"),
                "e2e_latency_ms": med("on", "end_to_end_latency_ms"),
            },
            "off": {
                "consensus_tps": off_tps,
                "e2e_tps": med("off", "end_to_end_tps"),
                "e2e_latency_ms": med("off", "end_to_end_latency_ms"),
            },
        },
        "pair_overheads": pair_overheads,
        "tps_overhead_fraction": (
            round(overhead, 4) if overhead is not None else None
        ),
        "gate": {"max_overhead": args.gate,
                 "statistic": "median of per-pair overheads"},
        "profile_top_committee": profile_top,
        "profile_top_by_file": [
            {"file": f, "self": s} for f, s in top_by_file[:10]
        ],
        "verify_dominates_self_time": verify_dominates,
    }
    artifact["ok"] = (
        overhead is not None
        and overhead <= args.gate
        and verify_dominates
    )
    os.makedirs(os.path.dirname(args.artifact) or ".", exist_ok=True)
    with open(args.artifact, "w") as f:
        json.dump(artifact, f, indent=1)
    print(
        f"A/B: on={on_tps} off={off_tps} tx/s, overhead="
        f"{overhead if overhead is None else round(100 * overhead, 2)}% "
        f"(gate {100 * args.gate:.0f}%), verify_dominates="
        f"{verify_dominates} -> {args.artifact}"
    )
    if profile_top:
        print("committee top self-time frames:")
        for row in profile_top[:8]:
            print(
                f"  {row['frame']}: self {row['self']} "
                f"({100 * row['self_frac']:.1f}%), total {row['total']}"
            )
    return 0 if artifact["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
